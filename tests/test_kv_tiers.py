"""Tiered KV cache (ISSUE 17): device → host → remote prefix reuse.

Four layers, cheapest first:

* **Codec units**: raw at-rest entries round-trip bit-exactly; fp8/int8
  entries round-trip within the shared codec's documented
  ``amax / ROUND_TRIP_DIVISOR`` bound (the quantized-tier exactness
  contract) at a fraction of the raw footprint; malformed blobs rejected.
* **Tier-manager invariants** (stub backend, no jax): an entry lives in
  exactly ONE tier; promotion reads the donor entry without evicting it;
  demotion under a full T1 spills-or-drops (counted) and never blocks; a
  stale ref degrades to a cold miss (``promote`` → False); release is
  idempotent and the resident gauges track every move.
* **T2 loopback** (real p2p endpoints, the weight-push control framing):
  put/get bit-exact with CRC verification, get-miss on unknown keys,
  delete takes, server-side LRU eviction notices ride the put response.
* **Oracle exactness** (real models): with the LOSSLESS tier configured,
  demote→promote cycles keep every output bit-equal to the one-shot
  ``generate`` oracle on the dense stack (tier-1) and the EP MoE stack
  (slow, like every multi-compile arm); the fp8-at-rest arm (slow: codec
  compiles per entry shape) stamps ``cache_hit_exact=False`` on every
  deep hit so lossy reuse is attributable per request.
"""

import threading

import numpy as np
import pytest

from uccl_tpu import obs
from uccl_tpu.p2p import Channel, Endpoint
from uccl_tpu.serving import (
    PrefixCache, ServingEngine, SlotPool, TieredKVCache, TierRef,
)
from uccl_tpu.serving.kv_tiers import (
    HostKVTier, KvTierServer, RemoteKVTier, decode_entry, encode_entry,
)

MAX_SEQ = 32


def _rows(rng, n_tokens, layers=2, heads=2, dim=8):
    shape = (layers, n_tokens, heads, dim)
    return (rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32))


class TestCodec:
    def test_raw_round_trip_bit_exact(self, rng):
        k, v = _rows(rng, 8)
        blob, meta = encode_entry(k, v)
        assert meta["enc"] == "raw" and blob.dtype == np.uint8
        assert blob.nbytes == 2 * k.nbytes
        k2, v2 = decode_entry(blob, meta)
        assert np.array_equal(k, k2) and np.array_equal(v, v2)

    @pytest.mark.parametrize("wd", ["fp8", "int8"])
    def test_quantized_round_trip_within_documented_bound(self, rng, wd):
        """THE lossy-tier contract: max abs error ≤ the codec's published
        ``round_trip_bound`` per block — and the blob is materially
        smaller than raw (the reason to opt in)."""
        from uccl_tpu.ops import quant

        k, v = _rows(rng, 8)
        blob, meta = encode_entry(k, v, wire_dtype=wd, block=4)
        assert meta["enc"] == wd
        k2, v2 = decode_entry(blob, meta)
        per_unit = quant.round_trip_bound(1.0, wd)  # bound scales with amax
        for a, b in ((k, k2), (v, v2)):
            amax = np.abs(a.reshape(-1, 4)).max(axis=1, keepdims=True)
            err = np.abs(a - b).reshape(-1, 4)
            assert (err <= amax * per_unit + 1e-7).all()
        # payload 1B/elem + f32 scale per 4-elem block = half of raw f32
        # (production block=32 amortizes the sidecar to ~28% of raw)
        raw_nbytes = 2 * k.nbytes
        assert blob.nbytes <= raw_nbytes / 2

    def test_malformed_inputs_rejected(self, rng):
        k, v = _rows(rng, 4)
        with pytest.raises(ValueError, match="shapes differ"):
            encode_entry(k, v[:, :3])
        blob, meta = encode_entry(k, v)
        with pytest.raises(ValueError, match="blob"):
            decode_entry(blob[:-4], meta)


class _TierStubBackend:
    """Host-array KV pool with the engine backends' export/import surface;
    rows are deterministic per (slot, position) so imports are checkable."""

    def __init__(self, n_slots=2, max_seq=MAX_SEQ, layers=2, heads=2,
                 dim=8):
        self.n_slots = n_slots
        self.k = np.zeros((layers, n_slots, max_seq, heads, dim),
                          np.float32)
        self.v = np.zeros_like(self.k)

    def fill(self, slot, n, seed):
        rng = np.random.default_rng(seed)
        self.k[:, slot, :n] = rng.standard_normal(
            self.k[:, slot, :n].shape)
        self.v[:, slot, :n] = rng.standard_normal(
            self.v[:, slot, :n].shape)
        return self.k[:, slot, :n].copy(), self.v[:, slot, :n].copy()

    def export_slot_kv(self, slot, lo, hi):
        return self.k[:, slot, lo:hi].copy(), self.v[:, slot, lo:hi].copy()

    def import_slot_kv(self, slot, k_rows, v_rows, *, length):
        self.k[:, slot, :length] = k_rows
        self.v[:, slot, :length] = v_rows


def _entry_bytes(n_tokens, layers=2, heads=2, dim=8):
    return 2 * layers * n_tokens * heads * dim * 4


class _FakeRemote:
    """In-process stand-in for :class:`RemoteKVTier`: the surface
    :class:`TieredKVCache` drives (put/get/delete + used accounting),
    with switchable failure injection for the never-blocks contracts."""

    def __init__(self, fail_puts=0, fail_gets=0):
        self.store = {}
        self.used_bytes = 0
        self.used_tokens = 0
        self.fail_puts = fail_puts
        self.fail_gets = fail_gets
        self.puts = 0

    def put(self, key, blob, meta):
        self.puts += 1
        if self.fail_puts:
            self.fail_puts -= 1
            raise IOError("t2 channel down")
        self.store[key] = (np.asarray(blob).copy(), dict(meta))
        return []

    def get(self, key):
        if self.fail_gets:
            self.fail_gets -= 1
            raise IOError("t2 channel down")
        return self.store.get(key)

    def delete(self, key):
        self.store.pop(key, None)


def _tier_setup(host_entries, *, entry_tokens=8, wire_dtype=None,
                remote=None, n_slots=2):
    backend = _TierStubBackend(n_slots=n_slots)
    pool = SlotPool(n_slots)
    pc = PrefixCache(4)
    tiers = TieredKVCache(
        host_bytes=host_entries * _entry_bytes(entry_tokens) + 1,
        wire_dtype=wire_dtype, remote=remote,
    )
    tiers.attach(backend, pc)
    return backend, pool, pc, tiers


def _park(backend, pool, pc, rid, prompt, seed):
    slot = pool.admit(rid)
    backend.fill(slot, prompt.size, seed)
    assert pc.park(pool, slot, prompt)
    return slot


class TestTierManager:
    def test_demotion_moves_entry_to_exactly_one_tier(self):
        backend, pool, pc, tiers = _tier_setup(4)
        p = np.arange(8, dtype=np.int32)
        _park(backend, pool, pc, 0, p, seed=1)
        d0 = obs.counter("kv_tier_demotions_total").get(tier="t1")
        victim = pc.evict_lru(pool, demote=tiers.demote)
        assert victim is not None and pool.n_free == 2
        # the entry lives in T1 and ONLY T1: no parked slot remains, the
        # trie's resident is the tier ref, and a match still finds it
        assert pool.n_parked == 0 and len(tiers.t1) == 1
        assert pc.n_resident == 0 and pc.n_tier_refs == 1
        m, donor = pc.match(np.concatenate([p, [9]]).astype(np.int32))
        assert m == 8 and isinstance(donor, TierRef)
        assert donor.tier == "t1" and donor.exact
        assert obs.counter("kv_tier_demotions_total").get(
            tier="t1") == d0 + 1
        assert obs.gauge("kv_tier_resident_bytes").get(
            tier="t1") == _entry_bytes(8)
        assert obs.gauge("kv_tier_resident_tokens").get(tier="t1") == 8

    def test_promotion_bit_exact_and_never_evicts_donor(self):
        backend, pool, pc, tiers = _tier_setup(4)
        p = np.arange(8, dtype=np.int32)
        slot = pool.admit(0)
        k_orig, v_orig = backend.fill(slot, 8, seed=2)
        assert pc.park(pool, slot, p)
        pc.evict_lru(pool, demote=tiers.demote)
        ref = pc.peek_donor(np.concatenate([p, [9]]).astype(np.int32))
        pr0 = obs.counter("kv_tier_promotions_total").get(tier="t1")
        # promote TWICE into different slots: the donor entry is read in
        # place, so the second hit must still find it intact
        for rid, seed in ((1, 77), (2, 78)):
            dst = pool.admit(rid)
            backend.fill(dst, 8, seed=seed)  # stale garbage to overwrite
            assert tiers.promote(ref, dst, 8)
            assert np.array_equal(backend.k[:, dst, :8], k_orig)
            assert np.array_equal(backend.v[:, dst, :8], v_orig)
            assert len(tiers.t1) == 1  # donor survived serving the hit
            pool.free(dst)
        assert obs.counter("kv_tier_promotions_total").get(
            tier="t1") == pr0 + 2

    def test_full_t1_drops_counted_never_blocks(self):
        """1-entry host pool under 3 demotions (no T2): each demotion
        succeeds immediately — the pool spills its LRU entry OUT (counted
        on drops) rather than refusing the newcomer, and the spilled
        entries' trie refs are gone."""
        backend, pool, pc, tiers = _tier_setup(1, n_slots=3)
        d0 = obs.counter("kv_tier_drops_total").get(tier="t1")
        prompts = [np.asarray([i, i, i + 1, i + 1, i + 2, i + 2, i + 3,
                               i + 3], np.int32) for i in (10, 20, 30)]
        for i, p in enumerate(prompts):
            _park(backend, pool, pc, i, p, seed=i)
            assert pc.evict_lru(pool, demote=tiers.demote) is not None
        assert len(tiers.t1) == 1
        assert tiers.t1.used_bytes <= tiers.t1.capacity_bytes
        assert obs.counter("kv_tier_drops_total").get(tier="t1") == d0 + 2
        # only the LAST prefix survived the churn
        hits = [pc.match(np.concatenate([p, [9]]).astype(np.int32))[0]
                for p in prompts]
        assert hits == [0, 0, 8]

    def test_oversize_entry_dropped_not_stored(self):
        backend, pool, pc, tiers = _tier_setup(1, entry_tokens=4)
        d0 = obs.counter("kv_tier_drops_total").get(tier="t1")
        p = np.arange(8, dtype=np.int32)  # 8-token entry > 4-token pool
        _park(backend, pool, pc, 0, p, seed=3)
        assert pc.evict_lru(pool, demote=tiers.demote) is not None
        assert len(tiers.t1) == 0 and pc.n_tier_refs == 0
        assert obs.counter("kv_tier_drops_total").get(tier="t1") == d0 + 1
        assert pc.match(np.concatenate([p, [9]]).astype(np.int32))[0] == 0

    def test_stale_ref_promotes_false(self):
        backend, pool, pc, tiers = _tier_setup(4)
        p = np.arange(8, dtype=np.int32)
        _park(backend, pool, pc, 0, p, seed=4)
        pc.evict_lru(pool, demote=tiers.demote)
        ref = pc.peek_donor(np.concatenate([p, [9]]).astype(np.int32))
        tiers.t1.pop(ref.key)  # simulate out-of-band loss
        dst = pool.admit(1)
        assert tiers.promote(ref, dst, 8) is False
        with pytest.raises(ValueError, match="promote of"):
            tiers.promote(ref, dst, 9)

    def test_t2_stale_ref_caller_owns_trie_drop(self):
        """promote() returning False on a stale T2 ref must release only
        the tier accounting and leave the trie resident to the CALLER
        (the engine drops it next) — dropping it inside promote too made
        the engine's follow-up ``replace_ref`` KeyError out of
        admission."""
        remote = _FakeRemote()
        backend, pool, pc, tiers = _tier_setup(1, remote=remote)
        pa = np.asarray([1, 1, 2, 2, 3, 3, 4, 4], np.int32)
        pb = np.asarray([5, 5, 6, 6, 7, 7, 8, 8], np.int32)
        for i, p in enumerate((pa, pb)):
            _park(backend, pool, pc, i, p, seed=i)
            pc.evict_lru(pool, demote=tiers.demote)
        ref = pc.peek_donor(np.concatenate([pa, [9]]).astype(np.int32))
        assert isinstance(ref, TierRef) and ref.tier == "t2"
        remote.store.clear()  # the peer lost the entry out-of-band
        d0 = obs.counter("kv_tier_drops_total").get(tier="t2")
        dst = pool.admit(9)
        assert tiers.promote(ref, dst, 8) is False
        assert ref in pc._resident  # the trie drop was left to us
        pc.replace_ref(ref, None)  # the engine's follow-up: must not raise
        assert ref not in pc._resident and pc.n_tier_refs == 1  # pb's ref
        assert obs.counter("kv_tier_drops_total").get(tier="t2") == d0 + 1
        assert remote.used_bytes == 0 and remote.used_tokens == 0

    def test_spill_remote_failure_drops_counted_never_raises(self):
        """A remote-put failure mid-spill degrades to the counted T1 drop
        (demotion never raises into the admission path), and after
        ``remote_fail_limit`` consecutive failures the tier latches dead
        so later spills stop touching the channel."""
        remote = _FakeRemote(fail_puts=99)
        backend, pool, pc, tiers = _tier_setup(1, remote=remote)
        d0 = obs.counter("kv_tier_drops_total").get(tier="t1")
        prompts = [np.asarray([i, i, i + 1, i + 1, i + 2, i + 2, i + 3,
                               i + 3], np.int32)
                   for i in (10, 20, 30, 40, 50)]
        for i, p in enumerate(prompts):
            _park(backend, pool, pc, i, p, seed=i)
            assert pc.evict_lru(pool, demote=tiers.demote) is not None
        # 4 spills attempted; the channel was only tried until the latch
        assert tiers._remote_dead
        assert remote.puts == tiers.remote_fail_limit
        assert obs.counter("kv_tier_drops_total").get(tier="t1") == d0 + 4
        assert len(tiers.t1) == 1 and pc.n_tier_refs == 1
        # the dropped prefixes left the trie; the survivor still hits
        hits = [pc.match(np.concatenate([p, [9]]).astype(np.int32))[0]
                for p in prompts]
        assert hits == [0, 0, 0, 0, 8]

    def test_stale_hit_counters_degrade_to_miss(self):
        """The reuse ledger on a stale deep ref: match() defers deep-tier
        hit counting to commit_hit(), so a failed promotion counts ONE
        miss and zero hit/reused tokens — metrics never credit skipped
        compute that was not skipped."""
        backend, pool, pc, tiers = _tier_setup(4)
        p = np.arange(8, dtype=np.int32)
        _park(backend, pool, pc, 0, p, seed=6)
        pc.evict_lru(pool, demote=tiers.demote)
        h0 = obs.counter("prefix_cache_hits_total").get()
        m0 = obs.counter("prefix_cache_misses_total").get()
        t0 = obs.counter("prefix_cache_tokens_reused_total").get()
        q = np.concatenate([p, [9]]).astype(np.int32)
        matched, donor = pc.match(q)
        assert matched == 8 and isinstance(donor, TierRef)
        assert obs.counter("prefix_cache_hits_total").get() == h0
        tiers.t1.pop(donor.key)  # lose the bytes: the promotion fails
        assert tiers.promote(donor, pool.admit(1), 8) is False
        pc.replace_ref(donor, None)  # the engine's stale sequence
        pc.count_stale_miss()
        assert obs.counter("prefix_cache_hits_total").get() == h0
        assert obs.counter("prefix_cache_misses_total").get() == m0 + 1
        assert (obs.counter("prefix_cache_tokens_reused_total").get()
                == t0)

    def test_release_is_idempotent_and_gauges_zero(self):
        backend, pool, pc, tiers = _tier_setup(4)
        p = np.arange(8, dtype=np.int32)
        _park(backend, pool, pc, 0, p, seed=5)
        pc.evict_lru(pool, demote=tiers.demote)
        ref = pc.peek_donor(np.concatenate([p, [9]]).astype(np.int32))
        pc.replace_ref(ref, None)  # trie drop → embedded release
        assert len(tiers.t1) == 0 and tiers.t1.used_bytes == 0
        tiers.release(ref)  # second release: no-op, no underflow
        assert tiers.t1.used_bytes == 0 and tiers.t1.used_tokens == 0
        assert obs.gauge("kv_tier_resident_bytes").get(tier="t1") == 0
        assert obs.gauge("kv_tier_resident_tokens").get(tier="t1") == 0

    def test_host_tier_rejects_duplicates_and_bad_capacity(self):
        with pytest.raises(ValueError, match="capacity_bytes"):
            HostKVTier(0)
        t1 = HostKVTier(1 << 20)
        blob = np.zeros(16, np.uint8)
        ref = TierRef("t1", 0, 4, True, 16)
        t1.put(0, blob, {}, ref)
        with pytest.raises(ValueError, match="already stored"):
            t1.put(0, blob, {}, ref)


def chan_pair(server_ep, client_ep, n_paths=2):
    res = {}
    t = threading.Thread(
        target=lambda: res.setdefault("c", Channel.accept(server_ep)))
    t.start()
    c = Channel.connect(client_ep, "127.0.0.1", server_ep.port,
                        n_paths=n_paths)
    t.join(timeout=20)
    assert "c" in res, "channel accept timed out"
    return res["c"], c


class TestRemoteTier:
    def test_put_get_delete_over_loopback(self, rng):
        """The T2 wire: CRC-verified put/get round trips bit-exactly
        through real endpoints, unknown keys miss, deletes take, and the
        service-level ingress rides p2p_bytes_total{verb="kv_tier"}."""
        k, v = _rows(rng, 8)
        blob, meta = encode_entry(k, v)
        verb0 = obs.counter("p2p_bytes_total").get(verb="kv_tier")
        srv = KvTierServer(capacity_bytes=4 * blob.nbytes)
        with Endpoint(n_engines=2) as sep, Endpoint(n_engines=2) as cep:
            schan, cchan = chan_pair(sep, cep)
            t = srv.serve_forever(schan, timeout_ms=2000)
            cli = RemoteKVTier(cchan, max_entry_bytes=blob.nbytes,
                               timeout_ms=2000)
            assert cli.put(7, blob, meta) == []
            got = cli.get(7)
            assert got is not None
            k2, v2 = decode_entry(*got)
            assert np.array_equal(k, k2) and np.array_equal(v, v2)
            assert cli.get(99) is None
            cli.delete(7)
            assert cli.get(7) is None
            cli.close()
            t.join(timeout=20)
        assert (obs.counter("p2p_bytes_total").get(verb="kv_tier")
                >= verb0 + 2 * blob.nbytes)  # put ingress + get egress

    def test_server_eviction_notice_rides_put_response(self, rng):
        """A 2-entry server under 3 puts LRU-drops the oldest key and
        NAMES it in the put response — the client's eager-invalidation
        feed (discovering staleness at promotion time would cost a wire
        round trip per doomed hit)."""
        k, v = _rows(rng, 4)
        blob, meta = encode_entry(k, v)
        srv = KvTierServer(capacity_bytes=2 * blob.nbytes)
        with Endpoint(n_engines=2) as sep, Endpoint(n_engines=2) as cep:
            schan, cchan = chan_pair(sep, cep)
            t = srv.serve_forever(schan, timeout_ms=2000)
            cli = RemoteKVTier(cchan, max_entry_bytes=blob.nbytes,
                               timeout_ms=2000)
            assert cli.put(1, blob, meta) == []
            assert cli.put(2, blob, meta) == []
            assert cli.put(3, blob, meta) == [1]  # oldest key evicted
            assert cli.get(1) is None and cli.get(3) is not None
            # an entry larger than the server's whole capacity is refused
            big = np.zeros(3 * blob.nbytes, np.uint8)
            assert cli.put(4, big, {"enc": "raw", "shape": [1]}) is None
            cli.close()
            t.join(timeout=20)

    def test_entry_larger_than_client_window_guarded_both_sides(self, rng):
        """Nothing may writev past the client's registered scratch
        window: an oversize put is refused CLIENT-side before touching
        the wire, and a get whose stored entry exceeds the requesting
        client's advertised window is served as a miss, never as an
        overrunning write."""
        k, v = _rows(rng, 8)
        blob, meta = encode_entry(k, v)
        srv = KvTierServer(capacity_bytes=4 * blob.nbytes)
        with Endpoint(n_engines=2) as sep, Endpoint(n_engines=2) as cep:
            schan, cchan = chan_pair(sep, cep)
            t = srv.serve_forever(schan, timeout_ms=2000)
            cli = RemoteKVTier(cchan, max_entry_bytes=blob.nbytes,
                               timeout_ms=2000)
            big = np.zeros(blob.nbytes + 1, np.uint8)
            assert cli.put(1, big, {"enc": "raw", "shape": [1]}) is None
            assert len(srv) == 0  # the oversize put never hit the wire
            assert cli.put(2, blob, meta) == []
            # shrink the advertised window: the server must miss rather
            # than write past the registration
            cli.max_entry_bytes = blob.nbytes - 1
            assert cli.get(2) is None
            cli.max_entry_bytes = blob.nbytes
            assert cli.get(2) is not None
            cli.close()
            t.join(timeout=20)


def _engine_with_tiers(backend, tiers):
    pc = PrefixCache(4)
    return ServingEngine(backend, prefill_chunk=4, prefix_cache=pc,
                         kv_tiers=tiers)


def _oracle(params, cfg, req):
    import jax.numpy as jnp

    from uccl_tpu.models.inference import generate

    toks = generate(params, jnp.asarray(req.prompt)[None], cfg,
                    max_new_tokens=req.max_new_tokens, max_seq=MAX_SEQ)
    return np.asarray(toks)[0, : req.n_generated].tolist()


@pytest.fixture(scope="module")
def dense_setup():
    """Same config family as test_serving/test_prefix_cache so the
    one-shot oracle programs are _GEN_CACHE hits across files."""
    import jax

    from uccl_tpu.models import dense
    from uccl_tpu.serving import DenseBackend

    cfg = dense.DenseConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=8,
        ffn=64,
    )
    params = dense.init_params(jax.random.PRNGKey(0), cfg)
    backend = DenseBackend(params, cfg, n_slots=2, max_seq=MAX_SEQ)
    return cfg, params, backend


class TestDenseTieredExact:
    def test_demote_promote_cycles_stay_bit_exact(self, dense_setup):
        """THE acceptance property: a working set of 4 distinct prefixes
        through 2 device slots — every donor is LRU-demoted to the host
        tier before its prefix returns, so round two serves exclusively
        tier promotions, and every output (both rounds) bit-equals the
        one-shot oracle with ``cache_hit_exact`` True throughout."""
        cfg, params, backend = dense_setup
        tiers = TieredKVCache(host_bytes=1 << 20)
        eng = _engine_with_tiers(backend, tiers)
        pr0 = obs.counter("kv_tier_promotions_total").get(tier="t1")
        h0 = obs.counter("prefix_cache_hits_total").get()
        s0 = sum(obs.counter("kv_tier_hits_total").get(tier=t)
                 for t in ("t0", "t1", "t2"))
        rng = np.random.default_rng(7)
        bases = [rng.integers(0, 64, 12).astype(np.int32)
                 for _ in range(4)]
        reqs = []
        for rnd in range(2):
            for p in bases:
                reqs.append(eng.submit(p.copy(), max_new_tokens=4))
                eng.drain()
        promoted = (obs.counter("kv_tier_promotions_total").get(tier="t1")
                    - pr0)
        assert promoted >= 4, "round two never hit the host tier"
        # the per-tier hit split sums to the trie's hit counter
        split = sum(obs.counter("kv_tier_hits_total").get(tier=t)
                    for t in ("t0", "t1", "t2")) - s0
        assert (obs.counter("prefix_cache_hits_total").get() - h0
                == split > 0)
        hits = [r.cache_hit_len for r in reqs]
        assert hits[:4] == [0] * 4 and all(h == 8 for h in hits[4:]), hits
        for r in reqs:
            assert r.cache_hit_exact is True
            assert r.out_tokens == _oracle(params, cfg, r), r.rid
        assert eng.pool.leaked() == 0
        eng.prefix_cache.clear(eng.pool)

    def test_promote_failure_degrades_to_cold_miss(self, dense_setup):
        """A stale tier ref at admission (entry lost under the trie) must
        cold-prefill and still match the oracle — never serve garbage —
        and the reuse ledger must record it as the miss it became, not
        the hit it promised."""
        cfg, params, backend = dense_setup
        tiers = TieredKVCache(host_bytes=1 << 20)
        eng = _engine_with_tiers(backend, tiers)
        rng = np.random.default_rng(8)
        p = rng.integers(0, 64, 12).astype(np.int32)
        eng.submit(p.copy(), max_new_tokens=4)
        eng.drain()
        eng.prefix_cache.evict_lru(eng.pool, demote=tiers.demote)
        for ref in eng.prefix_cache.tier_refs():
            tiers.t1.pop(ref.key)  # lose the bytes, keep the trie ref
        h0 = obs.counter("prefix_cache_hits_total").get()
        m0 = obs.counter("prefix_cache_misses_total").get()
        t0 = obs.counter("prefix_cache_tokens_reused_total").get()
        r = eng.submit(p.copy(), max_new_tokens=4)
        eng.drain()
        assert r.cache_hit_len == 0  # the stale hit became a cold miss
        assert obs.counter("prefix_cache_hits_total").get() == h0
        assert obs.counter("prefix_cache_misses_total").get() == m0 + 1
        assert (obs.counter("prefix_cache_tokens_reused_total").get()
                == t0)
        assert r.out_tokens == _oracle(params, cfg, r)
        assert eng.pool.leaked() == 0
        eng.prefix_cache.clear(eng.pool)

    def test_t2_stale_ref_cold_miss_through_engine(self, dense_setup):
        """The exact scenario REVIEW flagged: a remote peer answering a
        promotion's get with a miss. Admission must degrade to a counted
        cold miss (no KeyError out of the trie drop), stay oracle-exact,
        and leak nothing."""
        cfg, params, backend = dense_setup
        remote = _FakeRemote()
        tiers = TieredKVCache(host_bytes=1 << 20, remote=remote)
        eng = _engine_with_tiers(backend, tiers)
        rng = np.random.default_rng(11)
        p = rng.integers(0, 64, 12).astype(np.int32)
        eng.submit(p.copy(), max_new_tokens=4)
        eng.drain()
        eng.prefix_cache.evict_lru(eng.pool, demote=tiers.demote)  # → t1
        tiers._spill_lru()  # → t2 (the fake peer)
        assert [r.tier for r in eng.prefix_cache.tier_refs()] == ["t2"]
        remote.store.clear()  # the peer LRU-dropped the entry
        r = eng.submit(p.copy(), max_new_tokens=4)
        eng.drain()
        assert r.cache_hit_len == 0
        assert eng.prefix_cache.n_tier_refs == 0  # stale ref dropped once
        assert r.out_tokens == _oracle(params, cfg, r)
        assert eng.pool.leaked() == 0
        eng.prefix_cache.clear(eng.pool)


@pytest.mark.slow
class TestDenseQuantizedAtRest:
    def test_fp8_hits_stamped_inexact_and_bounded(self, dense_setup):
        """The opt-in lossy tier: deep hits resume from fp8-at-rest rows —
        each request that reused them carries ``cache_hit_exact=False``
        (attributable divergence, never silent), cold requests stay True,
        and generation still completes to budget."""
        cfg, params, backend = dense_setup
        tiers = TieredKVCache(host_bytes=1 << 20, wire_dtype="fp8")
        assert not tiers.exact
        eng = _engine_with_tiers(backend, tiers)
        rng = np.random.default_rng(9)
        bases = [rng.integers(0, 64, 12).astype(np.int32)
                 for _ in range(4)]
        reqs = []
        for rnd in range(2):
            for p in bases:
                reqs.append(eng.submit(p.copy(), max_new_tokens=4))
                eng.drain()
        deep = [r for r in reqs if not r.cache_hit_exact]
        assert len(deep) >= 4, "no request ever resumed from fp8 rows"
        assert all(r.cache_hit_len == 8 for r in deep)
        assert all(r.cache_hit_exact for r in reqs[:4])  # cold round
        assert all(r.n_generated == 4 for r in reqs)
        assert eng.pool.leaked() == 0
        eng.prefix_cache.clear(eng.pool)


@pytest.mark.slow
class TestMoETieredExact:
    def test_moe_demote_promote_bit_exact(self, devices):
        """The lossless tier through the EP-sharded MoE stack: the grid-
        mapped export/import views feed the same codec, and demote→promote
        cycles stay bit-exact vs the world-1 oracle."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from uccl_tpu.models.moe_inference import (
            MoEServeConfig, MoEServer, init_params,
        )
        from uccl_tpu.serving import MoEBackend

        cfg = MoEServeConfig(
            vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
            head_dim=8, moe_experts=8, moe_topk=2, moe_ffn=64,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        srv = MoEServer(cfg, Mesh(np.array(devices[:2]), ("dp",)))
        backend = MoEBackend(srv, srv.shard_params(params), batch_local=1,
                             max_seq=MAX_SEQ)
        tiers = TieredKVCache(host_bytes=1 << 20)
        eng = ServingEngine(backend, prefill_chunk=3,
                            prefix_cache=PrefixCache(3), kv_tiers=tiers)
        srv1 = MoEServer(cfg, Mesh(np.array(devices[:1]), ("dp",)))
        p1p = srv1.shard_params(params)
        pr0 = obs.counter("kv_tier_promotions_total").get(tier="t1")
        rng = np.random.default_rng(0)
        bases = [rng.integers(0, 64, 8).astype(np.int32)
                 for _ in range(3)]
        reqs = []
        for rnd in range(2):
            for p in bases:
                reqs.append(eng.submit(p.copy(), max_new_tokens=4))
                eng.drain()
        assert (obs.counter("kv_tier_promotions_total").get(tier="t1")
                > pr0), "no MoE promotion exercised"
        assert all(r.cache_hit_len == 6 for r in reqs[3:])
        for r in reqs:
            assert r.cache_hit_exact is True
            want = srv1.generate(p1p, jnp.asarray(r.prompt)[None, None],
                                 r.max_new_tokens, MAX_SEQ, impl="ll")
            assert r.out_tokens == np.asarray(want)[0, 0].tolist(), r.rid
        assert eng.pool.leaked() == 0
