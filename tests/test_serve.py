"""python -m uccl_tpu.serve: trainer checkpoints served through the EP
prefill/decode paths (the train -> checkpoint -> serve handoff)."""

import json
import os
import subprocess
import sys

import pytest

pytest.importorskip("orbax.checkpoint")

from uccl_tpu.utils import jaxcompat

# Every test here first TRAINS a checkpoint in a subprocess, which
# needs the modern shard_map vjp (legacy 0.4.x raises _SpecError on
# rank-0 residuals) — same gate as test_trainer/test_flagship. The
# serving paths themselves are covered without training by
# tests/test_serving.py and the CI serving smoke tier.
pytestmark = pytest.mark.skipif(
    not jaxcompat.MODERN_SHARD_MAP,
    reason="legacy shard_map vjp mishandles rank-0 residuals",
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(mod, extra, timeout=560):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", mod] + extra,
        capture_output=True, text=True, timeout=timeout, env=env, cwd=_REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout


def test_serve_trained_checkpoint_both_ep_paths(tmp_path):
    ck = str(tmp_path / "ck")
    _run("uccl_tpu.train", [
        "--devices", "8", "--mesh", "dp=2,cp=2,tp=2", "--batch", "4",
        "--seq", "32", "--steps", "2", "--log-every", "0",
        "--ckpt-dir", ck, "--ckpt-every", "2",
    ])
    seqs = {}
    for impl in ("ll", "sort"):
        out = _run("uccl_tpu.serve", [
            "--devices", "8", "--ckpt-dir", ck, "--batch", "8",
            "--prompt-len", "6", "--new-tokens", "8", "--impl", impl,
        ])
        assert f"serving {ck}/step_2" in out
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["ckpt_step"] == 2 and summary["impl"] == impl
        seqs[impl] = [
            l for l in out.splitlines() if l.startswith("first sequence")
        ][0]
    # greedy decode over the same params: the packed LL path and the sorted
    # throughput path must emit the same tokens (EP-path generation parity)
    assert seqs["ll"] == seqs["sort"]


def test_serve_rejects_mismatched_size_flags(tmp_path):
    ck = str(tmp_path / "ck")
    _run("uccl_tpu.train", [
        "--devices", "8", "--batch", "8", "--seq", "32", "--steps", "1",
        "--log-every", "0", "--ckpt-dir", ck, "--ckpt-every", "1",
    ])
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "uccl_tpu.serve", "--devices", "8",
         "--ckpt-dir", ck, "--vocab", "512"],
        capture_output=True, text=True, timeout=420, env=env, cwd=_REPO,
    )
    assert r.returncode != 0
    # conflicts are caught against the recorded config.json (new ckpts) or
    # by leaf-shape checks ("size flags") for config-less checkpoints
    assert ("!= checkpoint config" in r.stderr
            or "pass the training run's size flags" in r.stderr)


def test_serve_cross_topology(tmp_path):
    """A checkpoint trained on an 8-device mesh serves on a 4-device world:
    params restore to host numpy (metadata-derived restore args), so the
    serving topology is free."""
    ck = str(tmp_path / "ck")
    _run("uccl_tpu.train", [
        "--devices", "8", "--batch", "8", "--seq", "32", "--steps", "1",
        "--log-every", "0", "--ckpt-dir", ck, "--ckpt-every", "1",
    ])
    out = _run("uccl_tpu.serve", [
        "--devices", "4", "--ckpt-dir", ck, "--batch", "8",
        "--prompt-len", "4", "--new-tokens", "4",
    ])
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["world"] == 4 and summary["ckpt_step"] == 1


def test_serve_dense_checkpoint(tmp_path):
    """Dense-family trainer checkpoints generate through the cached
    single-shard KV path (config.json routes the family)."""
    ck = str(tmp_path / "ck")
    _run("uccl_tpu.train", [
        "--devices", "8", "--model", "dense", "--batch", "8", "--seq", "32",
        "--steps", "1", "--log-every", "0",
        "--ckpt-dir", ck, "--ckpt-every", "1",
    ])
    out = _run("uccl_tpu.serve", [
        "--devices", "8", "--ckpt-dir", ck, "--batch", "4",
        "--prompt-len", "4", "--new-tokens", "6",
    ])
    assert "(dense)" in out
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["impl"] == "dense" and summary["new_tokens"] == 6
