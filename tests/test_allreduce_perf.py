"""nccl-tests-shaped acceptance benchmark over the loadable net plugin.

The reference's system-level acceptance gate is nccl-tests' all_reduce_perf
against its NCCL net plugin (SURVEY.md §4.5). Our analog binary
(native/tests/allreduce_perf.cc) forks N ranks, speaks only the ucclt_net_v1
vtable via dlopen, and ring-allreduces with exact correctness checks — this
test builds and runs it at world 2 and 4.
"""

import os
import subprocess

import pytest

_NATIVE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)


@pytest.fixture(scope="module")
def binaries():
    r = subprocess.run(
        ["make", "-C", _NATIVE, "build/allreduce_perf",
         "build/libuccl_tpu_net.so"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    return (
        os.path.join(_NATIVE, "build", "allreduce_perf"),
        os.path.join(_NATIVE, "build", "libuccl_tpu_net.so"),
    )


@pytest.mark.parametrize("world", [2, 4])
def test_allreduce_perf_correct(binaries, world):
    exe, plugin = binaries
    r = subprocess.run(
        [exe, "-n", str(world), "-b", "1024", "-e", "65536", "-i", "2",
         "-w", "1", "-p", plugin],
        capture_output=True, text=True, timeout=240,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "# OK" in r.stdout
    rows = [l for l in r.stdout.splitlines() if not l.startswith("#")]
    assert len(rows) == 7  # 1K..64K at factor 2
    for row in rows:
        cols = row.split()
        assert cols[-1] == "0"  # wrong column
        assert float(cols[2]) > 0  # measured time


@pytest.mark.parametrize("world,bytes_", [(3, 1024), (7, 64)])
def test_allreduce_perf_ragged_segments(binaries, world, bytes_):
    """Rank counts that don't divide the element count produce short and
    empty ring segments — both sides must agree on per-direction sizes."""
    exe, plugin = binaries
    r = subprocess.run(
        [exe, "-n", str(world), "-b", str(bytes_), "-e", str(bytes_),
         "-i", "2", "-w", "1", "-p", plugin],
        capture_output=True, text=True, timeout=240,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "# OK" in r.stdout


def test_allreduce_perf_multi_nic_devices(binaries):
    """UCCL_TPU_NIC_LIST exposes one plugin device per NIC (reference:
    nccl_plugin.cc device enumeration); ranks round-robin devices, so this
    ring crosses two logical devices bound to distinct loopback NICs."""
    exe, plugin = binaries
    env = dict(os.environ, UCCL_TPU_NIC_LIST="127.0.0.41,127.0.0.42")
    r = subprocess.run(
        [exe, "-n", "2", "-b", "1024", "-e", "16384", "-i", "2",
         "-w", "1", "-p", plugin],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "# OK" in r.stdout
    for row in [l for l in r.stdout.splitlines() if not l.startswith("#")]:
        assert row.split()[-1] == "0"
