"""Continuous-batching engine: scheduler/slot properties + oracle exactness.

Two layers of test:

* **Properties** (stub backend, host-only, fast): FIFO admission order, no
  leaked slots after drain, retirement on EOS and on max-tokens,
  backpressure under a bounded queue, metrics conservation
  (submitted == completed + active + queued + rejected).
* **Oracle exactness** (real models): with ≥2 slots and staggered
  mixed-length arrivals, every request's tokens are bit-identical to the
  one-shot ``generate`` oracle — for the dense stack and for the EP MoE
  stack on a multi-shard mesh (whose oracle is the world-1 server; the
  repo's parity tests prove world-independence separately).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uccl_tpu.serving import (
    DenseBackend, MoEBackend, RequestState, ServingEngine,
)
from uccl_tpu.serving.metrics import percentile


class _StubBackend:
    """Deterministic token emitter: prefill emits 0, the i-th decode step
    emits i — EOS behavior is then fully predictable with no model."""

    def __init__(self, n_slots=2, max_seq=64):
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.n_decodes = 0

    def prefill(self, tokens, lens, mask):
        return np.zeros(self.n_slots, np.int32)

    def decode(self, tokens, active):
        self.n_decodes += 1
        return np.full(self.n_slots, self.n_decodes, np.int32)


def _prompt(rng, n):
    return rng.integers(0, 64, n).astype(np.int32)


class TestSchedulerProperties:
    def test_fifo_admission_order(self):
        eng = ServingEngine(_StubBackend(n_slots=2))
        reqs = [eng.submit([1, 2], max_new_tokens=3) for _ in range(7)]
        eng.drain()
        seqs = [r.admit_seq for r in reqs]
        assert seqs == sorted(seqs), "admission must preserve FIFO order"
        assert all(r.state is RequestState.FINISHED for r in reqs)

    def test_no_leaked_slots_after_drain(self):
        eng = ServingEngine(_StubBackend(n_slots=3))
        for i in range(8):
            eng.submit([1, 2, 3], max_new_tokens=2 + i % 3)
        eng.drain()
        assert eng.pool.leaked() == 0
        assert eng.pool.total_admits == eng.pool.total_frees == 8
        assert eng.pool.high_water <= eng.pool.n_slots

    def test_retirement_on_max_tokens(self):
        eng = ServingEngine(_StubBackend(n_slots=1))
        r = eng.submit([5], max_new_tokens=4)
        eng.drain()
        assert r.finish_reason == "length"
        assert r.n_generated == 4

    def test_retirement_on_eos(self):
        # stub emits 0 (prefill), 1, 2, ... — eos_id=2 retires mid-decode
        # after exactly 3 tokens, well under the 10-token budget
        eng = ServingEngine(_StubBackend(n_slots=1))
        r = eng.submit([5], max_new_tokens=10, eos_id=2)
        eng.drain()
        assert r.finish_reason == "eos"
        assert r.out_tokens == [0, 1, 2]

    def test_eos_at_prefill(self):
        eng = ServingEngine(_StubBackend(n_slots=1))
        r = eng.submit([5], max_new_tokens=10, eos_id=0)
        eng.drain()
        assert r.finish_reason == "eos" and r.out_tokens == [0]

    def test_backpressure_rejects_when_full(self):
        # 2 slots + queue bound 2: submissions beyond slots+queue reject
        eng = ServingEngine(_StubBackend(n_slots=2), max_queue=2)
        results = [eng.submit([1], max_new_tokens=3) for _ in range(8)]
        rejected = [r for r in results if r is None]
        accepted = [r for r in results if r is not None]
        assert len(rejected) == 6  # nothing admitted before the first step
        assert eng.metrics.rejected == 6
        eng.drain()
        assert eng.metrics.completed == len(accepted)
        assert eng.pool.leaked() == 0

    def test_queue_drains_between_steps(self):
        # backpressure QUEUES when slots are busy but the queue has room
        eng = ServingEngine(_StubBackend(n_slots=1), max_queue=8)
        reqs = [eng.submit([1], max_new_tokens=2) for _ in range(4)]
        assert all(r is not None for r in reqs)
        snap = eng.snapshot()
        assert snap["queued"] == 4 and snap["active"] == 0
        eng.drain()
        assert all(r.state is RequestState.FINISHED for r in reqs)

    def test_metrics_snapshot_consistency(self):
        eng = ServingEngine(_StubBackend(n_slots=2), max_queue=3)
        for _ in range(9):
            eng.submit([1, 2], max_new_tokens=6)
        # mid-flight and at every step boundary, requests are conserved:
        for _ in range(3):
            eng.step()
            s = eng.snapshot()
            assert (s["submitted"]
                    == s["completed"] + s["active"] + s["queued"]
                    + s["rejected"]), s
        eng.drain()
        s = eng.snapshot()
        assert s["active"] == s["queued"] == 0
        assert s["submitted"] == s["completed"] + s["rejected"]
        assert s["admitted"] == s["completed"]

    def test_submit_validation(self):
        eng = ServingEngine(_StubBackend(n_slots=1, max_seq=16))
        with pytest.raises(ValueError, match="non-empty"):
            eng.submit([], max_new_tokens=2)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit([1], max_new_tokens=0)
        with pytest.raises(ValueError, match="overflow"):
            eng.submit(np.arange(14), max_new_tokens=4)

    def test_percentile_helper(self):
        assert percentile([], 50) is None
        assert percentile([3.0], 95) == 3.0
        xs = [1.0, 2.0, 3.0, 4.0]
        assert percentile(xs, 50) == pytest.approx(2.5)
        assert percentile(xs, 100) == 4.0
        np.testing.assert_allclose(
            [percentile(xs, q) for q in (25, 95)],
            [np.percentile(xs, 25), np.percentile(xs, 95)],
        )


MAX_SEQ = 32


@pytest.fixture(scope="module")
def dense_setup():
    """Params + ONE shared backend: its per-shape jit cache then makes the
    later tests' compiles cache hits (and exercises cross-engine slot-pool
    reuse for free). Tier-1 wall time matters — the oracle (len, N) pairs
    below repeat across tests for the same reason (_GEN_CACHE hits)."""
    from uccl_tpu.models import dense

    cfg = dense.DenseConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=8,
        ffn=64,
    )
    params = dense.init_params(jax.random.PRNGKey(0), cfg)
    backend = DenseBackend(params, cfg, n_slots=2, max_seq=MAX_SEQ)
    return cfg, params, backend


class TestDenseOracle:
    def _oracle(self, params, cfg, req):
        from uccl_tpu.models.inference import generate

        toks = generate(params, jnp.asarray(req.prompt)[None], cfg,
                        max_new_tokens=req.max_new_tokens, max_seq=MAX_SEQ)
        return np.asarray(toks)[0, : req.n_generated].tolist()

    def test_staggered_mixed_lengths_exact(self, dense_setup):
        """The acceptance anchor: 2 slots, 6 mixed-length requests arriving
        mid-decode of each other — every emitted sequence bit-equals the
        one-shot oracle."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(0)
        eng = ServingEngine(backend)
        reqs = [eng.submit(_prompt(rng, 5), max_new_tokens=6),
                eng.submit(_prompt(rng, 3), max_new_tokens=4)]
        eng.step()  # both admitted, mid-decode...
        eng.step()
        for n, m in ((8, 5), (2, 6), (6, 3), (7, 5)):  # ...arrivals join
            reqs.append(eng.submit(_prompt(rng, n), max_new_tokens=m))
        eng.drain()
        assert eng.pool.leaked() == 0
        for r in reqs:
            assert r.n_generated == r.max_new_tokens
            assert r.out_tokens == self._oracle(params, cfg, r), r.rid
        # lifecycle timing populated for every request
        assert all(r.ttft is not None and r.latency is not None
                   for r in reqs)

    def test_eos_retirement_matches_oracle_prefix(self, dense_setup):
        """Using a token the oracle emits mid-stream as EOS, the engine
        must stop exactly there with the oracle's prefix."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(1)
        prompt = _prompt(rng, 5)
        eng = ServingEngine(backend)
        probe = eng.submit(prompt, max_new_tokens=6)
        eng.drain()
        full = probe.out_tokens
        assert full == self._oracle(params, cfg, probe)
        eos = full[3]
        k = full.index(eos)  # first occurrence may precede position 3
        r = eng.submit(prompt, max_new_tokens=6, eos_id=eos)
        eng.drain()
        assert r.finish_reason == "eos"
        assert r.out_tokens == full[: k + 1]
        assert eng.pool.leaked() == 0

    def test_slot_reuse_after_retirement(self, dense_setup):
        """More requests than slots: retired slots are re-prefilled by
        later requests and stale KV never bleeds into their outputs.
        (len, N) pairs repeat the staggered test's — fresh tokens, cached
        oracle programs."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(2)
        eng = ServingEngine(backend)
        reqs = [eng.submit(_prompt(rng, n), max_new_tokens=m)
                for n, m in ((5, 6), (3, 4), (8, 5), (2, 6), (6, 3), (7, 5))]
        eng.drain()
        assert eng.pool.total_admits == 6 and eng.pool.high_water == 2
        for r in reqs:
            assert r.out_tokens == self._oracle(params, cfg, r), r.rid


class TestMoEOracle:
    def test_staggered_mixed_lengths_exact(self, devices):
        """EP MoE stack on a 2-shard mesh (1 slot per shard): masked
        continuous batching bit-equals the world-1 one-shot oracle under
        staggered mixed-length arrivals. Lean on purpose — every distinct
        prompt shape costs a shard_map compile in the oracle, and tier-1
        wall time is budgeted: 3 lengths in one prefill bucket, one N."""
        from jax.sharding import Mesh

        from uccl_tpu.models.moe_inference import (
            MoEServeConfig, MoEServer, init_params,
        )

        cfg = MoEServeConfig(
            vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
            head_dim=8, moe_experts=8, moe_topk=2, moe_ffn=64,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        srv = MoEServer(cfg, Mesh(np.array(devices[:2]), ("dp",)))
        eng = ServingEngine(MoEBackend(
            srv, srv.shard_params(params), batch_local=1, max_seq=MAX_SEQ,
        ))
        rng = np.random.default_rng(0)
        reqs = [eng.submit(_prompt(rng, 5), max_new_tokens=4),
                eng.submit(_prompt(rng, 6), max_new_tokens=4)]
        eng.step()  # admit + first decode...
        reqs.append(eng.submit(_prompt(rng, 8), max_new_tokens=4))
        eng.drain()
        assert eng.pool.leaked() == 0

        srv1 = MoEServer(cfg, Mesh(np.array(devices[:1]), ("dp",)))
        p1 = srv1.shard_params(params)
        for r in reqs:
            want = srv1.generate(
                p1, jnp.asarray(r.prompt)[None, None], r.max_new_tokens,
                MAX_SEQ, impl="ll",
            )
            assert r.out_tokens == np.asarray(want)[0, 0].tolist(), r.rid

    def test_droppable_capacity_rejected(self, devices):
        """Slot serving's exactness needs a drop-free wire: a config whose
        per-expert capacity cannot cover worst-case routing is refused at
        the slot entry points (outputs would depend on batch neighbors)."""
        from jax.sharding import Mesh

        from uccl_tpu.models.moe_inference import MoEServeConfig, MoEServer

        cfg = MoEServeConfig(moe_experts=32, moe_topk=2,
                             capacity_factor=8.0)
        srv = MoEServer(cfg, Mesh(np.array(devices[:1]), ("dp",)))
        with pytest.raises(ValueError, match="drop-free"):
            srv.slot_cache(1, MAX_SEQ)
