"""Continuous-batching engine: scheduler/slot properties + oracle exactness.

Two layers of test:

* **Properties** (stub backend, host-only, fast): FIFO admission order, no
  leaked slots after drain, retirement on EOS and on max-tokens,
  backpressure under a bounded queue, metrics conservation
  (submitted == completed + active + queued + rejected), lowest-slot-first
  pool reuse, and the chunked-prefill scheduling contract — cursor
  resumption, budget-gated admission, and the decode stall bound (no
  active slot goes more than one step without a decode while another
  request prefills).
* **Oracle exactness** (real models): with ≥2 slots and staggered
  mixed-length arrivals, every request's tokens are bit-identical to the
  one-shot ``generate`` oracle — for the dense stack and for the EP MoE
  stack on a multi-shard mesh (whose oracle is the world-1 server; the
  repo's parity tests prove world-independence separately) — in
  whole-prompt mode AND under chunked prefill (chunk sizes odd /
  non-dividing, pow2, and ≥ the longest prompt).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uccl_tpu.serving import (
    DenseBackend, MoEBackend, RequestState, ServingEngine, SlotPool,
)
from uccl_tpu.serving.metrics import percentile


class _StubBackend:
    """Deterministic token emitter: prefill emits 0, the i-th decode step
    emits i — EOS behavior is then fully predictable with no model."""

    def __init__(self, n_slots=2, max_seq=64):
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.n_decodes = 0

    def prefill(self, tokens, lens, mask):
        return np.zeros(self.n_slots, np.int32)

    def decode(self, tokens, active):
        self.n_decodes += 1
        return np.full(self.n_slots, self.n_decodes, np.int32)


class _ChunkStubBackend:
    """Chunk-aware stub: records every backend call (kind, masked slots,
    start offsets) so scheduling order and cursor resumption are directly
    assertable. Prefill emits 100, the i-th decode step emits i."""

    def __init__(self, n_slots=2, max_seq=64):
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.n_decodes = 0
        self.calls = []

    def prefill(self, tokens, lens, mask, start=None):
        if start is None:
            start = np.zeros(self.n_slots, np.int32)
        slots = tuple(int(s) for s in np.flatnonzero(mask))
        self.calls.append(
            ("prefill", slots, tuple(int(start[s]) for s in slots))
        )
        return np.full(self.n_slots, 100, np.int32)

    def decode(self, tokens, active):
        self.n_decodes += 1
        self.calls.append(
            ("decode", tuple(int(s) for s in np.flatnonzero(active)))
        )
        return np.full(self.n_slots, self.n_decodes, np.int32)


def _prompt(rng, n):
    return rng.integers(0, 64, n).astype(np.int32)


class TestSchedulerProperties:
    def test_fifo_admission_order(self):
        eng = ServingEngine(_StubBackend(n_slots=2))
        reqs = [eng.submit([1, 2], max_new_tokens=3) for _ in range(7)]
        eng.drain()
        seqs = [r.admit_seq for r in reqs]
        assert seqs == sorted(seqs), "admission must preserve FIFO order"
        assert all(r.state is RequestState.FINISHED for r in reqs)

    def test_no_leaked_slots_after_drain(self):
        eng = ServingEngine(_StubBackend(n_slots=3))
        for i in range(8):
            eng.submit([1, 2, 3], max_new_tokens=2 + i % 3)
        eng.drain()
        assert eng.pool.leaked() == 0
        assert eng.pool.total_admits == eng.pool.total_frees == 8
        assert eng.pool.high_water <= eng.pool.n_slots

    def test_retirement_on_max_tokens(self):
        eng = ServingEngine(_StubBackend(n_slots=1))
        r = eng.submit([5], max_new_tokens=4)
        eng.drain()
        assert r.finish_reason == "length"
        assert r.n_generated == 4

    def test_retirement_on_eos(self):
        # stub emits 0 (prefill), 1, 2, ... — eos_id=2 retires mid-decode
        # after exactly 3 tokens, well under the 10-token budget
        eng = ServingEngine(_StubBackend(n_slots=1))
        r = eng.submit([5], max_new_tokens=10, eos_id=2)
        eng.drain()
        assert r.finish_reason == "eos"
        assert r.out_tokens == [0, 1, 2]

    def test_eos_at_prefill(self):
        eng = ServingEngine(_StubBackend(n_slots=1))
        r = eng.submit([5], max_new_tokens=10, eos_id=0)
        eng.drain()
        assert r.finish_reason == "eos" and r.out_tokens == [0]

    def test_backpressure_rejects_when_full(self):
        # 2 slots + queue bound 2: submissions beyond slots+queue reject
        eng = ServingEngine(_StubBackend(n_slots=2), max_queue=2)
        results = [eng.submit([1], max_new_tokens=3) for _ in range(8)]
        rejected = [r for r in results if r is None]
        accepted = [r for r in results if r is not None]
        assert len(rejected) == 6  # nothing admitted before the first step
        assert eng.metrics.rejected == 6
        eng.drain()
        assert eng.metrics.completed == len(accepted)
        assert eng.pool.leaked() == 0

    def test_queue_drains_between_steps(self):
        # backpressure QUEUES when slots are busy but the queue has room
        eng = ServingEngine(_StubBackend(n_slots=1), max_queue=8)
        reqs = [eng.submit([1], max_new_tokens=2) for _ in range(4)]
        assert all(r is not None for r in reqs)
        snap = eng.snapshot()
        assert snap["queued"] == 4 and snap["active"] == 0
        eng.drain()
        assert all(r.state is RequestState.FINISHED for r in reqs)

    def test_metrics_snapshot_consistency(self):
        eng = ServingEngine(_StubBackend(n_slots=2), max_queue=3)
        for _ in range(9):
            eng.submit([1, 2], max_new_tokens=6)
        # mid-flight and at every step boundary, requests are conserved:
        for _ in range(3):
            eng.step()
            s = eng.snapshot()
            assert (s["submitted"]
                    == s["completed"] + s["active"] + s["queued"]
                    + s["rejected"]), s
        eng.drain()
        s = eng.snapshot()
        assert s["active"] == s["queued"] == 0
        assert s["submitted"] == s["completed"] + s["rejected"]
        assert s["admitted"] == s["completed"]

    def test_submit_validation(self):
        eng = ServingEngine(_StubBackend(n_slots=1, max_seq=16))
        with pytest.raises(ValueError, match="non-empty"):
            eng.submit([], max_new_tokens=2)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit([1], max_new_tokens=0)
        with pytest.raises(ValueError, match="overflow"):
            eng.submit(np.arange(14), max_new_tokens=4)

    def test_percentile_helper(self):
        assert percentile([], 50) is None
        assert percentile([3.0], 95) == 3.0
        xs = [1.0, 2.0, 3.0, 4.0]
        assert percentile(xs, 50) == pytest.approx(2.5)
        assert percentile(xs, 100) == 4.0
        np.testing.assert_allclose(
            [percentile(xs, q) for q in (25, 95)],
            [np.percentile(xs, 25), np.percentile(xs, 95)],
        )

    def test_queue_wait_reported_separately(self):
        eng = ServingEngine(_StubBackend(n_slots=1))
        for _ in range(3):
            eng.submit([1, 2], max_new_tokens=2)
        eng.drain()
        s = eng.snapshot()
        # one queue-wait sample per admission, its own series next to TTFT
        assert len(eng.metrics.queue_wait_s) == s["admitted"] == 3
        assert "p50" in s["queue_wait_ms"] and "p50" in s["ttft_ms"]
        # queued-behind requests waited at least one engine step; the wait
        # is the admit mark minus the submit mark, never negative
        assert all(w >= 0.0 for w in eng.metrics.queue_wait_s)


class TestSlotPoolOrder:
    def test_lowest_slot_first_reuse(self):
        """Reuse must be lowest-slot-first, not FIFO-of-frees: after
        interleaved admits/frees the pool hands out the smallest free id."""
        pool = SlotPool(4)
        assert [pool.admit(r) for r in range(4)] == [0, 1, 2, 3]
        pool.free(2)
        pool.free(0)
        pool.free(3)  # frees arrive in order 2, 0, 3 — reuse must not
        assert pool.admit(10) == 0  # ...replay that order
        assert pool.admit(11) == 2
        pool.free(1)
        assert pool.admit(12) == 1  # 1 freed later but lower than 3
        assert pool.admit(13) == 3
        assert pool.n_free == 0

    def test_interleaved_admit_free_order(self):
        pool = SlotPool(3)
        a = pool.admit(0)
        b = pool.admit(1)
        assert (a, b) == (0, 1)
        pool.free(a)
        assert pool.admit(2) == 0  # lowest id again, not slot 2
        pool.free(b)
        pool.free(0)
        assert pool.admit(3) == 0 and pool.admit(4) == 1


class TestChunkedScheduling:
    def test_validation(self):
        with pytest.raises(ValueError, match="prefill_chunk must be"):
            ServingEngine(_ChunkStubBackend(), prefill_chunk=0)
        with pytest.raises(ValueError, match="requires prefill_chunk"):
            ServingEngine(_ChunkStubBackend(), step_tokens=8)
        with pytest.raises(ValueError, match="must be >= prefill_chunk"):
            ServingEngine(_ChunkStubBackend(), prefill_chunk=8,
                          step_tokens=4)

    def test_cursor_resumes_across_steps(self):
        """A 10-token prompt under chunk 4 prefills at starts 0, 4, 8 and
        only then emits its first token (PARTIAL_PREFILL → ACTIVE)."""
        eng = ServingEngine(_ChunkStubBackend(n_slots=1), prefill_chunk=4)
        r = eng.submit(list(range(10)), max_new_tokens=2)
        eng.step()
        assert r.state is RequestState.PARTIAL_PREFILL
        assert r.prefill_pos == 4 and r.n_generated == 0
        eng.step()
        assert r.prefill_pos == 8 and r.n_generated == 0
        eng.step()  # final (partial) chunk: emit + join decode same step
        assert r.state is not RequestState.PARTIAL_PREFILL
        assert r.prefill_pos == 10 and r.n_generated == 2
        starts = [c[2] for c in eng.backend.calls if c[0] == "prefill"]
        assert starts == [(0,), (4,), (8,)]
        eng.drain()
        assert eng.pool.leaked() == 0

    def test_decode_stall_bound(self):
        """THE property chunking buys: while one request prefills chunk by
        chunk, every in-flight decode advances one token per step — no
        active slot ever goes a step without a decode."""
        eng = ServingEngine(_ChunkStubBackend(n_slots=2), prefill_chunk=2)
        a = eng.submit([1], max_new_tokens=12)
        eng.step()  # A: single-chunk prefill + first decode
        assert a.n_generated == 2
        b = eng.submit(list(range(10)), max_new_tokens=2)  # 5 chunks
        n0 = a.n_generated
        for i in range(1, 6):
            eng.step()
            assert a.n_generated == n0 + i, (
                "decode stalled behind a prefill chunk"
            )
        assert b.n_generated >= 1  # B emitted at its final chunk
        # call-log shape: a step never runs two prefill calls, and every
        # prefill while A decoded is followed by A's decode in-step
        kinds = [c[0] for c in eng.backend.calls]
        for i in range(len(kinds) - 1):
            assert not (kinds[i] == kinds[i + 1] == "prefill")
        eng.drain()
        assert eng.pool.leaked() == 0

    def test_budget_gates_admission(self):
        """step_tokens caps the step's committed spend (decode = 1, chunk
        = C): admissions defer until budget frees up, FIFO order intact."""
        eng = ServingEngine(_ChunkStubBackend(n_slots=4), prefill_chunk=4,
                            step_tokens=8)
        reqs = [eng.submit(list(range(8)), max_new_tokens=3)
                for _ in range(3)]
        eng.step()  # budget 8 admits floor(8/4) = 2; third stays queued
        assert [r.state for r in reqs] == [
            RequestState.PARTIAL_PREFILL, RequestState.PARTIAL_PREFILL,
            RequestState.QUEUED,
        ]
        s = eng.snapshot()
        assert s["active"] == 2 and s["queued"] == 1
        assert (s["submitted"]
                == s["completed"] + s["active"] + s["queued"]
                + s["rejected"])
        eng.step()  # both mid-prefill slots still charge 2C = 8: no admit
        assert reqs[2].state is RequestState.QUEUED
        # first two finished prefill this step (first token) AND took the
        # step's decode pass immediately
        assert reqs[0].n_generated == 2
        eng.step()  # spend now 2 decodes = 2 → room for one chunk: admit
        assert reqs[2].state is RequestState.PARTIAL_PREFILL
        eng.drain()
        assert all(r.state is RequestState.FINISHED for r in reqs)
        assert eng.pool.leaked() == 0
        seqs = [r.admit_seq for r in reqs]
        assert seqs == sorted(seqs)

    def test_chunked_eos_and_conservation(self):
        """EOS at the first token retires straight out of prefill; metrics
        stay conserved with PARTIAL_PREFILL requests counted as active."""
        eng = ServingEngine(_ChunkStubBackend(n_slots=1), prefill_chunk=2,
                            max_queue=4)
        r = eng.submit([1, 2, 3], max_new_tokens=10, eos_id=100)
        eng.step()
        s = eng.snapshot()
        assert r.state is RequestState.PARTIAL_PREFILL
        assert (s["submitted"]
                == s["completed"] + s["active"] + s["queued"]
                + s["rejected"])
        eng.drain()
        assert r.finish_reason == "eos" and r.out_tokens == [100]
        assert eng.pool.leaked() == 0


MAX_SEQ = 32


@pytest.fixture(scope="module")
def dense_setup():
    """Params + ONE shared backend: its per-shape jit cache then makes the
    later tests' compiles cache hits (and exercises cross-engine slot-pool
    reuse for free). Tier-1 wall time matters — the oracle (len, N) pairs
    below repeat across tests for the same reason (_GEN_CACHE hits)."""
    from uccl_tpu.models import dense

    cfg = dense.DenseConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=8,
        ffn=64,
    )
    params = dense.init_params(jax.random.PRNGKey(0), cfg)
    backend = DenseBackend(params, cfg, n_slots=2, max_seq=MAX_SEQ)
    return cfg, params, backend


class TestDenseOracle:
    def _oracle(self, params, cfg, req):
        from uccl_tpu.models.inference import generate

        toks = generate(params, jnp.asarray(req.prompt)[None], cfg,
                        max_new_tokens=req.max_new_tokens, max_seq=MAX_SEQ)
        return np.asarray(toks)[0, : req.n_generated].tolist()

    def test_staggered_mixed_lengths_exact(self, dense_setup):
        """The acceptance anchor: 2 slots, 6 mixed-length requests arriving
        mid-decode of each other — every emitted sequence bit-equals the
        one-shot oracle."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(0)
        eng = ServingEngine(backend)
        reqs = [eng.submit(_prompt(rng, 5), max_new_tokens=6),
                eng.submit(_prompt(rng, 3), max_new_tokens=4)]
        eng.step()  # both admitted, mid-decode...
        eng.step()
        for n, m in ((8, 5), (2, 6), (6, 3), (7, 5)):  # ...arrivals join
            reqs.append(eng.submit(_prompt(rng, n), max_new_tokens=m))
        eng.drain()
        assert eng.pool.leaked() == 0
        for r in reqs:
            assert r.n_generated == r.max_new_tokens
            assert r.out_tokens == self._oracle(params, cfg, r), r.rid
        # lifecycle timing populated for every request
        assert all(r.ttft is not None and r.latency is not None
                   for r in reqs)

    def test_eos_retirement_matches_oracle_prefix(self, dense_setup):
        """Using a token the oracle emits mid-stream as EOS, the engine
        must stop exactly there with the oracle's prefix."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(1)
        prompt = _prompt(rng, 5)
        eng = ServingEngine(backend)
        probe = eng.submit(prompt, max_new_tokens=6)
        eng.drain()
        full = probe.out_tokens
        assert full == self._oracle(params, cfg, probe)
        eos = full[3]
        k = full.index(eos)  # first occurrence may precede position 3
        r = eng.submit(prompt, max_new_tokens=6, eos_id=eos)
        eng.drain()
        assert r.finish_reason == "eos"
        assert r.out_tokens == full[: k + 1]
        assert eng.pool.leaked() == 0

    def test_slot_reuse_after_retirement(self, dense_setup):
        """More requests than slots: retired slots are re-prefilled by
        later requests and stale KV never bleeds into their outputs.
        (len, N) pairs repeat the staggered test's — fresh tokens, cached
        oracle programs."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(2)
        eng = ServingEngine(backend)
        reqs = [eng.submit(_prompt(rng, n), max_new_tokens=m)
                for n, m in ((5, 6), (3, 4), (8, 5), (2, 6), (6, 3), (7, 5))]
        eng.drain()
        assert eng.pool.total_admits == 6 and eng.pool.high_water == 2
        for r in reqs:
            assert r.out_tokens == self._oracle(params, cfg, r), r.rid


class TestDenseChunkedOracle:
    """Chunked prefill stays bit-exact: the same math split along the
    sequence axis. (len, N) pairs repeat the whole-prompt tests' so oracle
    programs are _GEN_CACHE hits; the shared module backend means each
    chunk size costs exactly ONE new prefill compile ([n_slots, C])."""

    def _drive(self, backend, rng, *, prefill_chunk, step_tokens=None):
        eng = ServingEngine(backend, prefill_chunk=prefill_chunk,
                            step_tokens=step_tokens)
        reqs = [eng.submit(_prompt(rng, 5), max_new_tokens=6),
                eng.submit(_prompt(rng, 3), max_new_tokens=4)]
        eng.step()  # both mid-flight (prefilling or decoding)...
        eng.step()
        for n, m in ((8, 5), (2, 6), (6, 3), (7, 5)):  # ...arrivals join
            reqs.append(eng.submit(_prompt(rng, n), max_new_tokens=m))
        eng.drain()
        assert eng.pool.leaked() == 0
        return eng, reqs

    @pytest.mark.parametrize(
        "chunk,budget",
        [(3, None),   # odd, divides no prompt length here
         (4, 8),      # pow2 + a per-step token budget
         (64, None)], # ≥ every prompt: whole prompt in one chunk
    )
    def test_staggered_chunked_exact(self, dense_setup, chunk, budget):
        cfg, params, backend = dense_setup
        eng, reqs = self._drive(
            backend, np.random.default_rng(0),
            prefill_chunk=chunk, step_tokens=budget,
        )
        oracle = TestDenseOracle()
        for r in reqs:
            assert r.n_generated == r.max_new_tokens
            assert r.out_tokens == oracle._oracle(params, cfg, r), (
                f"chunk={chunk} rid={r.rid}"
            )
        if chunk < 8:
            # multi-chunk prompts really resumed: more chunk calls than
            # requests, every one through the single [n_slots, C] program
            assert eng.metrics.prefill_chunks > len(reqs)

    def test_chunk_none_is_whole_prompt_path(self, dense_setup):
        """prefill_chunk=None ≡ the PR 3 path: identical prompts through a
        None engine and a chunked engine produce identical tokens (and the
        None engine still buckets — no chunk calls)."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(7)
        prompts = [_prompt(rng, n) for n, _ in
                   ((5, 6), (3, 4), (8, 5), (2, 6), (6, 3), (7, 5))]
        outs = {}
        for chunk in (None, 3):
            eng = ServingEngine(backend, prefill_chunk=chunk)
            reqs = [eng.submit(p, max_new_tokens=m)
                    for p, (_, m) in zip(prompts, ((5, 6), (3, 4), (8, 5),
                                                   (2, 6), (6, 3), (7, 5)))]
            eng.drain()
            outs[chunk] = [r.out_tokens for r in reqs]
            if chunk is None:
                assert eng.metrics.prefill_chunks == 0
        assert outs[None] == outs[3]


class _ReplayDrafter:
    """Drafts from known full sequences (prompt + oracle continuation) —
    the deterministic full-acceptance driver for spec-decode tests: every
    proposal is exactly what the target will emit, so the accept path
    (multi-token commits, bonus tokens, cursor jumps) is exercised on
    every step while the output must STILL be bit-exact."""

    def __init__(self, seqs):
        self.seqs = [np.asarray(s, np.int32) for s in seqs]

    def draft(self, context, k):
        c = np.asarray(context)
        for s in self.seqs:
            if s.size >= c.size and np.array_equal(s[:c.size], c):
                return s[c.size:c.size + k]
        return np.zeros(0, np.int32)


class _GarbageDrafter:
    """Near-certain rejection: proposes off-by-17 tokens (still in-vocab),
    driving the correction path — one committed token per window."""

    def draft(self, context, k):
        return (np.asarray(context)[-1] + 17
                + np.arange(k, dtype=np.int32)) % 64


class TestDenseSpecOracle:
    """Speculative decoding stays bit-exact on the dense stack: greedy
    acceptance only ever commits the target's own argmaxes, so any
    drafter — always right, always wrong, or the real prompt-lookup
    NGramDrafter — yields the vanilla greedy output. (len, N) pairs repeat
    the whole-prompt tests' so oracle programs are _GEN_CACHE hits; the
    only new compiles are the [n_slots, k+1] verify programs."""

    _PAIRS = ((5, 6), (3, 4), (8, 5), (2, 6), (6, 3), (7, 5))

    def _oracle_seqs(self, params, cfg, prompts):
        from uccl_tpu.models.inference import generate

        seqs = []
        for p, (_, m) in zip(prompts, self._PAIRS):
            toks = np.asarray(generate(
                params, jnp.asarray(p)[None], cfg, max_new_tokens=m,
                max_seq=MAX_SEQ,
            ))[0]
            seqs.append(np.concatenate([p, toks]))
        return seqs

    def _drive(self, backend, prompts, drafter, spec_k, **engine_kw):
        from uccl_tpu.serving import ServingEngine

        eng = ServingEngine(backend, spec_k=spec_k, drafter=drafter,
                            **engine_kw)
        reqs = [eng.submit(p, max_new_tokens=m)
                for p, (_, m) in zip(prompts[:2], self._PAIRS[:2])]
        eng.step()  # staggered arrivals mid-flight, like the vanilla test
        eng.step()
        for p, (_, m) in zip(prompts[2:], self._PAIRS[2:]):
            reqs.append(eng.submit(p, max_new_tokens=m))
        eng.drain()
        assert eng.pool.leaked() == 0
        return eng, reqs

    def test_spec_staggered_exact_across_drafters(self, dense_setup):
        """The acceptance anchor: staggered mixed-length arrivals with
        slot reuse under spec_k=2, across the acceptance spectrum —
        full-accept (replay), near-full-reject (garbage) and the real
        NGramDrafter — every request bit-equals the one-shot oracle."""
        from uccl_tpu.serving import NGramDrafter

        cfg, params, backend = dense_setup
        rng = np.random.default_rng(0)
        prompts = [_prompt(rng, n) for n, _ in self._PAIRS]
        seqs = self._oracle_seqs(params, cfg, prompts)
        oracle = TestDenseOracle()
        accepted = {}
        for name, drafter in (("replay", _ReplayDrafter(seqs)),
                              ("garbage", _GarbageDrafter()),
                              ("ngram", NGramDrafter())):
            eng, reqs = self._drive(backend, prompts, drafter, spec_k=2)
            for r in reqs:
                assert r.n_generated == r.max_new_tokens
                assert r.out_tokens == oracle._oracle(params, cfg, r), (
                    f"drafter={name} rid={r.rid}"
                )
            accepted[name] = eng.metrics.spec_accepted
            if name == "replay":
                # full acceptance really multiplied tokens per model
                # call — strictly more commits than verify calls
                assert eng.metrics.decode_tokens > eng.metrics.decode_calls
        assert accepted["replay"] > accepted["garbage"]

    def test_spec_k1_equivalent_to_vanilla(self, dense_setup):
        """spec_k=1 emits the same stream as the vanilla engine — same
        tokens, same per-request counts — just 1-2 tokens per window."""
        from uccl_tpu.serving import NGramDrafter, ServingEngine

        cfg, params, backend = dense_setup
        rng = np.random.default_rng(0)
        prompts = [_prompt(rng, n) for n, _ in self._PAIRS]
        outs = {}
        for mode in ("vanilla", "spec"):
            eng = ServingEngine(
                backend,
                spec_k=1 if mode == "spec" else None,
                drafter=NGramDrafter() if mode == "spec" else None,
            )
            reqs = [eng.submit(p, max_new_tokens=m)
                    for p, (_, m) in zip(prompts, self._PAIRS)]
            eng.drain()
            outs[mode] = [r.out_tokens for r in reqs]
            assert eng.pool.leaked() == 0
        assert outs["spec"] == outs["vanilla"]

    def test_spec_composes_with_chunked_prefill(self, dense_setup):
        """spec_k x prefill_chunk: chunk-resumed prompts join the same
        step's verify when their cursor lands — outputs stay exact and
        chunks really resumed. Chunk 3 + verify [2, 3] are compile cache
        hits from the chunked and spec suites above."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(0)
        prompts = [_prompt(rng, n) for n, _ in self._PAIRS]
        seqs = self._oracle_seqs(params, cfg, prompts)
        eng, reqs = self._drive(backend, prompts, _ReplayDrafter(seqs),
                                spec_k=2, prefill_chunk=3)
        oracle = TestDenseOracle()
        for r in reqs:
            assert r.out_tokens == oracle._oracle(params, cfg, r), r.rid
        assert eng.metrics.prefill_chunks > len(reqs)
        assert eng.metrics.spec_accepted > 0

    def test_spec_composes_with_prefix_cache_hit(self, dense_setup):
        """spec_k x prefix cache: a hit resumes prefill at the matched
        boundary AND the continuation decodes speculatively — both
        requests bit-equal the oracle."""
        from uccl_tpu.serving import (
            NGramDrafter, PrefixCache, ServingEngine,
        )

        cfg, params, backend = dense_setup
        eng = ServingEngine(backend, prefill_chunk=4,
                            prefix_cache=PrefixCache(4), spec_k=2,
                            drafter=NGramDrafter())
        rng = np.random.default_rng(3)
        p0 = rng.integers(0, 64, 12).astype(np.int32)
        sharer = np.concatenate(
            [p0[:8], rng.integers(0, 64, 4).astype(np.int32)]
        )
        oracle = TestDenseOracle()
        cold = eng.submit(p0, max_new_tokens=4)
        eng.drain()
        hit = eng.submit(sharer, max_new_tokens=4)
        eng.drain()
        assert cold.cache_hit_len == 0 and hit.cache_hit_len == 8
        for r in (cold, hit):
            assert r.out_tokens == oracle._oracle(params, cfg, r), r.rid
        assert eng.pool.leaked() == 0


class TestDensePreemptionOracle:
    """Chunk-boundary preemption stays bit-exact on the dense stack: a
    paused victim's KV rows round-trip through the host save/restore (raw
    f32 — the PR 8 slot-row views), its cursor resumes via the PR 4 start
    offset, and every output — victim, survivor, and the interactive
    arrival that caused the pause — equals the one-shot oracle. Chunk 3
    and the [2, 3] verify window are compile-cache hits from the chunked
    and spec suites; the only new programs are the slot-row export/import
    jits (one each per pool shape)."""

    def _engine(self, backend, **kw):
        return ServingEngine(backend, prefill_chunk=3,
                             priority_classes=True, preempt=True, **kw)

    def _check(self, params, cfg, reqs):
        oracle = TestDenseOracle()
        for r in reqs:
            assert r.n_generated == r.max_new_tokens
            assert r.out_tokens == oracle._oracle(params, cfg, r), r.rid

    def test_preempt_mid_decode_exact(self, dense_setup):
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(0)
        eng = self._engine(backend)
        b1 = eng.submit(_prompt(rng, 5), max_new_tokens=6,
                        priority="batch")
        b2 = eng.submit(_prompt(rng, 3), max_new_tokens=6,
                        priority="batch")
        for _ in range(4):
            eng.step()  # both past prefill, mid-decode
        assert b1.state is RequestState.ACTIVE
        assert b2.state is RequestState.ACTIVE
        ia = eng.submit(_prompt(rng, 6), max_new_tokens=3,
                        priority="interactive")
        eng.step()
        assert b2.state is RequestState.PREEMPTED, (
            "newest batch request must pause for the interactive arrival"
        )
        assert b2.n_generated >= 1  # really paused MID-decode
        eng.drain()
        assert b2.preemptions == 1
        self._check(params, cfg, [b1, b2, ia])
        assert eng.pool.leaked() == 0
        assert eng.metrics.preempted == 1 and eng.metrics.resumed == 1

    def test_preempt_mid_prefill_exact(self, dense_setup):
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(1)
        eng = self._engine(backend)
        bb = eng.submit(_prompt(rng, 8), max_new_tokens=5,
                        priority="batch")
        other = eng.submit(_prompt(rng, 2), max_new_tokens=6,
                           priority="batch")
        eng.step()  # bb one 3-token chunk in, other already decoding
        assert bb.state is RequestState.PARTIAL_PREFILL
        assert bb.prefill_pos == 3
        i1 = eng.submit(_prompt(rng, 6), max_new_tokens=3,
                        priority="interactive")
        i2 = eng.submit(_prompt(rng, 7), max_new_tokens=5,
                        priority="interactive")
        eng.step()  # preempts bb (newest), i1 takes its slot
        assert bb.state is RequestState.PREEMPTED
        assert bb.prefill_pos == 3, "the cursor is the saved state"
        eng.drain()
        assert bb.preemptions >= 1
        self._check(params, cfg, [bb, other, i1, i2])
        assert eng.pool.leaked() == 0

    def test_preempt_spec_victim_exact(self, dense_setup):
        """Preemption × speculative decoding: the victim pauses between
        verify windows (its cursor already advanced by multi-token
        commits) and resumes speculating — still bit-exact."""
        from uccl_tpu.serving import NGramDrafter

        cfg, params, backend = dense_setup
        rng = np.random.default_rng(0)
        eng = self._engine(backend, spec_k=2, drafter=NGramDrafter())
        b1 = eng.submit(_prompt(rng, 5), max_new_tokens=6,
                        priority="batch")
        b2 = eng.submit(_prompt(rng, 3), max_new_tokens=6,
                        priority="batch")
        for _ in range(3):
            eng.step()
        ia = eng.submit(_prompt(rng, 6), max_new_tokens=3,
                        priority="interactive")
        eng.step()
        assert RequestState.PREEMPTED in (b1.state, b2.state)
        eng.drain()
        assert eng.metrics.preempted >= 1
        self._check(params, cfg, [b1, b2, ia])
        assert eng.pool.leaked() == 0

    def test_preempt_prefix_cache_hit_victim_exact(self, dense_setup):
        """Preemption × prefix cache: the victim resumed prefill from a
        cached prefix (its KV partly COPIED, not computed), then got
        preempted and resumed again — the save/restore must carry the
        copied rows bit-exactly too. Chunk 4 matches the prefix-cache
        suite's compiled programs."""
        from uccl_tpu.serving import PrefixCache

        cfg, params, backend = dense_setup
        rng = np.random.default_rng(3)
        eng = ServingEngine(backend, prefill_chunk=4,
                            prefix_cache=PrefixCache(4),
                            priority_classes=True, preempt=True)
        p0 = rng.integers(0, 64, 12).astype(np.int32)
        donor = eng.submit(p0, max_new_tokens=4, priority="batch")
        eng.drain()  # donor parks as a reuse donor
        sharer = np.concatenate(
            [p0[:8], rng.integers(0, 64, 8).astype(np.int32)]
        )
        hit = eng.submit(sharer, max_new_tokens=4, priority="batch")
        eng.step()  # hit copies [0, 8) and prefills [8, 12) — mid-prefill
        assert hit.cache_hit_len == 8
        assert hit.state is RequestState.PARTIAL_PREFILL
        # two interactive arrivals: the first evicts the parked donor for
        # its slot, the second must preempt the mid-prefill hit victim
        i1 = eng.submit(_prompt(rng, 6), max_new_tokens=3,
                        priority="interactive")
        i2 = eng.submit(_prompt(rng, 7), max_new_tokens=3,
                        priority="interactive")
        eng.step()
        assert hit.state is RequestState.PREEMPTED
        eng.drain()
        assert hit.preemptions >= 1 and hit.cache_hit_len == 8
        self._check(params, cfg, [donor, hit, i1, i2])
        assert eng.pool.leaked() == 0


@pytest.fixture(scope="module")
def moe_setup(devices):
    """ONE 2-shard server/backend + ONE world-1 oracle server for every MoE
    serving test: MoE programs are shard_map compiles (the expensive kind),
    so both the whole-prompt and chunked tests must share them. Oracle
    (len, N) pairs repeat across tests for the same reason."""
    from jax.sharding import Mesh

    from uccl_tpu.models.moe_inference import (
        MoEServeConfig, MoEServer, init_params,
    )

    cfg = MoEServeConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=8, moe_experts=8, moe_topk=2, moe_ffn=64,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    srv = MoEServer(cfg, Mesh(np.array(devices[:2]), ("dp",)))
    backend = MoEBackend(
        srv, srv.shard_params(params), batch_local=1, max_seq=MAX_SEQ,
    )
    srv1 = MoEServer(cfg, Mesh(np.array(devices[:1]), ("dp",)))
    return backend, srv1, srv1.shard_params(params)


class TestMoEOracle:
    def _check(self, reqs, srv1, p1):
        for r in reqs:
            want = srv1.generate(
                p1, jnp.asarray(r.prompt)[None, None], r.max_new_tokens,
                MAX_SEQ, impl="ll",
            )
            assert r.out_tokens == np.asarray(want)[0, 0].tolist(), r.rid

    def test_staggered_mixed_lengths_exact(self, moe_setup):
        """EP MoE stack on a 2-shard mesh (1 slot per shard): masked
        continuous batching bit-equals the world-1 one-shot oracle under
        staggered mixed-length arrivals. Lean on purpose — every distinct
        prompt shape costs a shard_map compile in the oracle, and tier-1
        wall time is budgeted: 3 lengths in one prefill bucket, one N."""
        backend, srv1, p1 = moe_setup
        eng = ServingEngine(backend)
        rng = np.random.default_rng(0)
        reqs = [eng.submit(_prompt(rng, 5), max_new_tokens=4),
                eng.submit(_prompt(rng, 6), max_new_tokens=4)]
        eng.step()  # admit + first decode...
        reqs.append(eng.submit(_prompt(rng, 8), max_new_tokens=4))
        eng.drain()
        assert eng.pool.leaked() == 0
        self._check(reqs, srv1, p1)

    def test_staggered_chunked_exact(self, moe_setup):
        """Chunked prefill on the EP MoE stack: chunk 3 divides none of the
        prompt lengths (5, 8) fully, so final partial chunks and the
        write-gate beyond the prompt end are exercised on the sharded
        cache. Same (len, N) pairs as above — oracle cache hits; the only
        new compile is the [W, 1, 3] chunk program."""
        backend, srv1, p1 = moe_setup
        eng = ServingEngine(backend, prefill_chunk=3, step_tokens=8)
        rng = np.random.default_rng(0)
        reqs = [eng.submit(_prompt(rng, 5), max_new_tokens=4),
                eng.submit(_prompt(rng, 6), max_new_tokens=4)]
        eng.step()  # both mid-prefill...
        reqs.append(eng.submit(_prompt(rng, 8), max_new_tokens=4))
        eng.drain()
        assert eng.pool.leaked() == 0
        assert eng.metrics.prefill_chunks > len(reqs)  # really multi-chunk
        self._check(reqs, srv1, p1)

    def test_spec_staggered_exact(self, moe_setup):
        """Speculative decoding on the EP-sharded MoE stack: the
        [W, B_loc, k+1] verify window routes every slot's draft through
        the drop-free sorted EP path, and full-acceptance drafting (the
        replay drafter) still bit-equals the world-1 oracle under
        staggered arrivals. Same (len, N) pairs as above — the only new
        compile is the verify program."""
        backend, srv1, p1 = moe_setup
        rng = np.random.default_rng(0)
        prompts = [_prompt(rng, n) for n in (5, 6, 8)]
        seqs = []
        for p in prompts:
            toks = srv1.generate(p1, jnp.asarray(p)[None, None], 4,
                                 MAX_SEQ, impl="ll")
            seqs.append(np.concatenate([p, np.asarray(toks)[0, 0]]))
        eng = ServingEngine(backend, spec_k=2,
                            drafter=_ReplayDrafter(seqs))
        reqs = [eng.submit(prompts[0], max_new_tokens=4),
                eng.submit(prompts[1], max_new_tokens=4)]
        eng.step()  # both mid-decode...
        reqs.append(eng.submit(prompts[2], max_new_tokens=4))
        eng.drain()
        assert eng.pool.leaked() == 0
        assert eng.metrics.spec_accepted > 0
        assert eng.metrics.decode_tokens > eng.metrics.decode_calls
        self._check(reqs, srv1, p1)

    def test_preemption_exact(self, moe_setup):
        """Chunk-boundary preemption on the EP-sharded MoE stack: the
        victim's KV rows round-trip through the MoESlotCache numpy
        mirrors (mid-prefill AND mid-decode victims across the two
        arrivals), and every output still bit-equals the world-1 oracle.
        Same (len, N) pairs as above — oracle + chunk programs are cache
        hits; export/import are host-side numpy, no new compiles."""
        backend, srv1, p1 = moe_setup
        eng = ServingEngine(backend, prefill_chunk=3,
                            priority_classes=True, preempt=True)
        rng = np.random.default_rng(0)
        b1 = eng.submit(_prompt(rng, 5), max_new_tokens=4,
                        priority="batch")
        b2 = eng.submit(_prompt(rng, 6), max_new_tokens=4,
                        priority="batch")
        eng.step()  # both mid-prefill (one 3-token chunk in)
        assert b2.state is RequestState.PARTIAL_PREFILL
        i1 = eng.submit(_prompt(rng, 8), max_new_tokens=4,
                        priority="interactive")
        eng.step()  # preempts the newest batch request mid-prefill
        assert b2.state is RequestState.PREEMPTED
        assert 0 < b2.prefill_pos < b2.prompt.size
        eng.drain()  # b2 resumes at its cursor and finishes
        assert b2.preemptions == 1
        # phase 2: a mid-DECODE victim (same shapes — cache-hit programs)
        b3 = eng.submit(_prompt(rng, 5), max_new_tokens=4,
                        priority="batch")
        b4 = eng.submit(_prompt(rng, 6), max_new_tokens=4,
                        priority="batch")
        for _ in range(16):
            if (b3.state is RequestState.ACTIVE
                    and b4.state is RequestState.ACTIVE):
                break
            eng.step()
        assert b4.state is RequestState.ACTIVE
        i2 = eng.submit(_prompt(rng, 5), max_new_tokens=4,
                        priority="interactive")
        eng.step()
        assert b4.state is RequestState.PREEMPTED, (
            "the newest decoding batch request must pause"
        )
        assert b4.n_generated >= 1  # really paused MID-decode
        eng.drain()
        assert eng.metrics.preempted == 2
        assert eng.metrics.resumed == eng.metrics.preempted
        assert eng.pool.leaked() == 0
        self._check([b1, b2, i1, i2, b3, b4], srv1, p1)

    def test_droppable_capacity_rejected(self, devices):
        """Slot serving's exactness needs a drop-free wire: a config whose
        per-expert capacity cannot cover worst-case routing is refused at
        the slot entry points (outputs would depend on batch neighbors)."""
        from jax.sharding import Mesh

        from uccl_tpu.models.moe_inference import MoEServeConfig, MoEServer

        cfg = MoEServeConfig(moe_experts=32, moe_topk=2,
                             capacity_factor=8.0)
        srv = MoEServer(cfg, Mesh(np.array(devices[:1]), ("dp",)))
        with pytest.raises(ValueError, match="drop-free"):
            srv.slot_cache(1, MAX_SEQ)
