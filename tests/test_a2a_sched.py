"""Contention-aware scheduled EP all-to-all + planned reduce_scatter.

Two layers of oracle discipline:

* HOST — the schedule builder (uccl_tpu.ep.a2a_sched) is pure numpy, so its
  contract is property-tested exhaustively: every decomposition round is a
  partial matching (no port contention), the rounds cover the traffic matrix
  exactly, the round count respects the greedy edge-coloring bound
  ``2Δ − 1``, and the heaviest rounds go first. ``wire_schedule`` then
  completes that to FULL permutations + the designated-round matrix K the
  device driver consumes.

* DEVICE — the scheduled kernel (pallas_a2a.scheduled_all_to_all), the
  sorted dispatch/combine path and the Buffer verbs are a pure reordering
  of the same write-once per-pair DMAs, so every arm is pinned
  bit-identical to the unscheduled wire / ``lax.all_to_all`` — including
  the fp8+scales wire format and ``n_chunks`` pipelining. Heavy worlds
  (8, 5) ride ``slow`` per the tier-1 budget convention of
  tests/test_pallas_a2a.py.

The planner arbitration (``ep_sched`` vs ``ep_streams`` under the one
alpha-beta-gamma model) and the fourth planned verb
(``Communicator.reduce_scatter``) are covered at the bottom.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from uccl_tpu.ep import Buffer, a2a_sched, pallas_a2a
from uccl_tpu.ep import ops as ep_ops
from uccl_tpu.utils.jaxcompat import shard_map

WORLDS_T1 = (4,
             pytest.param(8, marks=pytest.mark.slow),
             pytest.param(5, marks=pytest.mark.slow))


def _mesh(devices, n):
    return Mesh(np.array(devices[:n]), ("ep",))


def _run(mesh, fn, *args, out_specs=None):
    in_specs = tuple(P("ep") for _ in args)
    out_specs = P("ep") if out_specs is None else out_specs
    return jax.jit(
        shard_map(fn, mesh, in_specs, out_specs, check_vma=False)
    )(*args)


def _by_labels(samples):
    """counter.samples() → {sorted-label-items: value} (dicts unhashable)."""
    return {tuple(sorted(d.items())): v for d, v in samples}


def _skewed(rng, w, hot_scale=8.0):
    """A hot-row + hot-column traffic matrix (the MoE skew shape)."""
    m = rng.uniform(0.5, 2.0, (w, w))
    m[0] *= hot_scale       # member 0 sends a lot
    m[:, w - 1] *= hot_scale  # member w-1 hosts hot experts
    np.fill_diagonal(m, 0.0)
    return m


# ---------------------------------------------------------------------------
# host: the decomposition properties
# ---------------------------------------------------------------------------


class TestDecompose:
    @pytest.mark.parametrize("w", [2, 3, 4, 5, 8])
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_rounds_are_matchings(self, w, seed):
        rng = np.random.default_rng(seed)
        m = _skewed(rng, w)
        # sparsify some entries so partial rounds appear
        m[rng.uniform(size=m.shape) < 0.3] = 0.0
        np.fill_diagonal(m, 0.0)
        for r in a2a_sched.decompose(m):
            dsts = [d for d in r.perm if d >= 0]
            assert len(dsts) == len(set(dsts)), r  # receive ports
            # send ports are unique by construction (perm indexed by src);
            # no self-loops in a decomposition round
            assert all(r.perm[s] != s for s in range(w) if r.perm[s] >= 0)

    @pytest.mark.parametrize("w", [3, 4, 5, 8])
    def test_exact_cover(self, w):
        rng = np.random.default_rng(w)
        m = _skewed(rng, w)
        m[rng.uniform(size=m.shape) < 0.25] = 0.0
        np.fill_diagonal(m, 0.0)
        rounds = a2a_sched.decompose(m)
        got = np.zeros_like(m)
        for r in rounds:
            for s, d in enumerate(r.perm):
                if d >= 0:
                    assert got[s, d] == 0.0, f"pair ({s},{d}) in two rounds"
                    got[s, d] = m[s, d]
        np.testing.assert_array_equal(got, m)
        # per-round loads are the carried weights
        for r in rounds:
            want = sum(m[s, d] for s, d in enumerate(r.perm) if d >= 0)
            assert r.load == pytest.approx(want)

    @pytest.mark.parametrize("w", [3, 4, 5, 8])
    @pytest.mark.parametrize("seed", [0, 7])
    def test_round_bound_and_order(self, w, seed):
        rng = np.random.default_rng(seed)
        m = _skewed(rng, w)
        m[rng.uniform(size=m.shape) < 0.4] = 0.0
        np.fill_diagonal(m, 0.0)
        rounds = a2a_sched.decompose(m)
        delta = a2a_sched.max_degree(m)
        if delta:
            assert len(rounds) <= 2 * delta - 1
        loads = [r.load for r in rounds]
        assert loads == sorted(loads, reverse=True)  # heaviest first

    def test_degenerates(self):
        w = 4
        assert a2a_sched.decompose(np.zeros((w, w))) == []
        # single hot column: every member sends to member 0 — w-1 rounds of
        # one edge each (receive port 0 serializes, degree w-1)
        m = np.zeros((w, w))
        m[1:, 0] = 1.0
        rounds = a2a_sched.decompose(m)
        assert len(rounds) == w - 1
        assert all(r.n_edges == 1 for r in rounds)
        # uniform all-pairs: covers with a port-disjoint round set
        u = np.ones((w, w))
        np.fill_diagonal(u, 0.0)
        got = sum(r.n_edges for r in a2a_sched.decompose(u))
        assert got == w * (w - 1)

    def test_rejects_bad_matrices(self):
        with pytest.raises(ValueError, match="square"):
            a2a_sched.decompose(np.ones((2, 3)))
        with pytest.raises(ValueError, match="non-negative"):
            a2a_sched.decompose(np.array([[0.0, -1.0], [1.0, 0.0]]))

    def test_skew_feature(self):
        u = np.ones((4, 4))
        assert a2a_sched.skew(u) == pytest.approx(1.0)  # diag ignored
        assert a2a_sched.skew(np.zeros((4, 4))) == 1.0
        m = np.zeros((4, 4))
        m[0, 1:] = 1.0  # one member does all the talking
        assert a2a_sched.skew(m) == pytest.approx(4.0)


class TestWireSchedule:
    @pytest.mark.parametrize("w", [2, 3, 4, 5, 8])
    @pytest.mark.parametrize("seed", [0, 3])
    def test_full_permutations_and_k(self, w, seed):
        rng = np.random.default_rng(seed)
        m = _skewed(rng, w)
        m[rng.uniform(size=m.shape) < 0.3] = 0.0
        np.fill_diagonal(m, 0.0)
        rounds, k = a2a_sched.wire_schedule(m, w)
        for r in rounds:
            assert sorted(r.perm) == list(range(w))  # total permutation
        assert k.shape == (w, w) and k.dtype == np.int32
        for s in range(w):
            for d in range(w):
                if s != d:
                    assert rounds[k[s, d]].perm[s] == d, (s, d)

    def test_empty_matrix_is_the_rotation_wire(self):
        """Zero traffic completes to exactly the W−1 rotations the fixed
        streams would drive — no extra rounds from a ragged packing."""
        w = 5
        rounds, k = a2a_sched.wire_schedule(np.zeros((w, w)), w)
        assert len(rounds) == w - 1
        perms = {r.perm for r in rounds}
        want = {tuple((s + h) % w for s in range(w)) for h in range(1, w)}
        assert perms == want

    def test_heavy_prefix_preserved(self):
        """Completion only touches free ports: the decomposition's heavy
        rounds keep their designated edges and their order."""
        rng = np.random.default_rng(5)
        m = _skewed(rng, 4)
        base = a2a_sched.decompose(m)
        rounds, k = a2a_sched.wire_schedule(m, 4)
        assert len(rounds) >= len(base)
        for i, r in enumerate(base):
            for s, d in enumerate(r.perm):
                if d >= 0:
                    assert rounds[i].perm[s] == d
                    assert k[s, d] == i
            assert rounds[i].load == pytest.approx(r.load)

    def test_world_mismatch_raises(self):
        with pytest.raises(ValueError, match="world"):
            a2a_sched.wire_schedule(np.zeros((3, 3)), 4)


class TestTrafficHelpers:
    def test_traffic_from_topk_matches_drop_semantics(self):
        w, t, k, e, cap = 4, 16, 2, 8, 3
        rng = np.random.default_rng(2)
        idx = rng.integers(0, e, (w, t, k)).astype(np.int32)
        got = a2a_sched.traffic_from_topk(idx, e, cap, w)
        epp = e // w
        want = np.zeros((w, w), np.int64)
        for s in range(w):
            for ee in range(e):
                cnt = min(int((idx[s] == ee).sum()), cap)
                want[s, ee // epp] += cnt
        np.testing.assert_array_equal(got, want)

    def test_traffic_from_topk_rejects(self):
        with pytest.raises(ValueError, match="topk_idx"):
            a2a_sched.traffic_from_topk(np.zeros((3, 4), np.int32), 8, 2, 3)
        with pytest.raises(ValueError, match="divisible"):
            a2a_sched.traffic_from_topk(
                np.zeros((3, 4, 2), np.int32), 7, 2, 3
            )

    def test_zipf_topk_shapes_and_skew(self):
        rng = np.random.default_rng(0)
        idx = a2a_sched.zipf_topk(rng, 4, 256, 2, 8, alpha=1.2)
        assert idx.shape == (4, 256, 2) and idx.dtype == np.int32
        assert idx.min() >= 0 and idx.max() < 8
        hot = a2a_sched.traffic_from_topk(idx, 8, 10 ** 6, 4)
        uni = a2a_sched.traffic_from_topk(
            a2a_sched.zipf_topk(rng, 4, 256, 2, 8, alpha=0.0), 8, 10 ** 6, 4
        )
        assert a2a_sched.skew(hot) > a2a_sched.skew(uni)


# ---------------------------------------------------------------------------
# device: the scheduled kernel vs the lax contract
# ---------------------------------------------------------------------------


class TestScheduledKernel:
    @pytest.mark.parametrize("n", WORLDS_T1)
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_matches_lax(self, devices, rng, n, dtype):
        mesh = _mesh(devices, n)
        sched = a2a_sched.wire_schedule(_skewed(rng, n), n)
        # 5x9 trailing block keeps the per-chunk padding path hot
        x = jnp.asarray(rng.normal(size=(n, n, 5, 9)), dtype)
        got = np.asarray(_run(
            mesh,
            lambda v: pallas_a2a.scheduled_all_to_all(v[0], "ep", sched)[None],
            x,
        ))
        want = np.asarray(_run(
            mesh,
            lambda v: jax.lax.all_to_all(v[0], "ep", 0, 0, tiled=True)[None],
            x,
        ))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("chunks", [2, 3])
    def test_chunked_matches_lax(self, devices, rng, chunks):
        n = 4
        mesh = _mesh(devices, n)
        sched = a2a_sched.wire_schedule(_skewed(rng, n), n)
        x = jnp.asarray(rng.normal(size=(n, n, 5, 9)), jnp.float32)
        got = np.asarray(_run(
            mesh,
            lambda v: pallas_a2a.scheduled_all_to_all(
                v[0], "ep", sched, n_chunks=chunks, chunk_axis=2
            )[None],
            x,
        ))
        want = np.asarray(_run(
            mesh,
            lambda v: jax.lax.all_to_all(v[0], "ep", 0, 0, tiled=True)[None],
            x,
        ))
        np.testing.assert_array_equal(got, want)

    def test_zero_matrix_schedule_matches(self, devices, rng):
        """The rotation-completed empty schedule still ships every pair."""
        n = 4
        mesh = _mesh(devices, n)
        sched = a2a_sched.wire_schedule(np.zeros((n, n)), n)
        x = jnp.asarray(rng.normal(size=(n, n, 3, 5)), jnp.float32)
        got = np.asarray(_run(
            mesh,
            lambda v: pallas_a2a.scheduled_all_to_all(v[0], "ep", sched)[None],
            x,
        ))
        want = np.asarray(_run(
            mesh,
            lambda v: jax.lax.all_to_all(v[0], "ep", 0, 0, tiled=True)[None],
            x,
        ))
        np.testing.assert_array_equal(got, want)

    def test_budget_fallback_matches(self, devices, rng, monkeypatch):
        """Past the VMEM budget the scheduled call degrades to the
        unscheduled kernel and transitively to lax — same numbers."""
        from uccl_tpu.collective import dma

        monkeypatch.setenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES", "64")
        dma.MAX_VMEM_BYTES.reset()
        try:
            n = 4
            mesh = _mesh(devices, n)
            sched = a2a_sched.wire_schedule(_skewed(rng, n), n)
            x = jnp.asarray(rng.normal(size=(n, n, 8, 16)), jnp.float32)
            got = np.asarray(_run(
                mesh,
                lambda v: pallas_a2a.scheduled_all_to_all(
                    v[0], "ep", sched
                )[None],
                x,
            ))
            want = np.asarray(_run(
                mesh,
                lambda v: jax.lax.all_to_all(
                    v[0], "ep", 0, 0, tiled=True
                )[None],
                x,
            ))
            np.testing.assert_array_equal(got, want)
        finally:
            monkeypatch.delenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES")
            dma.MAX_VMEM_BYTES.reset()

    def test_bad_schedules_rejected(self, devices, rng):
        n = 4
        mesh = _mesh(devices, n)
        x = jnp.zeros((n, n, 4, 4), jnp.float32)
        rounds, k = a2a_sched.wire_schedule(_skewed(rng, n), n)

        def call(sched):
            _run(
                mesh,
                lambda v: pallas_a2a.scheduled_all_to_all(
                    v[0], "ep", sched
                )[None],
                x,
            )

        with pytest.raises(ValueError, match="not a permutation"):
            call(([(0, 0, 1, 2)], k))
        with pytest.raises(ValueError, match="designated-round"):
            call((rounds, np.zeros((3, 3), np.int32)))
        with pytest.raises(ValueError, match="at least one round"):
            call(([], np.zeros((n, n), np.int32)))
        bad_k = np.asarray(k).copy()
        bad_k[0, 1] = (bad_k[0, 1] + 1) % len(rounds)
        with pytest.raises(ValueError, match="does not carry"):
            call((rounds, bad_k))


class TestScheduledSortedPath:
    """dispatch_sorted/combine_sorted with a schedule vs the plain pallas
    wire — the scale exchange rides the same rounds under fp8."""

    @pytest.mark.parametrize("n", WORLDS_T1)
    @pytest.mark.parametrize("fp8", [False, True])
    def test_roundtrip_matches_unscheduled(self, devices, rng, n, fp8):
        mesh = _mesh(devices, n)
        t, h, e, k = 12, 24, 2 * n, 2
        cap = max(1, int(1.25 * t * k / e))
        x = rng.standard_normal((n, t, h)).astype(np.float32)
        idx = rng.integers(0, e, (n, t, k)).astype(np.int32)
        wts = rng.uniform(0.1, 1.0, (n, t, k)).astype(np.float32)
        mat = a2a_sched.traffic_from_topk(idx, e, cap, n).astype(float)
        sched = a2a_sched.wire_schedule(mat, n)
        sched_t = a2a_sched.wire_schedule(mat.T, n)

        def path(schedule, schedule_back):
            def f(xv, iv, wv):
                tfs, slot, _ = ep_ops.sorted_from_topk(iv[0], e, cap)
                recv = ep_ops.dispatch_sorted(
                    xv[0], tfs, e, cap, "ep", wire="pallas",
                    wire_fp8=fp8, schedule=schedule,
                )
                out = ep_ops.combine_sorted(
                    recv * 2.0, slot, wv[0], "ep", wire="pallas",
                    wire_fp8=fp8, schedule=schedule_back,
                )
                return recv[None], out[None]

            return _run(
                mesh, f, jnp.asarray(x), jnp.asarray(idx), jnp.asarray(wts),
                out_specs=(P("ep"), P("ep")),
            )

        recv_s, out_s = map(np.asarray, path(sched, sched_t))
        recv_u, out_u = map(np.asarray, path(None, None))
        np.testing.assert_array_equal(recv_s, recv_u)
        np.testing.assert_array_equal(out_s, out_u)


class TestBufferSched:
    """Buffer(a2a_sched=...): the knob surface — on/auto/off bit-identical,
    handles record the choice, combine rides the transposed matrix, and
    the decisions land on the obs pair."""

    def _case(self, rng, w, t, h, e, k, alpha=1.2):
        x = jnp.asarray(rng.standard_normal((w, t, h)), jnp.float32)
        idx = jnp.asarray(a2a_sched.zipf_topk(rng, w, t, k, e, alpha))
        traffic = a2a_sched.traffic_from_topk(np.asarray(idx), e, 8, w)
        return x, idx, traffic

    @pytest.mark.parametrize("n", WORLDS_T1)
    def test_modes_identical(self, devices, rng, n):
        mesh = _mesh(devices, n)
        e = 2 * n
        x, idx, traffic = self._case(rng, n, 16, 64, e, 2)
        outs = {}
        for mode in ("off", "on", "auto"):
            buf = Buffer(mesh, "ep", num_experts=e, wire="pallas",
                         a2a_sched=mode, a2a_traffic=traffic)
            recv, h = buf.dispatch(x, idx)
            out = buf.combine(recv * 2.0, h)
            outs[mode] = (np.asarray(recv), np.asarray(out), h.a2a_sched)
        assert outs["on"][2] is True and outs["off"][2] is False
        for mode in ("on", "auto"):
            np.testing.assert_array_equal(outs[mode][0], outs["off"][0])
            np.testing.assert_array_equal(outs[mode][1], outs["off"][1])

    @pytest.mark.slow
    def test_fp8_chunked_composition(self, devices, rng):
        mesh = _mesh(devices, 4)
        x, idx, traffic = self._case(rng, 4, 16, 64, 8, 2)
        outs = {}
        for mode in ("off", "on"):
            buf = Buffer(mesh, "ep", num_experts=8, wire="pallas",
                         a2a_sched=mode, a2a_traffic=traffic, n_chunks=2)
            recv, h = buf.dispatch(x, idx, wire_dtype="fp8")
            out = buf.combine(recv * 2.0, h, wire_dtype="fp8")
            outs[mode] = (np.asarray(recv), np.asarray(out))
        np.testing.assert_array_equal(outs["on"][0], outs["off"][0])
        np.testing.assert_array_equal(outs["on"][1], outs["off"][1])

    def test_auto_uniform_keeps_streams(self, devices, rng):
        mesh = _mesh(devices, 4)
        x, idx, _ = self._case(rng, 4, 16, 32, 8, 2, alpha=0.0)
        buf = Buffer(mesh, "ep", num_experts=8, wire="pallas",
                     a2a_sched="auto")  # no matrix: uniform default
        _, h = buf.dispatch(x, idx)
        assert h.a2a_sched is False

    def test_counters_fire(self, devices, rng):
        from uccl_tpu.collective import plan as _plan

        mesh = _mesh(devices, 4)
        x, idx, traffic = self._case(rng, 4, 16, 32, 8, 2)
        rounds_before = _by_labels(a2a_sched.ROUNDS_TOTAL.samples())
        plans_before = _by_labels(_plan.PLAN_TOTAL.samples())
        buf = Buffer(mesh, "ep", num_experts=8, wire="pallas",
                     a2a_sched="on", a2a_traffic=traffic)
        recv, h = buf.dispatch(x, idx)
        buf.combine(recv, h)
        rounds = _by_labels(a2a_sched.ROUNDS_TOTAL.samples())
        key = next(
            (k for k in rounds if dict(k)["algo"] == "ep_sched"), None
        )
        assert key is not None
        assert rounds[key] > rounds_before.get(key, 0)
        plans = _by_labels(_plan.PLAN_TOTAL.samples())
        ep_keys = [
            k for k in plans
            if dict(k).get("verb") == "ep_a2a"
            and dict(k)["algo"] == "ep_sched"
            and plans[k] > plans_before.get(k, 0)
        ]
        assert ep_keys, plans
        # the gauge saw the matrix (combine's transposed view lands last)
        [(_, sk)] = a2a_sched.SKEW_GAUGE.samples()
        assert sk >= 1.0

    def test_bad_mode_rejected(self, devices):
        mesh = _mesh(devices, 4)
        with pytest.raises(ValueError, match="a2a_sched"):
            Buffer(mesh, "ep", num_experts=8, a2a_sched="sometimes")


# ---------------------------------------------------------------------------
# the planner: ep_sched vs ep_streams, and the fourth verb
# ---------------------------------------------------------------------------


class TestPlanEpA2a:
    def _planner(self):
        from uccl_tpu.collective.plan import CollectivePlanner

        return CollectivePlanner()

    def test_skew_crossover(self):
        """Small payload (under the interpret budget): uniform keeps the
        streams, heavy skew flips to the schedule — the (skew−1)·β·B vs
        (rounds−1)·γ crossover of the one cost model."""
        p = self._planner()
        shape, dt, w = (4, 8, 128), jnp.float32, 4
        uni = p.plan_ep_a2a(shape, dt, w, skew=1.0, emit=False)
        assert uni.algo == "ep_streams" and uni.verb == "ep_a2a"
        hot = p.plan_ep_a2a(shape, dt, w, skew=6.0, n_rounds=3, emit=False)
        assert hot.algo == "ep_sched"
        assert hot.chunks == 3  # chunks field carries the round count

    def test_budget_gates_sched(self):
        """A payload past the kernel budget never plans ep_sched, however
        skewed — auto must not pick rounds whose first act is a counted
        fallback."""
        p = self._planner()
        big = p.plan_ep_a2a((8, 64, 512), jnp.bfloat16, 8, skew=6.0,
                            emit=False)
        assert big.algo == "ep_streams"

    def test_world1_degenerate(self):
        p = self._planner()
        one = p.plan_ep_a2a((1, 8), jnp.float32, 1, skew=9.0, emit=False)
        assert one.algo == "ep_streams" and one.predicted_us == 0.0


class TestPlannedReduceScatter:
    def _comm(self, devices, n=4):
        # single-named-axis mesh: the legacy discharge interpreter can only
        # address flat logical ids, so the ring arm needs Mesh(("dp",))
        from uccl_tpu.collective import Communicator

        return Communicator(
            Mesh(np.array(devices[:n]), ("dp",)), "dp"
        )

    @pytest.mark.parametrize("algo", ["auto", "ring", "xla"])
    def test_matches_numpy(self, devices, rng, algo):
        comm = self._comm(devices)
        x = rng.standard_normal((4, 8, 16)).astype(np.float32)
        out = np.asarray(
            comm.reduce_scatter(comm.device_put(x), algo=algo)
        )
        want = x.sum(0).reshape(4, 2, 16)
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)

    def test_fp8_wire_bounded_error(self, devices, rng):
        comm = self._comm(devices)
        x = rng.standard_normal((4, 8, 16)).astype(np.float32)
        out = np.asarray(comm.reduce_scatter(
            comm.device_put(x), algo="ring", wire_dtype="fp8"
        ))
        want = x.sum(0).reshape(4, 2, 16)
        err = np.abs(out - want) / (np.abs(want) + 1e-3)
        assert float(np.median(err)) < 0.15

    def test_plan_counter_rows(self, devices, rng):
        from uccl_tpu.collective import plan as _plan

        before = _by_labels(_plan.PLAN_TOTAL.samples())
        comm = self._comm(devices)
        x = rng.standard_normal((4, 8, 16)).astype(np.float32)
        comm.reduce_scatter(comm.device_put(x), algo="ring")
        comm.reduce_scatter(comm.device_put(x), algo="auto")
        after = _by_labels(_plan.PLAN_TOTAL.samples())
        rows = [
            dict(k) for k in after
            if dict(k).get("verb") == "reduce_scatter"
            and after[k] > before.get(k, 0)
        ]
        assert any(r["outcome"] == "explicit" for r in rows), rows
        assert any(r["outcome"] in ("model", "fallback") for r in rows), rows

    def test_planner_unit(self):
        from uccl_tpu.collective.plan import CollectivePlanner

        p = CollectivePlanner()
        auto = p.plan_reduce_scatter((4, 8, 16), jnp.float32, 4,
                                     pallas_ok=True, emit=False)
        assert auto.verb == "reduce_scatter"
        assert auto.algo in ("ring", "xla")
        no_pallas = p.plan_reduce_scatter((4, 8, 16), jnp.float32, 4,
                                          pallas_ok=False, emit=False)
        assert no_pallas.algo == "xla"
        one = p.plan_reduce_scatter((4, 8), jnp.float32, 1, emit=False)
        assert one.algo == "xla"


# ---------------------------------------------------------------------------
# cross-pod: scheduled rounds on the DCN wire
# ---------------------------------------------------------------------------


def _run_dcn_group(world, fn, tag):
    import threading

    from uccl_tpu.collective.hierarchical import DcnGroup
    from uccl_tpu.p2p.store import StoreClient, StoreServer
    from uccl_tpu.parallel.distributed import Session

    server = StoreServer()
    results = [None] * world
    errors = []

    def rank_main(r):
        try:
            client = StoreClient("127.0.0.1", server.port)
            sess = Session(rank=r, world=world, store=client)
            g = DcnGroup(sess, n_paths=2, tag=tag)
            try:
                results[r] = fn(g, r)
            finally:
                g.close()
                client.close()
        except Exception as e:  # pragma: no cover
            import traceback

            errors.append((r, traceback.format_exc()))

    ts = [threading.Thread(target=rank_main, args=(r,))
          for r in range(world)]
    [t.start() for t in ts]
    [t.join(timeout=120) for t in ts]
    server.close()
    assert not errors, errors[0][1]
    return results


class TestDcnScheduled:
    @pytest.mark.parametrize("world", [3, 4])
    def test_matches_unscheduled(self, rng, world):
        mat = _skewed(np.random.default_rng(world), world)
        sched = a2a_sched.wire_schedule(mat, world)
        xs = [rng.standard_normal((world, 7)).astype(np.float32)
              for _ in range(world)]
        outs_s = _run_dcn_group(
            world, lambda g, r: g.all_to_all(xs[r], schedule=sched),
            tag=f"sched{world}",
        )
        outs_u = _run_dcn_group(
            world, lambda g, r: g.all_to_all(xs[r]), tag=f"unsched{world}"
        )
        for a, b in zip(outs_s, outs_u):
            np.testing.assert_array_equal(a, b)

    def test_incomplete_schedule_rejected(self, rng):
        """A K matrix that misses a pair must fail fast on every rank, not
        deadlock the exchange."""
        world = 3
        rounds, k = a2a_sched.wire_schedule(np.zeros((world, world)), world)
        bad_k = np.asarray(k).copy()
        bad_k[0, 1] = (bad_k[0, 1] + 1) % len(rounds)

        def body(g, r):
            with pytest.raises(ValueError, match="does not carry"):
                g.all_to_all(np.zeros((world, 4), np.float32),
                             schedule=(rounds, bad_k))
            return True

        assert all(_run_dcn_group(world, body, tag="badk"))

    @pytest.mark.slow
    def test_mixed_with_unscheduled_and_broadcast(self):
        """Scheduled and unscheduled exchanges interleave on one group
        without poisoning the license/parity protocol."""
        world = 3
        mat = np.ones((world, world))
        np.fill_diagonal(mat, 0.0)
        sched = a2a_sched.wire_schedule(mat, world)

        def body(g, r):
            a1 = g.all_to_all(
                np.full((world, 4), float(10 * r), np.float32),
                schedule=sched,
            )
            a2 = g.all_to_all(
                np.full((world, 4), float(10 * r + 1), np.float32)
            )
            b = g.broadcast(np.full(8, float(r), np.float32), root=2)
            return ([a1[j][0] for j in range(world)],
                    [a2[j][0] for j in range(world)], b[0])

        for r, (a1, a2, b) in enumerate(_run_dcn_group(world, body, "mix")):
            assert a1 == [0.0, 10.0, 20.0]
            assert a2 == [1.0, 11.0, 21.0]
            assert b == 2.0


@pytest.mark.slow
class TestCrossPodScheduled:
    def test_two_pods_sched_matches_off(self, devices, rng):
        import threading

        from uccl_tpu.collective.hierarchical import DcnGroup
        from uccl_tpu.ep.cross_pod import CrossPodMoE
        from uccl_tpu.p2p.store import StoreClient, StoreServer
        from uccl_tpu.parallel.distributed import Session
        from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

        P_pods, E, T, H, F, K = 2, 8, 24, 16, 32, 2
        epp = E // P_pods
        wg = (rng.standard_normal((E, H, F)) * 0.2).astype(np.float32)
        wd = (rng.standard_normal((E, F, H)) * 0.2).astype(np.float32)
        x = rng.standard_normal((P_pods, T, H)).astype(np.float32)
        logits = rng.standard_normal((P_pods, T, E)).astype(np.float32)
        gates = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        ti = np.argsort(-gates, axis=-1)[..., :K].astype(np.int32)
        tv = np.take_along_axis(gates, ti, -1)
        tv = (tv / tv.sum(-1, keepdims=True)).astype(np.float32)
        skewed = np.array([[0.0, 5.0], [1.0, 0.0]])

        def expert_fn(buf, w):
            hmid = jnp.maximum(
                jnp.einsum("ech,ehf->ecf", buf, w["wg"]), 0.0
            )
            return jnp.einsum("ecf,efh->ech", hmid, w["wd"])

        def run(mode, traffic, tag):
            server = StoreServer()
            results, errors = {}, []

            def pod_main(p):
                try:
                    client = StoreClient("127.0.0.1", server.port)
                    sess = Session(rank=p, world=P_pods, store=client)
                    dcn = DcnGroup(sess, n_paths=2, tag=tag)
                    mesh = make_mesh(
                        MeshConfig(dp=4), devices[p * 4:(p + 1) * 4]
                    )
                    moe = CrossPodMoE(
                        dcn, mesh, num_global_experts=E, num_selected=K,
                        capacity_factor=float(E), a2a_sched=mode,
                        a2a_traffic=traffic,
                    )
                    results[p] = moe.forward(x[p], ti[p], tv[p], {
                        "fn": expert_fn,
                        "wg": jnp.asarray(wg[p * epp:(p + 1) * epp]),
                        "wd": jnp.asarray(wd[p * epp:(p + 1) * epp]),
                    })
                    dcn.close()
                    client.close()
                except Exception as e:  # pragma: no cover
                    import traceback

                    errors.append((p, traceback.format_exc()))

            ts = [threading.Thread(target=pod_main, args=(p,))
                  for p in range(P_pods)]
            [t.start() for t in ts]
            [t.join(timeout=180) for t in ts]
            server.close()
            assert not errors, errors[0][1]
            return results

        off = run("off", None, "xs_off")
        on = run("on", skewed, "xs_on")
        for p in range(P_pods):
            np.testing.assert_array_equal(
                np.asarray(on[p]), np.asarray(off[p])
            )
