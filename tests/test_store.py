"""OOB rendezvous store + session exchange (single-host multiprocess, the
shape of the reference's bootstrap handshakes)."""

import multiprocessing as mp
import threading
import time

import pytest

from uccl_tpu.p2p.store import StoreClient, StoreServer
from uccl_tpu.parallel.distributed import Session, exchange, exchange_json


@pytest.fixture
def store():
    server = StoreServer()
    client = StoreClient("127.0.0.1", server.port)
    yield server, client
    client.close()
    server.close()


class TestStore:
    def test_set_get(self, store):
        _, client = store
        client.set("k1", b"v1")
        assert client.get("k1") == b"v1"
        assert client.get("missing") is None

    def test_wait_blocks_until_set(self, store):
        server, client = store
        other = StoreClient("127.0.0.1", server.port)

        def setter():
            time.sleep(0.2)
            other.set("late", b"here")

        t = threading.Thread(target=setter)
        t.start()
        assert client.wait("late", timeout_s=5) == b"here"
        t.join()
        other.close()

    def test_wait_timeout(self, store):
        _, client = store
        with pytest.raises(TimeoutError):
            client.wait("never", timeout_s=0.3)

    def test_many_clients(self, store):
        server, _ = store
        clients = [StoreClient("127.0.0.1", server.port) for _ in range(4)]
        for i, c in enumerate(clients):
            c.set(f"rank/{i}", str(i).encode())
        for c in clients:
            for i in range(4):
                assert c.get(f"rank/{i}") == str(i).encode()
        [c.close() for c in clients]

    def test_binary_values(self, store):
        _, client = store
        blob = bytes(range(256)) * 100
        client.set("bin", blob)
        assert client.get("bin") == blob


class TestExchange:
    def test_exchange_two_ranks(self, store):
        server, c0 = store
        c1 = StoreClient("127.0.0.1", server.port)
        s0 = Session(rank=0, world=2, store=c0)
        s1 = Session(rank=1, world=2, store=c1)
        results = {}

        def run(sess, payload):
            results[sess.rank] = exchange(sess, "meta", payload, timeout_s=5)

        t0 = threading.Thread(target=run, args=(s0, b"zero"))
        t1 = threading.Thread(target=run, args=(s1, b"one"))
        t0.start(), t1.start()
        t0.join(), t1.join()
        assert results[0] == [b"zero", b"one"]
        assert results[1] == [b"zero", b"one"]
        c1.close()

    def test_exchange_json(self, store):
        server, c0 = store
        s0 = Session(rank=0, world=1, store=c0)
        out = exchange_json(s0, "cfg", {"port": 1234})
        assert out == [{"port": 1234}]
