"""Quantized wire collectives vs the full-precision oracle.

The EQuARX-shaped contract (docs/QUANT_WIRE.md): ``wire_dtype="fp8"|"int8"``
quantizes only what crosses the wire — reduce-scatter dequantizes before
accumulating in the input precision, all-gather quantizes once and forwards
verbatim — so the end-to-end error of a world-n allreduce is bounded by n
per-block quantize round trips (each ``<= amax / QERR``), regardless of how
partial sums grow. This suite pins:

* that bound, elementwise, at worlds 4 / 8 / 5 (odd world 5 = pad path +
  the one-credit ring schedule; marked ``slow`` per the tier-1 budget);
* exact zeros on zero input (the codec's scale-guard contract);
* outlier isolation (a huge value only pollutes its own 128-lane block);
* bit-identity between the Pallas kernel and its pure-lax mirror (the
  budget/addressability fallback MUST be the same math);
* counted-not-silent downgrades (non-float payload rides the
  full-precision wire, visible on ``ep_wire_fallback_total``);
* the Buffer-level EP arms — dispatch/combine under ``wire_dtype`` against
  the full-precision result, chunked ``n_chunks>1`` composing
  bit-identically, and ``ep_bytes_total`` carrying the quantized wire-byte
  arithmetic (payload + scale sidecar) under the ``wire_dtype`` label.

All meshes here are single-named-axis so every case runs under the legacy
discharge interpreter too (same choice as test_pallas_ccl's odd worlds).

Tier-1 time budget: the suite sits at the 870s cap (ROADMAP), so tier-1
keeps only a representative core — the world-4 fp8 bound arms of each
collective, the quantized Buffer round trip, and the wire-byte counter
contract (~9s) — and every other arm (world 8/5, int8, bf16, zero-exact
kernels, outlier, the kernel==mirror double-compile, the chunked
composition, counted downgrades, the moe_ffn knob) is marked ``slow``:
they run in qa.sh / ci.yml's unfiltered pytest, and the CI fail-fast
quantized smoke (pallas_a2a_proof --interpret --wire-dtype) re-proves
zero-exactness, the error bound, and pallas==lax bit-identity at worlds
4/5 per push anyway.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from uccl_tpu.collective import dma, pallas_ccl
from uccl_tpu.ep import ops as ep_ops
from uccl_tpu.utils.jaxcompat import shard_map

# per-round-trip error divisors of the codec (uccl_tpu.ops.quant module
# docstring): fp8 half-ulp at 448 + f16 double-rounding slack, int8 half a
# 1/127 step
QERR = {"fp8": 448.0 / 16.125, "int8": 254.0}

WORLDS = [4, pytest.param(8, marks=pytest.mark.slow),
          pytest.param(5, marks=pytest.mark.slow)]
WIRE_DTYPES = ["fp8", pytest.param("int8", marks=pytest.mark.slow)]


def _mesh(devices, n):
    return Mesh(np.array(devices[:n]), ("dp",))


def _run(mesh, fn, *args, out_specs=P("dp")):
    mapped = shard_map(
        fn, mesh=mesh, in_specs=tuple(P("dp") for _ in args),
        out_specs=out_specs, check_vma=False,
    )
    return np.asarray(jax.jit(mapped)(*args))


def _fb_snapshot():
    return {tuple(sorted(lb.items())): v
            for lb, v in dma.WIRE_FALLBACK.samples()}


def _fb_reasons(before):
    out = {}
    for k, v in _fb_snapshot().items():
        d = v - before.get(k, 0)
        if d > 0:
            lb = dict(k)
            out[(lb["what"], lb["reason"])] = int(d)
    return out


def _allreduce_bound(xs, n, wd):
    """Elementwise error budget of a quantized world-n allreduce: n block
    round trips (n-1 RS hops + the quantize-once AG), each bounded by its
    block amax / QERR; every partial sum's amax is bounded by the
    elementwise sum of absolutes."""
    return n * np.abs(xs).sum(axis=0).max() / QERR[wd] * 1.05


class TestQuantRings:
    @pytest.mark.parametrize("n", WORLDS)
    @pytest.mark.parametrize("wd", WIRE_DTYPES)
    def test_allreduce_within_bound(self, devices, rng, n, wd):
        mesh = _mesh(devices, n)
        xs = rng.normal(size=(n, 6, 100)).astype(np.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_reduce(
                v, "dp", interpret=True, wire_dtype=wd
            ),
            jnp.asarray(xs), out_specs=P("dp", None),
        )
        want = np.tile(xs.sum(0), (n, 1, 1))
        assert np.abs(got - want).max() <= _allreduce_bound(xs, n, wd)
        # every member dequantizes the same wire bytes -> identical copies
        per = got.reshape(n, 6, 100)
        assert (per == per[0]).all()

    @pytest.mark.slow
    @pytest.mark.parametrize("bidi", [False, True])
    def test_allreduce_unidirectional_and_nondividing(self, devices, rng,
                                                      bidi):
        """257-element payload: the pad path, both ring layouts."""
        n = 4
        mesh = _mesh(devices, n)
        xs = rng.normal(size=(n, 257)).astype(np.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_reduce(
                v, "dp", bidirectional=bidi, interpret=True,
                wire_dtype="fp8",
            ),
            jnp.asarray(xs), out_specs=P("dp", None),
        )
        want = np.tile(xs.sum(0), (n, 1))
        assert np.abs(got - want).max() <= _allreduce_bound(xs, n, "fp8")

    @pytest.mark.parametrize("wd", WIRE_DTYPES)
    @pytest.mark.slow
    def test_allreduce_zero_exact(self, devices, wd):
        n = 4
        mesh = _mesh(devices, n)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_reduce(
                v, "dp", interpret=True, wire_dtype=wd
            ),
            jnp.zeros((n, 3, 64), jnp.float32), out_specs=P("dp", None),
        )
        np.testing.assert_array_equal(got, 0.0)

    @pytest.mark.slow
    def test_allreduce_outlier_isolated_to_block(self, devices, rng):
        """A 1e4 outlier saturates its own 128-lane block's scale but must
        not degrade blocks it does not live in."""
        n = 4
        mesh = _mesh(devices, n)
        # exactly 2 wire rows per stream chunk: flat[0:128] is one block
        xs = rng.normal(size=(n, n * 2 * 2 * 128)).astype(np.float32)
        xs[0, 0] = 1e4
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_reduce(
                v, "dp", interpret=True, wire_dtype="fp8"
            ),
            jnp.asarray(xs), out_specs=P("dp", None),
        )
        want = np.tile(xs.sum(0), (n, 1))
        # the outlier's own value still lands within its (huge-amax) bound
        assert abs(got[0, 0] - want[0, 0]) <= _allreduce_bound(xs, n, "fp8")
        # all other blocks obey the bound computed WITHOUT the outlier
        clean = xs.copy()
        clean[0, 0] = 0.0
        bound = _allreduce_bound(clean, n, "fp8")
        assert np.abs(got[:, 128:] - want[:, 128:]).max() <= bound

    @pytest.mark.parametrize("wd", WIRE_DTYPES)
    def test_allgather_bounded_and_identical(self, devices, rng, wd):
        n = 4
        mesh = _mesh(devices, n)
        xs = rng.normal(size=(n, 4, 50)).astype(np.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_gather(
                v, "dp", interpret=True, wire_dtype=wd
            ),
            jnp.asarray(xs), out_specs=P("dp", None),
        )
        want = np.tile(xs.reshape(n * 4, 50), (n, 1)).reshape(got.shape)
        # one quantize round trip from the input, identical on all members
        assert np.abs(got - want).max() <= np.abs(xs).max() / QERR[wd] * 1.05
        per = got.reshape(n, n * 4, 50)
        assert (per == per[0]).all()

    @pytest.mark.parametrize("n", WORLDS)
    def test_reduce_scatter_within_bound(self, devices, rng, n):
        mesh = _mesh(devices, n)
        xs = rng.normal(size=(n, n * 6)).astype(np.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_reduce_scatter(
                v.reshape(n * 6), "dp", interpret=True, wire_dtype="fp8"
            ).reshape(1, 6),
            jnp.asarray(xs), out_specs=P("dp", None),
        )
        want = xs.sum(axis=0).reshape(n, 6)
        # n-1 hops of one round trip each
        bound = (n - 1) * np.abs(xs).sum(axis=0).max() / QERR["fp8"] * 1.05
        assert np.abs(got - want).max() <= bound

    @pytest.mark.slow
    def test_bf16_payload(self, devices, rng):
        n = 4
        mesh = _mesh(devices, n)
        xs = rng.normal(size=(n, 256)).astype(np.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_reduce(
                v, "dp", interpret=True, wire_dtype="fp8"
            ),
            jnp.asarray(xs, jnp.bfloat16), out_specs=P("dp", None),
        ).astype(np.float32)
        want = np.tile(xs.sum(0), (n, 1))
        # quant round trips + bf16 accumulation noise
        bound = _allreduce_bound(xs, n, "fp8") + 0.1 * np.abs(want).max()
        assert np.abs(got - want).max() <= bound

    @pytest.mark.parametrize("wd", WIRE_DTYPES)
    @pytest.mark.slow
    def test_kernel_bit_identical_to_lax_mirror(self, devices, rng,
                                                monkeypatch, wd):
        """The budget fallback of the quantized rings is a pure-lax mirror
        of the SAME per-hop math — forcing it must change nothing, bit for
        bit (the fallback is a transport decision, never a numerics one)."""
        n = 4
        mesh = _mesh(devices, n)
        xs = jnp.asarray(rng.normal(size=(n, 3, 70)).astype(np.float32))

        def ar(v):
            return pallas_ccl.ring_all_reduce(
                v, "dp", interpret=True, wire_dtype=wd
            )

        kernel = _run(mesh, ar, xs, out_specs=P("dp", None))
        monkeypatch.setenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES", "64")
        pallas_ccl._MAX_VMEM_BYTES.reset()
        try:
            mirror = _run(mesh, ar, xs, out_specs=P("dp", None))
        finally:
            monkeypatch.delenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES")
            pallas_ccl._MAX_VMEM_BYTES.reset()
        np.testing.assert_array_equal(kernel, mirror)

    @pytest.mark.slow
    def test_int_payload_downgrades_counted(self, devices):
        """wire_dtype on a non-float payload ships full precision (exact
        result) and counts the downgrade — never silent."""
        n = 4
        mesh = _mesh(devices, n)
        xs = np.arange(n * 32, dtype=np.int32).reshape(n, 32)
        before = _fb_snapshot()
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_reduce(
                v, "dp", interpret=True, wire_dtype="fp8"
            ),
            jnp.asarray(xs), out_specs=P("dp", None),
        )
        np.testing.assert_array_equal(got, np.tile(xs.sum(0), (n, 1)))
        assert _fb_reasons(before).get(("all_reduce", "quant_dtype"), 0) >= 1


class TestQuantBufferA2A:
    """Buffer-level EP arms under ``wire_dtype``."""

    def _data(self, rng, n, t=16, h=64, e_per=2, k=2):
        e = e_per * n
        xs = rng.standard_normal((n, t, h)).astype(np.float32)
        idx = rng.integers(0, e, (n, t, k)).astype(np.int32)
        wts = rng.uniform(0.1, 1.0, (n, t, k)).astype(np.float32)
        return e, xs, idx, wts

    def _roundtrip(self, buf, xs, idx, wts, **kw):
        recv, handle = buf.dispatch(
            jnp.asarray(xs), jnp.asarray(idx), jnp.asarray(wts), **kw
        )
        return np.asarray(recv), np.asarray(
            buf.combine(recv, handle, **kw)
        )

    @pytest.mark.parametrize("n", WORLDS)
    @pytest.mark.parametrize("wd", WIRE_DTYPES)
    def test_dispatch_combine_within_bound(self, devices, rng, n, wd):
        from uccl_tpu.ep import Buffer

        mesh = _mesh(devices, n)
        e, xs, idx, wts = self._data(rng, n)
        full = Buffer(mesh, "dp", num_experts=e, num_selected=2)
        quant = Buffer(mesh, "dp", num_experts=e, num_selected=2,
                       wire_dtype=wd)
        recv_f, out_f = self._roundtrip(full, xs, idx, wts)
        recv_q, out_q = self._roundtrip(quant, xs, idx, wts)
        # dispatch: one round trip per row, block amax <= row amax
        bound = np.abs(xs).max() / QERR[wd] * 1.05
        assert np.abs(recv_q - recv_f).max() <= bound
        # combine adds a second round trip; gate weights sum to <= k
        bound = 2 * 2 * np.abs(recv_f).max() / QERR[wd] * 1.1
        assert np.abs(out_q - out_f).max() <= bound

    @pytest.mark.slow
    def test_chunked_composes_bit_identically(self, devices, rng):
        """wire_dtype x n_chunks>1: blocks run along the hidden dim, the
        chunk split along capacity — quantize-then-chunk must equal the
        unchunked quantized exchange bit for bit."""
        from uccl_tpu.ep import Buffer

        n = 4
        mesh = _mesh(devices, n)
        e, xs, idx, wts = self._data(rng, n)
        one = Buffer(mesh, "dp", num_experts=e, num_selected=2,
                     wire="pallas", n_chunks=1, wire_dtype="fp8")
        two = Buffer(mesh, "dp", num_experts=e, num_selected=2,
                     wire="pallas", n_chunks=2, wire_dtype="fp8")
        recv1, out1 = self._roundtrip(one, xs, idx, wts)
        recv2, out2 = self._roundtrip(two, xs, idx, wts)
        np.testing.assert_array_equal(recv1, recv2)
        np.testing.assert_array_equal(out1, out2)

    def test_wire_bytes_counted_with_label(self, devices, rng):
        """ep_bytes_total must carry the QUANTIZED wire arithmetic
        (1 byte/elem + f32 scale sidecar) under the wire_dtype label, not
        logical element bytes."""
        from uccl_tpu.ep import Buffer
        from uccl_tpu.ep.buffer import EP_BYTES

        n = 4
        mesh = _mesh(devices, n)
        e, xs, idx, wts = self._data(rng, n)

        def snap():
            return {tuple(sorted(lb.items())): v
                    for lb, v in EP_BYTES.samples()}

        buf = Buffer(mesh, "dp", num_experts=e, num_selected=2,
                     wire_dtype="int8")
        before = snap()
        buf.dispatch(jnp.asarray(xs), jnp.asarray(idx), jnp.asarray(wts))
        deltas = {k: v - before.get(k, 0) for k, v in snap().items()
                  if v > before.get(k, 0)}
        (key, got), = deltas.items()
        lb = dict(key)
        assert lb["verb"] == "dispatch" and lb["wire_dtype"] == "int8"
        assert got == ep_ops.wire_bytes_of(xs.shape, xs.dtype, "int8")
        # and that is strictly less than the logical f32 bytes
        assert got < xs.size * 4

    @pytest.mark.slow
    def test_nonfloat_payload_downgrades_counted(self, devices, rng):
        """An integer payload under wire_dtype ships full precision —
        bit-exact vs the unquantized Buffer — and counts the downgrade on
        ep_wire_fallback_total{what=ep_wire_quant,reason=quant_dtype},
        the same rule the rings enforce. wire_bytes_of charges raw bytes
        for it (the counter must match what actually moved)."""
        from uccl_tpu.ep import Buffer

        n = 4
        mesh = _mesh(devices, n)
        e, _, idx, wts = self._data(rng, n)
        xs = rng.integers(-1000, 1000, (n, 16, 64)).astype(np.int32)
        full = Buffer(mesh, "dp", num_experts=e, num_selected=2)
        quant = Buffer(mesh, "dp", num_experts=e, num_selected=2,
                       wire_dtype="fp8")
        recv_f, _ = full.dispatch(
            jnp.asarray(xs), jnp.asarray(idx), jnp.asarray(wts))
        before = _fb_snapshot()
        recv_q, _ = quant.dispatch(
            jnp.asarray(xs), jnp.asarray(idx), jnp.asarray(wts))
        assert _fb_reasons(before).get(
            ("ep_wire_quant", "quant_dtype"), 0) >= 1
        np.testing.assert_array_equal(np.asarray(recv_q),
                                      np.asarray(recv_f))
        assert ep_ops.wire_bytes_of(xs.shape, xs.dtype, "fp8") == \
            xs.size * 4

    @pytest.mark.slow
    def test_moe_ffn_quantized_matches_full_precision(self, devices, rng):
        """The model-layer knob: moe_ffn(wire_dtype=) stays within a loose
        tolerance of the full-precision layer (2 wire round trips deep
        inside a SwiGLU stack — this is the flagship/moe_inference path)."""
        n = 4
        mesh = _mesh(devices, n)
        t, h, f, e, k = 8, 64, 32, 8, 2
        xs = rng.standard_normal((n, t, h)).astype(np.float32)
        logits = rng.standard_normal((n, t, e)).astype(np.float32)
        s = 1.0 / np.sqrt(h)
        wg = (rng.standard_normal((e, h, f)) * s).astype(np.float32)
        wu = (rng.standard_normal((e, h, f)) * s).astype(np.float32)
        wdn = (rng.standard_normal((e, f, h)) * s).astype(np.float32)

        def layer(wd):
            def f_(xv, lv, g, u, d):
                out, _, _ = ep_ops.moe_ffn(
                    xv[0], lv[0], g, u, d, "dp", num_selected=k,
                    capacity_factor=1.25, impl="sort", wire_dtype=wd,
                )
                return out[None]

            mapped = shard_map(
                f_, mesh=mesh,
                in_specs=tuple(P("dp") for _ in range(5)),
                out_specs=P("dp"), check_vma=False,
            )
            return np.asarray(jax.jit(mapped)(
                *map(jnp.asarray, (xs, logits, wg, wu, wdn))
            ))

        ref = layer(None)
        for wd in ("fp8", "int8"):
            got = layer(wd)
            err = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-12)
            assert err < {"fp8": 0.15, "int8": 0.03}[wd], (wd, err)
