"""FP8 wire-quantization roundtrip accuracy."""

import jax.numpy as jnp
import numpy as np
import pytest

from uccl_tpu.ops.quant import dequantize_fp8, quantize_fp8


def test_roundtrip_accuracy(rng):
    x = rng.standard_normal((4, 16, 256)).astype(np.float32)
    q, scale = quantize_fp8(jnp.asarray(x), group_size=128)
    assert q.dtype == jnp.float8_e4m3fn
    assert scale.shape == (4, 16, 2)
    back = np.asarray(dequantize_fp8(q, scale, 128, dtype=jnp.float32))
    rel = np.abs(back - x) / (np.abs(x).max() + 1e-9)
    assert rel.max() < 0.05  # e4m3 has ~2 decimal digits


def test_scale_handles_outliers(rng):
    x = rng.standard_normal((2, 256)).astype(np.float32)
    x[0, 0] = 1e4  # huge outlier in group 0
    q, scale = quantize_fp8(jnp.asarray(x), group_size=128)
    back = np.asarray(dequantize_fp8(q, scale, 128, dtype=jnp.float32))
    assert abs(back[0, 0] - 1e4) / 1e4 < 0.1
    # other group unaffected by the outlier (e4m3 ~6% relative precision)
    np.testing.assert_allclose(back[0, 128:], x[0, 128:], atol=0.25)


def test_zero_input():
    x = jnp.zeros((1, 128))
    q, scale = quantize_fp8(x)
    back = dequantize_fp8(q, scale, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(back), 0.0)


def test_bad_group():
    with pytest.raises(ValueError):
        quantize_fp8(jnp.zeros((2, 100)), group_size=128)
