"""FP8 wire-quantization roundtrip accuracy."""

import jax.numpy as jnp
import numpy as np
import pytest

from uccl_tpu.ops.quant import dequantize_fp8, quantize_fp8


def test_roundtrip_accuracy(rng):
    x = rng.standard_normal((4, 16, 256)).astype(np.float32)
    q, scale = quantize_fp8(jnp.asarray(x), group_size=128)
    assert q.dtype == jnp.float8_e4m3fn
    assert scale.shape == (4, 16, 2)
    back = np.asarray(dequantize_fp8(q, scale, 128, dtype=jnp.float32))
    rel = np.abs(back - x) / (np.abs(x).max() + 1e-9)
    assert rel.max() < 0.05  # e4m3 has ~2 decimal digits


def test_scale_handles_outliers(rng):
    x = rng.standard_normal((2, 256)).astype(np.float32)
    x[0, 0] = 1e4  # huge outlier in group 0
    q, scale = quantize_fp8(jnp.asarray(x), group_size=128)
    back = np.asarray(dequantize_fp8(q, scale, 128, dtype=jnp.float32))
    assert abs(back[0, 0] - 1e4) / 1e4 < 0.1
    # other group unaffected by the outlier (e4m3 ~6% relative precision)
    np.testing.assert_allclose(back[0, 128:], x[0, 128:], atol=0.25)


def test_zero_input():
    x = jnp.zeros((1, 128))
    q, scale = quantize_fp8(x)
    back = dequantize_fp8(q, scale, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(back), 0.0)


def test_bad_group():
    with pytest.raises(ValueError):
        quantize_fp8(jnp.zeros((2, 100)), group_size=128)


class TestWireCompress:
    """Host-side fp8 wire codec (reference: DietGPU compression on the P2P
    path, p2p/rdma/compression.h:46)."""

    def test_roundtrip_f32(self, rng):
        from uccl_tpu.p2p.compress import decode_fp8, encode_fp8

        x = (rng.standard_normal((3, 5, 130)) * 7).astype(np.float32)
        blob = encode_fp8(x)
        y = decode_fp8(blob)
        assert y.dtype == x.dtype and y.shape == x.shape
        rel = np.abs(y - x).max() / np.abs(x).max()
        assert rel < 0.05
        assert blob.nbytes < x.nbytes / 3  # ~3.8x for f32

    def test_roundtrip_bf16(self, rng):
        import ml_dtypes

        from uccl_tpu.p2p.compress import decode_fp8, encode_fp8

        x = (rng.standard_normal(1000) * 3).astype(ml_dtypes.bfloat16)
        y = decode_fp8(encode_fp8(x))
        assert y.dtype == x.dtype and y.shape == x.shape
        xf = x.astype(np.float32)
        rel = np.abs(y.astype(np.float32) - xf).max() / np.abs(xf).max()
        assert rel < 0.07  # fp8 e4m3 step + bf16 rounding

    def test_bound_covers_blob(self, rng):
        from uccl_tpu.p2p.compress import compressed_bound, encode_fp8

        for shape in [(7,), (129,), (4, 4, 4), (1000, 3)]:
            x = rng.standard_normal(shape).astype(np.float32)
            assert encode_fp8(x).nbytes <= compressed_bound(shape, np.float32)

    def test_threshold_policy(self, rng):
        from uccl_tpu.p2p.compress import maybe_compress

        small = rng.standard_normal(8).astype(np.float32)
        out, did = maybe_compress(small)
        assert not did and out is small
        ints = np.arange(1 << 18, dtype=np.int32)
        out, did = maybe_compress(ints)
        assert not did
        big = rng.standard_normal(1 << 18).astype(np.float32)
        out, did = maybe_compress(big)
        assert did and out.dtype == np.uint8

    def test_bad_blob_rejected(self):
        from uccl_tpu.p2p.compress import decode_fp8

        with pytest.raises(ValueError):
            decode_fp8(np.zeros(100, np.uint8))

    def test_channel_write_compressed(self, rng):
        import threading

        from uccl_tpu.p2p import Channel, Endpoint
        from uccl_tpu.p2p.compress import compressed_bound

        with Endpoint(n_engines=2) as server, Endpoint(n_engines=2) as client:
            res = {}
            t = threading.Thread(
                target=lambda: res.setdefault("c", Channel.accept(server))
            )
            t.start()
            chan = Channel.connect(client, "127.0.0.1", server.port, n_paths=2)
            t.join(20)
            src = (rng.standard_normal((64, 256)) * 5).astype(np.float32)
            window = np.zeros(
                compressed_bound(src.shape, src.dtype), np.uint8
            )
            fifo = server.advertise(server.reg(window))
            wire = chan.write_compressed(src, fifo)
            assert wire < src.nbytes / 3
            got = Channel.decode(window)
            rel = np.abs(got - src).max() / np.abs(src).max()
            assert rel < 0.05
