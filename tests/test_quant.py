"""Block-scale wire codec: fp8/int8 round trips, scale guards, and the
legacy-fp8 bit-equality regression (docs/QUANT_WIRE.md)."""

import jax.numpy as jnp
import numpy as np
import pytest

from uccl_tpu.ops.quant import (
    FP8_DTYPE,
    FP8_MAX,
    INT8_MAX,
    adapt_block,
    dequantize_block,
    dequantize_fp8,
    paying_block,
    quantize_block,
    quantize_fp8,
    resolve_wire_dtype,
)


def test_roundtrip_accuracy(rng):
    x = rng.standard_normal((4, 16, 256)).astype(np.float32)
    q, scale = quantize_fp8(jnp.asarray(x), group_size=128)
    assert q.dtype == jnp.float8_e4m3fn
    assert scale.shape == (4, 16, 2)
    back = np.asarray(dequantize_fp8(q, scale, 128, dtype=jnp.float32))
    rel = np.abs(back - x) / (np.abs(x).max() + 1e-9)
    assert rel.max() < 0.05  # e4m3 has ~2 decimal digits


def test_scale_handles_outliers(rng):
    x = rng.standard_normal((2, 256)).astype(np.float32)
    x[0, 0] = 1e4  # huge outlier in group 0
    q, scale = quantize_fp8(jnp.asarray(x), group_size=128)
    back = np.asarray(dequantize_fp8(q, scale, 128, dtype=jnp.float32))
    assert abs(back[0, 0] - 1e4) / 1e4 < 0.1
    # other group unaffected by the outlier (e4m3 ~6% relative precision)
    np.testing.assert_allclose(back[0, 128:], x[0, 128:], atol=0.25)


def test_zero_input():
    x = jnp.zeros((1, 128))
    q, scale = quantize_fp8(x)
    back = dequantize_fp8(q, scale, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(back), 0.0)


def test_bad_group():
    with pytest.raises(ValueError):
        quantize_fp8(jnp.zeros((2, 100)), group_size=128)


class TestBlockCodec:
    """The generic fp8/int8 block-scale codec every wire shares."""

    # fp8: half-ulp at 448 (16) + half an f16 ulp of cast double-rounding
    # (0.125 — XLA:CPU lowers the e4m3 cast through f16); int8: half a
    # step of amax/127. The module-docstring error model, verbatim.
    @pytest.mark.parametrize("wd,qerr", [("fp8", 448 / 16.125),
                                         ("int8", 254.0)])
    @pytest.mark.parametrize("shape,block", [
        ((4, 256), 128),   # dividing
        ((3, 300), 128),   # non-dividing trailing block (pad path)
        ((2, 3, 7), 4),    # small odd dims
        ((1, 5), 128),     # block > dim
    ])
    def test_roundtrip_within_documented_bound(self, rng, wd, qerr, shape,
                                               block):
        """One quantize→dequantize round trip obeys |err| <= amax/QERR per
        block — the documented per-hop unit the wire designs budget in."""
        x = (rng.standard_normal(shape) * 3).astype(np.float32)
        q, scale = quantize_block(jnp.asarray(x), wd, block)
        assert q.shape == x.shape
        assert scale.shape == x.shape[:-1] + (-(-x.shape[-1] // block),)
        back = np.asarray(
            dequantize_block(q, scale, block, dtype=jnp.float32)
        )
        d = x.shape[-1]
        nb = -(-d // block)
        pad = nb * block - d
        xp = np.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
        g = xp.reshape(x.shape[:-1] + (nb, block))
        amax = np.abs(g).max(-1)
        err = np.abs(back - x)
        ep = np.pad(err, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
        per_block = ep.reshape(x.shape[:-1] + (nb, block)).max(-1)
        assert (per_block <= amax / qerr + 1e-7).all()

    def test_int8_payload_contract(self, rng):
        x = rng.standard_normal((4, 128)).astype(np.float32)
        q, scale = quantize_block(jnp.asarray(x), "int8", 64)
        assert q.dtype == jnp.int8
        qn = np.asarray(q)
        assert qn.min() >= -127 and qn.max() <= 127  # symmetric: -128 unused
        # the per-block amax element must land on +/-QMAX exactly
        g = np.asarray(x).reshape(4, 2, 64)
        hit = np.abs(qn.reshape(4, 2, 64))[
            np.abs(g) == np.abs(g).max(-1, keepdims=True)
        ]
        assert (hit == 127).all()

    def test_padding_never_raises_real_scale(self, rng):
        """The zero-padded tail of a non-dividing trailing block cannot
        change the scale of the real data in that block."""
        x = rng.standard_normal((2, 130)).astype(np.float32)
        _, s_padded = quantize_block(jnp.asarray(x), "fp8", 128)
        # trailing block holds 2 real elements; its scale must equal the
        # amax of exactly those two
        want = np.abs(x[:, 128:]).max(-1) / FP8_MAX
        np.testing.assert_allclose(np.asarray(s_padded)[:, 1], want,
                                   rtol=1e-6)

    def test_zero_block_roundtrips_exact(self):
        """Exact-zero blocks take scale 1.0 and round-trip to EXACT zeros
        (the guard satellite: no inf/nan from a zero amax)."""
        x = jnp.zeros((3, 256), jnp.float32)
        for wd in ("fp8", "int8"):
            q, scale = quantize_block(x, wd, 128)
            np.testing.assert_array_equal(np.asarray(scale), 1.0)
            back = np.asarray(dequantize_block(q, scale, 128,
                                               dtype=jnp.float32))
            assert (back == 0.0).all()

    def test_denormal_amax_no_inf(self):
        """A block whose amax is denormal floors the scale at the smallest
        normal f32 — the divide stays finite, nothing becomes inf/nan."""
        tiny = np.float32(1e-42)  # denormal
        x = jnp.full((1, 128), tiny, jnp.float32)
        for wd in ("fp8", "int8"):
            q, scale = quantize_block(x, wd, 128)
            back = np.asarray(dequantize_block(q, scale, 128,
                                               dtype=jnp.float32))
            assert np.isfinite(back).all()

    def test_dequantize_guards_garbage_scales(self):
        """Zero / negative / nan / denormal wire scales dequantize their
        block to exact zeros instead of propagating garbage (regression for
        the zero/denormal-scale guard). A +inf scale is NOT garbage — it is
        the quantizer's poison marker for a non-finite input block and must
        stay loud (non-finite out, never silent zeros)."""
        q = jnp.ones((4, 128), FP8_DTYPE)
        for bad in (0.0, -1.0, np.nan, 1e-42):
            scale = jnp.full((4, 1), bad, jnp.float32)
            back = np.asarray(dequantize_block(q, scale, 128,
                                               dtype=jnp.float32))
            assert (back == 0.0).all(), f"scale {bad} leaked garbage"
        scale = jnp.full((4, 1), np.inf, jnp.float32)
        back = np.asarray(dequantize_block(q, scale, 128, dtype=jnp.float32))
        assert not np.isfinite(back).any(), "+inf poison scale went silent"

    @pytest.mark.parametrize("wd", ["fp8", "int8"])
    @pytest.mark.parametrize("val", [np.inf, -np.inf, np.nan])
    def test_nonfinite_block_stays_loud(self, wd, val):
        """A block holding any inf/nan element round-trips the WHOLE block
        non-finite (poisoned +inf scale) — one shared scale cannot carry
        inf and its finite neighbors, and a silent zero would mask the
        divergence a full-precision wire delivers (int8's nan→0 cast used
        to do exactly that). Neighboring finite blocks are untouched."""
        x = np.ones((2, 256), np.float32)
        x[0, 3] = val  # poisons block 0 of row 0 only
        q, scale = quantize_block(jnp.asarray(x), wd, 128)
        back = np.asarray(dequantize_block(q, scale, 128,
                                           dtype=jnp.float32))
        assert not np.isfinite(back[0, :128]).any(), "divergence masked"
        np.testing.assert_allclose(back[0, 128:], x[0, 128:], rtol=0.05)
        np.testing.assert_allclose(back[1], x[1], rtol=0.05)

    def test_resolve_and_knob_helpers(self):
        assert resolve_wire_dtype(None) is None
        assert resolve_wire_dtype("none") is None
        assert resolve_wire_dtype("fp8") == "fp8"
        assert resolve_wire_dtype("int8") == "int8"
        with pytest.raises(ValueError, match="unknown wire_dtype"):
            resolve_wire_dtype("fp4")
        assert adapt_block(256, 128) == 128
        assert adapt_block(300, 128) == 100  # largest divisor <= 128
        assert paying_block(256, 128) == 128
        assert paying_block(7, 128) is None  # only blocks < 8 divide

    def test_legacy_fp8_bit_equal_to_old_rule(self, rng):
        """The shared codec behind quantize_fp8/dequantize_fp8 must stay
        bit-equal to PR 1's private rule on its original contract —
        dividing group, per-block amax >= 1e-12 (the old rule's scale
        floor; below it the old rule collapsed blocks to q ≈ 0 while the
        codec keeps them representable, so wire bits legitimately differ)
        — the LL wire format cannot drift (dedupe satellite)."""
        x = (rng.standard_normal((4, 16, 256)) * 5).astype(np.float32)
        x[0, 0, :128] = 0.0  # a zero block: outputs must still agree
        x[0, 1, :128] = 1e-12  # the old floor boundary itself
        x[0, 2, :128] = 3.4e38  # near-f32-max amax

        def old_quantize(xv, group):
            *lead, d = xv.shape
            g = xv.reshape(*lead, d // group, group).astype(jnp.float32)
            amax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
            scale = jnp.maximum(amax, 1e-12) / FP8_MAX
            return (g / scale).astype(FP8_DTYPE).reshape(*lead, d), \
                scale[..., 0]

        def old_dequantize(qv, scale, group, dtype):
            *lead, d = qv.shape
            g = qv.reshape(*lead, d // group, group).astype(jnp.float32)
            return (g * scale[..., None]).reshape(*lead, d).astype(dtype)

        xj = jnp.asarray(x)
        q_new, s_new = quantize_fp8(xj, 128)
        q_old, s_old = old_quantize(xj, 128)
        np.testing.assert_array_equal(
            np.asarray(q_new).view(np.uint8), np.asarray(q_old).view(np.uint8)
        )
        back_new = np.asarray(dequantize_fp8(q_new, s_new, 128,
                                             dtype=jnp.float32))
        back_old = np.asarray(old_dequantize(q_old, s_old, 128, jnp.float32))
        np.testing.assert_array_equal(back_new, back_old)


class TestWireCompress:
    """Host-side fp8 wire codec (reference: DietGPU compression on the P2P
    path, p2p/rdma/compression.h:46)."""

    def test_roundtrip_f32(self, rng):
        from uccl_tpu.p2p.compress import decode_fp8, encode_fp8

        x = (rng.standard_normal((3, 5, 130)) * 7).astype(np.float32)
        blob = encode_fp8(x)
        y = decode_fp8(blob)
        assert y.dtype == x.dtype and y.shape == x.shape
        rel = np.abs(y - x).max() / np.abs(x).max()
        assert rel < 0.05
        assert blob.nbytes < x.nbytes / 3  # ~3.8x for f32

    def test_roundtrip_bf16(self, rng):
        import ml_dtypes

        from uccl_tpu.p2p.compress import decode_fp8, encode_fp8

        x = (rng.standard_normal(1000) * 3).astype(ml_dtypes.bfloat16)
        y = decode_fp8(encode_fp8(x))
        assert y.dtype == x.dtype and y.shape == x.shape
        xf = x.astype(np.float32)
        rel = np.abs(y.astype(np.float32) - xf).max() / np.abs(xf).max()
        assert rel < 0.07  # fp8 e4m3 step + bf16 rounding

    def test_bound_covers_blob(self, rng):
        from uccl_tpu.p2p.compress import compressed_bound, encode_fp8

        for shape in [(7,), (129,), (4, 4, 4), (1000, 3)]:
            x = rng.standard_normal(shape).astype(np.float32)
            assert encode_fp8(x).nbytes <= compressed_bound(shape, np.float32)

    def test_threshold_policy(self, rng):
        from uccl_tpu.p2p.compress import maybe_compress

        small = rng.standard_normal(8).astype(np.float32)
        out, did = maybe_compress(small)
        assert not did and out is small
        ints = np.arange(1 << 18, dtype=np.int32)
        out, did = maybe_compress(ints)
        assert not did
        big = rng.standard_normal(1 << 18).astype(np.float32)
        out, did = maybe_compress(big)
        assert did and out.dtype == np.uint8

    def test_bad_blob_rejected(self):
        from uccl_tpu.p2p.compress import decode_fp8

        with pytest.raises(ValueError):
            decode_fp8(np.zeros(100, np.uint8))

    def test_channel_write_compressed(self, rng):
        import threading

        from uccl_tpu.p2p import Channel, Endpoint
        from uccl_tpu.p2p.compress import compressed_bound

        with Endpoint(n_engines=2) as server, Endpoint(n_engines=2) as client:
            res = {}
            t = threading.Thread(
                target=lambda: res.setdefault("c", Channel.accept(server))
            )
            t.start()
            chan = Channel.connect(client, "127.0.0.1", server.port, n_paths=2)
            t.join(20)
            src = (rng.standard_normal((64, 256)) * 5).astype(np.float32)
            window = np.zeros(
                compressed_bound(src.shape, src.dtype), np.uint8
            )
            fifo = server.advertise(server.reg(window))
            wire = chan.write_compressed(src, fifo)
            assert wire < src.nbytes / 3
            got = Channel.decode(window)
            rel = np.abs(got - src).max() / np.abs(src).max()
            assert rel < 0.05
