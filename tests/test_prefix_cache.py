"""Prefix-reuse cache + disaggregated serving: trie/eviction properties and
bit-exactness of the resumed-prefill hit path.

Three layers, cheapest first:

* **Trie / wire-format units** (host-only, no jax): chunk-granular
  insert/match/evict, the ≥1-token-must-remain match clamp, LRU order,
  covered-prefix dedup, and the disagg KV wire format's byte-span math
  against a numpy flat-index oracle.
* **Engine properties** (stub backend): a hit resumes the prefill cursor at
  ``matched_len`` after one donor copy; retire parks; admission pressure
  evicts LRU parked donors and NEVER a live request's slot; no leaked
  slots; metric conservation with parked slots outside the live count.
* **Oracle exactness** (real models): cold vs hit outputs bit-equal the
  one-shot ``generate`` oracle on the dense stack (tier-1), and — marked
  slow, like every multi-compile arm — on the EP MoE stack and through the
  full in-process disaggregated pair (chunk-streamed KV over real loopback
  p2p endpoints, prefill fleet + decode fleet, cold and prefix-hit).
"""

import numpy as np
import pytest

from uccl_tpu.serving import (
    PrefixCache, RequestState, ServingEngine, SlotPool,
)
from uccl_tpu.serving.disagg import KVWireFormat


class TestTrie:
    def _prompt(self, *chunks):
        return np.concatenate([np.asarray(c, np.int32) for c in chunks])

    def test_miss_then_hit_at_chunk_granularity(self):
        pool = SlotPool(4)
        pc = PrefixCache(4)
        p = np.arange(12, dtype=np.int32)
        assert pc.match(p) == (0, None)
        slot = pool.admit(0)
        assert pc.park(pool, slot, p)
        # identical first 8 tokens -> depth-2 match; chunk 3 differs
        q = self._prompt(p[:8], [99, 98, 97, 96])
        m, donor = pc.match(q)
        assert (m, donor) == (8, slot)
        # sub-chunk prefix sharing (< 4 tokens) is below granularity
        r = self._prompt(p[:3], [50, 51, 52, 53, 54])
        assert pc.match(r) == (0, None)

    def test_match_leaves_at_least_one_token(self):
        """A fully cached prompt still recomputes its final chunk: the
        first generated token needs the last position's logits."""
        pool = SlotPool(2)
        pc = PrefixCache(4)
        p = np.arange(8, dtype=np.int32)  # exactly 2 chunks
        pc.park(pool, p1 := pool.admit(0), p)
        assert p1 is not None
        m, _ = pc.match(p)  # usable = (8-1)//4 = 1 chunk
        assert m == 4
        longer = self._prompt(p, [7, 7, 7])  # 11 tokens: both chunks usable
        m, _ = pc.match(longer)
        assert m == 8

    def test_short_prompt_never_cached(self):
        pool = SlotPool(2)
        pc = PrefixCache(8)
        slot = pool.admit(0)
        assert not pc.park(pool, slot, np.arange(5, dtype=np.int32))
        assert pc.n_resident == 0 and pool.n_parked == 0

    def test_covered_prefix_not_parked_twice(self):
        pool = SlotPool(4)
        pc = PrefixCache(4)
        p = np.arange(12, dtype=np.int32)
        assert pc.park(pool, pool.admit(0), p)
        # same full-chunk prefix again: parking is refused (slot freed by
        # the caller), residency stays 1
        s2 = pool.admit(1)
        assert not pc.park(pool, s2, p.copy())
        assert pc.n_resident == 1
        # a LONGER prompt extends the path -> parks
        s3 = pool.admit(2)
        assert pc.park(pool, s3, self._prompt(p, [1, 2, 3, 4]))
        assert pc.n_resident == 2

    def test_lru_eviction_order(self):
        pool = SlotPool(4)
        pc = PrefixCache(2)
        pa = np.asarray([1, 1, 2, 2], np.int32)
        pb = np.asarray([3, 3, 4, 4], np.int32)
        sa, sb = pool.admit(0), pool.admit(1)
        pc.park(pool, sa, pa)
        pc.park(pool, sb, pb)
        pc.match(pa)  # refresh A: B becomes LRU
        assert pc.evict_lru(pool) == sb
        assert pool.n_free == 3 and pc.n_resident == 1
        # the evicted prefix is gone from the trie
        assert pc.match(np.asarray([3, 3, 4, 4, 9], np.int32)) == (0, None)
        assert pc.evict_lru(pool) == sa
        assert pc.evict_lru(pool) is None

    def test_deepest_match_wins(self):
        pool = SlotPool(4)
        pc = PrefixCache(2)
        short = np.asarray([5, 6, 7, 8], np.int32)
        long = np.asarray([5, 6, 7, 8, 9, 10], np.int32)
        pc.park(pool, pool.admit(0), short)
        s_long = pool.admit(1)
        pc.park(pool, s_long, long)
        m, donor = pc.match(np.asarray([5, 6, 7, 8, 9, 10, 11], np.int32))
        assert (m, donor) == (6, s_long)

    def test_clear_reclaims_everything(self):
        pool = SlotPool(3)
        pc = PrefixCache(2)
        for i in range(3):
            pc.park(pool, pool.admit(i),
                    np.asarray([i, i, i + 1, i + 1], np.int32))
        assert pool.n_free == 0
        pc.clear(pool)
        assert pool.n_free == 3 and pc.n_resident == 0


class _PoisonedChildren(dict):
    """A children dict that detonates on any whole-dict traversal but
    still answers keyed lookups — removal along a DIFFERENT branch must
    never iterate this one."""

    def _boom(self, *a, **kw):
        raise AssertionError("sibling branch was traversed during _remove")

    __iter__ = keys = values = items = _boom


class TestRemoveByPath:
    def test_remove_walks_only_the_victim_branch(self):
        """The O(depth) eviction contract (ISSUE 17): ``_remove`` follows
        the victim's RECORDED chunk-key path, so a sibling branch — here
        booby-trapped to fail on iteration — is never visited. The pre-17
        implementation pruned the whole trie per eviction and trips this
        immediately."""
        pool = SlotPool(4)
        pc = PrefixCache(4)
        pa = np.asarray([1, 1, 1, 1, 2, 2, 2, 2], np.int32)
        pb = np.asarray([3, 3, 3, 3, 4, 4, 4, 4], np.int32)
        sa, sb = pool.admit(0), pool.admit(1)
        pc.park(pool, sa, pa)
        pc.park(pool, sb, pb)
        node_b = pc._root.children[pb[:4].tobytes()]
        node_b.children = _PoisonedChildren(node_b.children)
        assert pc.evict_lru(pool) == sa  # A out; B's branch untouched
        # B still serves hits (keyed .get() walks are allowed), and its
        # own removal — along ITS path — is equally traversal-free
        m, donor = pc.match(np.concatenate([pb, [9]]).astype(np.int32))
        assert (m, donor) == (8, sb)
        assert pc.evict_lru(pool) == sb
        assert pc.n_resident == 0 and pool.n_free == 4

    def test_deep_shared_prefix_prunes_deepest_first(self):
        """Two residents sharing chunk 1: evicting the deeper one prunes
        only its exclusive tail nodes; the shared node survives for the
        shallower resident."""
        pool = SlotPool(4)
        pc = PrefixCache(2)
        shallow = np.asarray([7, 7, 8, 8], np.int32)
        deep = np.asarray([7, 7, 8, 8, 9, 9], np.int32)
        s1 = pool.admit(0)
        pc.park(pool, s1, shallow)
        s2 = pool.admit(1)
        pc.park(pool, s2, deep)
        pc.match(np.concatenate([deep, [1]]).astype(np.int32))  # s2 hot
        assert pc.evict_lru(pool) == s1
        # the shared [7,7]/[8,8] nodes still resolve for the survivor
        m, donor = pc.match(np.concatenate([deep, [1]]).astype(np.int32))
        assert (m, donor) == (6, s2)

    def test_resident_tokens_gauge_tracks_park_evict_clear(self):
        """prefix_cache_resident_tokens (ISSUE 17): depth x chunk summed
        over device-tier residents, restamped on every park, eviction and
        clear — the cache-pressure axis capacity sweeps read."""
        from uccl_tpu import obs

        g = obs.gauge("prefix_cache_resident_tokens")
        pool = SlotPool(4)
        pc = PrefixCache(4)
        pc.park(pool, pool.admit(0), np.arange(8, dtype=np.int32))
        assert g.get() == 8  # depth 2 x chunk 4
        pc.park(pool, pool.admit(1), np.full(12, 9, np.int32))
        assert g.get() == 20
        pc.evict_lru(pool)
        assert g.get() == 12
        pc.clear(pool)
        assert g.get() == 0


class TestWireFormat:
    def test_spans_match_numpy_flat_offsets(self):
        fmt = KVWireFormat(n_layers=3, n_slots=4, max_seq=16,
                           n_kv_heads=2, head_dim=8)
        pool = np.arange(np.prod(fmt.pool_shape()), dtype=np.float32
                         ).reshape(fmt.pool_shape())
        flat = pool.reshape(-1).view(np.uint8)
        for slot, lo, hi in ((0, 0, 4), (2, 4, 9), (3, 15, 16)):
            spans = fmt.spans(slot, lo, hi)
            assert len(spans) == fmt.n_layers
            for layer, (off, ln) in enumerate(spans):
                want = pool[layer, slot, lo:hi].tobytes()
                assert flat[off:off + ln].tobytes() == want, (slot, lo, hi)

    def test_pool_nbytes_and_meta_roundtrip(self):
        fmt = KVWireFormat(n_layers=2, n_slots=2, max_seq=32,
                           n_kv_heads=2, head_dim=16)
        assert fmt.pool_nbytes() == 2 * 2 * 32 * 2 * 16 * 4
        assert KVWireFormat.from_meta(fmt.to_meta()) == fmt

    def test_bounds_rejected(self):
        fmt = KVWireFormat(n_layers=1, n_slots=2, max_seq=8,
                           n_kv_heads=1, head_dim=4)
        with pytest.raises(ValueError, match="rows"):
            fmt.spans(0, 4, 9)
        with pytest.raises(ValueError, match="slot"):
            fmt.spans(2, 0, 4)


class _CacheStubBackend:
    """Chunk-aware stub recording prefill starts and prefix copies."""

    def __init__(self, n_slots=2, max_seq=64):
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.n_decodes = 0
        self.calls = []

    def prefill(self, tokens, lens, mask, start=None):
        slots = tuple(int(s) for s in np.flatnonzero(mask))
        self.calls.append(
            ("prefill", slots, tuple(int(start[s]) for s in slots))
        )
        return np.full(self.n_slots, 100, np.int32)

    def decode(self, tokens, active):
        self.n_decodes += 1
        self.calls.append(
            ("decode", tuple(int(s) for s in np.flatnonzero(active)))
        )
        return np.full(self.n_slots, self.n_decodes, np.int32)

    def copy_slot_prefix(self, dst, src, n):
        self.calls.append(("copy", dst, src, n))


class TestEngineProperties:
    def test_requires_chunked_prefill(self):
        with pytest.raises(ValueError, match="requires prefill_chunk"):
            ServingEngine(_CacheStubBackend(), prefix_cache=PrefixCache(4))
        with pytest.raises(ValueError, match="must equal prefill_chunk"):
            ServingEngine(_CacheStubBackend(), prefill_chunk=8,
                          prefix_cache=PrefixCache(4))

    def test_hit_resumes_cursor_after_one_copy(self):
        eng = ServingEngine(_CacheStubBackend(n_slots=2), prefill_chunk=4,
                            prefix_cache=PrefixCache(4))
        p0 = np.arange(10, dtype=np.int32)
        eng.submit(p0, max_new_tokens=2)
        eng.drain()
        assert eng.pool.n_parked == 1  # retire parked, not freed
        r1 = eng.submit(np.concatenate([p0[:8], [9, 9, 9]]).astype(np.int32),
                        max_new_tokens=2)
        eng.drain()
        assert r1.cache_hit_len == 8
        copies = [c for c in eng.backend.calls if c[0] == "copy"]
        assert copies == [("copy", 1, 0, 8)]
        # r1's only prefill window starts at the matched boundary
        starts = [c[2] for c in eng.backend.calls if c[0] == "prefill"]
        assert starts[-1] == (8,)
        assert eng.pool.leaked() == 0

    def test_pressure_evicts_lru_donor_never_live(self):
        """2 slots: one parked donor + one live request; a second live
        arrival must evict the PARKED slot, never the live one."""
        eng = ServingEngine(_CacheStubBackend(n_slots=2), prefill_chunk=4,
                            prefix_cache=PrefixCache(4))
        eng.submit(np.arange(8, dtype=np.int32), max_new_tokens=2)
        eng.drain()
        donor = eng.pool.parked_slots()[0]
        live = eng.submit(np.full(20, 7, np.int32), max_new_tokens=4)
        eng.step()  # live mid-prefill (5 chunks), occupies the free slot
        assert live.state is RequestState.PARTIAL_PREFILL
        live_slot = live.slot
        third = eng.submit(np.full(9, 3, np.int32), max_new_tokens=2)
        eng.drain()
        # the donor slot was reclaimed for `third`; the live slot survived
        assert third.slot == donor
        assert live.slot == live_slot
        assert live.n_generated == live.max_new_tokens
        assert eng.pool.leaked() == 0
        assert eng.prefix_cache.n_resident >= 1  # retirees re-parked

    def test_sole_protected_donor_yields_when_nothing_live(self):
        """n_slots=1: the parked donor is the queue-head's match AND the
        only eviction candidate. With no live request to ever free a
        slot, admission must evict it (trading the hit for progress)
        instead of deadlocking drain()."""
        eng = ServingEngine(_CacheStubBackend(n_slots=1), prefill_chunk=4,
                            prefix_cache=PrefixCache(4))
        p = np.arange(8, dtype=np.int32)
        eng.submit(p, max_new_tokens=2)
        eng.drain()
        assert eng.pool.n_parked == 1
        r = eng.submit(p.copy(), max_new_tokens=2)  # would match the donor
        eng.drain()  # must terminate: donor evicted, cold prefill
        assert r.state is RequestState.FINISHED
        assert r.cache_hit_len == 0
        assert eng.pool.leaked() == 0

    def test_conservation_with_parked_slots(self):
        eng = ServingEngine(_CacheStubBackend(n_slots=2), prefill_chunk=4,
                            max_queue=4, prefix_cache=PrefixCache(4))
        for i in range(5):
            eng.submit(np.full(8, i, np.int32), max_new_tokens=2)
        while eng.has_work():
            eng.step()
            s = eng.snapshot()
            assert (s["submitted"]
                    == s["completed"] + s["active"] + s["queued"]
                    + s["rejected"]), s
        assert eng.pool.leaked() == 0

    def test_adopt_decodes_from_imported_state(self):
        """adopt() is the decode-side entry: ACTIVE at once, first token
        pre-seeded, decodes to the budget, conserved in the metrics."""
        eng = ServingEngine(_CacheStubBackend(n_slots=2))
        r = eng.adopt([1, 2, 3], 100, max_new_tokens=3,
                      queue_s=0.001, prefill_s=0.002, transfer_s=0.003)
        assert r.adopted and r.state is RequestState.ACTIVE
        assert r.out_tokens == [100]
        eng.drain()
        assert r.n_generated == 3 and r.finish_reason == "length"
        s = eng.snapshot()
        assert s["adopted"] == 1 and s["completed"] == 1
        assert s["submitted"] == s["completed"]
        assert "p50" in s["disagg_ttft_ms"]
        assert eng.pool.leaked() == 0

    def test_adopt_eos_and_budget_edge(self):
        eng = ServingEngine(_CacheStubBackend(n_slots=1))
        r = eng.adopt([1], 7, max_new_tokens=5, eos_id=7)
        assert r.is_done() and r.finish_reason == "eos"
        r2 = eng.adopt([1], 3, max_new_tokens=1)
        assert r2.is_done() and r2.finish_reason == "length"
        assert eng.pool.leaked() == 0


MAX_SEQ = 32


@pytest.fixture(scope="module")
def dense_setup():
    """Same config family as test_serving so the one-shot oracle programs
    are _GEN_CACHE hits across files; ONE backend per engine role keeps
    compile count at one [n_slots, C] prefill + one decode program."""
    import jax

    from uccl_tpu.models import dense
    from uccl_tpu.serving import DenseBackend

    cfg = dense.DenseConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=8,
        ffn=64,
    )
    params = dense.init_params(jax.random.PRNGKey(0), cfg)
    backend = DenseBackend(params, cfg, n_slots=2, max_seq=MAX_SEQ)
    return cfg, params, backend


def _oracle(params, cfg, req):
    import jax.numpy as jnp

    from uccl_tpu.models.inference import generate

    toks = generate(params, jnp.asarray(req.prompt)[None], cfg,
                    max_new_tokens=req.max_new_tokens, max_seq=MAX_SEQ)
    return np.asarray(toks)[0, : req.n_generated].tolist()


class TestDenseHitExact:
    def test_hit_path_bit_exact_vs_cold(self, dense_setup):
        """THE acceptance property: a prefix-cache hit (copy + resumed
        prefill) emits exactly the cold path's tokens, which in turn equal
        the one-shot oracle. Sequential requests guarantee the donor is
        parked before the sharer arrives."""
        cfg, params, backend = dense_setup
        eng = ServingEngine(backend, prefill_chunk=4,
                            prefix_cache=PrefixCache(4))
        rng = np.random.default_rng(3)
        p0 = rng.integers(0, 64, 12).astype(np.int32)
        sharers = [
            np.concatenate([p0[:8], rng.integers(0, 64, 4).astype(np.int32)]),
            p0.copy(),  # identical prompt: full-chunk re-match
        ]
        cold = eng.submit(p0, max_new_tokens=4)
        eng.drain()
        assert cold.cache_hit_len == 0
        assert cold.out_tokens == _oracle(params, cfg, cold)
        for p in sharers:
            r = eng.submit(p, max_new_tokens=4)
            eng.drain()
            assert r.cache_hit_len == 8, "expected a depth-2 (8-token) hit"
            assert r.out_tokens == _oracle(params, cfg, r), r.rid
        # identical prompt produced the identical continuation
        assert eng.pool.leaked() == 0

    def test_eviction_churn_stays_exact(self, dense_setup):
        """More distinct prompts than slots: donors park and are evicted
        under pressure; every output stays oracle-exact through the
        churn (stale donor KV can never corrupt a hit)."""
        from uccl_tpu import obs

        cfg, params, backend = dense_setup
        eng = ServingEngine(backend, prefill_chunk=4,
                            prefix_cache=PrefixCache(4))
        ev0 = obs.counter("prefix_cache_evictions_total").get()
        rng = np.random.default_rng(4)
        reqs = []
        for _ in range(5):
            reqs.append(eng.submit(rng.integers(0, 64, 12).astype(np.int32),
                                   max_new_tokens=4))
            eng.drain()
        for r in reqs:
            assert r.out_tokens == _oracle(params, cfg, r), r.rid
        # 5 distinct donors through 2 slots: pressure really evicted
        assert obs.counter("prefix_cache_evictions_total").get() - ev0 >= 3
        assert eng.pool.leaked() == 0


@pytest.mark.slow
class TestDisaggPairDense:
    """The full disaggregated pair over real loopback p2p endpoints —
    multi-compile (two engines) + native transfer engine, so slow-marked;
    qa.sh/CI run it unfiltered, and the example covers the two-process
    arrangement."""

    def test_cold_and_hit_streams_exact(self, dense_setup):
        from uccl_tpu.serving import DenseBackend
        from uccl_tpu.serving.disagg import (
            drive_pair, make_local_pair, warm_pair,
        )
        from uccl_tpu import obs

        cfg, params, _ = dense_setup
        pb = DenseBackend(params, cfg, n_slots=2, max_seq=MAX_SEQ)
        db = DenseBackend(params, cfg, n_slots=2, max_seq=MAX_SEQ)
        pe = ServingEngine(pb, prefill_chunk=4, prefix_cache=PrefixCache(4))
        de = ServingEngine(db)
        pw, dw = make_local_pair(pe, de)
        warm_pair(pw, dw, prompt_len=8)

        rng = np.random.default_rng(0)
        p0 = rng.integers(0, 64, 12).astype(np.int32)
        p1 = np.concatenate([p0[:8], rng.integers(0, 64, 4).astype(np.int32)])
        chunks0 = obs.counter("kv_stream_chunks_total").get(role="tx")
        cold, _ = drive_pair(pw, dw, [p0], [0.0], max_new_tokens=4)
        hit, _ = drive_pair(pw, dw, [p1], [0.0], max_new_tokens=4)
        for r in cold + hit:
            assert r.adopted
            assert r.out_tokens == _oracle(params, cfg, r), r.rid
        assert hit[0].cache_hit_len == 8  # reused rows still streamed
        # every KV row crossed the wire both times: the cold prompt as 3
        # C-token slabs, the hit as its copied [0, 8) prefix in ONE slab
        # plus the recomputed final chunk
        tx = obs.counter("kv_stream_chunks_total").get(role="tx") - chunks0
        assert tx == 5, tx
        snap = de.snapshot()
        assert snap["adopted"] == 2
        for key in ("disagg_queue_ms", "disagg_prefill_ms",
                    "disagg_transfer_ms", "disagg_ttft_ms"):
            assert "p50" in snap[key], key
        assert pe.pool.leaked() == 0 and de.pool.leaked() == 0
        pw.close()


@pytest.mark.slow
class TestDisaggFanIn:
    """N x 1 fan-in (ROADMAP: wired-but-untested): TWO PrefillWorkers
    stream interleaved requests into ONE DecodeWorker over separate
    loopback conns. Every adopted request must stay oracle-exact and land
    back on the conn that opened its stream — a cross-conn slot mixup
    would either corrupt outputs (wrong KV under a prompt) or break the
    per-(conn, rid) origin map. The decode engine runs spec_k=2, so the
    adopted continuations also cover the adopt() x speculative-decoding
    composition."""

    def test_two_prefill_workers_one_decoder(self, dense_setup):
        import time as _time

        from uccl_tpu.serving import DenseBackend, NGramDrafter
        from uccl_tpu.serving.disagg import (
            DecodeWorker, add_local_prefill,
        )
        from uccl_tpu.p2p import Endpoint

        cfg, params, _ = dense_setup
        pes = [ServingEngine(DenseBackend(params, cfg, n_slots=2,
                                          max_seq=MAX_SEQ),
                             prefill_chunk=4) for _ in range(2)]
        de = ServingEngine(DenseBackend(params, cfg, n_slots=4,
                                        max_seq=MAX_SEQ),
                           spec_k=2, drafter=NGramDrafter())
        dw = DecodeWorker(de, Endpoint())
        pws = [add_local_prefill(dw, pe) for pe in pes]

        def pump(n_done, done, deadline_s=120.0):
            deadline = _time.monotonic() + deadline_s
            while len(done) < n_done:
                for pw in pws:
                    pw.step()
                done.extend(dw.step())
                if _time.monotonic() > deadline:
                    raise TimeoutError(f"fan-in stalled at {len(done)}")
            return done

        # warm both streams (compiles chunk + verify programs), then zero
        for pw in pws:
            pw.submit(np.zeros(8, np.int32), max_new_tokens=2)
        pump(2, [])
        for eng in pes + [de]:
            eng.reset_metrics()

        rng = np.random.default_rng(11)
        prompts = [rng.integers(0, 64, 6 + i).astype(np.int32)
                   for i in range(6)]
        by_worker = {}  # worker index -> {remote rid -> prompt key}
        done = []
        for i, p in enumerate(prompts):
            w = i % 2  # interleaved across the two prefill fleets
            r = pws[w].submit(p, max_new_tokens=4)
            assert r is not None
            by_worker.setdefault(w, {})[r.rid] = tuple(p.tolist())
            for pw in pws:  # let streams interleave between arrivals
                pw.step()
            done.extend(dw.step())
        pump(6, done)

        # oracle-exact, all adopted, nothing leaked anywhere
        assert len(done) == 6
        for r in done:
            assert r.adopted
            assert r.out_tokens == _oracle(params, cfg, r), r.rid
        for eng in pes + [de]:
            assert eng.pool.leaked() == 0
        assert de.metrics.adopted == 6
        # no cross-conn leaks: each adopted request's origin (conn, rid)
        # must name the worker that actually submitted its prompt, and
        # the two workers' streams must sit on distinct conns
        conn_of_worker = {}
        for r in done:
            conn, remote_rid = dw.origin[r.rid]
            key = tuple(r.prompt.tolist())
            owners = [w for w, rids in by_worker.items()
                      if rids.get(remote_rid) == key]
            assert owners, f"request {r.rid} origin matches no stream"
            w = owners[0]
            assert conn_of_worker.setdefault(w, conn) == conn, (
                "one worker's streams landed on two conns"
            )
        assert len(conn_of_worker) == 2
        assert (conn_of_worker[0] != conn_of_worker[1]), (
            "both workers share a conn — fan-in never exercised"
        )
        # the spec x adopt composition really speculated on adopted work
        assert de.metrics.spec_windows > 0
        # shutdown is per-conn: ONE worker's BYE must not close the
        # decoder while the other conn is still attached
        def poll_until_byes(n, deadline_s=10.0):
            deadline = _time.monotonic() + deadline_s
            while dw._n_byes < n:
                dw.poll()
                if _time.monotonic() > deadline:
                    raise TimeoutError(f"bye {n} never arrived")
        pws[0].close()
        poll_until_byes(1)
        assert not dw.closed, "one BYE closed a 2-conn decoder"
        pws[1].close()
        poll_until_byes(2)
        assert dw.closed
        for pw in pws:
            pw.ep.close()
        dw.ep.close()


@pytest.mark.slow
class TestDisaggBackpressure:
    """Adoption backpressure over a real loopback pair (the PR 8 remnant
    the router consumes): GRANT responses carry the decode side's
    free-slot/queue-depth hints, the prefill worker surfaces them as
    ``adoption_backpressure()``, and the router's signal reader sees a
    saturated decode peer — then everything drains oracle-exact."""

    def test_grant_hints_surface_saturation(self, dense_setup):
        import time as _time

        from uccl_tpu.serving import DenseBackend, replica_signals
        from uccl_tpu.serving.disagg import make_local_pair, warm_pair

        cfg, params, _ = dense_setup
        pb = DenseBackend(params, cfg, n_slots=2, max_seq=MAX_SEQ)
        db = DenseBackend(params, cfg, n_slots=1, max_seq=MAX_SEQ)
        pe = ServingEngine(pb, prefill_chunk=4)
        de = ServingEngine(db)
        pw, dw = make_local_pair(pe, de)
        warm_pair(pw, dw, prompt_len=8)
        assert pw.adoption_backpressure() == 0

        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 64, 8).astype(np.int32)
                   for _ in range(3)]
        for p in prompts:
            assert pw.submit(p, max_new_tokens=4) is not None
        # drive to completion, watching the pressure surfaces as streams
        # contend for the single decode slot (the first GRANT can land
        # before the later BEGIN notifs drain, so saturation shows up on
        # the running maxima, not necessarily the first hint)
        deadline = _time.monotonic() + 120.0
        finished = []
        max_bp = seen_queued = sig_bp = 0
        while len(finished) < 3:
            pw.step()
            finished.extend(dw.step())
            bp = pw.adoption_backpressure()
            if bp > max_bp:
                max_bp = bp
                # the router reads the same number via its signal surface
                sig_bp = replica_signals(pw)["backpressure"]
            if pw.decode_hint is not None:
                # every grant empties the 1-slot pool
                assert pw.decode_hint["free"] == 0
                seen_queued = max(seen_queued, pw.decode_hint["queued"])
            if _time.monotonic() > deadline:
                raise TimeoutError(f"stalled at {len(finished)}/3")
        assert max_bp >= 1, "three streams never pressured one decode slot"
        assert seen_queued >= 1, "no GRANT ever reported a waiting BEGIN"
        assert sig_bp >= 1
        pw.drain()
        assert pw.adoption_backpressure() == 0  # pressure cleared
        for r in finished:
            assert r.adopted
            assert r.out_tokens == _oracle(params, cfg, r), r.rid
        assert pe.pool.leaked() == 0 and de.pool.leaked() == 0
        pw.close()
        pw.ep.close()
        dw.ep.close()


@pytest.mark.slow
class TestMoEHitExact:
    def test_moe_prefix_hit_bit_exact(self, devices):
        """Prefix-cache hits on the EP-sharded MoE stack: the grid-mapped
        copy/import views keep the resumed prefill bit-exact vs the
        world-1 oracle (cold and hit)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from uccl_tpu.models.moe_inference import (
            MoEServeConfig, MoEServer, init_params,
        )
        from uccl_tpu.serving import MoEBackend

        cfg = MoEServeConfig(
            vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
            head_dim=8, moe_experts=8, moe_topk=2, moe_ffn=64,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        srv = MoEServer(cfg, Mesh(np.array(devices[:2]), ("dp",)))
        backend = MoEBackend(srv, srv.shard_params(params), batch_local=1,
                             max_seq=MAX_SEQ)
        eng = ServingEngine(backend, prefill_chunk=3,
                            prefix_cache=PrefixCache(3))
        srv1 = MoEServer(cfg, Mesh(np.array(devices[:1]), ("dp",)))
        p1p = srv1.shard_params(params)
        rng = np.random.default_rng(0)
        p0 = rng.integers(0, 64, 8).astype(np.int32)
        share = np.concatenate([p0[:6], rng.integers(0, 64, 2).astype(np.int32)])
        reqs = []
        for p in (p0, share):
            reqs.append(eng.submit(p, max_new_tokens=4))
            eng.drain()
        assert reqs[1].cache_hit_len == 6
        for r in reqs:
            want = srv1.generate(p1p, jnp.asarray(r.prompt)[None, None],
                                 r.max_new_tokens, MAX_SEQ, impl="ll")
            assert r.out_tokens == np.asarray(want)[0, 0].tolist(), r.rid
        assert eng.pool.leaked() == 0
