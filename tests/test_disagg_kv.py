"""The prefill→decode KV handoff, regression-tested (not just an example).

examples/disagg_kv.py ships a prefill worker's KV cache through the P2P
one-sided write path to a decode worker and asserts the disaggregated
output matches single-worker generation bit-for-bit. Promoting that
assertion here makes the KV-transfer contract a tested invariant: the
script exits non-zero on any token mismatch, so a plain returncode check
carries the exact-match guarantee."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ~20s wall (two fresh jax processes + compiles): marked slow to protect the
# tier-1 suite's global timeout budget. The unfiltered CI pytest job and
# scripts/qa.sh still run it on every change.
@pytest.mark.slow
def test_disagg_kv_exact_match():
    env = dict(os.environ, UCCL_TPU_EXAMPLE_CPU="1", JAX_PLATFORMS="cpu")
    # spawn-safe: the example uses mp.get_context("spawn") internally; run
    # it as a subprocess so the worker re-imports cleanly under pytest
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", "disagg_kv.py"),
         "--cpu", "--new-tokens", "12"],
        capture_output=True, text=True, timeout=420, env=env, cwd=_REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "disaggregated tokens match single-worker generation: True" in r.stdout
