"""The prefill→decode KV handoff, regression-tested (not just an example).

examples/disagg_kv.py runs the chunk-streamed disaggregated serving pair
(PrefillWorker/DecodeWorker over the P2P one-sided write path, with the
prefix-reuse cache) across two real processes and asserts the
disaggregated output matches single-worker generation bit-for-bit — with
at least one prefix-cache hit counted. Promoting that assertion here makes
the KV-transfer contract a tested invariant: the script exits non-zero on
any token mismatch OR a hitless run, so a returncode check carries both
guarantees. The --metrics-out dump is additionally asserted to carry the
disagg telemetry series (p2p bytes per verb, KV stream chunks, prefix
cache hits) — the same series scripts/check_obs.py --disagg validates in
CI."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(extra, timeout=420):
    env = dict(os.environ, UCCL_TPU_EXAMPLE_CPU="1", JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", "disagg_kv.py"),
         "--cpu", "--new-tokens", "12", *extra],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=_REPO,
    )


# ~25s wall (two fresh jax processes + compiles): marked slow to protect the
# tier-1 suite's global timeout budget. The unfiltered CI pytest job and
# scripts/qa.sh still run it on every change.
@pytest.mark.slow
def test_disagg_stream_exact_match_and_metrics(tmp_path):
    metrics = tmp_path / "disagg_metrics.prom"
    r = _run(["--metrics-out", str(metrics)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "disaggregated tokens match single-worker generation: True" in r.stdout
    # the run demonstrably reused cached prefix tokens
    assert "prefix-cache hit" in r.stdout
    text = metrics.read_text()
    for series in ("p2p_bytes_total", "kv_stream_chunks_total",
                   "prefix_cache_hits_total", "prefix_cache_misses_total",
                   "serving_prefill_tokens_total"):
        assert series in text, f"missing {series} in --metrics-out dump"

    def sample(prefix):
        vals = [float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
                if ln.startswith(prefix)]
        assert vals, f"no sample for {prefix}"
        return sum(vals)

    assert sample('p2p_bytes_total{verb="write"}') > 0
    assert sample('kv_stream_chunks_total{role="tx"}') > 0
    assert sample("prefix_cache_hits_total") >= 1


@pytest.mark.slow
def test_disagg_kv_one_shot_exact_match():
    """The original whole-cache advertise→write→notif handoff (kept for
    the compressed/elastic wire demos) still matches the oracle exactly."""
    r = _run(["--one-shot"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "disaggregated tokens match single-worker generation: True" in r.stdout
