"""ResNet family: architecture fidelity and trainability.

The reference's DDP benchmark network (torchvision ResNet-50 in
examples/ddp_train.py / experimental/misc/resnet_ddp*.py); fidelity is
checked by parameter count against the canonical model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from uccl_tpu.models.resnet import (
    ResNetConfig,
    forward,
    init_params,
    loss_fn,
    num_params,
)


class TestArchitecture:
    # The full-width 1000-class param-count builds and the depth-50
    # forward are ~15 s of init on a pinned CPU core; tier-1 sits at its
    # 870 s cap, so they ride the unfiltered qa.sh/CI tiers (depth-18
    # shape coverage stays in tier-1).
    @pytest.mark.slow
    def test_resnet50_param_count(self):
        """25.56M @ 1000 classes — the canonical ResNet-50 size."""
        p, _ = init_params(jax.random.PRNGKey(0), ResNetConfig(depth=50))
        assert abs(num_params(p) / 1e6 - 25.56) < 0.02

    @pytest.mark.slow
    def test_resnet18_param_count(self):
        """11.69M @ 1000 classes — canonical ResNet-18."""
        p, _ = init_params(jax.random.PRNGKey(0), ResNetConfig(depth=18))
        assert abs(num_params(p) / 1e6 - 11.69) < 0.02

    @pytest.mark.parametrize(
        "depth", [18, pytest.param(50, marks=pytest.mark.slow)]
    )
    def test_forward_shapes(self, depth):
        cfg = ResNetConfig(depth=depth, num_classes=10, width=16)
        p, s = init_params(jax.random.PRNGKey(0), cfg)
        x = jnp.ones((2, 32, 32, 3))
        logits, s2 = jax.jit(lambda p, s, x: forward(p, s, x, cfg))(p, s, x)
        assert logits.shape == (2, 10)
        # running stats moved off their init values
        assert float(jnp.abs(s2["bn_stem"]["mean"]).sum()) > 0

    def test_bad_depth_rejected(self):
        with pytest.raises(ValueError, match="depth"):
            ResNetConfig(depth=77)

    def test_eval_uses_running_stats(self):
        cfg = ResNetConfig(depth=18, num_classes=4, width=8)
        p, s = init_params(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, 16, 3))
        # train mode shifts running stats; eval mode must not
        _, s_train = forward(p, s, x, cfg, train=True)
        logits_eval, s_eval = forward(p, s_train, x, cfg, train=False)
        chex_equal = jax.tree.map(
            lambda a, b: bool(jnp.array_equal(a, b)), s_train, s_eval
        )
        assert all(jax.tree.leaves(chex_equal))
        assert bool(jnp.all(jnp.isfinite(logits_eval)))


class TestTraining:
    def test_loss_decreases(self):
        """A learnable synthetic task: labels from the input channel means."""
        cfg = ResNetConfig(depth=18, num_classes=2, width=8)
        p, s = init_params(jax.random.PRNGKey(0), cfg)
        tx = optax.adam(1e-2)
        opt = tx.init(p)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((16, 16, 16, 3)), jnp.float32)
        y = jnp.asarray(
            (np.asarray(x).mean(axis=(1, 2, 3)) > 0).astype(np.int32)
        )

        @jax.jit
        def step(p, s, opt):
            (l, s2), g = jax.value_and_grad(loss_fn, has_aux=True)(
                p, s, x, y, cfg
            )
            u, opt2 = tx.update(g, opt, p)
            return optax.apply_updates(p, u), s2, opt2, l

        losses = []
        for _ in range(12):
            p, s, opt, l = step(p, s, opt)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.6, losses
