"""SACK window state machine: host-only properties (no transport, fast).

The windowed channel sender's whole reliability brain lives in
uccl_tpu/p2p/sack.py as a pure state machine; these tests drive it with a
virtual clock and scripted loss/reorder, pinning the properties the wire
tests then observe end-to-end: cumulative ack monotonicity, SACK bitmap
layout (native udp_send_ack convention), *selective* repeat (retx count ==
lost attempts, never the pending set), dup-ack fast retransmit, RTO
exponential backoff with Karn's rule, window gating, and per-path quality
steering.
"""

import numpy as np
import pytest

from uccl_tpu.p2p.sack import FAST, NEW, RTO, PathQuality, SackTxWindow


def _issue_all(win, now, cwnd=1 << 30):
    """Issue everything sendable; returns [(seq, kind, path)]."""
    out = []
    for seq, kind in win.sendable(now, cwnd):
        path = win.pick_path(seq, kind)
        win.mark_sent(seq, path, kind, now)
        out.append((seq, kind, path))
    return out


class TestPathQuality:
    def test_ack_and_loss_move_score(self):
        pq = PathQuality(2)
        for _ in range(5):
            pq.on_loss(0)
        assert pq.score[0] < 0.3 < pq.score[1]
        for _ in range(20):
            pq.on_ack(0)
        assert pq.score[0] > 0.9

    def test_pick_new_round_robins_healthy_paths(self):
        pq = PathQuality(4)
        picks = []
        for _ in range(8):
            p = pq.pick_new()
            pq.on_sent(p)
            picks.append(p)
        # equal scores -> quality/load balancing degenerates to round-robin
        assert picks[:4] == [0, 1, 2, 3] and sorted(picks[4:]) == [0, 1, 2, 3]

    def test_pick_new_starves_lossy_path(self):
        pq = PathQuality(3)
        for _ in range(6):
            pq.on_loss(1)
        picks = []
        for _ in range(9):
            p = pq.pick_new()
            pq.on_sent(p)
            picks.append(p)
        assert picks.count(1) < picks.count(0)
        assert picks.count(1) < picks.count(2)

    def test_pick_retx_avoids_failed_path(self):
        pq = PathQuality(3)
        for _ in range(50):
            assert pq.pick_retx(avoid=1) != 1
        # single path has no choice
        assert PathQuality(1).pick_retx(avoid=0) == 0

    def test_srtt_ewma(self):
        pq = PathQuality(1)
        pq.on_sent(0)
        pq.on_ack(0, rtt_us=100.0)
        assert pq.srtt_us[0] == 100.0
        for _ in range(50):
            pq.on_sent(0)
            pq.on_ack(0, rtt_us=500.0)
        assert 400 < pq.srtt_us[0] <= 500


class TestWindowBasics:
    def test_in_order_acks_advance_cum_ack(self):
        win = SackTxWindow([100] * 4, n_paths=2)
        _issue_all(win, 0.0)
        for s in range(4):
            win.on_ack(s, now=0.01, path=s % 2, rtt_us=100.0)
            assert win.cum_ack == s + 1
            assert win.sack_bitmap() == 0  # no holes ever
        assert win.done() and win.retx_fast == 0 and win.retx_rto == 0

    def test_sack_bitmap_matches_native_layout(self):
        """bit rel-1 set for acked seq cum_ack+rel (rel>=1) — the layout
        native udp_send_ack puts on the wire."""
        win = SackTxWindow([10] * 8, n_paths=1, dupack_k=100)
        _issue_all(win, 0.0)
        for s in (1, 3, 4, 7):
            win.on_ack(s, now=0.0)
        assert win.cum_ack == 0
        assert win.sack_bitmap() == (
            (1 << 0) | (1 << 2) | (1 << 3) | (1 << 6)
        )
        win.on_ack(0, now=0.0)  # fills the head hole
        assert win.cum_ack == 2
        assert win.sack_bitmap() == ((1 << 0) | (1 << 1) | (1 << 4))

    def test_window_gates_new_chunks_by_bytes(self):
        win = SackTxWindow([100] * 10, n_paths=1)
        sent = _issue_all(win, 0.0, cwnd=350)
        assert len(sent) == 3  # 3x100 <= 350, 4th would exceed
        assert win.inflight_bytes() == 300
        win.on_ack(0, now=0.01)
        sent = _issue_all(win, 0.01, cwnd=350)
        assert [s for s, _, _ in sent] == [3]  # exactly the freed room

    def test_collapsed_window_still_admits_one_chunk(self):
        win = SackTxWindow([1000], n_paths=1)
        assert [s for s, _ in win.sendable(0.0, 1)] == [0]

    def test_duplicate_ack_ignored(self):
        win = SackTxWindow([10, 10], n_paths=1)
        _issue_all(win, 0.0)
        assert win.on_ack(0, now=0.0)
        assert not win.on_ack(0, now=0.0)  # stale duplicate
        assert win.acks == 1


class TestSelectiveRepeat:
    def test_fast_retx_after_k_dupacks(self):
        win = SackTxWindow([10] * 6, n_paths=2, dupack_k=3)
        _issue_all(win, 0.0)
        # chunk 0 lost; later chunks complete out of order around it
        win.on_ack(1, now=0.01)
        win.on_ack(2, now=0.01)
        assert win.sendable(0.02, 1 << 30) == []  # 2 dupacks: not yet
        win.on_ack(3, now=0.02)
        out = win.sendable(0.02, 1 << 30)
        assert out == [(0, FAST)]
        path = win.pick_path(0, FAST)
        win.mark_sent(0, path, FAST, 0.02)
        assert win.retx_fast == 1
        # at most one fast retx per transmission: more dupacks don't re-mark
        win.on_ack(4, now=0.03)
        win.on_ack(5, now=0.03)
        assert win.sendable(0.03, 1 << 30) == []
        win.on_ack(0, now=0.04)
        assert win.done()

    def test_swap_adjacent_reorder_never_fast_retxes(self):
        """Reorder-by-one (the injected stash swap) yields exactly one
        dup-ack per displaced chunk — below k=3, so pure reordering never
        triggers spurious retransmission."""
        win = SackTxWindow([10] * 8, n_paths=1, dupack_k=3)
        _issue_all(win, 0.0)
        order = [1, 0, 3, 2, 5, 4, 7, 6]
        for s in order:
            win.on_ack(s, now=0.01)
            assert win.sendable(0.011, 1 << 30) == []
        assert win.done() and win.retx_fast == 0 and win.retx_rto == 0

    def test_retx_count_equals_lost_attempts(self):
        """THE selectivity property: with chunk i's first d_i attempts
        scripted lost, total retransmissions == sum(d_i) — never the whole
        pending set."""
        rng = np.random.default_rng(7)
        drops = {i: int(d) for i, d in enumerate(rng.integers(0, 3, 20))}
        win = SackTxWindow([64] * 20, n_paths=4, dupack_k=3, max_tx=8,
                           rto_init_s=0.1, rto_min_s=0.05)
        t, rtt = 0.0, 0.001
        pending = []  # (deliver_t, seq, path)
        attempts = {i: 0 for i in drops}
        while not win.done():
            assert t < 60.0, "window failed to converge"
            for seq, kind in win.sendable(t, 1 << 30):
                path = win.pick_path(seq, kind)
                win.mark_sent(seq, path, kind, t)
                attempts[seq] += 1
                if attempts[seq] > drops[seq]:  # this attempt survives
                    pending.append((t + rtt, seq, path))
            t += 0.0005
            due = [p for p in pending if p[0] <= t]
            pending = [p for p in pending if p[0] > t]
            for _, seq, path in due:
                win.on_ack(seq, now=t, path=path, rtt_us=rtt * 1e6)
        lost = sum(drops.values())
        assert win.retx_fast + win.retx_rto == lost
        assert win.stats()["cum_ack"] == 20

    def test_rto_fires_with_exponential_backoff(self):
        win = SackTxWindow([10], n_paths=1, max_tx=4, rto_init_s=0.1,
                           rto_min_s=0.1, rto_max_s=10.0)
        _issue_all(win, 0.0)
        assert win.sendable(0.05, 1 << 30) == []       # < rto
        assert win.sendable(0.11, 1 << 30) == [(0, RTO)]
        win.mark_sent(0, 0, RTO, 0.11)
        assert win.retx_rto == 1
        assert win.sendable(0.11 + 0.15, 1 << 30) == []  # backoff doubled
        assert win.sendable(0.11 + 0.21, 1 << 30) == [(0, RTO)]

    def test_exhausted_after_max_tx(self):
        win = SackTxWindow([10, 10], n_paths=1, max_tx=2, rto_init_s=0.1,
                           rto_min_s=0.1)
        _issue_all(win, 0.0)
        win.on_ack(1, now=0.01)
        _ = win.sendable(0.15, 1 << 30)
        win.mark_sent(0, 0, RTO, 0.15)          # 2nd and final attempt
        assert win.exhausted(0.2) == []          # still in flight
        assert win.exhausted(0.4) == [0]         # due again, no budget
        assert win.sendable(0.4, 1 << 30) == []  # never offered again

    def test_on_error_reissues_without_rto_wait(self):
        win = SackTxWindow([10] * 2, n_paths=2, rto_init_s=5.0,
                           rto_min_s=5.0)
        _issue_all(win, 0.0)
        win.on_error(1, path=1, now=0.001)  # conn died under the attempt
        out = win.sendable(0.002, 1 << 30)
        assert out == [(1, RTO)]
        assert win.pick_path(1, RTO) == 0  # steered off the dead path


class TestRttEstimator:
    def test_jacobson_srtt_and_rto(self):
        win = SackTxWindow([10] * 4, n_paths=1, rto_min_s=0.001)
        _issue_all(win, 0.0)
        for s in range(4):
            win.on_ack(s, now=0.01, rtt_us=1000.0)
        assert win.srtt_us == pytest.approx(1000.0)
        # steady 1ms RTT -> rttvar decays -> rto well under the 2s cap
        assert win.rto_s < 0.01

    def test_karn_rule_skips_retransmitted_samples(self):
        win = SackTxWindow([10, 10], n_paths=1, rto_init_s=0.1,
                           rto_min_s=0.05)
        _issue_all(win, 0.0)
        win.on_ack(1, now=0.01, rtt_us=500.0)
        srtt_before = win.srtt_us
        _ = win.sendable(0.2, 1 << 30)
        win.mark_sent(0, 0, RTO, 0.2)
        # ambiguous sample from a retransmitted chunk: no estimator update
        win.on_ack(0, now=0.25, rtt_us=250000.0)
        assert win.srtt_us == srtt_before

    def test_issue_kinds_label_correctly(self):
        win = SackTxWindow([10] * 5, n_paths=2, dupack_k=2, rto_init_s=0.1,
                           rto_min_s=0.1)
        kinds = [k for _, k in win.sendable(0.0, 1 << 30)]
        assert kinds == [NEW] * 5
