"""Congestion control math, pacing actuation, heartbeat failure detection."""

import time

import numpy as np
import pytest

from uccl_tpu.p2p import Endpoint
from uccl_tpu.p2p.cc import RateController, SwiftCC, TimelyCC
from uccl_tpu.p2p.store import StoreClient, StoreServer
from uccl_tpu.parallel.distributed import Session
from uccl_tpu.parallel.health import HeartbeatMonitor


class TestTimely:
    def test_increases_on_low_rtt(self):
        cc = TimelyCC(rate=100e6)
        for _ in range(10):
            cc.on_rtt(60.0)  # below t_low
        assert cc.rate > 100e6

    def test_decreases_on_high_rtt(self):
        cc = TimelyCC(rate=1e9)
        for _ in range(10):
            cc.on_rtt(10000.0)  # above t_high
        assert cc.rate < 1e9

    def test_gradient_response(self):
        cc = TimelyCC(rate=500e6)
        # rising RTTs in the mid band -> positive gradient -> decrease
        for rtt in np.linspace(200, 2000, 20):
            cc.on_rtt(float(rtt))
        assert cc.rate < 500e6
        # falling RTTs -> negative gradient -> increase
        r = cc.rate
        for rtt in np.linspace(2000, 200, 20):
            cc.on_rtt(float(rtt))
        assert cc.rate > r

    def test_bounds(self):
        cc = TimelyCC(rate=2e6, min_rate=1e6, max_rate=1e9)
        for _ in range(200):
            cc.on_rtt(50000.0)
        assert cc.rate >= cc.min_rate
        for _ in range(2000):
            cc.on_rtt(10.0)
        assert cc.rate <= cc.max_rate


class TestSwift:
    def test_aimd(self):
        cc = SwiftCC(cwnd=1e6)
        for i in range(5):
            cc.on_delay(100.0, now=float(i))
        assert cc.cwnd > 1e6
        w = cc.cwnd
        cc.on_delay(3000.0, now=100.0)
        assert cc.cwnd < w

    def test_rate_conversion(self):
        cc = SwiftCC(cwnd=1e6)
        assert cc.rate_for_rtt(1000.0) == pytest.approx(1e9)


class TestSwiftRateAdapter:
    """The controller adapter that used to live inline in Channel.enable_cc
    — now a tested class beside RateController (cc.py)."""

    def test_tracks_swift_rate(self):
        from uccl_tpu.p2p.cc import SwiftRateAdapter

        s = SwiftCC(cwnd=1e6)
        ad = SwiftRateAdapter(s)
        assert ad.rate == pytest.approx(s.rate_for_rtt(s.target_delay_us))
        r = ad.on_rtt(100.0)  # under target: cwnd grows, rate from THIS rtt
        assert r == ad.rate == pytest.approx(s.rate_for_rtt(100.0))
        assert s.cwnd > 1e6

    def test_duck_types_for_rate_controller(self):
        from uccl_tpu.p2p.cc import SwiftRateAdapter

        ad = SwiftRateAdapter(SwiftCC())
        assert callable(ad.on_rtt) and ad.rate > 0


class TestWindowCCAdapters:
    """Window-bytes CC protocol for the data path (the windowed channel
    sender feeds per-chunk completion RTTs and loss events)."""

    def test_windowed_swift_acks_grow_losses_shrink(self):
        from uccl_tpu.p2p.cc import WindowedSwift

        cc = WindowedSwift(SwiftCC(cwnd=1e6))
        for _ in range(5):
            cc.on_ack(100.0, 64 << 10)  # under target delay
        grown = cc.cwnd_bytes()
        assert grown > 1e6
        cc.on_loss(now=1e9)  # force past the decrease guard
        assert cc.cwnd_bytes() < grown

    def test_windowed_timely_is_rate_times_srtt(self):
        from uccl_tpu.p2p.cc import TimelyCC, WindowedTimely

        cc = WindowedTimely(TimelyCC(rate=100e6))
        cc.on_ack(1000.0, 64 << 10)
        # BDP of the controlled rate at the observed srtt
        expect = cc.timely.rate * cc.srtt_us / 1e6
        assert cc.cwnd_bytes() == pytest.approx(expect, rel=0.01)

    def test_windowed_timely_loss_collapses_window(self):
        from uccl_tpu.p2p.cc import TimelyCC, WindowedTimely

        cc = WindowedTimely(TimelyCC(rate=1e9))
        cc.on_ack(500.0, 64 << 10)
        w = cc.cwnd_bytes()
        for _ in range(10):
            cc.on_loss()  # loss-is-congestion: fed as rtt >> t_high
        assert cc.cwnd_bytes() < w

    def test_factory(self):
        from uccl_tpu.p2p.cc import (WindowedSwift, WindowedTimely,
                                     make_window_cc)

        assert make_window_cc(None) is None
        assert make_window_cc("off") is None
        assert isinstance(make_window_cc("swift"), WindowedSwift)
        assert isinstance(make_window_cc("timely"), WindowedTimely)
        with pytest.raises(ValueError):
            make_window_cc("vegas")


class TestPacing:
    def test_rate_limit_slows_transfers(self, rng):
        """With a 20 MB/s cap, a 4 MB transfer must take >= ~150 ms."""
        with Endpoint() as server, Endpoint() as client:
            conn = client.connect("127.0.0.1", server.port)
            server.accept()
            dst = np.zeros(4 << 20, np.uint8)
            fifo = server.advertise(server.reg(dst))
            src = rng.integers(0, 255, 4 << 20).astype(np.uint8)
            client.write(conn, src, fifo)  # unpaced warmup
            t0 = time.perf_counter()
            client.write(conn, src, fifo)
            unpaced = time.perf_counter() - t0
            client.set_rate_limit(20 << 20)  # 20 MiB/s
            t0 = time.perf_counter()
            client.write(conn, src, fifo)
            paced = time.perf_counter() - t0
            client.set_rate_limit(0)
            assert paced > max(0.15, unpaced * 2), (paced, unpaced)
            np.testing.assert_array_equal(dst, src)

    def test_rate_controller_actuates(self, rng):
        with Endpoint() as server, Endpoint() as client:
            conn = client.connect("127.0.0.1", server.port)
            server.accept()
            dst = np.zeros(64 << 10, np.uint8)
            fifo = server.advertise(server.reg(dst))
            src = rng.integers(0, 255, 64 << 10).astype(np.uint8)
            probe_buf = np.zeros(1, np.uint8)
            probe_fifo = server.advertise(server.reg(probe_buf))
            rc = RateController(client, TimelyCC(rate=50e6), update_every=1)
            rtts = []
            for _ in range(20):
                rtt = rc.probe(conn, probe_fifo)
                assert rtt > 0
                rtts.append(rtt)
            if max(rtts) >= rc.algo.t_low_us:
                pytest.skip("loopback RTT above t_low; host too loaded to assert")
            # loopback probe RTTs are tens of µs (< t_low) -> rate must grow
            assert rc.algo.rate > 50e6
            client.set_rate_limit(0)


class TestHeartbeat:
    def test_detects_silent_peer(self):
        server = StoreServer()
        c0 = StoreClient("127.0.0.1", server.port)
        s0 = Session(rank=0, world=2, store=c0)
        failures = []
        mon = HeartbeatMonitor(
            s0, interval_s=0.1, timeout_s=0.5, on_failure=failures.append
        )
        mon.start()
        time.sleep(1.0)  # rank 1 never posts
        assert mon.suspected() == [1]
        assert failures == [1]
        # rank 1 comes alive -> recovered
        c1 = StoreClient("127.0.0.1", server.port)
        s1 = Session(rank=1, world=2, store=c1)
        m1 = HeartbeatMonitor(s1, interval_s=0.1, timeout_s=0.5)
        m1.start()
        time.sleep(0.6)
        assert mon.suspected() == []
        mon.stop()
        m1.stop()
        c0.close()
        c1.close()
        server.close()


class TestChannelCC:
    """CC wired into the data path: the probe window is provisioned at the
    channel handshake and a background thread drives the pacer (VERDICT
    round 1 #5 — CC must act during real transfers, not on request)."""

    def _chan_pair(self):
        import threading

        from uccl_tpu.p2p.channel import Channel

        server = Endpoint(n_engines=2)
        client = Endpoint(n_engines=2)
        result = {}
        t = threading.Thread(
            target=lambda: result.setdefault("c", Channel.accept(server))
        )
        t.start()
        c_chan = Channel.connect(client, "127.0.0.1", server.port, n_paths=2)
        t.join(timeout=20)
        return server, client, result["c"], c_chan

    def test_probe_window_auto_provisioned(self):
        server, client, s_chan, c_chan = self._chan_pair()
        try:
            assert c_chan._peer_probe_fifo is not None
            assert s_chan._peer_probe_fifo is not None
        finally:
            client.close(); server.close()

    def test_background_cc_reacts_to_loss(self):
        server, client, s_chan, c_chan = self._chan_pair()
        try:
            c_chan.enable_cc("timely", interval_s=0.005, probe_timeout_ms=100)
            deadline = time.time() + 5
            grown = 0.0
            while time.time() < deadline:
                grown = c_chan.cc.algo.rate
                if grown > 100e6:  # rate grew above TimelyCC's initial
                    break
                time.sleep(0.05)
            if grown <= 100e6:
                pytest.skip("loopback too loaded for growth phase")
            # induced loss: every probe frame dropped -> rtt = full timeout
            client.set_drop_rate(1.0)
            deadline = time.time() + 8
            collapsed = grown
            while time.time() < deadline:
                collapsed = c_chan.cc.algo.rate
                if collapsed < grown / 4:
                    break
                time.sleep(0.05)
            client.set_drop_rate(0.0)
            assert collapsed < grown / 4, (grown, collapsed)
        finally:
            c_chan.disable_cc()
            client.close(); server.close()

    def test_swift_adapter(self):
        server, client, s_chan, c_chan = self._chan_pair()
        try:
            c_chan.enable_cc("swift", interval_s=0.005, probe_timeout_ms=100)
            time.sleep(0.5)
            assert c_chan.cc.algo.rate > 0
        finally:
            c_chan.disable_cc()
            client.close(); server.close()

    def test_probe_errors_counted_not_swallowed(self):
        """A failing probe loop must be VISIBLE (log-once + counted on
        p2p_cc_probe_errors_total) and must keep running — the old
        `except Exception: pass` silently killed CC for the channel's
        lifetime on the first transient error."""
        from uccl_tpu.p2p.channel import _CC_PROBE_ERRS

        server, client, s_chan, c_chan = self._chan_pair()
        try:
            c_chan.enable_cc("timely", interval_s=0.003,
                             probe_timeout_ms=50)

            def boom(*a, **k):
                raise RuntimeError("injected probe fault")

            c_chan.cc.probe = boom
            base = _CC_PROBE_ERRS.total()
            deadline = time.time() + 5
            while _CC_PROBE_ERRS.total() < base + 2 and time.time() < deadline:
                time.sleep(0.01)
            assert _CC_PROBE_ERRS.total() >= base + 2, (
                "probe errors not counted"
            )
            # ≥2 counted increments == the loop SURVIVED the first raise
            assert c_chan._cc_thread.is_alive()
            assert any(labels.get("reason") == "RuntimeError"
                       for labels, _ in _CC_PROBE_ERRS.samples())
        finally:
            c_chan.disable_cc()
            client.close(); server.close()


class TestProbeIsolation:
    """CC probes must not ride the control path (VERDICT round-2 weak #7):
    a large in-flight control message on path 0 queues ahead of a same-conn
    probe and inflates its RTT with zero network congestion. Probes ride the
    LAST path instead."""

    def test_probe_conn_is_last_path(self):
        from uccl_tpu.p2p.channel import Channel

        chan = Channel.__new__(Channel)
        chan.conns = [10, 11, 12]
        assert chan.probe_conn == 12
        chan.conns = [10]
        assert chan.probe_conn == 10

    def test_probe_rtt_immune_to_control_hol(self):
        """While a control burst saturates path 0, a probe on the isolated
        path stays fast; the same probe ON path 0 queues behind the burst.
        Relative comparison (isolated < busy/4) keeps the test robust to
        absolute machine speed."""
        import threading
        import time as _time

        import numpy as np

        from uccl_tpu.p2p.cc import RateController, TimelyCC

        server = Endpoint(n_engines=2)
        client = Endpoint(n_engines=2)
        try:
            import threading as _th

            from uccl_tpu.p2p.channel import Channel

            result = {}
            t = _th.Thread(
                target=lambda: result.setdefault("c", Channel.accept(server))
            )
            t.start()
            c_chan = Channel.connect(
                client, "127.0.0.1", server.port, n_paths=2
            )
            t.join(timeout=20)
            s_chan = result["c"]
            assert c_chan.probe_conn != c_chan.conns[0]

            rc = RateController(client, TimelyCC())
            burst = np.zeros(16 << 20, np.uint8)  # 16 MB control messages

            def control_burst():
                for _ in range(4):
                    c_chan.send(burst)  # path 0, FIFO ahead of any probe

            def timed_probe(conn):
                t0 = _time.perf_counter()
                rc.probe(conn, c_chan._peer_probe_fifo, timeout_ms=20000)
                return _time.perf_counter() - t0

            # Timing property on a 1-core shared box: a single scheduling
            # hiccup can inflate the isolated probe, so take the best of a
            # few attempts — the property under test is that isolation is
            # ACHIEVABLE (the isolated path is not FIFO-behind the burst),
            # not that every sample is noise-free.
            def isolated_ok(ti, tb):
                # A valid measurement requires the busy probe to have
                # GENUINELY queued behind the burst (tb past a floor) —
                # otherwise a drained-early burst would let any fast ti
                # pass vacuously, with no HOL present to be immune to.
                # The floor is ms-scale, not the burst's full drain time:
                # a fast loopback partially drains the queue before the
                # busy probe lands, and several ms of queueing is already
                # orders beyond an unblocked probe's RTT.
                return tb > 0.002 and ti < tb / 4

            attempts = []
            for _ in range(6):
                drained = _th.Thread(
                    target=lambda: [s_chan.recv(max_bytes=16 << 20,
                                                timeout_ms=30000)
                                    for _ in range(4)]
                )
                hol = _th.Thread(target=control_burst)
                drained.start(); hol.start()
                # let the burst reach path 0's tx queue, but probe while
                # it is still DRAINING — sleeping longer lets a fast
                # loopback drain the whole burst first, and the busy
                # probe then never queues (tb under the validity floor:
                # every attempt vacuous, the test flakes)
                _time.sleep(0.02)
                t_isolated = timed_probe(c_chan.probe_conn)
                t_busy = timed_probe(c_chan.conns[0])
                hol.join(timeout=120); drained.join(timeout=120)
                attempts.append((t_isolated, t_busy))
                if isolated_ok(t_isolated, t_busy):
                    break
                if hol.is_alive() or drained.is_alive():
                    # a wedged attempt would share s_chan/path-0 with the
                    # next one and corrupt its timings — stop measuring
                    break
            assert any(isolated_ok(ti, tb) for ti, tb in attempts), (
                "no attempt showed isolation: " + "; ".join(
                    f"isolated {ti*1e3:.1f}ms vs busy {tb*1e3:.1f}ms"
                    for ti, tb in attempts
                )
            )
        finally:
            client.close(); server.close()
