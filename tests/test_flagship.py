"""Flagship MoE transformer: sharded (pp/dp/cp/tp + ep) vs dense oracle.

The decisive test battery for the model stack: forward parity, gradient parity
(catches missing psums in shard_map transposes), and training convergence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uccl_tpu.models.flagship import (
    FlagshipConfig,
    init_params,
    forward,
    loss_fn,
    make_train_step,
    reference_forward,
    shard_params,
)
from uccl_tpu.parallel.mesh import MeshConfig, make_mesh
from uccl_tpu.utils import jaxcompat

# The grad paths differentiate shard_mapped programs from outside the
# shard_map; the legacy (0.4.x) experimental shard_map raises a _SpecError
# on the rank-0 residuals that creates (fixed in modern jax.shard_map).
_needs_modern_vjp = pytest.mark.skipif(
    not jaxcompat.MODERN_SHARD_MAP,
    reason="legacy shard_map vjp mishandles rank-0 residuals",
)


def _cfg(**kw):
    base = dict(
        vocab=64,
        dim=32,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=8,
        moe_experts=4,
        moe_topk=2,
        moe_ffn=32,
        capacity_factor=2.0,  # = E/k -> capacity == all local tokens, no drops
        n_microbatches=2,
        aux_loss_weight=0.0,
        z_loss_weight=0.0,
    )
    base.update(kw)
    return FlagshipConfig(**base)


MESHES = {
    "pp2_dp2_tp2": MeshConfig(pp=2, dp=2, cp=1, tp=2),
    "dp2_cp2_tp2": MeshConfig(pp=1, dp=2, cp=2, tp=2),
    "pp2_cp2_tp2": MeshConfig(pp=2, dp=1, cp=2, tp=2),
}


@pytest.fixture(params=list(MESHES))
def mesh_cfg(request, devices):
    return make_mesh(MESHES[request.param], devices), MESHES[request.param]


def _data(rng, cfg, batch=4, seq=16):
    tokens = rng.integers(0, cfg.vocab, (batch, seq)).astype(np.int32)
    targets = rng.integers(0, cfg.vocab, (batch, seq)).astype(np.int32)
    return jnp.asarray(tokens), jnp.asarray(targets)


class TestForwardParity:
    def test_matches_reference(self, mesh_cfg, rng):
        mesh, mc = mesh_cfg
        cfg = _cfg()
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens, _ = _data(rng, cfg)
        want = np.asarray(reference_forward(params, tokens, cfg))
        gp = shard_params(params, mesh, cfg)
        got = np.asarray(jax.jit(
            lambda p, t: forward(p, t, cfg, mesh)
        )(gp, tokens))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_flash_attention_path(self, devices, rng):
        """attn_impl='flash' (pallas kernel, interpret mode on CPU) must match
        the XLA attention path when cp == 1."""
        mesh = make_mesh(MeshConfig(pp=2, dp=2, cp=1, tp=2), devices)
        params = init_params(jax.random.PRNGKey(0), _cfg())
        tokens, _ = _data(rng, _cfg())
        outs = {}
        for impl in ("xla", "flash"):
            cfg = _cfg(attn_impl=impl)
            gp = shard_params(params, mesh, cfg)
            outs[impl] = np.asarray(
                jax.jit(lambda p, t, c=cfg: forward(p, t, c, mesh))(gp, tokens)
            )
        np.testing.assert_allclose(outs["flash"], outs["xla"], rtol=2e-3, atol=2e-3)

    def test_ll_moe_path(self, devices, rng):
        """moe_impl='ll' (packed grouped-GEMM path, no padded FLOPs) matches
        the dense oracle at drop-free settings."""
        mesh = make_mesh(MeshConfig(pp=1, dp=2, cp=2, tp=2), devices)
        cfg = _cfg(moe_impl="ll")
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens, _ = _data(rng, cfg)
        want = np.asarray(reference_forward(params, tokens, cfg))
        got = np.asarray(
            jax.jit(lambda p, t: forward(p, t, cfg, mesh))(
                shard_params(params, mesh, cfg), tokens
            )
        )
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_ulysses_mode(self, devices, rng):
        mesh = make_mesh(MeshConfig(pp=1, dp=2, cp=2, tp=2), devices)
        cfg = _cfg(seq_mode="ulysses")
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens, _ = _data(rng, cfg)
        want = np.asarray(reference_forward(params, tokens, cfg))
        got = np.asarray(
            jax.jit(lambda p, t: forward(p, t, cfg, mesh))(
                shard_params(params, mesh, cfg), tokens
            )
        )
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@_needs_modern_vjp
class TestGradParity:
    def test_grads_match_dense(self, mesh_cfg, rng):
        """Gradients through the fully sharded model == dense autodiff."""
        mesh, mc = mesh_cfg
        cfg = _cfg()
        params = init_params(jax.random.PRNGKey(1), cfg)
        tokens, targets = _data(rng, cfg)

        def dense_loss(p):
            logits = reference_forward(p, tokens, cfg)
            lse = jax.nn.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
            return jnp.mean(lse - tgt)

        def sharded_loss(p):
            total, ce = loss_fn(p, tokens, targets, cfg, mesh)
            return total

        g_dense = jax.jit(jax.grad(dense_loss))(params)
        gp = shard_params(params, mesh, cfg)
        g_shard = jax.jit(jax.grad(sharded_loss))(gp)
        flat_d, _ = jax.tree.flatten(g_dense)
        flat_s, _ = jax.tree.flatten(g_shard)
        for a, b in zip(flat_d, flat_s):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=5e-3, atol=1e-4
            )


@_needs_modern_vjp
class TestManualSchedule:
    """pp_schedule='1f1b': the manual pipeline training path must reproduce
    the autodiff-GPipe path's loss and gradients on the full MoE model."""

    @pytest.mark.parametrize(
        "mc,kw",
        [
            (MeshConfig(pp=2, dp=2, cp=1, tp=2), {}),
            (MeshConfig(pp=2, dp=2, cp=1, tp=2), {"attn_impl": "flash"}),
            (MeshConfig(pp=2, dp=2, cp=1, tp=2), {"moe_impl": "dense"}),
            (MeshConfig(pp=2, dp=2, cp=1, tp=2), {"moe_impl": "ll"}),
            (MeshConfig(pp=4, dp=2, cp=1, tp=1), {"n_layers": 4}),
            (MeshConfig(pp=2, dp=1, cp=2, tp=2), {}),
            (MeshConfig(pp=2, dp=2, cp=2, tp=1), {"seq_mode": "ulysses"}),
        ],
        ids=[
            "pp2_dp2_tp2", "flash", "dense_moe", "ll_moe", "pp4_dp2",
            "pp2_cp2_tp2", "pp2_dp2_cp2_ulysses",
        ],
    )
    def test_matches_gpipe_grads(self, devices, rng, mc, kw):
        from uccl_tpu.models.flagship import manual_loss_and_grads

        mesh = make_mesh(mc, devices)
        cfg = _cfg(aux_loss_weight=0.01, z_loss_weight=1e-3, **kw)
        params = init_params(jax.random.PRNGKey(4), cfg)
        tokens, targets = _data(rng, cfg)
        gp = shard_params(params, mesh, cfg)

        def gpipe_total(p):
            return loss_fn(p, tokens, targets, cfg, mesh)[0]

        want_total, want_g = jax.jit(jax.value_and_grad(gpipe_total))(gp)

        got_total, got_ce, got_g = jax.jit(
            lambda p: manual_loss_and_grads(p, tokens, targets, cfg, mesh)
        )(gp)

        np.testing.assert_allclose(
            float(got_total), float(want_total), rtol=1e-5
        )
        flat_w, tdef = jax.tree.flatten_with_path(want_g)
        flat_g, _ = jax.tree.flatten_with_path(got_g)
        for (pw, a), (pg, b) in zip(flat_w, flat_g):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=5e-3, atol=1e-5,
                err_msg=str(pw),
            )

    def test_trains(self, devices, rng):
        mesh = make_mesh(MeshConfig(pp=2, dp=2, cp=1, tp=2), devices)
        cfg = _cfg(pp_schedule="1f1b", aux_loss_weight=0.01, z_loss_weight=1e-3)
        params = shard_params(init_params(jax.random.PRNGKey(5), cfg), mesh, cfg)
        tokens, targets = _data(rng, cfg)
        train_step, init_opt = make_train_step(cfg, mesh, learning_rate=1e-2)
        opt_state = init_opt(params)
        step = jax.jit(train_step)
        losses = []
        for _ in range(10):
            params, opt_state, metrics = step(params, opt_state, tokens, targets)
            losses.append(float(metrics["ce"]))
        assert losses[-1] < losses[0] * 0.7, losses


@_needs_modern_vjp
class TestTraining:
    def test_loss_decreases(self, devices, rng):
        mesh = make_mesh(MeshConfig(pp=2, dp=2, cp=1, tp=2), devices)
        cfg = _cfg(aux_loss_weight=0.01, z_loss_weight=1e-3)
        params = shard_params(init_params(jax.random.PRNGKey(2), cfg), mesh, cfg)
        tokens, targets = _data(rng, cfg)
        train_step, init_opt = make_train_step(cfg, mesh, learning_rate=1e-2)
        opt_state = init_opt(params)
        step = jax.jit(train_step)
        losses = []
        for _ in range(10):
            params, opt_state, metrics = step(params, opt_state, tokens, targets)
            losses.append(float(metrics["ce"]))
        assert losses[-1] < losses[0] * 0.7, losses

    def test_aux_loss_positive(self, devices, rng):
        mesh = make_mesh(MeshConfig(pp=1, dp=2, cp=2, tp=2), devices)
        cfg = _cfg(aux_loss_weight=0.01, z_loss_weight=1e-3)
        params = shard_params(init_params(jax.random.PRNGKey(3), cfg), mesh, cfg)
        tokens, targets = _data(rng, cfg)
        total, ce = jax.jit(lambda p: loss_fn(p, tokens, targets, cfg, mesh))(params)
        assert float(total) > float(ce)


@_needs_modern_vjp
class TestRematModes:
    """remat="full"|"dots"|"mlp"|"none" change only the backward recompute
    schedule (_remat_wrap) — training must be bit-identical across them."""

    def test_remat_modes_bit_identical(self, devices, rng):
        mesh = make_mesh(MeshConfig(pp=2, dp=2, cp=1, tp=2), devices)
        tokens = targets = None
        losses = {}
        for mode in ("full", "dots", "mlp", "none"):
            cfg = _cfg(remat=mode, aux_loss_weight=0.01, z_loss_weight=1e-3)
            if tokens is None:
                tokens, targets = _data(rng, cfg)
            params = shard_params(
                init_params(jax.random.PRNGKey(5), cfg), mesh, cfg
            )
            train_step, init_opt = make_train_step(cfg, mesh)
            opt_state = init_opt(params)
            step = jax.jit(train_step)
            for _ in range(3):
                params, opt_state, metrics = step(
                    params, opt_state, tokens, targets
                )
            losses[mode] = float(metrics["loss"])
        assert (
            losses["full"] == losses["dots"] == losses["mlp"]
            == losses["none"]
        ), losses

    def test_unknown_remat_mode_raises(self, devices, rng):
        mesh = make_mesh(MeshConfig(), devices[:1])
        cfg = _cfg(remat="bogus")
        params = shard_params(init_params(jax.random.PRNGKey(5), cfg), mesh, cfg)
        tokens, targets = _data(rng, cfg)
        with pytest.raises(ValueError, match="remat"):
            jax.jit(lambda p: loss_fn(p, tokens, targets, cfg, mesh))(params)
