"""Loadable NCCL-net-shaped plugin ABI: dlopen the .so, drive the vtable.

The reference's collective pillar ships as a loadable NCCL net plugin
(collective/rdma/nccl_plugin.cc, vtable `ncclNetPlugin_v8`); these tests
prove our analog is a real loadable ABI — everything goes through dlopen +
the exported `ucclt_net_v1` struct of C function pointers, no Python
package plumbing involved.
"""

import ctypes
import os

import pytest

from uccl_tpu.p2p.endpoint import net_plugin_path

HANDLE_BYTES = 128
OK, ERR = 0, -1


class Props(ctypes.Structure):
    # Mirrors ucclt_net_props_t: post-v1 additions (addr) are APPENDED so a
    # consumer built against the original v1 prefix still reads its fields.
    _fields_ = [
        ("name", ctypes.c_char * 32),
        ("speed_mbps", ctypes.c_int),
        ("port", ctypes.c_int),
        ("max_comms", ctypes.c_int),
        ("max_recvs", ctypes.c_int),
        ("reg_is_global", ctypes.c_int),
        ("addr", ctypes.c_char * 64),
    ]


_P = ctypes.c_void_p
_PP = ctypes.POINTER(ctypes.c_void_p)


class NetV1(ctypes.Structure):
    _fields_ = [
        ("name", ctypes.c_char_p),
        ("init", ctypes.CFUNCTYPE(ctypes.c_int)),
        ("devices", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.POINTER(ctypes.c_int))),
        ("get_properties",
         ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int, ctypes.POINTER(Props))),
        ("listen", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int, _P, _PP)),
        ("connect", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int, _P, _PP)),
        ("accept", ctypes.CFUNCTYPE(ctypes.c_int, _P, _PP)),
        ("reg_mr", ctypes.CFUNCTYPE(
            ctypes.c_int, _P, _P, ctypes.c_size_t, ctypes.c_int, _PP)),
        ("dereg_mr", ctypes.CFUNCTYPE(ctypes.c_int, _P, _P)),
        ("isend", ctypes.CFUNCTYPE(
            ctypes.c_int, _P, _P, ctypes.c_size_t, ctypes.c_uint64, _P, _PP)),
        ("irecv", ctypes.CFUNCTYPE(
            ctypes.c_int, _P, _P, ctypes.c_size_t, ctypes.c_uint64, _P, _PP)),
        ("test", ctypes.CFUNCTYPE(
            ctypes.c_int, _P, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_size_t))),
        ("iflush", ctypes.CFUNCTYPE(
            ctypes.c_int, _P, _P, ctypes.c_size_t, _P, _PP)),
        ("close_send", ctypes.CFUNCTYPE(ctypes.c_int, _P)),
        ("close_recv", ctypes.CFUNCTYPE(ctypes.c_int, _P)),
        ("close_listen", ctypes.CFUNCTYPE(ctypes.c_int, _P)),
        ("finalize", ctypes.CFUNCTYPE(ctypes.c_int)),
    ]


@pytest.fixture(scope="module")
def net():
    lib = ctypes.CDLL(net_plugin_path())  # the dlopen the ABI exists for
    vt = NetV1.in_dll(lib, "ucclt_net_v1")
    assert vt.init() == OK
    yield vt
    vt.finalize()


def _pair(net, listen_id_reuse=None):
    """listen + connect + accept; returns (send_comm, recv_comm, listen)."""
    handle = ctypes.create_string_buffer(HANDLE_BYTES)
    lc = ctypes.c_void_p()
    assert net.listen(0, handle, ctypes.byref(lc)) == OK
    sc = ctypes.c_void_p()
    assert net.connect(0, handle, ctypes.byref(sc)) == OK
    rc = ctypes.c_void_p()
    assert net.accept(lc, ctypes.byref(rc)) == OK
    return sc, rc, lc


def _wait(net, req, timeout_iters=20000):
    done = ctypes.c_int(0)
    size = ctypes.c_size_t(0)
    for _ in range(timeout_iters):
        rc = net.test(req, ctypes.byref(done), ctypes.byref(size))
        if done.value:
            return rc, size.value
    raise AssertionError("request never completed")


class TestVtable:
    def test_identity_and_props(self, net):
        assert net.name == b"uccl_tpu_dcn"
        n = ctypes.c_int(0)
        assert net.devices(ctypes.byref(n)) == OK and n.value == 1
        props = Props()
        assert net.get_properties(0, ctypes.byref(props)) == OK
        assert props.name == b"uccl_tpu_dcn"
        assert props.port > 0
        assert net.get_properties(3, ctypes.byref(props)) == ERR

    def test_loopback_send_recv(self, net):
        sc, rc, lc = _pair(net)
        payload = os.urandom(100_000)
        sbuf = ctypes.create_string_buffer(payload, len(payload))
        rbuf = ctypes.create_string_buffer(len(payload))
        mh = ctypes.c_void_p()
        assert net.reg_mr(sc, sbuf, len(payload), 0, ctypes.byref(mh)) == OK

        rreq = ctypes.c_void_p()
        assert net.irecv(rc, rbuf, len(payload), 7, None,
                         ctypes.byref(rreq)) == OK
        sreq = ctypes.c_void_p()
        assert net.isend(sc, sbuf, len(payload), 7, mh,
                         ctypes.byref(sreq)) == OK
        rc_s, sz_s = _wait(net, sreq)
        assert rc_s == OK and sz_s == len(payload)
        rc_r, sz_r = _wait(net, rreq)
        assert rc_r == OK and sz_r == len(payload)
        assert rbuf.raw[: len(payload)] == payload

        freq = ctypes.c_void_p()
        assert net.iflush(rc, rbuf, len(payload), None, ctypes.byref(freq)) == OK
        assert _wait(net, freq)[0] == OK
        assert net.dereg_mr(sc, mh) == OK
        assert net.close_send(sc) == OK
        assert net.close_recv(rc) == OK
        assert net.close_listen(lc) == OK

    def test_tag_matching_out_of_order(self, net):
        sc, rc, lc = _pair(net)
        a, b = b"A" * 512, b"B" * 2048
        ra = ctypes.create_string_buffer(len(a))
        rb = ctypes.create_string_buffer(len(b))
        # post recvs for tags 1 and 2, send tag 2 FIRST
        req1, req2 = ctypes.c_void_p(), ctypes.c_void_p()
        assert net.irecv(rc, ra, len(a), 1, None, ctypes.byref(req1)) == OK
        assert net.irecv(rc, rb, len(b), 2, None, ctypes.byref(req2)) == OK
        for tag, data in ((2, b), (1, a)):
            buf = ctypes.create_string_buffer(data, len(data))
            sreq = ctypes.c_void_p()
            assert net.isend(sc, buf, len(data), tag, None,
                             ctypes.byref(sreq)) == OK
            assert _wait(net, sreq)[0] == OK
        assert _wait(net, req2)[1] == len(b)
        assert _wait(net, req1)[1] == len(a)
        assert ra.raw == a and rb.raw == b
        net.close_send(sc)
        net.close_recv(rc)
        net.close_listen(lc)

    def test_oversized_message_fails_recv(self, net):
        sc, rc, lc = _pair(net)
        big = b"x" * 4096
        sbuf = ctypes.create_string_buffer(big, len(big))
        small = ctypes.create_string_buffer(16)
        rreq = ctypes.c_void_p()
        assert net.irecv(rc, small, 16, 5, None, ctypes.byref(rreq)) == OK
        sreq = ctypes.c_void_p()
        assert net.isend(sc, sbuf, len(big), 5, None, ctypes.byref(sreq)) == OK
        assert _wait(net, sreq)[0] == OK
        rc_r, _ = _wait(net, rreq)
        assert rc_r == ERR  # larger than posted -> failed request
        net.close_send(sc)
        net.close_recv(rc)
        net.close_listen(lc)

    def test_concurrent_listens_route_by_handle(self, net):
        """Two outstanding listens; conns land on the right accept queues."""
        h1 = ctypes.create_string_buffer(HANDLE_BYTES)
        h2 = ctypes.create_string_buffer(HANDLE_BYTES)
        l1, l2 = ctypes.c_void_p(), ctypes.c_void_p()
        assert net.listen(0, h1, ctypes.byref(l1)) == OK
        assert net.listen(0, h2, ctypes.byref(l2)) == OK
        # connect to listen 2 first, then 1
        s2, s1 = ctypes.c_void_p(), ctypes.c_void_p()
        assert net.connect(0, h2, ctypes.byref(s2)) == OK
        assert net.connect(0, h1, ctypes.byref(s1)) == OK
        r1, r2 = ctypes.c_void_p(), ctypes.c_void_p()
        assert net.accept(l1, ctypes.byref(r1)) == OK
        assert net.accept(l2, ctypes.byref(r2)) == OK
        # verify channel isolation: message on s1 arrives at r1, not r2
        msg = b"channel-one"
        buf = ctypes.create_string_buffer(msg, len(msg))
        out = ctypes.create_string_buffer(len(msg))
        sreq, rreq = ctypes.c_void_p(), ctypes.c_void_p()
        assert net.irecv(r1, out, len(msg), 0, None, ctypes.byref(rreq)) == OK
        assert net.isend(s1, buf, len(msg), 0, None, ctypes.byref(sreq)) == OK
        assert _wait(net, sreq)[0] == OK
        assert _wait(net, rreq)[1] == len(msg)
        assert out.raw == msg
        for c in (s1, s2):
            net.close_send(c)
        for c in (r1, r2):
            net.close_recv(c)
        for l in (l1, l2):
            net.close_listen(l)

    def test_bad_handle_rejected(self, net):
        bogus = ctypes.create_string_buffer(b"\x00" * HANDLE_BYTES, HANDLE_BYTES)
        sc = ctypes.c_void_p()
        assert net.connect(0, bogus, ctypes.byref(sc)) == ERR

    def test_dead_peer_fails_posted_recv(self, net):
        """A posted irecv whose peer closed must fail via test(), not spin."""
        sc, rc, lc = _pair(net)
        buf = ctypes.create_string_buffer(64)
        rreq = ctypes.c_void_p()
        assert net.irecv(rc, buf, 64, 9, None, ctypes.byref(rreq)) == OK
        assert net.close_send(sc) == OK  # peer goes away, nothing sent
        rc_r, _ = _wait(net, rreq, timeout_iters=200000)
        assert rc_r == ERR
        net.close_recv(rc)
        net.close_listen(lc)


class TestMultiNicDevices:
    """One plugin device per UCCL_TPU_NIC_LIST entry (reference:
    nccl_plugin.cc enumerates one device per NIC). Runs in a subprocess:
    the plugin singleton in THIS process may already be initialized with
    the default single device."""

    def test_enumeration_props_and_cross_device_traffic(self, tmp_path):
        code = r"""
import ctypes, os, sys
sys.path.insert(0, "@TESTDIR@"); sys.path.insert(0, "@REPO@")
from test_net_plugin import NetV1, Props, OK, _wait
so = ctypes.CDLL("@SOPATH@")
net = NetV1.in_dll(so, "ucclt_net_v1")
assert net.init() == OK
n = ctypes.c_int(0)
assert net.devices(ctypes.byref(n)) == OK and n.value == 2, n.value
for dev, ip in ((0, b"127.0.0.41"), (1, b"127.0.0.42")):
    p = Props()
    assert net.get_properties(dev, ctypes.byref(p)) == OK
    assert p.name == b"uccl_tpu_dcn%d" % dev
    assert p.addr == ip
    assert p.port > 0
p0, p1 = Props(), Props()
net.get_properties(0, ctypes.byref(p0)); net.get_properties(1, ctypes.byref(p1))
assert p0.port != p1.port  # distinct endpoints
# listen on dev 1, dial from dev 0: cross-device conn moves real bytes
h = ctypes.create_string_buffer(128)
lc = ctypes.c_void_p()
assert net.listen(1, h, ctypes.byref(lc)) == OK
sc, rc = ctypes.c_void_p(), ctypes.c_void_p()
assert net.connect(0, h, ctypes.byref(sc)) == OK
assert net.accept(lc, ctypes.byref(rc)) == OK
payload = os.urandom(50_000)
sbuf = ctypes.create_string_buffer(payload, len(payload))
rbuf = ctypes.create_string_buffer(len(payload))
req_r = ctypes.c_void_p()
assert net.irecv(rc, rbuf, len(payload), 7, None, ctypes.byref(req_r)) == OK
req_s = ctypes.c_void_p()
assert net.isend(sc, sbuf, len(payload), 7, None, ctypes.byref(req_s)) == OK
_wait(net, req_s)
code_, size = _wait(net, req_r)
assert code_ == OK and size == len(payload)
assert rbuf.raw[:size] == payload
print("MULTI_NIC_OK")
"""
        import subprocess as sp
        import sys as _sys

        script = tmp_path / "multi_nic.py"
        script.write_text(
            code.replace("@TESTDIR@", os.path.dirname(os.path.abspath(__file__)))
            .replace("@REPO@", os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            .replace("@SOPATH@", net_plugin_path())
        )
        env = dict(os.environ, UCCL_TPU_NIC_LIST="127.0.0.41,127.0.0.42")
        r = sp.run(
            [_sys.executable, str(script)], capture_output=True, text=True,
            timeout=120, env=env,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "MULTI_NIC_OK" in r.stdout
