"""Chunk-graph ring collective schedules vs XLA collectives / numpy oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from uccl_tpu.collective import Communicator, plan
from uccl_tpu.parallel.mesh import MeshConfig, make_mesh


@pytest.fixture(scope="module")
def mesh(devices):
    return make_mesh(MeshConfig(dp=8), devices)


def _run(mesh, fn, x, in_spec=P("dp"), out_spec=P("dp")):
    mapped = jax.shard_map(
        fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec, check_vma=False
    )
    return np.asarray(jax.jit(mapped)(x))


class TestPlans:
    def test_plan_shapes(self):
        p = plan.plan_all_reduce(8)
        assert p.n_steps == 14  # 2*(n-1)
        assert p.n_slots == 8
        p.validate()

    def test_bad_direction(self):
        import dataclasses

        p = plan.plan_all_gather(4)
        bad = plan.RingPlan(
            4, 4, tuple(dataclasses.replace(s, dir=2) for s in p.steps)
        )
        with pytest.raises(ValueError):
            bad.validate()


class TestRingAllReduce:
    @pytest.mark.parametrize("bidi", [False, True])
    @pytest.mark.parametrize("payload", [64, 57])  # clean and ragged sizes
    def test_matches_psum(self, mesh, rng, bidi, payload):
        x = rng.standard_normal((8, payload)).astype(np.float32)
        got = _run(
            mesh, lambda v: plan.ring_all_reduce(v, "dp", bidirectional=bidi), x
        )
        want = np.broadcast_to(x.sum(0), x.shape)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_nd_payload(self, mesh, rng):
        x = rng.standard_normal((8, 4, 6)).astype(np.float32)
        got = _run(mesh, lambda v: plan.ring_all_reduce(v, "dp"), x)
        np.testing.assert_allclose(got, np.broadcast_to(x.sum(0), x.shape), rtol=1e-5)


class TestRingReduceScatterGather:
    def test_reduce_scatter(self, mesh, rng):
        x = rng.standard_normal((8, 16)).astype(np.float32)  # 2 elems/slot
        got = _run(mesh, lambda v: plan.ring_reduce_scatter(v.reshape(16), "dp").reshape(1, 2), x,
                   in_spec=P("dp"), out_spec=P("dp"))
        total = x.sum(0).reshape(8, 2)
        np.testing.assert_allclose(got.reshape(8, 2), total, rtol=1e-5)

    def test_all_gather(self, mesh, rng):
        x = rng.standard_normal((8, 3)).astype(np.float32)
        got = _run(
            mesh,
            lambda v: plan.ring_all_gather(v, "dp")[None],
            x,
            in_spec=P("dp"),
            out_spec=P("dp", None),
        )
        for r in range(8):
            np.testing.assert_allclose(got[r].reshape(8, 3), x, rtol=1e-6)

    def test_gather_of_scatter_roundtrip(self, mesh, rng):
        x = rng.standard_normal((8, 8)).astype(np.float32)

        def f(v):
            rs = plan.ring_reduce_scatter(v.reshape(8), "dp")
            return plan.ring_all_gather(rs, "dp")[None]

        got = _run(mesh, f, x, in_spec=P("dp"), out_spec=P("dp", None))
        want = x.sum(0)
        for r in range(8):
            np.testing.assert_allclose(got[r].reshape(8), want, rtol=1e-5)


class TestCommunicatorRing:
    def test_ring_algo_matches_xla(self, mesh, rng):
        comm = Communicator(mesh, "dp")
        x = rng.standard_normal((8, 130)).astype(np.float32)
        gx = comm.device_put(x)
        a = np.asarray(comm.all_reduce(gx))
        b = np.asarray(comm.all_reduce(gx, algo="ring"))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
