"""Chunk-graph ring collective schedules vs XLA collectives / numpy oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from uccl_tpu.collective import Communicator, plan
from uccl_tpu.parallel.mesh import MeshConfig, make_mesh


@pytest.fixture(scope="module")
def mesh(devices):
    return make_mesh(MeshConfig(dp=8), devices)


def _run(mesh, fn, x, in_spec=P("dp"), out_spec=P("dp")):
    mapped = jax.shard_map(
        fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec, check_vma=False
    )
    return np.asarray(jax.jit(mapped)(x))


class TestPlans:
    def test_plan_shapes(self):
        p = plan.plan_all_reduce(8)
        assert p.n_steps == 14  # 2*(n-1)
        assert p.n_slots == 8
        p.validate()

    def test_bad_direction(self):
        import dataclasses

        p = plan.plan_all_gather(4)
        bad = plan.RingPlan(
            4, 4, tuple(dataclasses.replace(s, dir=2) for s in p.steps)
        )
        with pytest.raises(ValueError):
            bad.validate()


class TestRingAllReduce:
    @pytest.mark.parametrize("bidi", [False, True])
    @pytest.mark.parametrize("payload", [64, 57])  # clean and ragged sizes
    def test_matches_psum(self, mesh, rng, bidi, payload):
        x = rng.standard_normal((8, payload)).astype(np.float32)
        got = _run(
            mesh, lambda v: plan.ring_all_reduce(v, "dp", bidirectional=bidi), x
        )
        want = np.broadcast_to(x.sum(0), x.shape)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_nd_payload(self, mesh, rng):
        x = rng.standard_normal((8, 4, 6)).astype(np.float32)
        got = _run(mesh, lambda v: plan.ring_all_reduce(v, "dp"), x)
        np.testing.assert_allclose(got, np.broadcast_to(x.sum(0), x.shape), rtol=1e-5)


class TestRingReduceScatterGather:
    def test_reduce_scatter(self, mesh, rng):
        x = rng.standard_normal((8, 16)).astype(np.float32)  # 2 elems/slot
        got = _run(mesh, lambda v: plan.ring_reduce_scatter(v.reshape(16), "dp").reshape(1, 2), x,
                   in_spec=P("dp"), out_spec=P("dp"))
        total = x.sum(0).reshape(8, 2)
        np.testing.assert_allclose(got.reshape(8, 2), total, rtol=1e-5)

    def test_all_gather(self, mesh, rng):
        x = rng.standard_normal((8, 3)).astype(np.float32)
        got = _run(
            mesh,
            lambda v: plan.ring_all_gather(v, "dp")[None],
            x,
            in_spec=P("dp"),
            out_spec=P("dp", None),
        )
        for r in range(8):
            np.testing.assert_allclose(got[r].reshape(8, 3), x, rtol=1e-6)

    def test_gather_of_scatter_roundtrip(self, mesh, rng):
        x = rng.standard_normal((8, 8)).astype(np.float32)

        def f(v):
            rs = plan.ring_reduce_scatter(v.reshape(8), "dp")
            return plan.ring_all_gather(rs, "dp")[None]

        got = _run(mesh, f, x, in_spec=P("dp"), out_spec=P("dp", None))
        want = x.sum(0)
        for r in range(8):
            np.testing.assert_allclose(got[r].reshape(8), want, rtol=1e-5)


class TestCommunicatorRing:
    def test_ring_algo_matches_xla(self, mesh, rng):
        comm = Communicator(mesh, "dp")
        x = rng.standard_normal((8, 130)).astype(np.float32)
        gx = comm.device_put(x)
        a = np.asarray(comm.all_reduce(gx))
        b = np.asarray(comm.all_reduce(gx, algo="ring"))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


@pytest.fixture(scope="module")
def mesh2d(devices):
    return make_mesh(MeshConfig(dp=2, tp=4), devices)


class TestChunkGraph:
    def test_layers_respect_deps(self):
        g = plan.graph_bidirectional_all_reduce(4, "dp")
        layers = g.layers()
        # two independent chains -> each layer holds one op per stream
        assert all(len(layer) == 2 for layer in layers)
        assert len(layers) == 2 * (4 - 1)
        done = set()
        for layer in layers:
            for op in layer:
                assert all(d in done for d in op.deps)
            done |= {op.id for op in layer}

    def test_cycle_detected(self):
        ops = (
            plan.ChunkOp(0, (1,), 0, 1, 0, -1, False),
            plan.ChunkOp(1, (0,), 0, 1, 0, -1, False),
        )
        g = plan.ChunkGraph(("dp",), (8,), 1, ops)
        with pytest.raises(ValueError, match="cycle"):
            g.layers()

    def test_validation(self):
        bad = plan.ChunkGraph(
            ("dp",), (8,), 1, (plan.ChunkOp(0, (), 3, 1, 0, -1, False),)
        )
        with pytest.raises(ValueError, match="axis"):
            bad.validate()
        bad2 = plan.ChunkGraph(
            ("dp", "tp"), (2, 4), 1,
            (plan.ChunkOp(0, (), 1, 1, 0, -1, False, shard_axis=1),),
        )
        with pytest.raises(ValueError, match="shard"):
            bad2.validate()

    def test_ring_graph_matches_psum(self, mesh, rng):
        x = rng.standard_normal((8, 32)).astype(np.float32)
        g = plan.graph_from_ring(plan.plan_all_reduce(8), "dp")
        got = _run(mesh, lambda v: plan.execute_graph(g, v), x)
        want = np.broadcast_to(x.sum(0, keepdims=True), x.shape)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_bidirectional_graph_matches_psum(self, mesh, rng):
        x = rng.standard_normal((8, 64)).astype(np.float32)
        g = plan.graph_bidirectional_all_reduce(8, "dp")
        got = _run(mesh, lambda v: plan.execute_graph(g, v), x)
        want = np.broadcast_to(x.sum(0, keepdims=True), x.shape)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestTorus2D:
    @pytest.mark.parametrize("payload", [16, 23, 256])
    def test_matches_psum(self, mesh2d, rng, payload):
        x = rng.standard_normal((2, 4, payload)).astype(np.float32)

        def f(v):
            return plan.torus_all_reduce(v[0, 0], ("dp", "tp"))[None, None]

        got = np.asarray(
            jax.jit(
                jax.shard_map(
                    f, mesh=mesh2d, in_specs=(P("dp", "tp"),),
                    out_specs=P("dp", "tp"), check_vma=False,
                )
            )(x)
        )
        want = x.sum(axis=(0, 1))
        for i in range(2):
            for j in range(4):
                np.testing.assert_allclose(got[i, j], want, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("shape", [(1, 8), (8, 1)])
    def test_degenerate_axis_falls_back(self, devices, rng, shape):
        """A 1-sized torus axis routes through the flat ring on the other."""
        a, b = shape
        m = make_mesh(MeshConfig(dp=a, tp=b), devices[: a * b])
        x = rng.standard_normal((a, b, 16)).astype(np.float32)

        def f(v):
            return plan.torus_all_reduce(v[0, 0], ("dp", "tp"))[None, None]

        got = np.asarray(
            jax.jit(
                jax.shard_map(
                    f, mesh=m, in_specs=(P("dp", "tp"),),
                    out_specs=P("dp", "tp"), check_vma=False,
                )
            )(x)
        )
        want = x.sum(axis=(0, 1))
        for i in range(a):
            for j in range(b):
                np.testing.assert_allclose(got[i, j], want, rtol=1e-4, atol=1e-5)


class TestTreeBroadcast:
    @pytest.mark.parametrize("root", [0, 3, 7])
    def test_all_ranks_get_root_value(self, mesh, rng, root):
        x = rng.standard_normal((8, 16)).astype(np.float32)
        got = _run(mesh, lambda v: plan.tree_broadcast(v[0], "dp", root)[None], x,
                   in_spec=P("dp"), out_spec=P("dp"))
        for i in range(8):
            np.testing.assert_array_equal(got[i], x[root])


class TestHalvingDoubling:
    def test_hd_matches_sum(self, mesh, rng):
        # padding path: 130 elems is not a multiple of world 8
        x = rng.standard_normal((8, 130)).astype(np.float32)
        got = _run(mesh, lambda v: plan.hd_all_reduce(v[0], "dp")[None], x,
                   in_spec=P("dp"), out_spec=P("dp"))
        want = x.sum(0)
        for r in range(8):
            np.testing.assert_allclose(got[r], want, rtol=1e-5, atol=1e-5)

    def test_hd_non_power_of_two_falls_back_to_ring(self, devices, rng):
        m = make_mesh(MeshConfig(dp=6), devices[:6])
        comm = Communicator(m, "dp")
        x = rng.standard_normal((6, 33)).astype(np.float32)
        gx = comm.device_put(x)
        np.testing.assert_allclose(
            np.asarray(comm.all_reduce(gx, algo="hd")),
            np.asarray(comm.all_reduce(gx)),
            rtol=1e-5, atol=1e-5,
        )

    def test_hd_small_world_comm(self, devices, rng):
        m = make_mesh(MeshConfig(dp=2, tp=4), devices)
        comm = Communicator(m, "dp")
        x = rng.standard_normal((2, 64)).astype(np.float32)
        gx = comm.device_put(x)
        np.testing.assert_allclose(
            np.asarray(comm.all_reduce(gx, algo="hd")),
            np.asarray(comm.all_reduce(gx)),
            rtol=1e-5, atol=1e-5,
        )

    def test_hd_algo_matches_xla_comm(self, mesh, rng):
        comm = Communicator(mesh, "dp")
        x = rng.standard_normal((8, 257)).astype(np.float32)
        gx = comm.device_put(x)
        np.testing.assert_allclose(
            np.asarray(comm.all_reduce(gx, algo="hd")),
            np.asarray(comm.all_reduce(gx)),
            rtol=1e-5, atol=1e-5,
        )


class TestPlanner:
    """The cost-model CollectivePlanner (round 8): wire-byte-aware
    decisions, obs emission, forced calibration, EP chunk depth."""

    def test_wire_dtype_shifts_hd_threshold(self):
        """Regression (PR 7 satellite): the byte threshold is charged at
        ACTUAL wire bytes, so fp8 pulls a just-over-threshold payload from
        xla/torus back to hd."""
        import jax.numpy as jnp

        pl = plan.get_planner()
        shape = (70000,)  # 280000 B f32 — just over the 262144 B hd cap
        assert pl.plan_all_reduce(shape, jnp.float32, 8).algo == "xla"
        assert pl.plan_all_reduce(
            shape, jnp.float32, 8, wire_dtype="fp8"
        ).algo == "hd"
        assert pl.plan_all_reduce(
            shape, jnp.float32, 8, n_axes=2, worlds=(2, 4)
        ).algo == "torus"
        assert pl.plan_all_reduce(
            shape, jnp.float32, 8, n_axes=2, worlds=(2, 4),
            wire_dtype="fp8"
        ).algo == "hd"

    def test_bidir_wins_large_single_axis_in_budget(self):
        """Eligible + in budget, the counter-rotating pair's halved serial
        byte volume beats hd/xla in the bandwidth range."""
        import jax.numpy as jnp

        pl = plan.get_planner()
        p = pl.plan_all_reduce((16384,), jnp.float32, 8, pallas_ok=True)
        assert p.algo == "bidir" and p.chunks == 2
        # over the interpret budget the quiet probe drops the candidate —
        # auto must not plan a kernel whose first act is a counted downgrade
        p2 = pl.plan_all_reduce((1 << 20,), jnp.float32, 8, pallas_ok=True)
        assert p2.algo == "xla"

    def test_decisions_land_on_obs(self):
        import jax.numpy as jnp
        from uccl_tpu.obs import counters as obsc

        fam = obsc.counter("collective_plan_total")
        before = {tuple(sorted(lb.items())): v for lb, v in fam.samples()}
        p = plan.get_planner().plan_all_reduce((256,), jnp.float32, 8)
        key = (("algo", p.algo), ("chunks", str(p.chunks)),
               ("outcome", "model"), ("wire_dtype", "none"))
        after = {tuple(sorted(lb.items())): v for lb, v in fam.samples()}
        assert after.get(key, 0) == before.get(key, 0) + 1
        g = obsc.gauge("collective_plan_predicted_us")
        assert g.get(algo=p.algo, chunks=str(p.chunks),
                     wire_dtype="none") == pytest.approx(p.predicted_us)

    def test_forced_outcome(self, monkeypatch):
        from uccl_tpu.utils import config as cfg

        import jax.numpy as jnp

        monkeypatch.setenv("UCCL_TPU_AR_ALGO", "ring")
        cfg.reset_all()
        try:
            p = plan.get_planner().plan_all_reduce((1 << 20,), jnp.float32,
                                                   8)
            assert p.algo == "ring" and p.outcome == "forced"
        finally:
            monkeypatch.delenv("UCCL_TPU_AR_ALGO")
            cfg.reset_all()

    def test_ep_auto_depth_scales_with_wire_time(self):
        pl = plan.get_planner()
        m = pl.model
        small = int(8 * m.gamma_us / m.beta_us_per_byte)
        big = int(100 * m.gamma_us / m.beta_us_per_byte)
        assert pl.ep_auto_depth(small, capacity=64) == 2
        assert pl.ep_auto_depth(big, capacity=64) == 4
        assert pl.ep_auto_depth(big, capacity=3) == 3  # capacity-capped

    def test_cost_features_shapes(self):
        hops, vol, launches = plan.cost_features("bidir", 8, 1000)
        assert hops == 14 and launches == 2
        assert vol == pytest.approx(7 / 8 * 1000)
        rh, rvol, rl = plan.cost_features("ring", 8, 1000)
        assert rvol == pytest.approx(2 * vol) and rl == 1
        th, tvol, _ = plan.cost_features("torus", 8, 1000, worlds=(2, 4))
        assert th == 2 * 1 + 2 * 3
        assert tvol == pytest.approx((1.0 + 6 / 8) * 1000)


class TestCalibrate:
    """scripts/plan_calibrate.py recovers model constants from bench JSON
    generated with known constants (pure numpy — no devices)."""

    @staticmethod
    def _calibrate_mod():
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "plan_calibrate.py")
        spec = importlib.util.spec_from_file_location("plan_calibrate", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_fit_recovers_constants(self):
        import json

        pc = self._calibrate_mod()
        model = plan.CostModel(
            alpha_us=3.0, beta_us_per_byte=2e-3, gamma_us=7.0,
            xla_alpha_us=55.0, xla_beta_us_per_byte=1.1e-3, xla_snake=2.0,
        )
        lines = []
        for nbytes in (4096, 65536, 1 << 20):
            arms = [
                {"algo": a, "time_us": model.predict(a, 8, nbytes),
                 "modeled_us": 0.0}
                for a in ("xla", "ring", "hd", "bidir")
            ]
            lines.append(json.dumps({
                "bench": "all_reduce_plan", "bytes": nbytes, "world": 8,
                "n_axes": 1, "mesh2d": None, "arms": arms,
            }))
        rows = pc._rows(lines)
        fitted = pc.fit(rows)
        assert fitted["PLAN_ALPHA_US"] == pytest.approx(3.0, rel=1e-3)
        assert fitted["PLAN_BETA_US_PER_BYTE"] == pytest.approx(2e-3,
                                                               rel=1e-3)
        assert fitted["PLAN_GAMMA_US"] == pytest.approx(7.0, rel=1e-3)
        assert fitted["PLAN_XLA_ALPHA_US"] == pytest.approx(55.0, rel=1e-3)
        assert fitted["PLAN_XLA_BETA_US_PER_BYTE"] == pytest.approx(
            1.1e-3, rel=1e-3)

    def test_no_arms_fails(self):
        pc = self._calibrate_mod()
        assert pc._rows(["not json", '{"bench": "other"}']) == []


class TestSelector:
    def test_small_power_of_two_prefers_hd(self):
        assert plan.select_all_reduce_algo(1024, 8) == "hd"

    def test_large_single_axis_prefers_xla(self):
        assert plan.select_all_reduce_algo(1 << 24, 8) == "xla"

    def test_large_two_axis_prefers_torus(self):
        assert plan.select_all_reduce_algo(1 << 24, 8, n_axes=2) == "torus"

    def test_world_one_is_xla(self):
        assert plan.select_all_reduce_algo(1024, 1) == "xla"

    def test_non_power_of_two_small_is_xla(self):
        assert plan.select_all_reduce_algo(1024, 6) == "xla"

    def test_env_override(self, monkeypatch):
        from uccl_tpu.utils import config as cfg
        monkeypatch.setenv("UCCL_TPU_AR_ALGO", "ring")
        cfg.reset_all()
        try:
            assert plan.select_all_reduce_algo(1 << 24, 8) == "ring"
        finally:
            monkeypatch.delenv("UCCL_TPU_AR_ALGO")
            cfg.reset_all()

    def test_auto_algo_through_communicator(self, mesh, rng):
        comm = Communicator(mesh, "dp")
        x = rng.standard_normal((8, 64)).astype(np.float32)  # small -> hd
        gx = comm.device_put(x)
        np.testing.assert_allclose(
            np.asarray(comm.all_reduce(gx, algo="auto")),
            np.asarray(comm.all_reduce(gx)),
            rtol=1e-5, atol=1e-5,
        )

    def test_auto_non_sum_routes_to_xla(self, mesh, rng):
        from uccl_tpu.collective.communicator import ReduceOp
        comm = Communicator(mesh, "dp")
        x = rng.standard_normal((8, 64)).astype(np.float32)
        gx = comm.device_put(x)
        got = np.asarray(comm.all_reduce(gx, op=ReduceOp.MAX, algo="auto"))
        np.testing.assert_allclose(got, np.tile(x.max(0), (8, 1)), rtol=1e-6)
