"""Dense model family + KV-cache inference correctness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uccl_tpu.models import dense
from uccl_tpu.models.inference import KVCache, decode_step, generate, prefill
from uccl_tpu.parallel.mesh import MeshConfig, make_mesh


def _cfg(**kw):
    base = dict(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=8,
        ffn=64, n_microbatches=2,
    )
    base.update(kw)
    return dense.DenseConfig(**base)


class TestDenseParity:
    @pytest.mark.parametrize(
        "mc",
        [MeshConfig(pp=2, dp=2, cp=1, tp=2), MeshConfig(pp=1, dp=2, cp=2, tp=2)],
        ids=["pp2_dp2_tp2", "dp2_cp2_tp2"],
    )
    def test_forward_matches_reference(self, devices, rng, mc):
        mesh = make_mesh(mc, devices)
        cfg = _cfg()
        params = dense.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (4, 16)), jnp.int32)
        want = np.asarray(dense.reference_forward(params, tokens, cfg))
        got = np.asarray(
            jax.jit(lambda p, t: dense.forward(p, t, cfg, mesh))(
                dense.shard_params(params, mesh, cfg), tokens
            )
        )
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_training_decreases_loss(self, devices, rng):
        mesh = make_mesh(MeshConfig(dp=4, tp=2), devices)
        cfg = _cfg(n_microbatches=1)
        params = dense.shard_params(
            dense.init_params(jax.random.PRNGKey(1), cfg), mesh, cfg
        )
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (4, 16)), jnp.int32)
        targets = jnp.asarray(rng.integers(0, cfg.vocab, (4, 16)), jnp.int32)
        train_step, init_opt = dense.make_train_step(cfg, mesh, 1e-2)
        opt = init_opt(params)
        step = jax.jit(train_step)
        losses = []
        for _ in range(8):
            params, opt, m = step(params, opt, tokens, targets)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] * 0.8, losses


class TestInference:
    def test_prefill_matches_forward(self, rng):
        cfg = _cfg()
        params = dense.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (2, 12)), jnp.int32)
        full_logits = dense.reference_forward(params, tokens, cfg)
        last, cache = prefill(params, tokens, cfg, max_seq=32)
        np.testing.assert_allclose(
            np.asarray(last), np.asarray(full_logits[:, -1]), rtol=1e-4, atol=1e-5
        )
        assert int(cache.length) == 12

    def test_decode_matches_full_recompute(self, rng):
        """Decoding token-by-token with the cache must equal running the whole
        sequence at once — the KV-cache correctness invariant."""
        cfg = _cfg()
        params = dense.init_params(jax.random.PRNGKey(0), cfg)
        seq = jnp.asarray(rng.integers(0, cfg.vocab, (1, 10)), jnp.int32)
        # full forward on 10 tokens
        full = np.asarray(dense.reference_forward(params, seq, cfg))
        # prefill 6, then decode tokens 6..9 one at a time
        last, cache = prefill(params, seq[:, :6], cfg, max_seq=16)
        np.testing.assert_allclose(last, full[:, 5], rtol=1e-4, atol=1e-5)
        for t in range(6, 10):
            logits, cache = decode_step(params, seq[:, t], cache, cfg)
            np.testing.assert_allclose(
                np.asarray(logits), full[:, t], rtol=1e-4, atol=1e-5
            )

    def test_generate_deterministic(self, rng):
        cfg = _cfg()
        params = dense.init_params(jax.random.PRNGKey(0), cfg)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab, (2, 5)), jnp.int32)
        a = np.asarray(generate(params, prompt, cfg, max_new_tokens=8, max_seq=32))
        b = np.asarray(generate(params, prompt, cfg, max_new_tokens=8, max_seq=32))
        assert a.shape == (2, 8)
        np.testing.assert_array_equal(a, b)
