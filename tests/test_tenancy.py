"""Multi-tenant serving plane (ISSUE 18): stochastic sampling, batched
per-slot LoRA adapters, per-tenant fair scheduling.

Layers of test, cheapest first:

* **Sampling math units** (tiny jit): temp-0 rows are exact argmax,
  top-k/top-p restrict the support, lockstep keys are deterministic in
  (seed, position) alone, and the sampled histogram tracks softmax — the
  residual-distribution property behind the rejection-sampling acceptance
  rule (a deterministic drafter's proposal is a point mass, so "accept iff
  draft == the position's lockstep sample" IS exact rejection sampling;
  docs/SERVING.md).
* **AdapterStore units** (host + tiny tables): publish/acquire/release
  refcount discipline, LRU eviction of unpinned rows, counter audit, and
  the weight-push ingest round trip.
* **TenantFairScheduler properties** (host only): DRR interleaving under
  a flooding tenant, the token-bucket ceiling with an injected clock, and
  deficit accumulation for requests costlier than one quantum.
* **Engine exactness** (real models): at equal seeds the engine's sampled
  output is bit-identical to the sampled one-shot ``generate`` oracle —
  mixed greedy/sampled batches with slot reuse, and under chunked prefill
  + speculative decoding; fused batched LoRA matches dense-materialized
  ``W + B@A`` params with mixed ranks and adapter-free slots in one
  batch; the prefix cache never crosses tenant/adapter-version
  namespaces. MoE arms are marked ``slow`` (shard_map compiles).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uccl_tpu.serving import (
    AdapterStore, DenseBackend, MoEBackend, PrefixCache, RequestState,
    SamplingParams, ServingEngine, SlotPool, TenantFairScheduler,
    make_lora, materialize,
)
from uccl_tpu.serving.request import Request

MAX_SEQ = 32


def _prompt(rng, n):
    return rng.integers(0, 64, n).astype(np.int32)


@pytest.fixture(scope="module")
def dense_setup():
    """Params + ONE shared backend per module (the test_serving rule):
    the backend's jit cache makes later compiles cache hits."""
    from uccl_tpu.models import dense

    cfg = dense.DenseConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=8,
        ffn=64,
    )
    params = dense.init_params(jax.random.PRNGKey(0), cfg)
    backend = DenseBackend(params, cfg, n_slots=2, max_seq=MAX_SEQ)
    return cfg, params, backend


def _store_for(cfg, **kw):
    return AdapterStore(
        cfg.n_layers, cfg.dim, cfg.n_heads * cfg.head_dim,
        cfg.n_kv_heads * cfg.head_dim, **kw,
    )


def _lora_for(cfg, rank, seed, scale=0.8):
    # scale 0.8 so the delta CHANGES the argmax: an adapter test whose
    # adapted tokens equal the base tokens proves nothing
    return make_lora(
        jax.random.PRNGKey(seed), cfg.n_layers, cfg.dim,
        cfg.n_heads * cfg.head_dim, cfg.n_kv_heads * cfg.head_dim, rank,
        scale=scale,
    )


class TestSamplingParams:
    def test_validation(self):
        # temp <= 0 is LEGAL (the per-row greedy rule) — only non-finite
        # temperatures are rejected
        assert SamplingParams(temperature=0.0).temperature == 0.0
        assert SamplingParams(temperature=-1.0).temperature == -1.0
        with pytest.raises(ValueError):
            SamplingParams(temperature=float("inf"))
        with pytest.raises(ValueError):
            SamplingParams(temperature=float("nan"))
        with pytest.raises(ValueError):
            SamplingParams(top_p=0.0)
        with pytest.raises(ValueError):
            SamplingParams(top_p=1.5)
        with pytest.raises(ValueError):
            SamplingParams(top_k=-1)
        with pytest.raises(ValueError):
            SamplingParams(seed=2**40)

    def test_slot_stamp_roundtrip(self):
        from uccl_tpu.serving.sampling import slot_arrays, stamp_slot

        arrs = slot_arrays(3)
        stamp_slot(arrs, 1, SamplingParams(temperature=0.7, top_p=0.9,
                                           top_k=5, seed=42))
        assert arrs["temp"][1] == np.float32(0.7)
        assert arrs["seeds"][1] == 42 and arrs["top_k"][1] == 5
        stamp_slot(arrs, 1, None)  # release → greedy defaults
        assert arrs["temp"][1] == 0.0 and arrs["top_p"][1] == 1.0
        assert arrs["temp"][0] == 0.0  # untouched rows stay greedy


class TestSamplingMath:
    def _rows(self, rng, b, v=16):
        return jnp.asarray(rng.normal(size=(b, v)).astype(np.float32))

    def test_temp0_is_exact_argmax(self, rng):
        from uccl_tpu.models.sampling import sample_tokens

        logits = self._rows(rng, 8)
        toks = sample_tokens(
            jnp.arange(8, dtype=jnp.int32), jnp.zeros(8, jnp.int32),
            logits, jnp.zeros(8, jnp.float32), jnp.ones(8, jnp.float32),
            jnp.zeros(8, jnp.int32),
        )
        assert np.array_equal(np.asarray(toks),
                              np.argmax(np.asarray(logits), -1))

    def test_lockstep_key_is_pure_in_seed_and_pos(self, rng):
        from uccl_tpu.models.sampling import sample_tokens

        logits = self._rows(rng, 4)

        def draw(seed, pos):
            return np.asarray(sample_tokens(
                jnp.full(4, seed, jnp.int32), jnp.full(4, pos, jnp.int32),
                logits, jnp.full(4, 1.0, jnp.float32),
                jnp.ones(4, jnp.float32), jnp.zeros(4, jnp.int32),
            ))

        assert np.array_equal(draw(7, 3), draw(7, 3))  # deterministic
        # over several positions, the draws cannot all coincide
        assert any(not np.array_equal(draw(7, 3), draw(7, p))
                   for p in range(4, 12))
        assert any(not np.array_equal(draw(7, 3), draw(s, 3))
                   for s in range(8, 16))

    def test_top_k_restricts_support(self, rng):
        from uccl_tpu.models.sampling import sample_tokens

        b = 64
        logits = jnp.tile(self._rows(rng, 1), (b, 1))
        top2 = set(np.argsort(-np.asarray(logits[0]))[:2].tolist())
        toks = sample_tokens(
            jnp.arange(b, dtype=jnp.int32), jnp.zeros(b, jnp.int32),
            logits, jnp.full(b, 1.5, jnp.float32),
            jnp.ones(b, jnp.float32), jnp.full(b, 2, jnp.int32),
        )
        assert set(np.asarray(toks).tolist()) <= top2
        assert len(set(np.asarray(toks).tolist())) == 2  # both reachable

    def test_top_p_restricts_support(self, rng):
        from uccl_tpu.models.sampling import sample_tokens

        # one dominant token holding > 0.5 of the mass: top_p=0.5 keeps
        # only it (the head always survives), so sampling is deterministic
        v, b = 8, 32
        row = np.zeros(v, np.float32)
        row[3] = 8.0
        logits = jnp.tile(jnp.asarray(row)[None], (b, 1))
        toks = sample_tokens(
            jnp.arange(b, dtype=jnp.int32), jnp.zeros(b, jnp.int32),
            logits, jnp.ones(b, jnp.float32),
            jnp.full(b, 0.5, jnp.float32), jnp.zeros(b, jnp.int32),
        )
        assert np.array_equal(np.asarray(toks), np.full(b, 3))

    def test_top_p_one_is_exact_noop(self):
        """``top_p=1.0`` documents "nucleus disabled" — and must be an
        EXACT no-op. Over a peaked distribution the float32 cumulative
        sum rounds to exactly 1.0 before the tail, so the ``< top_p``
        test alone masks extreme-tail tokens; the disable has to keep
        every token unconditionally."""
        from uccl_tpu.models.sampling import _nucleus_keep

        z = jnp.asarray([40.0] + [-40.0] * 7, jnp.float32)
        # adversarial precondition: the running mass really hits 1.0
        # at the head, so `cum_before < 1.0` is False for every tail token
        head_mass = jnp.sort(jax.nn.softmax(z))[::-1][0]
        assert float(head_mass) == 1.0
        assert bool(jnp.all(_nucleus_keep(z, jnp.float32(1.0))))
        # a real nucleus over the same row still truncates
        assert not bool(jnp.all(_nucleus_keep(z, jnp.float32(0.5))))

    def test_histogram_tracks_softmax(self, rng):
        """The residual-distribution property: across many seeds at one
        position, the empirical distribution of lockstep samples tracks
        softmax(logits/T) — the distribution the spec-decode commit loop
        emits on rejection (the sampled target token IS the residual for
        a point-mass proposal)."""
        from uccl_tpu.models.sampling import sample_tokens

        v, n = 4, 4096
        row = np.array([0.0, 0.5, 1.0, 1.5], np.float32)
        p_want = np.exp(row) / np.exp(row).sum()
        toks = np.asarray(sample_tokens(
            jnp.arange(n, dtype=jnp.int32), jnp.zeros(n, jnp.int32),
            jnp.tile(jnp.asarray(row)[None], (n, 1)),
            jnp.ones(n, jnp.float32), jnp.ones(n, jnp.float32),
            jnp.zeros(n, jnp.int32),
        ))
        p_got = np.bincount(toks, minlength=v) / n
        assert np.abs(p_got - p_want).max() < 0.04  # ~5 sigma at n=4096

    def test_window_matches_per_position_rows(self, rng):
        """sample_window column j ≡ sample_tokens at position pos0+j on
        the same logits row — the identity that makes verify-window
        samples exactly vanilla decode's draws."""
        from uccl_tpu.models.sampling import sample_tokens, sample_window

        b, s, v = 2, 3, 16
        logits = jnp.asarray(rng.normal(size=(b, s, v)).astype(np.float32))
        seeds = jnp.asarray([5, 9], jnp.int32)
        pos0 = jnp.asarray([4, 0], jnp.int32)
        temp = jnp.full(b, 0.8, jnp.float32)
        top_p = jnp.full(b, 0.95, jnp.float32)
        top_k = jnp.full(b, 3, jnp.int32)
        win = np.asarray(sample_window(seeds, pos0, logits, temp, top_p,
                                       top_k))
        for j in range(s):
            col = np.asarray(sample_tokens(
                seeds, pos0 + j, logits[:, j], temp, top_p, top_k
            ))
            assert np.array_equal(win[:, j], col), j


class TestAdapterStore:
    def _cfg(self):
        from uccl_tpu.models import dense

        return dense.DenseConfig(
            vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
            head_dim=8, ffn=64,
        )

    def test_acquire_release_refcount_and_lru(self):
        from uccl_tpu.serving import adapters as mod

        cfg = self._cfg()
        store = _store_for(cfg, max_rank=2, capacity=2)
        h0 = mod._HITS.total()
        m0 = mod._MISSES.total()
        e0 = mod._EVICTIONS.total()
        for t in ("a", "b", "c"):
            store.publish(t, _lora_for(cfg, 2, seed=hash(t) % 97))
        assert store.acquire(None) == 0  # zero-rank fast path, never pinned
        ra = store.acquire("a")          # miss: stage
        rb = store.acquire("b")          # miss: stage (store now full)
        assert ra != rb and 0 not in (ra, rb)
        assert store.acquire("a") == ra  # hit while resident
        with pytest.raises(RuntimeError):
            store.acquire("c")           # both rows pinned
        store.release(ra)
        store.release(ra)                # refcount 0 → evictable
        rc = store.acquire("c")          # LRU-evicts a's row
        assert rc == ra
        with pytest.raises(KeyError):
            store.acquire("nope")        # unpublished
        store.release(rb)
        store.release(rc)
        assert mod._HITS.total() - h0 == 1
        # 4 misses: a, b, the DENIED c attempt (a miss before discovering
        # every row was pinned), then c's successful restage
        assert mod._MISSES.total() - m0 == 4
        assert mod._EVICTIONS.total() - e0 == 1

    def test_device_tables_rank_padding_and_zero_row(self):
        cfg = self._cfg()
        store = _store_for(cfg, max_rank=4, capacity=2)
        tree = _lora_for(cfg, 2, seed=1)  # rank 2 under max_rank 4
        store.publish("acme", tree)
        row = store.acquire("acme")
        tabs = store.device_tables()
        a_q, b_q = tabs["wq"]
        assert a_q.shape == (cfg.n_layers, 3, cfg.dim, 4)
        assert np.all(np.asarray(a_q[:, 0]) == 0.0)  # row 0 = adapter-free
        # staged content: real ranks verbatim, the padding ranks zero
        assert np.array_equal(np.asarray(a_q[:, row, :, :2]),
                              np.asarray(tree["wq"]["a"]))
        assert np.all(np.asarray(a_q[:, row, :, 2:]) == 0.0)
        assert np.array_equal(np.asarray(tabs["wv"][1][:, row, :2]),
                              np.asarray(tree["wv"]["b"]))
        store.release(row)

    def test_publish_refresh_bumps_version_and_restages(self):
        cfg = self._cfg()
        store = _store_for(cfg, max_rank=2, capacity=2)
        v1 = store.publish("acme", _lora_for(cfg, 2, seed=1))
        row = store.acquire("acme")
        t2 = _lora_for(cfg, 2, seed=2)
        v2 = store.publish("acme", t2)  # live refresh, row stays pinned
        assert v2 == v1 + 1 and store.version("acme") == v2
        a_q = store.device_tables()["wq"][0]
        assert np.array_equal(np.asarray(a_q[:, row]),
                              np.asarray(t2["wq"]["a"]))
        store.release(row)

    def test_rank_over_max_rejected(self):
        cfg = self._cfg()
        store = _store_for(cfg, max_rank=2, capacity=2)
        with pytest.raises(ValueError):
            store.publish("big", _lora_for(cfg, 4, seed=1))

    def test_archive_eviction_prunes_pub_seq(self):
        """``max_published`` eviction drops the victim's publish-order
        stamp with it — leaving it would leak one ``_pub_seq`` entry per
        evicted tenant under publish/evict churn."""
        cfg = self._cfg()
        store = _store_for(cfg, max_rank=2, capacity=2, max_published=2)
        for i in range(6):
            store.publish(f"t{i}", _lora_for(cfg, 2, seed=i + 1))
        assert len(store._published) == 2
        assert set(store._pub_seq) == set(store._published)

    def test_can_acquire_and_row_accounting(self):
        """The non-raising admission gate: ``can_acquire`` predicts
        whether ``acquire`` would succeed, and ``n_available_rows`` with
        ``exclude`` models a batch that is about to pin its resident
        adapters (their unpinned rows are not available to stage into)."""
        cfg = self._cfg()
        store = _store_for(cfg, max_rank=2, capacity=2)
        for i, t in enumerate(("a", "b", "c")):
            store.publish(t, _lora_for(cfg, 2, seed=i + 1))
        assert store.can_acquire(None)         # row 0, always
        assert not store.can_acquire("ghost")  # unpublished
        assert store.n_available_rows() == 2
        ra, rb = store.acquire("a"), store.acquire("b")
        assert store.n_available_rows() == 0
        assert store.can_acquire("a")          # resident: refcount hit
        assert not store.can_acquire("c")      # every row pinned
        store.release(ra)
        assert store.n_available_rows() == 1
        assert store.is_resident("a")          # unpinned, still resident
        assert store.n_available_rows(exclude={"a"}) == 0
        assert store.can_acquire("c")          # a's row is evictable
        store.release(rb)

    def test_weight_push_ingest_round_trip(self):
        """The distribution path: adapters travel as versioned
        WeightPublisher snapshots; ``ingest`` maps ``adapter/<tenant>``
        names onto store tenants and pins the snapshot version."""
        from uccl_tpu.p2p.weight_push import WeightPublisher

        cfg = self._cfg()
        tree = _lora_for(cfg, 2, seed=3)
        pub = WeightPublisher()
        pub.publish("adapter/acme", tree)
        pub.publish("adapter/acme", _lora_for(cfg, 2, seed=4))  # v2
        snap = pub.get("adapter/acme")
        store = _store_for(cfg, max_rank=2, capacity=2)
        assert store.ingest(snap) == 2
        assert store.has("acme") and store.version("acme") == 2
        row = store.acquire("acme")
        want = snap.tree()["wq"]["a"]
        got = store.device_tables()["wq"][0][:, row]
        assert np.array_equal(np.asarray(got), np.asarray(want))
        store.release(row)


class TestTenantFairScheduler:
    def _req(self, rid, tenant, cost=8, preemptions=0):
        r = Request(rid=rid, prompt=np.zeros(cost // 2, np.int32),
                    max_new_tokens=cost - cost // 2, tenant=tenant)
        r.preemptions = preemptions
        return r

    def _drain_order(self, sched, pool):
        order = []
        while sched.qsize:
            got = sched.admit(pool)
            if not got:
                break
            for slot, req in got:
                order.append(req.tenant)
                pool.free(slot)
        return order

    def test_drr_interleaves_flooding_tenant(self):
        """Backlog buys nothing: with tenant A 10-deep and tenant B
        2-deep at equal request cost, B's head admits within the first
        round — not after A's flood."""
        sched = TenantFairScheduler(quantum=8)
        for i in range(10):
            sched.submit(self._req(i, "A"))
        for i in range(2):
            sched.submit(self._req(100 + i, "B"))
        order = self._drain_order(sched, SlotPool(1))
        assert order.index("B") <= 1
        assert sorted(order) == ["A"] * 10 + ["B"] * 2

    def test_token_bucket_is_a_hard_ceiling(self):
        """Above its rate a tenant holds in queue even with free slots;
        the bucket refills with (injected) clock time and caps at burst."""
        clk = {"t": 0.0}
        sched = TenantFairScheduler(quantum=100, rate=10.0, burst=10.0,
                                    clock=lambda: clk["t"])
        for i in range(3):
            sched.submit(self._req(i, "A", cost=10))
        pool = SlotPool(2)
        got = sched.admit(pool)
        assert len(got) == 1  # burst covers exactly one request
        pool.free(got[0][0])
        assert sched.admit(pool) == []  # bucket empty, slots free
        clk["t"] = 1.0  # +10 tokens
        got = sched.admit(pool)
        assert len(got) == 1
        pool.free(got[0][0])
        clk["t"] = 100.0  # refill caps at burst → still just one admission
        got = sched.admit(pool)
        assert len(got) == 1

    def test_preempted_request_not_recharged(self):
        clk = {"t": 0.0}
        sched = TenantFairScheduler(quantum=100, rate=10.0, burst=10.0,
                                    clock=lambda: clk["t"])
        sched.submit(self._req(0, "A", cost=10))
        pool = SlotPool(1)
        (slot, req), = sched.admit(pool)
        pool.free(slot)
        req.preemptions = 1
        sched.requeue(req)  # resume path: billed at first admission
        assert len(sched.admit(pool)) == 1  # admits on an empty bucket

    def test_oversized_request_rejected_at_submit(self):
        """A request costlier than ``burst`` could NEVER be admitted (the
        bucket refills only up to burst), so it must fail fast at submit
        — not sit at its tenant's queue head forever, wedging every later
        request behind a charge the bucket cannot cover (livelock)."""
        clk = {"t": 0.0}
        sched = TenantFairScheduler(quantum=100, rate=1.0, burst=4.0,
                                    clock=lambda: clk["t"])
        big = self._req(0, "A", cost=16)
        assert not sched.submit(big)
        assert big.state is RequestState.REJECTED
        assert big.finish_reason == "oversized"
        assert sched.qsize == 0
        # the tenant is NOT wedged: a fitting request still flows, even
        # across unlimited refill time
        sched.submit(self._req(1, "A", cost=4))
        clk["t"] = 1e6
        assert len(sched.admit(SlotPool(1))) == 1
        # cost == burst is admissible; no rate limit admits any cost
        assert TenantFairScheduler(rate=1.0, burst=4.0).submit(
            self._req(2, "A", cost=4))
        assert TenantFairScheduler(quantum=100).submit(
            self._req(3, "A", cost=16))

    def test_deficit_accumulates_across_rounds(self):
        """A request costlier than one quantum admits after enough visits
        — DRR never starves large requests."""
        sched = TenantFairScheduler(quantum=4)
        sched.submit(self._req(0, "A", cost=20))
        sched.submit(self._req(1, "B", cost=4))
        order = self._drain_order(sched, SlotPool(1))
        assert sorted(order) == ["A", "B"]

    def test_fifo_surfaces_route_through_tenant_queues(self):
        sched = TenantFairScheduler()
        reqs = [self._req(i, t) for i, t in enumerate("ABA")]
        for r in reqs:
            sched.submit(r)
        assert sched.qsize == 3
        assert sched.cancel(reqs[1].rid)
        assert sched.qsize == 2
        assert {r.rid for r in sched.take_all()} == {0, 2}

    def test_engine_rejects_tenant_fair_plus_priority_classes(self,
                                                              dense_setup):
        _, _, backend = dense_setup
        with pytest.raises(ValueError):
            ServingEngine(backend, tenant_fair=True, priority_classes=True)


def _sampled_oracle(params, cfg, req):
    from uccl_tpu.models.inference import generate

    toks = generate(params, jnp.asarray(req.prompt)[None], cfg,
                    max_new_tokens=req.max_new_tokens, max_seq=MAX_SEQ,
                    sampling=req.sampling)
    return np.asarray(toks)[0, : req.n_generated].tolist()


class TestDenseSampledOracle:
    def test_same_seed_bit_identity_mixed_batch(self, dense_setup):
        """The acceptance anchor: 2 slots, 6 staggered requests (slot
        reuse) mixing greedy and sampled rows with distinct seeds /
        temperatures / truncations — every sequence bit-equals the
        sampled one-shot oracle at the same seed."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(0)
        eng = ServingEngine(backend)
        sp = [
            SamplingParams(temperature=0.8, seed=1),
            None,  # greedy neighbor in a sampled batch
            SamplingParams(temperature=1.2, top_k=7, seed=2),
            SamplingParams(temperature=0.7, top_p=0.9, seed=3),
            SamplingParams(temperature=1.0, top_p=0.8, top_k=9, seed=4),
            SamplingParams(temperature=0.8, seed=1),  # seed reuse, new slot
        ]
        reqs = [eng.submit(_prompt(rng, 5), max_new_tokens=6,
                           sampling=sp[0]),
                eng.submit(_prompt(rng, 3), max_new_tokens=4,
                           sampling=sp[1])]
        eng.step()
        eng.step()
        for (n, m), s in zip(((8, 5), (2, 6), (6, 3), (7, 5)), sp[2:]):
            reqs.append(eng.submit(_prompt(rng, n), max_new_tokens=m,
                                   sampling=s))
        eng.drain()
        assert eng.pool.leaked() == 0
        for r in reqs:
            assert r.n_generated == r.max_new_tokens
            assert r.out_tokens == _sampled_oracle(params, cfg, r), r.rid
        # seed is the whole entropy source: equal prompt + equal seed on
        # DIFFERENT slots at different times → identical tokens
        twin = eng.submit(reqs[0].prompt.copy(), max_new_tokens=6,
                          sampling=sp[0])
        eng.drain()
        assert twin.out_tokens == reqs[0].out_tokens

    def test_sampled_not_equal_greedy(self, dense_setup):
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(3)
        prompt = _prompt(rng, 5)
        eng = ServingEngine(backend)
        hot = eng.submit(prompt.copy(), max_new_tokens=6,
                         sampling=SamplingParams(temperature=2.0, seed=11))
        cold = eng.submit(prompt.copy(), max_new_tokens=6)
        eng.drain()
        assert hot.out_tokens == _sampled_oracle(params, cfg, hot)
        assert cold.out_tokens == _sampled_oracle(params, cfg, cold)
        assert hot.out_tokens != cold.out_tokens, (
            "temperature-2 sampling should diverge from greedy here; if "
            "not, this fixture stopped exercising the sampled path"
        )

    def test_sampled_chunked_spec_exact(self, dense_setup):
        """Chunked prefill + speculative decoding + sampling compose:
        lockstep keys make the spec_k>0 commits same-seed EXACT, and the
        chunk cursor never perturbs a position's key."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(1)
        eng = ServingEngine(backend, prefill_chunk=3, spec_k=2)
        sp = [SamplingParams(temperature=0.9, seed=21),
              SamplingParams(temperature=0.9, top_k=5, seed=22),
              None,
              SamplingParams(temperature=1.1, top_p=0.85, seed=23)]
        reqs = [eng.submit(_prompt(rng, 7), max_new_tokens=6,
                           sampling=sp[0]),
                eng.submit(_prompt(rng, 4), max_new_tokens=5,
                           sampling=sp[1])]
        eng.step()
        eng.step()
        reqs.append(eng.submit(_prompt(rng, 8), max_new_tokens=4,
                               sampling=sp[2]))
        reqs.append(eng.submit(_prompt(rng, 5), max_new_tokens=6,
                               sampling=sp[3]))
        eng.drain()
        assert eng.pool.leaked() == 0
        for r in reqs:
            assert r.out_tokens == _sampled_oracle(params, cfg, r), r.rid

    def test_spec_equals_vanilla_at_same_seed(self, dense_setup):
        """spec_k>0 ≡ spec_k=0 at equal seeds, request for request — the
        strongest form of the distribution-identity bar."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(2)
        prompts = [_prompt(rng, n) for n in (5, 3, 8, 6)]
        sp = [SamplingParams(temperature=0.8, seed=31 + i)
              for i in range(4)]

        def run(spec_k):
            eng = ServingEngine(backend, spec_k=spec_k)
            reqs = [eng.submit(p.copy(), max_new_tokens=5, sampling=s)
                    for p, s in zip(prompts, sp)]
            eng.drain()
            assert eng.pool.leaked() == 0
            return [r.out_tokens for r in reqs]

        assert run(None) == run(2)

    def test_spec_resample_counter_counts_sampled_rejections(self,
                                                             dense_setup):
        from uccl_tpu.serving import engine as eng_mod

        cfg, params, backend = dense_setup
        before = eng_mod._SPEC_RESAMPLE.total()
        eng = ServingEngine(backend, spec_k=2)
        # a motif prompt makes the prompt-lookup drafter actually PROPOSE
        # (random prompts can starve it of n-gram matches); temperature-2
        # sampling then rejects some proposal at this seed
        r = eng.submit(np.tile(np.array([7, 9], np.int32), 6),
                       max_new_tokens=8,
                       sampling=SamplingParams(temperature=2.0, seed=6))
        eng.drain()
        assert r.out_tokens == _sampled_oracle(params, cfg, r)
        assert eng.metrics.snapshot()["spec_proposed"] > 0, (
            "drafter never proposed — the fixture stopped exercising the "
            "rejection path; pick a seed/motif that yields proposals"
        )
        assert eng_mod._SPEC_RESAMPLE.total() > before


class TestDenseLoRA:
    def test_fused_mixed_ranks_vs_materialized(self, dense_setup):
        """One batch holds a rank-2 adapter, a rank-4 adapter (rank
        padding in one compiled program) and an adapter-free request —
        each bit-equals generate() on its own dense-materialized
        ``W + B@A`` params, and the adapter-free neighbor is untouched."""
        from uccl_tpu.models.inference import generate

        cfg, params, backend = dense_setup
        rng = np.random.default_rng(0)
        store = _store_for(cfg, max_rank=4, capacity=2)
        trees = {"acme": _lora_for(cfg, 2, seed=1),
                 "beta": _lora_for(cfg, 4, seed=2)}
        for t, tree in trees.items():
            store.publish(t, tree)
        eng = ServingEngine(backend, adapters=store)
        prompt = _prompt(rng, 6)
        ra = eng.submit(prompt.copy(), max_new_tokens=6, adapter="acme")
        rb = eng.submit(prompt.copy(), max_new_tokens=6, adapter="beta")
        rn = eng.submit(prompt.copy(), max_new_tokens=6)
        eng.drain()
        assert eng.pool.leaked() == 0
        assert store.n_resident == 2  # retire released the pins

        def want(req, tree):
            p = materialize(params, tree) if tree is not None else params
            toks = generate(p, jnp.asarray(req.prompt)[None], cfg,
                            max_new_tokens=req.max_new_tokens,
                            max_seq=MAX_SEQ)
            return np.asarray(toks)[0, : req.n_generated].tolist()

        wa, wb, wn = (want(ra, trees["acme"]), want(rb, trees["beta"]),
                      want(rn, None))
        assert ra.out_tokens == wa and rb.out_tokens == wb
        assert rn.out_tokens == wn
        assert len({tuple(wa), tuple(wb), tuple(wn)}) == 3, (
            "adapters failed to change the argmax — raise the LoRA scale "
            "or this test proves nothing"
        )

    def test_lru_restage_under_bounded_store_stays_exact(self, dense_setup):
        """capacity=1: each alternating request evicts the other tenant's
        row; outputs stay exact through evict → restage cycles and the
        eviction counter records them."""
        from uccl_tpu.serving import adapters as mod

        cfg, params, backend = dense_setup
        rng = np.random.default_rng(1)
        store = _store_for(cfg, max_rank=2, capacity=1)
        trees = {"a": _lora_for(cfg, 2, seed=3),
                 "b": _lora_for(cfg, 2, seed=4)}
        for t, tree in trees.items():
            store.publish(t, tree)
        e0 = mod._EVICTIONS.total()
        eng = ServingEngine(backend, adapters=store)
        prompt = _prompt(rng, 5)
        outs = {}
        for name in ("a", "b", "a", "b"):
            r = eng.submit(prompt.copy(), max_new_tokens=5, adapter=name)
            eng.drain()
            outs.setdefault(name, []).append(r.out_tokens)
        assert mod._EVICTIONS.total() - e0 >= 3
        for name, runs in outs.items():
            assert runs[0] == runs[1], (name, "restage changed tokens")
        assert outs["a"][0] != outs["b"][0]

    def test_sampling_composes_with_adapters(self, dense_setup):
        from uccl_tpu.models.inference import generate

        cfg, params, backend = dense_setup
        rng = np.random.default_rng(2)
        store = _store_for(cfg, max_rank=2, capacity=2)
        tree = _lora_for(cfg, 2, seed=5)
        store.publish("acme", tree)
        eng = ServingEngine(backend, adapters=store)
        sp = SamplingParams(temperature=0.9, seed=41)
        r = eng.submit(_prompt(rng, 6), max_new_tokens=6, adapter="acme",
                       sampling=sp)
        eng.drain()
        toks = generate(materialize(params, tree),
                        jnp.asarray(r.prompt)[None], cfg,
                        max_new_tokens=r.max_new_tokens, max_seq=MAX_SEQ,
                        sampling=sp)
        assert r.out_tokens == np.asarray(toks)[0, : r.n_generated].tolist()

    def test_submit_unknown_adapter_rejected(self, dense_setup):
        cfg, params, backend = dense_setup
        store = _store_for(cfg, max_rank=2, capacity=2)
        eng = ServingEngine(backend, adapters=store)
        with pytest.raises(ValueError):
            eng.submit(np.zeros(4, np.int32), max_new_tokens=2,
                       adapter="ghost")
        eng2 = ServingEngine(backend)
        with pytest.raises(ValueError):
            eng2.submit(np.zeros(4, np.int32), max_new_tokens=2,
                        adapter="acme")  # no store configured


class TestAdapterAdmissionGate:
    """Admission-boundary re-validation of adapters (engine._gate_admitted):
    submit-time checks go stale while a request queues — the gate must
    defer (rows exhausted) or reject (adapter archive-evicted) instead of
    letting ``acquire`` raise mid-``step()`` after the slot was granted."""

    def _two_tenant_store(self, cfg, capacity):
        store = _store_for(cfg, max_rank=2, capacity=capacity)
        store.publish("a", _lora_for(cfg, 2, seed=8))
        store.publish("b", _lora_for(cfg, 2, seed=9))
        return store

    def test_exhausted_store_defers_whole_prompt(self, dense_setup):
        """More concurrent distinct adapters than table rows: the batch
        the scheduler admits would exhaust the store mid-stamp (pre-gate:
        RuntimeError inside step(), engine dead, pool inconsistent). The
        overflow request defers in queue until the first retire unpins
        its row; both finish."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(3)
        store = self._two_tenant_store(cfg, capacity=1)
        eng = ServingEngine(backend, adapters=store)
        ra = eng.submit(_prompt(rng, 4), max_new_tokens=3, adapter="a")
        rb = eng.submit(_prompt(rng, 4), max_new_tokens=3, adapter="b")
        done = eng.drain()
        assert {r.rid for r in done} == {ra.rid, rb.rid}
        assert ra.state is RequestState.FINISHED
        assert rb.state is RequestState.FINISHED
        assert eng.pool.leaked() == 0
        assert store.n_resident == 1  # b evicted a's unpinned row

    def test_exhausted_store_defers_chunked(self, dense_setup):
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(4)
        store = self._two_tenant_store(cfg, capacity=1)
        eng = ServingEngine(backend, prefill_chunk=2, adapters=store)
        ra = eng.submit(_prompt(rng, 4), max_new_tokens=3, adapter="a")
        rb = eng.submit(_prompt(rng, 4), max_new_tokens=3, adapter="b")
        eng.drain()
        assert ra.state is RequestState.FINISHED
        assert rb.state is RequestState.FINISHED
        assert eng.pool.leaked() == 0

    def test_archive_evicted_while_queued_is_rejected(self, dense_setup):
        """An adapter archive-evicted (max_published) after submit but
        before admission can never run again: the request exits REJECTED
        with ``finish_reason="adapter_lost"`` (pre-gate: KeyError
        mid-step) and later submissions keep flowing."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(5)
        store = _store_for(cfg, max_rank=2, capacity=2, max_published=2)
        store.publish("a", _lora_for(cfg, 2, seed=10))
        eng = ServingEngine(backend, adapters=store)
        r = eng.submit(_prompt(rng, 4), max_new_tokens=3, adapter="a")
        store.publish("b", _lora_for(cfg, 2, seed=11))
        store.publish("c", _lora_for(cfg, 2, seed=12))  # evicts "a"
        assert not store.has("a")
        done = eng.drain()
        assert done == []
        assert r.state is RequestState.REJECTED
        assert r.finish_reason == "adapter_lost"
        assert eng.pool.leaked() == 0
        ok = eng.submit(_prompt(rng, 4), max_new_tokens=3, adapter="b")
        eng.drain()
        assert ok.state is RequestState.FINISHED


class TestPrefixCacheTenancy:
    def _engine(self, backend, store=None):
        return ServingEngine(backend, prefill_chunk=4,
                             prefix_cache=PrefixCache(4), adapters=store,
                             tenant_fair=True)

    def test_cross_tenant_hit_attempt_is_a_miss(self, dense_setup):
        """Trie keys are namespaced by tenant: tenant B re-sending tenant
        A's exact prompt must NOT reuse A's parked KV (cross-tenant KV
        bleed), while A's own re-send hits."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(0)
        prompt = _prompt(rng, 8)
        eng = self._engine(backend)
        r0 = eng.submit(prompt.copy(), max_new_tokens=4, tenant="acme")
        eng.drain()
        assert r0.cache_hit_len == 0
        r1 = eng.submit(prompt.copy(), max_new_tokens=4, tenant="acme")
        eng.drain()
        assert r1.cache_hit_len > 0  # same tenant: real reuse
        r2 = eng.submit(prompt.copy(), max_new_tokens=4, tenant="beta")
        eng.drain()
        assert r2.cache_hit_len == 0, "cross-tenant prefix reuse"
        assert r2.out_tokens == r1.out_tokens == r0.out_tokens
        assert eng.pool.leaked() == 0

    def test_adapter_version_bump_invalidates_prefix(self, dense_setup):
        """The namespace includes the adapter VERSION: a wv delta lands in
        the V cache, so KV parked under v1 is wrong for v2 — a republish
        must turn the next same-prompt request into a miss."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(1)
        store = _store_for(cfg, max_rank=2, capacity=2)
        store.publish("acme", _lora_for(cfg, 2, seed=6))
        eng = self._engine(backend, store)
        prompt = _prompt(rng, 8)
        eng.submit(prompt.copy(), max_new_tokens=4, tenant="t",
                   adapter="acme")
        eng.drain()
        warm = eng.submit(prompt.copy(), max_new_tokens=4, tenant="t",
                          adapter="acme")
        eng.drain()
        assert warm.cache_hit_len > 0
        store.publish("acme", _lora_for(cfg, 2, seed=7))  # v2
        stale = eng.submit(prompt.copy(), max_new_tokens=4, tenant="t",
                           adapter="acme")
        eng.drain()
        assert stale.cache_hit_len == 0, "stale adapter-version KV reuse"
        assert eng.pool.leaked() == 0

    def test_republish_in_flight_parks_under_admitted_version(
            self, dense_setup):
        """The park namespace is CAPTURED at admission, not recomputed at
        retire: republishing while a request is in flight must not park
        its v1-computed KV under the v2 namespace — a later v2 request
        would silently reuse wrong rows (the exact contamination the
        versioned namespace exists to prevent)."""
        cfg, params, backend = dense_setup
        rng = np.random.default_rng(6)
        store = _store_for(cfg, max_rank=2, capacity=2)
        store.publish("acme", _lora_for(cfg, 2, seed=13))
        eng = self._engine(backend, store)
        prompt = _prompt(rng, 8)
        r1 = eng.submit(prompt.copy(), max_new_tokens=4, tenant="t",
                        adapter="acme")
        eng.step()  # admitted: namespace frozen at v1
        store.publish("acme", _lora_for(cfg, 2, seed=14))  # v2 mid-flight
        eng.drain()
        assert r1.state is RequestState.FINISHED
        r2 = eng.submit(prompt.copy(), max_new_tokens=4, tenant="t",
                        adapter="acme")
        eng.drain()
        assert r2.cache_hit_len == 0, "v1-derived KV served to v2"
        assert eng.pool.leaked() == 0


class TestTenantMetrics:
    def test_per_tenant_series_and_counters(self, dense_setup):
        from uccl_tpu.serving import engine as eng_mod

        cfg, params, backend = dense_setup
        rng = np.random.default_rng(0)
        before = {
            t: v for t, v in (
                (s.get("tenant"), v)
                for s, v in eng_mod._TENANT_REQS.samples()
            )
        }
        eng = ServingEngine(backend, tenant_fair=True)
        for t in ("acme", "beta", "acme"):
            eng.submit(_prompt(rng, 4), max_new_tokens=3, tenant=t)
        eng.drain()
        snap = eng.metrics.snapshot()
        per = snap["per_tenant"]
        assert set(per) == {"acme", "beta"}
        assert per["acme"]["completed"] == 2
        assert per["beta"]["output_tokens"] == 3
        after = {
            t: v for t, v in (
                (s.get("tenant"), v)
                for s, v in eng_mod._TENANT_REQS.samples()
            )
        }
        assert after.get("acme", 0) - before.get("acme", 0) == 2
        assert after.get("beta", 0) - before.get("beta", 0) == 1
        lines = "\n".join(
            eng.metrics.prometheus_lines(snap, prefix="uccl_serving")
        )
        assert 'uccl_serving_tenant_completed{tenant="acme"' in lines


@pytest.fixture(scope="module")
def moe_setup(devices):
    """ONE 2-shard server/backend + ONE world-1 oracle server (the
    test_serving rule: shard_map compiles are the expensive kind)."""
    from jax.sharding import Mesh

    from uccl_tpu.models.moe_inference import (
        MoEServeConfig, MoEServer, init_params,
    )

    cfg = MoEServeConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=8, moe_experts=8, moe_topk=2, moe_ffn=64,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    srv = MoEServer(cfg, Mesh(np.array(devices[:2]), ("dp",)))
    backend = MoEBackend(
        srv, srv.shard_params(params), batch_local=1, max_seq=MAX_SEQ,
    )
    srv1 = MoEServer(cfg, Mesh(np.array(devices[:1]), ("dp",)))
    return cfg, params, backend, srv1


@pytest.mark.slow
class TestMoETenancy:
    def _oracle(self, srv1, placed1, req):
        toks = srv1.generate(placed1, jnp.asarray(req.prompt)[None, None],
                             req.max_new_tokens, MAX_SEQ, impl="ll",
                             sampling=req.sampling)
        return np.asarray(toks)[0, 0, : req.n_generated].tolist()

    def test_sampled_spec_bit_identity(self, moe_setup):
        cfg, params, backend, srv1 = moe_setup
        placed1 = srv1.shard_params(params)
        rng = np.random.default_rng(0)
        eng = ServingEngine(backend, spec_k=2, tenant_fair=True)
        sp = [SamplingParams(temperature=0.8, seed=51),
              None,
              SamplingParams(temperature=1.1, top_p=0.9, top_k=7,
                             seed=52)]
        reqs = [eng.submit(_prompt(rng, 5), max_new_tokens=5,
                           sampling=sp[0], tenant="acme")]
        eng.step()
        reqs.append(eng.submit(_prompt(rng, 3), max_new_tokens=4,
                               sampling=sp[1], tenant="beta"))
        reqs.append(eng.submit(_prompt(rng, 6), max_new_tokens=5,
                               sampling=sp[2], tenant="acme"))
        eng.drain()
        assert eng.pool.leaked() == 0
        for r in reqs:
            assert r.out_tokens == self._oracle(srv1, placed1, r), r.rid
        assert set(eng.metrics.snapshot()["per_tenant"]) == {"acme",
                                                             "beta"}

    def test_fused_lora_vs_materialized(self, moe_setup):
        cfg, params, backend, srv1 = moe_setup
        rng = np.random.default_rng(1)
        store = _store_for(cfg, max_rank=4, capacity=2)
        trees = {"acme": _lora_for(cfg, 2, seed=8),
                 "beta": _lora_for(cfg, 4, seed=9)}
        for t, tree in trees.items():
            store.publish(t, tree)
        eng = ServingEngine(backend, adapters=store)
        prompt = _prompt(rng, 6)
        ra = eng.submit(prompt.copy(), max_new_tokens=5, adapter="acme")
        rb = eng.submit(prompt.copy(), max_new_tokens=5, adapter="beta")
        eng.drain()
        rn = eng.submit(prompt.copy(), max_new_tokens=5)
        eng.drain()
        assert eng.pool.leaked() == 0
        placed = {t: srv1.shard_params(materialize(params, tree))
                  for t, tree in trees.items()}
        placed[None] = srv1.shard_params(params)

        def want(req):
            toks = srv1.generate(placed[req.adapter],
                                 jnp.asarray(req.prompt)[None, None],
                                 req.max_new_tokens, MAX_SEQ, impl="ll")
            return np.asarray(toks)[0, 0, : req.n_generated].tolist()

        wa, wb, wn = want(ra), want(rb), want(rn)
        assert ra.out_tokens == wa and rb.out_tokens == wb
        assert rn.out_tokens == wn
        assert len({tuple(wa), tuple(wb), tuple(wn)}) >= 2, (
            "adapters failed to change the MoE argmax — raise the scale"
        )
