"""Device-side collective wrappers (uccl_tpu.collective.ops) exercised inside
shard_map on the virtual mesh — the compiled path models use."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from uccl_tpu.collective import ops


def _run(mesh, fn, x, in_spec, out_spec):
    mapped = jax.shard_map(
        fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec, check_vma=False
    )
    return np.asarray(jax.jit(mapped)(x))


@pytest.fixture(scope="module")
def mesh(devices):
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    return make_mesh(MeshConfig(dp=8), devices)


def test_all_reduce_ops(mesh, rng):
    x = rng.standard_normal((8, 4)).astype(np.float32)
    for op, red in [("sum", np.sum), ("max", np.max), ("min", np.min), ("mean", np.mean)]:
        out = _run(mesh, lambda v, op=op: ops.all_reduce(v, "dp", op), x, P("dp"), P("dp"))
        np.testing.assert_allclose(out, np.broadcast_to(red(x, 0), x.shape), rtol=1e-5)
    with pytest.raises(ValueError):
        _run(mesh, lambda v: ops.all_reduce(v, "dp", "bogus"), x, P("dp"), P("dp"))


def test_all_gather_reduce_scatter_roundtrip(mesh, rng):
    x = rng.standard_normal((8, 3)).astype(np.float32)
    g = _run(mesh, lambda v: ops.all_gather(v, "dp"), x, P("dp"), P(None))
    np.testing.assert_array_equal(g, x)
    rs = _run(mesh, lambda v: ops.reduce_scatter(v, "dp", dim=1),
              np.ones((8, 24), np.float32), P("dp"), P("dp"))
    np.testing.assert_allclose(rs, np.full((8, 3), 8.0))


def test_broadcast_op(mesh, rng):
    x = rng.standard_normal((8, 5)).astype(np.float32)
    out = _run(mesh, lambda v: ops.broadcast(v, "dp", root=3), x, P("dp"), P(None))
    np.testing.assert_array_equal(out, np.broadcast_to(x[3], (1, 5)))


def test_ring_shift_op(mesh, rng):
    x = rng.standard_normal((8, 2)).astype(np.float32)
    out = _run(mesh, lambda v: ops.ring_shift(v, "dp", 2), x, P("dp"), P("dp"))
    np.testing.assert_array_equal(out, np.roll(x, 2, axis=0))


def test_all_to_all_op(mesh, rng):
    x = rng.standard_normal((8, 8, 2)).astype(np.float32)
    out = _run(
        mesh,
        lambda v: ops.all_to_all(v, "dp", split_dim=1, concat_dim=1),
        x,
        P("dp"),
        P("dp"),
    )
    np.testing.assert_array_equal(out, x.transpose(1, 0, 2))


def test_axis_helpers(mesh):
    x = np.zeros((8, 1), np.float32)
    idx = _run(
        mesh,
        lambda v: v + ops.axis_index("dp").astype(np.float32),
        x,
        P("dp"),
        P("dp"),
    )
    np.testing.assert_array_equal(idx[:, 0], np.arange(8))
