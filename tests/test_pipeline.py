"""GPipe SPMD schedule correctness: pipelined result == sequential application."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from uccl_tpu.parallel.mesh import MeshConfig, make_mesh
from uccl_tpu.parallel.pipeline import gpipe_spmd


@pytest.fixture(scope="module")
def pp_mesh(devices):
    return make_mesh(MeshConfig(pp=4, dp=2), devices)


def test_matches_sequential(pp_mesh, rng):
    """4 stages each apply y = tanh(x @ w_s); compare against running the four
    matmuls sequentially on one device."""
    m, b, h = 3, 2, 8
    xmb = rng.standard_normal((m, b, h)).astype(np.float32)
    ws = rng.standard_normal((4, h, h)).astype(np.float32) * 0.5

    def f(w_local, x):
        def stage_fn(xm):
            return jnp.tanh(xm @ w_local[0]), jnp.sum(xm)

        return gpipe_spmd(stage_fn, x, "pp")

    mapped = jax.shard_map(
        f,
        mesh=pp_mesh,
        in_specs=(P("pp", None, None), P(None, None, None)),
        out_specs=(P(None, None, None), P()),
        check_vma=False,
    )
    out, aux = jax.jit(mapped)(ws, xmb)
    want = xmb
    for i in range(4):
        want = np.tanh(want @ ws[i])
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-6)


def test_aux_sums_over_stages_and_microbatches(pp_mesh):
    m, b, h = 2, 1, 4
    xmb = np.ones((m, b, h), np.float32)

    def f(x):
        def stage_fn(xm):
            return xm, jnp.asarray(1.0)  # each stage contributes 1 per valid mb

        return gpipe_spmd(stage_fn, x, "pp")

    mapped = jax.shard_map(
        f,
        mesh=pp_mesh,
        in_specs=(P(None, None, None),),
        out_specs=(P(None, None, None), P()),
        check_vma=False,
    )
    out, aux = jax.jit(mapped)(xmb)
    # identity stages: output == input; aux == stages * microbatches = 4*2
    np.testing.assert_allclose(np.asarray(out), xmb)
    assert float(aux) == 8.0


def test_gradients_flow(pp_mesh, rng):
    """d(sum of pipeline output)/d(stage weights) must match the sequential
    model's gradients — exercises the scan+ppermute transpose."""
    m, b, h = 2, 2, 4
    xmb = rng.standard_normal((m, b, h)).astype(np.float32)
    ws = rng.standard_normal((4, h, h)).astype(np.float32) * 0.5

    def pipeline_loss(w):
        def f(w_local, x):
            def stage_fn(xm):
                return jnp.tanh(xm @ w_local[0]), jnp.zeros(())

            out, _ = gpipe_spmd(stage_fn, x, "pp")
            return jnp.sum(out * out)

        mapped = jax.shard_map(
            f,
            mesh=pp_mesh,
            in_specs=(P("pp", None, None), P(None, None, None)),
            out_specs=P(),
            check_vma=False,
        )
        return mapped(w, xmb)

    def seq_loss(w):
        x = xmb
        for i in range(4):
            x = jnp.tanh(x @ w[i])
        return jnp.sum(x * x)

    g_pipe = jax.jit(jax.grad(pipeline_loss))(ws)
    g_seq = jax.jit(jax.grad(seq_loss))(ws)
    np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq), rtol=1e-4, atol=1e-5)


class TestOneFOneB:
    """Manual-schedule 1F1B: exact loss/grad parity with sequential autodiff."""

    def _setup(self, rng, p_stages, m, b=2, h=8):
        ws = rng.standard_normal((p_stages, h, h)).astype(np.float32) * 0.3
        bs = rng.standard_normal((p_stages, h)).astype(np.float32) * 0.1
        xmb = rng.standard_normal((m, b, h)).astype(np.float32)

        def stage(params, x):
            w, bias = params
            return jnp.tanh(x @ w + bias)

        def loss(y):
            return jnp.sum(y * y)

        return ws, bs, xmb, stage, loss

    def _reference(self, ws, bs, xmb, stage, loss):
        def total(ws, bs):
            acc = 0.0
            for k in range(xmb.shape[0]):
                x = xmb[k]
                for i in range(ws.shape[0]):
                    x = stage((ws[i], bs[i]), x)
                acc = acc + loss(x)
            return acc

        l, g = jax.value_and_grad(total, argnums=(0, 1))(ws, bs)
        return l, g

    @pytest.mark.parametrize("p_stages,m", [(2, 4), (4, 4), (4, 6), (4, 2)])
    def test_matches_sequential_autodiff(self, devices, rng, p_stages, m):
        from uccl_tpu.parallel.pipeline import one_f_one_b

        mesh = make_mesh(MeshConfig(pp=p_stages), devices[:p_stages])
        ws, bs, xmb, stage, loss = self._setup(rng, p_stages, m)
        want_l, (want_dw, want_db) = self._reference(ws, bs, xmb, stage, loss)

        def f(w, b, x):
            l, (dw, db) = one_f_one_b(stage, loss, (w[0], b[0]), x, "pp")
            return l, dw[None], db[None]

        got_l, got_dw, got_db = jax.jit(
            jax.shard_map(
                f, mesh=mesh,
                in_specs=(P("pp"), P("pp"), P(None)),
                out_specs=(P(), P("pp"), P("pp")),
                check_vma=False,
            )
        )(ws, bs, xmb)
        np.testing.assert_allclose(float(got_l), float(want_l), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(got_dw), want_dw, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(got_db), want_db, rtol=1e-4, atol=1e-5)

    def test_schedule_inflight_bound(self):
        from uccl_tpu.parallel.pipeline import _simulate_1f1b

        m, p = 12, 4
        do_f, f_mb, do_b, b_mb = _simulate_1f1b(m, p)
        fwd_done = np.zeros(p, int)
        bwd_done = np.zeros(p, int)
        for t in range(do_f.shape[0]):
            for s in range(p):
                fwd_done[s] += do_f[t, s]
                bwd_done[s] += do_b[t, s]
                inflight = fwd_done[s] - bwd_done[s]
                assert inflight <= min(m, p - s), (t, s, inflight)
        assert (fwd_done == m).all() and (bwd_done == m).all()
        # the 1F1B liveness bound: far below GPipe's M everywhere
        assert do_f.shape[0] < 3 * (m + p)


class TestInterleaved:
    """Interleaved (virtual-chunk) 1F1B: exact parity with sequential autodiff
    at L = P*V logical stages, and a ramp that shrinks with V."""

    def _setup(self, rng, n_stages, m, b=2, h=8):
        ws = rng.standard_normal((n_stages, h, h)).astype(np.float32) * 0.3
        bs = rng.standard_normal((n_stages, h)).astype(np.float32) * 0.1
        xmb = rng.standard_normal((m, b, h)).astype(np.float32)

        def stage(params, x):
            w, bias = params
            return jnp.tanh(x @ w + bias)

        def loss(y):
            return jnp.sum(y * y)

        return ws, bs, xmb, stage, loss

    def _reference(self, ws, bs, xmb, stage, loss):
        def total(ws, bs):
            acc = 0.0
            for k in range(xmb.shape[0]):
                x = xmb[k]
                for i in range(ws.shape[0]):
                    x = stage((ws[i], bs[i]), x)
                acc = acc + loss(x)
            return acc

        return jax.value_and_grad(total, argnums=(0, 1))(ws, bs)

    @staticmethod
    def _to_device_chunks(arr, p, v):
        """[L, ...] stage-major -> [P, V, ...] device-major (chunk c on device
        s holds global stage c*p + s)."""
        return np.moveaxis(arr.reshape((v, p) + arr.shape[1:]), 1, 0)

    @pytest.mark.parametrize("p_devs,v,m", [(2, 2, 4), (4, 2, 4), (2, 3, 5), (2, 1, 4)])
    def test_matches_sequential_autodiff(self, devices, rng, p_devs, v, m):
        from uccl_tpu.parallel.pipeline import interleaved_1f1b

        mesh = make_mesh(MeshConfig(pp=p_devs), devices[:p_devs])
        L = p_devs * v
        ws, bs, xmb, stage, loss = self._setup(rng, L, m)
        want_l, (want_dw, want_db) = self._reference(ws, bs, xmb, stage, loss)
        wd = self._to_device_chunks(ws, p_devs, v)  # [P, V, h, h]
        bd = self._to_device_chunks(bs, p_devs, v)  # [P, V, h]

        def f(w, b, x):
            l, (dw, db) = interleaved_1f1b(
                stage, loss, (w[0], b[0]), x, n_chunks=v, axis="pp"
            )
            return l, dw[None], db[None]

        got_l, got_dw, got_db = jax.jit(
            jax.shard_map(
                f, mesh=mesh,
                in_specs=(P("pp"), P("pp"), P(None)),
                out_specs=(P(), P("pp"), P("pp")),
                check_vma=False,
            )
        )(wd, bd, xmb)
        np.testing.assert_allclose(float(got_l), float(want_l), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(got_dw),
            self._to_device_chunks(want_dw, p_devs, v),
            rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(got_db),
            self._to_device_chunks(want_db, p_devs, v),
            rtol=1e-4, atol=1e-5,
        )

    def test_bf16_loss_dtype(self, devices, rng):
        """Regression: a non-f32 loss (normal TPU mixed precision) must not
        trip the scan's cond-branch dtype matching."""
        from uccl_tpu.parallel.pipeline import interleaved_1f1b

        p_devs, v, m = 2, 2, 2
        mesh = make_mesh(MeshConfig(pp=p_devs), devices[:p_devs])
        ws, bs, xmb, stage, _ = self._setup(rng, p_devs * v, m)
        wd = self._to_device_chunks(ws, p_devs, v).astype(jnp.bfloat16)
        bd = self._to_device_chunks(bs, p_devs, v).astype(jnp.bfloat16)
        xb = xmb.astype(jnp.bfloat16)

        def loss(y):
            return jnp.sum(y * y)  # bf16 in -> bf16 out

        def f(w, b, x):
            l, (dw, db) = interleaved_1f1b(
                stage, loss, (w[0], b[0]), x, n_chunks=v, axis="pp"
            )
            return l, dw[None], db[None]

        got_l, got_dw, _ = jax.jit(
            jax.shard_map(
                f, mesh=mesh,
                in_specs=(P("pp"), P("pp"), P(None)),
                out_specs=(P(), P("pp"), P("pp")),
                check_vma=False,
            )
        )(wd, bd, jnp.asarray(xb))
        assert jnp.isfinite(got_l)
        assert got_dw.dtype == jnp.bfloat16

    def test_ramp_shrinks_with_chunks(self):
        """In wall-clock units (a slot runs 1/V of a device's layers), the
        interleaved schedule's span T/V must beat non-interleaved 1F1B's and
        approach the 2M ideal as V grows."""
        from uccl_tpu.parallel.pipeline import _simulate_interleaved

        m, p = 8, 4
        spans = {}
        for v in (1, 2, 4):
            sched = _simulate_interleaved(m, p, v)
            spans[v] = sched["do_f"].shape[0] / v
        assert spans[2] < spans[1], spans
        assert spans[4] < spans[2], spans
        # every chunk ran every microbatch both directions
        sched = _simulate_interleaved(m, p, 2)
        assert sched["do_f"].sum() == 2 * m * p
        assert sched["do_b"].sum() == 2 * m * p

    def test_stash_bound(self):
        """Interleaved stash stays at the analytic cap, not O(M)."""
        from uccl_tpu.parallel.pipeline import _simulate_interleaved

        m, p, v = 16, 4, 2
        sched = _simulate_interleaved(m, p, v)
        cap = sum(min(m, (v - 1 - c) * p + p) for c in range(v))
        assert sched["n_stash"] <= cap
        assert sched["n_stash"] < m  # far below GPipe-style O(M) liveness


class TestPipelineTrain:
    """Full-model manual 1F1B: boundary grads (embedding input cotangents,
    loss-head params), per-microbatch targets, per-stage aux losses —
    exact parity with sequential autodiff over the WHOLE model."""

    @pytest.mark.parametrize("p_stages,m", [(2, 4), (4, 6)])
    def test_full_model_parity(self, devices, rng, p_stages, m):
        from uccl_tpu.parallel.pipeline import pipeline_train

        b, h, vocab, aux_w = 2, 8, 12, 0.05
        mesh = make_mesh(MeshConfig(pp=p_stages), devices[:p_stages])
        emb = rng.standard_normal((vocab, h)).astype(np.float32) * 0.5
        ws = rng.standard_normal((p_stages, h, h)).astype(np.float32) * 0.3
        head = rng.standard_normal((h, vocab)).astype(np.float32) * 0.5
        toks = jnp.asarray(rng.integers(0, vocab, (m, b)), jnp.int32)
        tgts = jnp.asarray(
            rng.standard_normal((m, b, vocab)), jnp.float32
        )  # per-microbatch targets

        # sequential autodiff over the whole model (embed -> stages+aux ->
        # head loss with per-mb targets)
        def seq_total(emb, ws, head):
            acc = 0.0
            for k in range(m):
                x = jnp.take(emb, toks[k], axis=0)
                for i in range(p_stages):
                    acc = acc + aux_w * 1e-3 * jnp.sum(x * x)
                    x = jnp.tanh(x @ ws[i])
                acc = acc + jnp.sum((x @ head - tgts[k]) ** 2)
            return acc

        want_l, (want_demb, want_dws, want_dhead) = jax.value_and_grad(
            seq_total, argnums=(0, 1, 2)
        )(emb, ws, head)

        def per_shard(emb_, ws_, head_, toks_, tgts_):
            xmb = jnp.take(emb_, toks_, axis=0)  # [M, B, H] embed forward

            def stage_fn(w, x):
                return jnp.tanh(x @ w[0]), 1e-3 * jnp.sum(x * x)

            def loss_fn(head_p, y, tgt):
                return jnp.sum((y @ head_p - tgt) ** 2)

            total, _ce, dws, dhead, dxmb = pipeline_train(
                stage_fn, loss_fn, (ws_[0],), head_, xmb, tgts_, "pp",
                aux_weight=aux_w,
            )
            # embedding backward: scatter-add the input cotangents
            demb = jnp.zeros_like(emb_).at[toks_.reshape(-1)].add(
                dxmb.reshape(-1, dxmb.shape[-1])
            )
            return total, dws[0][None], dhead, demb

        got_l, got_dws, got_dhead, got_demb = jax.jit(
            jax.shard_map(
                per_shard, mesh=mesh,
                in_specs=(P(None), P("pp"), P(None), P(None), P(None)),
                out_specs=(P(), P("pp"), P(None), P(None)),
                check_vma=False,
            )
        )(emb, ws, head, toks, tgts)

        np.testing.assert_allclose(float(got_l), float(want_l), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(got_dws), np.asarray(want_dws), rtol=1e-4,
            atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(got_dhead), np.asarray(want_dhead), rtol=1e-4,
            atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(got_demb), np.asarray(want_demb), rtol=1e-4, atol=1e-5
        )
