"""Low-latency EP path tests: counts contract, packed layout, round-trip
equality with a dense oracle, drop semantics, fp8 wire, and training grads.

The reference validates LL mode with correctness asserts inside
ep/bench/test_low_latency.py (dispatch/combine round-trips checked before the
latency loop, :418-464); these tests are that ladder on the virtual CPU mesh
(dense wire — the ragged wire needs TPU/GPU and is exercised by ep_bench and
the on-chip run)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from uccl_tpu.ep import ll as ep_ll


W = 4  # EP world for these tests


@pytest.fixture(scope="module")
def epmesh(devices):
    return Mesh(np.array(devices[:W]).reshape(W), ("ep",))


def _run_sharded(epmesh, fn, *args, out_extra=1):
    specs = tuple(P("ep") for _ in args)
    if isinstance(out_extra, tuple):
        out_specs = tuple(P("ep") for _ in out_extra)
    else:
        out_specs = P("ep")
    return jax.jit(
        shard_map(
            fn, mesh=epmesh, in_specs=specs, out_specs=out_specs,
            check_vma=False,
        )
    )(*args)


def _make_case(t=16, h=32, e=8, k=2, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((W, t, h)).astype(np.float32)
    idx = np.stack(
        [
            np.stack(
                [rng.choice(e, size=k, replace=False) for _ in range(t)]
            )
            for _ in range(W)
        ]
    ).astype(np.int32)
    wts = rng.uniform(0.1, 1.0, (W, t, k)).astype(np.float32)
    return x, idx, wts


def _oracle_moe(x, idx, wts, wg, wu, wd, e):
    """Per-token weighted SwiGLU expert mixture, no parallelism, no drops."""
    wcount, t, h = x.shape
    out = np.zeros_like(x)
    for r in range(wcount):
        for ti in range(t):
            acc = np.zeros(h, np.float32)
            for kk in range(idx.shape[-1]):
                ei = idx[r, ti, kk]
                g = x[r, ti] @ wg[ei]
                u = x[r, ti] @ wu[ei]
                silu = g / (1.0 + np.exp(-g)) * u
                acc += wts[r, ti, kk] * (silu @ wd[ei])
            out[r, ti] = acc
    return out


class TestLayoutAndCounts:
    def test_group_sizes_match_demand(self, epmesh):
        """recv counts per local expert == global demand for that expert."""
        x, idx, wts = _make_case()
        e, t, k = 8, 16, 2

        def f(xv, iv, wv):
            r = ep_ll.ll_dispatch(
                xv[0], iv[0], wv[0], e, "ep", wire="dense", wire_fp8=False
            )
            return r.group_sizes[None], r.state.recv_mat[None]

        gs, recv_mat = _run_sharded(
            epmesh, f, x, idx, wts, out_extra=(1, 2)
        )
        gs = np.asarray(gs)  # [W, E_local]
        demand = np.bincount(idx.reshape(-1), minlength=e).reshape(W, e // W)
        np.testing.assert_array_equal(gs, demand)
        # recv_mat row sums telescope to the same totals
        np.testing.assert_array_equal(
            np.asarray(recv_mat).sum(1), gs
        )

    def test_recv_rows_are_group_major_packed(self, epmesh):
        """Rows of local expert g occupy exactly positions
        [cumsum(gs)[g-1], cumsum(gs)[g]) and hold the right token set."""
        e, t, h, k = 8, 16, 32, 2
        x, idx, wts = _make_case(t=t, h=h, e=e, k=k)
        # make tokens identifiable: x[r, t] = r * 1000 + t in every column
        for r in range(W):
            for ti in range(t):
                x[r, ti] = r * 1000 + ti

        def f(xv, iv, wv):
            r = ep_ll.ll_dispatch(
                xv[0], iv[0], wv[0], e, "ep", wire="dense", wire_fp8=False
            )
            return r.recv_x[None], r.group_sizes[None]

        recv, gs = _run_sharded(epmesh, f, x, idx, wts, out_extra=(1, 1))
        recv, gs = np.asarray(recv), np.asarray(gs)
        e_local = e // W
        for rank in range(W):
            ends = np.cumsum(gs[rank])
            starts = ends - gs[rank]
            for le in range(e_local):
                ge = rank * e_local + le
                got = sorted(recv[rank, starts[le]:ends[le], 0].tolist())
                want = sorted(
                    float(r * 1000 + ti)
                    for r in range(W)
                    for ti in range(t)
                    for kk in range(k)
                    if idx[r, ti, kk] == ge
                )
                assert got == want, (rank, le)
            # zeros past the packed region
            assert np.all(recv[rank, ends[-1]:] == 0)


class TestRoundTrip:
    @pytest.mark.parametrize("wire_fp8", [False, True])
    def test_moe_matches_oracle(self, epmesh, wire_fp8):
        e, t, h, f_dim, k = 8, 16, 32, 64, 2
        x, idx, wts = _make_case(t=t, h=h, e=e, k=k)
        rng = np.random.default_rng(7)
        wg = rng.standard_normal((e, h, f_dim)).astype(np.float32) * 0.1
        wu = rng.standard_normal((e, h, f_dim)).astype(np.float32) * 0.1
        wd = rng.standard_normal((e, f_dim, h)).astype(np.float32) * 0.1
        e_local = e // W
        wg_s = wg.reshape(W, e_local, h, f_dim)
        wu_s = wu.reshape(W, e_local, h, f_dim)
        wd_s = wd.reshape(W, e_local, f_dim, h)

        def f(xv, iv, wv, g, u, d):
            r = ep_ll.ll_dispatch(
                xv[0], iv[0], wv[0], e, "ep", wire="dense",
                wire_fp8=wire_fp8,
            )
            y = ep_ll.grouped_ffn(r.recv_x, r.group_sizes, g[0], u[0], d[0])
            out = ep_ll.ll_combine(y, r.state, "ep", wire_fp8=wire_fp8)
            return out[None]

        out = _run_sharded(epmesh, f, x, idx, wts, wg_s, wu_s, wd_s)
        want = _oracle_moe(x, idx, wts, wg, wu, wd, e)
        tol = 0.08 if wire_fp8 else 2e-5
        np.testing.assert_allclose(np.asarray(out), want, atol=tol, rtol=tol)

    def test_matches_sorted_path_at_ample_capacity(self, epmesh):
        """The LL path (lossless) agrees with the existing sorted path when
        the sorted path's capacity is large enough that nothing drops."""
        from uccl_tpu.ep import ops as ep_ops

        e, t, h, f_dim, k = 8, 16, 32, 64, 2
        x, idx, wts = _make_case(t=t, h=h, e=e, k=k)
        rng = np.random.default_rng(3)
        wg = rng.standard_normal((e, h, f_dim)).astype(np.float32) * 0.1
        wu = rng.standard_normal((e, h, f_dim)).astype(np.float32) * 0.1
        wd = rng.standard_normal((e, f_dim, h)).astype(np.float32) * 0.1
        e_local = e // W
        shards = (
            wg.reshape(W, e_local, h, f_dim),
            wu.reshape(W, e_local, h, f_dim),
            wd.reshape(W, e_local, f_dim, h),
        )
        cap = t * k  # ample: no drops possible

        def f_ll(xv, iv, wv, g, u, d):
            r = ep_ll.ll_dispatch(
                xv[0], iv[0], wv[0], e, "ep", wire="dense", wire_fp8=False
            )
            y = ep_ll.grouped_ffn(r.recv_x, r.group_sizes, g[0], u[0], d[0])
            return ep_ll.ll_combine(y, r.state, "ep", wire_fp8=False)[None]

        def f_sorted(xv, iv, wv, g, u, d):
            xv, iv, wv = xv[0], iv[0], wv[0]
            token_for_slot, slot, _ = ep_ops.sorted_from_topk(iv, e, cap)
            xe = ep_ops.dispatch_sorted(xv, token_for_slot, e, cap, "ep")
            act = jax.nn.silu(
                jnp.einsum("ebh,ehf->ebf", xe, g[0])
            ) * jnp.einsum("ebh,ehf->ebf", xe, u[0])
            ye = jnp.einsum("ebf,efh->ebh", act, d[0])
            return ep_ops.combine_sorted(ye, slot, wv, "ep")[None]

        out_ll = _run_sharded(epmesh, f_ll, x, idx, wts, *shards)
        out_sorted = _run_sharded(epmesh, f_sorted, x, idx, wts, *shards)
        np.testing.assert_allclose(
            np.asarray(out_ll), np.asarray(out_sorted), atol=3e-5, rtol=3e-5
        )


class TestBounds:
    def test_default_bound_is_lossless(self):
        per_pair, r_max = ep_ll.ll_bounds(t=16, k=2, e_local=2, w=4, m=None)
        assert per_pair == 32  # min(16*min(2,2), 32)
        assert r_max == 4 * 32

    def test_violated_bound_drops_tail(self, epmesh):
        """With m too small, later rows aimed at a hot destination drop —
        combine still produces finite weighted sums for surviving rows."""
        e, t, h, k = 8, 16, 32, 2
        x, idx, wts = _make_case(t=t, h=h, e=e, k=k)
        idx[:] = 0  # everyone floods expert 0 (rank 0)
        m = 4  # per_pair = 4*min(2,2) = 8 < t*k = 32

        def f(xv, iv, wv):
            r = ep_ll.ll_dispatch(
                xv[0], iv[0], wv[0], e, "ep", wire="dense", wire_fp8=False,
                num_max_dispatch_tokens_per_rank=m,
            )
            out = ep_ll.ll_combine(r.recv_x, r.state, "ep", wire_fp8=False)
            return r.group_sizes[None], out[None]

        gs, out = _run_sharded(epmesh, f, x, idx, wts, out_extra=(1, 1))
        gs = np.asarray(gs)
        per_pair = m * 2
        # rank 0's expert 0 received exactly per_pair rows from each source
        assert gs[0, 0] == W * per_pair
        assert np.all(gs[1:] == 0)
        assert np.all(np.isfinite(np.asarray(out)))


class TestGrouped:
    def test_grouped_ffn_matches_padded_einsum(self):
        rng = np.random.default_rng(5)
        e_local, rows, h, f_dim = 3, 24, 16, 32
        gs = np.array([5, 0, 11], np.int32)
        x = rng.standard_normal((rows, h)).astype(np.float32)
        wg = rng.standard_normal((e_local, h, f_dim)).astype(np.float32)
        wu = rng.standard_normal((e_local, h, f_dim)).astype(np.float32)
        wd = rng.standard_normal((e_local, f_dim, h)).astype(np.float32)
        got = np.asarray(
            ep_ll.grouped_ffn(
                jnp.asarray(x), jnp.asarray(gs), jnp.asarray(wg),
                jnp.asarray(wu), jnp.asarray(wd),
            )
        )
        # reference: row-wise expert assignment from group sizes
        starts = np.cumsum(gs) - gs
        want = np.zeros((rows, h), np.float32)
        for g in range(e_local):
            for rix in range(starts[g], starts[g] + gs[g]):
                gg = x[rix] @ wg[g]
                uu = x[rix] @ wu[g]
                want[rix] = (gg / (1 + np.exp(-gg)) * uu) @ wd[g]
        np.testing.assert_allclose(got[: gs.sum()], want[: gs.sum()],
                                   atol=1e-4, rtol=1e-4)
        assert np.all(got[gs.sum():] == 0)


class TestTraining:
    def test_grads_flow_and_match_oracle(self, epmesh):
        """Dense-wire LL MoE is differentiable; grads match the oracle's
        (computed by jax on the unsharded formulation)."""
        e, t, h, f_dim, k = 8, 8, 16, 32, 2
        x, idx, wts = _make_case(t=t, h=h, e=e, k=k, seed=11)
        rng = np.random.default_rng(13)
        wg = rng.standard_normal((e, h, f_dim)).astype(np.float32) * 0.1
        wu = rng.standard_normal((e, h, f_dim)).astype(np.float32) * 0.1
        wd = rng.standard_normal((e, f_dim, h)).astype(np.float32) * 0.1
        e_local = e // W

        def loss_sharded(params, xv, iv, wv):
            def f(g, u, d, xs, is_, ws):
                r = ep_ll.ll_dispatch(
                    xs[0], is_[0], ws[0], e, "ep", wire="dense",
                    wire_fp8=False,
                )
                y = ep_ll.grouped_ffn(
                    r.recv_x, r.group_sizes, g[0], u[0], d[0]
                )
                out = ep_ll.ll_combine(y, r.state, "ep", wire_fp8=False)
                return jnp.sum(out**2)[None]

            g, u, d = params
            per = shard_map(
                f, mesh=epmesh,
                in_specs=(P("ep"),) * 6,
                out_specs=P("ep"),
                check_vma=False,
            )(
                g.reshape(W, e_local, h, f_dim),
                u.reshape(W, e_local, h, f_dim),
                d.reshape(W, e_local, f_dim, h),
                xv, iv, wv,
            )
            return jnp.sum(per)

        def loss_oracle(params, xv, iv, wv):
            g, u, d = params
            xf = xv.reshape(-1, h)
            idxf = iv.reshape(-1, k)
            wf = wv.reshape(-1, k)
            xe = xf[:, None, :]  # [TT, 1, H]
            gsel = g[idxf]  # [TT, K, H, F]
            usel = u[idxf]
            dsel = d[idxf]  # [TT, K, F, H]
            act = jax.nn.silu(jnp.einsum("tih,tkhf->tkf", xe, gsel)) * \
                jnp.einsum("tih,tkhf->tkf", xe, usel)
            y = jnp.einsum("tkf,tkfh->tkh", act, dsel)
            out = jnp.einsum("tk,tkh->th", wf, y)
            return jnp.sum(out**2)

        params = (jnp.asarray(wg), jnp.asarray(wu), jnp.asarray(wd))
        args = (jnp.asarray(x), jnp.asarray(idx), jnp.asarray(wts))
        g_sharded = jax.grad(loss_sharded)(params, *args)
        g_oracle = jax.grad(loss_oracle)(params, *args)
        for a, b in zip(g_sharded, g_oracle):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-3, rtol=2e-3
            )

    def test_ll_moe_ffn_end_to_end(self, epmesh):
        """ll_moe_ffn (router included) runs and differentiates."""
        e, t, h, f_dim, k = 8, 8, 16, 32, 2
        rng = np.random.default_rng(17)
        x = rng.standard_normal((W, t, h)).astype(np.float32)
        router = rng.standard_normal((h, e)).astype(np.float32) * 0.1
        wg = rng.standard_normal((e, h, f_dim)).astype(np.float32) * 0.1
        wu = rng.standard_normal((e, h, f_dim)).astype(np.float32) * 0.1
        wd = rng.standard_normal((e, f_dim, h)).astype(np.float32) * 0.1
        e_local = e // W

        def loss(params, xv):
            rt, g, u, d = params

            def f(xs, gs, us, ds):
                logits = xs[0] @ rt
                out, aux, z = ep_ll.ll_moe_ffn(
                    xs[0], logits, gs[0], us[0], ds[0], "ep",
                    num_selected=k, wire="dense",
                )
                return (jnp.sum(out**2) + 0.01 * aux + 1e-3 * z)[None]

            per = shard_map(
                f, mesh=epmesh, in_specs=(P("ep"),) * 4,
                out_specs=P("ep"), check_vma=False,
            )(
                xv,
                g.reshape(W, e_local, h, f_dim),
                u.reshape(W, e_local, h, f_dim),
                d.reshape(W, e_local, f_dim, h),
            )
            return jnp.sum(per)

        params = tuple(map(jnp.asarray, (router, wg, wu, wd)))
        val, grads = jax.value_and_grad(loss)(params, jnp.asarray(x))
        assert np.isfinite(float(val))
        for garr in grads:
            assert np.all(np.isfinite(np.asarray(garr)))
            assert float(jnp.sum(jnp.abs(garr))) > 0


class TestBufferContract:
    def test_low_latency_dispatch_returns_counts(self, epmesh, devices):
        """Buffer.low_latency_dispatch honors the DeepEP contract: packed
        recv buffers + per-expert recv counts + opaque handle; combine
        round-trips through grouped_ffn-shaped consumers."""
        from jax.sharding import Mesh

        from uccl_tpu.ep import Buffer
        from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(dp=8), devices)
        e, t, h, k = 16, 8, 32, 2
        buf = Buffer(mesh, num_experts=e, num_selected=k)
        rng = np.random.default_rng(23)
        x = buf.device_put(rng.standard_normal((8, t, h)).astype(np.float32))
        idx = buf.device_put(
            np.stack(
                [
                    np.stack(
                        [rng.choice(e, size=k, replace=False)
                         for _ in range(t)]
                    )
                    for _ in range(8)
                ]
            ).astype(np.int32)
        )
        recv, counts, handle = buf.low_latency_dispatch(
            x, idx, wire="dense", wire_fp8=False
        )
        counts_np = np.asarray(counts)
        demand = np.bincount(
            np.asarray(idx).reshape(-1), minlength=e
        ).reshape(8, e // 8)
        np.testing.assert_array_equal(counts_np, demand)
        # identity experts: combine returns each token's weight-sum * token
        out = buf.low_latency_combine(recv, handle)
        want = np.asarray(x)  # uniform weights sum to 1, experts = identity
        np.testing.assert_allclose(
            np.asarray(out), want, atol=2e-5, rtol=2e-5
        )

    def test_pair_capacity_factor_tightens_buffers(self, epmesh):
        per_lossless, r_lossless = ep_ll.ll_bounds(256, 4, 4, 8, None)
        per_cf, r_cf = ep_ll.ll_bounds(256, 4, 4, 8, None,
                                       pair_capacity_factor=1.25)
        assert per_cf < per_lossless
        assert per_cf == -(-int(1.25 * 256 * 4) // 8)


class TestNoExpertIds:
    """topk_idx == -1 means "no expert" (DeepEP contract,
    ep/bench/buffer.py:285): such assignments claim no wire slot, do not
    perturb other tokens' packing, and combine to zero."""

    def test_counts_and_roundtrip_with_minus_one(self, epmesh):
        e, t, h, k = 8, 16, 32, 2
        x, idx, wts = _make_case(t=t, h=h, e=e, k=k)
        rng = np.random.default_rng(3)
        drop = rng.random((W, t, k)) < 0.3
        idx_m = np.where(drop, -1, idx).astype(np.int32)

        def f(xv, iv, wv):
            r = ep_ll.ll_dispatch(
                xv[0], iv[0], wv[0], e, "ep", wire="dense", wire_fp8=False
            )
            # identity experts: combine = per-token weighted sums of x
            out = ep_ll.ll_combine(r.recv_x, r.state, "ep", wire_fp8=False)
            return out[None], r.group_sizes[None]

        out, gs = _run_sharded(epmesh, f, x, idx_m, wts, out_extra=(1, 1))
        # recv counts see only the valid assignments
        valid = idx_m.reshape(-1)[idx_m.reshape(-1) >= 0]
        demand = np.bincount(valid, minlength=e).reshape(W, e // W)
        np.testing.assert_array_equal(np.asarray(gs), demand)
        # clean tokens round-trip exactly; -1 slots contribute zero
        want = np.einsum(
            "wtk,wth->wth", np.where(drop, 0.0, wts), x
        ).astype(np.float32)
        np.testing.assert_allclose(np.asarray(out), want, atol=2e-5,
                                   rtol=2e-5)

    def test_all_minus_one_token_is_zero_and_others_clean(self, epmesh):
        """A token with every assignment dropped outputs exactly zero."""
        e, t, h, k = 8, 8, 16, 2
        x, idx, wts = _make_case(t=t, h=h, e=e, k=k)
        idx_m = idx.copy()
        idx_m[:, 0, :] = -1  # first token of every rank: no experts

        def f(xv, iv, wv):
            r = ep_ll.ll_dispatch(
                xv[0], iv[0], wv[0], e, "ep", wire="dense", wire_fp8=False
            )
            out = ep_ll.ll_combine(r.recv_x, r.state, "ep", wire_fp8=False)
            return out[None]

        out = np.asarray(_run_sharded(epmesh, f, x, idx_m, wts))
        assert np.all(out[:, 0] == 0.0)
        want = np.einsum("wtk,wth->wth", wts, x)[:, 1:]
        np.testing.assert_allclose(out[:, 1:], want, atol=2e-5, rtol=2e-5)
