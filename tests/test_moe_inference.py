"""MoE serving: prefill (sorted path) + decode (packed low-latency path).

The parity property under test is the strong one: the SAME weights served
on a 1-shard mesh and a 4-shard EP mesh must produce identical greedy
generations — the EP sharding (sorted prefill dispatch, LL decode
dispatch/combine) is semantics-free. Ample capacity + the LL lossless
default make both paths drop-free, so equality is exact at the token
level."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from uccl_tpu.models.moe_inference import (
    MoEServeConfig, MoEServer, init_params,
)

CFG = MoEServeConfig(
    vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=8,
    moe_experts=8, moe_topk=2, moe_ffn=64,
)


def _serve(devices, world, params, prompt_np, new_tokens, impl):
    mesh = Mesh(np.array(devices[:world]), ("dp",))
    srv = MoEServer(CFG, mesh)
    p = srv.shard_params(params)
    b_total, s = prompt_np.shape
    b_loc = b_total // world
    prompt = jnp.asarray(prompt_np.reshape(world, b_loc, s))
    toks = srv.generate(p, prompt, new_tokens, max_seq=32, impl=impl)
    return np.asarray(toks).reshape(b_total, new_tokens)


class TestShardingParity:
    @pytest.mark.parametrize("impl", ["ll", "sort"])
    def test_generation_identical_across_worlds(self, devices, impl):
        params = init_params(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, CFG.vocab, (4, 8)).astype(np.int32)
        single = _serve(devices, 1, params, prompt, 6, impl)
        sharded = _serve(devices, 4, params, prompt, 6, impl)
        np.testing.assert_array_equal(single, sharded)

    def test_pallas_chunked_wire_generation_identical(self, devices):
        """The serving config's moe_wire/moe_chunks knobs (device-initiated
        chunk-pipelined EP wire) are semantics-free: greedy generations
        match the default lax wire token for token."""
        import dataclasses

        params = init_params(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(0)
        prompt_np = rng.integers(0, CFG.vocab, (4, 8)).astype(np.int32)
        want = _serve(devices, 4, params, prompt_np, 4, "sort")
        cfg = dataclasses.replace(CFG, moe_wire="pallas", moe_chunks=2)
        mesh = Mesh(np.array(devices[:4]), ("dp",))
        srv = MoEServer(cfg, mesh)
        p = srv.shard_params(params)
        prompt = jnp.asarray(prompt_np.reshape(4, 1, 8))
        got = srv.generate(p, prompt, 4, max_seq=32, impl="sort")
        np.testing.assert_array_equal(
            np.asarray(got).reshape(4, 4), want
        )

    def test_decode_uses_ll_and_cache_advances(self, devices):
        params = init_params(jax.random.PRNGKey(1), CFG)
        mesh = Mesh(np.array(devices[:4]), ("dp",))
        srv = MoEServer(CFG, mesh)
        p = srv.shard_params(params)
        rng = np.random.default_rng(1)
        prompt = jnp.asarray(
            rng.integers(0, CFG.vocab, (4, 1, 8)).astype(np.int32)
        )
        logits, cache = srv.prefill(p, prompt, max_seq=32)
        assert logits.shape == (4, 1, CFG.vocab)
        assert int(np.asarray(cache.length)[0]) == 8
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits2, cache2 = srv.decode_step(p, tok, cache, impl="ll")
        assert logits2.shape == (4, 1, CFG.vocab)
        assert int(np.asarray(cache2.length)[0]) == 9
        # compiled executables are cached: a second step reuses them
        n_fns = len(srv._fns)
        srv.decode_step(p, tok, cache2, impl="ll")
        assert len(srv._fns) == n_fns
