"""The overlap/async half of the DeepEP Buffer contract, TPU-dataflow form:
EventOverlap (previous_event / async_finish), two-phase receive hooks
(return_recv_hook), and Config tuning hints — checklist vs reference
ep/bench/buffer.py:285-464 (LL verbs), :801-831 (normal verbs), :741
(configs). The load-bearing assertion everywhere: overlapped execution is
bit-identical to synchronous execution."""

import dataclasses

import jax
import numpy as np
import pytest

from uccl_tpu.ep import Buffer, Config, EventOverlap
from uccl_tpu.ep import ll as ep_ll
from uccl_tpu.parallel.mesh import AXIS, MeshConfig, make_mesh

W, E, T, H = 4, 8, 16, 32


@pytest.fixture(scope="module")
def ep_mesh(devices):
    return make_mesh(MeshConfig(dp=4, tp=2), devices)


def _buffer(mesh, **kw):
    kw.setdefault("num_experts", E)
    kw.setdefault("capacity_factor", float(E))  # no drops
    return Buffer(mesh, AXIS.EP, **kw)


def _routing(rng, k=2):
    x = rng.standard_normal((W, T, H)).astype(np.float32)
    idx = rng.integers(0, E, (W, T, k)).astype(np.int32)
    if k > 1:
        idx[..., 1] = (idx[..., 0] + 1) % E
    wts = np.full((W, T, k), 1.0 / k, np.float32)
    return x, idx, wts


class TestEventOverlap:
    def test_async_finish_returns_event(self, ep_mesh, rng):
        buf = _buffer(ep_mesh)
        x, idx, wts = _routing(rng)
        recv, handle, event = buf.dispatch(
            buf.device_put(x), buf.device_put(idx), buf.device_put(wts),
            async_finish=True,
        )
        assert isinstance(event, EventOverlap)
        event.current_stream_wait()  # host barrier on the dispatch outputs
        out, ev2 = buf.combine(recv, handle, async_finish=True)
        ev2.wait()
        np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5, atol=1e-6)

    def test_previous_event_chain_matches_sync(self, ep_mesh, rng):
        """dispatch → combine(previous_event=ev) must be bit-identical to
        the plain synchronous chain — the event only orders, never alters."""
        buf = _buffer(ep_mesh)
        x, idx, wts = _routing(rng)
        gx, gidx, gwts = (
            buf.device_put(x), buf.device_put(idx), buf.device_put(wts)
        )
        recv_s, handle_s = buf.dispatch(gx, gidx, gwts)
        want = np.asarray(buf.combine(recv_s, handle_s))

        recv, handle, event = buf.dispatch(gx, gidx, gwts, async_finish=True)
        got = np.asarray(buf.combine(recv, handle, previous_event=event))
        np.testing.assert_array_equal(got, want)

    def test_allocate_on_comm_stream_precondition(self, ep_mesh, rng):
        buf = _buffer(ep_mesh)
        x, idx, wts = _routing(rng)
        with pytest.raises(ValueError, match="allocate_on_comm_stream"):
            buf.dispatch(
                buf.device_put(x), buf.device_put(idx), buf.device_put(wts),
                allocate_on_comm_stream=True,
            )


class TestRecvHook:
    def test_overlapped_dispatch_gemm_identical(self, ep_mesh, rng):
        """The verdict's acceptance test: LL dispatch issued with
        return_recv_hook, an unrelated GEMM overlapped before the hook, then
        grouped FFN + combine — outputs identical to the fully synchronous
        path."""
        buf = _buffer(ep_mesh)
        x = (rng.standard_normal((W, T, 128)) * 2).astype(np.float32)
        idx = rng.integers(0, E, (W, T, 1)).astype(np.int32)
        wts = np.ones((W, T, 1), np.float32)
        gx, gidx, gwts = (
            buf.device_put(x), buf.device_put(idx), buf.device_put(wts)
        )
        kw = dict(wire="dense", wire_fp8=False)

        # synchronous reference
        recv_s, counts_s, handle_s = buf.low_latency_dispatch(
            gx, gidx, None, gwts, **kw
        )
        want = np.asarray(buf.low_latency_combine(recv_s, handle_s))

        # overlapped: issue dispatch, run an unrelated GEMM, then hook()
        recv, counts, handle, event, hook = buf.low_latency_dispatch(
            gx, gidx, None, gwts, async_finish=True, return_recv_hook=True,
            **kw,
        )
        a = jax.numpy.asarray(rng.standard_normal((64, 64)), jax.numpy.float32)
        overlap_result = (a @ a).block_until_ready()  # unrelated compute
        assert hook is not None and event is not None
        hook()  # arrival barrier
        out, ev, hk = buf.low_latency_combine(
            recv, handle, previous_event=event, async_finish=True,
            return_recv_hook=True,
        )
        assert ev is not None and hk is not None
        hk()
        got = np.asarray(out)
        np.testing.assert_array_equal(got, want)
        assert overlap_result.shape == (64, 64)

    def test_hook_only_returns_none_event(self, ep_mesh, rng):
        buf = _buffer(ep_mesh)
        x, idx, wts = _routing(rng, k=1)
        r = buf.low_latency_dispatch(
            buf.device_put(x), buf.device_put(idx), None,
            buf.device_put(wts), wire="dense", return_recv_hook=True,
        )
        recv, counts, handle, event, hook = r
        assert event is None and callable(hook)
        hook()


class TestConfig:
    def test_tables_cover_reference_worlds(self):
        for n in (2, 4, 8, 16, 24, 32, 64, 128):
            d = Buffer.get_dispatch_config(n)
            c = Buffer.get_combine_config(n)
            assert isinstance(d, Config) and isinstance(c, Config)
            assert c.wire_fp8 is False  # combine payloads stay bf16/f32

    def test_config_applies_as_defaults(self, ep_mesh, rng):
        buf = _buffer(ep_mesh)
        x, idx, wts = _routing(rng, k=1)
        gx, gidx, gwts = (
            buf.device_put(x), buf.device_put(idx), buf.device_put(wts)
        )
        cfg = dataclasses.replace(
            Buffer.get_dispatch_config(W), wire="dense", wire_fp8=False,
            max_tokens_per_rank=T,
        )
        recv_c, counts_c, handle_c = buf.low_latency_dispatch(
            gx, gidx, None, gwts, config=cfg
        )
        recv_e, counts_e, handle_e = buf.low_latency_dispatch(
            gx, gidx, T, gwts, wire="dense", wire_fp8=False
        )
        np.testing.assert_array_equal(np.asarray(recv_c), np.asarray(recv_e))
        np.testing.assert_array_equal(
            np.asarray(counts_c), np.asarray(counts_e)
        )


class TestStatsNegativeIds:
    def test_minus_one_not_counted(self, ep_mesh, rng):
        """-1 'no expert' assignments claim no slot and must not inflate
        routed_rows as expert-0 demand (round-4 advisor finding)."""
        buf = _buffer(ep_mesh)
        x, idx, wts = _routing(rng)
        idx[:, :, 1] = -1  # half the assignments route nowhere
        buf.dispatch(
            buf.device_put(x), buf.device_put(idx), buf.device_put(wts)
        )
        s = buf.stats()
        assert s["dispatch"]["routed_rows"] == W * T  # only the k=0 column
