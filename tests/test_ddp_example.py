"""DDP example: mesh-device sync vs process-rank sync over the DCN engine.

The reference's DDP workload runs torch DDP over its NCCL plugin
(examples/ddp_train.py there); here the same example trains with replicas
as mesh devices (Communicator) OR as OS processes (compat.dist over the
engine). The decisive property: identical loss trajectories on the same
global batch — the gradient-sync substrate must be invisible to training.
"""

import os
import re
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLE = os.path.join(_REPO, "examples", "ddp_train.py")


def _run(extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DDP_CHILD_RANK", None)
    r = subprocess.run(
        [sys.executable, _EXAMPLE, "--steps", "6", "--batch", "8"] + extra,
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    return re.findall(r"step\s+(\d+) loss ([\d.]+)", r.stdout)


@pytest.mark.slow  # ~9 s subprocess example; covered by qa.sh's example
def test_process_ranks_match_mesh_trajectory():  # tier + unfiltered pytest
    mesh = _run(["--devices", "2"])
    procs = _run(["--processes", "2"])
    assert mesh and procs
    assert [s for s, _ in mesh] == [s for s, _ in procs]
    for (_, lm), (_, lp) in zip(mesh, procs):
        # same data partition + averaged grads; only collective summation
        # order differs (psum vs ring adds) — trajectories match to print
        # precision or very near it
        assert abs(float(lm) - float(lp)) < 2e-3, (mesh, procs)
