"""Ring attention / Ulysses vs the single-shard reference — exact-math checks of
the sequence/context-parallel layer on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from uccl_tpu.ops.attention import (
    attention_reference,
    ring_attention,
    ulysses_attention,
)


def _qkv(rng, b=2, s=32, h=4, hkv=None, d=16, dtype=np.float32):
    hkv = hkv or h
    q = rng.standard_normal((b, s, h, d)).astype(dtype)
    k = rng.standard_normal((b, s, hkv, d)).astype(dtype)
    v = rng.standard_normal((b, s, hkv, d)).astype(dtype)
    return q, k, v


@pytest.fixture(scope="module")
def cp_mesh(devices):
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    return make_mesh(MeshConfig(cp=4, dp=2), devices)


def _run_cp(mesh, fn, arrays, n_cp=4):
    """Run per-shard fn over the cp axis with sequence (dim 1) sharded."""
    spec = P(None, "cp", None, None)
    mapped = jax.shard_map(
        fn, mesh=mesh, in_specs=(spec,) * len(arrays), out_specs=spec, check_vma=False
    )
    return np.asarray(jax.jit(mapped)(*arrays))


class TestReference:
    def test_causal_masking(self, rng):
        q, k, v = _qkv(rng, s=8)
        out = attention_reference(q, k, v, causal=True)
        # last position attends to everything; first position only to itself
        out_nc = attention_reference(q, k, v, causal=False)
        np.testing.assert_allclose(out[:, -1], out_nc[:, -1], rtol=1e-5)
        assert not np.allclose(out[:, 0], out_nc[:, 0])

    def test_gqa(self, rng):
        q, k, v = _qkv(rng, h=8, hkv=2)
        out = attention_reference(q, k, v)
        # manual repeat must match
        k_rep = np.repeat(k, 4, axis=2)
        v_rep = np.repeat(v, 4, axis=2)
        want = attention_reference(q, jnp.asarray(k_rep), jnp.asarray(v_rep))
        np.testing.assert_allclose(out, want, rtol=1e-5)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, cp_mesh, rng, causal):
        q, k, v = _qkv(rng, s=32)
        want = np.asarray(attention_reference(q, k, v, causal=causal))
        got = _run_cp(
            cp_mesh, lambda a, b, c: ring_attention(a, b, c, "cp", causal=causal), (q, k, v)
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_gqa_ring(self, cp_mesh, rng):
        q, k, v = _qkv(rng, s=32, h=8, hkv=2)
        want = np.asarray(attention_reference(q, k, v))
        got = _run_cp(cp_mesh, lambda a, b, c: ring_attention(a, b, c, "cp"), (q, k, v))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_bf16(self, cp_mesh, rng):
        q, k, v = _qkv(rng, s=32)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        want = np.asarray(
            attention_reference(jnp.asarray(qb), jnp.asarray(kb), jnp.asarray(vb))
        ).astype(np.float32)
        got = _run_cp(
            cp_mesh, lambda a, b, c: ring_attention(a, b, c, "cp"), (qb, kb, vb)
        ).astype(np.float32)
        np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)


class TestUlysses:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, cp_mesh, rng, causal):
        q, k, v = _qkv(rng, s=32, h=8, hkv=4)
        want = np.asarray(attention_reference(q, k, v, causal=causal))
        got = _run_cp(
            cp_mesh,
            lambda a, b, c: ulysses_attention(a, b, c, "cp", causal=causal),
            (q, k, v),
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_indivisible_heads_raises(self, cp_mesh, rng):
        q, k, v = _qkv(rng, s=32, h=6)
        with pytest.raises(ValueError):
            _run_cp(cp_mesh, lambda a, b, c: ulysses_attention(a, b, c, "cp"), (q, k, v))


class TestRingFlash:
    """ring_attention(impl='flash'): Pallas blocks + LSE merge, fwd and bwd."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, cp_mesh, rng, causal):
        q, k, v = _qkv(rng, s=128, d=32)  # s_loc=32 >= min block 8
        want = np.asarray(attention_reference(q, k, v, causal=causal))
        got = _run_cp(
            cp_mesh,
            lambda a, b, c: ring_attention(a, b, c, "cp", causal=causal, impl="flash"),
            (q, k, v),
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_gqa(self, cp_mesh, rng):
        q, k, v = _qkv(rng, s=128, h=8, hkv=2, d=32)
        want = np.asarray(attention_reference(q, k, v))
        got = _run_cp(
            cp_mesh,
            lambda a, b, c: ring_attention(a, b, c, "cp", impl="flash"),
            (q, k, v),
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_grads_match_xla_ring(self, cp_mesh, rng):
        q, k, v = _qkv(rng, b=1, s=128, h=2, d=32)

        def make(impl):
            spec = P(None, "cp", None, None)
            fn = jax.shard_map(
                lambda a, b, c: ring_attention(a, b, c, "cp", impl=impl),
                mesh=cp_mesh, in_specs=(spec,) * 3, out_specs=spec,
                check_vma=False,
            )
            return jax.grad(
                lambda a, b, c: jnp.sum(jnp.sin(fn(a, b, c))), argnums=(0, 1, 2)
            )

        gf = make("flash")(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        gx = make("xla")(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        for a, b in zip(gf, gx):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5
            )

    def test_small_seq_falls_back(self, cp_mesh, rng):
        """s_loc below the minimum block size silently uses the XLA path."""
        q, k, v = _qkv(rng, s=8)  # s_loc = 2
        want = np.asarray(attention_reference(q, k, v))
        got = _run_cp(
            cp_mesh,
            lambda a, b, c: ring_attention(a, b, c, "cp", impl="flash"),
            (q, k, v),
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


class TestUlyssesFlash:
    def test_matches_reference(self, cp_mesh, rng):
        q, k, v = _qkv(rng, s=128, h=8, hkv=4, d=32)
        want = np.asarray(attention_reference(q, k, v, causal=True))
        got = _run_cp(
            cp_mesh,
            lambda a, b, c: ulysses_attention(a, b, c, "cp", impl="flash"),
            (q, k, v),
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
