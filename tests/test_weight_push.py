"""Versioned fleet weight push (ISSUE 14): codec/manifest units, loopback
publish→fetch bit-exactness + counters, the relay chain (root ships each
chunk once), and the serving/elastic consumers."""

import threading

import numpy as np
import pytest

from uccl_tpu import obs
from uccl_tpu.p2p import Channel, Endpoint, WeightPublisher
from uccl_tpu.p2p import weight_push as wp


def chan_pair(server_ep, client_ep, n_paths=2):
    res = {}
    t = threading.Thread(
        target=lambda: res.setdefault("c", Channel.accept(server_ep)))
    t.start()
    c = Channel.connect(client_ep, "127.0.0.1", server_ep.port,
                        n_paths=n_paths)
    t.join(timeout=20)
    assert "c" in res, "channel accept timed out"
    return res["c"], c


def small_tree(rng, scale=1):
    return {
        "layers": [
            {"w": rng.standard_normal((32 * scale, 16)).astype(np.float32),
             "b": rng.standard_normal(16).astype(np.float32)}
            for _ in range(2)
        ],
        "emb": rng.standard_normal((64, 8)).astype(np.float32),
        "step": np.asarray([42], np.int64),
    }


def trees_equal(a, b):
    fa = {k: v for k, v in wp.flatten_tree(a)}
    fb = {k: v for k, v in wp.flatten_tree(b)}
    return (set(fa) == set(fb)
            and all(np.array_equal(fa[k], fb[k]) for k in fa))


class TestTreeCodec:
    def test_flatten_unflatten_roundtrip(self, rng):
        tree = small_tree(rng)
        pairs = wp.flatten_tree(tree)
        assert [k for k, _ in pairs] == sorted(k for k, _ in pairs)
        rebuilt = wp.unflatten_tree(dict(pairs))
        assert isinstance(rebuilt["layers"], list)
        assert trees_equal(tree, rebuilt)

    def test_empty_tree_rejected(self):
        with pytest.raises(ValueError):
            wp.flatten_tree({})
        with pytest.raises(ValueError):
            wp.flatten_tree({"a": {}})

    def test_manifest_groups_cover_everything(self, rng):
        pub = WeightPublisher(group_bytes=1024)
        pub.publish("m", small_tree(rng))
        snap = pub.get("m")
        ents = snap.manifest["entries"]
        covered = []
        for g, (lo, hi) in enumerate(snap.manifest["groups"]):
            covered.extend(range(lo, hi))
            a, b = snap.group_range(g)
            assert b > a
            assert snap.group_crc(g) == snap.manifest["group_crcs"][g]
        assert covered == list(range(len(ents)))
        total = sum(int(e["nbytes"]) for e in ents)
        assert total == snap.total_bytes == snap.buf.nbytes

    def test_versioning_and_eviction(self, rng):
        pub = WeightPublisher(keep_versions=2)
        t = small_tree(rng)
        assert pub.publish("m", t) == 1
        assert pub.publish("m", t) == 2
        assert pub.publish("m", t) == 3
        assert pub.get("m").version == 3
        assert pub.get("m", 2).version == 2
        with pytest.raises(KeyError):  # evicted by keep_versions=2
            pub.get("m", 1)
        with pytest.raises(KeyError):
            pub.get("nope")
        with pytest.raises(ValueError):
            pub.publish("m", t, version=3)  # already published

    def test_fp8_wire_canonicalized_once(self, rng):
        """The published fp8 version is its own canonical bytes: decode
        is deterministic and within the codec's round trip of the
        input; non-float leaves ship raw (bit-exact)."""
        pub = WeightPublisher()
        tree = small_tree(rng)
        pub.publish("m", tree, wire="fp8")
        flat = pub.get("m").flat()
        assert np.array_equal(flat["step"], tree["step"])  # raw non-float
        w = tree["layers"][0]["w"]
        got = flat["layers.0.w"]
        assert not np.array_equal(got, w)  # lossy...
        np.testing.assert_allclose(got, w, rtol=0.2, atol=0.1)  # ...bounded
        with pytest.raises(ValueError):
            pub.publish("m2", tree, wire="nope")


class TestLoopback:
    def test_publish_fetch_bit_exact_with_counters(self, rng):
        pub = WeightPublisher(group_bytes=8 << 10)
        tree = small_tree(rng)
        v = pub.publish("model", tree)
        rx0 = obs.counter("weight_push_bytes_total").get(role="rx",
                                                         name="model")
        peers0 = obs.counter("weight_push_peers_total").get(name="model")
        verb0 = obs.counter("p2p_bytes_total").get(verb="weight_push")
        with Endpoint(n_engines=2) as pep, Endpoint(n_engines=2) as sep:
            srv, cli = chan_pair(pep, sep)
            t = threading.Thread(target=lambda: pub.serve(srv))
            t.start()
            snap = wp.fetch(cli, "model")
            t.join(timeout=20)
        assert snap.version == v
        assert trees_equal(snap.tree(), tree)
        total = snap.total_bytes
        assert obs.counter("weight_push_bytes_total").get(
            role="rx", name="model") == rx0 + total
        assert obs.counter("weight_push_peers_total").get(
            name="model") == peers0 + 1
        assert obs.counter("p2p_bytes_total").get(
            verb="weight_push") >= verb0 + total

    def test_fetch_unknown_name_fails_loudly(self, rng):
        pub = WeightPublisher()
        pub.publish("model", small_tree(rng))
        with Endpoint(n_engines=2) as pep, Endpoint(n_engines=2) as sep:
            srv, cli = chan_pair(pep, sep)
            err = []

            def serve():
                try:
                    pub.serve(srv)
                except KeyError as e:
                    err.append(e)

            t = threading.Thread(target=serve)
            t.start()
            with pytest.raises(Exception):
                wp.fetch(cli, "other", timeout_ms=3000)
            t.join(timeout=20)
            assert err  # server named the missing snapshot


@pytest.mark.slow
class TestRelayChain:
    @pytest.mark.parametrize("wire", [None, "fp8"])
    def test_three_peer_chain_bit_exact(self, rng, wire):
        """root -> s1 -> s2 -> s3: every peer bit-exact vs the PUBLISHED
        version, and the root's counted egress stays ONE snapshot (the
        peers forwarded the rest)."""
        pub = WeightPublisher(group_bytes=16 << 10)
        tree = small_tree(rng, scale=4)
        pub.publish("m", tree, wire=wire)
        canon = pub.get("m").flat()
        fam = obs.counter("weight_push_bytes_total")
        root0 = fam.get(role="tx", name="m", src="publisher")
        eps = [Endpoint(n_engines=2) for _ in range(4)]
        try:
            d0, u1 = chan_pair(eps[0], eps[1])
            d1, u2 = chan_pair(eps[1], eps[2])
            d2, u3 = chan_pair(eps[2], eps[3])
            snaps = {}

            def node(i, up, downs):
                snaps[i] = wp.fetch(up, "m", forward_to=downs)

            ts = [threading.Thread(target=node, args=(1, u1, [d1])),
                  threading.Thread(target=node, args=(2, u2, [d2])),
                  threading.Thread(target=node, args=(3, u3, []))]
            for t in ts:
                t.start()
            pub.serve(d0)
            for t in ts:
                t.join(timeout=60)
            assert sorted(snaps) == [1, 2, 3]
            for i in (1, 2, 3):
                flat = snaps[i].flat()
                assert all(np.array_equal(flat[k], canon[k])
                           for k in canon), f"peer {i} diverged"
            snap = pub.get("m")
            assert fam.get(role="tx", name="m", src="publisher") \
                == root0 + snap.total_bytes
        finally:
            for ep in eps:
                ep.close()


class TestConsumers:
    def test_replicate_backend_serves_pushed_version(self, rng):
        """replicate_backend(weights=) spins every replica up on the
        fetched tree (structure-validated), sharing the prototype's
        compiled-fn cache."""
        import jax

        from uccl_tpu.models import dense
        from uccl_tpu.serving.engine import DenseBackend, replicate_backend

        cfg = dense.DenseConfig(vocab=32, dim=16, n_layers=1, n_heads=2,
                                n_kv_heads=1, head_dim=8, ffn=32)
        params = dense.init_params(jax.random.PRNGKey(0), cfg)
        backend = DenseBackend(params, cfg, n_slots=2, max_seq=16)
        pub = WeightPublisher()
        pub.publish("dense", jax.tree_util.tree_map(np.asarray, params))
        reps = replicate_backend(backend, 2, weights=pub.get("dense"))
        assert len(reps) == 2
        assert reps[0]._fns is reps[1]._fns is backend._fns
        for a, b in zip(jax.tree_util.tree_leaves(reps[1].params),
                        jax.tree_util.tree_leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # structure mismatches fail loudly before any replica serves
        bad = {"not": np.zeros(3, np.float32)}
        with pytest.raises(ValueError):
            replicate_backend(backend, 2, weights=bad)

    def test_warm_spare_admit_counts_weight_push_bytes(self, rng):
        """ep/elastic warm-spare admission: a snapshot import rides the
        fetch's counted bytes; a raw-tree import (the legacy untracked
        copy) is counted HERE on p2p_bytes_total{verb=weight_push}."""
        from uccl_tpu.ep.elastic import ElasticBuffer, admit_warm_spare

        tree = {"w": rng.standard_normal((16, 16)).astype(np.float32)}
        buf = ElasticBuffer(1 << 20)
        before = obs.counter("p2p_bytes_total").get(verb="weight_push")
        n = admit_warm_spare(buf, tree)
        assert n == 16 * 16 * 4
        assert obs.counter("p2p_bytes_total").get(
            verb="weight_push") == before + n
        assert buf.names() == ["w"]
        pub = WeightPublisher()
        pub.publish("m", tree)
        before = obs.counter("p2p_bytes_total").get(verb="weight_push")
        admit_warm_spare(buf, pub.get("m"), prefix="v1.")
        # snapshot bytes were counted at fetch time, not re-counted here
        assert obs.counter("p2p_bytes_total").get(
            verb="weight_push") == before
        np.testing.assert_array_equal(
            np.asarray(buf.get("v1.w")), tree["w"])


class TestResume:
    def test_fault_injected_fetch_resumes_by_group_crc(self, rng):
        """A fetch killed mid-transfer by native data-plane loss resumes
        off its FetchError.partial: already-verified groups are skipped
        (CRC-guarded, counted on weight_push_resumed_groups_total) and
        the completed snapshot is bit-exact vs the published version."""
        pub = WeightPublisher(group_bytes=1024)
        tree = small_tree(rng)
        pub.publish("model", tree)
        n_groups = len(pub.get("model").manifest["groups"])
        assert n_groups >= 3, "resume needs several groups to matter"
        with Endpoint(n_engines=2) as pep, Endpoint(n_engines=2) as sep:
            srv, cli = chan_pair(pep, sep)
            srv.retries = 0  # the serve side gives up fast under loss

            def serve_once():
                try:
                    pub.serve(srv, timeout_ms=4000)
                except Exception:
                    pass  # the faulted serve dies; counted server-side

            t = threading.Thread(target=serve_once)
            t.start()

            # after group 0 verifies, total data-plane loss: the serve
            # side's windowed writev exhausts its attempts and dies, our
            # fetch times out — deterministically partial
            def on_group(g):
                if g == 0:
                    pep.set_drop_rate(1.0)

            with pytest.raises(wp.FetchError) as ei:
                wp.fetch(cli, "model", timeout_ms=1500,
                         on_group=on_group)
            t.join(timeout=30)
            pep.set_drop_rate(0.0)
            err = ei.value
            assert err.partial is not None
            assert 1 <= len(err.groups_ok) < n_groups
            assert err.groups_ok[0] == 0

            # retry with resume: only the missing groups cross the wire
            res0 = obs.counter("weight_push_resumed_groups_total").get()
            rx0 = obs.counter("weight_push_bytes_total").get(
                role="rx", name="model")
            srv2, cli2 = chan_pair(pep, sep)
            t2 = threading.Thread(target=lambda: pub.serve(srv2))
            t2.start()
            snap = wp.fetch(cli2, "model", resume=err.partial)
            t2.join(timeout=30)
            assert trees_equal(snap.tree(), tree)
            skipped = obs.counter(
                "weight_push_resumed_groups_total").get() - res0
            assert skipped == len(err.groups_ok)
            # rx bytes on the resumed fetch exclude the skipped groups
            rx = obs.counter("weight_push_bytes_total").get(
                role="rx", name="model") - rx0
            skipped_bytes = sum(
                snap.group_range(g)[1] - snap.group_range(g)[0]
                for g in err.groups_ok
            )
            assert rx == snap.total_bytes - skipped_bytes

    def test_resume_against_different_version_falls_back_full(self, rng):
        """A stale partial (the publisher moved on) matches nothing: the
        fetch silently degrades to a full transfer, still bit-exact."""
        pub = WeightPublisher(group_bytes=1024)
        tree = small_tree(rng)
        pub.publish("model", tree)
        stale_man = dict(pub.get("model").manifest, version=99)
        stale = wp.WeightSnapshot(stale_man,
                                  pub.get("model").buf.copy())
        res0 = obs.counter("weight_push_resumed_groups_total").get()
        with Endpoint(n_engines=2) as pep, Endpoint(n_engines=2) as sep:
            srv, cli = chan_pair(pep, sep)
            t = threading.Thread(target=lambda: pub.serve(srv))
            t.start()
            snap = wp.fetch(cli, "model", resume=stale)
            t.join(timeout=20)
        assert trees_equal(snap.tree(), tree)
        assert obs.counter(
            "weight_push_resumed_groups_total").get() == res0
