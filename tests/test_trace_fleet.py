"""Fleet-wide distributed tracing + merge-safe telemetry (PR 12).

Host-only fast tier: the clock-offset estimator (symmetric/asymmetric
RTT, negative offsets), trace-context propagation through an IN-PROCESS
disagg pair over a jax-free stub backend (the full BEGIN/GRANT/FINAL
control plane, clock handshake and flow events without a single
compile), clock-aligned trace merging on synthetic skewed-clock files,
the pull-based metrics federator (files AND live /metrics scrapes), and
the ephemeral MetricsServer. The 2-real-process end-to-end arm
(example -> trace_merge -> aggregate -> check_obs --fleet) is marked
``slow`` — qa.sh/CI run it unfiltered, tier-1 keeps its budget.
"""

import importlib.util
import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from uccl_tpu import obs
from uccl_tpu.serving import ServingEngine

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    """Import a scripts/*.py module by path (scripts/ is not a package)."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def tracer():
    t = obs.enable_tracing(65536)
    yield t
    obs.disable_tracing()


class TestClockOffset:
    def test_symmetric_rtt_exact(self):
        # true offset +10ms, 2ms each way, 3ms peer processing
        t0, d, proc, off = 100.0, 0.002, 0.003, 0.010
        t1 = t0 + d + off
        t2 = t1 + proc
        t3 = t2 - off + d
        est, rtt = obs.estimate_clock_offset(t0, t1, t2, t3)
        assert abs(est - off) < 1e-12
        assert abs(rtt - 2 * d) < 1e-12

    def test_negative_offset_exact(self):
        t0, d, off = 50.0, 0.001, -0.25  # peer clock 250ms BEHIND
        t1 = t0 + d + off
        t2 = t1 + 0.004
        t3 = t2 - off + d
        est, rtt = obs.estimate_clock_offset(t0, t1, t2, t3)
        assert abs(est - off) < 1e-12 and rtt > 0

    def test_asymmetric_rtt_error_bounded_by_half_rtt(self):
        # 1ms out, 7ms back: the midpoint assumption is wrong by
        # (back - out) / 2 = 3ms, always within rtt / 2 = 4ms
        t0, out, back, off = 0.0, 0.001, 0.007, 0.020
        t1 = t0 + out + off
        t2 = t1 + 0.002
        t3 = t2 - off + back
        est, rtt = obs.estimate_clock_offset(t0, t1, t2, t3)
        assert abs(rtt - (out + back)) < 1e-12
        assert abs(est - off) <= rtt / 2 + 1e-12
        assert abs(est - off) == pytest.approx((back - out) / 2)


class TestTraceContext:
    def test_mint_unique_and_counted(self):
        c = obs.counter("obs_trace_contexts_total")
        before = c.get()
        a, b = obs.new_context(), obs.new_context()
        assert a.trace_id != b.trace_id
        assert len(a.trace_id) == 16 and len(a.span_id) == 8
        assert c.get() == before + 2

    def test_wire_roundtrip_and_malformed(self):
        from uccl_tpu.obs import TraceContext

        ctx = obs.new_context()
        assert TraceContext.from_wire(ctx.to_wire()) == ctx
        assert TraceContext.from_wire(None) is None
        assert TraceContext.from_wire({"trace_id": ""}) is None

    def test_flow_id_deterministic_int(self):
        ctx = obs.new_context()
        assert obs.flow_id(ctx.trace_id) == obs.flow_id(ctx.trace_id)
        assert isinstance(obs.flow_id(ctx.trace_id), int)

    def test_engine_submit_stamps_and_router_propagates(self):
        from uccl_tpu.serving import Router

        engines = [ServingEngine(_StubKVBackend()) for _ in range(2)]
        r = Router(engines)
        req = r.submit([1, 2, 3], max_new_tokens=2)
        assert req.trace_id is not None and req.span_id is not None
        r.drain()
        r.close()


class _StubKVBackend:
    """Slot-pool backend with the disagg KV-movement surface but no jax:
    prefill emits 0, the i-th decode step emits i, exported KV rows are
    zeros shaped by a tiny fixed config — enough for the FULL disagg
    control plane (BEGIN/GRANT/stream/FINAL/adopt) to run over loopback
    endpoints in milliseconds."""

    class _Cfg:
        n_layers = 1
        n_kv_heads = 1
        head_dim = 2

    cfg = _Cfg()

    def __init__(self, n_slots=2, max_seq=32):
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.n_decodes = 0

    def prefill(self, tokens, lens, mask, start=None):
        return np.zeros(self.n_slots, np.int32)

    def decode(self, tokens, active):
        self.n_decodes += 1
        return np.full(self.n_slots, self.n_decodes, np.int32)

    def export_slot_kv(self, slot, lo, hi):
        shape = (1, hi - lo, 1, 2)
        return (np.zeros(shape, np.float32), np.zeros(shape, np.float32))

    def import_slot_kv(self, slot, k_rows, v_rows, *, length):
        pass

    def copy_slot_prefix(self, dst, src, n):
        pass


class TestDisaggTracePropagation:
    def _drive_pair(self):
        from uccl_tpu.serving.disagg import make_local_pair

        pe = ServingEngine(_StubKVBackend(), prefill_chunk=4)
        de = ServingEngine(_StubKVBackend())
        pw, dw = make_local_pair(pe, de)
        return pw, dw

    def test_context_rides_begin_and_flows_bind(self, tracer):
        pw, dw = self._drive_pair()
        try:
            req = pw.submit(np.arange(8, dtype=np.int32),
                            max_new_tokens=3)
            assert req.trace_id is not None
            done = []
            deadline = time.monotonic() + 30.0
            while len(done) < 1:
                pw.step()
                done.extend(dw.step())
                assert time.monotonic() < deadline, "pair stalled"
            pw.drain()
            # the decode-side request carries the SAME trace id the
            # prefill ingress minted — one timeline across "processes"
            assert done[0].trace_id == req.trace_id
            evs = tracer.events()
            grant = [e for e in evs if e.name == "grant"]
            assert grant and grant[0].args["trace_id"] == req.trace_id
            adopt = [e for e in evs if e.name == "adopt"]
            assert adopt and adopt[0].args["trace_id"] == req.trace_id
            # flow pair: s inside kv_stream.tx, f inside kv_stream.import,
            # one shared id derived from the trace id
            fid = obs.flow_id(req.trace_id)
            s = [e for e in evs if e.ph == "s" and e.fid == fid]
            f = [e for e in evs if e.ph == "f" and e.fid == fid]
            assert len(s) == 1 and len(f) == 1
            tx = [e for e in evs if e.name == "kv_stream.tx"]
            imp = [e for e in evs if e.name == "kv_stream.import"]
            assert tx[0].args["trace_id"] == req.trace_id
            assert imp[0].args["trace_id"] == req.trace_id
            # s/f timestamps sit INSIDE their spans (Perfetto binding)
            assert tx[0].ts_us <= s[0].ts_us <= tx[0].ts_us + tx[0].dur_us
            assert (imp[0].ts_us <= f[0].ts_us
                    <= imp[0].ts_us + imp[0].dur_us)
        finally:
            pw.ep.close()
            dw.ep.close()

    def test_clock_handshake_syncs_both_sides(self, tracer):
        pw, dw = self._drive_pair()
        try:
            # pump until ping -> pong -> sync lands on both sides (the
            # native notif plane delivers asynchronously, so this is a
            # deadline poll, not a fixed iteration count)
            deadline = time.monotonic() + 30.0
            while (pw.clock_rtt_s is None or dw.clock_offset_us is None):
                pw.step()
                dw.step()
                time.sleep(0.001)
                assert time.monotonic() < deadline, "clock sync stalled"
            assert pw.clock_rtt_s is not None and pw.clock_rtt_s >= 0
            assert pw.clock_offset_s is not None
            assert dw.clock_offset_us is not None
            # in-process loopback: both clocks are the same clock, so the
            # estimate must be tiny (bounded by the measured rtt)
            assert abs(pw.clock_offset_s) <= max(pw.clock_rtt_s, 1e-4)
            # the decode "process" recorded its offset in trace metadata
            assert tracer.clock_meta.get("peer") in ("prefill", "decode")
        finally:
            pw.ep.close()
            dw.ep.close()


def _synthetic_role_traces(skew_us: float, grant_before_begin=False):
    """Two per-role trace dicts with WILDLY skewed wall clocks whose
    alignment metadata (wall anchor + estimated offset) brings them onto
    one timeline. trace_id 'deadbeefcafe0123'; flow ids per obs.flow_id."""
    tid = "deadbeefcafe0123"
    fid = obs.flow_id(tid)

    def meta(pid_name):
        return [{"name": "process_name", "ph": "M", "pid": 1,
                 "args": {"name": pid_name}},
                {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
                 "args": {"name": "wire"}}]

    prefill = {
        "traceEvents": meta("uccl_tpu.prefill") + [
            {"name": "submit", "ph": "i", "pid": 1, "tid": 1, "ts": 100.0,
             "s": "t", "args": {"trace_id": tid}},
            {"name": "kv_stream.tx", "ph": "X", "pid": 1, "tid": 1,
             "ts": 200.0, "dur": 50.0, "args": {"trace_id": tid}},
            {"name": "kv_handoff", "ph": "s", "pid": 1, "tid": 1,
             "ts": 225.0, "cat": "flow", "id": fid},
        ],
        "otherData": {"clock": {"wall_epoch_us": 1_000_000.0,
                                "offset_us": 0.0}},
    }
    # decode's wall clock reads `skew_us` ahead; its HELLO-estimated
    # offset records exactly that, so alignment subtracts it back out
    grant_ts = 50.0 if grant_before_begin else 400.0
    decode = {
        "traceEvents": meta("uccl_tpu.decode") + [
            {"name": "grant", "ph": "i", "pid": 1, "tid": 1,
             "ts": grant_ts, "s": "t", "args": {"trace_id": tid}},
            {"name": "kv_stream.import", "ph": "X", "pid": 1, "tid": 1,
             "ts": 500.0, "dur": 40.0, "args": {"trace_id": tid}},
            {"name": "kv_handoff", "ph": "f", "pid": 1, "tid": 1,
             "ts": 520.0, "cat": "flow", "id": fid, "bp": "e"},
            {"name": "adopt", "ph": "i", "pid": 1, "tid": 1, "ts": 560.0,
             "s": "t", "args": {"trace_id": tid}},
        ],
        "otherData": {"clock": {"wall_epoch_us": 1_000_000.0 + skew_us,
                                "offset_us": skew_us}},
    }
    return prefill, decode


class TestTraceMerge:
    def _write(self, tmp_path, *traces):
        paths = []
        for i, t in enumerate(traces):
            p = tmp_path / f"role{i}.json"
            p.write_text(json.dumps(t))
            paths.append(str(p))
        return paths

    def test_skewed_clocks_align_and_flows_resolve(self, tmp_path):
        tm = _load_script("trace_merge")
        # half a second of wall skew — hopeless without alignment
        paths = self._write(tmp_path,
                            *_synthetic_role_traces(skew_us=500_000.0))
        merged = tm.merge_traces(paths)
        stats = tm.validate_merged(merged)
        assert stats["cross_process_requests"] == 1
        assert stats["trace_ids"] == 1
        by = {(e["name"], e["pid"]): e for e in merged["traceEvents"]
              if e.get("ph") in ("i", "X")}
        # after alignment the decode events sit on the prefill timeline
        assert by[("submit", 1)]["ts"] == 100.0
        assert by[("grant", 2)]["ts"] == 400.0  # skew removed exactly
        assert by[("submit", 1)]["ts"] <= by[("grant", 2)]["ts"] \
            <= by[("adopt", 2)]["ts"]
        # pids were re-homed per file and named
        names = {m["pid"]: m["process_name"]
                 for m in merged["otherData"]["merged_from"]}
        assert names == {1: "uccl_tpu.prefill", 2: "uccl_tpu.decode"}

    def test_causal_violation_is_a_named_failure(self, tmp_path):
        tm = _load_script("trace_merge")
        paths = self._write(
            tmp_path,
            *_synthetic_role_traces(skew_us=0.0, grant_before_begin=True),
        )
        merged = tm.merge_traces(paths)
        with pytest.raises(SystemExit):
            tm.validate_merged(merged)

    def test_unanchored_trace_strict_vs_degraded(self, tmp_path):
        tm = _load_script("trace_merge")
        p = tmp_path / "bare.json"
        p.write_text(json.dumps({"traceEvents": []}))
        # --strict keeps the old hard fail; the default degrades to an
        # unadjusted merge (a worker dying before its clock exchange no
        # longer loses the whole fleet view)
        with pytest.raises(SystemExit):
            tm.load_trace(str(p), strict=True)
        trace = tm.load_trace(str(p))
        assert trace["_anchored"] is False

    def test_real_tracer_roundtrip_merges(self, tmp_path, tracer):
        """Two dumps of REAL tracers (one re-homed by a synthetic offset)
        merge and validate — the exporter's clock metadata and the merge
        tool agree on field names end to end."""
        from uccl_tpu.obs import chrome_trace

        tm = _load_script("trace_merge")
        ctx = obs.new_context()
        obs.instant("submit", track="req", trace_id=ctx.trace_id)
        p1 = str(tmp_path / "a.json")
        chrome_trace.dump(p1, process_name="uccl_tpu.prefill")
        tracer.clear()
        obs.set_clock_offset(123_456.0, rtt_us=40.0, peer="prefill")
        tracer.wall_epoch_us += 123_456.0  # pretend a skewed host
        obs.instant("grant", track="wire", trace_id=ctx.trace_id)
        obs.instant("adopt", track="req", trace_id=ctx.trace_id)
        p2 = str(tmp_path / "b.json")
        chrome_trace.dump(p2, process_name="uccl_tpu.decode")
        merged = tm.merge_traces([p1, p2])
        stats = tm.validate_merged(merged)  # causal order must hold
        assert stats["trace_ids"] == 1


class TestAggregate:
    def _texts(self):
        r1 = obs.Registry()
        r2 = obs.Registry()
        for reg, vals in ((r1, [0.001, 0.02]), (r2, [0.3])):
            h = reg.histogram("serving_ttft_seconds",
                              buckets=[0.01, 0.1, 1.0])
            for v in vals:
                h.observe(v)
            reg.counter("requests_total").inc(len(vals))
            reg.gauge("occupancy").set(0.5)
        return obs.prometheus_text(r1), obs.prometheus_text(r2)

    def test_counters_and_histograms_sum_gauges_stay_per_replica(self):
        from uccl_tpu.obs import aggregate as agg

        t1, t2 = self._texts()
        a = agg.aggregate([("p", t1), ("d", t2)])
        text = agg.fleet_text(a)
        assert "requests_total 3" in text  # fleet sum
        assert 'requests_total{replica="p"} 2' in text
        assert 'serving_ttft_seconds_count 3' in text
        assert 'serving_ttft_seconds_bucket{le="0.01",replica="p"} 1' \
            in text
        # gauges: per-replica only, never a fleet sum line
        assert 'occupancy{replica="p"} 0.5' in text
        assert "\noccupancy 1" not in text
        # fleet quantile off the summed buckets: the fleet median sample
        # (0.02) lies in bucket (0.01, 0.1] — the estimate must too
        assert 0.01 < agg.fleet_quantile(a, "serving_ttft_seconds", 50) \
            <= 0.1
        assert agg.fleet_quantile(a, "serving_ttft_seconds", 50,
                                  replica="d") > 0.1

    def test_type_conflict_rejected(self):
        from uccl_tpu.obs import aggregate as agg

        with pytest.raises(ValueError):
            agg.aggregate([
                ("a", "# TYPE x counter\nx 1\n"),
                ("b", "# TYPE x gauge\nx 1\n"),
            ])

    def test_http_pull_path(self):
        """The federator really PULLS: two live MetricsServers on
        ephemeral ports (the port=0 satellite — no port race on one
        host), scraped over HTTP and summed."""
        from uccl_tpu.obs import aggregate as agg

        regs = [obs.Registry(), obs.Registry()]
        for i, reg in enumerate(regs):
            reg.counter("pulled_total").inc(i + 1)
        servers = [obs.MetricsServer(0, registry=reg) for reg in regs]
        try:
            assert servers[0].port != servers[1].port
            scrapes = [
                (f"r{i}",
                 agg.scrape(f"http://127.0.0.1:{s.port}/metrics"))
                for i, s in enumerate(servers)
            ]
            a = agg.aggregate(scrapes)
            assert agg.fleet_text(a).splitlines().count(
                "pulled_total 3") == 1
        finally:
            for s in servers:
                s.close()

    def test_cli_on_files(self, tmp_path):
        from uccl_tpu.obs import aggregate as agg

        t1, t2 = self._texts()
        p1, p2 = tmp_path / "a.prom", tmp_path / "b.prom"
        p1.write_text(t1)
        p2.write_text(t2)
        out = tmp_path / "fleet.prom"
        assert agg.main([f"p={p1}", f"d={p2}", "--out", str(out)]) == 0
        assert "requests_total 3" in out.read_text()


class TestMetricsServerEphemeral:
    def test_default_port_is_ephemeral_and_reported(self):
        a = obs.MetricsServer()
        b = obs.MetricsServer()  # second worker on the same host: no race
        try:
            assert a.port > 0 and b.port > 0 and a.port != b.port
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{a.port}/metrics", timeout=5
            ).read().decode()
            assert "obs_trace_dropped_total" in body
        finally:
            a.close()
            b.close()


# ~40s wall (two fresh jax processes + compiles): slow-marked so tier-1
# keeps its budget; qa.sh and the unfiltered CI pytest job run it on
# every change, and the dedicated qa/ci fleet smoke arm runs the same
# pipeline against the shared example artifacts.
@pytest.mark.slow
def test_fleet_smoke_end_to_end(tmp_path):
    """2 real processes -> per-role dumps -> clock-aligned merge ->
    federated metrics -> check_obs --fleet: >= 1 flow-linked
    cross-process request timeline, causally ordered, and fleet TTFT
    histogram percentiles within one bucket width of the per-replica
    sample-derived ones."""
    env = dict(os.environ, UCCL_TPU_EXAMPLE_CPU="1", JAX_PLATFORMS="cpu")
    trace = tmp_path / "fleet.json"
    metrics = tmp_path / "fleet.prom"
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", "disagg_kv.py"),
         "--cpu", "--trace-out", str(trace), "--metrics-out", str(metrics)],
        capture_output=True, text=True, timeout=420, env=env, cwd=_REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    merged = tmp_path / "merged.json"
    fleet = tmp_path / "fleet_agg.prom"
    for cmd in (
        [sys.executable, os.path.join(_REPO, "scripts", "trace_merge.py"),
         "--out", str(merged), str(trace),
         str(tmp_path / "fleet.decode.json")],
        [sys.executable, "-m", "uccl_tpu.obs.aggregate", "--out",
         str(fleet), f"prefill={metrics}",
         f"decode={tmp_path / 'fleet.decode.prom'}"],
        [sys.executable, os.path.join(_REPO, "scripts", "check_obs.py"),
         "--fleet", str(merged), str(fleet)],
    ):
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=120, env=env, cwd=_REPO)
        assert r.returncode == 0, (cmd, r.stdout, r.stderr)
    stats = json.loads(merged.read_text())["otherData"]["stats"]
    assert stats["cross_process_requests"] >= 1
