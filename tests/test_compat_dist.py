"""torch.distributed-shaped compat API — multiprocess, the way reference
users launch (one process per rank)."""

import multiprocessing as mp
import socket

import numpy as np
import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _rank_main(rank, world, port, q):
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from uccl_tpu.compat import dist

    dist.init_process_group(rank, world, master_port=port)
    assert dist.is_initialized()
    assert dist.get_rank() == rank and dist.get_world_size() == world

    x = np.full(8, float(rank + 1), np.float32)
    dist.all_reduce(x)

    g = np.full(4, float(rank), np.float32)
    outs = [np.zeros(4, np.float32) for _ in range(world)]
    dist.all_gather(outs, g)

    b = np.full(3, float(rank), np.float32)
    dist.broadcast(b, src=1)

    dist.barrier()
    q.put((rank, x.copy(), [o.copy() for o in outs], b.copy()))
    dist.destroy_process_group()


def test_process_group_end_to_end():
    world = 2
    port = _free_port()
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_rank_main, args=(r, world, port, q))
        for r in range(world)
    ]
    [p.start() for p in procs]
    results = {}
    for _ in procs:
        rank, x, outs, b = q.get(timeout=120)
        results[rank] = (x, outs, b)
    [p.join(timeout=60) for p in procs]
    for rank in range(world):
        x, outs, b = results[rank]
        np.testing.assert_array_equal(x, np.full(8, 3.0))  # 1 + 2
        for i in range(world):
            np.testing.assert_array_equal(outs[i], np.full(4, float(i)))
        np.testing.assert_array_equal(b, np.full(3, 1.0))  # src=1


def test_requires_init():
    from uccl_tpu.compat import dist

    if not dist.is_initialized():
        with pytest.raises(RuntimeError):
            dist.get_rank()
