"""torch.distributed-shaped compat API — multiprocess, the way reference
users launch (one process per rank)."""

import multiprocessing as mp
import socket

import numpy as np
import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _rank_main(rank, world, port, q):
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from uccl_tpu.compat import dist

    dist.init_process_group(rank, world, master_port=port)
    assert dist.is_initialized()
    assert dist.get_rank() == rank and dist.get_world_size() == world

    x = np.full(8, float(rank + 1), np.float32)
    dist.all_reduce(x)

    g = np.full(4, float(rank), np.float32)
    outs = [np.zeros(4, np.float32) for _ in range(world)]
    dist.all_gather(outs, g)

    b = np.full(3, float(rank), np.float32)
    dist.broadcast(b, src=1)

    dist.barrier()
    q.put((rank, x.copy(), [o.copy() for o in outs], b.copy()))
    dist.destroy_process_group()


def test_process_group_end_to_end():
    world = 2
    port = _free_port()
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_rank_main, args=(r, world, port, q))
        for r in range(world)
    ]
    [p.start() for p in procs]
    results = {}
    for _ in procs:
        rank, x, outs, b = q.get(timeout=120)
        results[rank] = (x, outs, b)
    [p.join(timeout=60) for p in procs]
    for rank in range(world):
        x, outs, b = results[rank]
        np.testing.assert_array_equal(x, np.full(8, 3.0))  # 1 + 2
        for i in range(world):
            np.testing.assert_array_equal(outs[i], np.full(4, float(i)))
        np.testing.assert_array_equal(b, np.full(3, 1.0))  # src=1


def test_requires_init():
    from uccl_tpu.compat import dist

    if not dist.is_initialized():
        with pytest.raises(RuntimeError):
            dist.get_rank()


def _rank_main_jax(rank, world, port, q):
    import os
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from uccl_tpu.compat import dist

    dist.init_process_group(rank, world, master_port=port)

    # device arrays: functional return, placed like the input
    x = jnp.full((8,), float(rank + 1), jnp.float32)
    y = dist.all_reduce(x)
    assert isinstance(y, jax.Array) and y.sharding == x.sharding

    g = jnp.full((4,), float(rank), jnp.float32)
    outs = dist.all_gather(None, g)
    assert all(isinstance(o, jax.Array) for o in outs)

    b = jnp.full((3,), float(rank), jnp.float32)
    bb = dist.broadcast(b, src=1)

    a2a = dist.all_to_all(None, jnp.arange(world, dtype=jnp.float32) + rank)

    dist.barrier()
    q.put((
        rank, np.asarray(y), [np.asarray(o) for o in outs], np.asarray(bb),
        np.asarray(a2a),
    ))
    dist.destroy_process_group()


def test_process_group_jax_arrays():
    """Device arrays through the same verbs (VERDICT round-2 weak #6: the
    compat face must back a real DDP step on device values, not just
    host buffers)."""
    world = 2
    port = _free_port()
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_rank_main_jax, args=(r, world, port, q))
        for r in range(world)
    ]
    [p.start() for p in procs]
    results = {}
    for _ in procs:
        rank, y, outs, b, a2a = q.get(timeout=180)
        results[rank] = (y, outs, b, a2a)
    [p.join(timeout=60) for p in procs]
    for rank in range(world):
        y, outs, b, a2a = results[rank]
        np.testing.assert_array_equal(y, np.full(8, 3.0))
        for i in range(world):
            np.testing.assert_array_equal(outs[i], np.full(4, float(i)))
        np.testing.assert_array_equal(b, np.full(3, 1.0))
        # all_to_all: row j of rank r's input (= j + r) lands at rank j
        np.testing.assert_array_equal(
            a2a, np.asarray([rank + 0.0, rank + 1.0])
        )
