"""The obs layer: tracer ring/concurrency, Chrome-trace validity, request
lifecycle completeness, wire-fallback counters, Prometheus export.

Lifecycle tests drive the real ServingEngine over a stub backend (no jax
compiles — tier-1 wall time); the one jitted test (forced lax fallback
through a real shard_map) shares the suite's virtual mesh.
"""

import json
import threading
from collections import Counter, defaultdict

import numpy as np
import pytest

from uccl_tpu import obs
from uccl_tpu.collective import dma
from uccl_tpu.serving import ServingEngine


@pytest.fixture
def tracer():
    """Fresh enabled tracer, always disabled after (global state)."""
    t = obs.enable_tracing(4096)
    yield t
    obs.disable_tracing()


class _StubBackend:
    """Prefill emits 0, the i-th decode step emits i (no model, no jax)."""

    def __init__(self, n_slots=2, max_seq=64):
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.n_decodes = 0

    def prefill(self, tokens, lens, mask, start=None):
        return np.zeros(self.n_slots, np.int32)

    def decode(self, tokens, active):
        self.n_decodes += 1
        return np.full(self.n_slots, self.n_decodes, np.int32)


def _prompt(rng, n):
    return rng.integers(0, 64, n).astype(np.int32)


class TestTracer:
    def test_ring_buffer_bounds_memory(self):
        t = obs.Tracer(capacity=100)
        for i in range(500):
            t.instant(f"e{i}", track="t")
        evs = t.events()
        assert len(evs) == 100
        assert t.dropped == 400
        assert evs[0].name == "e400"  # oldest survivor

    def test_concurrent_writers(self):
        t = obs.Tracer(capacity=100000)
        errs = []
        # all 8 workers provably concurrent (ident reuse after a thread
        # dies would otherwise fold auto tracks together)
        barrier = threading.Barrier(8)

        def worker(k):
            try:
                barrier.wait(timeout=30)
                for i in range(500):
                    t.instant(f"w{k}-{i}")
                    with t.span(f"s{k}-{i}"):
                        pass
                barrier.wait(timeout=30)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(8)]
        [th.start() for th in threads]
        [th.join() for th in threads]
        assert not errs
        evs = t.events()
        assert len(evs) == 8 * 1000 and t.dropped == 0
        # auto tracks keep concurrent writers on distinct rows
        assert len({e.track for e in evs}) == 8
        assert all(e.dur_us >= 0 for e in evs)

    def test_disabled_is_noop(self):
        obs.disable_tracing()
        assert obs.get_tracer() is None
        with obs.span("nothing", track="x", a=1):
            obs.instant("also-nothing")
        obs.begin("b")
        obs.end("b")
        assert obs.get_tracer() is None  # still off, nothing recorded

    def test_span_and_clear(self, tracer):
        with obs.span("outer", track="t", k="v"):
            obs.instant("mark", track="t")
        evs = tracer.events()
        assert [e.ph for e in evs] == ["i", "X"]  # X lands at exit
        assert evs[1].args == {"k": "v"} and evs[1].dur_us >= 0
        tracer.clear()
        assert len(tracer) == 0


def _phase_counts(trace):
    """{track: [event names in ts order]} + B/E balance per tid."""
    tracks = {e["tid"]: e["args"]["name"] for e in trace["traceEvents"]
              if e.get("name") == "thread_name"}
    by_track = defaultdict(list)
    b, e_ = Counter(), Counter()
    for ev in trace["traceEvents"]:
        if ev["ph"] in "XBEi":
            by_track[tracks[ev["tid"]]].append(ev)
        if ev["ph"] == "B":
            b[ev["tid"]] += 1
        elif ev["ph"] == "E":
            e_[ev["tid"]] += 1
    for evs in by_track.values():
        evs.sort(key=lambda ev: ev["ts"])
    return by_track, b, e_


class TestHistograms:
    def _fam(self, reg=None):
        reg = reg if reg is not None else obs.Registry()
        return reg.histogram("lat_seconds", "test latencies",
                             buckets=[0.001, 0.01, 0.1, 1.0])

    def test_observe_and_text_export(self):
        reg = obs.Registry()
        h = self._fam(reg)
        for v in (0.0005, 0.0005, 0.05, 5.0):
            h.observe(v)
        reg.histogram("declared_empty_seconds", "exists as zeros")
        txt = obs.prometheus_text(reg)
        assert "# TYPE lat_seconds histogram" in txt
        # cumulative buckets, inclusive le, +Inf last
        assert 'lat_seconds_bucket{le="0.001"} 2' in txt
        assert 'lat_seconds_bucket{le="0.01"} 2' in txt
        assert 'lat_seconds_bucket{le="0.1"} 3' in txt
        assert 'lat_seconds_bucket{le="+Inf"} 4' in txt
        assert "lat_seconds_sum 5.051" in txt
        assert "lat_seconds_count 4" in txt
        # declared-but-empty family still exports an assertable series
        assert 'declared_empty_seconds_bucket{le="+Inf"} 0' in txt
        assert "declared_empty_seconds_count 0" in txt

    def test_labels_and_inclusive_edge(self):
        h = self._fam()
        h.observe(0.001, cls="a")  # == the edge: le is inclusive
        h.observe(0.002, cls="b")
        assert h.get(cls="a") == 1 and h.total() == 2
        (labels, counts, s), = [x for x in h.hist_samples()
                                if x[0] == {"cls": "a"}]
        assert counts[0] == 1 and sum(counts) == 1

    def test_quantile_matches_samples_within_bucket_width(self, rng):
        from uccl_tpu.serving.metrics import percentile

        h = obs.Registry().histogram(
            "q_seconds", buckets=obs.DEFAULT_LATENCY_BUCKETS
        )
        xs = list(rng.lognormal(-4.0, 1.5, 200))
        for v in xs:
            h.observe(v)
        for q in (50, 95):
            hv = h.quantile(q)
            sv = percentile(xs, q)
            assert abs(hv - sv) <= obs.bucket_width(h.uppers, hv), (q, hv, sv)

    def test_merge_safety_sum_equals_union(self, rng):
        """The fleet-aggregation property: two processes' bucket counts
        SUM into the distribution one process observing everything would
        have recorded — bit-exact, not approximate."""
        a, b = self._fam(), self._fam()
        union = self._fam()
        xs, ys = rng.exponential(0.05, 50), rng.exponential(0.5, 70)
        for v in xs:
            a.observe(v)
            union.observe(v)
        for v in ys:
            b.observe(v)
            union.observe(v)
        (_, ca, sa), = a.hist_samples()
        (_, cb, sb), = b.hist_samples()
        (_, cu, su), = union.hist_samples()
        assert [x + y for x, y in zip(ca, cb)] == cu
        assert abs((sa + sb) - su) < 1e-9
        for q in (50, 95):
            assert obs.histogram_quantile(
                a.uppers, [x + y for x, y in zip(ca, cb)], q
            ) == union.quantile(q)

    def test_bucket_mismatch_and_type_conflict_rejected(self):
        reg = obs.Registry()
        reg.histogram("h_seconds", buckets=[0.1, 1.0])
        with pytest.raises(ValueError):
            reg.histogram("h_seconds", buckets=[0.2, 1.0])
        with pytest.raises(TypeError):
            reg.counter("h_seconds")

    def test_serving_hooks_observe_histograms(self):
        """The lifecycle hooks feed the merge-safe histograms the SAME
        values they append as samples — the within-one-bucket agreement
        the fleet gate rests on."""
        from uccl_tpu.serving.metrics import (
            TTFT_HIST, ServingMetrics, reset_latency_histograms,
        )

        reset_latency_histograms()
        m = ServingMetrics()
        rng = np.random.default_rng(1)
        eng = ServingEngine(_StubBackend(n_slots=2))
        eng.submit(_prompt(rng, 4), max_new_tokens=2)
        eng.drain()
        assert TTFT_HIST.total() == 1
        assert len(eng.metrics.ttft_s) == 1
        assert abs(TTFT_HIST.quantile(50) - eng.metrics.ttft_s[0]) \
            <= obs.bucket_width(TTFT_HIST.uppers, TTFT_HIST.quantile(50))
        eng.reset_metrics()  # warmup reset clears the histograms too
        assert TTFT_HIST.total() == 0
        del m

    def test_trace_dropped_total_exported(self):
        obs.disable_tracing()
        txt = obs.prometheus_text(obs.Registry())
        assert "obs_trace_dropped_total 0" in txt
        t = obs.enable_tracing(4)
        try:
            for i in range(10):
                t.instant(f"e{i}", track="x")
            txt = obs.prometheus_text(obs.Registry())
            assert "obs_trace_dropped_total 6" in txt
        finally:
            obs.disable_tracing()


class TestChromeTrace:
    def test_valid_json_balanced_and_nonnegative(self, tracer):
        obs.begin("open-span", track="manual")
        obs.instant("tick", track="manual")
        obs.end("open-span", track="manual")
        obs.begin("left-open", track="manual")  # exporter must close it
        with obs.span("x", track="other"):
            pass
        from uccl_tpu.obs import chrome_trace

        trace = json.loads(chrome_trace.dumps())
        assert isinstance(trace["traceEvents"], list)
        _, b, e_ = _phase_counts(trace)
        assert b == e_  # every B has a matching E
        assert all(ev.get("dur", 0) >= 0 for ev in trace["traceEvents"]
                   if ev["ph"] == "X")

    def test_orphan_end_dropped(self, tracer):
        obs.end("never-began", track="t")
        obs.instant("i", track="t")
        trace = obs.to_chrome_trace()
        _, b, e_ = _phase_counts(trace)
        assert b == e_ == Counter()

    def test_flow_events_and_clock_metadata(self, tracer):
        fid = obs.flow_id("deadbeefcafe0123")
        with obs.span("tx", track="wire"):
            obs.flow_start("kv_handoff", fid, track="wire")
        with obs.span("import", track="wire"):
            obs.flow_end("kv_handoff", fid, track="wire")
        obs.set_clock_offset(-1234.5, rtt_us=80.0, peer="prefill")
        trace = obs.to_chrome_trace(process_name="uccl_tpu.decode")
        s = [e for e in trace["traceEvents"] if e["ph"] == "s"]
        f = [e for e in trace["traceEvents"] if e["ph"] == "f"]
        assert len(s) == len(f) == 1
        assert s[0]["id"] == f[0]["id"] == fid
        assert s[0]["cat"] == "flow" and f[0]["bp"] == "e"
        clock = trace["otherData"]["clock"]
        assert clock["offset_us"] == -1234.5
        assert clock["rtt_us"] == 80.0 and clock["peer"] == "prefill"
        assert clock["wall_epoch_us"] > 0
        assert trace["otherData"]["process_name"] == "uccl_tpu.decode"


class TestRequestLifecycle:
    def _run(self, *, prefill_chunk=None, n_reqs=4):
        rng = np.random.default_rng(0)
        eng = ServingEngine(_StubBackend(n_slots=2),
                            prefill_chunk=prefill_chunk)
        reqs = []
        # staggered: 2 submitted up front, the rest dribble in mid-flight,
        # so admission overlaps active decodes and slots get reused
        reqs.append(eng.submit(_prompt(rng, 5), max_new_tokens=3))
        reqs.append(eng.submit(_prompt(rng, 7), max_new_tokens=2))
        eng.step()
        reqs.append(eng.submit(_prompt(rng, 3), max_new_tokens=4))
        eng.step()
        reqs.append(eng.submit(_prompt(rng, 6), max_new_tokens=2))
        eng.drain()
        assert eng.pool.leaked() == 0
        return eng, reqs

    def test_lifecycle_complete_whole_prompt(self, tracer):
        _, reqs = self._run()
        trace = obs.to_chrome_trace()
        by_track, b, e_ = _phase_counts(trace)
        assert b == e_
        for r in reqs:
            names = [ev["name"] for ev in by_track[r.track]]
            # the full lifecycle, in timeline order, on the request's row
            assert names[0] == "submit"
            assert names[1] == "admit"
            assert "prefill" in names
            ft, fin = names.index("first_token"), names.index("finish")
            assert names.index("prefill") < ft < fin == len(names) - 1
        # engine-step and wire spans exist alongside the request rows
        assert any(ev["name"] == "engine.step"
                   for ev in by_track["engine"])
        wire = [ev["name"] for ev in by_track["wire"]]
        assert "wire.prefill" in wire and "wire.decode" in wire

    def test_lifecycle_complete_chunked(self, tracer):
        _, reqs = self._run(prefill_chunk=2)
        trace = obs.to_chrome_trace()
        by_track, _, _ = _phase_counts(trace)
        for r in reqs:
            names = [ev["name"] for ev in by_track[r.track]]
            chunks = names.count("prefill_chunk")
            # one chunk span per C-token advance of the cursor
            assert chunks == -(-r.prompt.size // 2)
            assert names[:2] == ["submit", "admit"]
            assert names[-1] == "finish" and "first_token" in names

    def test_disabled_tracer_records_nothing(self):
        obs.disable_tracing()
        eng, reqs = self._run()
        assert obs.get_tracer() is None
        assert all(r.state.value == "finished" for r in reqs)
        # counters stay live even with tracing off
        assert obs.gauge("serving_slot_high_water").get() >= 1


class TestFallbackCounters:
    def _delta(self, before):
        after = {tuple(sorted(lb.items())): v
                 for lb, v in dma.WIRE_FALLBACK.samples()}
        return {k: v - before.get(k, 0) for k, v in after.items()
                if v > before.get(k, 0)}

    def _snap(self):
        return {tuple(sorted(lb.items())): v
                for lb, v in dma.WIRE_FALLBACK.samples()}

    def test_resolver_reasons(self):
        from uccl_tpu.ep import ll as ep_ll
        from uccl_tpu.ep import ops as ep_ops

        b = self._snap()
        assert ep_ops.resolve_chunks(2, "pallas", 1, 8, 2, 64, 4) == 1
        assert ep_ops.resolve_chunks(2, "pallas", 4, 1, 2, 64, 4) == 1
        assert ep_ll.resolve_ll_chunks(2, "pallas", 1, 8) == 1
        # NOT fallbacks, must not count: chunks off the pallas wire are a
        # no-op knob, and auto (0) resolving to 1 on an unchunkable config
        # is the correct auto answer, not a downgrade
        assert ep_ops.resolve_chunks(2, "lax", 4, 8, 2, 64, 4) == 1
        assert ep_ops.resolve_chunks(0, "pallas", 1, 8, 2, 64, 4) == 1
        assert ep_ops.resolve_chunks(0, "pallas", 4, 1, 2, 64, 4) == 1
        assert ep_ll.resolve_ll_chunks(0, "pallas", 1, 8) == 1
        d = self._delta(b)
        key = lambda what, reason: (("reason", reason), ("what", what))  # noqa: E731
        assert d[key("ep_moe_chunked", "world_size")] == 1
        assert d[key("ep_moe_chunked", "capacity")] == 1
        assert d[key("ep_ll_chunked", "world_size")] == 1
        assert sum(d.values()) == 3
        # the depth gauge reflects the LAST resolution — a downgraded
        # layer reads 1, never a stale earlier depth
        assert obs.gauge("ep_chunk_depth").get(what="moe_layer") == 1

    def test_buffer_verb_downgrade_counted_once(self, devices):
        """Buffer host paths memoize static wire decisions: a hot loop of
        verb calls over one config records ONE fallback event, matching
        the per-compile semantics of the traced gates."""
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from uccl_tpu.ep.buffer import Buffer
        from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

        # multi-axis mesh under the legacy interpreter: pallas cannot
        # address it and every verb transparently rides the XLA wire
        mesh = make_mesh(MeshConfig(dp=2), devices[:2])
        if len(mesh.axis_names) == 1:  # pragma: no cover
            pytest.skip("mesh collapsed to one axis; nothing to downgrade")
        buf = Buffer(mesh, axis="dp", num_experts=4, num_selected=2,
                     capacity_factor=8.0, wire="pallas")
        if buf._pallas_wire_ok():  # pragma: no cover (faithful interp)
            pytest.skip("pallas can address this mesh; no downgrade here")
        x = buf.device_put(jnp.zeros((2, 4, 8), jnp.float32))
        idx = buf.device_put(jnp.zeros((2, 4, 2), jnp.int32))
        b = self._snap()
        for _ in range(3):
            recv, handle = buf.dispatch(x, idx)
            buf.combine(recv, handle)
        d = self._delta(b)
        k = (("reason", "legacy_interpret_mesh"), ("what", "buffer_verb"))
        assert d == {k: 1}, d

    def test_budget_gate_counts_and_quiet_probe_does_not(self):
        b = self._snap()
        assert not dma.check_budget(1 << 40, "ep_all_to_all", True)
        assert not dma.check_budget(1 << 40, "ep_all_to_all", True,
                                    quiet=True)
        d = self._delta(b)
        assert d == {(("reason", "interpret_budget"),
                      ("what", "ep_all_to_all")): 1}

    def test_forced_lax_wire_records_reason(self, devices):
        """A REAL over-budget pallas a2a inside shard_map: the exchange
        transparently rides lax and the fallback is counted, not silent."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from uccl_tpu.ep import pallas_a2a
        from uccl_tpu.utils.jaxcompat import shard_map

        from jax import lax

        mesh = Mesh(np.array(devices[:2]), ("x",))
        # per-shard [2, 8192] f32: 2 * n * m * 4B = 128 KiB > the 64 KiB
        # interpreter ceiling, so the kernel must take the lax fallback
        x = jnp.arange(4 * 8192, dtype=jnp.float32).reshape(4, 8192)
        b = self._snap()

        def shmap(f):
            return jax.jit(shard_map(
                f, mesh=mesh, in_specs=(P("x"),), out_specs=P("x"),
                check_vma=False,
            ))

        out = np.asarray(shmap(
            lambda v: pallas_a2a.all_to_all(v, "x"))(x))
        want = np.asarray(shmap(lambda v: lax.all_to_all(
            v, "x", split_axis=0, concat_axis=0, tiled=True))(x))
        # the lax fallback is numerically the same exchange
        np.testing.assert_array_equal(out, want)
        d = self._delta(b)
        assert any(dict(k)["what"] == "ep_all_to_all"
                   and dict(k)["reason"].endswith("_budget")
                   for k in d), d


class TestPrometheusExport:
    def test_sanitizer(self):
        assert obs.sanitize_name("a.b-c/d") == "a_b_c_d"
        assert obs.sanitize_name("9lives") == "_9lives"
        assert obs.sanitize_name("ok_name:x") == "ok_name:x"
        assert obs.escape_label_value('a"b\nc\\d') == 'a\\"b\\nc\\\\d'

    def test_counter_and_gauge_text(self):
        reg = obs.Registry()
        c = reg.counter("events_total", "help text")
        c.inc(reason="a b")
        c.inc(2, reason="x")
        reg.gauge("depth").set(3, what="moe")
        reg.counter("declared_but_empty_total", "exists as 0")
        txt = obs.prometheus_text(reg)
        assert '# TYPE events_total counter' in txt
        assert 'events_total{reason="a b"} 1' in txt
        assert 'events_total{reason="x"} 2' in txt
        assert 'depth{what="moe"} 3' in txt
        assert "declared_but_empty_total 0" in txt

    def test_sources_flatten_nested(self):
        reg = obs.Registry()
        reg.register_source("srv", lambda: {
            "goodput": 11.5, "ttft_ms": {"p50": 1.25}, "skip": "str",
        })
        txt = obs.prometheus_text(reg)
        assert "srv_goodput 11.5" in txt
        assert "srv_ttft_ms_p50 1.25" in txt
        assert "skip" not in txt
        snap = reg.snapshot()
        assert snap["sources"]["srv"]["ttft_ms"]["p50"] == 1.25

    def test_counter_rejects_decrease(self):
        with pytest.raises(ValueError):
            obs.Registry().counter("c").inc(-1)

    def test_serving_metrics_prometheus_lines(self):
        from uccl_tpu.serving.metrics import ServingMetrics

        m = ServingMetrics()
        m.completed = 3
        m.ttft_s.extend([0.01, 0.02])
        snap = m.snapshot(queued=1, active=2, n_slots=4, occupancy=0.5)
        lines = ServingMetrics.prometheus_lines(snap)
        assert "uccl_serving_completed 3" in lines
        assert any(line.startswith('uccl_serving_ttft_ms{q="p50"} ')
                   for line in lines)

    def test_stats_registry_mirrors_into_obs(self):
        from uccl_tpu.utils import stats

        stats.registry.register("obs_shim_test", lambda: {"v": 7.0})
        try:
            assert obs.REGISTRY.sources_snapshot()["obs_shim_test"] == {
                "v": 7.0
            }
        finally:
            stats.registry.unregister("obs_shim_test")
        assert "obs_shim_test" not in obs.REGISTRY.sources_snapshot()

    def test_timed_scope_thread_safety_and_obs_source(self):
        from uccl_tpu.utils import tracing

        tracing.reset_scopes()
        errs = []

        def worker():
            try:
                for _ in range(200):
                    with tracing.timed_scope("obs_scope_stress"):
                        pass
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert not errs
        s = tracing.scope_stats("obs_scope_stress")
        assert s is not None and s["count"] == 1600  # no racy-lost samples
        # re-pointed at obs: the scopes source exports the same summary
        src = obs.REGISTRY.sources_snapshot()["scopes"]
        assert src["obs_scope_stress"]["count"] == 1600
        tracing.reset_scopes()
        assert tracing.scope_stats("obs_scope_stress") is None

    def test_json_snapshot_shape(self):
        snap = obs.json_snapshot()
        assert snap["schema_version"] == obs.SCHEMA_VERSION
        assert "metrics" in snap and "tracer" in snap
        json.dumps(snap)  # JSON-ready end to end

    def test_exit_net_defers_to_explicit_dump(self, tmp_path):
        """dump_at_exit's fallback must not clobber an explicit dump's
        richer output (extra lines) with the bare registry state."""
        from uccl_tpu.obs import export

        class Args:
            trace_out = ""
            metrics_out = str(tmp_path / "m.prom")

        args = Args()
        export.dump_from_args(args, extra_lines=["rich_extra_series 1"])
        assert "rich_extra_series 1" in (tmp_path / "m.prom").read_text()
        # the registered fallback is a no-op once an explicit dump ran
        assert id(args) in export._dumped_args
        args2 = Args()
        args2.metrics_out = str(tmp_path / "m2.prom")
        assert id(args2) not in export._dumped_args
