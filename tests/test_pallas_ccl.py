"""Pallas remote-DMA ring collectives, run under the TPU interpreter on the
virtual 8-device mesh (remote DMAs + semaphores simulated faithfully).

Numerics oracle: numpy / lax.psum. Schedule oracle: the lax.ppermute plan
lowering of the same ring schedules (uccl_tpu.collective.plan)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from uccl_tpu.collective import pallas_ccl, plan
from uccl_tpu.parallel.mesh import MeshConfig, make_mesh
from uccl_tpu.utils import jaxcompat
from uccl_tpu.utils.jaxcompat import shard_map

# The canonical 4-axis make_mesh fixtures need the faithful multi-device
# interpreter (pltpu.InterpretParams): the legacy discharge interpreter
# (jax 0.4.x) can only address single-named-axis meshes. The odd-world
# tests below use 1-axis meshes and run everywhere.
_needs_faithful = pytest.mark.skipif(
    not jaxcompat.FAITHFUL_PALLAS_INTERPRET,
    reason="legacy pallas interpreter cannot address multi-axis meshes",
)


@pytest.fixture(scope="module")
def mesh(devices):
    return make_mesh(MeshConfig(dp=8), devices)


@pytest.fixture(scope="module")
def mesh2d(devices):
    return make_mesh(MeshConfig(dp=2, tp=4), devices)


def _run(mesh, fn, x, in_spec, out_spec):
    mapped = shard_map(
        fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec,
        check_vma=False,
    )
    return np.asarray(jax.jit(mapped)(x))


@_needs_faithful
class TestAllGather:
    @pytest.mark.parametrize("direction", [1, -1])
    def test_matches_tile(self, mesh, rng, direction):
        x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_gather(
                v, "dp", direction=direction, interpret=True
            ),
            x, P("dp"), P("dp", None),
        )
        # every member outputs the full gather; out_spec stacks all 8 copies
        np.testing.assert_array_equal(got, np.tile(np.asarray(x), (8, 1)))

    def test_matches_plan_lowering(self, mesh, rng):
        x = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_gather(v, "dp", interpret=True),
            x, P("dp"), P("dp", None),
        )
        want = _run(
            mesh, lambda v: plan.ring_all_gather(v, "dp"),
            x, P("dp"), P("dp", None),
        )
        np.testing.assert_array_equal(got, want)

    def test_subaxis_ring(self, mesh2d, rng):
        """Ring over tp inside a dp×tp mesh: MESH device addressing keeps
        the dp coordinate fixed."""
        x = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
        got = _run(
            mesh2d,
            lambda v: pallas_ccl.ring_all_gather(v, "tp", interpret=True),
            x, P(("dp", "tp")), P(("dp", "tp"), None),
        )
        xs = np.asarray(x)
        want = np.concatenate(
            [np.tile(xs[g * 4: (g + 1) * 4], (4, 1)) for g in range(2)]
        )
        np.testing.assert_array_equal(got, want)


@_needs_faithful
class TestReduceScatter:
    @pytest.mark.parametrize("direction", [1, -1])
    def test_matches_numpy(self, mesh, rng, direction):
        x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_reduce_scatter(
                v.reshape(16), "dp", direction=direction, interpret=True
            ).reshape(1, 2),
            x, P("dp"), P("dp", None),
        )
        full = np.asarray(x).sum(axis=0)  # [16]; member r keeps slot r
        want = full.reshape(8, 2)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_indivisible_raises(self, mesh):
        x = jnp.ones((8, 9), jnp.float32)
        with pytest.raises(ValueError, match="not divisible"):
            _run(
                mesh,
                lambda v: pallas_ccl.ring_reduce_scatter(
                    v.reshape(9), "dp", interpret=True
                ),
                x, P("dp"), P("dp"),
            )


@_needs_faithful
class TestAllReduce:
    @pytest.mark.parametrize("bidi", [False, True])
    @pytest.mark.parametrize("payload", [64, 257])  # 257: padding path
    def test_matches_psum(self, mesh, rng, bidi, payload):
        x = jnp.asarray(rng.normal(size=(8, payload)), jnp.float32)

        def f(v):
            return pallas_ccl.ring_all_reduce(
                v, "dp", bidirectional=bidi, interpret=True
            )

        got = _run(mesh, f, x, P("dp"), P("dp", None))
        want = np.tile(np.asarray(x).sum(0), (8, 1))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_bf16(self, mesh, rng):
        x = jnp.asarray(rng.normal(size=(8, 128)), jnp.bfloat16)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_reduce(v, "dp", interpret=True),
            x, P("dp"), P("dp", None),
        )
        want = _run(
            mesh, lambda v: jax.lax.psum(v, "dp"), x, P("dp"), P("dp", None)
        )
        np.testing.assert_allclose(
            got.astype(np.float32), want.astype(np.float32),
            rtol=5e-2, atol=5e-2,
        )

    def test_world2_subaxis(self, mesh2d, rng):
        """n=2 ring (left == right) over the dp axis of the 2D mesh."""
        x = jnp.asarray(rng.normal(size=(8, 32)), jnp.float32)
        got = _run(
            mesh2d,
            lambda v: pallas_ccl.ring_all_reduce(v, "dp", interpret=True),
            x, P(("dp", "tp")), P(("dp", "tp"), None),
        )
        xs = np.asarray(x)
        # dp pairs: shard (g, t) pairs with (1-g, t); shards are row groups
        want = np.empty_like(xs)
        for g in range(2):
            for t in range(4):
                a, b = g * 4 + t, (1 - g) * 4 + t
                want[a] = xs[a] + xs[b]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_nd_payload(self, mesh, rng):
        x = jnp.asarray(rng.normal(size=(8, 3, 5)), jnp.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_reduce(v, "dp", interpret=True),
            x, P("dp"), P("dp", None, None),
        )
        want = np.tile(np.asarray(x).sum(0), (8, 1, 1))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_communicator_algo(self, mesh, rng):
        """algo="pallas" through the public Communicator API == psum."""
        from uccl_tpu.collective import Communicator

        comm = Communicator(mesh, "dp")
        x = comm.device_put(
            np.asarray(rng.normal(size=(8, 32)), np.float32)
        )
        got = np.asarray(comm.all_reduce(x, algo="pallas"))
        want = np.asarray(comm.all_reduce(x, algo="xla"))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_vmem_budget_fallback(self, mesh, rng, monkeypatch):
        """Over-budget payloads take the ppermute plan path (still correct)."""
        monkeypatch.setenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES", "64")
        pallas_ccl._MAX_VMEM_BYTES.reset()
        try:
            x = jnp.asarray(rng.normal(size=(8, 64)), jnp.float32)
            got = _run(
                mesh,
                lambda v: pallas_ccl.ring_all_reduce(v, "dp", interpret=True),
                x, P("dp"), P("dp", None),
            )
            want = np.tile(np.asarray(x).sum(0), (8, 1))
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        finally:
            monkeypatch.delenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES")
            pallas_ccl._MAX_VMEM_BYTES.reset()


class TestBidir:
    """The paired counter-rotating ring kernels (round 8, the FlexLink
    pair): two unidirectional kernels on paired collective ids, each
    carrying half the payload. 1-axis meshes so every arm runs under the
    legacy discharge interpreter too; worlds 4/8/5 — the odd world is what
    catches the credit fenceposts, exactly like TestOddWorlds."""

    @staticmethod
    def _mesh(devices, n):
        return Mesh(np.array(devices[:n]), ("dp",))

    @staticmethod
    def _mirror_fn(n, wire_dtype=None):
        """The directed lax mirror pair — the exact code the pair-level
        budget fallback runs, so kernel == this pins kernel == fallback."""

        def f(v):
            flat = v.reshape(-1)
            half = flat.size // 2
            fwd = pallas_ccl._directed_ar_mirror(flat[:half], "dp", n, 1,
                                                 wire_dtype)
            bwd = pallas_ccl._directed_ar_mirror(flat[half:], "dp", n, -1,
                                                 wire_dtype)
            return jnp.concatenate([fwd, bwd]).reshape(v.shape)

        return f

    def test_matches_sum_and_mirror(self, devices, rng):
        """World 4, f32 (the tier-1 arm): oracle-exact vs the sum AND
        bit-identical to the directed lax mirror pair."""
        n = 4
        mesh = self._mesh(devices, n)
        x = jnp.asarray(rng.normal(size=(n, 41)), jnp.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.bidir_all_reduce(v, "dp", interpret=True),
            x, P("dp"), P("dp", None),
        )
        want = np.tile(np.asarray(x).sum(0), (n, 1))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        mirror = _run(mesh, self._mirror_fn(n), x, P("dp"), P("dp", None))
        np.testing.assert_array_equal(got, mirror)

    def test_budget_fallback_counted(self, devices, rng, monkeypatch):
        """The pair-level budget gate degrades BOTH rings to the mirror as
        a unit — counted on ep_wire_fallback_total{what="all_reduce_bidir"}
        AND collective_plan_total{algo="bidir", outcome="fallback"}, and
        still numerically correct."""
        from uccl_tpu.collective import dma, plan as plan_mod

        monkeypatch.setenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES", "64")
        pallas_ccl._MAX_VMEM_BYTES.reset()
        try:
            n = 4
            mesh = self._mesh(devices, n)
            x = jnp.asarray(rng.normal(size=(n, 64)), jnp.float32)
            fb = {tuple(sorted(lb.items())): v
                  for lb, v in dma.WIRE_FALLBACK.samples()}
            pk = (("algo", "bidir"), ("chunks", "2"),
                  ("outcome", "fallback"), ("wire_dtype", "none"))
            pl = {tuple(sorted(lb.items())): v
                  for lb, v in plan_mod.PLAN_TOTAL.samples()}
            got = _run(
                mesh,
                lambda v: pallas_ccl.bidir_all_reduce(v, "dp",
                                                      interpret=True),
                x, P("dp"), P("dp", None),
            )
            want = np.tile(np.asarray(x).sum(0), (n, 1))
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
            fb2 = {tuple(sorted(lb.items())): v
                   for lb, v in dma.WIRE_FALLBACK.samples()}
            hit = [k for k, v in fb2.items()
                   if v > fb.get(k, 0)
                   and dict(k)["what"] == "all_reduce_bidir"]
            assert hit, f"no counted all_reduce_bidir downgrade in {fb2}"
            pl2 = {tuple(sorted(lb.items())): v
                   for lb, v in plan_mod.PLAN_TOTAL.samples()}
            assert pl2.get(pk, 0) == pl.get(pk, 0) + 1
        finally:
            monkeypatch.delenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES")
            pallas_ccl._MAX_VMEM_BYTES.reset()

    @pytest.mark.slow
    @pytest.mark.parametrize("n", [8, 5])
    def test_oracle_worlds(self, devices, rng, n):
        x = jnp.asarray(rng.normal(size=(n, 72)), jnp.float32)
        got = _run(
            self._mesh(devices, n),
            lambda v: pallas_ccl.bidir_all_reduce(v, "dp", interpret=True),
            x, P("dp"), P("dp", None),
        )
        want = np.tile(np.asarray(x).sum(0), (n, 1))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        mirror = _run(self._mesh(devices, n), self._mirror_fn(n), x,
                      P("dp"), P("dp", None))
        np.testing.assert_array_equal(got, mirror)

    @pytest.mark.slow
    @pytest.mark.parametrize("n", [4, 8, 5])
    def test_bf16(self, devices, rng, n):
        x = jnp.asarray(rng.normal(size=(n, 64)), jnp.bfloat16)
        got = _run(
            self._mesh(devices, n),
            lambda v: pallas_ccl.bidir_all_reduce(v, "dp", interpret=True),
            x, P("dp"), P("dp", None),
        ).astype(np.float32)
        want = np.tile(
            np.asarray(x, np.float32).sum(0, keepdims=True), (n, 1)
        )
        np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)

    @pytest.mark.slow
    @pytest.mark.parametrize("n", [4, 8, 5])
    def test_fp8_wire(self, devices, rng, n):
        """fp8 wire: tolerance-exact vs the f32 oracle AND bit-identical to
        the quantized directed mirror pair (the counted fallback path)."""
        mesh = self._mesh(devices, n)
        x = jnp.asarray(rng.normal(size=(n, 40)), jnp.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.bidir_all_reduce(v, "dp", interpret=True,
                                                  wire_dtype="fp8"),
            x, P("dp"), P("dp", None),
        )
        want = np.tile(np.asarray(x).sum(0), (n, 1))
        # one quantize round trip per RS hop + one on the gathered copy
        # (docs/QUANT_WIRE.md error model)
        np.testing.assert_allclose(got, want, rtol=0.2, atol=0.6)
        mirror = _run(mesh, self._mirror_fn(n, "fp8"), x, P("dp"),
                      P("dp", None))
        np.testing.assert_array_equal(got, mirror)


class TestOddWorlds:
    """Rings at n ∈ {3, 5} on 1-axis meshes: odd n is exactly what catches
    the ``s <= n - 4`` credit-window arithmetic (n=5 has ONE credited step
    per direction, n=3 none — a fencepost slip deadlocks or unbalances the
    semaphores), and the 1-axis mesh keeps these runnable under the legacy
    discharge interpreter as well as the faithful one."""

    @staticmethod
    def _mesh(devices, n):
        return Mesh(np.array(devices[:n]), ("dp",))

    @pytest.mark.parametrize("n", [3, 5])
    @pytest.mark.parametrize("bidi", [False, True])
    def test_allreduce_matches_sum(self, devices, rng, n, bidi):
        mesh = self._mesh(devices, n)
        x = jnp.asarray(rng.normal(size=(n, 40)), jnp.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_reduce(
                v, "dp", bidirectional=bidi, interpret=True
            ),
            x, P("dp"), P("dp", None),
        )
        want = np.tile(np.asarray(x).sum(0), (n, 1))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("n", [3, 5])
    @pytest.mark.parametrize("direction", [1, -1])
    def test_allgather_matches_tile(self, devices, rng, n, direction):
        mesh = self._mesh(devices, n)
        x = jnp.asarray(rng.normal(size=(n, 8)), jnp.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_all_gather(
                v, "dp", direction=direction, interpret=True
            ),
            x, P("dp"), P("dp", None),
        )
        np.testing.assert_array_equal(got, np.tile(np.asarray(x), (n, 1)))

    @pytest.mark.parametrize("n", [3, 5])
    def test_reduce_scatter_matches_numpy(self, devices, rng, n):
        mesh = self._mesh(devices, n)
        # payload divisible by n: n rows of 2n elements
        x = jnp.asarray(rng.normal(size=(n, 2 * n)), jnp.float32)
        got = _run(
            mesh,
            lambda v: pallas_ccl.ring_reduce_scatter(
                v.reshape(2 * n), "dp", interpret=True
            ).reshape(1, 2),
            x, P("dp"), P("dp", None),
        )
        want = np.asarray(x).sum(axis=0).reshape(n, 2)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
