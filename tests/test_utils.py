"""Unit tests for the substrate (config/logging/latency/topology) — the analog of
the reference's include/util unit tests (include/util/util_test.cc)."""

import os

import numpy as np
import pytest

from uccl_tpu.utils import config as cfg
from uccl_tpu.utils.latency import LatencyHistogram
from uccl_tpu.utils.logging import CHECK, DCHECK, CheckError, get_logger, log
from uccl_tpu.utils import topology as topo


class TestConfig:
    def test_default(self):
        p = cfg.param("test_default_xyz", 42)
        assert p.get() == 42

    def test_env_override(self, monkeypatch):
        p = cfg.param("test_env_abc", 7)
        monkeypatch.setenv("UCCL_TPU_TEST_ENV_ABC", "99")
        p.reset()
        assert p.get() == 99

    def test_types(self, monkeypatch):
        pb = cfg.param("test_bool_flag", False)
        monkeypatch.setenv("UCCL_TPU_TEST_BOOL_FLAG", "true")
        pb.reset()
        assert pb.get() is True
        pf = cfg.param("test_float_val", 1.5)
        monkeypatch.setenv("UCCL_TPU_TEST_FLOAT_VAL", "2.25")
        pf.reset()
        assert pf.get() == 2.25

    def test_programmatic_override(self):
        p = cfg.param("test_prog", 1)
        p.set(5)
        assert p.get() == 5
        p.reset()
        assert p.get() == 1

    def test_idempotent_registry(self):
        a = cfg.param("test_same", 1)
        b = cfg.param("test_same", 2)
        assert a is b

    def test_env_file(self, tmp_path):
        f = tmp_path / "env"
        f.write_text("# comment\nUCCL_TPU_TEST_FROM_FILE=123\n")
        p = cfg.param("test_from_file", 0)
        cfg.set_env_file(str(f))
        assert p.get() == 123
        cfg.reset_all()

    def test_dump(self):
        cfg.param("test_dump_me", 3)
        d = cfg.dump_params()
        assert d["test_dump_me"] == 3


class TestLogging:
    def test_get_logger(self):
        lg = get_logger("COLL")
        lg.info("hello")

    def test_bad_subsys(self):
        with pytest.raises(ValueError):
            get_logger("NOPE")

    def test_fatal_raises(self):
        with pytest.raises(RuntimeError):
            log("FATAL", "boom", subsys="UTIL")

    def test_check(self):
        CHECK(True)
        with pytest.raises(CheckError):
            CHECK(False, "nope")
        DCHECK(True)


class TestLatency:
    def test_basic_percentiles(self):
        h = LatencyHistogram()
        samples = np.linspace(1, 1000, 1000)
        h.record_many(samples)
        assert h.count == 1000
        assert abs(h.mean - samples.mean()) < 1.0
        # 5% bucket resolution
        assert abs(h.percentile(50) - 500) / 500 < 0.10
        assert abs(h.percentile(99) - 990) / 990 < 0.10
        assert h.percentile(100) <= h.summary()["max_us"]

    def test_empty(self):
        h = LatencyHistogram()
        assert h.percentile(50) == 0.0
        assert h.summary()["count"] == 0

    def test_thread_safety(self):
        import threading

        h = LatencyHistogram()

        def worker():
            for i in range(1000):
                h.record(float(i % 100 + 1))

        ts = [threading.Thread(target=worker) for _ in range(4)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert h.count == 4000


class TestTopology:
    def test_ring_order(self):
        assert topo.ring_order(4) == [0, 1, 2, 3]
        assert topo.ring_order(4, offset=1) == [1, 2, 3, 0]
        assert topo.ring_order(4, reverse=True) == [0, 3, 2, 1]

    def test_neighbors(self):
        assert topo.ring_neighbors(0, 4) == (3, 1)
        assert topo.ring_neighbors(0, 4, reverse=True) == (1, 3)

    def test_ppermute_pairs(self):
        assert topo.ppermute_pairs(3) == [(0, 1), (1, 2), (2, 0)]

    def test_factor_2d(self):
        assert topo.factor_2d(8) == (2, 4)
        assert topo.factor_2d(16) == (4, 4)
        assert topo.factor_2d(7) == (1, 7)

    def test_recursive_halving(self):
        assert topo.recursive_halving_peers(0, 8) == [4, 2, 1]
        with pytest.raises(ValueError):
            topo.recursive_halving_peers(0, 6)


class TestTimingPerturb:
    """uccl_tpu.utils.timing.perturb: the carry coupling must be value-
    preserving for EVERY carry, or the timing harness silently times a
    different computation than the one it reports."""

    def test_int_leaves_unchanged_for_negative_carry(self):
        """Regression (round-5 ADVICE): the int branch used min(carry, 0),
        which is only zero for non-negative carries — a slope carry that
        drifts negative (reductions of signed outputs do) mutated every int
        leaf it coupled. min(|carry|, 0) is provably zero for any carry."""
        import jax.numpy as jnp

        from uccl_tpu.utils.timing import perturb

        a = jnp.arange(6, dtype=jnp.int32)
        for carry in (-3.7, -1.0, 0.0, 2.5):
            out = perturb(a, jnp.float32(carry))
            assert out.dtype == a.dtype
            np.testing.assert_array_equal(np.asarray(out), np.asarray(a))

    def test_float_coupling_negligible(self):
        import jax.numpy as jnp

        from uccl_tpu.utils.timing import perturb

        a = jnp.ones((4,), jnp.float32)
        out = perturb(a, jnp.float32(-2.0))
        np.testing.assert_allclose(np.asarray(out), np.ones(4), rtol=1e-6)
