"""Lossless wire codec: exact round-trips, ratios, codec routing.

The DietGPU-analog requirements (reference p2p/rdma/compression.h:46 —
DietGPU is a LOSSLESS ANS float codec): bit-identical round trips on every
supported dtype, ratio > 1.5x on checkpoint-like bf16 tensors, and blobs
routable off the same wire as fp8 blobs."""

import ml_dtypes
import numpy as np
import pytest

from uccl_tpu.p2p import lossless as lz
from uccl_tpu.p2p.compress import decode_any, encode, encode_fp8


@pytest.fixture(scope="module")
def rng_():
    return np.random.default_rng(0)


DTYPES = [
    np.dtype(np.float32),
    np.dtype(ml_dtypes.bfloat16),
    np.dtype(np.float16),
    np.dtype(np.float64),
    np.dtype(np.int32),
    np.dtype(np.int8),
    np.dtype(np.uint8),
    np.dtype(np.int64),
]


class TestRoundTrip:
    @pytest.mark.parametrize("dtype", DTYPES, ids=str)
    def test_bit_exact(self, rng_, dtype):
        if dtype.kind in "iu":
            a = rng_.integers(-100 if dtype.kind == "i" else 0, 100,
                              4097).astype(dtype)
        else:
            a = (rng_.standard_normal(4097) * 0.02).astype(dtype)
        a = a.reshape(17, 241)
        back = lz.decode_lossless(lz.encode_lossless(a))
        assert back.dtype == a.dtype and back.shape == a.shape
        np.testing.assert_array_equal(
            back.view(np.uint8), a.view(np.uint8)
        )

    def test_specials_survive(self):
        """NaN payloads, infs, -0.0, denormals round-trip bit-exactly
        (a lossless codec must not normalize anything)."""
        a = np.array(
            [0.0, -0.0, np.inf, -np.inf, np.nan, 1e-40, -1e-40, 448.0],
            np.float32,
        )
        a = np.concatenate([a, np.frombuffer(b"\x01\x00\x80\x7f" * 4,
                                             np.float32)])
        back = lz.decode_lossless(lz.encode_lossless(a))
        np.testing.assert_array_equal(back.view(np.uint32), a.view(np.uint32))

    def test_empty_and_scalarish(self):
        for a in (np.zeros((0,), np.float32), np.ones((1,), np.float32)):
            back = lz.decode_lossless(lz.encode_lossless(a))
            np.testing.assert_array_equal(back, a)


class TestRatio:
    def test_bf16_checkpoint_beats_1p5(self, rng_):
        """Weight-like bf16 (the checkpoint dtype): > 1.5x, the DietGPU-class
        target (VERDICT r2 missing #5)."""
        w = (rng_.standard_normal(1 << 19) * 0.02).astype(ml_dtypes.bfloat16)
        assert lz.ratio(w) > 1.5

    def test_low_entropy_tensors_compress_hard(self, rng_):
        gains = (1.0 + rng_.standard_normal(1 << 15) * 0.01).astype(
            ml_dtypes.bfloat16
        )
        assert lz.ratio(gains) > 3.0
        sparse = (
            np.where(rng_.random(1 << 18) < 0.05,
                     rng_.standard_normal(1 << 18), 0.0) * 0.01
        ).astype(np.float32)
        assert lz.ratio(sparse) > 5.0

    def test_incompressible_overhead_is_bounded(self, rng_):
        """Pure-noise uint8 must not blow up: planes ship raw, overhead is
        just the header."""
        noise = rng_.integers(0, 256, 1 << 16).astype(np.uint8)
        blob = lz.encode_lossless(noise)
        assert blob.nbytes < noise.nbytes + 256


class TestCodecRouting:
    def test_decode_any_routes_both_magics(self, rng_):
        a = (rng_.standard_normal(2048) * 0.1).astype(np.float32)
        exact = decode_any(encode(a, "lossless"))
        np.testing.assert_array_equal(exact, a)
        lossy = decode_any(encode_fp8(a))
        assert np.abs(lossy - a).max() < 0.05

    def test_unknown_magic_rejected(self):
        with pytest.raises(ValueError):
            decode_any(np.zeros(64, np.uint8))


class TestZlibFallback:
    def test_roundtrip_without_native(self, rng_, monkeypatch):
        """With the native coder disabled the codec stays correct (zlib
        planes) and can still decode its own blobs."""
        monkeypatch.setattr(lz, "_codec_lib", False)
        a = (rng_.standard_normal(8192) * 0.02).astype(ml_dtypes.bfloat16)
        blob = lz.encode_lossless(a)
        back = lz.decode_lossless(blob)
        np.testing.assert_array_equal(back.view(np.uint8), a.view(np.uint8))


class TestPurePythonRansDecode:
    """The sender's toolchain picks the encoding, so a receiver without the
    native runtime must decode rANS planes too (pure-Python fallback)."""

    def test_plane_decode_matches_native_encode(self, rng_):
        if lz._native() is None:
            pytest.skip("native codec unavailable")
        plane = (rng_.standard_normal(8192) * 3).astype(np.int8).tobytes()
        tag, data = lz._encode_plane(plane)
        if tag != lz._RANS:
            pytest.skip("plane did not take the rANS path")
        assert lz._rans_decode_py(data, len(plane)) == plane

    def test_blob_decodes_without_native(self, rng_, monkeypatch):
        if lz._native() is None:
            pytest.skip("native codec unavailable")
        a = (rng_.standard_normal(8192) * 0.02).astype(ml_dtypes.bfloat16)
        blob = lz.encode_lossless(a)
        monkeypatch.setattr(lz, "_codec_lib", False)  # receiver: no native
        back = lz.decode_lossless(blob)
        np.testing.assert_array_equal(back.view(np.uint8), a.view(np.uint8))

    def test_corrupt_plane_rejected(self):
        with pytest.raises(ValueError):
            lz._rans_decode_py(b"\x01" + b"\x00" * 600, 64)


class TestFuzzRoundTrip:
    """Randomized sweep: every supported dtype × shapes × distributions must
    round-trip bit-exactly through whichever plane encodings the content
    selects (raw / rANS / zlib), including the pure-Python decode path."""

    def test_fuzz_bit_exact(self, rng_):
        shapes = [(0,), (1,), (7,), (256,), (33, 5), (4, 3, 2, 5), (1023,)]
        dists = [
            lambda s: rng_.standard_normal(s) * 0.02,        # weight-like
            lambda s: rng_.standard_normal(s) * 1e8,          # huge scale
            lambda s: np.zeros(s),                            # constant
            lambda s: rng_.integers(-3, 3, s).astype(float),  # tiny alphabet
            lambda s: rng_.uniform(-1, 1, s),                 # dense mantissa
        ]
        cases = 0
        for dtype in DTYPES:
            for shape in shapes:
                for make in dists:
                    with np.errstate(over="ignore"):  # f16 inf: intentional
                        a = np.asarray(make(shape)).astype(dtype)
                    blob = lz.encode_lossless(a)
                    back = lz.decode_lossless(blob)
                    assert back.dtype == a.dtype and back.shape == a.shape
                    np.testing.assert_array_equal(
                        back.view(np.uint8), a.view(np.uint8)
                    )
                    cases += 1
        assert cases == len(DTYPES) * len(shapes) * len(dists)

    def test_fuzz_python_decode_of_native_blobs(self, rng_, monkeypatch):
        if lz._native() is None:
            pytest.skip("native codec unavailable")
        arrays = [
            (rng_.standard_normal(4096) * 0.02).astype(ml_dtypes.bfloat16),
            rng_.integers(-2, 2, 2048).astype(np.int8),
            (rng_.standard_normal(1000) * 5).astype(np.float32),
        ]
        blobs = [lz.encode_lossless(a) for a in arrays]
        monkeypatch.setattr(lz, "_codec_lib", False)  # decode w/o native
        for a, blob in zip(arrays, blobs):
            back = lz.decode_lossless(blob)
            np.testing.assert_array_equal(
                back.view(np.uint8), a.view(np.uint8)
            )
