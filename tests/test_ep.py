"""Expert-parallel dispatch/combine correctness — the analog of the reference's
ep/bench/test_low_latency.py correctness asserts ("All correctness tests
passed"), against a dense-MoE numpy oracle on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from uccl_tpu.ep import Buffer, ops as ep_ops
from uccl_tpu.parallel.mesh import AXIS, MeshConfig, make_mesh


@pytest.fixture(scope="module")
def ep_mesh(devices):
    return make_mesh(MeshConfig(dp=4, tp=2), devices)


W = 4  # EP world (dp=4)
E = 8  # global experts
T = 16  # tokens per member
H = 32  # hidden


def _shard_run(mesh, fn, in_arrays, in_dims, out_dims):
    specs_in = tuple(P(("dp", "cp"), *([None] * d)) for d in in_dims)
    specs_out = jax.tree.map(lambda d: P(("dp", "cp"), *([None] * d)), out_dims)
    mapped = jax.shard_map(
        fn, mesh=mesh, in_specs=specs_in, out_specs=specs_out, check_vma=False
    )
    return jax.jit(mapped)(*in_arrays)


class TestRouting:
    def test_masks_from_topk_positions(self):
        idx = jnp.asarray([[0], [0], [1], [0]])
        wts = jnp.ones((4, 1), jnp.float32)
        disp, comb, counts = ep_ops.masks_from_topk(idx, wts, 2, capacity=2)
        # expert 0 receives tokens 0,1 at slots 0,1; token 3 dropped (capacity)
        assert disp[0, 0, 0] and disp[1, 0, 1] and disp[2, 1, 0]
        assert not disp[3].any()
        np.testing.assert_array_equal(np.asarray(counts), [2, 1])

    def test_route_topk_losses(self, rng):
        logits = jnp.asarray(rng.standard_normal((T, E)).astype(np.float32))
        r = ep_ops.route_topk(logits, 2, capacity=8)
        assert r.aux_loss.shape == () and r.z_loss.shape == ()
        assert float(r.aux_loss) > 0
        # each token contributes weight ~1 across experts (renormalized top-2)
        total = np.asarray(r.combine_weights.sum(axis=(1, 2)))
        np.testing.assert_allclose(total, 1.0, atol=1e-5)

    @pytest.mark.parametrize("capacity", [1, 2, 5, 64])
    def test_sorted_matches_dense_masks(self, rng, capacity):
        """sorted_from_topk assigns the exact same (expert, slot) per
        assignment — including which over-capacity assignments drop — as
        masks_from_topk."""
        k = 3
        idx = jnp.asarray(rng.integers(0, E, (T, k)).astype(np.int32))
        wts = jnp.asarray(rng.random((T, k)).astype(np.float32))
        disp, comb, counts = ep_ops.masks_from_topk(idx, wts, E, capacity)
        token_for_slot, slot, kept = ep_ops.sorted_from_topk(idx, E, capacity)
        np.testing.assert_array_equal(np.asarray(kept), np.asarray(counts))
        slot_np = np.asarray(slot)
        disp_np = np.asarray(disp)
        for t in range(T):
            for j in range(k):
                s = slot_np[t, j]
                if s == E * capacity:  # dropped; aggregate check below
                    continue
                e_s, c_s = divmod(int(s), capacity)
                assert e_s == int(idx[t, j])
                assert disp_np[t, e_s, c_s]
                assert int(np.asarray(token_for_slot)[s]) == t
        # aggregate: every dense slot is claimed by exactly one assignment
        n_dense = int(disp_np.sum())
        n_sorted = int((slot_np < E * capacity).sum())
        assert n_dense == n_sorted

    def test_route_topk_sorted_losses_match_dense(self, rng):
        logits = jnp.asarray(rng.standard_normal((T, E)).astype(np.float32))
        r = ep_ops.route_topk(logits, 2, capacity=4)
        rs = ep_ops.route_topk_sorted(logits, 2, capacity=4)
        np.testing.assert_allclose(
            float(rs.aux_loss), float(r.aux_loss), rtol=1e-6
        )
        np.testing.assert_allclose(float(rs.z_loss), float(r.z_loss), rtol=1e-6)


class TestDispatchCombine:
    def _oracle_moe(self, x, idx, wts, wg, wu, wd):
        """Dense per-token oracle: out[t] = sum_k w[t,k] * FFN_{e(t,k)}(x[t])."""
        out = np.zeros_like(x)
        for t in range(x.shape[0]):
            for kk in range(idx.shape[1]):
                e = idx[t, kk]
                hgate = x[t] @ wg[e]
                hup = x[t] @ wu[e]
                act = hgate * (1 / (1 + np.exp(-hgate))) * hup
                out[t] += wts[t, kk] * (act @ wd[e])
        return out

    @pytest.mark.parametrize("impl", ["sort", "dense"])
    def test_moe_ffn_matches_dense_oracle(self, ep_mesh, rng, impl):
        """High capacity => no drops => exact match with dense computation."""
        F = 16
        e_local = E // W
        x = rng.standard_normal((W, T, H)).astype(np.float32)
        logits = rng.standard_normal((W, T, E)).astype(np.float32)
        wg = rng.standard_normal((E, H, F)).astype(np.float32) * 0.1
        wu = rng.standard_normal((E, H, F)).astype(np.float32) * 0.1
        wd = rng.standard_normal((E, F, H)).astype(np.float32) * 0.1

        def f(xv, lg, g, u, d):
            out, aux, z = ep_ops.moe_ffn(
                xv[0], lg[0], g[0], u[0], d[0], ("dp", "cp"),
                num_selected=2, capacity_factor=float(E) / 2 * 2,  # no drops
                impl=impl,
            )
            return out[None]

        # expert weights sharded over EP: member i holds experts [2i, 2i+1]
        gq = wg.reshape(W, e_local, H, F)
        uq = wu.reshape(W, e_local, H, F)
        dq = wd.reshape(W, e_local, F, H)
        out = _shard_run(
            ep_mesh, f, (x, logits, gq, uq, dq), (2, 2, 3, 3, 3), 2
        )
        # oracle with renormalized top-2 of softmax
        gates = jax.nn.softmax(jnp.asarray(logits), axis=-1)
        tv, ti = jax.lax.top_k(gates, 2)
        tv = tv / tv.sum(-1, keepdims=True)
        for w_i in range(W):
            want = self._oracle_moe(
                x[w_i], np.asarray(ti)[w_i], np.asarray(tv)[w_i], wg, wu, wd
            )
            np.testing.assert_allclose(np.asarray(out)[w_i], want, rtol=5e-4, atol=5e-5)


class TestSortedEquivalence:
    """The sorted (ragged) impl is exactly the dense impl at ANY capacity —
    same outputs, same drops, same gradients."""

    def _run_moe(self, ep_mesh, rng, impl, capacity_factor, with_grad=False):
        F = 16
        e_local = E // W
        x = rng.standard_normal((W, T, H)).astype(np.float32)
        logits = rng.standard_normal((W, T, E)).astype(np.float32)
        wg = (rng.standard_normal((W, e_local, H, F)) * 0.1).astype(np.float32)
        wu = (rng.standard_normal((W, e_local, H, F)) * 0.1).astype(np.float32)
        wd = (rng.standard_normal((W, e_local, F, H)) * 0.1).astype(np.float32)

        def f(xv, lg, g, u, d):
            out, aux, z = ep_ops.moe_ffn(
                xv[0], lg[0], g[0], u[0], d[0], ("dp", "cp"),
                num_selected=2, capacity_factor=capacity_factor, impl=impl,
            )
            return out[None], (aux + z)[None]

        if not with_grad:
            return _shard_run(
                ep_mesh, f, (x, logits, wg, wu, wd), (2, 2, 3, 3, 3), (2, 0)
            )

        def loss(args):
            out, auxz = _shard_run(
                ep_mesh, f, args, (2, 2, 3, 3, 3), (2, 0)
            )
            return jnp.sum(out * out) + jnp.sum(auxz)

        return jax.grad(lambda a: loss(a))((x, logits, wg, wu, wd))

    @pytest.mark.parametrize("capacity_factor", [0.5, 1.0, 8.0])
    def test_sort_equals_dense_any_capacity(self, ep_mesh, capacity_factor):
        rng1 = np.random.default_rng(7)
        rng2 = np.random.default_rng(7)
        out_s, aux_s = self._run_moe(ep_mesh, rng1, "sort", capacity_factor)
        out_d, aux_d = self._run_moe(ep_mesh, rng2, "dense", capacity_factor)
        np.testing.assert_allclose(
            np.asarray(out_s), np.asarray(out_d), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(aux_s), np.asarray(aux_d), rtol=1e-6
        )

    def test_sort_grads_equal_dense(self, ep_mesh):
        """Tight capacity (drops happen) — gradients agree too."""
        g_s = self._run_moe(
            ep_mesh, np.random.default_rng(3), "sort", 0.75, with_grad=True
        )
        g_d = self._run_moe(
            ep_mesh, np.random.default_rng(3), "dense", 0.75, with_grad=True
        )
        for a, b in zip(jax.tree.leaves(g_s), jax.tree.leaves(g_d)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
            )


class TestBuffer:
    def _buffer(self, mesh, **kw):
        kw.setdefault("num_experts", E)
        kw.setdefault("capacity_factor", float(E))  # no drops in tests
        return Buffer(mesh, AXIS.EP, **kw)

    def test_layout(self, ep_mesh, rng):
        buf = self._buffer(ep_mesh)
        idx = rng.integers(0, E, (W, T, 2)).astype(np.int32)
        per_rank, per_expert, in_rank = buf.get_dispatch_layout(buf.device_put(idx))
        per_expert = np.asarray(per_expert)
        # total demand conserved
        assert per_expert.sum() == W * T * 2
        for w_i in range(W):
            counts = np.bincount(idx[w_i].reshape(-1), minlength=E)
            np.testing.assert_array_equal(per_expert[w_i], counts)
        assert np.asarray(per_rank).shape == (W, W)
        assert np.asarray(in_rank).shape == (W, T, W)

    def test_dispatch_combine_roundtrip(self, ep_mesh, rng):
        """Identity experts + weights summing to 1 => combine(dispatch(x)) == x."""
        buf = self._buffer(ep_mesh)
        x = rng.standard_normal((W, T, H)).astype(np.float32)
        idx = rng.integers(0, E, (W, T, 2)).astype(np.int32)
        # make the two choices distinct to avoid double-slotting ambiguity
        idx[..., 1] = (idx[..., 0] + 1) % E
        wts = np.full((W, T, 2), 0.5, np.float32)
        gx = buf.device_put(x)
        recv, handle = buf.dispatch(gx, buf.device_put(idx), buf.device_put(wts))
        assert recv.shape[0] == W and recv.shape[1] == E // W
        out = buf.combine(recv, handle)
        np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5, atol=1e-6)

    def test_low_latency_fp8_roundtrip(self, ep_mesh, rng):
        buf = self._buffer(ep_mesh)
        x = (rng.standard_normal((W, T, 128)) * 4).astype(np.float32)
        idx = rng.integers(0, E, (W, T, 1)).astype(np.int32)
        wts = np.ones((W, T, 1), np.float32)
        gx = buf.device_put(x)
        recv, counts, handle = buf.low_latency_dispatch(
            gx, buf.device_put(idx), None, buf.device_put(wts),
            wire="dense",  # virtual CPU mesh: no ragged-all-to-all thunk
        )
        # the DeepEP contract returns per-expert recv counts alongside
        assert np.asarray(counts).sum() == W * T * 1
        out = np.asarray(buf.low_latency_combine(recv, handle))
        rel = np.abs(out - x) / (np.abs(x).max() + 1e-9)
        assert rel.max() < 0.08  # two fp8 quantization hops

    def test_bad_expert_count(self, ep_mesh):
        with pytest.raises(ValueError):
            Buffer(ep_mesh, AXIS.EP, num_experts=6)


class TestCrossPod:
    """Experts sharded over DCN-connected pods (the reference's inter-node
    EP leg, proxies posting RDMA — here DcnGroup pairwise writes)."""

    def test_two_pods_match_dense_oracle(self, devices, rng):
        import threading

        from uccl_tpu.collective.hierarchical import DcnGroup
        from uccl_tpu.ep.cross_pod import CrossPodMoE
        from uccl_tpu.p2p.store import StoreClient, StoreServer
        from uccl_tpu.parallel.distributed import Session
        from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

        P_pods, E, T, H, F, K = 2, 8, 24, 16, 32, 2
        epp = E // P_pods
        wg = (rng.standard_normal((E, H, F)) * 0.2).astype(np.float32)
        wd = (rng.standard_normal((E, F, H)) * 0.2).astype(np.float32)
        x = rng.standard_normal((P_pods, T, H)).astype(np.float32)
        logits = rng.standard_normal((P_pods, T, E)).astype(np.float32)
        gates = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        ti = np.argsort(-gates, axis=-1)[..., :K].astype(np.int32)
        tv = np.take_along_axis(gates, ti, -1)
        tv = (tv / tv.sum(-1, keepdims=True)).astype(np.float32)

        def expert_fn(buf, w):
            # buf: [epp, cap, H] — per-expert ReLU MLP
            hmid = jnp.maximum(jnp.einsum("ech,ehf->ecf", buf, w["wg"]), 0.0)
            return jnp.einsum("ecf,efh->ech", hmid, w["wd"])

        server = StoreServer()
        results = {}
        errors = []

        def pod_main(p):
            try:
                client = StoreClient("127.0.0.1", server.port)
                sess = Session(rank=p, world=P_pods, store=client)
                dcn = DcnGroup(sess, n_paths=2, tag="xpod")
                mesh = make_mesh(
                    MeshConfig(dp=4), devices[p * 4 : (p + 1) * 4]
                )
                moe = CrossPodMoE(
                    dcn, mesh, num_global_experts=E, num_selected=K,
                    capacity_factor=float(E),  # ample: no drops
                )
                w_local = {
                    "fn": expert_fn,
                    "wg": jnp.asarray(wg[p * epp : (p + 1) * epp]),
                    "wd": jnp.asarray(wd[p * epp : (p + 1) * epp]),
                }
                results[p] = moe.forward(x[p], ti[p], tv[p], w_local)
                dcn.close()
                client.close()
            except Exception as e:  # pragma: no cover
                import traceback

                errors.append((p, e, traceback.format_exc()))

        ts = [threading.Thread(target=pod_main, args=(p,)) for p in range(P_pods)]
        [t.start() for t in ts]
        [t.join(timeout=180) for t in ts]
        server.close()
        assert not errors, errors[0][2]

        # dense oracle: every token through its topk experts
        for p in range(P_pods):
            want = np.zeros((T, H), np.float32)
            for t in range(T):
                for j in range(K):
                    e = ti[p, t, j]
                    hmid = np.maximum(x[p, t] @ wg[e], 0.0)
                    want[t] += tv[p, t, j] * (hmid @ wd[e])
            np.testing.assert_allclose(results[p], want, rtol=2e-4, atol=2e-5)

    def test_two_pods_tight_capacity_runs(self, devices, rng):
        """Tight per-pod buckets drop excess (token,pod) pairs; output stays
        finite and the exchange completes."""
        import threading

        from uccl_tpu.collective.hierarchical import DcnGroup
        from uccl_tpu.ep.cross_pod import CrossPodMoE
        from uccl_tpu.p2p.store import StoreClient, StoreServer
        from uccl_tpu.parallel.distributed import Session
        from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

        P_pods, E, T, H, F, K = 2, 4, 16, 8, 16, 2
        epp = E // P_pods
        wg = (rng.standard_normal((E, H, F)) * 0.2).astype(np.float32)
        wd = (rng.standard_normal((E, F, H)) * 0.2).astype(np.float32)

        def expert_fn(buf, w):
            hmid = jnp.maximum(jnp.einsum("ech,ehf->ecf", buf, w["wg"]), 0.0)
            return jnp.einsum("ecf,efh->ech", hmid, w["wd"])

        # draw inputs on the main thread: numpy Generators are not
        # thread-safe under concurrent use
        xs = rng.standard_normal((P_pods, T, H)).astype(np.float32)
        tis = rng.integers(0, E, (P_pods, T, K)).astype(np.int32)
        tvs = np.full((P_pods, T, K), 0.5, np.float32)
        server = StoreServer()
        results, errors = {}, []

        def pod_main(p):
            try:
                client = StoreClient("127.0.0.1", server.port)
                sess = Session(rank=p, world=P_pods, store=client)
                dcn = DcnGroup(sess, n_paths=2, tag="xpod_tight")
                mesh = make_mesh(MeshConfig(dp=4), devices[p * 4 : (p + 1) * 4])
                moe = CrossPodMoE(
                    dcn, mesh, num_global_experts=E, num_selected=K,
                    capacity_factor=0.5,  # forces drops
                )
                results[p] = moe.forward(xs[p], tis[p], tvs[p], {
                    "fn": expert_fn,
                    "wg": jnp.asarray(wg[p * epp : (p + 1) * epp]),
                    "wd": jnp.asarray(wd[p * epp : (p + 1) * epp]),
                })
                dcn.close(); client.close()
            except Exception as e:  # pragma: no cover
                import traceback
                errors.append((p, traceback.format_exc()))

        ts = [threading.Thread(target=pod_main, args=(p,)) for p in range(P_pods)]
        [t.start() for t in ts]; [t.join(timeout=180) for t in ts]
        server.close()
        assert not errors, errors[0][1]
        for p in range(P_pods):
            assert np.isfinite(results[p]).all()


class TestCrossPodTraining:
    """Training-grade cross-pod EP: backward runs the same DCN exchanges and
    gradients match a single-process jax oracle (the reference serves EP
    inside torch autograd — ep/src/proxy.cpp:701 posts RDMA in fwd AND bwd)."""

    def _run_pods(self, devices, rng, n_chunks):
        import threading

        from uccl_tpu.collective.hierarchical import DcnGroup
        from uccl_tpu.ep.cross_pod import CrossPodMoE
        from uccl_tpu.p2p.store import StoreClient, StoreServer
        from uccl_tpu.parallel.distributed import Session
        from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

        P_pods, E, T, H, F, K = 2, 8, 24, 16, 32, 2
        epp = E // P_pods
        wg = (rng.standard_normal((E, H, F)) * 0.2).astype(np.float32)
        wd = (rng.standard_normal((E, F, H)) * 0.2).astype(np.float32)
        x = rng.standard_normal((P_pods, T, H)).astype(np.float32)
        logits = rng.standard_normal((P_pods, T, E)).astype(np.float32)
        gates = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        ti = np.argsort(-gates, axis=-1)[..., :K].astype(np.int32)
        tv = np.take_along_axis(gates, ti, -1)
        tv = (tv / tv.sum(-1, keepdims=True)).astype(np.float32)

        def expert_fn(buf, w):
            hmid = jnp.maximum(jnp.einsum("ech,ehf->ecf", buf, w["wg"]), 0.0)
            return jnp.einsum("ecf,efh->ech", hmid, w["wd"])

        server = StoreServer()
        results = {}
        errors = []

        def pod_main(p):
            try:
                client = StoreClient("127.0.0.1", server.port)
                sess = Session(rank=p, world=P_pods, store=client)
                dcn = DcnGroup(sess, n_paths=2, tag=f"xpodtr{n_chunks}")
                mesh = make_mesh(
                    MeshConfig(dp=4), devices[p * 4 : (p + 1) * 4]
                )
                moe = CrossPodMoE(
                    dcn, mesh, num_global_experts=E, num_selected=K,
                    capacity_factor=float(E), n_chunks=n_chunks,
                )
                w_local = {
                    "fn": expert_fn,
                    "wg": jnp.asarray(wg[p * epp : (p + 1) * epp]),
                    "wd": jnp.asarray(wd[p * epp : (p + 1) * epp]),
                }
                out = moe.forward(x[p], ti[p], tv[p], w_local)
                # loss = sum(out^2) per pod -> dout = 2*out
                dx, dw, dwarr = moe.backward(2.0 * out)
                results[p] = (out, dx, dw, dwarr)
                dcn.close()
                client.close()
            except Exception as e:  # pragma: no cover
                import traceback

                errors.append((p, e, traceback.format_exc()))

        ts = [threading.Thread(target=pod_main, args=(p,))
              for p in range(P_pods)]
        [t.start() for t in ts]
        [t.join(timeout=180) for t in ts]
        server.close()
        assert not errors, errors[0][2]
        return results, (x, ti, tv, wg, wd, P_pods, E, T, H, F, K, epp)

    @pytest.mark.parametrize("n_chunks", [1, 2], ids=["serial", "overlap"])
    def test_grads_match_oracle(self, devices, rng, n_chunks):
        results, (x, ti, tv, wg, wd, P_pods, E, T, H, F, K, epp) = (
            self._run_pods(devices, rng, n_chunks)
        )

        # oracle: global loss = sum over pods of sum(out_p^2); autodiff
        def oracle_loss(xg, tvg, wgg, wdg):
            total = 0.0
            for p in range(P_pods):
                out = jnp.zeros((T, H), jnp.float32)
                for j in range(K):
                    e = ti[p, :, j]
                    hmid = jnp.maximum(
                        jnp.einsum("th,thf->tf", xg[p], wgg[e]), 0.0
                    )
                    y = jnp.einsum("tf,tfh->th", hmid, wdg[e])
                    out = out + tvg[p, :, j][:, None] * y
                total = total + jnp.sum(out**2)
            return total

        g_x, g_tv, g_wg, g_wd = jax.grad(oracle_loss, argnums=(0, 1, 2, 3))(
            jnp.asarray(x), jnp.asarray(tv), jnp.asarray(wg), jnp.asarray(wd)
        )
        for p in range(P_pods):
            out, dx, dw, dwarr = results[p]
            np.testing.assert_allclose(
                dx, np.asarray(g_x[p]), rtol=2e-3, atol=2e-4
            )
            np.testing.assert_allclose(
                dw, np.asarray(g_tv[p]), rtol=2e-3, atol=2e-4
            )
            np.testing.assert_allclose(
                dwarr["wg"], np.asarray(g_wg[p * epp:(p + 1) * epp]),
                rtol=2e-3, atol=2e-4,
            )
            np.testing.assert_allclose(
                dwarr["wd"], np.asarray(g_wd[p * epp:(p + 1) * epp]),
                rtol=2e-3, atol=2e-4,
            )

    def test_overlap_matches_serial_forward(self, devices, rng):
        """n_chunks=2 (pipelined exchanges) is numerically identical to the
        serial schedule."""
        r1, _ = self._run_pods(devices, rng, 1)
        rng2 = np.random.default_rng(0)
        r2, _ = self._run_pods(devices, rng2, 2)
        # same rng fixture seed drives both runs via _run_pods args
        for p in r1:
            np.testing.assert_allclose(
                r1[p][0], r2[p][0], rtol=1e-5, atol=1e-6
            )


class TestCrossPodCaches:
    def test_cache_keys_include_expert_fn_identity(self):
        """Same shapes + a different expert_fn must not reuse the stale
        jitted closure (the caches close over expert_fn)."""
        from uccl_tpu.ep.cross_pod import CrossPodMoE

        moe = object.__new__(CrossPodMoE)
        moe.experts_per_pod = 2
        moe._compute_cache = {}
        moe._vjp_cache = {}

        def fn_a(buf, w):
            return buf * 2.0

        def fn_b(buf, w):
            return buf * 3.0

        shape_key = ((4, 8), 2)
        fa = moe._local_compute(shape_key, fn_a)
        fb = moe._local_compute(shape_key, fn_b)
        assert fa is not fb

        xs = jnp.ones((4, 8), jnp.float32)
        idx = np.zeros((4, 2), np.int32)
        idx[:, 1] = 1
        wts = jnp.full((4, 2), 0.5, jnp.float32)
        ya = np.asarray(fa(xs, jnp.asarray(idx), wts, {}))
        yb = np.asarray(fb(xs, jnp.asarray(idx), wts, {}))
        assert not np.allclose(ya, yb)
        np.testing.assert_allclose(yb, ya * 1.5, rtol=1e-6)

        va = moe._local_vjp(shape_key, fn_a)
        vb = moe._local_vjp(shape_key, fn_b)
        assert va is not vb


class TestBufferStats:
    """Per-op EP stats (reference: Stats class bound at uccl_ep.cc:2411)."""

    def test_counters_and_drop_aggregates(self, devices):
        import jax.numpy as jnp

        from uccl_tpu.ep import Buffer
        from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(dp=8), devices)
        e, t, k, h, w = 8, 16, 2, 32, 8
        buf = Buffer(mesh, num_experts=e, capacity_factor=0.25)  # tight: drops
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((w, t, h)).astype(np.float32))
        idx = jnp.asarray(rng.integers(0, e, (w, t, k)).astype(np.int32))
        recv, handle = buf.dispatch(x, idx)
        buf.combine(recv, handle)
        rx, counts, ll_handle = buf.low_latency_dispatch(
            x, idx, wire="dense", wire_fp8=False
        )
        buf.low_latency_combine(rx, ll_handle)
        s = buf.stats()
        assert s["ops"]["dispatch"] == 1
        assert s["ops"]["combine"] == 1
        assert s["ops"]["low_latency_dispatch"] == 1
        assert s["ops"]["low_latency_combine"] == 1
        d = s["dispatch"]
        assert d["routed_rows"] == w * t * k
        assert d["kept_rows"] + d["dropped_rows"] == d["routed_rows"]
        assert d["dropped_rows"] > 0  # cf=0.25 must drop
        assert 0 < d["drop_fraction"] < 1
        ll = s["low_latency"]
        assert ll["recv_rows"] == w * t * k  # LL default bound is lossless
        assert ll["wire_payload_bytes"] == ll["recv_rows"] * h * 2


class TestDispatchRecvCounts:
    """The sorted-path handle carries per-(source, local-expert) received
    row counts (VERDICT round-2 weak #4: consumers must be able to skip
    empty slots / size grouped GEMMs without assuming full capacity)."""

    def test_counts_match_demand_under_capacity(self, devices):
        import jax.numpy as jnp

        from uccl_tpu.ep import Buffer
        from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(dp=8), devices)
        e, t, k, h, w = 8, 16, 2, 32, 8
        buf = Buffer(mesh, num_experts=e, capacity_factor=0.5)  # drops
        cap = buf.capacity(t)
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.standard_normal((w, t, h)).astype(np.float32))
        idx_np = rng.integers(0, e, (w, t, k)).astype(np.int32)
        recv, handle = buf.dispatch(x, jnp.asarray(idx_np))
        rc = np.asarray(handle.recv_counts)  # [W, W_src, E_local]
        assert rc.shape == (w, w, e // w)
        e_local = e // w
        for dst in range(w):
            for src in range(w):
                for le in range(e_local):
                    ge = dst * e_local + le
                    demand = int((idx_np[src] == ge).sum())
                    assert rc[dst, src, le] == min(demand, cap), (
                        dst, src, le, demand, cap
                    )
        # occupancy bound: each (src, expert) chunk holds <= capacity rows
        assert rc.max() <= cap


class TestCrossImplFuzz:
    """Randomized shape/seed sweep: the three moe_ffn implementations
    (dense mask-einsum oracle, sorted/ragged fast path, packed low-latency
    grouped-GEMM) must agree at ample capacity across arbitrary
    (T, E, K, H, F) — the property the fixed-shape oracle tests pin at one
    point each. Catches shape-dependent layout bugs (odd T, K > 2,
    non-power-of-two H) that single-shape tests cannot."""

    @pytest.mark.parametrize("seed", range(6))
    def test_impls_agree_on_random_shapes(self, ep_mesh, seed):
        rng = np.random.default_rng(1000 + seed)
        t = int(rng.integers(5, 40))
        e = int(rng.choice([8, 16]))  # divisible by W=4
        k = int(rng.integers(1, 4))
        h = int(rng.choice([8, 24, 48]))
        f = int(rng.choice([8, 32]))
        e_local = e // W
        x = rng.standard_normal((W, t, h)).astype(np.float32)
        logits = rng.standard_normal((W, t, e)).astype(np.float32)
        wg = (rng.standard_normal((W, e_local, h, f)) * 0.1).astype(np.float32)
        wu = (rng.standard_normal((W, e_local, h, f)) * 0.1).astype(np.float32)
        wd = (rng.standard_normal((W, e_local, f, h)) * 0.1).astype(np.float32)

        outs = {}
        for impl in ("dense", "sort", "ll"):
            def fn(xv, lg, g, u, d, impl=impl):
                out, aux, z = ep_ops.moe_ffn(
                    xv[0], lg[0], g[0], u[0], d[0], ("dp", "cp"),
                    num_selected=k, capacity_factor=float(e),  # no drops
                    impl=impl,
                )
                return out[None]

            outs[impl] = np.asarray(
                _shard_run(
                    ep_mesh, fn, (x, logits, wg, wu, wd), (2, 2, 3, 3, 3), 2
                )
            )
            assert outs[impl].shape == (W, t, h), (impl, outs[impl].shape)
        shapes = f"T={t} E={e} K={k} H={h} F={f}"
        np.testing.assert_allclose(
            outs["sort"], outs["dense"], rtol=2e-3, atol=1e-5,
            err_msg=f"sort vs dense at {shapes}",
        )
        np.testing.assert_allclose(
            outs["ll"], outs["dense"], rtol=2e-3, atol=1e-5,
            err_msg=f"ll vs dense at {shapes}",
        )
