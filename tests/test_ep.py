"""Expert-parallel dispatch/combine correctness — the analog of the reference's
ep/bench/test_low_latency.py correctness asserts ("All correctness tests
passed"), against a dense-MoE numpy oracle on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from uccl_tpu.ep import Buffer, ops as ep_ops
from uccl_tpu.parallel.mesh import AXIS, MeshConfig, make_mesh


@pytest.fixture(scope="module")
def ep_mesh(devices):
    return make_mesh(MeshConfig(dp=4, tp=2), devices)


W = 4  # EP world (dp=4)
E = 8  # global experts
T = 16  # tokens per member
H = 32  # hidden


def _shard_run(mesh, fn, in_arrays, in_dims, out_dims):
    specs_in = tuple(P(("dp", "cp"), *([None] * d)) for d in in_dims)
    specs_out = jax.tree.map(lambda d: P(("dp", "cp"), *([None] * d)), out_dims)
    mapped = jax.shard_map(
        fn, mesh=mesh, in_specs=specs_in, out_specs=specs_out, check_vma=False
    )
    return jax.jit(mapped)(*in_arrays)


class TestRouting:
    def test_masks_from_topk_positions(self):
        idx = jnp.asarray([[0], [0], [1], [0]])
        wts = jnp.ones((4, 1), jnp.float32)
        disp, comb, counts = ep_ops.masks_from_topk(idx, wts, 2, capacity=2)
        # expert 0 receives tokens 0,1 at slots 0,1; token 3 dropped (capacity)
        assert disp[0, 0, 0] and disp[1, 0, 1] and disp[2, 1, 0]
        assert not disp[3].any()
        np.testing.assert_array_equal(np.asarray(counts), [2, 1])

    def test_route_topk_losses(self, rng):
        logits = jnp.asarray(rng.standard_normal((T, E)).astype(np.float32))
        r = ep_ops.route_topk(logits, 2, capacity=8)
        assert r.aux_loss.shape == () and r.z_loss.shape == ()
        assert float(r.aux_loss) > 0
        # each token contributes weight ~1 across experts (renormalized top-2)
        total = np.asarray(r.combine_weights.sum(axis=(1, 2)))
        np.testing.assert_allclose(total, 1.0, atol=1e-5)


class TestDispatchCombine:
    def _oracle_moe(self, x, idx, wts, wg, wu, wd):
        """Dense per-token oracle: out[t] = sum_k w[t,k] * FFN_{e(t,k)}(x[t])."""
        out = np.zeros_like(x)
        for t in range(x.shape[0]):
            for kk in range(idx.shape[1]):
                e = idx[t, kk]
                hgate = x[t] @ wg[e]
                hup = x[t] @ wu[e]
                act = hgate * (1 / (1 + np.exp(-hgate))) * hup
                out[t] += wts[t, kk] * (act @ wd[e])
        return out

    def test_moe_ffn_matches_dense_oracle(self, ep_mesh, rng):
        """High capacity => no drops => exact match with dense computation."""
        F = 16
        e_local = E // W
        x = rng.standard_normal((W, T, H)).astype(np.float32)
        logits = rng.standard_normal((W, T, E)).astype(np.float32)
        wg = rng.standard_normal((E, H, F)).astype(np.float32) * 0.1
        wu = rng.standard_normal((E, H, F)).astype(np.float32) * 0.1
        wd = rng.standard_normal((E, F, H)).astype(np.float32) * 0.1

        def f(xv, lg, g, u, d):
            out, aux, z = ep_ops.moe_ffn(
                xv[0], lg[0], g[0], u[0], d[0], ("dp", "cp"),
                num_selected=2, capacity_factor=float(E) / 2 * 2,  # no drops
            )
            return out[None]

        # expert weights sharded over EP: member i holds experts [2i, 2i+1]
        gq = wg.reshape(W, e_local, H, F)
        uq = wu.reshape(W, e_local, H, F)
        dq = wd.reshape(W, e_local, F, H)
        out = _shard_run(
            ep_mesh, f, (x, logits, gq, uq, dq), (2, 2, 3, 3, 3), 2
        )
        # oracle with renormalized top-2 of softmax
        gates = jax.nn.softmax(jnp.asarray(logits), axis=-1)
        tv, ti = jax.lax.top_k(gates, 2)
        tv = tv / tv.sum(-1, keepdims=True)
        for w_i in range(W):
            want = self._oracle_moe(
                x[w_i], np.asarray(ti)[w_i], np.asarray(tv)[w_i], wg, wu, wd
            )
            np.testing.assert_allclose(np.asarray(out)[w_i], want, rtol=5e-4, atol=5e-5)


class TestBuffer:
    def _buffer(self, mesh, **kw):
        kw.setdefault("num_experts", E)
        kw.setdefault("capacity_factor", float(E))  # no drops in tests
        return Buffer(mesh, AXIS.EP, **kw)

    def test_layout(self, ep_mesh, rng):
        buf = self._buffer(ep_mesh)
        idx = rng.integers(0, E, (W, T, 2)).astype(np.int32)
        per_rank, per_expert, in_rank = buf.get_dispatch_layout(buf.device_put(idx))
        per_expert = np.asarray(per_expert)
        # total demand conserved
        assert per_expert.sum() == W * T * 2
        for w_i in range(W):
            counts = np.bincount(idx[w_i].reshape(-1), minlength=E)
            np.testing.assert_array_equal(per_expert[w_i], counts)
        assert np.asarray(per_rank).shape == (W, W)
        assert np.asarray(in_rank).shape == (W, T, W)

    def test_dispatch_combine_roundtrip(self, ep_mesh, rng):
        """Identity experts + weights summing to 1 => combine(dispatch(x)) == x."""
        buf = self._buffer(ep_mesh)
        x = rng.standard_normal((W, T, H)).astype(np.float32)
        idx = rng.integers(0, E, (W, T, 2)).astype(np.int32)
        # make the two choices distinct to avoid double-slotting ambiguity
        idx[..., 1] = (idx[..., 0] + 1) % E
        wts = np.full((W, T, 2), 0.5, np.float32)
        gx = buf.device_put(x)
        recv, handle = buf.dispatch(gx, buf.device_put(idx), buf.device_put(wts))
        assert recv.shape[0] == W and recv.shape[1] == E // W
        out = buf.combine(recv, handle)
        np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5, atol=1e-6)

    def test_low_latency_fp8_roundtrip(self, ep_mesh, rng):
        buf = self._buffer(ep_mesh)
        x = (rng.standard_normal((W, T, 128)) * 4).astype(np.float32)
        idx = rng.integers(0, E, (W, T, 1)).astype(np.int32)
        wts = np.ones((W, T, 1), np.float32)
        gx = buf.device_put(x)
        recv, handle = buf.low_latency_dispatch(gx, buf.device_put(idx), buf.device_put(wts))
        out = np.asarray(buf.low_latency_combine(recv, handle))
        rel = np.abs(out - x) / (np.abs(x).max() + 1e-9)
        assert rel.max() < 0.08  # two fp8 quantization hops

    def test_bad_expert_count(self, ep_mesh):
        with pytest.raises(ValueError):
            Buffer(ep_mesh, AXIS.EP, num_experts=6)
