"""Fleet prefix-cache directory + cache-aware N×M routing (ISSUE 19).

Four layers, cheapest first:

* **Directory units** (dict-backed store, no wire): publish registers
  every chunk-aligned prefix depth, lookup is a deepest-first
  longest-prefix match clamped to the requester's usable depth, the
  PR 18 namespace tag isolates tenants by construction, withdraw only
  tombstones entries still pointing at the withdrawn blob, a dead
  owner's index sweeps clean, malformed store bytes never raise.
* **Publisher units**: capacity-evicted blobs are de-published, oversize
  and remote-tier (T2) residents are never advertised, every failure is
  counted and swallowed (admission never blocks on the fleet plane).
* **Fleet plane over real loopback** (StoreServer + Endpoints +
  Channels, stub KV backends): a prefix computed on worker A lands on
  worker B as a counter-audited hit (``fleet_cache_hits_total`` +
  ``p2p_bytes_total{verb=kv_tier}``) with B's prefill resuming past the
  imported rows; the fetched prefix self-propagates (B's second request
  is a local T0 hit); a stale owner degrades to the already-counted
  cold miss — never wrong bytes; a dead peer latches after
  ``fail_limit`` failures and its directory entries are swept.
* **Routing + N×M plane**: the router steers toward the replica owning
  the deepest cached prefix (local trie and directory credit), tenants
  ride ``Router.submit`` → BEGIN → adoption so fleet-merged
  ``per_tenant`` series stay truthful, and a 3×2 prefill/decode fan-in
  survives a mid-stream prefill-engine kill with lease conservation
  (the bit-exact 3×2 arm lives in tests/test_disagg_transport.py,
  slow-marked like every multi-compile arm).
"""

import time
import types

import numpy as np
import pytest

from uccl_tpu import obs
from uccl_tpu.p2p import Endpoint
from uccl_tpu.p2p.store import StoreClient, StoreServer
from uccl_tpu.serving import (
    FailureDetector, PrefixCache, RequestState, Router, ServingEngine,
    TierRef,
)
from uccl_tpu.serving.fleet import (
    FleetCachePublisher, FleetDirectory, FleetKvServer, FleetWorker,
    _ChunkShim,
)
from uccl_tpu.serving.metrics import ServingMetrics

CHUNK = 4


class _DictStore:
    """The two store verbs the directory uses, over a plain dict."""

    def __init__(self):
        self.d = {}

    def set(self, key, value):
        self.d[key] = bytes(value)

    def get(self, key):
        return self.d.get(key)


def _keys(prompt, n_chunks, ns=""):
    """The trie's namespaced chunk-key path for ``prompt`` — built by the
    SAME generator the cache and directory share (zero drift)."""
    return list(PrefixCache._chunks(
        _ChunkShim(CHUNK), np.asarray(prompt, np.int32), n_chunks, ns))


def _expected_rows(n, layers=2, heads=2, dim=4):
    pos = np.arange(n, dtype=np.float32)
    k = np.broadcast_to((pos + 1.0)[None, :, None, None],
                        (layers, n, heads, dim)).copy()
    return k, -k


class _FleetStubBackend:
    """Chunk-aware stub with a REAL host KV pool: prefill writes
    deterministic rows (k=pos+1, v=-(pos+1)) so a cross-worker import is
    checkable byte-for-byte, and export/import/copy follow the engine
    backends' surface."""

    def __init__(self, n_slots=2, max_seq=64, layers=2, heads=2, dim=4):
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.k = np.zeros((layers, n_slots, max_seq, heads, dim),
                          np.float32)
        self.v = np.zeros_like(self.k)
        self.n_decodes = 0
        self.calls = []
        self.imports = []

    def _write(self, slot, lo, hi):
        pos = np.arange(lo, hi, dtype=np.float32)
        self.k[:, slot, lo:hi] = (pos + 1.0)[None, :, None, None]
        self.v[:, slot, lo:hi] = -(pos + 1.0)[None, :, None, None]

    def prefill(self, tokens, lens, mask, start=None):
        slots = tuple(int(s) for s in np.flatnonzero(mask))
        starts = tuple(int(start[s]) for s in slots) if start is not None \
            else (0,) * len(slots)
        self.calls.append(("prefill", slots, starts))
        for s, lo in zip(slots, starts):
            self._write(s, lo, min(lo + tokens.shape[1], int(lens[s])))
        return np.full(self.n_slots, 100, np.int32)

    def decode(self, tokens, active):
        self.n_decodes += 1
        return np.full(self.n_slots, self.n_decodes, np.int32)

    def copy_slot_prefix(self, dst, src, n):
        self.calls.append(("copy", dst, src, n))
        self.k[:, dst, :n] = self.k[:, src, :n]
        self.v[:, dst, :n] = self.v[:, src, :n]

    def export_slot_kv(self, slot, lo, hi):
        return (self.k[:, slot, lo:hi].copy(),
                self.v[:, slot, lo:hi].copy())

    def import_slot_kv(self, slot, k_rows, v_rows, *, length):
        self.imports.append((slot, int(length)))
        self.k[:, slot, :length] = k_rows
        self.v[:, slot, :length] = v_rows


class TestDirectory:
    def test_publish_every_depth_deepest_lookup_wins(self):
        d = FleetDirectory(_DictStore(), "A", CHUNK)
        p = np.arange(12, dtype=np.int32)
        dks = d.publish(_keys(p, 3), 7, True, 1536)
        assert len(dks) == 3
        assert obs.gauge("fleet_dir_resident_entries").get() == 3
        # the requester's OWN usable depth clamps the match: a 12-token
        # prompt can resume from 8, a 13-token one from 12
        hit = d.lookup(p)
        assert (hit["owner"], hit["key"], hit["tokens"]) == ("A", 7, 8)
        assert d.lookup(np.concatenate([p, [99]]))["tokens"] == 12
        assert d.lookup(p[:9])["tokens"] == 8
        assert d.lookup(p[:5])["tokens"] == 4
        assert d.lookup(p[:4]) is None  # usable depth 0
        assert d.lookup(np.arange(50, 62, dtype=np.int32)) is None

    def test_namespace_isolation_by_construction(self):
        d = FleetDirectory(_DictStore(), "A", CHUNK)
        p = np.arange(12, dtype=np.int32)
        d.publish(_keys(p, 3, ns="acme|style@1"), 0, True, 100)
        assert d.lookup(p, "") is None
        assert d.lookup(p, "other") is None
        assert d.lookup(p, "acme|style@1")["tokens"] == 8

    def test_tombstone_falls_back_to_shallower_depth(self):
        d = FleetDirectory(_DictStore(), "A", CHUNK)
        p = np.arange(16, dtype=np.int32)
        d.publish(_keys(p, 3), 0, True, 100)
        i0 = obs.counter("fleet_dir_invalidations_total").get()
        d.tombstone(d.lookup(p)["dir_key"])  # kills the depth-3 entry
        assert obs.counter("fleet_dir_invalidations_total").get() == i0 + 1
        assert d.lookup(p)["tokens"] == 8  # depth-2 survives

    def test_withdraw_only_kills_matching_blob_key(self):
        d = FleetDirectory(_DictStore(), "A", CHUNK)
        p = np.arange(12, dtype=np.int32)
        dks = d.publish(_keys(p, 3), 0, True, 100)
        d.publish(_keys(p, 3), 1, True, 100)  # re-published, newer blob
        d.withdraw(dks, 0)  # stale withdraw: keys now point at blob 1
        assert d.lookup(p)["key"] == 1
        d.withdraw(dks, 1)
        assert d.lookup(p) is None
        assert obs.gauge("fleet_dir_resident_entries").get() == 0

    def test_invalidate_owner_sweeps_only_the_dead(self):
        store = _DictStore()
        da = FleetDirectory(store, "A", CHUNK)
        db = FleetDirectory(store, "B", CHUNK)
        pa = np.arange(12, dtype=np.int32)
        pb = np.arange(20, 32, dtype=np.int32)
        da.publish(_keys(pa, 3), 0, True, 100)
        db.publish(_keys(pb, 3), 0, True, 100)
        assert db.invalidate_owner("A") == 3
        assert db.lookup(pa) is None
        assert db.lookup(pb)["owner"] == "B"
        assert db.invalidate_owner("A") == 0  # idempotent
        assert db.invalidate_owner("never-existed") == 0

    def test_malformed_store_bytes_never_raise(self):
        store = _DictStore()
        d = FleetDirectory(store, "A", CHUNK)
        p = np.arange(12, dtype=np.int32)
        dks = d.publish(_keys(p, 3), 0, True, 100)
        store.set(dks[-1], b"not json")  # corrupt the depth-3 entry
        # a 13-token prompt probes depth 3 first: the corrupt entry is
        # skipped, the depth-2 one answers
        assert d.lookup(np.concatenate([p, [99]]))["tokens"] == 8
        store.set("fdir_idx/A", b"garbage")
        assert d.invalidate_owner("A") == 0


class TestPublisher:
    def _pub(self, capacity, n_slots=2):
        backend = _FleetStubBackend(n_slots=n_slots)
        for s in range(n_slots):
            backend._write(s, 0, 8)
        d = FleetDirectory(_DictStore(), "W", CHUNK)
        srv = FleetKvServer(capacity_bytes=capacity, ep=None)
        return FleetCachePublisher(d, srv, backend), d

    def test_capacity_eviction_depublishes(self):
        # one 8-token blob is 1024B here; 1600B holds exactly one
        pub, d = self._pub(1600)
        p1 = np.arange(8, dtype=np.int32)
        p2 = np.arange(40, 48, dtype=np.int32)
        pub.on_insert(0, _keys(p1, 2))
        assert d.lookup(np.concatenate([p1, [9]]))["tokens"] == 8
        pub.on_insert(1, _keys(p2, 2))
        # blob 0 was LRU-evicted for blob 1: its directory entries die too
        assert d.lookup(np.concatenate([p1, [9]])) is None
        assert d.lookup(np.concatenate([p2, [9]]))["tokens"] == 8
        assert obs.gauge("fleet_dir_resident_entries").get() == 2

    def test_oversize_and_t2_residents_not_advertised(self):
        pub, d = self._pub(512)  # smaller than one entry
        p1 = np.arange(8, dtype=np.int32)
        pub.on_insert(0, _keys(p1, 2))
        assert d.lookup(np.concatenate([p1, [9]])) is None
        # a T2 ref's bytes live on a remote tier peer: never advertised
        pub2, d2 = self._pub(1 << 20)
        ref = TierRef("t2", 5, 8, True, 1024)
        pub2.on_insert(ref, _keys(p1, 2))
        assert d2.lookup(np.concatenate([p1, [9]])) is None

    def test_remove_withdraws_and_drops_blob(self):
        pub, d = self._pub(1 << 20)
        p1 = np.arange(8, dtype=np.int32)
        pub.on_insert(0, _keys(p1, 2))
        pub.on_remove(0)
        assert d.lookup(np.concatenate([p1, [9]])) is None
        assert pub.server._get(0) is None
        pub.on_remove(0)  # idempotent

    def test_publish_failure_is_counted_not_raised(self):
        pub, d = self._pub(1 << 20)
        pub.backend = object()  # no export surface
        e0 = obs.counter("fleet_cache_errors_total").get(reason="publish")
        pub.on_insert(0, _keys(np.arange(8, dtype=np.int32), 2))
        assert obs.counter("fleet_cache_errors_total").get(
            reason="publish") == e0 + 1


@pytest.fixture
def fleet():
    """Factory for (engine, FleetWorker) pairs sharing one real store
    server, talking over real loopback endpoints/channels."""
    srv = StoreServer()
    made = []

    def make(name, n_slots=2, **kw):
        sc = StoreClient("127.0.0.1", srv.port)
        eng = ServingEngine(_FleetStubBackend(n_slots=n_slots),
                            prefill_chunk=CHUNK,
                            prefix_cache=PrefixCache(CHUNK))
        kw.setdefault("capacity_bytes", 1 << 20)
        kw.setdefault("max_entry_bytes", 1 << 20)
        kw.setdefault("fail_limit", 1)
        kw.setdefault("timeout_ms", 5000)
        fw = FleetWorker(name, sc, Endpoint(), chunk=CHUNK, **kw)
        eng.attach_fleet(fw)
        made.append((eng, fw, sc))
        return eng, fw

    yield make
    for eng, fw, sc in made:
        fw.close()
        try:
            fw.ep.close()
        except Exception:
            pass
        sc.close()
    srv.close()


class TestFleetPlane:
    def test_cross_worker_hit_counted_and_self_propagates(self, fleet):
        eng_a, fw_a = fleet("A")
        eng_b, fw_b = fleet("B")
        p = (np.arange(12) % 64).astype(np.int32)
        eng_a.submit(p, max_new_tokens=2)
        eng_a.drain()
        assert eng_a.pool.n_parked == 1
        assert obs.gauge("fleet_dir_resident_entries").get() == 3
        h0 = obs.counter("fleet_cache_hits_total").get()
        t0 = obs.counter("fleet_cache_tokens_imported_total").get()
        b0 = obs.counter("p2p_bytes_total").get(verb="kv_tier")
        r = eng_b.submit(p.copy(), max_new_tokens=2)
        eng_b.drain()
        assert r.state is RequestState.FINISHED
        assert r.cache_hit_len == 8 and r.cache_hit_exact
        # THE acceptance audit: the hit counter moved AND real bytes rode
        # the T2 wire path (not a local alias)
        assert obs.counter("fleet_cache_hits_total").get() == h0 + 1
        assert obs.counter("fleet_cache_tokens_imported_total").get() \
            == t0 + 8
        assert obs.counter("p2p_bytes_total").get(verb="kv_tier") > b0
        # the import landed in B's OWN slot and prefill resumed past it
        assert eng_b.backend.imports == [(r.slot, 8)]
        starts = [c[2][c[1].index(r.slot)] for c in eng_b.backend.calls
                  if c[0] == "prefill" and r.slot in c[1]]
        assert starts and min(starts) == 8
        ek, ev = _expected_rows(12)
        np.testing.assert_array_equal(eng_b.backend.k[:, r.slot, :12], ek)
        np.testing.assert_array_equal(eng_b.backend.v[:, r.slot, :12], ev)
        assert eng_b.pool.leaked() == 0
        # self-propagation: the fetched prefix parked locally on retire,
        # so B's next identical prompt is a plain T0 hit — no new fetch
        r2 = eng_b.submit(p.copy(), max_new_tokens=2)
        eng_b.drain()
        assert r2.cache_hit_len == 8
        assert obs.counter("fleet_cache_hits_total").get() == h0 + 1
        assert any(c[0] == "copy" for c in eng_b.backend.calls)

    def test_namespace_isolation_across_workers(self, fleet):
        eng_a, _ = fleet("A", n_slots=3)
        eng_b, _ = fleet("B", n_slots=3)
        p = (np.arange(30, 42) % 64).astype(np.int32)
        eng_a.submit(p, max_new_tokens=2, tenant="acme")
        eng_a.drain()
        h0 = obs.counter("fleet_cache_hits_total").get()
        r = eng_b.submit(p.copy(), max_new_tokens=2)  # default tenant
        eng_b.drain()
        assert r.cache_hit_len == 0
        assert obs.counter("fleet_cache_hits_total").get() == h0
        r2 = eng_b.submit(p.copy(), max_new_tokens=2, tenant="acme")
        eng_b.drain()
        assert r2.cache_hit_len == 8
        assert obs.counter("fleet_cache_hits_total").get() == h0 + 1

    def test_stale_owner_is_a_counted_cold_miss(self, fleet):
        """Owner drops the blob between the directory read and the fetch:
        the request degrades to the cold miss admission already counted —
        never wrong bytes — and the entry is tombstoned."""
        eng_a, fw_a = fleet("A")
        eng_b, _ = fleet("B")
        p = (np.arange(7, 19) % 64).astype(np.int32)
        eng_a.submit(p, max_new_tokens=2)
        eng_a.drain()
        fw_a.server.drop_local(0)  # blob gone, directory entries live
        s0 = obs.counter("fleet_cache_stale_total").get()
        h0 = obs.counter("fleet_cache_hits_total").get()
        r = eng_b.submit(p.copy(), max_new_tokens=2)
        eng_b.drain()
        assert r.state is RequestState.FINISHED
        assert r.cache_hit_len == 0
        assert obs.counter("fleet_cache_stale_total").get() == s0 + 1
        assert obs.counter("fleet_cache_hits_total").get() == h0
        # B prefilled cold and bit-correct rows landed anyway
        ek, _ = _expected_rows(12)
        np.testing.assert_array_equal(eng_b.backend.k[:, r.slot, :12], ek)
        assert eng_b.pool.leaked() == 0

    def test_eviction_withdraws_directory_entries(self, fleet):
        eng_a, fw_a = fleet("A")
        eng_b, _ = fleet("B")
        p = (np.arange(11, 23) % 64).astype(np.int32)
        eng_a.submit(p, max_new_tokens=2)
        eng_a.drain()
        assert eng_a.prefix_cache.evict_lru(eng_a.pool) is not None
        assert fw_a.directory.lookup(p, "") is None
        h0 = obs.counter("fleet_cache_hits_total").get()
        r = eng_b.submit(p.copy(), max_new_tokens=2)
        eng_b.drain()
        assert r.cache_hit_len == 0
        assert obs.counter("fleet_cache_hits_total").get() == h0

    def test_dead_peer_latches_and_sweeps_directory(self, fleet):
        eng_a, _ = fleet("A", n_slots=3)
        eng_b, fw_b = fleet("B", n_slots=3)
        p1 = (np.arange(3, 15) % 64).astype(np.int32)
        p2 = (np.arange(41, 53) % 64).astype(np.int32)
        eng_a.submit(p1, max_new_tokens=2)
        eng_a.drain()
        r1 = eng_b.submit(p1.copy(), max_new_tokens=2)  # dials A: a hit
        eng_b.drain()
        assert r1.cache_hit_len == 8
        eng_a.submit(p2, max_new_tokens=2)
        eng_a.drain()

        class _Dead:
            def get(self, key):
                raise IOError("peer gone")

            def close(self):
                pass

        fw_b.client._remotes["A"] = _Dead()  # the peer dies mid-channel
        e0 = obs.counter("fleet_cache_errors_total").get(reason="fetch")
        i0 = obs.counter("fleet_dir_invalidations_total").get()
        r2 = eng_b.submit(p2.copy(), max_new_tokens=2)
        eng_b.drain()
        # the fetch failure is NOT an engine fault: cold, complete, exact
        assert r2.state is RequestState.FINISHED
        assert r2.cache_hit_len == 0
        assert obs.counter("fleet_cache_errors_total").get(
            reason="fetch") == e0 + 1
        # fail_limit=1: the peer latched dead and its index was swept
        # (p2's 3 depths — p1's entries were re-owned by B's own park)
        assert fw_b.client._remotes["A"] is None
        assert obs.counter("fleet_dir_invalidations_total").get() == i0 + 3
        hit = fw_b.directory.lookup(p2, "")
        assert hit is not None and hit["owner"] == "B"  # B's re-publish
        assert eng_b.pool.leaked() == 0

    def test_publish_failure_never_blocks_admission(self, fleet):
        eng_a, fw_a = fleet("A")
        fw_a.publisher.backend = object()  # breaks export at park time
        e0 = obs.counter("fleet_cache_errors_total").get(reason="publish")
        r = eng_a.submit((np.arange(12) % 64).astype(np.int32),
                         max_new_tokens=2)
        eng_a.drain()
        assert r.state is RequestState.FINISHED
        assert eng_a.pool.n_parked == 1  # the local trie still parked
        assert obs.counter("fleet_cache_errors_total").get(
            reason="publish") == e0 + 1


class _ChunkStub:
    """Chunk-aware stub (tests/test_router.py shape)."""

    def __init__(self, n_slots=2, max_seq=64):
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.n_decodes = 0

    def prefill(self, tokens, lens, mask, start=None):
        return np.full(self.n_slots, 100, np.int32)

    def decode(self, tokens, active):
        self.n_decodes += 1
        return np.full(self.n_slots, self.n_decodes, np.int32)

    def copy_slot_prefix(self, dst, src, n):
        pass

    def export_slot_kv(self, slot, lo, hi):
        z = np.zeros((1, hi - lo, 1, 2), np.float32)
        return z, z

    def import_slot_kv(self, slot, k_rows, v_rows, *, length):
        pass


class _StubKV(_ChunkStub):
    """_ChunkStub plus the model dims the disagg wire format needs."""

    class _Cfg:
        n_layers = 1
        n_kv_heads = 1
        head_dim = 2

    cfg = _Cfg()

    def __init__(self, n_slots=2, max_seq=32):
        super().__init__(n_slots=n_slots, max_seq=max_seq)


class TestCacheAwareSteering:
    def _engines(self, n=2):
        return [ServingEngine(_ChunkStub(), prefill_chunk=CHUNK,
                              prefix_cache=PrefixCache(CHUNK))
                for _ in range(n)]

    def test_steers_to_the_trie_owner(self):
        engs = self._engines()
        p = np.arange(12, dtype=np.int32)
        engs[1].submit(p, max_new_tokens=2)  # warm replica 1's trie
        engs[1].drain()
        r = Router(engs)
        c0 = obs.counter("serving_router_cache_steered_total").get()
        req = r.submit(p.copy(), max_new_tokens=2)
        assert req is not None
        # equal load + index rotation favor replica 0; the 8 cached
        # tokens outvote both — the steering signal changed placement
        assert r.routed == [0, 1]
        assert obs.counter(
            "serving_router_cache_steered_total").get() == c0 + 1
        r.drain()
        assert req.cache_hit_len == 8

    def test_directory_credit_steers_to_fleet_owner(self):
        """A replica with a COLD trie still wins when the fleet directory
        says it owns the prefix (its fetch is a loopback to itself never
        taken — the router credit models the local hit it will get)."""
        engs = [ServingEngine(_ChunkStub()) for _ in range(2)]
        engs[0].fleet = types.SimpleNamespace(worker="w0")
        engs[1].fleet = types.SimpleNamespace(worker="w1")
        store = _DictStore()
        d = FleetDirectory(store, "w1", CHUNK)
        p = np.arange(12, dtype=np.int32)
        d.publish(_keys(p, 3), 0, True, 100)
        r = Router(engs, directory=d)
        c0 = obs.counter("serving_router_cache_steered_total").get()
        req = r.submit(p.copy(), max_new_tokens=2)
        assert req is not None
        assert r.routed == [0, 1]
        assert obs.counter(
            "serving_router_cache_steered_total").get() == c0 + 1
        r.drain()

    def test_no_prefix_no_steering_counter(self):
        engs = self._engines()
        c0 = obs.counter("serving_router_cache_steered_total").get()
        r = Router(engs)
        r.submit(np.arange(60, 68, dtype=np.int32), max_new_tokens=2)
        r.drain()
        assert obs.counter(
            "serving_router_cache_steered_total").get() == c0


class TestPerTenantFleetSeries:
    def test_merged_keeps_per_tenant_and_per_class(self):
        """The satellite regression: sub-snapshots must survive a fleet
        merge — one replica per tenant is exactly the fleet case that
        used to collapse to a single unlabeled series."""
        e1 = ServingEngine(_ChunkStub())
        e2 = ServingEngine(_ChunkStub())
        e1.submit(np.arange(6, dtype=np.int32), max_new_tokens=2,
                  tenant="a", priority="interactive")
        e2.submit(np.arange(6, dtype=np.int32), max_new_tokens=2,
                  tenant="b", priority="batch")
        e1.drain()
        e2.drain()
        snap = ServingMetrics.merged([e1.metrics, e2.metrics]).snapshot()
        assert set(snap["per_tenant"]) == {"a", "b"}
        assert set(snap["per_class"]) == {"interactive", "batch"}
        assert sum(v["completed"] for v in snap["per_tenant"].values()) \
            == 2

    def test_router_submit_threads_tenant(self):
        r = Router([ServingEngine(_ChunkStub()),
                    ServingEngine(_ChunkStub())])
        assert r.submit(np.arange(6, dtype=np.int32), max_new_tokens=2,
                        tenant="a") is not None
        assert r.submit(np.arange(6, dtype=np.int32), max_new_tokens=2,
                        tenant="b") is not None
        r.drain()
        snap = r.snapshot()
        assert set(snap["per_tenant"]) == {"a", "b"}

    def test_begin_carries_tenant_to_adoption(self):
        from uccl_tpu.serving.disagg import make_local_pair

        pe = ServingEngine(_StubKV(), prefill_chunk=CHUNK)
        de = ServingEngine(_StubKV())
        pw, dw = make_local_pair(pe, de)
        try:
            pw.submit(np.arange(6, dtype=np.int32), max_new_tokens=2,
                      tenant="acme")
            pw.submit(np.arange(8, dtype=np.int32), max_new_tokens=2)
            done = []
            deadline = time.monotonic() + 30
            while len(done) < 2:
                pw.step()
                done.extend(dw.step())
                assert time.monotonic() < deadline
            assert sorted(r.tenant for r in done) == ["acme", "default"]
            snap = de.snapshot()
            assert set(snap["per_tenant"]) == {"acme", "default"}
        finally:
            pw.ep.close()
            dw.ep.close()


class TestFanIn3x2:
    def test_kill_one_prefill_engine_conserves(self):
        """The ≥3×2 plane survives a mid-stream prefill kill: the victim's
        GRANT lease expires (reason=peer_dead) on its decode worker, every
        live bond's request completes, and no pool leaks a slot."""
        from uccl_tpu.serving.disagg import DecodeWorker, add_local_prefill
        from uccl_tpu.serving.disagg import _ChunkFanout

        pes = [ServingEngine(_StubKV(n_slots=2), prefill_chunk=CHUNK)
               for _ in range(3)]
        des = [ServingEngine(_StubKV(n_slots=4)) for _ in range(2)]
        dws = [DecodeWorker(de, Endpoint(), grant_lease_s=60.0,
                            detector=FailureDetector(suspect_after_s=0.05,
                                                     dead_after_s=0.12))
               for de in des]
        pws = {}
        try:
            for i, pe in enumerate(pes):
                for j, dw in enumerate(dws):
                    pws[(i, j)] = add_local_prefill(
                        dw, pe, transport="ep", heartbeat_s=0.02)
            # 6 bonds through 3 shared fan-out sinks: the N×M plane
            for pe in pes:
                assert isinstance(pe.chunk_sink, _ChunkFanout)
                assert len(pe.chunk_sink.sinks) == 2

            # the doomed request: BEGIN through bond (2, 0), wait for its
            # GRANT to reserve a decode slot, then kill the engine
            victim = pws[(2, 0)].submit(np.arange(6, dtype=np.int32),
                                        max_new_tokens=2)
            assert victim is not None
            deadline = time.monotonic() + 10
            while not dws[0]._granted:
                pws[(2, 0)].pump()
                dws[0].poll()
                assert time.monotonic() < deadline
            assert des[0].pool.n_free == des[0].pool.n_slots - 1
            c0 = obs.counter("disagg_leases_expired_total").get(
                reason="peer_dead")
            pes[2].kill()  # heartbeats stop; the slabs never ship

            done = []
            live = [(i, j) for (i, j) in pws if i != 2]
            for n, (i, j) in enumerate(live):
                assert pws[(i, j)].submit(
                    np.arange(4 + n, dtype=np.int32),
                    max_new_tokens=2) is not None
            deadline = time.monotonic() + 30
            while len(done) < 4:
                for key in live:
                    pws[key].step()
                for dw in dws:
                    done.extend(dw.step())
                assert time.monotonic() < deadline
            assert all(r.adopted and r.n_generated == 2 for r in done)
            # the victim's lease expires the moment its conn ages DEAD
            deadline = time.monotonic() + 10
            while any(dw._granted for dw in dws):
                for key in live:
                    pws[key].pump()
                for dw in dws:
                    dw.poll()
                time.sleep(0.005)
                assert time.monotonic() < deadline
            assert obs.counter("disagg_leases_expired_total").get(
                reason="peer_dead") == c0 + 1
            # conservation: every decode slot came back, nothing leaked
            for de in des:
                assert de.pool.n_free == de.pool.n_slots
                assert de.pool.leaked() == 0
            for pe in pes[:2]:
                assert pe.pool.leaked() == 0
        finally:
            for pw in pws.values():
                pw.ep.close()
            for dw in dws:
                dw.ep.close()
