"""Checkpoint/resume through orbax: the framework's params/opt pytrees are
checkpoint-transparent.

The reference treats checkpointing as out of comm-layer scope (SURVEY §5)
and leans on its consumers' frameworks; the equivalent contract here is
that every state tree the framework produces (flagship params, optimizer
state) round-trips through orbax unchanged and training resumes
bit-identically — so a user switching from the reference keeps their
checkpoint workflow."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uccl_tpu.utils import jaxcompat

ocp = pytest.importorskip("orbax.checkpoint")


@pytest.fixture(scope="module")
def tiny_setup(mesh8):
    from uccl_tpu.models.flagship import (
        FlagshipConfig, init_params, make_train_step, shard_params,
    )

    mesh = mesh8
    cfg = FlagshipConfig(
        dim=64, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=16,
        moe_ffn=128, vocab=256, moe_experts=8, n_microbatches=1,
    )
    params = shard_params(init_params(jax.random.PRNGKey(0), cfg), mesh, cfg)
    train_step, init_opt = make_train_step(cfg, mesh)
    return cfg, mesh, params, train_step, init_opt


def _tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestOrbaxRoundTrip:
    def test_params_and_opt_state_roundtrip(self, tiny_setup, tmp_path):
        cfg, mesh, params, train_step, init_opt = tiny_setup
        opt_state = init_opt(params)
        ckpt = ocp.PyTreeCheckpointer()
        ckpt.save(tmp_path / "params", params)
        ckpt.save(tmp_path / "opt", opt_state)
        restored_p = ckpt.restore(tmp_path / "params", item=params)
        restored_o = ckpt.restore(tmp_path / "opt", item=opt_state)
        _tree_equal(params, restored_p)
        _tree_equal(opt_state, restored_o)

    @pytest.mark.skipif(
        not jaxcompat.MODERN_SHARD_MAP,
        reason="legacy shard_map vjp mishandles rank-0 residuals",
    )
    def test_resume_is_bit_identical(self, tiny_setup, tmp_path, rng):
        """step; checkpoint; step again = restore; step — same trajectory."""
        cfg, mesh, params, train_step, init_opt = tiny_setup
        step = jax.jit(train_step)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (2, 32)), jnp.int32)
        targets = jnp.asarray(rng.integers(0, cfg.vocab, (2, 32)), jnp.int32)

        p1, o1, _ = step(params, init_opt(params), tokens, targets)
        ckpt = ocp.PyTreeCheckpointer()
        ckpt.save(tmp_path / "p1", p1)
        ckpt.save(tmp_path / "o1", o1)
        p2, o2, m2 = step(p1, o1, tokens, targets)

        rp = ckpt.restore(tmp_path / "p1", item=p1)
        ro = ckpt.restore(tmp_path / "o1", item=o1)
        # restored trees are host arrays; resharding must be transparent
        from uccl_tpu.models.flagship import shard_params

        rp = shard_params(jax.tree.map(jnp.asarray, rp), mesh, cfg)
        p2r, o2r, m2r = step(rp, jax.tree.map(jnp.asarray, ro), tokens,
                             targets)
        _tree_equal(p2, p2r)
        np.testing.assert_allclose(
            float(m2["loss"]), float(m2r["loss"]), rtol=0, atol=0
        )
