"""UDP wire mode: the engine's unreliable-datagram path, where SACK-based
selective repeat and CC pacing are load-bearing — packet loss is REAL (the
datagrams are genuinely dropped before the socket), and the bytes only
arrive because the reliability layer recovers them. The analog of the
reference's packet-level transports (collective/afxdp, collective/efa) and
their PCB/SACK machinery (collective/rdma/pcb.h:20).

The wire is selected per-endpoint via UCCL_TPU_WIRE=udp at construction;
all endpoints in a test must agree (the kHello handshake gates connect/
accept on the datagram path coming up on both ends).
"""

import os

import numpy as np
import pytest

from uccl_tpu.p2p.endpoint import Endpoint


@pytest.fixture()
def udp_pair():
    os.environ["UCCL_TPU_WIRE"] = "udp"
    a = b = None
    try:
        a = Endpoint(port=0, n_engines=1)
        b = Endpoint(port=0, n_engines=1)
        cid_ab = a.connect("127.0.0.1", b.port)
        assert cid_ab >= 0, "UDP handshake failed"
        cid_ba = b.accept(timeout_ms=5000)
        assert cid_ba >= 0
        yield a, b, cid_ab, cid_ba
    finally:
        # close in the finally: a failing test must not leak engine threads
        if a is not None:
            a.close()
        if b is not None:
            b.close()
        del os.environ["UCCL_TPU_WIRE"]


class TestUdpBasics:
    def test_handshake_active(self, udp_pair):
        a, b, cid_ab, cid_ba = udp_pair
        assert a.conn_stats(cid_ab)["udp_active"]
        assert b.conn_stats(cid_ba)["udp_active"]

    def test_send_recv_roundtrip(self, udp_pair):
        a, b, cid_ab, cid_ba = udp_pair
        msg = np.arange(300_000, dtype=np.uint8)  # spans many packets
        a.send(cid_ab, msg)
        out = np.zeros_like(msg)
        n = b.recv_into(cid_ba, out, timeout_ms=10000)
        assert n == msg.nbytes
        np.testing.assert_array_equal(out, msg)

    def test_write_read_windows(self, udp_pair):
        a, b, cid_ab, cid_ba = udp_pair
        dst = np.zeros(1 << 18, np.uint8)
        mr = b.reg(dst)
        item = b.advertise(mr, 0, dst.nbytes)
        src = np.random.default_rng(1).integers(0, 256, 1 << 18).astype(
            np.uint8
        )
        assert a.wait(a.write_async(cid_ab, src, item), timeout_ms=10000)
        np.testing.assert_array_equal(dst, src)
        # one-sided read back
        got = np.zeros(1 << 18, np.uint8)
        item2 = b.advertise(mr, 0, dst.nbytes)
        assert a.wait(a.read_async(cid_ab, got, item2), timeout_ms=10000)
        np.testing.assert_array_equal(got, src)

    def test_rtt_sampled(self, udp_pair):
        a, b, cid_ab, cid_ba = udp_pair
        dst = np.zeros(4096, np.uint8)
        mr = b.reg(dst)
        src = np.ones(4096, np.uint8)
        for _ in range(3):
            item = b.advertise(mr, 0, dst.nbytes)
            assert a.wait(a.write_async(cid_ab, src, item), timeout_ms=5000)
        st = a.conn_stats(cid_ab)
        assert st["rtt_us"] > 0.0
        assert st["pkts_tx"] >= 3 and st["acks_rx"] >= 1


class TestUdpLoss:
    """Bit-exact delivery over REAL packet loss, recovered by repo code —
    the acceptance bar of VERDICT round-4 item 5."""

    @pytest.mark.parametrize("loss", [0.05, 0.2])
    def test_bit_exact_under_loss(self, udp_pair, loss):
        a, b, cid_ab, cid_ba = udp_pair
        dst = np.zeros(1 << 20, np.uint8)
        mr = b.reg(dst)
        item = b.advertise(mr, 0, dst.nbytes)
        src = np.random.default_rng(2).integers(0, 256, 1 << 20).astype(
            np.uint8
        )
        a.set_drop_rate(loss)
        try:
            xid = a.write_async(cid_ab, src, item)
            assert a.wait(xid, timeout_ms=60000), f"lost at {loss:.0%}"
        finally:
            a.set_drop_rate(0.0)
        np.testing.assert_array_equal(dst, src)
        st = a.conn_stats(cid_ab)
        assert st["pkts_rtx"] > 0, "recovery must be retransmission-driven"

    def test_flush_means_acked(self, udp_pair):
        a, b, cid_ab, cid_ba = udp_pair
        dst = np.zeros(1 << 19, np.uint8)
        mr = b.reg(dst)
        item = b.advertise(mr, 0, dst.nbytes)
        src = np.full(1 << 19, 7, np.uint8)
        a.set_drop_rate(0.1)
        try:
            xid = a.write_async(cid_ab, src, item)
            assert a.flush(cid_ab, timeout_ms=60000)
        finally:
            a.set_drop_rate(0.0)
        # flush == every serialized byte acked => the frame fully landed
        assert a.wait(xid, timeout_ms=10000)
        np.testing.assert_array_equal(dst, src)
        assert a.conn_stats(cid_ab)["bytes_unacked"] == 0


class TestUdpCc:
    def test_cc_controller_governs_rate(self, udp_pair):
        """Timely reads the in-protocol RTT and actuates the per-conn
        pacer; retransmissions trigger multiplicative decrease."""
        from uccl_tpu.p2p.cc import CcController, TimelyCC

        a, b, cid_ab, cid_ba = udp_pair
        dst = np.zeros(1 << 18, np.uint8)
        mr = b.reg(dst)
        src = np.ones(1 << 18, np.uint8)
        cc = CcController(a, cid_ab, TimelyCC(rate=50e6))
        assert cc.tick() is None  # no RTT signal yet
        item = b.advertise(mr, 0, dst.nbytes)
        assert a.wait(a.write_async(cid_ab, src, item), timeout_ms=10000)
        r1 = cc.tick()
        assert r1 is not None and r1 >= cc.min_rate
        assert a.conn_stats(cid_ab)["rate_bps"] == int(r1)
        # loss epoch: inflated RTT engages decrease
        a.set_drop_rate(0.3)
        try:
            item = b.advertise(mr, 0, dst.nbytes)
            assert a.wait(a.write_async(cid_ab, src, item), timeout_ms=60000)
        finally:
            a.set_drop_rate(0.0)
        r2 = cc.tick()
        assert r2 is not None and r2 < r1, "loss must cut the rate"

    def test_per_conn_rate_paces_transfer(self, udp_pair):
        """A tight per-conn rate visibly slows a transfer (the pacer is in
        the datapath, not advisory)."""
        import time

        a, b, cid_ab, cid_ba = udp_pair
        dst = np.zeros(1 << 19, np.uint8)
        mr = b.reg(dst)
        src = np.ones(1 << 19, np.uint8)
        item = b.advertise(mr, 0, dst.nbytes)
        a.set_conn_rate(cid_ab, 1 << 20)  # 1 MiB/s for a 512 KiB payload
        t0 = time.perf_counter()
        try:
            assert a.wait(a.write_async(cid_ab, src, item), timeout_ms=30000)
        finally:
            a.set_conn_rate(cid_ab, 0)
        dt = time.perf_counter() - t0
        assert dt > 0.2, f"paced transfer finished in {dt:.3f}s — pacer inert?"
        np.testing.assert_array_equal(dst, src)


class TestUdpTeardown:
    def test_remove_conn_fails_cleanly(self, udp_pair):
        a, b, cid_ab, cid_ba = udp_pair
        assert b.remove_conn(cid_ba)
        # sender's conn eventually observes death via the TCP liveness fd
        deadline = 50
        while a.conn_alive(cid_ab) and deadline > 0:
            import time

            time.sleep(0.1)
            deadline -= 1
        assert not a.conn_alive(cid_ab)
