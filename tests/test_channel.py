"""Multipath channel: handshake grouping, chunk spraying, offset math."""

import threading

import numpy as np
import pytest

from uccl_tpu.p2p import Channel, Endpoint, FifoItem


@pytest.fixture
def chan_pair():
    with Endpoint(n_engines=4) as server, Endpoint(n_engines=4) as client:
        result = {}

        def srv():
            result["chan"] = Channel.accept(server, chunk_bytes=64 << 10)

        t = threading.Thread(target=srv)
        t.start()
        c_chan = Channel.connect(
            client, "127.0.0.1", server.port, n_paths=4, chunk_bytes=64 << 10
        )
        t.join(timeout=20)
        yield server, client, result["chan"], c_chan


class TestFifoItem:
    def test_pack_roundtrip(self):
        item = FifoItem(rid=7, size=1000, token=0xDEADBEEF, offset=0)
        assert FifoItem.unpack(item.pack()) == item
        assert len(item.pack()) == 64

    def test_slice(self):
        item = FifoItem(rid=1, size=100, token=2, offset=0)
        s = item.slice(40, 60)
        assert (s.offset, s.size) == (40, 60)
        with pytest.raises(ValueError):
            item.slice(50, 60)

    def test_matches_engine_layout(self):
        """Engine-produced descriptors must parse with the python struct."""
        with Endpoint() as ep:
            buf = np.zeros(128, np.uint8)
            mr = ep.reg(buf)
            raw = ep.advertise(mr, offset=16, length=64)
            item = FifoItem.unpack(raw)
            assert item.size == 64 and item.offset == 0 and item.rid > 0


class TestChannel:
    def test_handshake_groups_paths(self, chan_pair):
        _, _, s_chan, c_chan = chan_pair
        assert s_chan.n_paths == 4 and c_chan.n_paths == 4

    def test_small_write_single_path(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        dst = np.zeros(1024, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, 1024).astype(np.uint8)
        c_chan.write(src, fifo)
        np.testing.assert_array_equal(dst, src)

    def test_chunked_multipath_write(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        n = 1 << 20  # 16 chunks of 64K across 4 paths
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        c_chan.write(src, fifo)
        np.testing.assert_array_equal(dst, src)

    def test_chunked_write_typed_array(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        dst = np.zeros((256, 256), np.float32)  # 256 KB -> 4 chunks
        fifo = server.advertise(server.reg(dst))
        src = rng.standard_normal((256, 256)).astype(np.float32)
        c_chan.write(src, fifo)
        np.testing.assert_array_equal(dst, src)

    def test_chunked_multipath_read(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        n = 512 << 10
        src = rng.integers(0, 255, n).astype(np.uint8)
        fifo = server.advertise(server.reg(src))
        dst = np.zeros(n, np.uint8)
        c_chan.read(dst, fifo)
        np.testing.assert_array_equal(dst, src)

    def test_control_plane_ordering(self, chan_pair):
        server, client, s_chan, c_chan = chan_pair
        for i in range(10):
            c_chan.send(f"m{i}".encode())
        for i in range(10):
            assert s_chan.recv() == f"m{i}".encode()

    def test_non_contiguous_rejected(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        dst = np.zeros(1 << 20, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, (1024, 2048)).astype(np.uint8)[:, ::2]
        with pytest.raises(ValueError):
            c_chan.write(src, fifo)


class TestMultiNic:
    """Multi-NIC data-path striping: per-path source binding on loopback
    aliases (127.0.0.0/8 binds freely on Linux), verified from the peer."""

    def test_paths_stripe_across_source_ips(self):
        import threading

        with Endpoint(n_engines=2) as server, Endpoint(n_engines=2) as client:
            result = {}

            def srv():
                result["chan"] = Channel.accept(server, chunk_bytes=64 << 10)

            t = threading.Thread(target=srv)
            t.start()
            c_chan = Channel.connect(
                client, "127.0.0.1", server.port, n_paths=4,
                chunk_bytes=64 << 10, nics=["127.0.0.21", "127.0.0.22"],
            )
            t.join(timeout=20)
            s_chan = result["chan"]
            # the server sees each path's source IP = the bound NIC
            seen = {
                server.peer_addr(cid).split(":")[0] for cid in s_chan.conns
            }
            assert seen == {"127.0.0.21", "127.0.0.22"}
            # data still flows across the striped paths
            dst = np.zeros(1 << 18, np.uint8)
            fifo = server.advertise(server.reg(dst))
            src = np.random.default_rng(0).integers(0, 255, 1 << 18).astype(np.uint8)
            c_chan.write(src, fifo)
            np.testing.assert_array_equal(dst, src)

    def test_env_nic_list(self, monkeypatch):
        import threading

        from uccl_tpu.utils import config as cfg

        monkeypatch.setenv("UCCL_TPU_NIC_LIST", "127.0.0.31")
        cfg.reset_all()
        try:
            with Endpoint(n_engines=1) as server, Endpoint(n_engines=1) as client:
                result = {}

                def srv():
                    result["chan"] = Channel.accept(server)

                t = threading.Thread(target=srv)
                t.start()
                Channel.connect(client, "127.0.0.1", server.port, n_paths=2)
                t.join(timeout=20)
                ips = {
                    server.peer_addr(cid).split(":")[0]
                    for cid in result["chan"].conns
                }
                assert ips == {"127.0.0.31"}
        finally:
            monkeypatch.delenv("UCCL_TPU_NIC_LIST")
            cfg.reset_all()

    def test_bogus_nic_fails_cleanly(self):
        with Endpoint(n_engines=1) as server, Endpoint(n_engines=1) as client:
            with pytest.raises(ConnectionError, match="local_ip"):
                client.connect("127.0.0.1", server.port, local_ip="203.0.113.7")

    def test_partial_handshake_failure_cleans_up(self):
        """A later path's bad NIC tears down the established paths."""
        with Endpoint(n_engines=1) as server, Endpoint(n_engines=1) as client:
            before = client  # path 0 connects, path 1's bind fails
            with pytest.raises(ConnectionError):
                Channel.connect(
                    before, "127.0.0.1", server.port, n_paths=2,
                    nics=["127.0.0.51", "203.0.113.9"],
                )
            # path-0 conn was removed: the server side sees it die rather
            # than sitting in a half-open handshake
            cid = server.accept(timeout_ms=5000)
            deadline = __import__("time").time() + 10
            while server.conn_alive(cid) and __import__("time").time() < deadline:
                __import__("time").sleep(0.05)
            assert not server.conn_alive(cid)


class TestRetransmission:
    """Channel-level loss recovery: the analog of the reference's SACK
    retransmit path (transport.cc __retransmit_for_flow) at chunk
    granularity — injected frame loss is recovered by re-issuing the timed
    -out chunks on rotated paths, bit-exactly."""

    def test_lossy_chunked_write_recovers_exactly(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        c_chan.retries = 8  # drop 0.25^9 per chunk ~ never fails the test
        n = 1 << 20  # 16 chunks of 64K
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_drop_rate(0.25)
        try:
            c_chan.write(src, fifo, timeout_ms=500)
        finally:
            client.set_drop_rate(0.0)
        np.testing.assert_array_equal(dst, src)
        assert c_chan.retransmitted_chunks > 0

    def test_total_loss_raises_after_retries(self, chan_pair, rng):
        from uccl_tpu import obs

        server, client, s_chan, c_chan = chan_pair
        c_chan.retries = 1
        n = 256 << 10  # 4 chunks
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_drop_rate(1.0)
        f0 = obs.counter("p2p_transfer_failures_total").get(
            reason="undelivered")
        try:
            with pytest.raises(IOError, match="after 2 attempts"):
                c_chan.write(src, fifo, timeout_ms=300)
        finally:
            client.set_drop_rate(0.0)
        # the terminal failure is auditable from metrics alone: every
        # exhausted chunk counted on the failure family
        assert obs.counter("p2p_transfer_failures_total").get(
            reason="undelivered") >= f0 + 1

    def test_single_path_retry_honors_timeout(self, chan_pair, rng):
        """Small (single-chunk) transfers retry on the caller's timeout
        budget — not the native sync op's fixed internal one."""
        import time as _time

        server, client, s_chan, c_chan = chan_pair
        c_chan.retries = 1
        dst = np.zeros(1024, np.uint8)
        fifo = server.advertise(server.reg(dst))
        client.set_drop_rate(1.0)
        t0 = _time.perf_counter()
        try:
            with pytest.raises(IOError, match="after 2 attempts"):
                c_chan.write(
                    rng.integers(0, 255, 1024).astype(np.uint8), fifo,
                    timeout_ms=200,
                )
        finally:
            client.set_drop_rate(0.0)
        assert _time.perf_counter() - t0 < 5.0

    def test_fence_clears_abandoned(self, chan_pair, rng):
        """After a lossy write, fence() must either drain every abandoned
        transfer to terminal or raise — here with 0 drop restored and no
        genuinely-lost frames pending, any deferred ids resolve quickly."""
        server, client, s_chan, c_chan = chan_pair
        c_chan.retries = 8
        n = 1 << 20
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_drop_rate(0.25)
        try:
            c_chan.write(src, fifo, timeout_ms=500)
        finally:
            client.set_drop_rate(0.0)
        # drop-injected frames never terminate; fence must say so (raise)
        # or, if all abandoned ids happened to be slow-acks, clear them.
        try:
            c_chan.fence(timeout_ms=1000)
            assert c_chan._abandoned == []
        except IOError as e:
            assert "still in flight" in str(e)
        np.testing.assert_array_equal(dst, src)

    def test_retx_split_is_counted(self, chan_pair, rng):
        """Windowed recovery exports its fast-vs-RTO split: after a lossy
        transfer the per-channel totals reconcile with the lifetime
        retransmission count."""
        server, client, s_chan, c_chan = chan_pair
        c_chan.retries = 8
        n = 1 << 20
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_drop_rate(0.3)
        try:
            c_chan.write(src, fifo, timeout_ms=1000)
        finally:
            client.set_drop_rate(0.0)
        np.testing.assert_array_equal(dst, src)
        assert c_chan.retransmitted_chunks > 0
        assert c_chan.retx_fast + c_chan.retx_rto == c_chan.retransmitted_chunks
        st = c_chan.transport_stats()
        assert st["retx_fast_total"] == c_chan.retx_fast
        assert st["srtt_us"] > 0  # completion RTTs fed the estimator

    @pytest.mark.parametrize("seed", range(4))
    def test_lossy_write_never_corrupts(self, chan_pair, seed):
        """THE retransmission invariant, fuzzed: whatever the (drop rate,
        retry budget, message size) combination, a write() that RETURNS
        implies the peer window holds exactly the sent bytes; the only
        other allowed outcome is IOError. Silent corruption — returning
        with partial/stale data — fails the assert."""
        server, client, s_chan, c_chan = chan_pair
        rng = np.random.default_rng(7000 + seed)
        c_chan.retries = int(rng.choice([0, 2, 8]))
        drop = float(rng.choice([0.0, 0.1, 0.4]))
        n = int(rng.integers(1, 21)) * (32 << 10)  # 32K..640K, 64K chunks
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_drop_rate(drop)
        try:
            try:
                c_chan.write(src, fifo, timeout_ms=400)
            except IOError:
                return  # allowed outcome under loss; nothing to assert
        finally:
            client.set_drop_rate(0.0)
        np.testing.assert_array_equal(dst, src)


class TestReorderInjection:
    """Out-of-order delivery (satellite of the windowed-transport PR): the
    engine's reorder injection swaps same-conn data frames, so chunks land
    — and their completions arrive — out of order. The SACK window must
    converge bit-exactly, and pure reordering must never trigger the mass
    or spurious retransmission the old attempt-batched path risked."""

    def test_reordered_chunks_bit_exact_no_spurious_retx(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        # dup-ack fast retx disabled (k > chunk count): with no loss, ANY
        # retransmission would be spurious — the assert below is exact
        c_chan.dupack_k = 64
        n = 1 << 20  # 16 chunks of 64K over 4 paths
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_reorder_rate(0.5)
        try:
            c_chan.write(src, fifo, timeout_ms=5000)
        finally:
            client.set_reorder_rate(0.0)
        np.testing.assert_array_equal(dst, src)
        assert c_chan.retransmitted_chunks == 0
        win = c_chan._last_win
        assert win.done() and win.sack_bitmap() == 0  # SACK converged

    def test_reorder_with_default_dupack_k_stays_selective(self, chan_pair,
                                                           rng):
        """With the default k=3, heavy injected reorder may fire a few
        fast retransmits (dup-ack schemes trade exactly this) — but
        recovery must stay bounded and bit-exact, never the pending set."""
        server, client, s_chan, c_chan = chan_pair
        n = 1 << 20
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_reorder_rate(0.5)
        try:
            c_chan.write(src, fifo, timeout_ms=5000)
        finally:
            client.set_reorder_rate(0.0)
        np.testing.assert_array_equal(dst, src)
        assert c_chan.retransmitted_chunks <= 4  # selective, not mass

    def test_drop_plus_reorder_bit_exact(self, chan_pair, rng):
        """The combined fault the acceptance bar names: loss AND
        reordering at once, recovered exactly."""
        server, client, s_chan, c_chan = chan_pair
        c_chan.retries = 8
        n = 1 << 20
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_drop_rate(0.15)
        client.set_reorder_rate(0.3)
        try:
            c_chan.write(src, fifo, timeout_ms=2000)
        finally:
            client.set_drop_rate(0.0)
            client.set_reorder_rate(0.0)
        np.testing.assert_array_equal(dst, src)
        assert c_chan.retransmitted_chunks > 0

    def test_delay_jitter_completes_and_samples_rtt(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        c_chan.dupack_k = 64  # jitter != loss: no fast retx wanted
        n = 512 << 10
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_delay_jitter_us(3000)
        try:
            c_chan.write(src, fifo, timeout_ms=10000)
        finally:
            client.set_delay_jitter_us(0)
        np.testing.assert_array_equal(dst, src)
        assert c_chan._last_win.srtt_us > 500  # the jitter showed up


class TestPathSteering:
    def test_retx_and_new_chunks_avoid_lossy_path(self, chan_pair, rng):
        """Per-path quality EWMA (the anti-blind-rotation satellite of the
        tentpole): with ONE path fault-injected lossy, recovery is exact
        and the window's learned path score for the lossy path drops below
        the healthy ones."""
        server, client, s_chan, c_chan = chan_pair
        c_chan.retries = 8
        lossy = 1
        client.set_conn_fault(c_chan.conns[lossy], drop=0.7)
        n = 2 << 20  # 32 chunks
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        try:
            c_chan.write(src, fifo, timeout_ms=2000)
        finally:
            client.set_conn_fault(c_chan.conns[lossy], drop=-1.0)
        np.testing.assert_array_equal(dst, src)
        scores = c_chan._last_win.stats()["path_scores"]
        healthy = [s for i, s in enumerate(scores) if i != lossy]
        assert scores[lossy] < min(healthy), scores


class TestWindowCC:
    def test_swift_window_cc_recovers_lossy_transfer(self, chan_pair, rng):
        """Window CC on the data path: Swift fed by per-chunk completion
        RTTs carries a lossy transfer exactly, and losses shrink the cwnd
        below its starting point."""
        from uccl_tpu.p2p.cc import WindowedSwift

        server, client, s_chan, c_chan = chan_pair
        c_chan.retries = 8
        c_chan.enable_window_cc("swift")
        assert isinstance(c_chan.window_cc, WindowedSwift)
        cwnd0 = c_chan.window_cc.cwnd_bytes()
        n = 1 << 20
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_drop_rate(0.3)
        try:
            c_chan.write(src, fifo, timeout_ms=2000)
        finally:
            client.set_drop_rate(0.0)
            c_chan.disable_window_cc()
        np.testing.assert_array_equal(dst, src)
        assert c_chan.retransmitted_chunks > 0

    def test_timely_window_cc_clean_transfer(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        c_chan.enable_window_cc("timely")
        n = 1 << 20
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        try:
            c_chan.write(src, fifo, timeout_ms=5000)
        finally:
            c_chan.disable_window_cc()
        np.testing.assert_array_equal(dst, src)
        assert c_chan.retransmitted_chunks == 0

    def test_writev_windowed_batch(self, chan_pair, rng):
        """writev: many (src, fifo) elements ride ONE windowed transfer."""
        server, client, s_chan, c_chan = chan_pair
        dst = np.zeros(256 << 10, np.uint8)
        mr = server.reg(dst)
        srcs, fifos = [], []
        step = 32 << 10
        for off in range(0, dst.nbytes, step):
            srcs.append(rng.integers(0, 255, step).astype(np.uint8))
            fifos.append(server.advertise(mr, offset=off, length=step))
        c_chan.writev(srcs, fifos, timeout_ms=5000)
        np.testing.assert_array_equal(dst, np.concatenate(srcs))
