"""Multipath channel: handshake grouping, chunk spraying, offset math."""

import threading

import numpy as np
import pytest

from uccl_tpu.p2p import Channel, Endpoint, FifoItem


@pytest.fixture
def chan_pair():
    with Endpoint(n_engines=4) as server, Endpoint(n_engines=4) as client:
        result = {}

        def srv():
            result["chan"] = Channel.accept(server, chunk_bytes=64 << 10)

        t = threading.Thread(target=srv)
        t.start()
        c_chan = Channel.connect(
            client, "127.0.0.1", server.port, n_paths=4, chunk_bytes=64 << 10
        )
        t.join(timeout=20)
        yield server, client, result["chan"], c_chan


class TestFifoItem:
    def test_pack_roundtrip(self):
        item = FifoItem(rid=7, size=1000, token=0xDEADBEEF, offset=0)
        assert FifoItem.unpack(item.pack()) == item
        assert len(item.pack()) == 64

    def test_slice(self):
        item = FifoItem(rid=1, size=100, token=2, offset=0)
        s = item.slice(40, 60)
        assert (s.offset, s.size) == (40, 60)
        with pytest.raises(ValueError):
            item.slice(50, 60)

    def test_matches_engine_layout(self):
        """Engine-produced descriptors must parse with the python struct."""
        with Endpoint() as ep:
            buf = np.zeros(128, np.uint8)
            mr = ep.reg(buf)
            raw = ep.advertise(mr, offset=16, length=64)
            item = FifoItem.unpack(raw)
            assert item.size == 64 and item.offset == 0 and item.rid > 0


class TestChannel:
    def test_handshake_groups_paths(self, chan_pair):
        _, _, s_chan, c_chan = chan_pair
        assert s_chan.n_paths == 4 and c_chan.n_paths == 4

    def test_small_write_single_path(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        dst = np.zeros(1024, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, 1024).astype(np.uint8)
        c_chan.write(src, fifo)
        np.testing.assert_array_equal(dst, src)

    def test_chunked_multipath_write(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        n = 1 << 20  # 16 chunks of 64K across 4 paths
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        c_chan.write(src, fifo)
        np.testing.assert_array_equal(dst, src)

    def test_chunked_write_typed_array(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        dst = np.zeros((256, 256), np.float32)  # 256 KB -> 4 chunks
        fifo = server.advertise(server.reg(dst))
        src = rng.standard_normal((256, 256)).astype(np.float32)
        c_chan.write(src, fifo)
        np.testing.assert_array_equal(dst, src)

    def test_chunked_multipath_read(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        n = 512 << 10
        src = rng.integers(0, 255, n).astype(np.uint8)
        fifo = server.advertise(server.reg(src))
        dst = np.zeros(n, np.uint8)
        c_chan.read(dst, fifo)
        np.testing.assert_array_equal(dst, src)

    def test_control_plane_ordering(self, chan_pair):
        server, client, s_chan, c_chan = chan_pair
        for i in range(10):
            c_chan.send(f"m{i}".encode())
        for i in range(10):
            assert s_chan.recv() == f"m{i}".encode()

    def test_non_contiguous_rejected(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        dst = np.zeros(1 << 20, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, (1024, 2048)).astype(np.uint8)[:, ::2]
        with pytest.raises(ValueError):
            c_chan.write(src, fifo)


class TestMultiNic:
    """Multi-NIC data-path striping: per-path source binding on loopback
    aliases (127.0.0.0/8 binds freely on Linux), verified from the peer."""

    def test_paths_stripe_across_source_ips(self):
        import threading

        with Endpoint(n_engines=2) as server, Endpoint(n_engines=2) as client:
            result = {}

            def srv():
                result["chan"] = Channel.accept(server, chunk_bytes=64 << 10)

            t = threading.Thread(target=srv)
            t.start()
            c_chan = Channel.connect(
                client, "127.0.0.1", server.port, n_paths=4,
                chunk_bytes=64 << 10, nics=["127.0.0.21", "127.0.0.22"],
            )
            t.join(timeout=20)
            s_chan = result["chan"]
            # the server sees each path's source IP = the bound NIC
            seen = {
                server.peer_addr(cid).split(":")[0] for cid in s_chan.conns
            }
            assert seen == {"127.0.0.21", "127.0.0.22"}
            # data still flows across the striped paths
            dst = np.zeros(1 << 18, np.uint8)
            fifo = server.advertise(server.reg(dst))
            src = np.random.default_rng(0).integers(0, 255, 1 << 18).astype(np.uint8)
            c_chan.write(src, fifo)
            np.testing.assert_array_equal(dst, src)

    def test_env_nic_list(self, monkeypatch):
        import threading

        from uccl_tpu.utils import config as cfg

        monkeypatch.setenv("UCCL_TPU_NIC_LIST", "127.0.0.31")
        cfg.reset_all()
        try:
            with Endpoint(n_engines=1) as server, Endpoint(n_engines=1) as client:
                result = {}

                def srv():
                    result["chan"] = Channel.accept(server)

                t = threading.Thread(target=srv)
                t.start()
                Channel.connect(client, "127.0.0.1", server.port, n_paths=2)
                t.join(timeout=20)
                ips = {
                    server.peer_addr(cid).split(":")[0]
                    for cid in result["chan"].conns
                }
                assert ips == {"127.0.0.31"}
        finally:
            monkeypatch.delenv("UCCL_TPU_NIC_LIST")
            cfg.reset_all()

    def test_bogus_nic_fails_cleanly(self):
        with Endpoint(n_engines=1) as server, Endpoint(n_engines=1) as client:
            with pytest.raises(ConnectionError, match="local_ip"):
                client.connect("127.0.0.1", server.port, local_ip="203.0.113.7")

    def test_partial_handshake_failure_cleans_up(self):
        """A later path's bad NIC tears down the established paths."""
        with Endpoint(n_engines=1) as server, Endpoint(n_engines=1) as client:
            before = client  # path 0 connects, path 1's bind fails
            with pytest.raises(ConnectionError):
                Channel.connect(
                    before, "127.0.0.1", server.port, n_paths=2,
                    nics=["127.0.0.51", "203.0.113.9"],
                )
            # path-0 conn was removed: the server side sees it die rather
            # than sitting in a half-open handshake
            cid = server.accept(timeout_ms=5000)
            deadline = __import__("time").time() + 10
            while server.conn_alive(cid) and __import__("time").time() < deadline:
                __import__("time").sleep(0.05)
            assert not server.conn_alive(cid)


class TestRetransmission:
    """Channel-level loss recovery: the analog of the reference's SACK
    retransmit path (transport.cc __retransmit_for_flow) at chunk
    granularity — injected frame loss is recovered by re-issuing the timed
    -out chunks on rotated paths, bit-exactly."""

    def test_lossy_chunked_write_recovers_exactly(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        c_chan.retries = 8  # drop 0.25^9 per chunk ~ never fails the test
        n = 1 << 20  # 16 chunks of 64K
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_drop_rate(0.25)
        try:
            c_chan.write(src, fifo, timeout_ms=500)
        finally:
            client.set_drop_rate(0.0)
        np.testing.assert_array_equal(dst, src)
        assert c_chan.retransmitted_chunks > 0

    def test_total_loss_raises_after_retries(self, chan_pair, rng):
        server, client, s_chan, c_chan = chan_pair
        c_chan.retries = 1
        n = 256 << 10  # 4 chunks
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_drop_rate(1.0)
        try:
            with pytest.raises(IOError, match="after 2 attempts"):
                c_chan.write(src, fifo, timeout_ms=300)
        finally:
            client.set_drop_rate(0.0)

    def test_single_path_retry_honors_timeout(self, chan_pair, rng):
        """Small (single-chunk) transfers retry on the caller's timeout
        budget — not the native sync op's fixed internal one."""
        import time as _time

        server, client, s_chan, c_chan = chan_pair
        c_chan.retries = 1
        dst = np.zeros(1024, np.uint8)
        fifo = server.advertise(server.reg(dst))
        client.set_drop_rate(1.0)
        t0 = _time.perf_counter()
        try:
            with pytest.raises(IOError, match="after 2 attempts"):
                c_chan.write(
                    rng.integers(0, 255, 1024).astype(np.uint8), fifo,
                    timeout_ms=200,
                )
        finally:
            client.set_drop_rate(0.0)
        assert _time.perf_counter() - t0 < 5.0

    def test_fence_clears_abandoned(self, chan_pair, rng):
        """After a lossy write, fence() must either drain every abandoned
        transfer to terminal or raise — here with 0 drop restored and no
        genuinely-lost frames pending, any deferred ids resolve quickly."""
        server, client, s_chan, c_chan = chan_pair
        c_chan.retries = 8
        n = 1 << 20
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_drop_rate(0.25)
        try:
            c_chan.write(src, fifo, timeout_ms=500)
        finally:
            client.set_drop_rate(0.0)
        # drop-injected frames never terminate; fence must say so (raise)
        # or, if all abandoned ids happened to be slow-acks, clear them.
        try:
            c_chan.fence(timeout_ms=1000)
            assert c_chan._abandoned == []
        except IOError as e:
            assert "still in flight" in str(e)
        np.testing.assert_array_equal(dst, src)

    @pytest.mark.parametrize("seed", range(4))
    def test_lossy_write_never_corrupts(self, chan_pair, seed):
        """THE retransmission invariant, fuzzed: whatever the (drop rate,
        retry budget, message size) combination, a write() that RETURNS
        implies the peer window holds exactly the sent bytes; the only
        other allowed outcome is IOError. Silent corruption — returning
        with partial/stale data — fails the assert."""
        server, client, s_chan, c_chan = chan_pair
        rng = np.random.default_rng(7000 + seed)
        c_chan.retries = int(rng.choice([0, 2, 8]))
        drop = float(rng.choice([0.0, 0.1, 0.4]))
        n = int(rng.integers(1, 21)) * (32 << 10)  # 32K..640K, 64K chunks
        dst = np.zeros(n, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.set_drop_rate(drop)
        try:
            try:
                c_chan.write(src, fifo, timeout_ms=400)
            except IOError:
                return  # allowed outcome under loss; nothing to assert
        finally:
            client.set_drop_rate(0.0)
        np.testing.assert_array_equal(dst, src)
