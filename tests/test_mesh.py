"""Mesh construction + sharding helper tests."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from uccl_tpu.parallel.mesh import AXIS, MeshConfig, make_mesh, mesh_axis_size
from uccl_tpu.parallel import sharding


class TestMeshConfig:
    def test_size(self):
        c = MeshConfig(pp=2, dp=2, cp=1, tp=2)
        assert c.size == 8
        assert c.ep == 2

    def test_auto_8(self):
        c = MeshConfig.auto(8)
        assert c.size == 8

    def test_auto_various(self):
        for n in (1, 2, 3, 4, 6, 8, 16, 32, 12):
            assert MeshConfig.auto(n).size == n

    def test_auto_no_pp(self):
        c = MeshConfig.auto(8, want_pp=False)
        assert c.size == 8 and c.pp == 1


class TestMakeMesh:
    def test_mesh8(self, devices):
        m = make_mesh(MeshConfig(pp=2, dp=2, cp=1, tp=2), devices)
        assert m.shape == {"pp": 2, "dp": 2, "cp": 1, "tp": 2}
        assert mesh_axis_size(m, AXIS.EP) == 2

    def test_wrong_count(self, devices):
        with pytest.raises(ValueError):
            make_mesh(MeshConfig(dp=3), devices)

    def test_default_dp(self, devices):
        m = make_mesh(devices=devices)
        assert m.shape["dp"] == 8


class TestSharding:
    def test_put_and_constraint(self, mesh8):
        x = np.arange(2 * 4 * 6, dtype=np.float32).reshape(2, 4, 6)
        gx = sharding.put(mesh8, x, P(AXIS.DP, AXIS.CP, None))
        assert gx.shape == x.shape
        np.testing.assert_array_equal(np.asarray(gx), x)

    def test_activation_spec(self):
        assert sharding.activation_spec() == P(AXIS.DP, AXIS.CP, None)
        assert sharding.activation_spec(False) == P(AXIS.DP, None, None)
