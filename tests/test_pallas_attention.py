"""Pallas flash attention vs reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uccl_tpu.ops.attention import attention_reference
from uccl_tpu.ops.pallas_attention import flash_attention


def _qkv(rng, b=2, s=128, h=4, hkv=None, d=64, dtype=np.float32):
    hkv = hkv or h
    return (
        jnp.asarray(rng.standard_normal((b, s, h, d)), dtype),
        jnp.asarray(rng.standard_normal((b, s, hkv, d)), dtype),
        jnp.asarray(rng.standard_normal((b, s, hkv, d)), dtype),
    )


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(rng, causal):
    q, k, v = _qkv(rng)
    got = np.asarray(flash_attention(q, k, v, causal, 64, 64))
    want = np.asarray(attention_reference(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_gqa(rng):
    q, k, v = _qkv(rng, h=8, hkv=2)
    got = np.asarray(flash_attention(q, k, v, True, 64, 64))
    want = np.asarray(attention_reference(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_block_shapes(rng):
    q, k, v = _qkv(rng, s=128)
    a = np.asarray(flash_attention(q, k, v, True, 128, 32))
    b = np.asarray(flash_attention(q, k, v, True, 32, 128))
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_bad_block_divisibility(rng):
    q, k, v = _qkv(rng, s=100)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, True, 64, 64)


def test_grad_matches_reference(rng):
    q, k, v = _qkv(rng, b=1, s=64, h=2, d=32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, 32, 32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4)


def test_bf16(rng):
    q, k, v = _qkv(rng, dtype=jnp.bfloat16)
    got = np.asarray(flash_attention(q, k, v, True, 64, 64)).astype(np.float32)
    want = np.asarray(attention_reference(q, k, v, causal=True)).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)
