"""Pallas flash attention vs reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uccl_tpu.ops.attention import attention_reference
from uccl_tpu.ops.pallas_attention import flash_attention


def _qkv(rng, b=2, s=128, h=4, hkv=None, d=64, dtype=np.float32):
    hkv = hkv or h
    return (
        jnp.asarray(rng.standard_normal((b, s, h, d)), dtype),
        jnp.asarray(rng.standard_normal((b, s, hkv, d)), dtype),
        jnp.asarray(rng.standard_normal((b, s, hkv, d)), dtype),
    )


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(rng, causal):
    q, k, v = _qkv(rng)
    got = np.asarray(flash_attention(q, k, v, causal, 64, 64))
    want = np.asarray(attention_reference(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_gqa(rng):
    q, k, v = _qkv(rng, h=8, hkv=2)
    got = np.asarray(flash_attention(q, k, v, True, 64, 64))
    want = np.asarray(attention_reference(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_block_shapes(rng):
    q, k, v = _qkv(rng, s=128)
    a = np.asarray(flash_attention(q, k, v, True, 128, 32))
    b = np.asarray(flash_attention(q, k, v, True, 32, 128))
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_bad_block_divisibility(rng):
    q, k, v = _qkv(rng, s=100)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, True, 64, 64)


def test_grad_matches_reference(rng):
    q, k, v = _qkv(rng, b=1, s=64, h=2, d=32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, 32, 32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4)


def test_bf16(rng):
    q, k, v = _qkv(rng, dtype=jnp.bfloat16)
    got = np.asarray(flash_attention(q, k, v, True, 64, 64)).astype(np.float32)
    want = np.asarray(attention_reference(q, k, v, causal=True)).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)


def test_lse_matches_logsumexp(rng):
    import math

    from uccl_tpu.ops.pallas_attention import flash_attention_lse

    q, k, v = _qkv(rng, b=1, s=64, h=2, d=32)
    _, lse = flash_attention_lse(q, k, v, True, 32, 32)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / math.sqrt(q.shape[-1])
    mask = jnp.arange(64)[:, None] >= jnp.arange(64)[None, :]
    s = jnp.where(mask[None, None], s, -1e30)
    want = jax.nn.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_lse_cotangent_flows(rng):
    """grad through the lse output (what ring merging differentiates)."""
    import math

    from uccl_tpu.ops.pallas_attention import flash_attention_lse

    q, k, v = _qkv(rng, b=1, s=32, h=2, d=16)

    def f(q):
        return jnp.sum(flash_attention_lse(q, k, v, True, 16, 16)[1])

    def ref(q):
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        ) / math.sqrt(q.shape[-1])
        mask = jnp.arange(32)[:, None] >= jnp.arange(32)[None, :]
        return jnp.sum(jax.nn.logsumexp(jnp.where(mask[None, None], s, -1e30), -1))

    np.testing.assert_allclose(
        np.asarray(jax.grad(f)(q)), np.asarray(jax.grad(ref)(q)),
        rtol=2e-4, atol=2e-5,
    )


def test_grad_gqa(rng):
    """Backward kernels fold the repeated q-head contributions onto KV heads."""
    q, k, v = _qkv(rng, b=1, s=64, h=4, hkv=2, d=32)

    def loss(fn):
        return jax.grad(
            lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v))), argnums=(0, 1, 2)
        )(q, k, v)

    gf = loss(lambda q, k, v: flash_attention(q, k, v, True, 32, 32))
    gr = loss(lambda q, k, v: attention_reference(q, k, v, causal=True))
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4)


def test_backward_no_quadratic_memory():
    """The train-step promise: no [S, S] materialization in fwd OR bwd.

    Compares compiled temp-buffer usage of the flash grad at S=2048 against the
    S*S f32 score-matrix size — the flash backward must stay well under one
    score matrix, while the XLA reference backward (which materializes
    softmax residuals) is far above it."""
    s = 2048
    q = jnp.zeros((1, s, 2, 32), jnp.float32)

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, 128, 128) ** 2)

    compiled = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2))).lower(q, q, q).compile()
    mem = compiled.memory_analysis()
    if mem is None or not hasattr(mem, "temp_size_in_bytes"):
        pytest.skip("backend exposes no memory analysis")
    score_bytes = s * s * 4  # one [S, S] f32 per (b, h)
    assert mem.temp_size_in_bytes < score_bytes, (
        f"flash backward temps {mem.temp_size_in_bytes} >= one score matrix "
        f"{score_bytes}"
    )


class TestBlockConfig:
    def test_env_knob_sets_default_blocks(self, monkeypatch):
        """UCCL_TPU_FLASH_BLOCK_Q/K retune the default tiles without code
        changes (the on-chip sweep's actuation path)."""
        from uccl_tpu.ops import pallas_attention as pa
        from uccl_tpu.utils import config as cfg

        monkeypatch.setenv("UCCL_TPU_FLASH_BLOCK_Q", "64")
        monkeypatch.setenv("UCCL_TPU_FLASH_BLOCK_K", "32")
        # params cache their env reads; force a re-read. Register with the
        # PRODUCTION default (0 = auto-size): param() is first-registration-
        # wins, so a stale default here would silently re-pin the fixed-tile
        # behavior for every later flash call in this test process.
        for name in ("flash_block_q", "flash_block_k"):
            p = cfg.param(name, 0)
            p.reset()
        try:
            assert pa._default_blocks() == (64, 32)
        finally:
            monkeypatch.undo()
            for name in ("flash_block_q", "flash_block_k"):
                cfg.param(name, 0).reset()

    def test_grad_with_default_blocks(self):
        """Differentiation with blocks left at their defaults must work —
        custom_vjp routes through the vjp fwd, so None-resolution has to sit
        outside the custom_vjp boundary (regression for exactly that)."""
        import jax
        import jax.numpy as jnp

        from uccl_tpu.ops.pallas_attention import flash_attention

        q = jax.random.normal(jax.random.PRNGKey(0), (1, 128, 2, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 128, 1, 32))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 128, 1, 32))
        g = jax.grad(
            lambda q_: jnp.sum(flash_attention(q_, k, v).astype(jnp.float32))
        )(q)
        assert g.shape == q.shape and bool(jnp.isfinite(g).all())
