"""Replica router + SLO priority scheduling: host-only property tests.

Everything here runs on stub backends (no jax compiles — tier-1 budget):

* **PriorityScheduler** — class-ordered admission (interactive before
  batch, FIFO within class), requeue-at-head for preemption victims.
* **Queue aging + cancellation** — deadline-expired and cancelled queued
  requests leave as ``RequestState.EXPIRED``, counted on
  ``serving_rejected_total{reason=...}``, with conservation intact.
* **Preemption scheduling** — an interactive arrival pauses the newest
  batch request at a chunk boundary (KV exported, slot freed, victim
  requeued at its class head) and the victim resumes with its KV imported
  into whatever slot frees up; the save/restore call pairing is asserted
  on the stub. Oracle bit-exactness of preempted runs lives in
  tests/test_serving.py (real models).
* **Router** — least-loaded admission off live signals (the invariant:
  a strictly less-loaded replica always wins), spillover on a bounded-
  queue race, counted router-level rejection when every replica is
  saturated, backpressure steering, and conservation: every submitted
  request finishes, rejects, or expires exactly once across the set.
"""

import numpy as np
import pytest

from uccl_tpu import obs
from uccl_tpu.serving import (
    PriorityScheduler, RequestState, Router, ServingEngine, SlotPool,
    replica_signals,
)
from uccl_tpu.serving.request import Request, now


def _req(rid, n=2, priority="interactive", deadline_ms=None):
    r = Request(rid=rid, prompt=np.arange(n, dtype=np.int32),
                max_new_tokens=2, t_submit=now(), priority=priority,
                deadline_ms=deadline_ms)
    return r


class _ChunkStub:
    """Chunk-aware stub backend recording every call, including the
    preemption KV save/restore pair. Prefill emits 100, the i-th decode
    step emits i."""

    def __init__(self, n_slots=2, max_seq=64):
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.n_decodes = 0
        self.calls = []

    def prefill(self, tokens, lens, mask, start=None):
        if start is None:
            start = np.zeros(self.n_slots, np.int32)
        slots = tuple(int(s) for s in np.flatnonzero(mask))
        self.calls.append(
            ("prefill", slots, tuple(int(start[s]) for s in slots))
        )
        return np.full(self.n_slots, 100, np.int32)

    def decode(self, tokens, active):
        self.n_decodes += 1
        self.calls.append(
            ("decode", tuple(int(s) for s in np.flatnonzero(active)))
        )
        return np.full(self.n_slots, self.n_decodes, np.int32)

    def export_slot_kv(self, slot, lo, hi):
        self.calls.append(("export", slot, lo, hi))
        z = np.zeros((1, hi - lo, 1, 1), np.float32)
        return z, z

    def import_slot_kv(self, slot, k_rows, v_rows, *, length):
        self.calls.append(("import", slot, length))


class TestPriorityScheduler:
    def test_class_order_beats_arrival_order(self):
        sched = PriorityScheduler()
        pool = SlotPool(4)
        b = _req(0, priority="batch")
        i1 = _req(1, priority="interactive")
        b2 = _req(2, priority="batch")
        i2 = _req(3, priority="interactive")
        for r in (b, i1, b2, i2):
            assert sched.submit(r)
        admitted = [r.rid for _, r in sched.admit(pool)]
        assert admitted == [1, 3, 0, 2], (
            "interactive must drain before batch, FIFO within class"
        )

    def test_requeue_goes_to_class_head(self):
        sched = PriorityScheduler()
        b1, b2 = _req(0, priority="batch"), _req(1, priority="batch")
        sched.submit(b1)
        sched.submit(b2)
        victim = _req(9, priority="batch")
        victim.state = RequestState.PREEMPTED
        sched.requeue(victim)
        assert [r.rid for r in sched.queued_requests()] == [9, 0, 1]

    def test_unknown_class_rejected(self):
        sched = PriorityScheduler()
        with pytest.raises(ValueError, match="unknown priority"):
            sched.submit(_req(0, priority="realtime"))
        eng = ServingEngine(_ChunkStub(), priority_classes=True,
                            prefill_chunk=4)
        with pytest.raises(ValueError, match="unknown priority"):
            eng.submit([1, 2], priority="realtime")

    def test_shared_bound_covers_both_classes(self):
        sched = PriorityScheduler(max_queue=2)
        assert sched.submit(_req(0, priority="batch"))
        assert sched.submit(_req(1, priority="interactive"))
        r = _req(2, priority="interactive")
        assert not sched.submit(r)
        assert r.state is RequestState.REJECTED

    def test_engine_flag_validation(self):
        with pytest.raises(ValueError, match="requires priority_classes"):
            ServingEngine(_ChunkStub(), prefill_chunk=4, preempt=True)
        with pytest.raises(ValueError, match="requires prefill_chunk"):
            ServingEngine(_ChunkStub(), priority_classes=True,
                          preempt=True)


class TestAgingAndCancel:
    def test_deadline_expires_queued_request(self):
        import time

        eng = ServingEngine(_ChunkStub(n_slots=1), prefill_chunk=4)
        hog = eng.submit([1, 2], max_new_tokens=20)
        doomed = eng.submit([1, 2], max_new_tokens=2, deadline_ms=1.0)
        c0 = obs.counter("serving_rejected_total").get(reason="deadline")
        eng.step()
        time.sleep(0.005)
        eng.step()
        assert doomed.state is RequestState.EXPIRED
        assert doomed.finish_reason == "deadline"
        assert doomed.is_done()
        assert obs.counter("serving_rejected_total").get(
            reason="deadline") == c0 + 1
        eng.drain()
        assert hog.state is RequestState.FINISHED
        s = eng.snapshot()
        assert s["expired"] == 1
        assert (s["submitted"] == s["completed"] + s["active"]
                + s["queued"] + s["rejected"] + s["expired"])
        assert eng.pool.leaked() == 0

    def test_deadline_survives_fast_admission(self):
        eng = ServingEngine(_ChunkStub(n_slots=2), prefill_chunk=4)
        r = eng.submit([1, 2], max_new_tokens=2, deadline_ms=60000.0)
        eng.drain()
        assert r.state is RequestState.FINISHED  # admitted well in time

    def test_cancel_queued_only(self):
        eng = ServingEngine(_ChunkStub(n_slots=1), prefill_chunk=4)
        a = eng.submit([1, 2], max_new_tokens=4)
        b = eng.submit([1, 2], max_new_tokens=4)
        eng.step()  # a admitted, b queued
        c0 = obs.counter("serving_rejected_total").get(reason="cancel")
        assert not eng.cancel(a.rid), "in-slot requests run to completion"
        assert eng.cancel(b.rid)
        assert not eng.cancel(b.rid), "second cancel is a no-op"
        assert b.state is RequestState.EXPIRED
        assert b.finish_reason == "cancel"
        assert obs.counter("serving_rejected_total").get(
            reason="cancel") == c0 + 1
        eng.drain()
        s = eng.snapshot()
        assert s["expired"] == 1 and s["completed"] == 1
        assert (s["submitted"] == s["completed"] + s["rejected"]
                + s["expired"])

    def test_submit_validation(self):
        eng = ServingEngine(_ChunkStub(), prefill_chunk=4)
        with pytest.raises(ValueError, match="deadline_ms"):
            eng.submit([1], deadline_ms=0)


class TestPreemptionScheduling:
    def _engine(self, n_slots=2):
        return ServingEngine(_ChunkStub(n_slots=n_slots), prefill_chunk=4,
                             priority_classes=True, preempt=True)

    def test_interactive_preempts_newest_batch(self):
        eng = self._engine()
        b1 = eng.submit(list(range(8)), max_new_tokens=6, priority="batch")
        b2 = eng.submit(list(range(8)), max_new_tokens=6, priority="batch")
        eng.step()
        eng.step()  # both finished prefill, decoding
        p0 = obs.counter("serving_preempted_total").get()
        ia = eng.submit([1, 2, 3], max_new_tokens=2,
                        priority="interactive")
        done = eng.step()  # ia may finish inside the preempting step
        # newest-first: b2 (higher admit_seq) pauses, b1 keeps decoding
        assert b2.state is RequestState.PREEMPTED
        assert b1.state is RequestState.ACTIVE
        assert b2.slot is None and b2.preemptions == 1
        assert obs.counter("serving_preempted_total").get() == p0 + 1
        # save happened: KV exported for the victim's live rows
        kinds = [c[0] for c in eng.backend.calls]
        assert "export" in kinds
        r0 = obs.counter("serving_resumed_total").get()
        done += eng.drain()
        assert {r.rid for r in done} == {b1.rid, b2.rid, ia.rid}
        assert all(r.state is RequestState.FINISHED
                   for r in (b1, b2, ia))
        # restore happened exactly once, stamping back the victim's saved
        # live length (prompt + tokens committed before the pause, minus
        # the first token which writes no KV row)
        imports = [c for c in eng.backend.calls if c[0] == "import"]
        assert len(imports) == 1
        assert imports[0][2] == b2.prompt.size + 1  # 2 tokens at preempt
        assert obs.counter("serving_resumed_total").get() == r0 + 1
        s = eng.snapshot()
        assert s["preempted"] == 1 and s["resumed"] == 1
        assert eng.pool.leaked() == 0

    def test_batch_head_never_preempts(self):
        eng = self._engine()
        b1 = eng.submit(list(range(8)), max_new_tokens=8, priority="batch")
        b2 = eng.submit(list(range(8)), max_new_tokens=8, priority="batch")
        eng.step()
        b3 = eng.submit([1, 2], max_new_tokens=2, priority="batch")
        eng.step()
        eng.step()
        assert b3.state is RequestState.QUEUED, (
            "a batch arrival must wait for a free slot, never preempt"
        )
        assert b1.state is not RequestState.PREEMPTED
        assert b2.state is not RequestState.PREEMPTED
        eng.drain()
        assert eng.pool.leaked() == 0

    def test_no_batch_victim_means_waiting(self):
        eng = self._engine()
        i1 = eng.submit(list(range(8)), max_new_tokens=8,
                        priority="interactive")
        i2 = eng.submit(list(range(8)), max_new_tokens=8,
                        priority="interactive")
        eng.step()
        i3 = eng.submit([1, 2], max_new_tokens=2, priority="interactive")
        eng.step()
        assert i3.state is RequestState.QUEUED, (
            "interactive never preempts interactive"
        )
        eng.drain()
        assert all(r.state is RequestState.FINISHED for r in (i1, i2, i3))

    def test_mid_prefill_victim_resumes_at_cursor(self):
        eng = self._engine(n_slots=1)
        bb = eng.submit(list(range(12)), max_new_tokens=2,
                        priority="batch")
        eng.step()  # one 4-token chunk in
        assert bb.prefill_pos == 4
        ia = eng.submit([1, 2], max_new_tokens=2, priority="interactive")
        eng.step()
        assert bb.state is RequestState.PREEMPTED
        assert bb.prefill_pos == 4, "the cursor is the saved state"
        eng.drain()
        assert bb.state is RequestState.FINISHED
        # the resumed prefill continued at start=4 — never re-ran chunk 0
        starts = [c[2] for c in eng.backend.calls if c[0] == "prefill"]
        resumed_starts = [s for st in starts for s in st]
        assert resumed_starts.count(0) == 2  # bb chunk 0 + ia chunk 0
        assert 4 in resumed_starts and 8 in resumed_starts
        assert eng.pool.leaked() == 0

    def test_victim_requeues_ahead_of_batch_arrivals(self):
        eng = self._engine()
        b1 = eng.submit(list(range(8)), max_new_tokens=6, priority="batch")
        b2 = eng.submit(list(range(8)), max_new_tokens=6, priority="batch")
        eng.step()
        eng.step()
        later = eng.submit([1, 2], max_new_tokens=2, priority="batch")
        ia = eng.submit([1, 2, 3], max_new_tokens=2,
                        priority="interactive")
        eng.step()  # preempts b2; batch queue: [b2(head), later]
        assert b2.state is RequestState.PREEMPTED
        eng.drain()
        # resume order: b2 re-admitted BEFORE `later` was first admitted
        # (admit_seq is re-stamped at the resume admission)
        assert b2.admit_seq < later.admit_seq
        assert b2.state is RequestState.FINISHED
        assert later.state is RequestState.FINISHED


class TestRouter:
    def _mk(self, n=2, n_slots=2, max_queue=None, **kw):
        return [ServingEngine(_ChunkStub(n_slots=n_slots),
                              prefill_chunk=4, max_queue=max_queue, **kw)
                for _ in range(n)]

    def test_least_loaded_invariant(self):
        """THE routing property: a strictly less-loaded replica always
        receives the next request, wherever it sits in the list."""
        for busy_idx in (0, 1, 2):
            engines = self._mk(3)
            r = Router(engines)
            # skew: load one replica with queued+active work
            for _ in range(4):
                engines[busy_idx].submit(list(range(8)),
                                         max_new_tokens=8)
            req = r.submit([1, 2], max_new_tokens=2)
            chosen = [i for i, e in enumerate(engines)
                      if any(q is req
                             for q in e.sched.queued_requests())]
            assert chosen and chosen[0] != busy_idx, (
                f"routed to the loaded replica {busy_idx}"
            )
            r.drain()
            assert r.leaked() == 0

    def test_signals_expose_the_decision_inputs(self):
        engines = self._mk(2)
        engines[0].submit(list(range(8)), max_new_tokens=8)
        s0 = replica_signals(engines[0])
        s1 = replica_signals(engines[1])
        assert s0["debt_tokens"] == 16 and s1["debt_tokens"] == 0
        assert s0["queued"] == 1 and s1["queued"] == 0
        assert s1["free_slots"] == 2
        Router(engines).drain()

    def test_conservation_across_replicas(self):
        """Every submitted request finishes, rejects, or expires exactly
        once across the replica set — the router never loses or
        double-runs one."""
        engines = self._mk(3, n_slots=2, max_queue=2)
        r = Router(engines)
        results = [r.submit([1, 2, 3], max_new_tokens=3)
                   for _ in range(24)]
        accepted = [q for q in results if q is not None]
        rejected = 24 - len(accepted)
        finished = r.drain()
        assert len(finished) == len(accepted)
        # exactly-once: the finished set IS the accepted set, no dupes
        # (rids repeat across replicas — identity is the honest key)
        assert {id(q) for q in finished} == {id(q) for q in accepted}
        assert rejected >= 0  # bound 2×3 queues + 6 slots < 24 offered
        snap = r.snapshot()
        assert snap["completed"] == len(accepted)
        assert (snap["submitted"] == snap["completed"] + snap["active"]
                + snap["queued"] + snap["rejected"] + snap["expired"])
        assert r.leaked() == 0
        assert sum(snap["routed"]) == len(accepted)
        assert all(s.state is RequestState.FINISHED for s in accepted)

    def test_round_robin_when_equal(self):
        engines = self._mk(3)
        r = Router(engines)
        for _ in range(6):
            r.submit([1, 2], max_new_tokens=2)
        assert r.routed == [2, 2, 2], (
            "equal replicas must take turns, not pile on replica 0"
        )
        r.drain()

    def test_spillover_when_choice_rejects(self):
        """The bounded-queue race: the least-loaded replica can reject
        between the signal read and the submit — the router spills to the
        next-ranked one and counts it."""
        engines = self._mk(2, n_slots=1)
        # replica 0: lightly loaded but saturated — full pool, and the
        # queue bound closes AFTER the hog is in its slot (max_queue=0
        # rejects at submit, the documented backpressure edge)
        hog = engines[0].submit(list(range(4)), max_new_tokens=8)
        engines[0].step()  # hog admitted: pool full
        engines[0].sched.max_queue = 0
        assert engines[0].sched.qsize == 0
        # replica 1: MORE debt so replica 0 ranks first, but queue room
        engines[1].submit(list(range(8)), max_new_tokens=8)
        engines[1].submit(list(range(8)), max_new_tokens=8)
        engines[1].step()
        s0 = obs.counter("serving_router_spillover_total").get()
        r = Router(engines)
        assert replica_signals(engines[0])["debt_tokens"] < \
            replica_signals(engines[1])["debt_tokens"]
        req = r.submit([1, 2], max_new_tokens=2)
        assert req is not None
        assert obs.counter("serving_router_spillover_total").get() == s0 + 1
        r.drain()
        assert r.leaked() == 0

    def test_all_saturated_counts_router_rejection(self):
        engines = self._mk(2, n_slots=1)
        r = Router(engines)
        for e in engines:
            e.submit(list(range(4)), max_new_tokens=4)
            e.step()
            e.sched.max_queue = 0  # pool full + no queue room = saturated
        c0 = obs.counter("serving_router_rejected_total").get(
            reason="saturated")
        assert r.submit([1, 2], max_new_tokens=2) is None
        assert obs.counter("serving_router_rejected_total").get(
            reason="saturated") == c0 + 1
        r.drain()

    def test_routed_counter_labels_per_replica(self):
        engines = self._mk(2)
        c = obs.counter("serving_router_requests_total")
        before = [c.get(replica="0"), c.get(replica="1")]
        r = Router(engines)
        for _ in range(4):
            r.submit([1, 2], max_new_tokens=2)
        deltas = [c.get(replica="0") - before[0],
                  c.get(replica="1") - before[1]]
        assert deltas == r.routed == [2, 2]
        r.drain()

    def test_backpressure_steers_away(self):
        """A disagg-style replica reporting adoption backpressure loses to
        an equal-debt replica without it (the GRANT-hint signal)."""

        class _Worker:
            """Replica wrapper shaped like disagg.PrefillWorker."""

            def __init__(self, engine, bp):
                self.engine = engine
                self._bp = bp

            def adoption_backpressure(self):
                return self._bp

            def submit(self, prompt, *, max_new_tokens=16, eos_id=None,
                       priority="interactive", tenant="default",
                       trace=None):
                return self.engine.submit(prompt,
                                          max_new_tokens=max_new_tokens,
                                          eos_id=eos_id,
                                          priority=priority,
                                          tenant=tenant, trace=trace)

            def step(self):
                if self.engine.has_work():
                    self.engine.step()

            def idle(self):
                return not self.engine.has_work()

        engines = self._mk(2)
        saturated = _Worker(engines[0], bp=3)
        free = _Worker(engines[1], bp=0)
        r = Router([saturated, free])
        assert replica_signals(saturated)["backpressure"] == 3
        req = r.submit([1, 2], max_new_tokens=2)
        assert any(q.rid == req.rid
                   for q in engines[1].sched.queued_requests()), (
            "new prompts must steer away from the saturated decode peer"
        )
        r.drain()
        assert r.leaked() == 0

    def test_priority_and_deadline_ride_through(self):
        engines = self._mk(2, priority_classes=True)
        r = Router(engines)
        req = r.submit([1, 2], max_new_tokens=2, priority="batch",
                       deadline_ms=60000.0)
        assert req.priority == "batch" and req.deadline_ms == 60000.0
        r.drain()

    def test_merged_snapshot_percentiles(self):
        engines = self._mk(2)
        r = Router(engines)
        for _ in range(6):
            r.submit([1, 2], max_new_tokens=3)
        r.drain()
        snap = r.snapshot()
        assert snap["completed"] == 6
        assert "p50" in snap["ttft_ms"]
        assert len(snap["per_replica"]) == 2
        assert sum(p["completed"] for p in snap["per_replica"]) == 6
