"""Speculative decoding: drafter units + spec scheduling/accounting
properties (host-only, stub backend — the real-model oracle-exactness
suites live in tests/test_serving.py beside the engine's other oracle
tests, sharing its module fixtures).

The stub target model's greedy continuation for EVERY slot is the fixed
arithmetic sequence last+1, last+2, ... seeded at 100 by prefill — so
acceptance, multi-token commits, EOS truncation inside an accepted prefix,
and the token-budget cap are all fully predictable with no jax."""

import numpy as np
import pytest

from uccl_tpu.serving import (
    NGramDrafter, RequestState, ServingEngine,
)
from uccl_tpu.serving.spec import Drafter


class _SpecStubBackend:
    """Target 'model' whose greedy continuation is always last_token + 1:
    prefill emits 100, verify scores a window against the arithmetic
    continuation of its column-0 token (stateless — the committed history
    is encoded in the last token itself). Records every call."""

    def __init__(self, n_slots=2, max_seq=64):
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.calls = []

    def prefill(self, tokens, lens, mask, start=None):
        slots = tuple(int(s) for s in np.flatnonzero(mask))
        self.calls.append(("prefill", slots))
        return np.full(self.n_slots, 100, np.int32)

    def decode(self, tokens, active):
        self.calls.append(
            ("decode", tuple(int(s) for s in np.flatnonzero(active)))
        )
        return (tokens + 1).astype(np.int32)

    def verify(self, tokens, active):
        s = tokens.shape[1]
        out = np.zeros((self.n_slots, s), np.int32)
        n_acc = np.zeros(self.n_slots, np.int32)
        for b in np.flatnonzero(active):
            out[b] = int(tokens[b, 0]) + 1 + np.arange(s)
            m = 0
            for j in range(1, s):
                if tokens[b, j] != out[b, j - 1]:
                    break
                m += 1
            n_acc[b] = m
        self.calls.append(
            ("verify", tuple(int(b) for b in np.flatnonzero(active)))
        )
        return out, n_acc


class _ArithmeticDrafter(Drafter):
    """Always right for the stub target: proposes last+1, last+2, ..."""

    def draft(self, context, k):
        return np.asarray(context)[-1] + 1 + np.arange(k, dtype=np.int32)


class _ZeroDrafter(Drafter):
    """Always wrong for the stub target (its continuations are >= 101)."""

    def draft(self, context, k):
        return np.zeros(k, np.int32)


def _stub_oracle(n):
    """What the stub target emits for any prompt: 100, 101, ..."""
    return list(range(100, 100 + n))


class TestNGramDrafter:
    def test_periodic_suffix_proposes_cycle_continuation(self):
        d = NGramDrafter(max_ngram=3)
        got = d.draft(np.array([1, 2, 3, 1, 2, 3, 1, 2]), 3)
        assert got.tolist() == [3, 1, 2]

    def test_most_recent_match_wins(self):
        d = NGramDrafter(max_ngram=2)
        # suffix [1, 2] occurs at i=1 and i=4; the later one's
        # continuation (9) must win over the earlier one's (7)
        got = d.draft(np.array([5, 1, 2, 7, 1, 2, 9, 1, 2]), 2)
        assert got.tolist() == [9, 1]

    def test_longest_ngram_preferred(self):
        d = NGramDrafter(max_ngram=3, min_ngram=1)
        # trigram [2, 3, 4] matches at i=0 (→ 8); the unigram [4] also
        # matches at i=5 (→ 9) but the longer match must be taken
        got = d.draft(np.array([2, 3, 4, 8, 7, 4, 9, 2, 3, 4]), 1)
        assert got.tolist() == [8]

    def test_no_repetition_abstains(self):
        d = NGramDrafter()
        assert d.draft(np.arange(10), 4).size == 0

    def test_short_context_and_k_zero(self):
        d = NGramDrafter()
        assert d.draft(np.array([7]), 4).size == 0
        assert d.draft(np.array([1, 2, 1]), 0).size == 0

    def test_validation(self):
        with pytest.raises(ValueError, match="min_ngram"):
            NGramDrafter(max_ngram=2, min_ngram=3)
        with pytest.raises(ValueError, match="min_ngram"):
            NGramDrafter(max_ngram=2, min_ngram=0)

    def test_proposal_capped_at_k(self):
        d = NGramDrafter(max_ngram=1)
        got = d.draft(np.array([4, 5, 6, 7, 4]), 2)
        assert got.tolist() == [5, 6]


class TestSpecScheduling:
    def test_validation(self):
        with pytest.raises(ValueError, match="spec_k must be"):
            ServingEngine(_SpecStubBackend(), spec_k=0)
        with pytest.raises(ValueError, match="drafter requires spec_k"):
            ServingEngine(_SpecStubBackend(), drafter=_ZeroDrafter())

    def test_full_accept_commits_k_plus_one_per_step(self):
        eng = ServingEngine(_SpecStubBackend(n_slots=1), spec_k=3,
                            drafter=_ArithmeticDrafter())
        r = eng.submit([1, 2], max_new_tokens=9)
        eng.step()  # prefill emits token 1, verify commits 4 more in-step
        assert r.n_generated == 5
        eng.drain()
        assert r.out_tokens == _stub_oracle(9)
        assert r.finish_reason == "length"
        # 9 tokens: 1 at prefill + two full windows (4 + 4)
        assert eng.metrics.decode_calls == 2
        assert eng.metrics.decode_tokens == 8
        assert eng.metrics.spec_accepted == 6 and eng.metrics.spec_proposed == 6
        assert eng.pool.leaked() == 0

    def test_full_reject_is_vanilla_pace_same_output(self):
        eng = ServingEngine(_SpecStubBackend(n_slots=1), spec_k=3,
                            drafter=_ZeroDrafter())
        r = eng.submit([1, 2], max_new_tokens=5)
        eng.drain()
        assert r.out_tokens == _stub_oracle(5)
        # every window commits exactly the bonus token: vanilla pace
        assert eng.metrics.decode_calls == 4
        assert eng.metrics.decode_tokens == 4
        assert eng.metrics.spec_accepted == 0
        assert eng.metrics.accepted_len == [0, 0, 0, 0]

    def test_eos_inside_accepted_prefix_truncates_commit(self):
        # stub emits 100, 101, 102, ...; EOS 102 arrives mid-window with a
        # fully accepted draft — commits must stop AT the EOS token
        eng = ServingEngine(_SpecStubBackend(n_slots=1), spec_k=4,
                            drafter=_ArithmeticDrafter())
        r = eng.submit([1], max_new_tokens=10, eos_id=102)
        eng.drain()
        assert r.finish_reason == "eos"
        assert r.out_tokens == [100, 101, 102]
        assert eng.pool.leaked() == 0

    def test_budget_truncates_commit_at_max_tokens(self):
        eng = ServingEngine(_SpecStubBackend(n_slots=1), spec_k=4,
                            drafter=_ArithmeticDrafter())
        r = eng.submit([1], max_new_tokens=3)
        eng.drain()
        assert r.finish_reason == "length"
        assert r.out_tokens == _stub_oracle(3)
        # one window was enough: 1 prefill token + 2 committed of the 5
        assert eng.metrics.decode_calls == 1
        assert eng.metrics.decode_tokens == 2

    def test_chunk_finishing_joins_same_step_verify(self):
        """A prompt finishing its last prefill chunk emits its first token
        AND takes the same step's verify pass (the chunked-prefill rule,
        unchanged under speculation)."""
        eng = ServingEngine(_SpecStubBackend(n_slots=2), prefill_chunk=2,
                            spec_k=2, drafter=_ArithmeticDrafter())
        r = eng.submit([1, 2, 3, 4], max_new_tokens=6)
        eng.step()  # chunk [0, 2): still mid-prefill, no decode work
        assert r.state is RequestState.PARTIAL_PREFILL
        assert eng.backend.calls == [("prefill", (0,))]
        eng.step()  # final chunk + SAME-step verify
        assert eng.backend.calls[1:] == [("prefill", (0,)), ("verify", (0,))]
        assert r.n_generated == 4  # first token + k+1 window commits
        eng.drain()
        assert r.out_tokens == _stub_oracle(6)
        assert eng.pool.leaked() == 0

    def test_spec_budget_charges_verify_width(self):
        """step_tokens accounts 1+k tokens per decoding slot: with k=3 a
        decoding slot charges 4, so a budget of 8 cannot admit a chunk of
        8 while one decode is in flight (8 - 4 < 8)."""
        eng = ServingEngine(_SpecStubBackend(n_slots=2), prefill_chunk=8,
                            step_tokens=8, spec_k=3,
                            drafter=_ZeroDrafter())
        a = eng.submit([1, 2], max_new_tokens=8)
        eng.step()  # admit + prefill A (spends the whole budget)
        b = eng.submit([3, 4], max_new_tokens=2)
        eng.step()  # A decodes (charges 4): B's chunk of 8 must defer
        assert b.state is RequestState.QUEUED
        eng.drain()
        assert a.out_tokens == _stub_oracle(8)
        assert b.out_tokens == _stub_oracle(2)
        assert eng.pool.leaked() == 0

    def test_mixed_slots_conservation_and_metrics(self):
        eng = ServingEngine(_SpecStubBackend(n_slots=2), spec_k=2,
                            drafter=_ArithmeticDrafter(), max_queue=4)
        reqs = [eng.submit([1], max_new_tokens=5) for _ in range(4)]
        for _ in range(2):
            eng.step()
            s = eng.snapshot()
            assert (s["submitted"]
                    == s["completed"] + s["active"] + s["queued"]
                    + s["rejected"]), s
        eng.drain()
        for r in reqs:
            assert r.out_tokens == _stub_oracle(5)
        s = eng.snapshot()
        assert s["decode_tokens"] == eng.metrics.decode_tokens == 4 * 4
        assert "decode_tok_s" in s
        assert s["spec_acceptance_rate"] == 1.0
        assert "p50" in s["accepted_len"] and "mean" in s["accepted_len"]
        assert eng.pool.leaked() == 0


class TestVanillaAccountingRegression:
    """Satellite: multi-token-step accounting must leave vanilla numbers
    unchanged — a vanilla decode call still counts exactly one token per
    active slot, and the pre-existing snapshot keys keep their values."""

    def test_vanilla_decode_tokens_one_per_slot_step(self):
        eng = ServingEngine(_SpecStubBackend(n_slots=2))
        reqs = [eng.submit([1, 2], max_new_tokens=4) for _ in range(3)]
        eng.drain()
        # 3 requests x 4 tokens, first token of each from prefill
        assert eng.metrics.decode_tokens == sum(
            r.n_generated - 1 for r in reqs
        )
        s = eng.snapshot()
        assert s["output_tokens"] == 12
        assert s["decode_tokens"] == 9
        assert "spec_acceptance_rate" not in s  # no spec series w/o spec
        assert s["decode_calls"] == eng.metrics.decode_calls

    def test_eos_at_prefill_counts_zero_decode_tokens(self):
        eng = ServingEngine(_SpecStubBackend(n_slots=1))
        r = eng.submit([5], max_new_tokens=10, eos_id=100)
        eng.drain()
        assert r.out_tokens == [100]
        assert eng.metrics.decode_tokens == 0
        assert "decode_tok_s" not in eng.snapshot()
