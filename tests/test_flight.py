"""Tier-1 unit path for the black-box observability stack: flight
recorder trigger-to-bundle semantics, SLO burn-rate monitors, the
transport/health trigger hooks, the doctor forensic CLI, trace_merge
graceful degradation, and the federator edge cases.

Everything here is in-process with injectable clocks — the heavy
end-to-end attribution arms (chaos_bench/fleet_bench with
``--flight-dir``) live behind the qa.sh chaos tier; this file is the
fast gate that keeps the recorder's contract honest on every tier-1
run.
"""

import importlib.util
import json
import os
import sys

import pytest

from uccl_tpu import doctor as doctor_mod
from uccl_tpu import obs
from uccl_tpu.obs import aggregate as agg_mod
from uccl_tpu.obs import counters as obs_counters
from uccl_tpu.obs import flight as flight_mod
from uccl_tpu.obs import slo as slo_mod
from uccl_tpu.obs import tracer as tracer_mod
from uccl_tpu.p2p import sack as sack_mod
from uccl_tpu.p2p.sack import FAST, NEW, RTO, SackTxWindow
from uccl_tpu.serving.health import FailureDetector

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    """scripts/ is not a package — load a script module by path."""
    path = os.path.join(_REPO, "scripts", name)
    spec = importlib.util.spec_from_file_location(name[:-3], path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_obs():
    """Flight recorder, tracer and sack arming are process-global —
    every test leaves them off so no other test inherits a live
    recorder."""
    yield
    flight_mod.disable()
    obs.disable_tracing()
    sack_mod.arm_flight(None, None)


# -- flight recorder core ----------------------------------------------------

def test_trigger_writes_schema_bundle(tmp_path):
    rec = flight_mod.enable(str(tmp_path))
    path = rec.trigger("retx_storm", key="w0", retx_fast=3, chunks=10)
    assert path is not None and os.path.basename(path) == \
        "flight_001_retx_storm.json"
    with open(path) as f:
        b = json.load(f)
    assert b["schema"] == "uccl_tpu.flight/1"
    assert b["seq"] == 1
    assert b["trigger"]["kind"] == "retx_storm"
    assert b["trigger"]["key"] == "w0"
    assert b["trigger"]["context"] == {"retx_fast": 3, "chunks": 10}
    for k in ("host", "events", "state", "metrics_prom", "registry"):
        assert k in b
    assert b["host"]["pid"] == os.getpid()
    assert rec.bundles == [path]
    # count-before-snapshot: the bundle's own registry text shows this
    # very dump (what check_obs --flight asserts per bundle)
    own = [ln for ln in b["metrics_prom"].splitlines()
           if ln.startswith('obs_flight_dumps_total{trigger="retx_storm"}')]
    assert own and float(own[0].rsplit(" ", 1)[1]) >= 1


def test_unknown_trigger_kind_raises(tmp_path):
    rec = flight_mod.enable(str(tmp_path))
    with pytest.raises(ValueError, match="unknown flight trigger"):
        rec.trigger("made_up_kind")


def test_dedup_rate_and_cap_suppression(tmp_path):
    clk = [0.0]
    rec = flight_mod.enable(str(tmp_path), min_interval_s=10.0,
                            max_dumps=2, clock=lambda: clk[0])
    sup = flight_mod._SUPPRESSED

    def sup_count(reason):
        return sum(v for lbl, v in sup.samples()
                   if lbl.get("reason") == reason)

    base = {r: sup_count(r) for r in ("dedup", "rate", "cap")}
    assert rec.trigger("peer_dead", key="a") is not None
    # same (kind, key) again -> one fault, one bundle
    assert rec.trigger("peer_dead", key="a") is None
    assert sup_count("dedup") == base["dedup"] + 1
    # different key but inside min_interval_s -> rate-limited
    assert rec.trigger("peer_dead", key="b") is None
    assert sup_count("rate") == base["rate"] + 1
    clk[0] = 11.0
    assert rec.trigger("peer_dead", key="c") is not None
    # recorder full -> capped even after the interval passes
    clk[0] = 22.0
    assert rec.trigger("peer_dead", key="d") is None
    assert sup_count("cap") == base["cap"] + 1
    assert len(rec.bundles) == 2


def test_reenable_resets_dedup_state(tmp_path):
    rec1 = flight_mod.enable(str(tmp_path / "run1"))
    assert rec1.trigger("peer_dead", key="x") is not None
    # a new enable() replaces the singleton with fresh dedup state —
    # the bench's clean phase relies on this
    rec2 = flight_mod.enable(str(tmp_path / "run2"))
    assert flight_mod.get_recorder() is rec2
    assert rec2.trigger("peer_dead", key="x") is not None
    assert len(rec2.bundles) == 1


def test_module_trigger_noop_when_disabled():
    flight_mod.disable()
    assert not flight_mod.enabled()
    assert flight_mod.trigger("peer_dead", key="z") is None


def test_state_providers_and_broken_provider(tmp_path):
    rec = flight_mod.enable(str(tmp_path))
    rec.register_provider("good", lambda: {"depth": 4})
    rec.register_provider("bad", lambda: 1 / 0)
    path = rec.trigger("step_stall", dur_s=0.5)
    with open(path) as f:
        b = json.load(f)
    assert b["state"]["good"] == {"depth": 4}
    # a raising provider must not lose the dump — its error is frozen
    assert "ZeroDivisionError" in b["state"]["bad"]["error"]


def test_record_exception_and_excepthook_idempotent(tmp_path):
    rec = flight_mod.enable(str(tmp_path), min_interval_s=0.0)
    try:
        raise RuntimeError("boom")
    except RuntimeError as e:
        path = flight_mod.record_exception(e, where="unit")
    with open(path) as f:
        b = json.load(f)
    assert b["trigger"]["kind"] == "uncaught_exception"
    assert b["trigger"]["key"] == "unit:RuntimeError"
    assert b["trigger"]["context"]["exc_type"] == "RuntimeError"
    assert "boom" in b["trigger"]["context"]["traceback_tail"]

    prev_hook, prev_state = sys.excepthook, flight_mod._prev_excepthook
    try:
        flight_mod.install_excepthook("unit")
        installed = sys.excepthook
        flight_mod.install_excepthook("unit")  # second install is a no-op
        assert sys.excepthook is installed
    finally:
        sys.excepthook = prev_hook
        flight_mod._prev_excepthook = prev_state


def test_tracer_ring_overflow_counts_dropped():
    before = tracer_mod._EVENTS_DROPPED.total()
    tr = obs.enable_tracing(1)
    tr.instant("a")
    tr.instant("b")
    tr.instant("c")
    assert tr.dropped == 2
    assert tracer_mod._EVENTS_DROPPED.total() == before + 2


# -- SLO burn-rate monitors --------------------------------------------------

def _slo_setup(threshold_s=0.1):
    reg = obs_counters.Registry()
    fam = reg.histogram("unit_ttft_seconds", buckets=[0.01, 0.1, 1.0])
    clk = [0.0]
    obj = slo_mod.Objective(name="ttft", metric="unit_ttft_seconds",
                            threshold_s=threshold_s, target=0.99)
    mon = slo_mod.BurnRateMonitor([obj], windows=((60.0, 1.0),),
                                  registry=reg, clock=lambda: clk[0])
    return reg, fam, clk, mon


def test_slo_clean_window_no_alerts():
    _reg, fam, clk, mon = _slo_setup()
    for _ in range(5):
        fam.observe(0.05)  # compliant: under the 0.1 s objective
    mon.sample()
    clk[0] = 61.0
    assert mon.evaluate() == []
    assert mon.alerts_fired == 0


def test_slo_burn_alerts_counts_and_flight(tmp_path):
    rec = flight_mod.enable(str(tmp_path))
    _reg, fam, clk, mon = _slo_setup()
    mon.sample()
    for _ in range(5):
        fam.observe(0.5)  # every request past the objective
    clk[0] = 61.0
    before = slo_mod._ALERTS.total()
    alerts = mon.evaluate()
    assert len(alerts) == 1
    a = alerts[0]
    assert a.objective == "ttft" and a.window_s == 60.0
    assert a.violations == 5 and a.total == 5
    assert a.error_rate == 1.0 and a.burn == pytest.approx(100.0)
    assert mon.alerts_fired == 1
    assert slo_mod._ALERTS.total() == before + 1
    assert [os.path.basename(p) for p in rec.bundles] == \
        ["flight_001_slo_burn.json"]
    # emit=False re-evaluates without side effects (doctor's replay path)
    assert len(mon.evaluate(emit=False)) == 1
    assert mon.alerts_fired == 1
    assert slo_mod._ALERTS.total() == before + 1
    assert len(rec.bundles) == 1


def test_slo_counter_reset_clamps_to_current():
    _reg, fam, clk, mon = _slo_setup()
    for _ in range(10):
        fam.observe(0.5)
    mon.sample()
    # restarted worker: cumulative counts drop below the snapshot
    fam.clear()
    fam.observe(0.5)
    fam.observe(0.5)
    clk[0] = 61.0
    alerts = mon.evaluate(emit=False)
    assert len(alerts) == 1
    assert alerts[0].total == 2 and alerts[0].violations == 2


def test_slo_objective_target_validation():
    with pytest.raises(ValueError):
        slo_mod.Objective(name="x", metric="m", threshold_s=1.0,
                          target=1.0)


# -- transport / health trigger hooks ----------------------------------------

def test_sack_armed_storm_and_backoff_trigger(tmp_path):
    rec = flight_mod.enable(str(tmp_path), min_interval_s=0.0)
    sack_mod.arm_flight(storm_after=2, rto_backoff_s=0.0)
    win = SackTxWindow([100] * 4, n_paths=2)
    for seq in range(4):
        win.mark_sent(seq, 0, NEW, 0.0)
    # an RTO retransmit past the armed backoff ceiling -> rto_backoff
    win.mark_sent(0, 1, RTO, 1.0)
    # second retransmit reaches storm_after=2 -> retx_storm
    win.mark_sent(1, 1, FAST, 1.1)
    kinds = [os.path.basename(p) for p in rec.bundles]
    assert kinds == ["flight_001_rto_backoff.json",
                     "flight_002_retx_storm.json"]
    # more retx on a FRESH window: the process-wide sack:<kind> key
    # dedupes — one sustained loss episode, one bundle per fault class
    win2 = SackTxWindow([100] * 4, n_paths=2)
    for seq in range(4):
        win2.mark_sent(seq, 0, NEW, 2.0)
    win2.mark_sent(0, 1, RTO, 3.0)
    win2.mark_sent(1, 1, FAST, 3.1)
    assert len(rec.bundles) == 2


def test_sack_unarmed_never_triggers(tmp_path):
    rec = flight_mod.enable(str(tmp_path), min_interval_s=0.0)
    sack_mod.arm_flight(None, None)
    win = SackTxWindow([100] * 4, n_paths=2)
    for seq in range(4):
        win.mark_sent(seq, 0, NEW, 0.0)
    win.mark_sent(0, 1, RTO, 1.0)
    win.mark_sent(1, 1, FAST, 1.1)
    assert rec.bundles == []


def test_health_dead_peer_fires_flight_per_detector(tmp_path):
    rec = flight_mod.enable(str(tmp_path), min_interval_s=0.0)
    clk = [0.0]
    det1 = FailureDetector(suspect_after_s=0.5, dead_after_s=1.5,
                           clock=lambda: clk[0])
    det2 = FailureDetector(suspect_after_s=0.5, dead_after_s=1.5,
                           clock=lambda: clk[0])
    # two detectors (router + disagg) can both track a peer named "0" —
    # each death gets its own bundle because the dedup key carries the
    # detector identity
    det1.register("0")
    det2.register("0")
    clk[0] = 2.0
    det1.tick()
    det2.tick()
    kinds = [os.path.basename(p) for p in rec.bundles]
    assert kinds == ["flight_001_peer_dead.json",
                     "flight_002_peer_dead.json"]
    # DEAD is terminal: further ticks re-fire nothing
    clk[0] = 4.0
    det1.tick()
    assert len(rec.bundles) == 2
    b = doctor_mod.load_bundle(rec.bundles[0])
    assert b["trigger"]["context"]["peer"] == "0"
    assert doctor_mod.diagnose(b)["root_cause"] == "replica_failure"


# -- doctor ------------------------------------------------------------------

def test_doctor_root_causes_and_json_cli(tmp_path, capsys):
    rec = flight_mod.enable(str(tmp_path), min_interval_s=0.0)
    rec.trigger("peer_dead", key="h:0", peer="r0", source="health")
    rec.trigger("retx_storm", key="s", retx_fast=3, retx_rto=1,
                chunks=10, path_scores=[1.0, 0.2])
    rec.trigger("ctrl_storm", key="c", retries=4)
    try:
        raise KeyError("gone")
    except KeyError as e:
        flight_mod.record_exception(e, where="unit")
    flight_mod.disable()

    assert doctor_mod.main([str(tmp_path), "--json"]) == 0
    verdicts = json.loads(capsys.readouterr().out)
    assert [v["root_cause"] for v in verdicts] == [
        "replica_failure", "path_loss", "control_plane_loss",
        "driver_crash"]
    storm = verdicts[1]
    assert storm["details"]["retx_fast"] == 3
    assert storm["details"]["worst_path"] == 1
    # prose mode renders every bundle too
    assert doctor_mod.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "root cause: path_loss" in out
    assert "4 bundle(s) examined" in out


def test_doctor_rejects_non_bundle(tmp_path):
    p = tmp_path / "flight_001_bogus.json"
    p.write_text(json.dumps({"schema": "nope"}))
    with pytest.raises(ValueError, match="not a flight bundle"):
        doctor_mod.load_bundle(str(p))
    assert doctor_mod.main([str(p)]) == 1


# -- trace_merge graceful degradation ----------------------------------------

def _write_trace(path, events, clock=None):
    doc = {"traceEvents": events, "otherData": {}}
    if clock is not None:
        doc["otherData"]["clock"] = clock
    path.write_text(json.dumps(doc))
    return str(path)


def test_trace_merge_unanchored_degrades_not_fails(tmp_path):
    tm = _load_script("trace_merge.py")
    tid = "a" * 16
    anchored = _write_trace(
        tmp_path / "router.json",
        [{"name": "submit", "ph": "i", "ts": 100.0, "tid": 1,
          "args": {"trace_id": tid}}],
        clock={"wall_epoch_us": 1000.0})
    orphan = _write_trace(
        tmp_path / "worker.json",
        [{"name": "grant", "ph": "i", "ts": 50.0, "tid": 1,
          "args": {"trace_id": tid}}])
    merged = tm.merge_traces([anchored, orphan])
    meta = merged["otherData"]
    assert meta["merged_wall_epoch_us"] == 1000.0
    assert [m["anchored"] for m in meta["merged_from"]] == [True, False]
    # unanchored file merges with shift 0 — its own timeline
    assert meta["merged_from"][1]["shift_us"] == 0.0
    # grant@50 "precedes" submit@100 but the chain touches the
    # unanchored pid, so the causal check is skipped, not failed
    stats = tm.validate_merged(merged)
    assert stats["unanchored_files"] == 1
    assert stats["causal_checks_skipped"] == 1


def test_trace_merge_strict_rejects_unanchored(tmp_path):
    tm = _load_script("trace_merge.py")
    orphan = _write_trace(
        tmp_path / "worker.json",
        [{"name": "grant", "ph": "i", "ts": 50.0, "tid": 1}])
    with pytest.raises(SystemExit):
        tm.merge_traces([orphan], strict=True)


def test_trace_merge_aligns_anchored_epochs(tmp_path):
    tm = _load_script("trace_merge.py")
    a = _write_trace(tmp_path / "a.json",
                     [{"name": "x", "ph": "i", "ts": 5.0, "tid": 1}],
                     clock={"wall_epoch_us": 1000.0})
    b = _write_trace(tmp_path / "b.json",
                     [{"name": "y", "ph": "i", "ts": 10.0, "tid": 1}],
                     clock={"wall_epoch_us": 3000.0})
    merged = tm.merge_traces([a, b])
    by_name = {ev["name"]: ev for ev in merged["traceEvents"]}
    assert by_name["x"]["ts"] == 5.0
    assert by_name["y"]["ts"] == 2010.0  # 10 + (3000 - 1000)


# -- federator edge cases ----------------------------------------------------

_ZERO_HIST = """# TYPE h histogram
h_bucket{le="0.1"} 0
h_bucket{le="+Inf"} 0
h_sum 0
h_count 0
"""

_LIVE_HIST = """# TYPE h histogram
h_bucket{le="0.1"} 3
h_bucket{le="+Inf"} 5
h_sum 1.5
h_count 5
"""


def test_aggregate_tolerates_all_zero_worker():
    agg = agg_mod.aggregate([("w0", _ZERO_HIST), ("w1", _LIVE_HIST)])
    assert agg["fleet"]["h_count"][()] == 5.0
    assert agg["fleet"]["h_bucket"][(("le", "+Inf"),)] == 5.0


def test_aggregate_rejects_mismatched_bucket_bounds():
    other = _LIVE_HIST.replace('le="0.1"', 'le="0.2"')
    with pytest.raises(ValueError, match="mismatched bucket bounds"):
        agg_mod.aggregate([("w0", _ZERO_HIST), ("w1", other)])


def test_counter_resets_flags_restarted_replica():
    prev = agg_mod.aggregate([("w0", "# TYPE c counter\nc 10\n")])
    cur = agg_mod.aggregate([("w0", "# TYPE c counter\nc 2\n")])
    assert agg_mod.counter_resets(prev, cur) == [("w0", "c", (), 10.0,
                                                  2.0)]
    assert agg_mod.counter_resets(prev, prev) == []
