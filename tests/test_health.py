"""Fleet fault tolerance: detector, recovery, leases, idempotent retries.

Host-only stub tier (no jax compiles — tier-1 budget): the
HEALTHY→SUSPECT→DEAD state machine under an injected clock (no sleeps),
the suspect-grace no-flap property, exactly-once recovery conservation
across a killed replica, GRANT-lease expiry reclaiming the decode slot,
idempotent BEGIN retry (rid-keyed dedup never double-reserves), the
structured drain-timeout diagnostics, and the detach/attach elastic
membership primitives. The oracle-exact 2-process/real-model chaos arms
live in ``benchmarks/chaos_bench.py --smoke`` (qa.sh + ci.yml chaos
tier) with a ``slow``-marked pytest wrapper here.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from uccl_tpu import obs
from uccl_tpu.serving import (
    DEAD, HEALTHY, SUSPECT, FailureDetector, RequestState, Router,
    ServingEngine, abandon_engine,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _ChunkStub:
    """Chunk-aware stub backend (tests/test_router.py shape): prefill
    emits 100, the i-th decode step emits i."""

    def __init__(self, n_slots=2, max_seq=64):
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.n_decodes = 0

    def prefill(self, tokens, lens, mask, start=None):
        return np.full(self.n_slots, 100, np.int32)

    def decode(self, tokens, active):
        self.n_decodes += 1
        return np.full(self.n_slots, self.n_decodes, np.int32)

    def export_slot_kv(self, slot, lo, hi):
        z = np.zeros((1, hi - lo, 1, 1), np.float32)
        return z, z

    def import_slot_kv(self, slot, k_rows, v_rows, *, length):
        pass

    def copy_slot_prefix(self, dst, src, n):
        pass


class _StubKV(_ChunkStub):
    """_ChunkStub plus the model dims the disagg wire format needs — the
    full BEGIN/GRANT/FINAL control plane runs over loopback endpoints in
    milliseconds (tests/test_trace_fleet.py idiom)."""

    class _Cfg:
        n_layers = 1
        n_kv_heads = 1
        head_dim = 2

    cfg = _Cfg()

    def __init__(self, n_slots=2, max_seq=32):
        super().__init__(n_slots=n_slots, max_seq=max_seq)

    def export_slot_kv(self, slot, lo, hi):
        z = np.zeros((1, hi - lo, 1, 2), np.float32)
        return z, z


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestFailureDetector:
    def test_window_validation(self):
        with pytest.raises(ValueError, match="suspect_after_s"):
            FailureDetector(suspect_after_s=0)
        with pytest.raises(ValueError, match="grace window"):
            FailureDetector(suspect_after_s=1.0, dead_after_s=1.0)

    def test_transitions_and_telemetry(self):
        clk = _Clock()
        det = FailureDetector(suspect_after_s=0.5, dead_after_s=1.5,
                              clock=clk)
        det.register("a")
        assert det.state("a") == HEALTHY and det.is_routable("a")
        clk.t = 0.6
        assert det.tick() == [("a", SUSPECT)]
        assert det.state("a") == SUSPECT and not det.is_routable("a")
        assert obs.gauge("fleet_peer_state").get(peer="a") == 1
        clk.t = 1.6
        assert det.tick() == [("a", DEAD)]
        assert obs.gauge("fleet_peer_state").get(peer="a") == 2
        # DEAD is terminal per registration: a late heartbeat must not
        # resurrect state the fleet already recovered
        det.heartbeat("a")
        assert det.state("a") == DEAD
        assert det.tick() == []
        # explicit resurrection: re-register
        det.register("a")
        assert det.state("a") == HEALTHY

    def test_suspect_grace_no_flap(self):
        """A peer that resumes heartbeating inside the grace window
        returns to HEALTHY — no DEAD fire, no recovery churn."""
        clk = _Clock()
        det = FailureDetector(suspect_after_s=0.5, dead_after_s=1.5,
                              clock=clk)
        det.register("a")
        h0 = obs.counter("fleet_heartbeats_total").get(peer="a")
        clk.t = 0.7
        assert det.tick() == [("a", SUSPECT)]
        det.heartbeat("a")  # inside the grace window
        assert det.state("a") == HEALTHY
        assert obs.counter("fleet_heartbeats_total").get(
            peer="a") == h0 + 1
        clk.t = 1.1  # 0.4s after the hb: inside suspect window again
        assert det.tick() == []
        assert det.state("a") == HEALTHY, "no flap"
        clk.t = 3.0  # now genuinely silent past dead_after
        fired = det.tick()
        assert ("a", DEAD) in fired

    def test_probe_is_the_inprocess_heartbeat(self):
        clk = _Clock()
        det = FailureDetector(suspect_after_s=0.5, dead_after_s=1.5,
                              clock=clk)
        alive = [True]
        det.register("e", probe=lambda: alive[0])
        clk.t = 10.0  # probes True: age never accumulates
        assert det.tick() == []
        assert det.state("e") == HEALTHY
        alive[0] = False
        clk.t = 10.6
        assert det.tick() == [("e", SUSPECT)]
        alive[0] = True  # probe recovers inside the grace window
        clk.t = 10.7
        det.tick()
        assert det.state("e") == HEALTHY
        alive[0] = False
        clk.t = 12.3
        assert ("e", DEAD) in det.tick()

    def test_raising_probe_is_dead(self):
        clk = _Clock()
        det = FailureDetector(suspect_after_s=0.1, dead_after_s=0.2,
                              clock=clk)

        def boom():
            raise RuntimeError("gone")

        det.register("e", probe=boom)
        clk.t = 0.5
        assert ("e", DEAD) in det.tick()


class TestRouterRecovery:
    def _router(self, n=3, n_slots=2, **kw):
        engines = [ServingEngine(_ChunkStub(n_slots=n_slots),
                                 prefill_chunk=4, **kw)
                   for _ in range(n)]
        r = Router(engines)
        clk = _Clock()
        r.enable_health(suspect_after_s=0.05, dead_after_s=0.1,
                        clock=clk)
        return r, engines, clk

    def test_exactly_once_conservation_across_a_kill(self):
        r, engines, clk = self._router()
        reqs = [r.submit(list(range(8)), max_new_tokens=4)
                for _ in range(6)]
        assert all(q is not None for q in reqs)
        r.step()  # work lands in slots on every replica
        victim = 0
        assert engines[victim].has_work()
        n_stranded = (engines[victim].sched.qsize
                      + len(engines[victim]._by_slot))
        c0 = [obs.counter("serving_recovered_total").get(outcome=o)
              for o in ("resubmitted", "restarted", "lost")]
        engines[victim].kill()
        clk.t = 0.2  # past dead_after: next step recovers
        done = r.drain()
        deltas = [obs.counter("serving_recovered_total").get(outcome=o)
                  - b for o, b in
                  zip(("resubmitted", "restarted", "lost"), c0)]
        assert sum(deltas) == n_stranded == len(r.recoveries)
        assert deltas[2] == 0, "2 healthy survivors: nothing lost"
        # exactly-once: every accepted trace completes exactly once
        traces = [q.trace_id for q in done]
        assert len(traces) == len(set(traces)) == 6
        assert set(traces) == {q.trace_id for q in reqs}
        snap = r.snapshot()
        assert snap["lost"] == n_stranded  # the dead replica's copies
        assert snap["submitted"] == (
            snap["completed"] + snap["active"] + snap["queued"]
            + snap["rejected"] + snap["expired"] + snap["lost"]
        )
        assert r.leaked() == 0
        assert snap["dead_replicas"] == 1
        r.close()

    def test_double_dead_fire_recovers_once(self):
        r, engines, clk = self._router(n=2)
        r.submit([1, 2, 3], max_new_tokens=4)
        r.step()
        engines_with_work = [e for e in engines if e.has_work()]
        victim = engines.index(engines_with_work[0])
        engines[victim].kill()
        clk.t = 0.2
        r.step()
        n = len(r.recoveries)
        assert n >= 1
        r._recover(victim)  # a duplicate fire must be a no-op
        assert len(r.recoveries) == n
        r.drain()
        assert r.leaked() == 0
        r.close()

    def test_suspect_excluded_but_not_recovered(self):
        """SUSPECT = routing exclusion only: the grace window must not
        trigger recovery, and a heartbeat restores routability."""
        r, engines, clk = self._router(n=2)
        det = r.detector
        # make replica 0 probe-less so silence (not the probe) drives it
        det._peers["0"].probe = None
        clk.t = 0.07  # past suspect, inside dead
        r.step()
        assert det.state("0") == SUSPECT
        assert not r._routable(0) and r._routable(1)
        req = r.submit([1, 2], max_new_tokens=2)
        assert any(q is req
                   for q in engines[1].sched.queued_requests())
        assert not r.recoveries, "grace window must not recover"
        det.heartbeat("0")
        assert r._routable(0)
        r.drain()
        r.close()

    def test_cascading_failure_recovers_the_same_trace_again(self):
        """A survivor that took recovered work can die too: the trace is
        legitimately recovered AGAIN (a new incarnation under the same
        context) — never silently dropped, conservation intact."""
        r, engines, clk = self._router(n=3)
        req = r.submit(list(range(8)), max_new_tokens=4)
        # first death: whoever holds the request
        holder = next(i for i, e in enumerate(engines)
                      if any(q is req for q in e.sched.queued_requests()))
        engines[holder].kill()
        clk.t = 0.2
        r.step()
        assert len(r.recoveries) == 1
        # the survivor that took it dies too, before finishing
        taker = next(i for i, e in enumerate(engines)
                     if not e.dead and e.has_work())
        engines[taker].kill()
        clk.t = 0.4
        done = r.drain()
        recovered_traces = [x["trace_id"] for x in r.recoveries]
        assert recovered_traces.count(req.trace_id) == 2
        assert [q.trace_id for q in done] == [req.trace_id]
        snap = r.snapshot()
        assert snap["submitted"] == (
            snap["completed"] + snap["active"] + snap["queued"]
            + snap["rejected"] + snap["expired"] + snap["lost"]
        )
        assert snap["lost"] == 2  # one dead copy per incarnation
        assert r.leaked() == 0
        r.close()

    def test_lost_when_no_survivor_has_room(self):
        r, engines, clk = self._router(n=2, n_slots=1)
        a = r.submit(list(range(8)), max_new_tokens=8)
        b = r.submit(list(range(8)), max_new_tokens=8)
        r.step()
        # saturate the survivor's queue so recovery cannot place work
        victim = 0
        survivor = 1
        engines[survivor].sched.max_queue = engines[survivor].sched.qsize
        engines[victim].kill()
        clk.t = 0.2
        c0 = obs.counter("serving_recovered_total").get(outcome="lost")
        done = r.drain()
        lost = obs.counter("serving_recovered_total").get(
            outcome="lost") - c0
        assert lost >= 1
        snap = r.snapshot()
        assert snap["lost"] >= 1
        assert snap["submitted"] == (
            snap["completed"] + snap["active"] + snap["queued"]
            + snap["rejected"] + snap["expired"] + snap["lost"]
        )
        assert r.leaked() == 0
        # the lost request object is terminally marked
        lost_reqs = [q for q in (a, b)
                     if q.state is RequestState.LOST]
        assert len(lost_reqs) == lost
        assert all(q.finish_reason == "replica_dead" for q in lost_reqs)
        assert all(q.is_done() for q in lost_reqs)
        _ = done
        r.close()

    def test_abandon_engine_counts_all_lost(self):
        eng = ServingEngine(_ChunkStub(), prefill_chunk=4)
        r1 = eng.submit([1, 2, 3], max_new_tokens=4)
        eng.step()
        r2 = eng.submit([1, 2, 3], max_new_tokens=4)
        eng.kill()
        c0 = obs.counter("serving_recovered_total").get(outcome="lost")
        gone = abandon_engine(eng)
        assert {q.rid for q in gone} == {r1.rid, r2.rid}
        assert obs.counter("serving_recovered_total").get(
            outcome="lost") == c0 + 2
        snap = eng.snapshot()
        assert snap["lost"] == 2
        assert snap["submitted"] == (
            snap["completed"] + snap["active"] + snap["queued"]
            + snap["rejected"] + snap["expired"] + snap["lost"]
        )
        assert eng.pool.leaked() == 0

    def test_killed_engine_step_raises(self):
        eng = ServingEngine(_ChunkStub(), prefill_chunk=4)
        eng.kill()
        with pytest.raises(RuntimeError, match="dead"):
            eng.step()


class TestElasticMembership:
    def test_detach_drains_then_removes(self):
        engines = [ServingEngine(_ChunkStub(), prefill_chunk=4)
                   for _ in range(2)]
        r = Router(engines)
        reqs = [r.submit([1, 2, 3], max_new_tokens=3) for _ in range(4)]
        d0 = obs.counter("serving_router_detached_total").get()
        finished = r.detach(0)
        assert obs.counter("serving_router_detached_total").get() == d0 + 1
        assert len(r.replicas) == 1
        # the detached replica's work FINISHED (drained, not dropped)
        done = finished + r.drain()
        assert {q.rid for q in done} == {q.rid for q in reqs}
        assert all(q.state is RequestState.FINISHED for q in reqs)
        assert r.leaked() == 0
        with pytest.raises(ValueError, match="last replica"):
            r.detach(0)
        r.close()

    def test_detach_hands_parked_donors_back(self):
        from uccl_tpu.serving import PrefixCache

        engines = [
            ServingEngine(_ChunkStub(), prefill_chunk=4,
                          prefix_cache=PrefixCache(chunk=4)),
            ServingEngine(_ChunkStub(), prefill_chunk=4),
        ]
        r = Router(engines)
        r.submit(list(range(8)), max_new_tokens=2)
        r.submit(list(range(8)), max_new_tokens=2)
        r.drain()
        parked = engines[0].pool.n_parked
        assert parked >= 1, "retire should park a donor"
        r.detach(0)
        assert engines[0].pool.n_parked == 0
        assert engines[0].pool.n_free == engines[0].pool.n_slots
        r.close()

    def test_attach_is_routable_with_stable_ids(self):
        engines = [ServingEngine(_ChunkStub(), prefill_chunk=4)
                   for _ in range(2)]
        r = Router(engines)
        r.enable_health(suspect_after_s=10, dead_after_s=20)
        spare = ServingEngine(_ChunkStub(), prefill_chunk=4)
        pid = r.attach(spare)
        assert pid == 2 and len(r.replicas) == 3
        assert r.detector.state(str(pid)) == HEALTHY
        # load the originals so the spare wins the next route
        for e in engines:
            e.submit(list(range(8)), max_new_tokens=8)
        req = r.submit([1, 2], max_new_tokens=2)
        assert any(q is req for q in spare.sched.queued_requests())
        r.drain()
        assert r.leaked() == 0
        r.close()


class TestDisaggLease:
    def _pair(self, **kw):
        from uccl_tpu.serving.disagg import make_local_pair

        pe = ServingEngine(_StubKV(), prefill_chunk=4)
        de = ServingEngine(_StubKV())
        pw, dw = make_local_pair(pe, de, **kw)
        return pw, dw

    def test_lease_expiry_reclaims_slot(self):
        pw, dw = self._pair(grant_lease_s=0.15, ctrl_retry_s=30.0)
        try:
            pw.submit(np.arange(6, dtype=np.int32), max_new_tokens=3)
            deadline = time.monotonic() + 10
            while not dw._granted:  # BEGIN -> GRANT; engine never steps
                dw.poll()
                assert time.monotonic() < deadline
            assert dw.engine.pool.n_free == dw.engine.pool.n_slots - 1
            c0 = obs.counter("disagg_leases_expired_total").get(
                reason="timeout")
            time.sleep(0.2)  # the prefill worker "dies": no FINAL ever
            dw.poll()
            assert not dw._granted
            assert dw.engine.pool.n_free == dw.engine.pool.n_slots
            assert dw.engine.pool.leaked() == 0
            assert obs.counter("disagg_leases_expired_total").get(
                reason="timeout") == c0 + 1
        finally:
            pw.ep.close()
            dw.ep.close()

    def test_peer_dead_expires_lease(self):
        det = FailureDetector(suspect_after_s=0.05, dead_after_s=0.1)
        pw, dw = self._pair(grant_lease_s=60.0, detector=det,
                            ctrl_retry_s=30.0)
        try:
            pw.submit(np.arange(6, dtype=np.int32), max_new_tokens=3)
            deadline = time.monotonic() + 10
            while not dw._granted:
                dw.poll()
                assert time.monotonic() < deadline
            c0 = obs.counter("disagg_leases_expired_total").get(
                reason="peer_dead")
            time.sleep(0.15)  # no heartbeats: the conn goes DEAD
            dw.poll()
            assert not dw._granted
            assert dw.engine.pool.leaked() == 0
            assert obs.counter("disagg_leases_expired_total").get(
                reason="peer_dead") == c0 + 1
        finally:
            pw.ep.close()
            dw.ep.close()

    def test_live_peer_timeout_quarantines_until_final(self):
        """A lease timing out while the peer is provably ALIVE (still
        heartbeating) must NOT free the slot — its stream may still be
        one-sided-writing into the mirror rows. The slot is quarantined
        (expiry counted) and freed only when the stream terminates: the
        late FINAL is dropped as stale, never adopted."""
        det = FailureDetector(suspect_after_s=60, dead_after_s=120)
        pw, dw = self._pair(grant_lease_s=0.1, detector=det,
                            ctrl_retry_s=30.0, heartbeat_s=0.01)
        try:
            pw.submit(np.arange(6, dtype=np.int32), max_new_tokens=3)
            deadline = time.monotonic() + 10
            while not dw._granted:
                pw.pump()  # heartbeats flow; the engine never steps
                dw.poll()
                assert time.monotonic() < deadline
            c0 = obs.counter("disagg_leases_expired_total").get(
                reason="timeout")
            s0 = obs.counter("disagg_stale_finals_total").get()
            time.sleep(0.15)  # past the lease, peer still heartbeating
            pw.pump()
            dw.poll()
            st = next(iter(dw._granted.values()))
            assert st.get("expired"), "lease should be quarantined"
            assert obs.counter("disagg_leases_expired_total").get(
                reason="timeout") == c0 + 1
            assert dw.engine.pool.n_free == dw.engine.pool.n_slots - 1, \
                "quarantined slot must stay reserved (no mid-write reuse)"
            # the stalled stream finally finishes: FINAL arrives, is
            # dropped as stale, and ONLY THEN is the slot freed
            done = []
            deadline = time.monotonic() + 30
            while dw._granted:
                pw.step()
                done.extend(dw.step())
                assert time.monotonic() < deadline
            assert not done, "a lapsed lease's request must not adopt"
            assert dw.engine.pool.n_free == dw.engine.pool.n_slots
            assert dw.engine.pool.leaked() == 0
            assert obs.counter("disagg_stale_finals_total").get() \
                == s0 + 1
        finally:
            pw.ep.close()
            dw.ep.close()

    def test_begin_retry_unwedges_a_reclaimed_lease(self):
        """All GRANTs lost for a whole lease: after reclaim, the still-
        retrying BEGIN (which proves nothing was ever shipped) must open
        a FRESH stream, not be dropped forever."""
        from uccl_tpu.serving.disagg import set_ctrl_drop

        pw, dw = self._pair(grant_lease_s=0.08, ctrl_retry_s=0.02)
        try:
            # BEGIN #1 gets through, then a TOTAL control blackout: the
            # GRANT and every retried BEGIN vanish (retries would
            # otherwise renew the lease — contact is renewal), so the
            # never-delivered grant's lease reclaims at timeout
            req = pw.submit(np.arange(6, dtype=np.int32),
                            max_new_tokens=3)
            set_ctrl_drop(1.0, seed=11)
            deadline = time.monotonic() + 10
            while not dw._expired_leases:
                pw.pump()
                dw.poll()
                time.sleep(0.005)
                assert time.monotonic() < deadline
            set_ctrl_drop(0.0)
            done = []
            deadline = time.monotonic() + 30
            while len(done) < 1:
                pw.step()
                done.extend(dw.step())
                assert time.monotonic() < deadline
            assert done[0].n_generated == 3
            assert dw.engine.pool.leaked() == 0
            _ = req
        finally:
            set_ctrl_drop(0.0)
            pw.ep.close()
            dw.ep.close()

    def test_idempotent_begin_never_double_reserves(self):
        from uccl_tpu.serving.disagg import _send_msg

        pw, dw = self._pair(ctrl_retry_s=30.0)
        try:
            msg = {"t": "begin", "rid": 7, "prompt": [1, 2, 3],
                   "max_new_tokens": 2, "eos_id": None,
                   "priority": "interactive", "t_submit": time.time(),
                   "trace": None}
            _send_msg(pw.ep, pw.conn, msg)
            deadline = time.monotonic() + 10
            while not dw._granted:
                dw.poll()
                assert time.monotonic() < deadline
            free = dw.engine.pool.n_free
            slot = next(iter(dw._granted.values()))["slot"]
            g0 = obs.counter("disagg_ctrl_retries_total").get(msg="grant")
            _send_msg(pw.ep, pw.conn, msg)  # retried BEGIN (lost GRANT)
            deadline = time.monotonic() + 10
            while obs.counter("disagg_ctrl_retries_total").get(
                    msg="grant") != g0 + 1:
                dw.poll()
                assert time.monotonic() < deadline
            assert dw.engine.pool.n_free == free, "double-reserved!"
            assert len(dw._granted) == 1
            assert next(iter(dw._granted.values()))["slot"] == slot
        finally:
            pw.ep.close()
            dw.ep.close()

    def test_begin_retry_converges_after_total_ctrl_loss(self):
        from uccl_tpu.serving.disagg import set_ctrl_drop

        pw, dw = self._pair(ctrl_retry_s=0.02)
        try:
            set_ctrl_drop(1.0, seed=3)  # the first BEGIN vanishes
            req = pw.submit(np.arange(6, dtype=np.int32),
                            max_new_tokens=3)
            assert req is not None
            dw.poll()
            assert not dw._granted and not dw._pending
            set_ctrl_drop(0.0)  # plane heals: the retry must converge
            done = []
            deadline = time.monotonic() + 30
            while len(done) < 1:
                pw.step()
                done.extend(dw.step())
                assert time.monotonic() < deadline
            assert done[0].n_generated == 3
        finally:
            set_ctrl_drop(0.0)
            pw.ep.close()
            dw.ep.close()

    def test_drain_timeout_names_outstanding(self):
        from uccl_tpu.serving.disagg import set_ctrl_drop

        pw, dw = self._pair(ctrl_retry_s=30.0)
        try:
            set_ctrl_drop(1.0, seed=5)  # BEGIN never reaches decode
            req = pw.submit(np.arange(6, dtype=np.int32),
                            max_new_tokens=3)
            d0 = obs.counter("disagg_drain_timeouts_total").get(
                role="prefill")
            with pytest.raises(TimeoutError) as ei:
                pw.drain(timeout_s=0.05)
            assert f"rid=[{req.rid}]" in str(ei.value)
            assert "ungranted" in str(ei.value)
            assert obs.counter("disagg_drain_timeouts_total").get(
                role="prefill") == d0 + 1
        finally:
            set_ctrl_drop(0.0)
            pw.ep.close()
            dw.ep.close()


@pytest.mark.slow
class TestChaosSmoke:
    def test_chaos_bench_smoke_and_validator(self, tmp_path):
        """The full real-model chaos proof (router kill + disagg lease
        arms, oracle-exact, counter-audited) as CI runs it."""
        metrics = tmp_path / "chaos.prom"
        bench = tmp_path / "chaos.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        subprocess.run(
            [sys.executable, os.path.join(_REPO, "benchmarks",
                                          "chaos_bench.py"),
             "--smoke", "--metrics-out", str(metrics),
             "--json-out", str(bench)],
            check=True, env=env, cwd=_REPO, timeout=600,
        )
        subprocess.run(
            [sys.executable, os.path.join(_REPO, "scripts",
                                          "check_obs.py"),
             "--chaos", str(metrics), str(bench)],
            check=True, cwd=_REPO, timeout=60,
        )
