"""python -m uccl_tpu.train: the unified trainer entry.

Contract under test: an interrupted run (checkpoint at step k, restart
with --resume) replays the exact trajectory of an uninterrupted run —
the synthetic data stream is a function of the step index and the state
trees are checkpoint-transparent (tests/test_checkpoint.py), so final
losses must agree bit-for-bit at print precision.
"""

import json
import os
import re
import subprocess
import sys

import pytest

pytest.importorskip("orbax.checkpoint")

from uccl_tpu.utils import jaxcompat

# The trainer subprocess differentiates shard_mapped programs from
# outside the shard_map; the legacy (0.4.x) experimental shard_map vjp
# raises a _SpecError on rank-0 residuals there (fixed in modern
# jax.shard_map) — same gate as test_flagship/test_checkpoint.
_needs_modern_vjp = pytest.mark.skipif(
    not jaxcompat.MODERN_SHARD_MAP,
    reason="legacy shard_map vjp mishandles rank-0 residuals",
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_COMMON = [
    "--devices", "8", "--mesh", "dp=2,cp=2,tp=2", "--batch", "4",
    "--seq", "32", "--log-every", "0",
]


def _run(extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "uccl_tpu.train"] + _COMMON + extra,
        capture_output=True, text=True, timeout=560, env=env, cwd=_REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    return summary, r.stdout


@_needs_modern_vjp
def test_resume_matches_uninterrupted(tmp_path):
    straight, _ = _run(["--steps", "6"])
    ck = str(tmp_path / "ck")
    first, out1 = _run(
        ["--steps", "3", "--ckpt-dir", ck, "--ckpt-every", "3"]
    )
    assert "checkpointed step 3" in out1
    resumed, out2 = _run(["--steps", "6", "--ckpt-dir", ck, "--resume"])
    assert re.search(r"resumed from .*step_3", out2)
    assert resumed["steps"] == 3  # only ran 4..6
    assert resumed["final_loss"] == straight["final_loss"]


def test_mesh_size_mismatch_fails_cleanly(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "uccl_tpu.train", "--devices", "8",
         "--mesh", "dp=3", "--steps", "1"],
        capture_output=True, text=True, timeout=120, env=env, cwd=_REPO,
    )
    assert r.returncode != 0
    assert "mesh size 3 != device count 8" in r.stderr


@_needs_modern_vjp
def test_joins_launcher_session(tmp_path):
    """UCCL_TPU_COORD et al (set by scripts/launch.py) make the trainer
    join the multi-host session before touching devices."""
    port = _free_port_pair()
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        UCCL_TPU_COORD=f"127.0.0.1:{port}", UCCL_TPU_RANK="0",
        UCCL_TPU_WORLD="1", UCCL_TPU_INIT_JAX="0",
    )
    r = subprocess.run(
        [sys.executable, "-m", "uccl_tpu.train", "--devices", "8",
         "--mesh", "dp=2,cp=2,tp=2", "--batch", "4", "--seq", "32",
         "--steps", "1", "--log-every", "1"],
        capture_output=True, text=True, timeout=420, env=env, cwd=_REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "joined session rank 0/1" in r.stdout
    assert "step     1 loss" in r.stdout


def _free_port_pair():
    """The store binds coordinator-port + 1, so reserve the PAIR."""
    import socket

    for _ in range(50):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            cand = s.getsockname()[1]
        try:
            with socket.socket() as s2:
                s2.bind(("127.0.0.1", cand + 1))
            return cand
        except OSError:
            continue
    raise RuntimeError("no free port pair")


@_needs_modern_vjp
def test_two_process_training_matches_single(tmp_path):
    """TRUE multi-controller training: two processes under jax.distributed,
    each owning 4 virtual devices of the same 8-device global mesh, must
    replay the single-controller trajectory exactly — the data is global
    and deterministic, so the sharding substrate is the only variable.
    The 2-process run also checkpoints (collective orbax save), and a
    SINGLE-controller resume from that checkpoint — a different process
    topology — must land on the same trajectory (elastic restart)."""
    single, _ = _run(["--steps", "4"])

    ck = str(tmp_path / "ck2p")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "launch.py"),
         "--nproc", "2", "--coordinator", f"127.0.0.1:{_free_port_pair()}",
         os.path.join(_REPO, "uccl_tpu", "train.py"),
         "--devices", "4", "--mesh", "dp=2,cp=2,tp=2",
         "--batch", "4", "--seq", "32", "--steps", "4", "--log-every", "0",
         "--ckpt-dir", ck, "--ckpt-every", "3"],
        capture_output=True, text=True, timeout=560, env=env, cwd=_REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    line = [l for l in r.stdout.splitlines() if '"processes": 2' in l]
    assert line, r.stdout
    multi = json.loads(line[-1].split("] ", 1)[-1])
    assert multi["final_loss"] == single["final_loss"]

    # cross-topology elastic resume: 1 controller picks up the 2-process
    # checkpoint (step 3, saved mid-run) and finishes the trajectory.
    # Tolerance, not equality: restored state carries committed shardings
    # (e.g. adam's count replicated) where a fresh run holds uncommitted
    # scalars, so XLA compiles an equivalent-but-not-identical program —
    # observed drift is 1 ulp at the 6th decimal.
    resumed, out = _run(["--steps", "4", "--ckpt-dir", ck, "--resume"])
    assert re.search(r"resumed from .*step_3", out), out
    assert abs(resumed["final_loss"] - single["final_loss"]) < 1e-4


@_needs_modern_vjp
def test_data_corpus_mode(tmp_path):
    """--data: batches are next-token windows from a memmapped token file,
    deterministic per step (resume-consistent) — loss should drop fast on
    a trivially periodic corpus."""
    import numpy as np

    path = str(tmp_path / "corpus.npy")
    np.save(path, (np.arange(5000) % 200).astype(np.int32))
    out1, _ = _run(["--steps", "3", "--data", path])
    out2, _ = _run(["--steps", "3", "--data", path])
    assert out1["final_loss"] == out2["final_loss"]  # deterministic stream

    bad = str(tmp_path / "bad.npy")
    np.save(bad, np.zeros((4, 4), np.int32))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "uccl_tpu.train"] + _COMMON
        + ["--steps", "1", "--data", bad],
        capture_output=True, text=True, timeout=120, env=env, cwd=_REPO,
    )
    assert r.returncode != 0 and "1-D integer token array" in r.stderr
