"""Elastic hybrid device/host buffers: placement, spill, and decode parity.

The reference backs EP windows with host memory when device memory is short
(lite-ep ElasticBuffer, csrc/elastic/buffer.hpp; README.md:35 "elastic
hybrid GPU/CPU buffers"); the TPU analog offloads via XLA memory kinds.
These tests run on the CPU backend, which exposes the same
device/pinned_host memory spaces as TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from uccl_tpu.ep.elastic import ElasticBuffer, ElasticKVCache
from uccl_tpu.models import dense
from uccl_tpu.models.inference import (
    KVCache,
    decode_step,
    decode_step_elastic,
    prefill,
)


def _f32(shape, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)


class TestElasticBuffer:
    def test_budget_placement_and_spill(self):
        buf = ElasticBuffer(hbm_budget_bytes=3 * 1024)
        a = _f32((16, 16))  # 1 KiB
        b = _f32((16, 16), 1)
        c = _f32((16, 16), 2)
        d = _f32((16, 16), 3)
        buf.put("a", a)
        buf.put("b", b)
        buf.put("c", c)
        buf.put("d", d)  # over budget -> host
        assert buf.placement("a") == "device"
        assert buf.placement("c") == "device"
        if buf.has_host:
            assert buf.placement("d") == "host"
            assert buf.device_bytes <= 3 * 1024
            assert buf.host_bytes == 1024
        got = buf.get("d")
        # "device" on TPU/GPU-shaped backends; CPU backends may name their
        # only (device-resident) space differently, e.g. "unpinned_host"
        dev_kind = jax.devices()[0].default_memory().kind
        assert got.sharding.memory_kind in (None, "device", dev_kind)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(d))
        # the durable placement is unchanged by a read
        if buf.has_host:
            assert buf.placement("d") == "host"

    def test_pin_overrides_budget(self):
        buf = ElasticBuffer(hbm_budget_bytes=0)
        buf.put("w", _f32((8, 8)), pin=True)
        assert buf.placement("w") == "device"

    def test_offload_and_delete(self):
        buf = ElasticBuffer(hbm_budget_bytes=1 << 20)
        buf.put("x", _f32((8, 8)))
        assert buf.placement("x") == "device"
        buf.offload("x")
        if buf.has_host:
            assert buf.placement("x") == "host"
            assert buf._store["x"].sharding.memory_kind == "pinned_host"
        np.testing.assert_array_equal(
            np.asarray(buf.get("x")), np.asarray(_f32((8, 8)))
        )
        buf.delete("x")
        assert "x" not in buf.names()


class TestElasticKVCache:
    def _mk(self, **kw):
        base = dict(
            n_layers=2, batch=2, n_kv_heads=2, head_dim=4,
            block_tokens=8, hot_blocks=2,
        )
        base.update(kw)
        return ElasticKVCache(**base)

    def test_append_and_gather_roundtrip(self):
        ekv = self._mk()
        k = _f32((2, 2, 37, 2, 4), 0)  # 4 full blocks + partial 5
        v = _f32((2, 2, 37, 2, 4), 1)
        ekv.append_tokens(k, v)
        assert ekv.length == 37
        # 4 sealed blocks, hot keeps 2, so 2 went cold
        assert ekv.cold_blocks == 2
        kk, vv, length = ekv.kv()
        assert length == 37
        np.testing.assert_allclose(
            np.asarray(kk[:, :, :37]), np.asarray(k), rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(vv[:, :, :37]), np.asarray(v), rtol=1e-6
        )

    def test_cold_blocks_live_in_host_memory(self):
        ekv = self._mk()
        if not ekv.has_host:
            pytest.skip("backend has no host memory space")
        k = _f32((2, 2, 40, 2, 4))
        ekv.append_tokens(k, k)
        assert ekv.cold_blocks == 3
        for ck, cv in ekv._cold:
            assert ck.sharding.memory_kind == "pinned_host"
            assert cv.sharding.memory_kind == "pinned_host"
        for hk, hv in ekv._hot:
            assert hk.sharding.memory_kind == "device"

    def test_device_committed_is_bounded(self):
        """Growing the context grows host bytes, not committed HBM."""
        ekv = self._mk(hot_blocks=2)
        committed = []
        for _ in range(6):
            ekv.append_tokens(_f32((2, 2, 8, 2, 4)), _f32((2, 2, 8, 2, 4)))
            committed.append(ekv.device_committed_bytes())
        if ekv.has_host:
            # after the hot ring fills, committed HBM stops growing
            assert committed[-1] == committed[2]
        assert ekv.cold_blocks == 4


class TestElasticDecodeParity:
    def test_matches_dense_decode(self, rng):
        """Greedy decode over the elastic cache (with forced cold spills)
        must produce exactly the dense decode_step logits."""
        cfg = dense.DenseConfig(
            vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=8,
            ffn=64,
        )
        params = dense.init_params(jax.random.PRNGKey(0), cfg)
        prompt = jnp.asarray(
            rng.integers(0, cfg.vocab, (2, 21)), jnp.int32
        )
        max_seq = 64
        logits_d, cache = prefill(params, prompt, cfg, max_seq)
        # block_tokens=8, hot_blocks=1: the 21-token prompt spills cold
        ekv = ElasticKVCache.from_cache(
            cache, block_tokens=8, hot_blocks=1
        )
        assert ekv.cold_blocks >= 1
        logits_e = logits_d
        tok_d = tok_e = None
        for step in range(6):
            tok_d = jnp.argmax(logits_d, axis=-1).astype(jnp.int32)
            tok_e = jnp.argmax(logits_e, axis=-1).astype(jnp.int32)
            np.testing.assert_array_equal(np.asarray(tok_d), np.asarray(tok_e))
            logits_d, cache = decode_step(params, tok_d, cache, cfg)
            logits_e = decode_step_elastic(params, tok_e, ekv, cfg)
            np.testing.assert_allclose(
                np.asarray(logits_e), np.asarray(logits_d), rtol=2e-4, atol=2e-5
            )
        assert ekv.length == 21 + 6


@pytest.mark.slow
class TestWarmReplicaAdmission:
    def test_admit_warm_replica_attaches_and_serves(self, rng):
        """The elastic up-scale twin of Router.detach: a warm spare built
        off the prototype (shared compile caches) joins the live router
        mid-run, is routable, serves bit-exact, and can be detached
        again with nothing leaked."""
        from uccl_tpu.ep.elastic import admit_warm_replica
        from uccl_tpu.models.inference import generate
        from uccl_tpu.serving import Router, ServingEngine
        from uccl_tpu.serving.engine import DenseBackend

        cfg = dense.DenseConfig(vocab=64, dim=32, n_layers=1, n_heads=2,
                                n_kv_heads=1, head_dim=16, ffn=64)
        params = dense.init_params(jax.random.PRNGKey(0), cfg)
        proto = DenseBackend(params, cfg, n_slots=2, max_seq=16)
        eng0 = ServingEngine(proto, prefill_chunk=4)
        r = Router([eng0])
        r.enable_health(suspect_after_s=5, dead_after_s=10)
        spare = admit_warm_replica(
            r, proto, engine_kw={"prefill_chunk": 4})
        assert len(r.replicas) == 2
        assert spare.backend._fns is proto._fns, "compiles must share"
        # load the original so the spare wins the route
        eng0.submit(list(range(8)), max_new_tokens=4)
        prompt = np.arange(1, 7, dtype=np.int32)
        req = r.submit(prompt, max_new_tokens=4)
        assert any(q is req for q in spare.sched.queued_requests())
        done = r.drain()
        want = np.asarray(generate(params, jnp.asarray(prompt)[None],
                                   cfg, max_new_tokens=4, max_seq=16))[0]
        got = [q for q in done if q is req][0]
        np.testing.assert_array_equal(np.asarray(got.out_tokens), want)
        finished = r.detach(1)
        assert len(r.replicas) == 1 and not finished
        assert r.leaked() == 0 and spare.pool.leaked() == 0
        r.close()
