"""Aux subsystems: stats registry/thread and tracing scopes."""

import time

import pytest

from uccl_tpu.utils import stats, tracing


class TestStats:
    def test_registry_snapshot(self):
        reg = stats.StatsRegistry()
        reg.register("engine", lambda: {"tx": 10.0, "rx": 5.0})
        reg.register("broken", lambda: 1 / 0)
        snap = reg.snapshot()
        assert snap["engine"] == {"tx": 10.0, "rx": 5.0}
        assert "error" in snap["broken"]
        reg.unregister("engine")
        assert "engine" not in reg.snapshot()

    def test_thread_lifecycle(self):
        reg = stats.StatsRegistry()
        calls = []
        reg.register("c", lambda: calls.append(1) or {"n": len(calls)})
        stats._interval.set(0.05)
        try:
            t = stats.StatsThread(reg)
            t.start()
            t.start()  # idempotent
            time.sleep(0.3)
            t.stop()
        finally:
            stats._interval.reset()
        assert len(calls) >= 2

    def test_quiet(self):
        reg = stats.StatsRegistry()
        calls = []
        reg.register("c", lambda: calls.append(1) or {})
        stats._quiet.set(True)
        stats._interval.set(0.05)
        try:
            t = stats.StatsThread(reg)
            t.start()
            time.sleep(0.2)
            t.stop()
        finally:
            stats._quiet.reset()
            stats._interval.reset()
        assert calls == []


class TestTracing:
    def test_timed_scope(self):
        tracing.reset_scopes()
        for _ in range(5):
            with tracing.timed_scope("unit_test_scope"):
                time.sleep(0.001)
        s = tracing.scope_stats("unit_test_scope")
        assert s is not None and s["count"] == 5 and s["p50_us"] >= 500

    def test_unknown_scope(self):
        assert tracing.scope_stats("nope") is None

    def test_annotate_runs(self):
        import jax.numpy as jnp

        with tracing.annotate("region"):
            x = jnp.ones((4,)).sum()
        assert float(x) == 4.0
