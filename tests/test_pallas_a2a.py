"""Device-initiated EP all-to-all (``wire="pallas"``): interpret-mode tests.

Oracle discipline: the Pallas remote-DMA kernel implements the exact tiled
``lax.all_to_all`` contract, so every path that selects it — the raw kernel,
the sorted dispatch/combine (ep/ops.py), the LL dense-chunk row format
(ep/ll.py) and the Buffer verbs — is checked bit-/tolerance-exact against
the lax-wire lowering of the same program, at worlds 4 and 8 plus odd
worlds (5, and 3 for the raw kernel), over f32/bf16 payloads and the
fp8+scales wire format.

All meshes here are single-axis, which keeps every test runnable under BOTH
TPU interpreters: the faithful one (pltpu.InterpretParams — remote DMAs,
semaphores and the credit flow simulated) and the legacy discharge one
(jax 0.4.x — remote DMA data movement only; the kernel statically elides
the barrier/credit traffic there, see uccl_tpu.collective.dma)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from uccl_tpu.ep import Buffer, pallas_a2a
from uccl_tpu.ep import ll as ep_ll
from uccl_tpu.ep import ops as ep_ops
from uccl_tpu.utils.jaxcompat import shard_map

WORLDS = (4, 8, 5)  # the acceptance grid: powers of two plus one odd world

# The heavy end-to-end path suites (sorted/LL roundtrips, chunked layers,
# Buffer verb parity) keep world 4 in tier-1 and push the wide (8) and odd
# (5) arms to `slow` — the 870s tier-1 cap is the ONLY consumer of that
# filter: qa.sh and ci.yml run this file UNFILTERED in their dedicated
# pallas smoke tier (and exclude it from their full-suite pytest), so the
# 8/5 coverage is unchanged there. Kernel-level suites stay on the full
# grid — their arms are cheap. (The heavy-worlds-slow convention from the
# PR 6 quant-wire suites.)
WORLDS_T1 = (4,
             pytest.param(8, marks=pytest.mark.slow),
             pytest.param(5, marks=pytest.mark.slow))
ODD_T1 = (4, pytest.param(5, marks=pytest.mark.slow))


def _mesh(devices, n):
    return Mesh(np.array(devices[:n]), ("ep",))


def _run(mesh, fn, *args, out_specs=None):
    in_specs = tuple(P("ep") for _ in args)
    out_specs = P("ep") if out_specs is None else out_specs
    return jax.jit(
        shard_map(fn, mesh, in_specs, out_specs, check_vma=False)
    )(*args)


class TestKernel:
    """The raw [W, ...] exchange against lax.all_to_all (tiled contract)."""

    @pytest.mark.parametrize("n", [2, 3, 4, 5, 8])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_matches_lax(self, devices, rng, n, dtype):
        mesh = _mesh(devices, n)
        # 5x9 trailing block: chunk sizes are NOT an 8x128 multiple, so the
        # per-chunk padding path is always exercised
        x = jnp.asarray(rng.normal(size=(n, n, 5, 9)), dtype)

        got = np.asarray(_run(
            mesh, lambda v: pallas_a2a.all_to_all(v[0], "ep")[None], x
        ))
        want = np.asarray(_run(
            mesh,
            lambda v: jax.lax.all_to_all(v[0], "ep", 0, 0, tiled=True)[None],
            x,
        ))
        np.testing.assert_array_equal(got, want)

    def test_world1_identity(self, devices, rng):
        mesh = _mesh(devices, 1)
        x = jnp.asarray(rng.normal(size=(1, 1, 4, 4)), jnp.float32)
        got = np.asarray(_run(
            mesh, lambda v: pallas_a2a.all_to_all(v[0], "ep")[None], x
        ))
        np.testing.assert_array_equal(got, np.asarray(x))

    def test_leading_dim_mismatch_raises(self, devices):
        mesh = _mesh(devices, 4)
        x = jnp.zeros((4, 3, 8), jnp.float32)
        with pytest.raises(ValueError, match="leading dim"):
            _run(mesh, lambda v: pallas_a2a.all_to_all(v[0], "ep")[None], x)

    def test_budget_fallback_matches(self, devices, rng, monkeypatch):
        """Over-budget payloads take the lax lowering — same numbers."""
        from uccl_tpu.collective import dma

        monkeypatch.setenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES", "64")
        dma.MAX_VMEM_BYTES.reset()
        try:
            mesh = _mesh(devices, 4)
            x = jnp.asarray(rng.normal(size=(4, 4, 8, 16)), jnp.float32)
            got = np.asarray(_run(
                mesh, lambda v: pallas_a2a.all_to_all(v[0], "ep")[None], x
            ))
            want = np.asarray(_run(
                mesh,
                lambda v: jax.lax.all_to_all(
                    v[0], "ep", 0, 0, tiled=True
                )[None],
                x,
            ))
            np.testing.assert_array_equal(got, want)
        finally:
            monkeypatch.delenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES")
            dma.MAX_VMEM_BYTES.reset()


def _case(rng, w, t, h, e, k):
    x = rng.standard_normal((w, t, h)).astype(np.float32)
    idx = rng.integers(0, e, (w, t, k)).astype(np.int32)
    wts = rng.uniform(0.1, 1.0, (w, t, k)).astype(np.float32)
    return x, idx, wts


class TestSortedPath:
    """dispatch_sorted/combine_sorted on the pallas wire vs the lax wire
    (which test_ep.py pins to the dense-mask oracle)."""

    @pytest.mark.parametrize("n", WORLDS_T1)
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_dispatch_combine_roundtrip(self, devices, rng, n, dtype):
        mesh = _mesh(devices, n)
        t, h, e, k = 12, 24, 2 * n, 2
        cap = max(1, int(1.25 * t * k / e))
        x, idx, wts = _case(rng, n, t, h, e, k)
        x = jnp.asarray(x, dtype)

        def path(wire):
            def f(xv, iv, wv):
                tfs, slot, _ = ep_ops.sorted_from_topk(iv[0], e, cap)
                recv = ep_ops.dispatch_sorted(
                    xv[0], tfs, e, cap, "ep", wire=wire
                )
                out = ep_ops.combine_sorted(
                    recv * 2.0, slot, wv[0], "ep", wire=wire
                )
                return recv[None], out[None]

            return _run(
                mesh, f, x, jnp.asarray(idx), jnp.asarray(wts),
                out_specs=(P("ep"), P("ep")),
            )

        recv_p, out_p = map(np.asarray, path("pallas"))
        recv_l, out_l = map(np.asarray, path("lax"))
        np.testing.assert_array_equal(recv_p, recv_l)
        np.testing.assert_array_equal(out_p, out_l)

    @pytest.mark.parametrize("n", ODD_T1)
    def test_fp8_wire_format(self, devices, rng, n):
        """fp8+scales payloads: quantized values and scales both ride the
        pallas wire; dequantized results must equal the lax-wire path
        bit-for-bit (identical quantization, identical transport)."""
        mesh = _mesh(devices, n)
        t, h, e, k = 8, 32, 2 * n, 2
        cap = max(1, int(1.25 * t * k / e))
        x, idx, _ = _case(rng, n, t, h, e, k)

        def f(wire):
            def g(xv, iv):
                tfs, _, _ = ep_ops.sorted_from_topk(iv[0], e, cap)
                return ep_ops.dispatch_sorted(
                    xv[0], tfs, e, cap, "ep", wire_fp8=True, wire=wire
                )[None]

            return np.asarray(_run(mesh, g, jnp.asarray(x),
                                   jnp.asarray(idx)))

        np.testing.assert_array_equal(f("pallas"), f("lax"))


class TestLLPath:
    """The LL dense-chunk row format on the pallas wire vs wire="dense"
    (same layout, XLA transport) — recv buffers, counts, and the combine
    round trip."""

    @pytest.mark.parametrize("n", WORLDS_T1)
    @pytest.mark.parametrize("fp8", [False, True])
    def test_ll_roundtrip(self, devices, rng, n, fp8):
        mesh = _mesh(devices, n)
        t, h, e, k = 8, 32, 2 * n, 2
        x, idx, wts = _case(rng, n, t, h, e, k)

        def path(wire):
            def f(xv, iv, wv):
                r = ep_ll.ll_dispatch(
                    xv[0], iv[0], wv[0], e, "ep", wire=wire, wire_fp8=fp8
                )
                out = ep_ll.ll_combine(
                    r.recv_x * 2.0, r.state, "ep", wire_fp8=fp8
                )
                return r.recv_x[None], r.group_sizes[None], out[None]

            return _run(
                mesh, f, jnp.asarray(x), jnp.asarray(idx), jnp.asarray(wts),
                out_specs=(P("ep"), P("ep"), P("ep")),
            )

        rp, gp, op = map(np.asarray, path("pallas"))
        rd, gd, od = map(np.asarray, path("dense"))
        np.testing.assert_array_equal(rp, rd)
        np.testing.assert_array_equal(gp, gd)
        np.testing.assert_allclose(op, od, rtol=1e-6, atol=1e-6)

    def test_bf16_rows(self, devices, rng):
        mesh = _mesh(devices, 4)
        t, h, e, k = 8, 32, 8, 2
        x, idx, wts = _case(rng, 4, t, h, e, k)
        x16 = jnp.asarray(x, jnp.bfloat16)

        def path(wire):
            def f(xv, iv, wv):
                r = ep_ll.ll_dispatch(
                    xv[0], iv[0], wv[0], e, "ep", wire=wire, wire_fp8=False
                )
                return r.recv_x[None]

            return np.asarray(_run(
                mesh, f, x16, jnp.asarray(idx), jnp.asarray(wts)
            ).astype(jnp.float32))

        np.testing.assert_array_equal(path("pallas"), path("dense"))


class TestBuffer:
    """Buffer(wire="pallas"): the DeepEP surface selects the kernel
    transparently for BOTH row formats, records it in the handles, and
    matches the default wire bit-for-bit."""

    @pytest.mark.parametrize("n", WORLDS)
    def test_normal_verbs_match_default_wire(self, devices, rng, n):
        mesh = _mesh(devices, n)
        e, k, t, h = 2 * n, 2, 12, 24
        x, idx, wts = _case(rng, n, t, h, e, k)
        outs = {}
        for wire in ("auto", "pallas"):
            buf = Buffer(mesh, "ep", num_experts=e, num_selected=k,
                         wire=wire)
            xx, ii, ww = map(buf.device_put, (x, idx, wts))
            recv, handle = buf.dispatch(xx, ii, ww)
            out = buf.combine(recv * 2.0, handle)
            outs[wire] = (np.asarray(recv), np.asarray(out), handle.wire)
        assert outs["auto"][2] == "lax" and outs["pallas"][2] == "pallas"
        np.testing.assert_array_equal(outs["auto"][0], outs["pallas"][0])
        np.testing.assert_array_equal(outs["auto"][1], outs["pallas"][1])

    @pytest.mark.parametrize("n", ODD_T1)
    @pytest.mark.parametrize("fp8", [False, True])
    def test_ll_verbs_match_default_wire(self, devices, rng, n, fp8):
        mesh = _mesh(devices, n)
        e, k, t, h = 2 * n, 2, 8, 32
        x, idx, wts = _case(rng, n, t, h, e, k)
        outs = {}
        for wire in ("auto", "pallas"):
            buf = Buffer(mesh, "ep", num_experts=e, num_selected=k,
                         wire=wire)
            xx, ii, ww = map(buf.device_put, (x, idx, wts))
            recv, counts, handle = buf.low_latency_dispatch(
                xx, ii, None, ww, wire_fp8=fp8
            )
            out = buf.low_latency_combine(recv * 2.0, handle)
            outs[wire] = (
                np.asarray(recv), np.asarray(counts), np.asarray(out),
                handle.wire,
            )
        assert outs["pallas"][3] == "pallas"
        assert outs["auto"][3] in ("ragged", "dense")
        np.testing.assert_array_equal(outs["auto"][0], outs["pallas"][0])
        np.testing.assert_array_equal(outs["auto"][1], outs["pallas"][1])
        np.testing.assert_allclose(
            outs["auto"][2], outs["pallas"][2], rtol=1e-6, atol=1e-6
        )

    def test_config_wire_selects_pallas(self, devices, rng):
        """A Config(wire="pallas") on a default-wire Buffer flips one verb
        pair onto the kernel (explicit config wins over the Buffer)."""
        from uccl_tpu.ep import Config

        mesh = _mesh(devices, 4)
        e, k, t, h = 8, 2, 8, 16
        x, idx, wts = _case(rng, 4, t, h, e, k)
        buf = Buffer(mesh, "ep", num_experts=e, num_selected=k)
        xx, ii, ww = map(buf.device_put, (x, idx, wts))
        cfg = Config(wire="pallas", wire_fp8=False)
        recv, handle = buf.dispatch(xx, ii, ww, config=cfg)
        assert handle.wire == "pallas"
        recv_d, handle_d = buf.dispatch(xx, ii, ww)
        assert handle_d.wire == "lax"
        np.testing.assert_array_equal(np.asarray(recv), np.asarray(recv_d))

    def test_bad_wire_rejected(self, devices):
        mesh = _mesh(devices, 4)
        with pytest.raises(ValueError, match="unknown wire"):
            Buffer(mesh, "ep", num_experts=8, wire="tcp")


class TestChunkedKernel:
    """n_chunks > 1: the chunk axis splits into double-buffered per-chunk
    kernels on rotated collective ids — numerics pinned to the unchunked
    lax contract at every world, including the slot-axis pad path (5 is not
    divisible by 2 or 4)."""

    @pytest.mark.parametrize("n", WORLDS)
    @pytest.mark.parametrize("chunks", [1, 2, 4])
    def test_matches_lax(self, devices, rng, n, chunks):
        mesh = _mesh(devices, n)
        x = jnp.asarray(rng.normal(size=(n, n, 5, 9)), jnp.float32)
        got = np.asarray(_run(
            mesh,
            lambda v: pallas_a2a.all_to_all(
                v[0], "ep", n_chunks=chunks, chunk_axis=2
            )[None],
            x,
        ))
        want = np.asarray(_run(
            mesh,
            lambda v: jax.lax.all_to_all(v[0], "ep", 0, 0, tiled=True)[None],
            x,
        ))
        np.testing.assert_array_equal(got, want)

    def test_bf16_chunked(self, devices, rng):
        mesh = _mesh(devices, 4)
        x = jnp.asarray(rng.normal(size=(4, 4, 6, 9)), jnp.bfloat16)
        got = np.asarray(_run(
            mesh,
            lambda v: pallas_a2a.all_to_all(
                v[0], "ep", n_chunks=2, chunk_axis=2
            )[None],
            x,
        ).astype(jnp.float32))
        want = np.asarray(_run(
            mesh,
            lambda v: jax.lax.all_to_all(v[0], "ep", 0, 0, tiled=True)[None],
            x,
        ).astype(jnp.float32))
        np.testing.assert_array_equal(got, want)

    def test_chunk_axis0_rejected(self, devices):
        mesh = _mesh(devices, 4)
        x = jnp.zeros((4, 4, 8), jnp.float32)
        with pytest.raises(ValueError, match="member axis"):
            _run(
                mesh,
                lambda v: pallas_a2a.all_to_all(
                    v[0], "ep", n_chunks=2, chunk_axis=0
                )[None],
                x,
            )


class TestChunkBudget:
    """The 2x double-buffer footprint gate (dma.chunk_budget) and its clean
    fallback chain: chunked → unchunked pallas → lax, all bit-identical."""

    def test_double_buffer_charge(self, monkeypatch):
        """Compiled mode charges TWO resident chunk pairs; the interpreter
        gates per-buffer (deadlock ceiling), so the same chunk passes."""
        from uccl_tpu.collective import dma

        world, itemsize = 4, 4
        pair = 2 * world * dma.CHUNK_QUANTUM * itemsize
        monkeypatch.setenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES", str(pair + 1))
        monkeypatch.setenv("UCCL_TPU_PALLAS_CCL_INTERP_MAX_BYTES", str(pair))
        dma.MAX_VMEM_BYTES.reset()
        dma.MAX_INTERP_BYTES.reset()
        try:
            assert not dma.chunk_budget(world, 1, itemsize, "t",
                                        interpret=False)
            assert dma.check_budget(pair, "t", False)  # 1 pair fits
            assert dma.chunk_budget(world, 1, itemsize, "t", interpret=True)
        finally:
            monkeypatch.delenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES")
            monkeypatch.delenv("UCCL_TPU_PALLAS_CCL_INTERP_MAX_BYTES")
            dma.MAX_VMEM_BYTES.reset()
            dma.MAX_INTERP_BYTES.reset()

    def test_over_budget_chunked_falls_back_clean(self, devices, rng,
                                                  monkeypatch):
        from uccl_tpu.collective import dma

        rejected = []
        orig = dma.chunk_budget

        def spy(world, elems, itemsize, what, interpret=None):
            ok = orig(world, elems, itemsize, what, interpret)
            if not ok:
                rejected.append(what)
            return ok

        monkeypatch.setattr(dma, "chunk_budget", spy)
        monkeypatch.setenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES", "64")
        dma.MAX_VMEM_BYTES.reset()
        try:
            mesh = _mesh(devices, 4)
            x = jnp.asarray(rng.normal(size=(4, 4, 8, 16)), jnp.float32)
            got = np.asarray(_run(
                mesh,
                lambda v: pallas_a2a.all_to_all(
                    v[0], "ep", n_chunks=2, chunk_axis=2
                )[None],
                x,
            ))
            want = np.asarray(_run(
                mesh,
                lambda v: jax.lax.all_to_all(
                    v[0], "ep", 0, 0, tiled=True
                )[None],
                x,
            ))
            np.testing.assert_array_equal(got, want)
            assert "ep_all_to_all_chunked" in rejected
        finally:
            monkeypatch.delenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES")
            dma.MAX_VMEM_BYTES.reset()


class TestChunkedSortedPath:
    """dispatch_sorted/combine_sorted with n_chunks ∈ {1, 2, 4} pinned to
    the unchunked lax wire — the SlotPlan form, both sides consuming the
    one permutation."""

    @pytest.mark.parametrize("n", WORLDS_T1)
    @pytest.mark.parametrize("chunks", [1, 2, 4])
    def test_roundtrip_matches_lax(self, devices, rng, n, chunks):
        mesh = _mesh(devices, n)
        t, h, e, k = 12, 24, 2 * n, 2
        cap = max(1, int(1.25 * t * k / e))
        x, idx, wts = _case(rng, n, t, h, e, k)

        def path(wire, nc):
            def f(xv, iv, wv):
                plan = ep_ops.plan_slots(iv[0], e, cap)
                recv = ep_ops.dispatch_sorted(
                    xv[0], plan, e, cap, "ep", wire=wire, n_chunks=nc
                )
                out = ep_ops.combine_sorted(
                    recv * 2.0, plan, wv[0], "ep", wire=wire, n_chunks=nc
                )
                return recv[None], out[None]

            return _run(
                mesh, f, jnp.asarray(x), jnp.asarray(idx), jnp.asarray(wts),
                out_specs=(P("ep"), P("ep")),
            )

        recv_p, out_p = map(np.asarray, path("pallas", chunks))
        recv_l, out_l = map(np.asarray, path("lax", 1))
        np.testing.assert_array_equal(recv_p, recv_l)
        np.testing.assert_array_equal(out_p, out_l)

    @pytest.mark.parametrize("n", ODD_T1)
    def test_fp8_wire_chunked(self, devices, rng, n):
        """fp8 groups ride the hidden axis; chunking the capacity axis must
        leave quantization bit-identical to the unchunked lax wire."""
        mesh = _mesh(devices, n)
        t, h, e, k = 8, 32, 2 * n, 2
        cap = max(1, int(1.25 * t * k / e))
        x, idx, _ = _case(rng, n, t, h, e, k)

        def f(wire, nc):
            def g(xv, iv):
                plan = ep_ops.plan_slots(iv[0], e, cap)
                return ep_ops.dispatch_sorted(
                    xv[0], plan, e, cap, "ep", wire_fp8=True, wire=wire,
                    n_chunks=nc,
                )[None]

            return np.asarray(_run(mesh, g, jnp.asarray(x),
                                   jnp.asarray(idx)))

        np.testing.assert_array_equal(f("pallas", 2), f("lax", 1))


class TestChunkedLLPath:
    """The LL dense-chunk format with a chunk-pipelined pallas wire vs
    wire="dense" — the fp8+scales format stays first-class in the
    pipeline."""

    @pytest.mark.parametrize("n", ODD_T1)
    @pytest.mark.parametrize("fp8", [False, True])
    def test_ll_roundtrip_chunked(self, devices, rng, n, fp8):
        mesh = _mesh(devices, n)
        t, h, e, k = 8, 32, 2 * n, 2
        x, idx, wts = _case(rng, n, t, h, e, k)

        def path(wire, nc):
            def f(xv, iv, wv):
                r = ep_ll.ll_dispatch(
                    xv[0], iv[0], wv[0], e, "ep", wire=wire, wire_fp8=fp8,
                    n_chunks=nc,
                )
                out = ep_ll.ll_combine(
                    r.recv_x * 2.0, r.state, "ep", wire_fp8=fp8
                )
                return r.recv_x[None], r.group_sizes[None], out[None]

            return _run(
                mesh, f, jnp.asarray(x), jnp.asarray(idx), jnp.asarray(wts),
                out_specs=(P("ep"), P("ep"), P("ep")),
            )

        rp, gp, op = map(np.asarray, path("pallas", 2))
        rd, gd, od = map(np.asarray, path("dense", 1))
        np.testing.assert_array_equal(rp, rd)
        np.testing.assert_array_equal(gp, gd)
        np.testing.assert_allclose(op, od, rtol=1e-6, atol=1e-6)


class TestChunkedMoELayer:
    """The tentpole: the chunk-pipelined MoE step (dispatch chunk c+1 /
    expert GEMM c / combine c-1 as independent per-chunk dependency chains)
    is numerically identical to the strictly phased lax layer — slot rows
    are independent through the SwiGLU GEMMs and the wire is
    position-preserving, so chunking changes the schedule, never the
    math."""

    @pytest.mark.parametrize("n", WORLDS_T1)
    @pytest.mark.parametrize("chunks", [2, 4])
    def test_pipelined_layer_matches_lax(self, devices, rng, n, chunks):
        mesh = _mesh(devices, n)
        t, h, f_, e, k = 12, 16, 32, 2 * n, 2
        x = rng.standard_normal((n, t, h)).astype(np.float32)
        logits = rng.standard_normal((n, t, e)).astype(np.float32)
        wg = (rng.standard_normal((e, h, f_)) * 0.2).astype(np.float32)
        wu = (rng.standard_normal((e, h, f_)) * 0.2).astype(np.float32)
        wd = (rng.standard_normal((e, f_, h)) * 0.2).astype(np.float32)

        def layer(wire, nc):
            def f(xv, lv, g, u, d):
                out, aux, z = ep_ops.moe_ffn(
                    xv[0], lv[0], g, u, d, "ep", num_selected=k,
                    capacity_factor=1.25, impl="sort", wire=wire,
                    n_chunks=nc,
                )
                return out[None], aux[None], z[None]

            return _run(
                mesh, f, *map(jnp.asarray, (x, logits, wg, wu, wd)),
                out_specs=(P("ep"), P("ep"), P("ep")),
            )

        out_p, aux_p, z_p = map(np.asarray, layer("pallas", chunks))
        out_l, aux_l, z_l = map(np.asarray, layer("lax", 1))
        np.testing.assert_allclose(out_p, out_l, rtol=1e-6, atol=1e-6)
        np.testing.assert_array_equal(aux_p, aux_l)
        np.testing.assert_array_equal(z_p, z_l)

    def test_auto_chunks(self, devices, rng):
        """n_chunks=0 resolves itself (2 when the budget allows) and stays
        numerically identical to the phased layer."""
        mesh = _mesh(devices, 4)
        t, h, f_, e, k = 8, 16, 16, 8, 2
        x = rng.standard_normal((4, t, h)).astype(np.float32)
        logits = rng.standard_normal((4, t, e)).astype(np.float32)
        wg = (rng.standard_normal((e, h, f_)) * 0.2).astype(np.float32)
        wu = (rng.standard_normal((e, h, f_)) * 0.2).astype(np.float32)
        wd = (rng.standard_normal((e, f_, h)) * 0.2).astype(np.float32)

        def layer(wire, nc):
            def f(xv, lv, g, u, d):
                out, _, _ = ep_ops.moe_ffn(
                    xv[0], lv[0], g, u, d, "ep", num_selected=k,
                    capacity_factor=1.25, impl="sort", wire=wire,
                    n_chunks=nc,
                )
                return out[None]

            return np.asarray(_run(
                mesh, f, *map(jnp.asarray, (x, logits, wg, wu, wd))
            ))

        np.testing.assert_allclose(
            layer("pallas", 0), layer("lax", 1), rtol=1e-6, atol=1e-6
        )


class TestChunkedBuffer:
    """Buffer(wire="pallas", n_chunks=N): the DeepEP surface records the
    depth in its handles and stays bit-identical to the default wire."""

    @pytest.mark.parametrize("chunks", [2, 0])
    def test_normal_verbs_chunked(self, devices, rng, chunks):
        mesh = _mesh(devices, 4)
        e, k, t, h = 8, 2, 12, 24
        x, idx, wts = _case(rng, 4, t, h, e, k)
        ref = Buffer(mesh, "ep", num_experts=e, num_selected=k)
        buf = Buffer(mesh, "ep", num_experts=e, num_selected=k,
                     wire="pallas", n_chunks=chunks)
        xx, ii, ww = map(buf.device_put, (x, idx, wts))
        recv_r, handle_r = ref.dispatch(xx, ii, ww)
        out_r = ref.combine(recv_r * 2.0, handle_r)
        recv, handle = buf.dispatch(xx, ii, ww)
        out = buf.combine(recv * 2.0, handle)
        assert handle.wire == "pallas" and handle.n_chunks == 2
        assert handle_r.n_chunks == 1
        np.testing.assert_array_equal(np.asarray(recv), np.asarray(recv_r))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out_r))

    def test_ll_verbs_chunked(self, devices, rng):
        mesh = _mesh(devices, 4)
        e, k, t, h = 8, 2, 8, 32
        x, idx, wts = _case(rng, 4, t, h, e, k)
        ref = Buffer(mesh, "ep", num_experts=e, num_selected=k)
        buf = Buffer(mesh, "ep", num_experts=e, num_selected=k,
                     wire="pallas", n_chunks=2)
        xx, ii, ww = map(buf.device_put, (x, idx, wts))
        recv_r, counts_r, handle_r = ref.low_latency_dispatch(
            xx, ii, None, ww, wire_fp8=True
        )
        out_r = ref.low_latency_combine(recv_r * 2.0, handle_r)
        recv, counts, handle = buf.low_latency_dispatch(
            xx, ii, None, ww, wire_fp8=True
        )
        out = buf.low_latency_combine(recv * 2.0, handle)
        assert handle.wire == "pallas" and handle.n_chunks == 2
        np.testing.assert_array_equal(np.asarray(recv), np.asarray(recv_r))
        np.testing.assert_array_equal(np.asarray(counts),
                                      np.asarray(counts_r))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(out_r), rtol=1e-6, atol=1e-6
        )

    def test_config_chunks_selects(self, devices, rng):
        """Config(n_chunks=2) fills the knob a default Buffer left unset."""
        from uccl_tpu.ep import Config

        mesh = _mesh(devices, 4)
        e, k, t, h = 8, 2, 8, 16
        x, idx, wts = _case(rng, 4, t, h, e, k)
        buf = Buffer(mesh, "ep", num_experts=e, num_selected=k)
        xx, ii, ww = map(buf.device_put, (x, idx, wts))
        cfg = Config(wire="pallas", wire_fp8=False, n_chunks=2)
        recv, handle = buf.dispatch(xx, ii, ww, config=cfg)
        assert handle.wire == "pallas" and handle.n_chunks == 2
        recv_d, handle_d = buf.dispatch(xx, ii, ww)
        assert handle_d.wire == "lax" and handle_d.n_chunks == 1
        np.testing.assert_array_equal(np.asarray(recv), np.asarray(recv_d))
