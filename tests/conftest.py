"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

The reference validates on real NICs/GPUs only (SURVEY.md §4); the idiomatic TPU
answer for CI without a pod is XLA's host-platform device virtualization — every
sharding/collective test here runs on 8 virtual CPU devices and is
topology-faithful to an 8-chip slice.
"""

import os

# Force CPU even when the ambient environment points JAX at a real TPU (a
# sitecustomize may have pre-registered a TPU PJRT plugin, so the env var alone
# is not enough — override the jax config too): tests must be runnable anywhere
# and need 8 virtual devices.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Version-bridge the jax APIs the codebase targets (jax.shard_map,
# lax.axis_size, ...) BEFORE any test module imports them — on modern jax
# this is a no-op, on 0.4.x containers it installs the polyfills.
import uccl_tpu.utils.jaxcompat  # noqa: E402,F401
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    return make_mesh(MeshConfig(pp=1, dp=2, cp=2, tp=2), devices)


@pytest.fixture(scope="session")
def mesh_dp8(devices):
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    return make_mesh(MeshConfig(dp=8), devices)


@pytest.fixture
def rng():
    return np.random.default_rng(0)
