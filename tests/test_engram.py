"""Engram remote-memory row fetch (reference: lite-ep engram_write/
engram_fetch, tests/elastic/test_engram.py — random global indices must
gather exactly the rows the owning ranks hold)."""

import threading

import numpy as np
import pytest

from uccl_tpu.ep.engram import EngramTable, mesh_fetch
from uccl_tpu.p2p import Endpoint

ENTRIES, HIDDEN = 64, 48


@pytest.fixture
def linked_pair():
    """Two single-process 'hosts', each owning one shard of the table."""
    rng = np.random.default_rng(0)
    shards = [
        np.ascontiguousarray(rng.standard_normal((ENTRIES, HIDDEN))
                             .astype(np.float32))
        for _ in range(2)
    ]
    with Endpoint() as a, Endpoint() as b:
        acc = {}
        t = threading.Thread(target=lambda: acc.setdefault("c", b.accept(10000)))
        t.start()
        conn_ab = a.connect("127.0.0.1", b.port)
        t.join()
        ta = EngramTable(a, shards[0], rank=0, world=2)
        tb = EngramTable(b, shards[1], rank=1, world=2)
        done = {}
        t2 = threading.Thread(target=lambda: done.setdefault(
            "x", tb.link({0: acc["c"]})))
        t2.start()
        ta.link({1: conn_ab})
        t2.join()
        yield ta, tb, np.concatenate(shards, axis=0)


class TestEngram:
    def test_fetch_matches_global_table(self, linked_pair, rng):
        ta, tb, global_table = linked_pair
        idx = rng.integers(0, 2 * ENTRIES, 37)
        np.testing.assert_array_equal(ta.fetch(idx), global_table[idx])
        np.testing.assert_array_equal(tb.fetch(idx), global_table[idx])

    def test_async_hook_overlaps(self, linked_pair, rng):
        ta, _, global_table = linked_pair
        idx = rng.integers(ENTRIES, 2 * ENTRIES, 16)  # all remote rows
        out, wait = ta.fetch_async(idx)
        local_work = float(np.square(np.arange(1000)).sum())  # overlap slot
        got = wait()
        assert got is out and local_work > 0
        np.testing.assert_array_equal(got, global_table[idx])

    def test_local_only_fetch_never_touches_wire(self, linked_pair, rng):
        ta, _, global_table = linked_pair
        before = ta.ep.stats["bytes_tx"]
        idx = rng.integers(0, ENTRIES, 8)  # rank 0 owns all of these
        np.testing.assert_array_equal(ta.fetch(idx), global_table[idx])
        assert ta.ep.stats["bytes_tx"] == before

    def test_out_of_range_rejected(self, linked_pair):
        ta, _, _ = linked_pair
        with pytest.raises(ValueError):
            ta.fetch([2 * ENTRIES])
        with pytest.raises(ValueError):
            ta.fetch([-1])

    def test_duplicate_and_repeated_indices(self, linked_pair, rng):
        ta, _, global_table = linked_pair
        idx = np.array([5, 5, ENTRIES + 3, 5, ENTRIES + 3, 0])
        np.testing.assert_array_equal(ta.fetch(idx), global_table[idx])


class TestMeshFetch:
    def test_matches_numpy_take(self, mesh8, rng):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        table = rng.standard_normal((64, 16)).astype(np.float32)
        sharded = jax.device_put(
            jnp.asarray(table), NamedSharding(mesh8, P(("dp", "cp"), None))
        )
        idx = jnp.asarray(rng.integers(0, 64, 23), jnp.int32)
        out = jax.jit(mesh_fetch)(sharded, idx)
        np.testing.assert_allclose(np.asarray(out), table[np.asarray(idx)])
