"""Torch interop: tensors through the engine, DDP-style grad averaging."""

import threading

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from uccl_tpu.interop import allreduce_gradients, register_tensor, send_tensor
from uccl_tpu.p2p import Endpoint


class TestTensorTransfer:
    def test_one_sided_tensor_write(self):
        with Endpoint() as server, Endpoint() as client:
            conn = client.connect("127.0.0.1", server.port)
            server.accept()
            dst = torch.zeros(1024, dtype=torch.float32)
            mr = register_tensor(server, dst)
            fifo = server.advertise(mr)
            src = torch.randn(1024)
            send_tensor(client, conn, src, fifo)
            assert torch.equal(dst, src)  # landed in-place, zero copies

    def test_non_contiguous_rejected(self):
        with Endpoint() as ep:
            t = torch.randn(8, 8).t()
            with pytest.raises(ValueError):
                register_tensor(ep, t)

    def test_dtype_roundtrip(self):
        with Endpoint() as server, Endpoint() as client:
            conn = client.connect("127.0.0.1", server.port)
            server.accept()
            for dtype in (torch.float16, torch.int64, torch.uint8, torch.bfloat16):
                dst = torch.zeros(64, dtype=dtype)
                fifo = server.advertise(register_tensor(server, dst))
                src = (torch.arange(64) % 7).to(dtype)
                send_tensor(client, conn, src, fifo)
                assert torch.equal(dst, src)


class TestDdpGradients:
    def test_allreduce_gradients_matches_manual_average(self):
        from uccl_tpu.collective.hierarchical import DcnGroup
        from uccl_tpu.p2p.store import StoreClient, StoreServer
        from uccl_tpu.parallel.distributed import Session

        torch.manual_seed(0)
        world = 2
        models = [torch.nn.Linear(8, 4) for _ in range(world)]
        # identical params, different grads
        models[1].load_state_dict(models[0].state_dict())
        data = [torch.randn(16, 8) for _ in range(world)]
        for m, x in zip(models, data):
            m.zero_grad()
            m(x).pow(2).mean().backward()
        want_w = (models[0].weight.grad + models[1].weight.grad) / 2
        want_b = (models[0].bias.grad + models[1].bias.grad) / 2

        server = StoreServer()
        errors = []

        def rank_main(r):
            try:
                sess = Session(
                    rank=r, world=world, store=StoreClient("127.0.0.1", server.port)
                )
                g = DcnGroup(sess, n_paths=2)
                allreduce_gradients(models[r].parameters(), g)
                g.close()
            except Exception as e:
                errors.append(e)

        ts = [threading.Thread(target=rank_main, args=(r,)) for r in range(world)]
        [t.start() for t in ts]
        [t.join(timeout=60) for t in ts]
        server.close()
        assert not errors, errors
        for m in models:
            assert torch.allclose(m.weight.grad, want_w, rtol=1e-5)
            assert torch.allclose(m.bias.grad, want_b, rtol=1e-5)
