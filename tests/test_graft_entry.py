"""The driver contract: entry() compiles single-device; dryrun_multichip(n)
compiles + executes the full training step on the virtual mesh.

Each dryrun runs in its OWN subprocess (the way the driver invokes it).
Stacking dryruns over DIFFERENT device counts in one process aborts inside
XLA:CPU's in-process collective rendezvous — after a 2-device
collective_permute program, a 4-device program dies with
``rendezvous.h:315 Check failed: id < num_threads (4 vs. 4)`` /
``use_count 5 vs. 4`` (a stale participant from the smaller clique). An
upstream XLA:CPU cross-program bug, not a property of the sharded step
being tested, so the test matches the driver's process-per-run contract
instead of stacking programs."""

import os
import subprocess
import sys

import jax
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def repo_on_path():
    sys.path.insert(0, _REPO)
    yield
    sys.path.remove(_REPO)


def test_entry_compiles(devices):
    import __graft_entry__ as g

    fn, args = g.entry()
    compiled = jax.jit(fn).lower(*args).compile()
    out = compiled(*args)
    assert out.shape == (2, 32, 512)


@pytest.mark.parametrize("n", [1, 2, 4, 8])
def test_dryrun_multichip(n):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
    r = subprocess.run(
        [
            sys.executable, "-c",
            "import sys; sys.path.insert(0, %r)\n"
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import __graft_entry__ as g\n"
            "g.dryrun_multichip(%d)" % (_REPO, n),
        ],
        capture_output=True, text=True, timeout=540, env=env, cwd=_REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert f"dryrun_multichip({n})" in r.stdout + r.stderr
