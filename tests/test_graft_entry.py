"""The driver contract: entry() compiles single-device; dryrun_multichip(8)
compiles + executes the full training step on the virtual mesh."""

import sys

import jax
import pytest


@pytest.fixture(scope="module", autouse=True)
def repo_on_path():
    sys.path.insert(0, "/root/repo")
    yield
    sys.path.remove("/root/repo")


def test_entry_compiles(devices):
    import __graft_entry__ as g

    fn, args = g.entry()
    compiled = jax.jit(fn).lower(*args).compile()
    out = compiled(*args)
    assert out.shape == (2, 32, 512)


@pytest.mark.parametrize("n", [1, 2, 4, 8])
def test_dryrun_multichip(n, devices):
    import __graft_entry__ as g

    g.dryrun_multichip(n)
