"""P2P transfer engine over TCP loopback — the analog of the reference's
p2p/tests/test_engine_write.py / test_engine_read.py (multiprocess server/client
with an advertise handshake), plus in-process pairs for the fast paths."""

import multiprocessing as mp
import time

import numpy as np
import pytest

from uccl_tpu.p2p import Endpoint


@pytest.fixture
def pair():
    """Two endpoints connected over loopback in one process."""
    with Endpoint() as server, Endpoint() as client:
        conn_c = client.connect("127.0.0.1", server.port)
        conn_s = server.accept()
        yield server, client, conn_s, conn_c


class TestOneSided:
    def test_write(self, pair, rng):
        server, client, conn_s, conn_c = pair
        dst = np.zeros(1024, np.float32)
        mr = server.reg(dst)
        fifo = server.advertise(mr)
        src = rng.standard_normal(1024).astype(np.float32)
        client.write(conn_c, src, fifo)
        np.testing.assert_array_equal(dst, src)

    def test_read(self, pair, rng):
        server, client, conn_s, conn_c = pair
        src = rng.standard_normal(2048).astype(np.float64)
        mr = server.reg(src)
        fifo = server.advertise(mr)
        dst = np.zeros(2048, np.float64)
        client.read(conn_c, dst, fifo)
        np.testing.assert_array_equal(dst, src)

    def test_write_at_offset(self, pair, rng):
        server, client, conn_s, conn_c = pair
        dst = np.zeros(100, np.uint8)
        mr = server.reg(dst)
        fifo = server.advertise(mr, offset=10, length=50)
        src = np.arange(50, dtype=np.uint8)
        client.write(conn_c, src, fifo)
        np.testing.assert_array_equal(dst[10:60], src)
        assert dst[:10].sum() == 0 and dst[60:].sum() == 0

    def test_large_transfer(self, pair, rng):
        server, client, conn_s, conn_c = pair
        n = 16 << 20  # 16 MB
        dst = np.zeros(n, np.uint8)
        mr = server.reg(dst)
        src = rng.integers(0, 255, n).astype(np.uint8)
        client.write(conn_c, src, server.advertise(mr))
        np.testing.assert_array_equal(dst, src)

    def test_async_and_poll(self, pair, rng):
        server, client, conn_s, conn_c = pair
        dst = np.zeros(4096, np.float32)
        mr = server.reg(dst)
        src = rng.standard_normal(4096).astype(np.float32)
        xid = client.write_async(conn_c, src, server.advertise(mr))
        assert client.wait(xid)
        np.testing.assert_array_equal(dst, src)
        # completions are one-shot (engine.h contract): a consumed id is gone
        with pytest.raises(IOError):
            client.poll_async(xid)

    def test_writev(self, pair, rng):
        server, client, conn_s, conn_c = pair
        dsts = [np.zeros(256, np.float32) for _ in range(4)]
        fifos = [server.advertise(server.reg(d)) for d in dsts]
        srcs = [rng.standard_normal(256).astype(np.float32) for _ in range(4)]
        client.writev(conn_c, srcs, fifos)
        for d, s in zip(dsts, srcs):
            np.testing.assert_array_equal(d, s)


class TestTwoSided:
    def test_send_recv_bytes(self, pair):
        server, client, conn_s, conn_c = pair
        client.send(conn_c, b"hello uccl")
        assert server.recv(conn_s) == b"hello uccl"

    def test_send_recv_ordering(self, pair):
        server, client, conn_s, conn_c = pair
        for i in range(20):
            client.send(conn_c, f"msg{i}".encode())
        for i in range(20):
            assert server.recv(conn_s) == f"msg{i}".encode()

    def test_recv_timeout(self, pair):
        server, client, conn_s, conn_c = pair
        with pytest.raises(TimeoutError):
            server.recv(conn_s, timeout_ms=100)


class TestSafety:
    def test_bad_token_rejected(self, pair, rng):
        """A forged FifoItem (wrong token) must not corrupt memory."""
        server, client, conn_s, conn_c = pair
        dst = np.zeros(64, np.uint8)
        mr = server.reg(dst)
        fifo = bytearray(server.advertise(mr))
        fifo[16] ^= 0xFF  # corrupt the token field
        src = np.ones(64, np.uint8)
        with pytest.raises(IOError):
            client.write(conn_c, src, bytes(fifo))
        assert dst.sum() == 0

    def test_out_of_range_write_rejected(self, pair):
        server, client, conn_s, conn_c = pair
        dst = np.zeros(64, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = np.ones(128, np.uint8)  # larger than advertised
        with pytest.raises(IOError):
            client.write(conn_c, src, fifo)
        assert dst.sum() == 0

    def test_dereg_then_write_fails(self, pair):
        server, client, conn_s, conn_c = pair
        dst = np.zeros(64, np.uint8)
        mr = server.reg(dst)
        fifo = server.advertise(mr)
        server.dereg(mr)
        with pytest.raises(IOError):
            client.write(conn_c, np.ones(64, np.uint8), fifo)

    def test_drop_rate_times_out(self, pair):
        """Fault injection: 100% frame drop -> transfer never completes."""
        server, client, conn_s, conn_c = pair
        dst = np.zeros(64, np.uint8)
        fifo = server.advertise(server.reg(dst))
        client.set_drop_rate(1.0)
        xid = client.write_async(conn_c, np.ones(64, np.uint8), fifo)
        assert not client.wait(xid, timeout_ms=300)
        client.set_drop_rate(0.0)

    def test_stats_counters(self, pair, rng):
        server, client, conn_s, conn_c = pair
        dst = np.zeros(1000, np.uint8)
        fifo = server.advertise(server.reg(dst))
        client.write(conn_c, rng.integers(0, 255, 1000).astype(np.uint8), fifo)
        assert client.stats["bytes_tx"] >= 1000
        assert server.stats["bytes_rx"] >= 1000


class TestLifecycle:
    def test_use_after_close_raises(self):
        ep = Endpoint()
        ep.close()
        with pytest.raises(ValueError):
            _ = ep.port
        ep.close()  # double close is a no-op

    def test_port_in_use_raises(self):
        with Endpoint() as ep:
            with pytest.raises(RuntimeError):
                Endpoint(ep.port)

    def test_large_message_recv_retries(self, pair):
        server, client, conn_s, conn_c = pair
        big = np.arange(2 << 20, dtype=np.uint8)  # 2 MB > default 1 MB buffer
        client.send(conn_c, big)
        got = server.recv(conn_s)  # transparently retries with exact size
        np.testing.assert_array_equal(np.frombuffer(got, np.uint8), big)

    def test_async_temporary_buffer_survives(self, pair, rng):
        """The engine must keep async sources alive until completion (the
        caller may pass a temporary)."""
        server, client, conn_s, conn_c = pair
        dst = np.zeros(1 << 20, np.float32)
        fifo = server.advertise(server.reg(dst))
        src = rng.standard_normal(1 << 20).astype(np.float32)
        xid = client.write_async(conn_c, src + 0.0, fifo)  # temporary!
        import gc

        gc.collect()
        assert client.wait(xid)
        np.testing.assert_array_equal(dst, src)

    def test_concurrent_bidirectional_reads(self, pair, rng):
        """Large reads in both directions at once must not deadlock the
        engines (read responses ride the tx proxy, not the io thread)."""
        server, client, conn_s, conn_c = pair
        n = 8 << 20
        a = rng.integers(0, 255, n).astype(np.uint8)
        b = rng.integers(0, 255, n).astype(np.uint8)
        fifo_a = server.advertise(server.reg(a))
        fifo_b = client.advertise(client.reg(b))
        dst_a = np.zeros(n, np.uint8)
        dst_b = np.zeros(n, np.uint8)
        xc = client.read_async(conn_c, dst_a, fifo_a)
        xs = server.read_async(conn_s, dst_b, fifo_b)
        assert client.wait(xc, timeout_ms=60000)
        assert server.wait(xs, timeout_ms=60000)
        np.testing.assert_array_equal(dst_a, a)
        np.testing.assert_array_equal(dst_b, b)


def _server_proc(port_q, result_q):
    server = Endpoint()
    port_q.put(server.port)
    conn = server.accept(timeout_ms=20000)
    dst = np.zeros(4096, np.float32)
    mr = server.reg(dst)
    server.send(conn, server.advertise(mr))  # fifo travels over the wire OOB
    # wait for the client's completion signal
    assert server.recv(conn, timeout_ms=20000) == b"done"
    result_q.put(dst.copy())
    server.close()


def test_multiprocess_write():
    """Two real processes, advertise handshake over the engine itself —
    the shape of reference p2p/tests/test_engine_write.py:28-75."""
    ctx = mp.get_context("spawn")
    port_q, result_q = ctx.Queue(), ctx.Queue()
    p = ctx.Process(target=_server_proc, args=(port_q, result_q))
    p.start()
    try:
        port = port_q.get(timeout=30)
        client = Endpoint()
        conn = client.connect("127.0.0.1", port)
        fifo = client.recv(conn, timeout_ms=20000)
        src = np.arange(4096, dtype=np.float32)
        client.write(conn, src, fifo)
        client.send(conn, b"done")
        got = result_q.get(timeout=30)
        np.testing.assert_array_equal(got, src)
        client.close()
    finally:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()


class TestNoHeadOfLine:
    """One misbehaving peer must not stall an engine's other connections
    (reference discipline: strictly non-blocking engine run loops,
    transport.cc:443-470; round-1 ADVICE flagged the blocking recv here)."""

    def test_stalled_partial_frame_peer_does_not_block_rx(self, rng):
        import socket
        import struct

        with Endpoint(n_engines=1) as server, Endpoint() as client:
            # Rogue peer: connects raw and sends HALF a frame header, then
            # stalls forever. Under a blocking dispatch loop this wedges the
            # engine (and with it the listener + every other conn).
            rogue = socket.create_connection(("127.0.0.1", server.port))
            rogue.sendall(struct.pack("<IHH", 0x7C71, 1, 0)[:6])  # 6 of 48 bytes
            time.sleep(0.2)  # let the io thread observe the partial header

            # The healthy path must still fully work: accept + one-sided write.
            conn_c = client.connect("127.0.0.1", server.port)
            assert server.accept() >= 0
            dst = np.zeros(1 << 16, np.uint8)
            fifo = server.advertise(server.reg(dst))
            src = rng.integers(0, 255, 1 << 16).astype(np.uint8)
            client.write(conn_c, src, fifo)  # raises on failure/timeout
            np.testing.assert_array_equal(dst, src)
            rogue.close()

    def test_backpressured_peer_does_not_block_tx(self, rng):
        import socket

        with Endpoint(n_engines=1) as server, Endpoint() as client:
            # Rogue peer that never reads: the server's sends to it will fill
            # the kernel socket buffers and hit EAGAIN.
            rogue = socket.create_connection(("127.0.0.1", server.port))
            rogue_conn = server.accept()
            payload = bytes(256 << 10)
            for _ in range(64):  # ~16 MB queued, far beyond socket buffers
                server.send(rogue_conn, payload)
            time.sleep(0.1)

            # A healthy conn served by the SAME single engine must still move
            # one-sided traffic while the rogue conn's queue is backed up.
            conn_c = client.connect("127.0.0.1", server.port)
            conn_s = server.accept()
            dst = np.zeros(1 << 16, np.uint8)
            fifo = client.advertise(client.reg(dst))
            src = rng.integers(0, 255, 1 << 16).astype(np.uint8)
            server.write(conn_s, src, fifo)  # server tx must not be wedged
            np.testing.assert_array_equal(dst, src)
            rogue.close()


class TestVectorized:
    """Descriptor-array transfers (reference: writev/readv + XferDescList,
    p2p/engine.h:308-344, engine_api.cc:448) — one C call, one proxy wake."""

    def test_writev_readv_roundtrip(self, pair, rng):
        server, client, conn_s, conn_c = pair
        dsts = [np.zeros(4096, np.uint8) for _ in range(6)]
        fifos = [server.advertise(server.reg(d)) for d in dsts]
        srcs = [rng.integers(0, 255, 4096).astype(np.uint8) for _ in range(6)]
        client.writev(conn_c, srcs, fifos)
        for d, s in zip(dsts, srcs):
            np.testing.assert_array_equal(d, s)
        # readv the same windows back
        back = [np.zeros(4096, np.uint8) for _ in range(6)]
        client.readv(conn_c, back, fifos)
        for b, s in zip(back, srcs):
            np.testing.assert_array_equal(b, s)

    def test_writev_async_out_of_order_completion(self, pair, rng):
        server, client, conn_s, conn_c = pair
        sizes = [1 << 20, 1024, 256 << 10, 64]
        dsts = [np.zeros(n, np.uint8) for n in sizes]
        fifos = [server.advertise(server.reg(d)) for d in dsts]
        srcs = [rng.integers(0, 255, n).astype(np.uint8) for n in sizes]
        xids = client.writev_async(conn_c, srcs, fifos)
        assert len(set(xids)) == len(sizes)
        for x in xids:
            assert client.wait(x)
        for d, s in zip(dsts, srcs):
            np.testing.assert_array_equal(d, s)

    def test_writev_element_over_window_fails_cleanly(self, pair, rng):
        server, client, conn_s, conn_c = pair
        dst = np.zeros(128, np.uint8)
        fifo = server.advertise(server.reg(dst))
        big = rng.integers(0, 255, 4096).astype(np.uint8)
        ok = rng.integers(0, 255, 128).astype(np.uint8)
        dst2 = np.zeros(128, np.uint8)
        fifo2 = server.advertise(server.reg(dst2))
        xids = client.writev_async(conn_c, [big, ok], [fifo, fifo2])
        assert not client.wait(xids[0])   # over-window element fails
        assert client.wait(xids[1])       # sibling still lands
        np.testing.assert_array_equal(dst2, ok)


class TestJaxStaging:
    """Pipelined HBM<->host<->wire staging (SURVEY §7 hard-part 3): chunked
    send_jax/recv_jax round-trips, and interop with monolithic senders."""

    def _roundtrip(self, pair, x, shape, dtype, *, chunk_bytes=None):
        import threading

        server, client, conn_s, conn_c = pair
        kw = {} if chunk_bytes is None else {"chunk_bytes": chunk_bytes}
        t = threading.Thread(
            target=client.send_jax, args=(conn_c, x), kwargs=kw
        )
        t.start()
        y = server.recv_jax(conn_s, shape, dtype)
        t.join(timeout=60)
        assert not t.is_alive()
        return y

    def test_chunked_roundtrip(self, pair):
        import jax.numpy as jnp

        x = jnp.arange(1 << 16, dtype=jnp.float32).reshape(256, 256)
        y = self._roundtrip(
            pair, x, (256, 256), np.float32, chunk_bytes=64 << 10
        )
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))

    def test_uneven_tail_chunk(self, pair):
        import jax.numpy as jnp

        x = jnp.arange(1000, dtype=jnp.int32)  # 4000 B, 1024-B chunks
        y = self._roundtrip(pair, x, (1000,), np.int32, chunk_bytes=1024)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))

    def test_monolithic_sender_chunked_receiver_interop(self, pair):
        import jax.numpy as jnp

        x = jnp.ones((64, 64), jnp.float32) * 3.5
        # huge chunk_bytes => single-message path on the sender
        y = self._roundtrip(
            pair, x, (64, 64), np.float32, chunk_bytes=1 << 30
        )
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))

    def test_bf16_payload(self, pair):
        import jax.numpy as jnp

        x = jnp.linspace(-4.0, 4.0, 8192, dtype=jnp.bfloat16)
        y = self._roundtrip(
            pair, x, (8192,), jnp.bfloat16, chunk_bytes=4096
        )
        np.testing.assert_array_equal(
            np.asarray(y).view(np.uint8), np.asarray(x).view(np.uint8)
        )

    def test_numpy_input_still_works(self, pair):
        x = np.random.default_rng(0).standard_normal(512).astype(np.float32)
        y = self._roundtrip(pair, x, (512,), np.float32, chunk_bytes=512)
        np.testing.assert_array_equal(np.asarray(y), x)


class TestEngineStats:
    """Hot-loop observability (reference: transport.cc:1797 stats thread +
    util/latency.h): per-engine frame counts, service-latency percentiles,
    queue depths, and the periodic stats thread heartbeat."""

    def test_stats_shape_and_latency_percentiles(self, pair, rng):
        server, client, conn_s, conn_c = pair
        dst = np.zeros(1 << 16, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, 1 << 16).astype(np.uint8)
        for _ in range(20):
            client.write(conn_c, src, fifo)
        s = client.stats
        assert s["bytes_tx"] > 20 * (1 << 16)
        engines = s["engines"]
        assert len(engines) >= 1
        tx_frames = sum(e["tx_frames"] for e in engines)
        assert tx_frames >= 20
        busy = [e for e in engines if e["tx_frames"] > 0]
        for e in busy:
            assert e["tx_p99_us"] >= e["tx_p50_us"] > 0
        r = server.stats
        rx_frames = sum(e["rx_frames"] for e in r["engines"])
        assert rx_frames >= 20
        for e in r["engines"]:
            if e["rx_frames"]:
                assert e["rx_p99_us"] >= e["rx_p50_us"] > 0

    def test_stats_thread_ticks(self, monkeypatch):
        monkeypatch.setenv("UCCL_TPU_ENGINE_STATS_MS", "40")
        with Endpoint() as ep:
            time.sleep(0.5)
            assert ep.stats["stats_ticks"] >= 2


class TestNotifs:
    """NIXL notify pattern (reference p2p/uccl_engine.h:218-226): small
    tagged messages drained non-blocking across all conns — the
    "data has landed" side channel for one-sided transfers."""

    def test_notif_roundtrip_after_write(self, pair, rng):
        server, client, conn_s, conn_c = pair
        assert server.get_notifs() == []  # non-blocking empty drain
        dst = np.zeros(4096, np.uint8)
        fifo = server.advertise(server.reg(dst))
        src = rng.integers(0, 255, 4096).astype(np.uint8)
        client.write(conn_c, src, fifo)
        client.send_notif(conn_c, b"xfer:kv:done")
        for _ in range(100):
            notifs = server.get_notifs()
            if notifs:
                break
            time.sleep(0.02)
        assert notifs == [(conn_s, b"xfer:kv:done")]
        np.testing.assert_array_equal(dst, src)

    def test_notifs_do_not_consume_recv_queue(self, pair):
        server, client, conn_s, conn_c = pair
        client.send_notif(conn_c, b"n1")
        client.send(conn_c, b"regular")
        assert server.recv(conn_s) == b"regular"
        for _ in range(100):
            notifs = server.get_notifs()
            if notifs:
                break
            time.sleep(0.02)
        assert notifs == [(conn_s, b"n1")]

    def test_pending_notifs_visible_in_stats(self, pair):
        server, client, conn_s, conn_c = pair
        client.send_notif(conn_c, b"queued")
        for _ in range(100):
            if server.stats.get("notifs_pending", 0) == 1:
                break
            time.sleep(0.02)
        assert server.stats["notifs_pending"] == 1
        assert server.get_notifs() == [(conn_s, b"queued")]
        assert server.stats["notifs_pending"] == 0

    def test_notif_ordering_and_large(self, pair):
        server, client, conn_s, conn_c = pair
        big = b"B" * 10000  # larger than the 4096 drain buffer
        client.send_notif(conn_c, b"first")
        client.send_notif(conn_c, big)
        got = []
        for _ in range(200):
            got += server.get_notifs()
            if len(got) == 2:
                break
            time.sleep(0.02)
        assert got == [(conn_s, b"first"), (conn_s, big)]
