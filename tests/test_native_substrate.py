"""Native substrate unit tests (rings, lrpc, pool) + sanitizer gate.

The reference validates its substrate with pure-CPU unit mains
(util_lrpc_test.cc, util_test.cc — SURVEY.md §4.1) and ships NO sanitizer
coverage (§5: "the TPU build can do better cheaply") — so our CI runs the
threaded substrate tests under ThreadSanitizer too.
"""

import fcntl
import os
import shutil
import subprocess

import pytest

# slow: these build + run the native binaries under three sanitizer
# configs (~40 s pinned) and are exact duplicates of CI's dedicated
# `native` job (make -C native test/tsan/asan) and qa.sh's native step —
# tier-1 sat at the 870 s cap, so the duplicated copies moved out of it
# (they still run in the unfiltered qa.sh/CI pytest tiers).
pytestmark = pytest.mark.slow

_NATIVE = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")


def _make(target: str, timeout: int = 300):
    # Serialize across PROCESSES: two test runs (e.g. a suite and qa.sh
    # racing) invoking make in one build dir can relink a binary while the
    # other run executes it — observed as a corrupted sanitizer run. The
    # lock spans build AND run, since make's targets execute the tests.
    lock_path = os.path.join(_NATIVE, ".build.lock")
    with open(lock_path, "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        try:
            return subprocess.run(
                ["make", "-C", _NATIVE, target],
                capture_output=True, text=True, timeout=timeout,
            )
        finally:
            fcntl.flock(lk, fcntl.LOCK_UN)


def test_substrate_units():
    r = _make("test")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ALL SUBSTRATE TESTS PASSED" in r.stdout


def test_substrate_under_tsan():
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    r = _make("tsan", timeout=600)
    if r.returncode != 0 and "unrecognized" in r.stderr:
        pytest.skip("toolchain lacks -fsanitize=thread")
    assert r.returncode == 0, r.stdout + r.stderr[-3000:]
    assert "ALL SUBSTRATE TESTS PASSED" in r.stdout


def test_substrate_under_asan():
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    r = _make("asan", timeout=600)
    if r.returncode != 0 and "unrecognized" in r.stderr:
        pytest.skip("toolchain lacks -fsanitize=address")
    assert r.returncode == 0, r.stdout + r.stderr[-3000:]
    assert "ALL SUBSTRATE TESTS PASSED" in r.stdout
