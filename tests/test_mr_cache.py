"""Registration cache + interval tree — the reference's
test_util_interval_tree.py / test_register_memory_cache.py contracts:
containment reuse, fresh handles over shared key material, refcounted
eviction, partial-overlap and disjoint misses."""

import numpy as np
import pytest

from uccl_tpu.p2p import XferEndpoint
from uccl_tpu.p2p.mr_cache import ClosedIntervalTree


class TestIntervalTree:
    def test_containing_queries(self):
        t = ClosedIntervalTree()
        for s, e, d in [(1, 10, "large"), (2, 5, "sub"), (3, 4, "core"),
                        (15, 25, "region"), (20, 30, "overlap")]:
            t.add(s, e, d)
        got = {d for _, _, d in t.query_containing(3, 4)}
        assert got == {"large", "sub", "core"}
        assert {d for _, _, d in t.query_containing(16, 18)} == {"region"}
        assert {d for _, _, d in t.query_containing(22, 24)} == {
            "region", "overlap"
        }
        assert t.query_containing(12, 14) == []

    def test_remove_and_iterate(self):
        t = ClosedIntervalTree()
        t.add(1, 10, "a")
        t.add(2, 5, "b")
        assert len(t) == 2
        assert t.remove(2, 5, "b")
        assert not t.remove(2, 5, "b")  # already gone
        assert [(s, e, d) for s, e, d in t] == [(1, 10, "a")]

    def test_exact_and_overlapping(self):
        t = ClosedIntervalTree()
        t.add(10, 20, "x")
        t.add(10, 30, "y")
        assert [r[2] for r in t.query_exact(10, 20)] == ["x"]
        assert {r[2] for r in t.query_overlapping(25, 40)} == {"y"}
        assert t.query_overlapping(40, 50) == []

    def test_bad_interval(self):
        with pytest.raises(ValueError):
            ClosedIntervalTree().add(5, 3, "bad")


class TestMrCache:
    def test_same_buffer_reuses_base(self):
        xp = XferEndpoint(n_engines=1)
        try:
            arr = np.ones(4096, np.float32)
            d1 = xp.register_memory([arr])[0]
            d2 = xp.register_memory([arr])[0]
            # fresh API handle, shared key material (reference contract)
            assert d1["handle"] != d2["handle"]
            assert d1["mr_id"] == d2["mr_id"]
            # releasing one handle keeps the cached base alive
            xp.deregister_memory([d1])
            d3 = xp.register_memory([arr])[0]
            assert d3["mr_id"] == d2["mr_id"]
            xp.deregister_memory([d2, d3])
            assert xp.mr_cache.stats()["bases"] == 0
        finally:
            xp.close()

    def test_subregion_reuses_base(self):
        xp = XferEndpoint(n_engines=1)
        try:
            arr = np.ones(4096, np.float32)
            sub = arr[256:1280]  # contiguous view inside arr
            base = xp.register_memory([arr])[0]
            subd = xp.register_memory([sub])[0]
            assert subd["mr_id"] == base["mr_id"]
            assert subd["handle"] != base["handle"]
            xp.deregister_memory([base])
            sub2 = xp.register_memory([sub])[0]
            assert sub2["mr_id"] == subd["mr_id"]  # alive while referenced
            xp.deregister_memory([subd, sub2])
            assert xp.mr_cache.stats()["bases"] == 0
        finally:
            xp.close()

    def test_partial_overlap_and_disjoint_miss(self):
        xp = XferEndpoint(n_engines=1)
        try:
            arr = np.ones(4096, np.float32)
            a = xp.register_memory([arr[:2048]])[0]
            b = xp.register_memory([arr[1024:3072]])[0]  # partial overlap
            c = xp.register_memory([arr[2048:]])[0]  # disjoint from a
            assert len({a["mr_id"], b["mr_id"], c["mr_id"]}) == 3
            st = xp.mr_cache.stats()
            assert st["misses"] == 3 and st["hits"] == 0
            xp.deregister_memory([a, b, c])
        finally:
            xp.close()

    def test_cached_subregion_transfer_lands_correctly(self):
        """A window advertised through a cache hit must target the
        subregion's bytes, not the base's start."""
        import multiprocessing as mp

        def server(q):
            sxp = XferEndpoint(n_engines=1)
            buf = np.zeros(4096, np.float32)
            base = sxp.register_memory([buf])[0]
            sub = sxp.register_memory([buf[1024:2048]])[0]
            assert sub["mr_id"] == base["mr_id"]
            q.put((sxp.get_metadata(),
                   sxp.get_serialized_descs([sub])))
            assert sxp.accept() >= 0
            import time

            for _ in range(400):
                if any(p == b"DONE" for _, p in sxp.get_notifs()):
                    break
                time.sleep(0.05)
            # only [1024:2048] may have been written
            q.put((float(buf[:1024].sum()), float(buf[1024:2048].sum()),
                   float(buf[2048:].sum())))
            sxp.close()

        q = mp.Queue()
        proc = mp.Process(target=server, args=(q,))
        proc.start()
        try:
            md, blob = q.get(timeout=30)
            xp = XferEndpoint(n_engines=1)
            ok, conn = xp.add_remote_endpoint(md)
            assert ok
            remote = XferEndpoint.deserialize_descs(blob)
            src = np.ones(1024, np.float32)
            assert xp.wait(xp.transfer(conn, "WRITE", [src], remote))
            xp.send_notif(conn, b"DONE")
            lo, mid, hi = q.get(timeout=60)
            assert (lo, mid, hi) == (0.0, 1024.0, 0.0)
            xp.close()
        finally:
            proc.join(timeout=30)
            if proc.is_alive():
                proc.terminate()

    def test_deregister_unknown_handle(self):
        xp = XferEndpoint(n_engines=1)
        try:
            with pytest.raises(KeyError):
                xp.deregister_memory([{"handle": 999}])
        finally:
            xp.close()

    def test_deregister_drains_past_bad_handle(self):
        xp = XferEndpoint(n_engines=1)
        try:
            arr = np.ones(1024, np.float32)
            d1 = xp.register_memory([arr])[0]
            d2 = xp.register_memory([arr])[0]
            with pytest.raises(KeyError):
                xp.deregister_memory([d1, {"handle": 999}, d2])
            # d1 AND d2 were both released despite the bad middle handle
            assert xp.mr_cache.stats()["handles"] == 0
            assert xp.mr_cache.stats()["bases"] == 0
        finally:
            xp.close()

    def test_failed_batch_unwinds(self):
        xp = XferEndpoint(n_engines=1)
        try:
            good = np.ones(1024, np.float32)
            with pytest.raises(TypeError):
                xp.register_memory([good, [1, 2, 3]])
            with pytest.raises(ValueError, match="zero-size"):
                xp.register_memory([good, np.zeros(0, np.float32)])
            # nothing may remain registered from the failed batches
            assert xp.mr_cache.stats()["handles"] == 0
            assert xp.mr_cache.stats()["bases"] == 0
        finally:
            xp.close()

    def test_dereg_while_cached_hit_active_keeps_windows_valid(self):
        """MrCache sits above Endpoint.dereg's pin machinery: freeing the
        base only happens at refcount 0, so this mostly documents the
        lifecycle; stats expose hit/miss for the KV-transfer measurement."""
        xp = XferEndpoint(n_engines=1)
        try:
            arr = np.ones(2048, np.float32)
            d1 = xp.register_memory([arr])[0]
            d2 = xp.register_memory([arr[:1024]])[0]
            st = xp.mr_cache.stats()
            assert st == {"bases": 1, "handles": 2, "hits": 1, "misses": 1}
            xp.deregister_memory([d1, d2])
        finally:
            xp.close()
