"""Cross-process DCN collective group (threaded ranks over real loopback TCP)."""

import threading

import numpy as np
import pytest

from uccl_tpu.collective.hierarchical import DcnGroup
from uccl_tpu.p2p.store import StoreClient, StoreServer
from uccl_tpu.parallel.distributed import Session


def _run_group(world, fn):
    """Spin up `world` ranks as threads, each with its own DcnGroup."""
    server = StoreServer()
    results = [None] * world
    errors = []

    def rank_main(r):
        try:
            client = StoreClient("127.0.0.1", server.port)
            sess = Session(rank=r, world=world, store=client)
            g = DcnGroup(sess, n_paths=2)
            try:
                results[r] = fn(g, r)
            finally:
                g.close()
                client.close()
        except Exception as e:  # pragma: no cover - surfaced via assert below
            errors.append((r, e))

    threads = [threading.Thread(target=rank_main, args=(r,)) for r in range(world)]
    [t.start() for t in threads]
    [t.join(timeout=120) for t in threads]
    server.close()
    assert not errors, errors
    return results


@pytest.mark.parametrize("world", [2, 3])
def test_all_reduce(world, rng):
    xs = [rng.standard_normal(100).astype(np.float32) for _ in range(world)]
    want = np.sum(xs, axis=0)
    outs = _run_group(world, lambda g, r: g.all_reduce(xs[r]))
    for out in outs:
        np.testing.assert_allclose(out, want, rtol=1e-5)


def test_all_reduce_2d_payload(rng):
    xs = [rng.standard_normal((7, 13)).astype(np.float32) for _ in range(2)]
    outs = _run_group(2, lambda g, r: g.all_reduce(xs[r]))
    np.testing.assert_allclose(outs[0], xs[0] + xs[1], rtol=1e-5)
    np.testing.assert_allclose(outs[1], xs[0] + xs[1], rtol=1e-5)


def test_all_gather(rng):
    xs = [rng.standard_normal(16).astype(np.float32) for _ in range(3)]
    outs = _run_group(3, lambda g, r: g.all_gather(xs[r]))
    for out in outs:
        for i in range(3):
            np.testing.assert_array_equal(out[i], xs[i])


@pytest.mark.parametrize("world", [2, 3])
def test_all_to_all(world, rng):
    xs = [rng.standard_normal((world, 5)).astype(np.float32) for _ in range(world)]
    outs = _run_group(world, lambda g, r: g.all_to_all(xs[r]))
    for i, out in enumerate(outs):
        for j in range(world):
            # out[j] on rank i == rank j's row i
            np.testing.assert_array_equal(out[j], xs[j][i])


def test_all_to_all_regrow_after_calls(rng):
    """Regrow right after all_to_all calls: the deferred consume-licenses
    (up to two unconsumed ACs per channel) must be skipped by the MF
    descriptor exchange, not poison the group (round-5 review finding)."""
    def body(g, r):
        small = [np.full((2, 4), float(10 * p + i), np.float32)
                 for p in range(2) for i in range(2)]
        for i in range(2):  # leaves deferred ACs queued (consumed at i+2)
            g.all_to_all(np.full((2, 4), float(10 * r + i), np.float32))
        big = rng.standard_normal((2, 4096)).astype(np.float32) + r
        out = g.all_to_all(big)  # regrow -> MF exchange over the same chans
        # then a broadcast still works on the post-regrow group
        b = g.broadcast(np.full(8, 3.0 + r, np.float32), root=1)
        return out, b

    outs = _run_group(2, body)
    for i, (out, b) in enumerate(outs):
        assert b[0] == 4.0  # root 1's value
        assert out.shape == (2, 4096)


def test_world_one_degenerate(rng):
    x = rng.standard_normal(10).astype(np.float32)
    outs = _run_group(1, lambda g, r: g.all_reduce(x))
    np.testing.assert_array_equal(outs[0], x)


def test_barrier():
    _run_group(2, lambda g, r: g.barrier())


@pytest.mark.parametrize("world,root", [(2, 0), (3, 1), (4, 3)])
def test_broadcast(world, root, rng):
    xs = [rng.standard_normal((4, 9)).astype(np.float32) for _ in range(world)]
    outs = _run_group(world, lambda g, r: g.broadcast(xs[r], root=root))
    for out in outs:
        np.testing.assert_array_equal(out, xs[root])


def test_all_to_all_world4(rng):
    world = 4
    xs = [rng.standard_normal((world, 17)).astype(np.float32) for _ in range(world)]
    outs = _run_group(world, lambda g, r: g.all_to_all(xs[r]))
    for i, out in enumerate(outs):
        for j in range(world):
            np.testing.assert_array_equal(out[j], xs[j][i])


def test_all_to_all_traffic_is_pairwise(rng):
    """VERDICT round 1 #6: all_to_all must move O(rows) bytes per rank, not
    O(world*rows) like the old ring-gather + column-select."""
    world, row_bytes = 4, 256 << 10
    rows = row_bytes // 4

    def fn(g, r):
        x = rng.standard_normal((world, rows)).astype(np.float32)
        before = g.ep.stats["bytes_tx"]
        g.all_to_all(x)
        return g.ep.stats["bytes_tx"] - before

    sent = _run_group(world, fn)
    # pairwise: (world-1) rows + handshakes/fifo exchange. gather-based was
    # (world-1) * world rows = 12 * row_bytes. Assert well under half that.
    budget = (world - 1) * row_bytes + (64 << 10)
    for r, tx in enumerate(sent):
        assert tx < 1.6 * budget, f"rank {r} sent {tx} bytes (budget {budget})"


def test_mixed_ops_interleave(rng):
    """Ring and mesh collectives share one endpoint without cross-talk."""
    def fn(g, r):
        s = g.all_reduce(np.full(8, float(r), np.float32))
        b = g.broadcast(np.full(8, float(r), np.float32), root=2)
        a = g.all_to_all(np.full((3, 4), float(r), np.float32))
        return s[0], b[0], [a[j][0] for j in range(3)]

    outs = _run_group(3, fn)
    for r, (s, b, a) in enumerate(outs):
        assert s == 0.0 + 1.0 + 2.0
        assert b == 2.0
        assert a == [0.0, 1.0, 2.0]


def test_heal_after_rank_death(rng):
    """VERDICT round 1 #9: kill a rank; survivors heal the ring and the
    collective completes (reference analog: add/remove_remote_endpoint,
    p2p/engine.h:269,273)."""
    import time as _time

    world = 3
    server = StoreServer()
    results = {}
    errors = []
    barrier = threading.Barrier(world)

    def rank_main(r):
        client = StoreClient("127.0.0.1", server.port)
        sess = Session(rank=r, world=world, store=client)
        g = DcnGroup(sess, n_paths=2, tag="heal")
        try:
            # a first healthy collective so ring buffers are live
            out = g.all_reduce(np.full(16, float(r + 1), np.float32))
            assert abs(out[0] - 6.0) < 1e-5
            barrier.wait(timeout=60)
            if r == 2:
                return  # rank 2 "dies" (closes in finally)
            _time.sleep(0.3)  # let rank 2's teardown land
            g.heal([2])
            out2 = g.all_reduce(np.full(16, float(r + 1), np.float32))
            results[r] = out2[0]
        except Exception as e:  # pragma: no cover
            errors.append((r, e))
        finally:
            g.close()
            client.close()

    ts = [threading.Thread(target=rank_main, args=(r,)) for r in range(world)]
    [t.start() for t in ts]
    [t.join(timeout=120) for t in ts]
    server.close()
    assert not errors, errors
    # survivors 0 and 1: sum = 1 + 2
    assert results[0] == pytest.approx(3.0)
    assert results[1] == pytest.approx(3.0)


def test_heartbeat_drives_heal(rng):
    """Full elastic loop: monitor suspects the dead rank -> heal -> the next
    collective completes with survivors."""
    import time as _time

    from uccl_tpu.parallel.health import HeartbeatMonitor

    world = 3
    server = StoreServer()
    results = {}
    errors = []
    barrier = threading.Barrier(world)

    def rank_main(r):
        client = StoreClient("127.0.0.1", server.port)
        sess = Session(rank=r, world=world, store=client)
        g = DcnGroup(sess, n_paths=2, tag="hb_heal")
        mon = HeartbeatMonitor(sess, interval_s=0.1, timeout_s=0.6)
        try:
            out = g.all_reduce(np.full(8, float(r + 1), np.float32))
            assert abs(out[0] - 6.0) < 1e-5
            barrier.wait(timeout=60)
            if r == 1:
                return  # dies without ever starting its monitor
            mon.start()
            deadline = _time.time() + 15
            while _time.time() < deadline and mon.suspected() != [1]:
                _time.sleep(0.05)
            assert mon.suspected() == [1], mon.suspected()
            g.heal(mon.suspected())
            out2 = g.all_reduce(np.full(8, float(r + 1), np.float32))
            results[r] = out2[0]
        except Exception as e:  # pragma: no cover
            errors.append((r, e))
        finally:
            mon.stop()
            g.close()
            client.close()

    ts = [threading.Thread(target=rank_main, args=(r,)) for r in range(world)]
    [t.start() for t in ts]
    [t.join(timeout=120) for t in ts]
    server.close()
    assert not errors, errors
    assert results[0] == pytest.approx(4.0)  # 1 + 3 (ranks 0 and 2)
    assert results[2] == pytest.approx(4.0)


def test_heal_then_broadcast_and_a2a(rng):
    """Mesh collectives also run over the survivor set."""
    world = 3
    server = StoreServer()
    results = {}
    errors = []

    def rank_main(r):
        client = StoreClient("127.0.0.1", server.port)
        sess = Session(rank=r, world=world, store=client)
        g = DcnGroup(sess, n_paths=2, tag="heal_mesh")
        try:
            g.barrier()
            if r == 0:
                return  # rank 0 dies; survivors are 1 and 2
            import time as _time

            _time.sleep(0.3)
            g.heal([0])
            b = g.broadcast(np.full(8, float(r), np.float32), root=2)
            a = g.all_to_all(np.full((2, 4), float(r), np.float32))
            results[r] = (b[0], [a[j][0] for j in range(2)])
        except Exception as e:  # pragma: no cover
            errors.append((r, e))
        finally:
            g.close()
            client.close()

    ts = [threading.Thread(target=rank_main, args=(r,)) for r in range(world)]
    [t.start() for t in ts]
    [t.join(timeout=120) for t in ts]
    server.close()
    assert not errors, errors
    for r in (1, 2):
        b, a = results[r]
        assert b == 2.0
        assert a == [1.0, 2.0]


def test_scheduled_a2a_demotes_degraded_edges(rng):
    """Cross-pod scheduled a2a consults link quality (ISSUE 19): an edge
    whose channel EWMA sank below ``path_floor`` is demoted to the
    invocation tail instead of stalling healthy rounds behind it. The
    degraded view is asymmetric ON PURPOSE — link scores are per-endpoint
    observations, not SPMD state — and the exchange must stay bit-correct
    anyway (reordering is a purely local decision: sends are one-sided
    and licensed two invocations back, recvs wait on exact call indices
    over independent channels)."""
    from uccl_tpu import obs
    from uccl_tpu.ep import a2a_sched

    world = 3
    m = np.ones((world, world)) - np.eye(world)
    sched = a2a_sched.wire_schedule(m, world)
    xs = [rng.standard_normal((world, 6)).astype(np.float32)
          for _ in range(world)]

    def body(g, r):
        o1 = g.all_to_all(xs[r], schedule=sched)  # builds the mesh
        if r == 0:
            # only rank 0 sees its link to rank 1 as sick
            g._mesh[1]._link_ewma = 0.0
        o2 = g.all_to_all(xs[r] + 1.0, schedule=sched, path_floor=0.5)
        # and the next interleaved replay still lines up (the demotion
        # changed wait order only, never call indices)
        o3 = g.all_to_all(xs[r] + 2.0, schedule=sched)
        return o1, o2, o3

    s0 = obs.counter("dcn_a2a_demotions_total").get(dir="send")
    r0 = obs.counter("dcn_a2a_demotions_total").get(dir="recv")
    outs = _run_group(world, body)
    for i, (o1, o2, o3) in enumerate(outs):
        for j in range(world):
            np.testing.assert_array_equal(o1[j], xs[j][i])
            np.testing.assert_array_equal(o2[j], xs[j][i] + 1.0)
            np.testing.assert_array_equal(o3[j], xs[j][i] + 2.0)
    # rank 0 demoted exactly its send to and recv from rank 1
    assert obs.counter("dcn_a2a_demotions_total").get(dir="send") == s0 + 1
    assert obs.counter("dcn_a2a_demotions_total").get(dir="recv") == r0 + 1


def test_scheduled_a2a_without_floor_matches(rng):
    """The scheduled path (no floor) replays the contention-aware round
    interleaving and stays exact — the ISSUE 19 restructure must not
    have changed the default order's semantics."""
    from uccl_tpu.ep import a2a_sched

    world = 4
    mat = np.abs(rng.standard_normal((world, world)))
    np.fill_diagonal(mat, 0)
    sched = a2a_sched.wire_schedule(mat, world)
    xs = [rng.standard_normal((world, 9)).astype(np.float32)
          for _ in range(world)]
    outs = _run_group(
        world, lambda g, r: g.all_to_all(xs[r], schedule=sched))
    for i, out in enumerate(outs):
        for j in range(world):
            np.testing.assert_array_equal(out[j], xs[j][i])
