"""Bandwidth-optimal broadcast / all-gather (ISSUE 14).

Kernel oracle: broadcast and all-gather are PURE DATA MOVEMENT at full
precision, so the scatter-allgather kernels must equal the root row /
input tile EXACTLY (array_equal, not allclose); quantized wires pay one
documented codec round trip and every member dequantizes the same bytes.
Planner: the new verbs' decisions land on
``collective_plan_total{verb=...}`` and their crossovers shift with
quantized wire bytes (the PR 7 rule, via the budget probe).
Wire audit: the psum-baseline reduction is a COUNTER delta on
``ep_bytes_total{verb="bcast"}``, never model math.

Worlds 4/8/5 on 1-axis meshes (runnable under the legacy discharge
interpreter, like TestBidir); heavy arms are ``slow`` — tier-1 keeps the
world-4 kernel core + the world-8 counter regressions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from uccl_tpu.collective import Communicator, dma, pallas_ccl, plan
from uccl_tpu.utils.jaxcompat import shard_map


def _run(mesh, fn, x, in_spec=P("dp"), out_spec=P("dp", None)):
    mapped = shard_map(fn, mesh=mesh, in_specs=(in_spec,),
                       out_specs=out_spec, check_vma=False)
    return np.asarray(jax.jit(mapped)(x))


def _mesh(devices, n):
    return Mesh(np.array(devices[:n]), ("dp",))


def _fb_snap():
    return {tuple(sorted(lb.items())): v
            for lb, v in dma.WIRE_FALLBACK.samples()}


def _plan_snap():
    return {tuple(sorted(lb.items())): v
            for lb, v in plan.PLAN_TOTAL.samples()}


def _bytes_snap(verb="bcast"):
    from uccl_tpu.obs import counters as obsc

    return {tuple(sorted(lb.items())): v
            for lb, v in obsc.counter("ep_bytes_total").samples()
            if lb.get("verb") == verb}


def _bytes_delta(before, verb="bcast"):
    return sum(int(v - before.get(k, 0))
               for k, v in _bytes_snap(verb).items()
               if v - before.get(k, 0) > 0)


class TestScatterAgBroadcast:
    """The kernel pair: root scatters S/n chunks, the counter-rotating
    all-gather pair completes — bit-exact at full precision."""

    @pytest.mark.parametrize("root", [0, 2])
    def test_matches_root_exact(self, devices, rng, root):
        n = 4
        x = jnp.asarray(rng.normal(size=(n, 41)), jnp.float32)
        got = _run(
            _mesh(devices, n),
            lambda v: pallas_ccl.scatter_ag_broadcast(
                v, "dp", root, interpret=True),
            x,
        )
        np.testing.assert_array_equal(
            got, np.tile(np.asarray(x)[root], (n, 1)))

    def test_budget_fallback_counted(self, devices, rng, monkeypatch):
        """Over-budget: the whole decomposition rides the bit-identical
        lax mirror, counted on ep_wire_fallback_total{what="broadcast"}
        AND collective_plan_total{verb="broadcast", outcome="fallback"}
        — and stays exact (pure movement either way)."""
        monkeypatch.setenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES", "64")
        pallas_ccl._MAX_VMEM_BYTES.reset()
        try:
            n = 4
            x = jnp.asarray(rng.normal(size=(n, 64)), jnp.float32)
            fb, pl = _fb_snap(), _plan_snap()
            pk = (("algo", "scatter_ag"), ("chunks", "2"),
                  ("outcome", "fallback"), ("verb", "broadcast"),
                  ("wire_dtype", "none"))
            got = _run(
                _mesh(devices, n),
                lambda v: pallas_ccl.scatter_ag_broadcast(
                    v, "dp", 1, interpret=True),
                x,
            )
            np.testing.assert_array_equal(
                got, np.tile(np.asarray(x)[1], (n, 1)))
            fb2 = _fb_snap()
            hit = [k for k, v in fb2.items()
                   if v > fb.get(k, 0) and dict(k)["what"] == "broadcast"]
            assert hit, f"no counted broadcast downgrade in {fb2}"
            assert _plan_snap().get(pk, 0) == pl.get(pk, 0) + 1
        finally:
            monkeypatch.delenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES")
            pallas_ccl._MAX_VMEM_BYTES.reset()

    @pytest.mark.slow
    @pytest.mark.parametrize("n", [4, 8, 5])
    def test_every_root_worlds(self, devices, rng, n):
        """The acceptance sweep: exact at EVERY root, worlds 4/8/5."""
        x = jnp.asarray(rng.normal(size=(n, 72)), jnp.float32)
        for root in range(n):
            got = _run(
                _mesh(devices, n),
                lambda v, r=root: pallas_ccl.scatter_ag_broadcast(
                    v, "dp", r, interpret=True),
                x,
            )
            np.testing.assert_array_equal(
                got, np.tile(np.asarray(x)[root], (n, 1)))

    @pytest.mark.slow
    @pytest.mark.parametrize("n", [4, 8, 5])
    def test_fp8_wire(self, devices, rng, n):
        """fp8 wire: one quantize round trip of error vs the root row,
        every member identical, and bit-identical to the lax mirror (the
        counted fallback path)."""
        x = jnp.asarray(rng.normal(size=(n, 40)), jnp.float32)
        got = _run(
            _mesh(devices, n),
            lambda v: pallas_ccl.scatter_ag_broadcast(
                v, "dp", 1, interpret=True, wire_dtype="fp8"),
            x,
        )
        want = np.tile(np.asarray(x)[1], (n, 1))
        # one e4m3 round trip (+ XLA's double-rounding slack,
        # docs/QUANT_WIRE.md)
        np.testing.assert_allclose(got, want, rtol=0.15, atol=0.1)
        for r in range(1, n):
            np.testing.assert_array_equal(got[0], got[r])

    @pytest.mark.slow
    def test_int8_wire(self, devices, rng):
        n = 4
        x = jnp.asarray(rng.normal(size=(n, 40)), jnp.float32)
        got = _run(
            _mesh(devices, n),
            lambda v: pallas_ccl.scatter_ag_broadcast(
                v, "dp", 3, interpret=True, wire_dtype="int8"),
            x,
        )
        want = np.tile(np.asarray(x)[3], (n, 1))
        np.testing.assert_allclose(got, want, rtol=0.02, atol=0.02)

    @pytest.mark.slow
    def test_bf16_exact(self, devices, rng):
        """Full-precision movement is dtype-agnostic: bf16 stays exact."""
        n = 4
        x = jnp.asarray(rng.normal(size=(n, 64)), jnp.bfloat16)
        got = _run(
            _mesh(devices, n),
            lambda v: pallas_ccl.scatter_ag_broadcast(
                v, "dp", 2, interpret=True),
            x,
        )
        np.testing.assert_array_equal(
            got.astype(np.float32),
            np.tile(np.asarray(x[2], np.float32), (n, 1)))

    @pytest.mark.slow
    def test_mirror_bit_identity_fp8(self, devices, rng, monkeypatch):
        """kernel == counted lax fallback, bit for bit, on the quantized
        wire (quantize-once + verbatim forwarding on both paths)."""
        n = 4
        x = jnp.asarray(rng.normal(size=(n, 40)), jnp.float32)
        kern = _run(
            _mesh(devices, n),
            lambda v: pallas_ccl.scatter_ag_broadcast(
                v, "dp", 0, interpret=True, wire_dtype="fp8"),
            x,
        )
        monkeypatch.setenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES", "64")
        pallas_ccl._MAX_VMEM_BYTES.reset()
        try:
            mirror = _run(
                _mesh(devices, n),
                lambda v: pallas_ccl.scatter_ag_broadcast(
                    v, "dp", 0, interpret=True, wire_dtype="fp8"),
                x,
            )
        finally:
            monkeypatch.delenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES")
            pallas_ccl._MAX_VMEM_BYTES.reset()
        np.testing.assert_array_equal(kern, mirror)


class TestBidirAllGather:
    """The counter-rotating AG pair: write-once forwarding, exact at full
    precision, one round trip quantized."""

    def test_matches_tile_exact(self, devices, rng):
        n = 4
        x = jnp.asarray(rng.normal(size=(n, 41)), jnp.float32)  # odd split
        got = _run(
            _mesh(devices, n),
            lambda v: pallas_ccl.bidir_all_gather(v, "dp", interpret=True),
            x,
        )
        np.testing.assert_array_equal(got, np.tile(np.asarray(x), (n, 1)))

    def test_budget_fallback_counted(self, devices, rng, monkeypatch):
        monkeypatch.setenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES", "64")
        pallas_ccl._MAX_VMEM_BYTES.reset()
        try:
            n = 4
            x = jnp.asarray(rng.normal(size=(n, 64)), jnp.float32)
            fb, pl = _fb_snap(), _plan_snap()
            pk = (("algo", "bidir"), ("chunks", "2"),
                  ("outcome", "fallback"), ("verb", "all_gather"),
                  ("wire_dtype", "none"))
            got = _run(
                _mesh(devices, n),
                lambda v: pallas_ccl.bidir_all_gather(v, "dp",
                                                      interpret=True),
                x,
            )
            np.testing.assert_array_equal(
                got, np.tile(np.asarray(x), (n, 1)))
            hit = [k for k, v in _fb_snap().items()
                   if v > fb.get(k, 0)
                   and dict(k)["what"] == "all_gather_bidir"]
            assert hit
            assert _plan_snap().get(pk, 0) == pl.get(pk, 0) + 1
        finally:
            monkeypatch.delenv("UCCL_TPU_PALLAS_CCL_MAX_BYTES")
            pallas_ccl._MAX_VMEM_BYTES.reset()

    @pytest.mark.slow
    @pytest.mark.parametrize("n", [8, 5])
    def test_oracle_worlds(self, devices, rng, n):
        x = jnp.asarray(rng.normal(size=(n, 27)), jnp.float32)
        got = _run(
            _mesh(devices, n),
            lambda v: pallas_ccl.bidir_all_gather(v, "dp", interpret=True),
            x,
        )
        np.testing.assert_array_equal(got, np.tile(np.asarray(x), (n, 1)))

    @pytest.mark.slow
    @pytest.mark.parametrize("n", [4, 8, 5])
    @pytest.mark.parametrize("wd", ["fp8", "int8"])
    def test_quant_wire(self, devices, rng, n, wd):
        """Quantized gather: every gathered row one codec round trip from
        its contributor, all members dequantizing the same bytes."""
        x = jnp.asarray(rng.normal(size=(n, 24)), jnp.float32)
        got = _run(
            _mesh(devices, n),
            lambda v: pallas_ccl.bidir_all_gather(
                v, "dp", interpret=True, wire_dtype=wd),
            x,
        )
        tol = dict(fp8=(0.15, 0.1), int8=(0.02, 0.02))[wd]
        got = got.reshape(n, n, 24)  # [member, gathered row, payload]
        np.testing.assert_allclose(got[0], np.asarray(x),
                                   rtol=tol[0], atol=tol[1])
        for r in range(1, n):  # every member dequantizes the same bytes
            np.testing.assert_array_equal(got[0], got[r])

    @pytest.mark.slow
    def test_bf16_exact(self, devices, rng):
        n = 5
        x = jnp.asarray(rng.normal(size=(n, 16)), jnp.bfloat16)
        got = _run(
            _mesh(devices, n),
            lambda v: pallas_ccl.bidir_all_gather(v, "dp", interpret=True),
            x,
        )
        np.testing.assert_array_equal(
            got.astype(np.float32),
            np.tile(np.asarray(x, np.float32), (n, 1)))


class TestCommunicatorVerbs:
    """The planned Communicator surface + the counter-audited wire-byte
    regressions (the ISSUE's acceptance numbers)."""

    @pytest.fixture(scope="class")
    def comm8(self, devices):
        return Communicator(_mesh(devices, 8), "dp")

    def test_xla_scatter_gather_beats_psum_wire_bytes(self, comm8, rng):
        """The satellite regression: the re-lowered xla broadcast
        (ppermute scatter + ring gather) halves the counted wire bytes of
        the legacy masked psum — a counter delta, not model math."""
        n = 8
        x = rng.standard_normal((n, 16384)).astype(np.float32)  # 64 KiB
        gx = comm8.device_put(x)
        b = _bytes_snap()
        out = np.asarray(comm8.broadcast(gx, 3, algo="psum"))
        np.testing.assert_array_equal(out, np.tile(x[3], (n, 1)))
        psum_bytes = _bytes_delta(b)
        b = _bytes_snap()
        out = np.asarray(comm8.broadcast(gx, 3, algo="xla"))
        np.testing.assert_array_equal(out, np.tile(x[3], (n, 1)))
        xla_bytes = _bytes_delta(b)
        assert psum_bytes > 0 and xla_bytes > 0
        assert psum_bytes / xla_bytes >= 2.0, (psum_bytes, xla_bytes)

    def test_pallas_bcast_beats_psum_wire_bytes(self, comm8, rng):
        """Acceptance: the planned pallas broadcast's counter-audited
        wire bytes are >= ~2x below the masked-psum baseline at world 8
        (and the result stays bit-exact for every member)."""
        n = 8
        x = rng.standard_normal((n, 16384)).astype(np.float32)
        gx = comm8.device_put(x)
        b = _bytes_snap()
        out = np.asarray(comm8.broadcast(gx, 5, algo="psum"))
        psum_bytes = _bytes_delta(b)
        b = _bytes_snap()
        out = np.asarray(comm8.broadcast(gx, 5, algo="scatter_ag"))
        np.testing.assert_array_equal(out, np.tile(x[5], (n, 1)))
        pallas_bytes = _bytes_delta(b)
        assert pallas_bytes > 0
        assert psum_bytes / pallas_bytes >= 2.0, (psum_bytes, pallas_bytes)

    @pytest.mark.slow
    def test_fp8_bcast_wire_bytes(self, comm8, rng):
        """...and more with an fp8 wire (acceptance): >= 4x below the
        baseline, within the codec's round-trip bound."""
        n = 8
        x = rng.standard_normal((n, 16384)).astype(np.float32)
        gx = comm8.device_put(x)
        b = _bytes_snap()
        np.asarray(comm8.broadcast(gx, 0, algo="psum"))
        psum_bytes = _bytes_delta(b)
        b = _bytes_snap()
        out = np.asarray(comm8.broadcast(gx, 0, algo="scatter_ag",
                                         wire_dtype="fp8"))
        fp8_bytes = _bytes_delta(b)
        ref = np.tile(x[0], (n, 1))
        np.testing.assert_allclose(out, ref, rtol=0.15, atol=0.1)
        assert fp8_bytes > 0
        assert psum_bytes / fp8_bytes >= 4.0, (psum_bytes, fp8_bytes)

    def test_tree_and_auto_match(self, comm8, rng):
        x = rng.standard_normal((8, 96)).astype(np.float32)
        gx = comm8.device_put(x)
        want = np.tile(x[2], (8, 1))
        np.testing.assert_array_equal(
            np.asarray(comm8.broadcast(gx, 2, algo="tree")), want)
        np.testing.assert_array_equal(
            np.asarray(comm8.broadcast(gx, 2, algo="auto")), want)

    def test_bad_args(self, comm8):
        x = comm8.device_put(np.zeros((8, 4), np.float32))
        with pytest.raises(ValueError, match="root"):
            comm8.broadcast(x, 9)
        with pytest.raises(ValueError, match="wire_dtype"):
            comm8.broadcast(x, 0, algo="tree", wire_dtype="fp8")
        with pytest.raises(ValueError, match="wire_dtype"):
            comm8.all_gather(x, algo="xla", wire_dtype="fp8")
        with pytest.raises(ValueError, match="unknown broadcast"):
            comm8.broadcast(x, 0, algo="nope")
        with pytest.raises(ValueError, match="unknown all_gather"):
            comm8.all_gather(x, algo="nope")

    @pytest.mark.slow
    def test_all_gather_algos_match(self, comm8, rng):
        x = rng.standard_normal((8, 128)).astype(np.float32)
        gx = comm8.device_put(x)
        for algo in ("xla", "ring", "bidir", "auto"):
            np.testing.assert_array_equal(
                np.asarray(comm8.all_gather(gx, algo=algo)), x,
                err_msg=algo)


class TestPlannerVerbs:
    """Pure planner decisions (no mesh): candidates, budget-probe
    crossovers, verb-labeled emission."""

    def test_broadcast_tiny_prefers_tree(self):
        p = plan.get_planner().plan_broadcast((64,), jnp.float32, 8,
                                              pallas_ok=True)
        assert p.algo == "tree" and p.verb == "broadcast"

    def test_broadcast_bandwidth_range_prefers_scatter_ag(self):
        p = plan.get_planner().plan_broadcast((16384,), jnp.float32, 8,
                                              pallas_ok=True)
        assert p.algo == "scatter_ag" and p.chunks == 2

    def test_fp8_shifts_the_budget_crossover(self):
        """The PR 7 rule at the new verb: a payload whose f32 kernel pair
        overflows the (interpreter) budget plans xla, but its QUANTIZED
        wire fits — fp8 flips the decision to the kernel."""
        pl = plan.get_planner()
        shape = (8 * 8192,)  # f32 pair charge > interpret budget
        assert pl.plan_broadcast(shape, jnp.float32, 8,
                                 pallas_ok=True).algo == "xla"
        p = pl.plan_broadcast(shape, jnp.float32, 8, pallas_ok=True,
                              wire_dtype="fp8")
        assert p.algo == "scatter_ag" and p.wire_dtype == "fp8"

    def test_quant_relabel_on_non_kernel_winner(self):
        """A quantized request whose winner can't carry the wire is
        emitted at full precision (the caller counts the downgrade)."""
        p = plan.get_planner().plan_broadcast((64,), jnp.float32, 8,
                                              pallas_ok=True,
                                              wire_dtype="fp8")
        assert p.algo == "tree" and p.wire_dtype is None

    def test_all_gather_candidates(self):
        pl = plan.get_planner()
        p = pl.plan_all_gather((2048,), jnp.float32, 8, pallas_ok=True)
        assert p.algo == "bidir" and p.verb == "all_gather"
        # over the quiet budget probe: kernels drop out
        p2 = pl.plan_all_gather((1 << 20,), jnp.float32, 8,
                                pallas_ok=True)
        assert p2.algo == "xla"
        # no kernel addressability: xla is the only candidate
        p3 = pl.plan_all_gather((2048,), jnp.float32, 8, pallas_ok=False)
        assert p3.algo == "xla"

    def test_verb_emission_labels(self):
        before = _plan_snap()
        p = plan.get_planner().plan_broadcast((256,), jnp.float32, 8,
                                              pallas_ok=False)
        key = (("algo", p.algo), ("chunks", str(p.chunks)),
               ("outcome", "model"), ("verb", "broadcast"),
               ("wire_dtype", "none"))
        assert _plan_snap().get(key, 0) == before.get(key, 0) + 1
        from uccl_tpu.obs import counters as obsc

        g = obsc.gauge("collective_plan_predicted_us")
        assert g.get(algo=p.algo, chunks=str(p.chunks), wire_dtype="none",
                     verb="broadcast") == pytest.approx(p.predicted_us)

    def test_verb_cost_features(self):
        hops, vol, launches = plan.verb_cost_features(
            "broadcast", "scatter_ag", 8, 1000)
        assert hops == 14 and launches == 2
        assert vol == pytest.approx(1.5 * 7 / 8 * 1000)
        th, tvol, _ = plan.verb_cost_features("broadcast", "tree", 8, 1000)
        assert th == 3 and tvol == pytest.approx(3000)
        rh, rvol, rl = plan.verb_cost_features("all_gather", "ring", 8,
                                               1000)
        bh, bvol, bl = plan.verb_cost_features("all_gather", "bidir", 8,
                                               1000)
        assert rvol == pytest.approx(2 * bvol) and (rl, bl) == (1, 2)
        assert plan.xla_wire_volume("all_gather", 8, 1000) == 7000
        assert plan.xla_wire_volume("broadcast", 8, 1000) == 1000
        with pytest.raises(ValueError):
            plan.verb_cost_features("broadcast", "nope", 8, 1000)

    def test_tree_rounds_schedule(self):
        """The shared binomial schedule (utils.topology.bcast_tree_rounds
        — the dedupe target of plan.tree_broadcast and
        DcnGroup.broadcast): every non-root member receives exactly once,
        from a member that already holds the value."""
        from uccl_tpu.utils.topology import bcast_tree_rounds

        for n in (2, 3, 5, 8):
            for root in (0, n - 1):
                rounds = bcast_tree_rounds(n, root)
                holders = {root}
                seen = set()
                for pairs in rounds:
                    new = set()
                    for s, d in pairs:
                        assert s in holders, (n, root, s)
                        assert d not in holders and d not in seen
                        new.add(d)
                        seen.add(d)
                    holders |= new
                assert holders == set(range(n))
                assert len(rounds) == max(1, (n - 1).bit_length())


class TestCalibrateVerbs:
    """plan_calibrate fits the SAME alpha/beta/gamma from synthetic
    broadcast/all-gather arms (collective_plan lines) — one calibration
    repricing every verb."""

    @staticmethod
    def _calibrate_mod():
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "plan_calibrate.py")
        spec = importlib.util.spec_from_file_location("plan_calibrate",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_fit_recovers_constants_from_verb_arms(self):
        import json

        pc = self._calibrate_mod()
        model = plan.CostModel(
            alpha_us=3.0, beta_us_per_byte=2e-3, gamma_us=7.0,
            xla_alpha_us=55.0, xla_beta_us_per_byte=1.1e-3, xla_snake=2.0,
        )
        lines = []
        for nbytes in (4096, 65536, 1 << 20):
            for verb, algos in (("broadcast",
                                 ("xla", "tree", "scatter_ag")),
                                ("all_gather", ("xla", "ring", "bidir"))):
                arms = [
                    {"algo": a,
                     "time_us": model.predict_verb(verb, a, 8, nbytes),
                     "modeled_us": 0.0}
                    for a in algos
                ]
                lines.append(json.dumps({
                    "bench": "collective_plan", "verb": verb,
                    "bytes": nbytes, "world": 8, "n_axes": 1,
                    "mesh2d": None, "arms": arms,
                }))
        rows = pc._rows(lines)
        assert rows and all(r[0] in ("broadcast", "all_gather")
                            for r in rows)
        fitted = pc.fit(rows)
        assert fitted["PLAN_ALPHA_US"] == pytest.approx(3.0, rel=1e-3)
        assert fitted["PLAN_BETA_US_PER_BYTE"] == pytest.approx(2e-3,
                                                                rel=1e-3)
        assert fitted["PLAN_GAMMA_US"] == pytest.approx(7.0, rel=1e-3)
        assert fitted["PLAN_XLA_ALPHA_US"] == pytest.approx(55.0, rel=1e-3)
        assert fitted["PLAN_XLA_BETA_US_PER_BYTE"] == pytest.approx(
            1.1e-3, rel=1e-3)
