"""NIXL/Ray-facing tensor-transfer API (uccl_tpu.p2p.XferEndpoint) — the
adapter surface the reference validates in p2p/tests/test_ray_api.py
(register_memory descriptor structure, serialize/deserialize roundtrip,
metadata exchange + add_remote_endpoint, WRITE/READ transfers over
descriptor lists)."""

import multiprocessing as mp

import numpy as np
import pytest

from uccl_tpu.p2p import XferEndpoint


class TestDescriptors:
    def test_register_memory_fields(self):
        xp = XferEndpoint(n_engines=1)
        try:
            arrs = [np.ones(1024, np.float32), np.zeros(512, np.float32)]
            descs = xp.register_memory(arrs)
            assert len(descs) == 2
            for arr, d in zip(arrs, descs):
                assert d["addr"] == arr.ctypes.data
                assert d["size"] == arr.nbytes
                assert d["mr_id"] > 0
                assert len(bytes.fromhex(d["fifo"])) == 64
        finally:
            xp.close()

    def test_serialize_roundtrip(self):
        xp = XferEndpoint(n_engines=1)
        try:
            arrs = [
                np.ones(1024, np.float32),
                np.zeros(512, np.float16),
                np.ones(256, np.int32),
            ]
            descs = xp.register_memory(arrs)
            blob = xp.get_serialized_descs(descs)
            back = XferEndpoint.deserialize_descs(blob)
            assert back == descs
        finally:
            xp.close()

    def test_non_numpy_rejected(self):
        xp = XferEndpoint(n_engines=1)
        try:
            with pytest.raises(TypeError, match="numpy"):
                xp.register_memory([[1, 2, 3]])
        finally:
            xp.close()

    def test_transfer_validation(self):
        xp = XferEndpoint(n_engines=1)
        try:
            with pytest.raises(ValueError, match="WRITE or READ"):
                xp.transfer(1, "PUT", [], [])
            with pytest.raises(ValueError, match="local arrays"):
                xp.transfer(1, "WRITE", [np.ones(4, np.float32)], [])
        finally:
            xp.close()


def _server(q):
    xp = XferEndpoint(n_engines=1)
    dst = [np.zeros(4096, np.float32), np.zeros(100, np.float32)]
    descs = xp.register_memory(dst)
    q.put((xp.get_metadata(), xp.get_serialized_descs(descs)))
    assert xp.accept() >= 0
    import time

    for _ in range(400):
        if any(p == b"DONE" for _, p in xp.get_notifs()):
            break
        time.sleep(0.05)
    q.put([float(d.sum()) for d in dst])
    # serve the client's READ-back before closing
    for _ in range(400):
        if any(p == b"READ_DONE" for _, p in xp.get_notifs()):
            break
        time.sleep(0.05)
    xp.close()


class TestTwoProcessTransfer:
    def test_write_then_read(self):
        """The reference's client/server flow (test_ray_api.py:442-600):
        metadata + descs out-of-band, WRITE local->remote, verify, then
        READ the same windows back and verify bit-exactness."""
        q = mp.Queue()
        proc = mp.Process(target=_server, args=(q,))
        proc.start()
        try:
            metadata, blob = q.get(timeout=30)
            xp = XferEndpoint(n_engines=1)
            ok, conn = xp.add_remote_endpoint(metadata)
            assert ok and conn >= 0
            remote = XferEndpoint.deserialize_descs(blob)
            rng = np.random.default_rng(5)
            src = [
                rng.standard_normal(4096).astype(np.float32),
                rng.standard_normal(100).astype(np.float32),
            ]
            xids = xp.transfer(conn, "WRITE", src, remote)
            assert xp.wait(xids)
            xp.send_notif(conn, b"DONE")
            sums = q.get(timeout=60)
            np.testing.assert_allclose(
                sums, [float(s.sum()) for s in src], rtol=1e-5
            )
            # READ the windows back: must be bit-exact with what we wrote
            back = [np.zeros(4096, np.float32), np.zeros(100, np.float32)]
            xids = xp.transfer(conn, "READ", back, remote)
            assert xp.wait(xids)
            for b, s in zip(back, src):
                np.testing.assert_array_equal(b, s)
            xp.send_notif(conn, b"READ_DONE")
            xp.close()
        finally:
            proc.join(timeout=30)
            if proc.is_alive():
                proc.terminate()
        assert proc.exitcode == 0


class TestExampleRuns:
    @pytest.mark.slow
    def test_disagg_proxy_example(self):
        # slow: ~16 s subprocess example run; qa.sh executes the proxy
        # example directly and its unfiltered pytest tier keeps this —
        # moved out of tier-1 to stay under the 870 s cap
        """The vLLM-style prefill/decode router end-to-end: HTTP two-step
        routing, KV pulled by one-sided READ, exact-match generation."""
        import subprocess
        import sys
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, UCCL_TPU_EXAMPLE_CPU="1")
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "examples",
                                          "disagg_proxy.py"),
             "--new-tokens", "8"],
            capture_output=True, text=True, timeout=300, cwd=repo, env=env,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "exact match vs single worker: True" in r.stdout

    def test_weight_transfer_example(self):
        """The Ray-actor example end-to-end (multiprocessing fallback in
        this image; identical transfer path under real Ray)."""
        import subprocess
        import sys
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "examples",
                                          "ray_weight_transfer.py")],
            capture_output=True, text=True, timeout=120, cwd=repo,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "OK" in r.stdout
