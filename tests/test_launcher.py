"""scripts/launch.py smoke: spawn 3 local ranks, run a DCN allreduce."""

import os
import socket
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_coordinator() -> str:
    """Pick a coordinator ip:port whose store port (port+1) is also free."""
    for _ in range(50):
        with socket.socket() as a:
            a.bind(("127.0.0.1", 0))
            port = a.getsockname()[1]
        try:
            with socket.socket() as b:
                b.bind(("127.0.0.1", port + 1))
            return f"127.0.0.1:{port}"
        except OSError:
            continue
    raise RuntimeError("no free port pair")


def test_launch_local_allreduce():
    r = subprocess.run(
        [
            sys.executable, os.path.join(_REPO, "scripts", "launch.py"),
            "--nproc", "3", "--no-jax-dist",
            "--coordinator", _free_coordinator(),
            os.path.join(_REPO, "examples", "launch_allreduce.py"),
        ],
        capture_output=True, text=True, timeout=180,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    for rank in range(3):
        assert f"rank {rank}/3: allreduce sum=6.0 OK" in r.stdout, r.stdout
        assert f"rank {rank}/3: hierarchical sum=24.0 OK" in r.stdout, r.stdout


def test_launch_failure_propagates(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")
    r = subprocess.run(
        [
            sys.executable, os.path.join(_REPO, "scripts", "launch.py"),
            "--nproc", "2", "--no-jax-dist",
            "--coordinator", _free_coordinator(), str(bad),
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 3
