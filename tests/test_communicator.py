"""Communicator collective numerics vs numpy — the analog of the reference's
transport correctness tests (collective/rdma/transport_test.cc data-pattern
asserts), but exact: every collective verb checked against a numpy oracle on the
8-device virtual mesh."""

import numpy as np
import pytest

from uccl_tpu.collective import Communicator, ReduceOp
from uccl_tpu.parallel.mesh import AXIS


@pytest.fixture(scope="module", params=["dp8", "tp_of_8", "ep_tuple"])
def comm(request, devices):
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    if request.param == "dp8":
        mesh = make_mesh(MeshConfig(dp=8), devices)
        return Communicator(mesh, AXIS.DP)
    if request.param == "tp_of_8":
        mesh = make_mesh(MeshConfig(dp=2, tp=4), devices)
        return Communicator(mesh, AXIS.TP)
    mesh = make_mesh(MeshConfig(dp=2, cp=2, tp=2), devices)
    return Communicator(mesh, AXIS.EP)


def _ranked_input(comm, rng, payload=(6, 4)):
    x = rng.standard_normal((comm.world, *payload)).astype(np.float32)
    return x, comm.device_put(x)


class TestAllReduce:
    def test_sum(self, comm, rng):
        x, gx = _ranked_input(comm, rng)
        out = np.asarray(comm.all_reduce(gx))
        want = np.broadcast_to(x.sum(0, keepdims=True), x.shape)
        np.testing.assert_allclose(out, want, rtol=1e-5)

    @pytest.mark.parametrize("op", [ReduceOp.MAX, ReduceOp.MIN, ReduceOp.AVG, ReduceOp.PROD])
    def test_other_ops(self, comm, rng, op):
        x, gx = _ranked_input(comm, rng, payload=(4,))
        out = np.asarray(comm.all_reduce(gx, op))
        red = {
            ReduceOp.MAX: np.max,
            ReduceOp.MIN: np.min,
            ReduceOp.AVG: np.mean,
            ReduceOp.PROD: np.prod,
        }[op]
        want = np.broadcast_to(red(x, axis=0, keepdims=True), x.shape)
        np.testing.assert_allclose(out, want, rtol=1e-5)

    def test_rejects_bad_shape(self, comm):
        import jax.numpy as jnp

        with pytest.raises(ValueError):
            comm.all_reduce(np.zeros((comm.world + 1, 2), np.float32))
        with pytest.raises(ValueError):
            comm.all_reduce(jnp.zeros((comm.world + 1, 2), jnp.float32))
        with pytest.raises(ValueError):
            comm.device_put(np.zeros((comm.world + 1, 2), np.float32))


class TestAllGather:
    def test_replicates(self, comm, rng):
        x, gx = _ranked_input(comm, rng)
        out = comm.all_gather(gx)
        np.testing.assert_array_equal(np.asarray(out), x)
        assert out.sharding.is_fully_replicated


class TestReduceScatter:
    def test_sum(self, comm, rng):
        n = comm.world * 3
        x = rng.standard_normal((comm.world, n)).astype(np.float32)
        out = np.asarray(comm.reduce_scatter(comm.device_put(x)))
        total = x.sum(0)
        want = total.reshape(comm.world, 3)
        np.testing.assert_allclose(out, want, rtol=1e-5)

    def test_bad_payload(self, comm):
        x = comm.device_put(np.zeros((comm.world, comm.world * 2 + 1), np.float32))
        with pytest.raises(ValueError):
            comm.reduce_scatter(x)


class TestAllToAll:
    def test_transpose(self, comm, rng):
        x = rng.standard_normal((comm.world, comm.world, 5)).astype(np.float32)
        out = np.asarray(comm.all_to_all(comm.device_put(x)))
        np.testing.assert_allclose(out, x.transpose(1, 0, 2), rtol=1e-6)


class TestBroadcastPermute:
    def test_broadcast(self, comm, rng):
        x, gx = _ranked_input(comm, rng)
        for root in (0, comm.world - 1):
            out = np.asarray(comm.broadcast(gx, root))
            want = np.broadcast_to(x[root : root + 1], x.shape)
            np.testing.assert_array_equal(out, want)

    def test_ring_shift(self, comm, rng):
        x, gx = _ranked_input(comm, rng)
        out = np.asarray(comm.ring_shift(gx, 1))
        np.testing.assert_array_equal(out, np.roll(x, 1, axis=0))

    def test_send_recv(self, comm, rng):
        x, gx = _ranked_input(comm, rng, payload=(3,))
        out = np.asarray(comm.send_recv(gx, src=0, dst=comm.world - 1))
        assert np.array_equal(out[comm.world - 1], x[0])
        # non-destinations receive zeros (ppermute semantics)
        assert np.array_equal(out[0], np.zeros_like(x[0]))

    def test_barrier(self, comm):
        comm.barrier()


class TestCache:
    def test_compile_cache_hit(self, comm, rng):
        x, gx = _ranked_input(comm, rng)
        comm.all_reduce(gx)
        n = len(comm._cache)
        comm.all_reduce(gx)
        assert len(comm._cache) == n


class TestPlanCacheKeys:
    """PR 7 satellite: the _compiled cache key carries the RESOLVED plan
    (algo + chunks + wire_dtype), never the "auto" spelling — two calls
    that resolve to different plans must not share a compiled fn."""

    def test_auto_resolutions_do_not_share_compiled_fn(
            self, mesh_dp8, rng, monkeypatch):
        from uccl_tpu.utils import config as cfg

        comm = Communicator(mesh_dp8, "dp")
        x = rng.standard_normal((8, 16)).astype(np.float32)
        gx = comm.device_put(x)
        a = np.asarray(comm.all_reduce(gx, algo="auto"))  # small pow2: hd
        monkeypatch.setenv("UCCL_TPU_AR_ALGO", "ring")
        cfg.reset_all()
        try:
            b = np.asarray(comm.all_reduce(gx, algo="auto"))  # forced ring
        finally:
            monkeypatch.delenv("UCCL_TPU_AR_ALGO")
            cfg.reset_all()
        keys = [k for k in comm._cache if k[0] == "ar"]
        assert len(keys) == 2, keys
        assert {k[2] for k in keys} == {"hd", "ring"}
        # key layout: ("ar", op, algo, chunks, shape, dtype, wire_dtype)
        for k in keys:
            assert isinstance(k[3], int) and k[6] is None
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


class TestTorusAlgo:
    def test_torus_matches_xla(self, devices, rng):
        from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(dp=2, tp=4), devices)
        comm = Communicator(mesh, ("dp", "tp"))
        x = comm.device_put(rng.standard_normal((8, 33)).astype(np.float32))
        got = np.asarray(comm.all_reduce(x, algo="torus"))
        want = np.asarray(comm.all_reduce(x, algo="xla"))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_torus_needs_two_axes(self, mesh_dp8, rng):
        comm = Communicator(mesh_dp8, "dp")
        x = comm.device_put(rng.standard_normal((8, 8)).astype(np.float32))
        with pytest.raises(ValueError, match="2-axis"):
            comm.all_reduce(x, algo="torus")
