"""Disagg fan-in over the windowed SACK transport, under injected faults.

The acceptance arm of the windowed-transport PR: TWO PrefillWorkers stream
interleaved KV into ONE DecodeWorker over multipath *Channels* (selective
repeat + per-path steering + receiver-driven pull credit), with drop AND
reorder injected on both prefill endpoints' data planes. Every adopted
request must stay bit-identical to the one-shot oracle — loss is recovered
by the transport, not visible to serving — and the run must actually
exercise the machinery: ≥1 counted retransmission, pull credit granted and
consumed.

Multi-compile (three engines) + native transfer engine => slow-marked;
runs unfiltered in CI/qa.sh like the other disagg arms. The transport
itself is tier-1-covered host-only (tests/test_sack.py) and at channel
level (tests/test_channel.py).
"""

import time

import numpy as np
import pytest

from uccl_tpu.serving import ServingEngine

MAX_SEQ = 32


@pytest.fixture(scope="module")
def dense_setup():
    import jax

    from uccl_tpu.models import dense
    from uccl_tpu.serving import DenseBackend

    cfg = dense.DenseConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=8,
        ffn=64,
    )
    params = dense.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params, DenseBackend


def _oracle(params, cfg, req):
    import jax.numpy as jnp

    from uccl_tpu.models.inference import generate

    toks = generate(params, jnp.asarray(req.prompt)[None], cfg,
                    max_new_tokens=req.max_new_tokens, max_seq=MAX_SEQ)
    return np.asarray(toks)[0, : req.n_generated].tolist()


@pytest.mark.slow
class TestLossyChannelFanIn:
    def test_two_prefill_one_decode_lossy_reordering(self, dense_setup):
        from uccl_tpu.p2p import Endpoint
        from uccl_tpu.serving.disagg import DecodeWorker, add_local_prefill

        cfg, params, DenseBackend = dense_setup
        pes = [ServingEngine(DenseBackend(params, cfg, n_slots=2,
                                          max_seq=MAX_SEQ),
                             prefill_chunk=4) for _ in range(2)]
        de = ServingEngine(DenseBackend(params, cfg, n_slots=4,
                                        max_seq=MAX_SEQ))
        # decode side = the incast actuator: PullPacer grants byte credit
        # across both inbound channels at the configured drain rate
        dw = DecodeWorker(de, Endpoint(), pull_rate_bps=64e6)
        pws = [
            add_local_prefill(dw, pe, transport="channel", n_paths=2,
                              chunk_bytes=8 << 10, pull=True,
                              window_cc="swift")
            for pe in pes
        ]
        for pw in pws:
            assert pw.chan is not None
            pw.chan.retries = 8  # loss-soak budget

        def pump(n_done, done, deadline_s=120.0):
            deadline = time.monotonic() + deadline_s
            while len(done) < n_done:
                for pw in pws:
                    pw.step()
                done.extend(dw.step())
                if time.monotonic() > deadline:
                    raise TimeoutError(f"fan-in stalled at {len(done)}")
            return done

        try:
            # warm (compiles + full wire path), then zero the metrics
            for pw in pws:
                pw.submit(np.zeros(8, np.int32), max_new_tokens=2)
            pump(2, [])
            for eng in pes + [de]:
                eng.reset_metrics()

            # loss + reorder on BOTH prefill data planes (scoped to
            # one-sided data frames: BEGIN/GRANT/FINAL notifs are the
            # reliable control plane, like the reference's ctrl QP)
            for pw in pws:
                pw.ep.set_drop_rate(0.2)
                pw.ep.set_reorder_rate(0.3)

            rng = np.random.default_rng(23)
            prompts = [rng.integers(0, 64, 6 + i).astype(np.int32)
                       for i in range(6)]
            done = []
            for i, p in enumerate(prompts):
                r = pws[i % 2].submit(p, max_new_tokens=4)
                assert r is not None
                for pw in pws:
                    pw.step()
                done.extend(dw.step())
            pump(6, done)
        finally:
            for pw in pws:
                pw.ep.set_drop_rate(0.0)
                pw.ep.set_reorder_rate(0.0)
            rx_chans = list(dw.channels)  # close() releases the list
            dw.close()

        # oracle-exact through injected loss+reorder — the transport
        # recovered every slab bit-exactly or this fails loudly
        assert len(done) == 6
        for r in done:
            assert r.adopted
            assert r.out_tokens == _oracle(params, cfg, r), r.rid
        for eng in pes + [de]:
            assert eng.pool.leaked() == 0

        # the run really exercised the windowed transport:
        retx = sum(pw.chan.retransmitted_chunks for pw in pws)
        assert retx >= 1, "no retransmission counted at 20% injected drop"
        # ...selectively: far fewer retx than total issued chunks
        issued = sum(pw.chan._last_win.stats()["chunks"] for pw in pws)
        assert issued > 0
        # ...and under receiver-driven credit that actually flowed
        assert rx_chans and all(ch.pull_granted > 0 for ch in rx_chans)
        assert all(pw.chan.pull_credit > 0 for pw in pws)
        assert all(pw.chan._pull_sent > 0 for pw in pws)
        # the TTFT transfer leg was measured under incast for every adopt
        assert len(de.metrics.disagg_transfer_s) == 6
        assert all(t >= 0 for t in de.metrics.disagg_transfer_s)

    def test_ttft_transfer_leg_measured(self, dense_setup):
        """The TTFT split survives the channel transport: adopted requests
        carry a nonzero transfer leg (measured under the windowed ship)."""
        from uccl_tpu.p2p import Endpoint
        from uccl_tpu.serving.disagg import DecodeWorker, add_local_prefill

        cfg, params, DenseBackend = dense_setup
        pe = ServingEngine(DenseBackend(params, cfg, n_slots=2,
                                        max_seq=MAX_SEQ), prefill_chunk=4)
        de = ServingEngine(DenseBackend(params, cfg, n_slots=2,
                                        max_seq=MAX_SEQ))
        dw = DecodeWorker(de, Endpoint())
        pw = add_local_prefill(dw, pe, transport="channel", n_paths=2,
                               chunk_bytes=8 << 10)
        pw.submit(np.arange(8, dtype=np.int32) % 64, max_new_tokens=3)
        done = []
        deadline = time.monotonic() + 120.0
        while len(done) < 1:
            pw.step()
            done.extend(dw.step())
            assert time.monotonic() < deadline
        (r,) = done
        assert r.adopted
        assert len(de.metrics.disagg_transfer_s) == 1
        assert de.metrics.disagg_transfer_s[0] >= 0
        assert r.out_tokens == _oracle(params, cfg, r)


@pytest.mark.slow
class TestFanIn3x2BitExact:
    def test_three_prefill_two_decode_bit_exact(self, dense_setup):
        """The N×M plane (ISSUE 19): THREE prefill engines × TWO decode
        workers, six channel bonds through shared per-engine fan-out
        sinks. Every adopted request — spread so each engine serves both
        decode pools and each pool adopts from all three engines — must
        stay bit-identical to the one-shot oracle, and tenants must ride
        BEGIN to the adopting side's per-tenant series."""
        from uccl_tpu.p2p import Endpoint
        from uccl_tpu.serving.disagg import (
            DecodeWorker, _ChunkFanout, add_local_prefill,
        )

        cfg, params, DenseBackend = dense_setup
        pes = [ServingEngine(DenseBackend(params, cfg, n_slots=2,
                                          max_seq=MAX_SEQ),
                             prefill_chunk=4) for _ in range(3)]
        des = [ServingEngine(DenseBackend(params, cfg, n_slots=4,
                                          max_seq=MAX_SEQ))
               for _ in range(2)]
        dws = [DecodeWorker(de, Endpoint(), pull_rate_bps=64e6)
               for de in des]
        pws = {}
        for i, pe in enumerate(pes):
            for j, dw in enumerate(dws):
                pws[(i, j)] = add_local_prefill(
                    dw, pe, transport="channel", n_paths=2,
                    chunk_bytes=8 << 10, pull=True)
        for pe in pes:
            assert isinstance(pe.chunk_sink, _ChunkFanout)
            assert len(pe.chunk_sink.sinks) == 2

        def pump(n_done, done, deadline_s=180.0):
            deadline = time.monotonic() + deadline_s
            while len(done) < n_done:
                for pw in pws.values():
                    pw.step()
                for dw in dws:
                    done.extend(dw.step())
                if time.monotonic() > deadline:
                    raise TimeoutError(f"3x2 stalled at {len(done)}")
            return done

        try:
            rng = np.random.default_rng(29)
            prompts = [rng.integers(0, 64, 6 + i).astype(np.int32)
                       for i in range(6)]
            done = []
            for i, p in enumerate(prompts):
                r = pws[(i % 3, i % 2)].submit(
                    p, max_new_tokens=4,
                    tenant="acme" if i % 2 else "default")
                assert r is not None
                for pw in pws.values():
                    pw.step()
                for dw in dws:
                    done.extend(dw.step())
            pump(6, done)
        finally:
            for dw in dws:
                dw.close()

        assert len(done) == 6
        for r in done:
            assert r.adopted
            assert r.out_tokens == _oracle(params, cfg, r), r.rid
        # the spread was real: both decode pools adopted 3 requests
        for de in des:
            assert de.metrics.snapshot()["completed"] == 3
            assert de.pool.leaked() == 0
        for pe in pes:
            assert pe.pool.leaked() == 0
        # tenants rode BEGIN across all six bonds
        assert sorted(r.tenant for r in done) \
            == ["acme"] * 3 + ["default"] * 3
