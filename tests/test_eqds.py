"""EQDS-style receiver-driven pull mode (reference include/cc/eqds.h; pacer
collective/rdma/eqds.h:93): senders issue chunks only under receiver
credit, a PullPacer fair-shares the receiver's downlink across inbound
channels, and credits ride the isolated probe path as one-sided writes."""

import threading
import time

import numpy as np
import pytest

from uccl_tpu.p2p import Endpoint, PullPacer
from uccl_tpu.p2p.channel import Channel


def _chan_pair(server, client, n_paths=2):
    result = {}
    t = threading.Thread(
        target=lambda: result.setdefault("c", Channel.accept(server))
    )
    t.start()
    c_chan = Channel.connect(client, "127.0.0.1", server.port, n_paths=n_paths)
    t.join(timeout=20)
    return result["c"], c_chan


class TestPullMode:
    def test_write_blocks_until_credit(self):
        """No credit => the pull-mode write stalls; granting releases it at
        chunk granularity."""
        with Endpoint(n_engines=2) as server, Endpoint(n_engines=2) as client:
            s_chan, c_chan = _chan_pair(server, client)
            c_chan.chunk_bytes = 64 << 10
            c_chan.enable_pull_sender()
            dst = np.zeros(256 << 10, np.uint8)
            fifo = server.advertise(server.reg(dst))
            src = np.arange(256 << 10, dtype=np.uint8) % 251

            done = threading.Event()

            def tx():
                c_chan.write(src, fifo, timeout_ms=30000)
                done.set()

            t = threading.Thread(target=tx)
            t.start()
            time.sleep(0.15)
            assert not done.is_set(), "write proceeded without any credit"
            s_chan.grant_credit(128 << 10)  # half: still blocked
            time.sleep(0.2)
            assert not done.is_set(), "write finished on partial credit"
            s_chan.grant_credit(128 << 10)  # remainder
            t.join(timeout=20)
            assert done.is_set()
            np.testing.assert_array_equal(dst, src)
            assert c_chan.pull_credit == 256 << 10
            assert s_chan.pull_granted == 256 << 10

    def test_credit_stall_times_out(self):
        from uccl_tpu.p2p.channel import _CREDIT_STALL

        with Endpoint(n_engines=2) as server, Endpoint(n_engines=2) as client:
            s_chan, c_chan = _chan_pair(server, client)
            c_chan.enable_pull_sender()
            dst = np.zeros(4096, np.uint8)
            fifo = server.advertise(server.reg(dst))
            base = _CREDIT_STALL.total()
            with pytest.raises(TimeoutError, match="pull credit stalled"):
                c_chan.write(np.ones(4096, np.uint8), fifo, timeout_ms=300)
            # the stall is VISIBLE: ~0.3 s landed on the counter
            assert _CREDIT_STALL.total() - base >= 0.25

    def test_credit_gauges_track_grant_and_consumption(self):
        from uccl_tpu.p2p.channel import _CREDIT_CONSUMED, _CREDIT_GRANTED

        with Endpoint(n_engines=2) as server, Endpoint(n_engines=2) as client:
            s_chan, c_chan = _chan_pair(server, client)
            c_chan.chunk_bytes = 16 << 10
            c_chan.enable_pull_sender()
            dst = np.zeros(64 << 10, np.uint8)
            fifo = server.advertise(server.reg(dst))
            src = np.arange(64 << 10, dtype=np.uint8) % 251
            s_chan.grant_credit(64 << 10)
            c_chan.write(src, fifo, timeout_ms=20000)
            np.testing.assert_array_equal(dst, src)
            granted = {labels.get("conn"): v
                       for labels, v in _CREDIT_GRANTED.samples()}
            consumed = {labels.get("conn"): v
                        for labels, v in _CREDIT_CONSUMED.samples()}
            assert granted[str(s_chan.conns[0])] == 64 << 10
            assert consumed[str(c_chan.conns[0])] == 64 << 10

    def test_pacer_rate_bounds_transfer(self):
        """8 MB at a 32 MB/s grant rate cannot finish in under ~200 ms (the
        pacer is the clock; generous bound for a 1-core sandbox)."""
        with Endpoint(n_engines=2) as server, Endpoint(n_engines=2) as client:
            s_chan, c_chan = _chan_pair(server, client)
            c_chan.chunk_bytes = 256 << 10
            c_chan.enable_pull_sender()
            total = 8 << 20
            dst = np.zeros(total, np.uint8)
            fifo = server.advertise(server.reg(dst))
            src = (np.arange(total) % 256).astype(np.uint8)
            pacer = PullPacer(32e6, tick_s=0.002)
            pacer.attach(s_chan)
            pacer.start()
            try:
                t0 = time.perf_counter()
                c_chan.write(src, fifo, timeout_ms=60000)
                dt = time.perf_counter() - t0
            finally:
                pacer.stop()
            np.testing.assert_array_equal(dst, src)
            assert dt > 0.2, f"8MB at 32MB/s finished in {dt*1e3:.0f}ms"

    def test_pacer_fair_shares_incast(self):
        """Two pull-mode senders into one receiver: the pacer splits grants
        evenly, so granted totals track each other."""
        with Endpoint(n_engines=2) as server, \
             Endpoint(n_engines=2) as c1, Endpoint(n_engines=2) as c2:
            s1, ch1 = _chan_pair(server, c1)
            s2, ch2 = _chan_pair(server, c2)
            for ch in (ch1, ch2):
                ch.chunk_bytes = 128 << 10
                ch.enable_pull_sender()
            total = 2 << 20
            d1 = np.zeros(total, np.uint8)
            d2 = np.zeros(total, np.uint8)
            f1 = server.advertise(server.reg(d1))
            f2 = server.advertise(server.reg(d2))
            src = (np.arange(total) % 256).astype(np.uint8)
            pacer = PullPacer(64e6, tick_s=0.002)
            pacer.attach(s1)
            pacer.attach(s2)
            pacer.start()
            try:
                ts = [
                    threading.Thread(
                        target=lambda ch=ch, f=f: ch.write(src, f, timeout_ms=60000)
                    )
                    for ch, f in ((ch1, f1), (ch2, f2))
                ]
                [t.start() for t in ts]
                [t.join(timeout=60) for t in ts]
            finally:
                pacer.stop(flush_bytes=total)
            np.testing.assert_array_equal(d1, src)
            np.testing.assert_array_equal(d2, src)
            g1, g2 = s1.pull_granted, s2.pull_granted
            assert abs(g1 - g2) <= max(g1, g2) * 0.25 + (2 << 20), (g1, g2)

    def test_normal_channels_unaffected(self):
        """Channels that never enable pull mode keep push semantics."""
        with Endpoint(n_engines=2) as server, Endpoint(n_engines=2) as client:
            s_chan, c_chan = _chan_pair(server, client)
            dst = np.zeros(4096, np.uint8)
            fifo = server.advertise(server.reg(dst))
            src = np.ones(4096, np.uint8)
            c_chan.write(src, fifo)
            np.testing.assert_array_equal(dst, src)


class TestPullReenable:
    def test_reenable_does_not_inherit_stale_credit(self):
        """Credits are cumulative per connection; a re-enabled sender must
        baseline at the current grant, not treat history as fresh credit."""
        with Endpoint(n_engines=2) as server, Endpoint(n_engines=2) as client:
            s_chan, c_chan = _chan_pair(server, client)
            c_chan.chunk_bytes = 16 << 10
            c_chan.enable_pull_sender()
            dst = np.zeros(64 << 10, np.uint8)
            fifo = server.advertise(server.reg(dst))
            src = np.arange(64 << 10, dtype=np.uint8) % 251
            s_chan.grant_credit(64 << 10)
            c_chan.write(src, fifo, timeout_ms=20000)  # consumes all credit
            np.testing.assert_array_equal(dst, src)

            c_chan.disable_pull_sender()
            c_chan.enable_pull_sender()  # baseline = 64 KiB already granted
            done = threading.Event()

            def tx():
                c_chan.write(src, fifo, timeout_ms=30000)
                done.set()

            t = threading.Thread(target=tx)
            t.start()
            time.sleep(0.2)
            assert not done.is_set(), "re-enable inherited stale credit"
            s_chan.grant_credit(64 << 10)  # NEW credit releases it
            t.join(timeout=20)
            assert done.is_set()


class TestPacerFlush:
    def test_stop_flush_releases_stragglers(self):
        """stop(flush_bytes=N) hands attached channels a final allowance so
        an in-flight sender finishes instead of stalling when the pacer
        goes away."""
        with Endpoint(n_engines=2) as server, Endpoint(n_engines=2) as client:
            s_chan, c_chan = _chan_pair(server, client)
            c_chan.chunk_bytes = 64 << 10
            c_chan.enable_pull_sender()
            dst = np.zeros(256 << 10, np.uint8)
            fifo = server.advertise(server.reg(dst))
            src = (np.arange(256 << 10) % 251).astype(np.uint8)
            pacer = PullPacer(1.0, tick_s=0.01)  # ~zero rate: never enough
            pacer.attach(s_chan)
            pacer.start()
            done = threading.Event()

            def tx():
                c_chan.write(src, fifo, timeout_ms=30000)
                done.set()

            t = threading.Thread(target=tx)
            t.start()
            time.sleep(0.3)
            assert not done.is_set()  # starved by the near-zero rate
            pacer.stop(flush_bytes=1 << 20)  # final allowance
            t.join(timeout=20)
            assert done.is_set()
            np.testing.assert_array_equal(dst, src)
