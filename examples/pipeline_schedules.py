"""Pipeline schedule comparison: GPipe vs 1F1B vs interleaved 1F1B.

Trains the same stacked-MLP pipeline under all three schedules on a virtual
pp mesh and reports, per schedule: loss parity against sequential autodiff,
the slot-synchronous span (the bubble), and the peak stashed-microbatch
liveness — the trade every pipeline framework makes (GPipe: autodiff
simplicity, O(M) liveness; 1F1B: bounded liveness; interleaved: ~V-fold
smaller bubble for V-fold more, smaller, stashes).

The reference's PP story is one-sided zero-SM activation sends
(experimental/lite/lite-ep/README.md:24); here every hop is a lax.ppermute
the compiler overlaps with compute, and the schedules are static tables
driven by one lax.scan (parallel/pipeline.py).

Usage: python examples/pipeline_schedules.py [--devices 4] [--chunks 2]
       [--microbatches 8]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=4)
    ap.add_argument("--chunks", type=int, default=2)
    ap.add_argument("--microbatches", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--mb-batch", type=int, default=4)
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.devices}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh
    from uccl_tpu.parallel.pipeline import (
        _simulate_1f1b,
        _simulate_interleaved,
        gpipe_spmd,
        interleaved_1f1b,
        one_f_one_b,
    )

    p, v, m, h, b = (
        args.devices, args.chunks, args.microbatches, args.hidden,
        args.mb_batch,
    )
    L = p * v
    mesh = make_mesh(MeshConfig(pp=p), jax.devices()[:p])
    rng = np.random.default_rng(0)
    ws = rng.standard_normal((L, h, h)).astype(np.float32) * 0.3
    bs = rng.standard_normal((L, h)).astype(np.float32) * 0.1
    xmb = rng.standard_normal((m, b, h)).astype(np.float32)

    def stage(params, x):
        w, bias = params
        return jnp.tanh(x @ w + bias)

    def loss(y):
        return jnp.sum(y * y)

    # ---- sequential autodiff reference
    def total(ws, bs):
        acc = 0.0
        for k in range(m):
            x = xmb[k]
            for i in range(L):
                x = stage((ws[i], bs[i]), x)
            acc = acc + loss(x)
        return acc

    want = float(jax.jit(total)(ws, bs))

    # ---- GPipe (autodiff through the scan; only a p-stage pipeline, so run
    # it with v stages fused per device to cover the same L layers)
    def gpipe_loss(w, b_, x):
        wl, bl = w[0], b_[0]  # local shard: [v, h, h], [v, h]

        def stage_fn(xm):
            y = xm
            for c in range(v):
                y = stage((wl[c], bl[c]), y)
            return y, jnp.zeros(())

        out, _ = gpipe_spmd(stage_fn, x, "pp")
        return jnp.sum(out * out)

    wg = ws.reshape(p, v, h, h)  # contiguous fused stages for gpipe
    bg = bs.reshape(p, v, h)
    gp = jax.jit(
        jax.shard_map(
            gpipe_loss, mesh=mesh,
            in_specs=(P("pp"), P("pp"), P(None)),
            out_specs=P(), check_vma=False,
        )
    )
    got_gpipe = float(gp(wg, bg, xmb))

    # ---- manual 1F1B (p fused stages, like gpipe)
    def fused_stage(params, x):
        w, b_ = params
        y = x
        for c in range(v):
            y = stage((w[c], b_[c]), y)
        return y

    def f1b(w, b_, x):
        l, _ = one_f_one_b(fused_stage, loss, (w[0], b_[0]), x, "pp")
        return l

    got_1f1b = float(
        jax.jit(
            jax.shard_map(
                f1b, mesh=mesh,
                in_specs=(P("pp"), P("pp"), P(None)),
                out_specs=P(), check_vma=False,
            )
        )(wg, bg, xmb)
    )

    # ---- interleaved 1F1B (true L = p*v logical stages, chunked assignment)
    wi = np.moveaxis(ws.reshape(v, p, h, h), 1, 0)  # [P, V, h, h]
    bi = np.moveaxis(bs.reshape(v, p, h), 1, 0)

    def inter(w, b_, x):
        l, _ = interleaved_1f1b(
            stage, loss, (w[0], b_[0]), x, n_chunks=v, axis="pp"
        )
        return l

    got_inter = float(
        jax.jit(
            jax.shard_map(
                inter, mesh=mesh,
                in_specs=(P("pp"), P("pp"), P(None)),
                out_specs=P(), check_vma=False,
            )
        )(wi, bi, xmb)
    )

    # ---- schedule shape: spans and liveness
    do_f, _, do_b, _ = _simulate_1f1b(m, p)
    span_1f1b = do_f.shape[0]
    sched_i = _simulate_interleaved(m, p, v)
    span_inter = sched_i["do_f"].shape[0] / v  # slots are 1/v the work
    span_gpipe = 2 * (m + p - 1)  # fwd scan + bwd scan of the same length

    print(f"layers={L} (p={p} x v={v}), microbatches={m}")
    print(f"sequential loss  {want:.6f}")
    for name, got in (
        ("gpipe", got_gpipe), ("1f1b", got_1f1b), ("interleaved", got_inter)
    ):
        ok = "OK" if abs(got - want) < 1e-3 * abs(want) else "MISMATCH"
        print(f"  {name:<12} loss {got:.6f}  [{ok}]")
    print("schedule span (full-stage units; lower = smaller bubble):")
    print(f"  gpipe        {span_gpipe}")
    print(f"  1f1b         {span_1f1b}  (same span, bounded liveness)")
    print(f"  interleaved  {span_inter:.2f}  (ramp / v)")
    print(f"liveness: gpipe stashes O(M)={m} microbatches/stage; 1f1b <= "
          f"min(M,P)={min(m, p)}; interleaved stash slots={sched_i['n_stash']}"
          f" (1/v-sized chunks)")


if __name__ == "__main__":
    main()
