"""Minimal launched worker: DCN allreduce across launcher-spawned ranks,
then a full hierarchical (ICI reduce-scatter -> DCN ring -> ICI all-gather)
allreduce with each process owning its own virtual 4-device mesh.

Run: python scripts/launch.py --nproc 3 --no-jax-dist examples/launch_allreduce.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=4").strip()

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from uccl_tpu.collective import Communicator
from uccl_tpu.collective.hierarchical import DcnGroup, hierarchical_all_reduce
from uccl_tpu.parallel.distributed import initialize_from_env
from uccl_tpu.parallel.mesh import MeshConfig, make_mesh


def main():
    sess = initialize_from_env()
    g = DcnGroup(sess, n_paths=2)
    try:
        x = np.full(64, float(sess.rank + 1), np.float32)
        out = g.all_reduce(x)
        want = sum(range(1, sess.world + 1))
        assert abs(out[0] - want) < 1e-5, (out[0], want)
        print(f"rank {sess.rank}/{sess.world}: allreduce sum={out[0]:.1f} OK")

        # hierarchical: this process's 4-device mesh is its "pod"
        mesh = make_mesh(MeshConfig(dp=4), jax.devices()[:4])
        comm = Communicator(mesh, "dp")
        local = comm.device_put(
            np.full((4, 32), float(sess.rank + 1), np.float32)
        )
        result = np.asarray(hierarchical_all_reduce(comm, g, local))
        want_h = 4 * want  # 4 local members x sum over pods
        assert np.allclose(result, want_h), (result[0, 0], want_h)
        print(f"rank {sess.rank}/{sess.world}: hierarchical sum={result[0,0]:.1f} OK")
    finally:
        g.close()
        sess.close()


if __name__ == "__main__":
    main()
