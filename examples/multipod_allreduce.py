"""Cross-pod hierarchical allreduce: ICI inside each pod, DCN between pods.

Two processes each own a "pod" (a 4-device mesh); gradients reduce-scatter
over the pod's ICI, the shards allreduce across pods through the transfer
engine (ring over multipath channels), and the result redistributes — the
reference's cross-rack story (README.md:29 "cross-rack AllReduce beats NCCL")
re-expressed for TPU pods.

Usage: python examples/multipod_allreduce.py [--pods 2] [--elems 4096]
Runs on CPU (each process forces a virtual 4-device mesh) so it works anywhere.
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LOCAL_DEVICES = 4


def pod_main(rank, world, store_port, elems, result_q):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={LOCAL_DEVICES}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from uccl_tpu.collective import Communicator
    from uccl_tpu.collective.hierarchical import DcnGroup, hierarchical_all_reduce
    from uccl_tpu.p2p.store import StoreClient
    from uccl_tpu.parallel.distributed import Session
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=LOCAL_DEVICES))
    comm = Communicator(mesh, "dp")
    sess = Session(rank=rank, world=world, store=StoreClient("127.0.0.1", store_port))
    dcn = DcnGroup(sess, n_paths=2)

    # every mesh member of every pod contributes a distinct buffer
    rng = np.random.default_rng(rank)
    x = rng.standard_normal((LOCAL_DEVICES, elems)).astype(np.float32)
    out = np.asarray(hierarchical_all_reduce(comm, dcn, comm.device_put(x)))

    result_q.put((rank, x, out[0]))  # row 0 == every row (replicated result)
    dcn.close()
    sess.store.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=2)
    ap.add_argument("--elems", type=int, default=4096)
    args = ap.parse_args()

    from uccl_tpu.p2p.store import StoreServer

    server = StoreServer()
    ctx = mp.get_context("spawn")
    result_q = ctx.Queue()
    procs = [
        ctx.Process(
            target=pod_main,
            args=(r, args.pods, server.port, args.elems, result_q),
        )
        for r in range(args.pods)
    ]
    [p.start() for p in procs]
    results = [result_q.get(timeout=300) for _ in procs]
    [p.join(timeout=60) for p in procs]
    server.close()

    import numpy as np

    want = np.sum([x for _, x, _ in results], axis=0).sum(axis=0)  # global sum
    ok = all(np.allclose(out, want, rtol=1e-4, atol=1e-5) for _, _, out in results)
    print(
        f"hierarchical allreduce across {args.pods} pods x {LOCAL_DEVICES} devices: "
        f"{'OK' if ok else 'MISMATCH'}"
    )
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
