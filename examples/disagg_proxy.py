"""Disaggregated serving PROXY: the vLLM-style prefill/decode router.

The reference ships an HTTP proxy that (1) sends each request to the
prefill deployment with max_tokens=1, (2) lifts ``kv_transfer_params``
out of the prefill response, (3) forwards the request plus those params
to the decode deployment, whose NIXL connector pulls the KV cache over
RDMA (ep/bench/vllm/disagg_proxy.py:13-15,64-67). This example is that
router over this framework's stack:

* prefill worker — runs the prompt, registers the KV cache through
  ``XferEndpoint.register_memory`` and answers with kv_transfer_params =
  {endpoint metadata, serialized descriptors, length, first token},
* decode worker — one-sided READs the cache windows (the NIXL-pull
  analog), then generates,
* proxy — plain stdlib HTTP front doing the two-step routing; the client
  sees one /v1/completions-shaped call.

The run asserts the disaggregated tokens match single-worker generation
exactly. Usage: python examples/disagg_proxy.py [--new-tokens 12]
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# one shared disaggregation fixture: model config, prompt shape, and the
# CPU-forcing gate live in disagg_kv so the two exact-match demos can
# never drift apart
from examples.disagg_kv import BATCH, MAX_SEQ, _make, _maybe_force_cpu


def _model():
    return _make(seed=0)


def _post(url: str, payload: dict, timeout: float = 120.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        # workers reply 500 with a JSON {"error": ...} body — pass it
        # through so the proxy (and the client) see the cause, mirroring
        # the reference proxy's error forwarding (disagg_proxy.py:56-59)
        try:
            return json.loads(e.read().decode())
        except Exception:
            return {"error": f"HTTP {e.code}"}


def _serve(app, port_q):
    """Bind an ephemeral JSON HTTP server around ``app(path, payload)``,
    report the port, serve forever (shared by all three workers)."""
    srv = HTTPServer(("127.0.0.1", 0), _JsonHandler)
    srv.app = app  # type: ignore[attr-defined]
    port_q.put(srv.server_address[1])
    srv.serve_forever()


class _JsonHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # quiet
        pass

    def _reply(self, obj: dict, code: int = 200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802 (stdlib naming)
        n = int(self.headers.get("Content-Length", "0"))
        payload = json.loads(self.rfile.read(n).decode() or "{}")
        try:
            self._reply(self.server.app(self.path, payload))  # type: ignore
        except Exception as e:  # surface worker errors to the proxy
            self._reply({"error": repr(e)}, code=500)


def prefill_worker(port_q):
    """POST /prefill {"prompt_ids"} -> kv_transfer_params (the reference's
    max_tokens=1 leg: populate the cache, describe how to pull it)."""
    _maybe_force_cpu()
    import jax.numpy as jnp

    from uccl_tpu.models.inference import prefill
    from uccl_tpu.p2p import XferEndpoint

    cfg, params = _model()
    xp = XferEndpoint(n_engines=1)

    def app(path, payload):
        assert path == "/prefill", path
        prompt = np.asarray(payload["prompt_ids"], np.int32)
        logits, cache = prefill(params, jnp.asarray(prompt), cfg, MAX_SEQ)
        first = np.asarray(
            jnp.argmax(logits, axis=-1), np.int32
        )
        k_host = np.ascontiguousarray(np.asarray(cache.k, np.float32))
        v_host = np.ascontiguousarray(np.asarray(cache.v, np.float32))
        # register + advertise; the endpoint's registry pins the arrays
        # for the worker's lifetime (a production server would
        # deregister_memory once the decode side confirms the pull)
        descs = xp.register_memory([k_host, v_host])
        return {
            "first_token": first.tolist(),
            "kv_transfer_params": {
                "metadata": xp.get_metadata().decode(),
                "descs": xp.get_serialized_descs(descs).decode(),
                "kv_shape": list(k_host.shape),
                "length": int(cache.length),
            },
        }

    def accept_loop():  # serve decode-worker connections as they dial in
        while True:
            try:
                xp.accept(timeout_ms=1000)
            except TimeoutError:
                continue

    threading.Thread(target=accept_loop, daemon=True).start()

    _serve(app, port_q)


def decode_worker(port_q):
    """POST /decode {"max_tokens", "first_token", "kv_transfer_params"} ->
    generated tokens. Pulls the KV cache with one-sided READs (the NIXL
    do_remote_prefill pull, reference :64-67)."""
    _maybe_force_cpu()
    import jax.numpy as jnp

    from uccl_tpu.models.inference import KVCache
    from uccl_tpu.p2p import XferEndpoint
    from uccl_tpu.serving.disagg import decode_continue

    cfg, params = _model()
    xp = XferEndpoint(n_engines=1)
    conns = {}  # prefill metadata -> conn id (dial once, reuse)

    def app(path, payload):
        assert path == "/decode", path
        ktp = payload["kv_transfer_params"]
        md = ktp["metadata"].encode()
        if md not in conns:
            ok, cid = xp.add_remote_endpoint(md)
            assert ok, "dial prefill failed"
            conns[md] = cid
        cid = conns[md]
        shape = tuple(ktp["kv_shape"])
        k_host = np.zeros(shape, np.float32)
        v_host = np.zeros(shape, np.float32)
        remote = XferEndpoint.deserialize_descs(ktp["descs"].encode())
        xids = xp.transfer(cid, "READ", [k_host, v_host], remote)
        assert xp.wait(xids), "KV pull failed"
        cache = KVCache(
            jnp.asarray(k_host), jnp.asarray(v_host),
            jnp.int32(ktp["length"]),
        )
        tok = jnp.asarray(np.asarray(payload["first_token"], np.int32))
        toks = decode_continue(
            params, cfg, cache, tok, int(payload["max_tokens"])
        )
        return {"tokens": toks.tolist()}

    _serve(app, port_q)


def proxy_worker(port_q, prefill_port, decode_port):
    """The router itself — the reference proxy's two-step flow."""

    def app(path, payload):
        assert path == "/v1/completions", path
        # Step 1: prefill leg (max_tokens=1 equivalent: the prompt pass)
        pre = _post(
            f"http://127.0.0.1:{prefill_port}/prefill",
            {"prompt_ids": payload["prompt_ids"]},
        )
        if "error" in pre:
            return pre
        # Step 2: decode leg with the lifted kv_transfer_params
        dec = _post(
            f"http://127.0.0.1:{decode_port}/decode",
            {
                "max_tokens": payload.get("max_tokens", 8),
                "first_token": pre["first_token"],
                "kv_transfer_params": pre["kv_transfer_params"],
            },
        )
        return dec

    _serve(app, port_q)


def _single_worker_reference(prompt, new_tokens):
    _maybe_force_cpu()
    from uccl_tpu.serving.disagg import oneshot_reference

    cfg, params = _model()
    return oneshot_reference(params, cfg, prompt, new_tokens, MAX_SEQ)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--new-tokens", type=int, default=12)
    args = ap.parse_args()

    qs = [mp.Queue() for _ in range(3)]
    pre = mp.Process(target=prefill_worker, args=(qs[0],), daemon=True)
    dec = mp.Process(target=decode_worker, args=(qs[1],), daemon=True)
    pre.start()
    dec.start()
    pre_port = qs[0].get(timeout=60)
    dec_port = qs[1].get(timeout=60)
    prox = mp.Process(
        target=proxy_worker, args=(qs[2], pre_port, dec_port), daemon=True
    )
    prox.start()
    proxy_port = qs[2].get(timeout=60)

    prompt = np.random.default_rng(7).integers(
        0, 128, (BATCH, 8)
    ).astype(np.int32)
    out = _post(
        f"http://127.0.0.1:{proxy_port}/v1/completions",
        {"prompt_ids": prompt.tolist(), "max_tokens": args.new_tokens},
        timeout=300.0,
    )
    if "error" in out:
        print("worker error:", out["error"])
        return 1
    got = np.asarray(out["tokens"], np.int32)
    want = _single_worker_reference(prompt, args.new_tokens)
    ok = np.array_equal(got, want)
    print(f"disagg proxy: {got.shape[1]} tokens/seq via prefill->decode "
          f"routing; exact match vs single worker: {ok}")
    for p in (pre, dec, prox):
        p.terminate()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
