"""RL-style weight transfer between actors over the P2P engine.

Mirrors the reference's Ray consumption pattern (p2p/tests/test_ray_api.py:
actors register tensor lists, swap serialized descriptors + endpoint
metadata out-of-band, then one-sided WRITE the weights): a "trainer" actor
pushes updated weights straight into an "inference" actor's registered
buffers — no copies through the object store, which is the point of the
API for RL frameworks.

Runs under Ray when it is installed (`pip install ray`); in this
environment (no ray) the SAME actor class runs in plain multiprocessing —
the transfer code path is identical, only the actor scheduling differs.

    python examples/ray_weight_transfer.py
"""

from __future__ import annotations

import multiprocessing as mp
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    import ray
except ImportError:  # this image ships no ray; the mp fallback runs
    ray = None


class InferenceActor:
    """Owns live model weights; exposes registered buffers for the trainer
    to write into (the reference's receiver side)."""

    def __init__(self):
        from uccl_tpu.p2p import XferEndpoint

        self.xp = XferEndpoint()
        self.weights = [
            np.zeros((256, 256), np.float32),
            np.zeros((256,), np.float32),
        ]
        self.descs = self.xp.register_memory(self.weights)

    def handshake(self) -> tuple:
        """(endpoint metadata, serialized descriptors) for the trainer."""
        return (
            self.xp.get_metadata(),
            self.xp.get_serialized_descs(self.descs),
        )

    def wait_update(self) -> float:
        """Accept the trainer's conn, wait for its WEIGHTS_READY notif,
        return a checksum of the received weights."""
        assert self.xp.accept() >= 0
        import time

        for _ in range(600):
            notifs = self.xp.get_notifs()
            if any(p == b"WEIGHTS_READY" for _, p in notifs):
                break
            time.sleep(0.05)
        else:
            raise TimeoutError("no WEIGHTS_READY notification")
        return float(sum(float(np.abs(w).sum()) for w in self.weights))

    def close(self):
        self.xp.close()


class TrainerActor:
    """Produces new weights and pushes them (the reference's sender)."""

    def __init__(self, metadata: bytes, desc_blob: bytes):
        from uccl_tpu.p2p import XferEndpoint

        self.xp = XferEndpoint()
        ok, self.conn = self.xp.add_remote_endpoint(metadata)
        assert ok, "connect failed"
        self.remote_descs = self.xp.deserialize_descs(desc_blob)

    def push_weights(self) -> float:
        rng = np.random.default_rng(7)
        new_w = [
            rng.standard_normal((256, 256)).astype(np.float32),
            rng.standard_normal((256,)).astype(np.float32),
        ]
        xids = self.xp.transfer(self.conn, "WRITE", new_w, self.remote_descs)
        assert self.xp.wait(xids)
        self.xp.send_notif(self.conn, b"WEIGHTS_READY")
        return float(sum(float(np.abs(w).sum()) for w in new_w))

    def close(self):
        self.xp.close()


def _mp_inference(q_out):
    actor = InferenceActor()
    q_out.put(actor.handshake())
    got = actor.wait_update()
    q_out.put(got)
    actor.close()


def main():
    if ray is not None:
        ray.init(num_cpus=2)
        Inf = ray.remote(InferenceActor)
        inf = Inf.remote()
        metadata, blob = ray.get(inf.handshake.remote())
        pending = inf.wait_update.remote()
        trainer = TrainerActor(metadata, blob)
        sent = trainer.push_weights()
        got = ray.get(pending)
        trainer.close()
        ray.shutdown()
    else:
        q_out = mp.Queue()
        proc = mp.Process(target=_mp_inference, args=(q_out,))
        proc.start()
        metadata, blob = q_out.get(timeout=30)
        trainer = TrainerActor(metadata, blob)
        sent = trainer.push_weights()
        got = q_out.get(timeout=60)
        trainer.close()
        proc.join(timeout=20)
    ok = abs(sent - got) < 1e-3 * max(1.0, abs(sent))
    print(f"weight transfer: sent-checksum={sent:.3f} "
          f"received-checksum={got:.3f} {'OK' if ok else 'MISMATCH'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
