"""Data-parallel training with explicit Communicator gradient sync.

The analog of the reference's examples/ddp_train.py (PyTorch DDP over the
UCCL NCCL plugin): per-replica forward/backward, then an explicit allreduce of
gradients through the collectives layer — the same contract DDP has with NCCL,
expressed over the mesh. A small CNN classifier on synthetic data.

Usage: python examples/ddp_train.py [--devices N] [--steps 20] [--algo xla|ring]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--algo", default="xla", choices=["xla", "ring"])
    args = ap.parse_args()

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    import jax.numpy as jnp
    import numpy as np
    import optax

    from uccl_tpu.collective import Communicator
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    n = len(jax.devices())
    mesh = make_mesh(MeshConfig(dp=n))
    comm = Communicator(mesh, "dp")

    # --- tiny CNN (NCHW) on synthetic 16x16 10-class data -----------------
    def init(key):
        k = jax.random.split(key, 4)
        return {
            "conv1": jax.random.normal(k[0], (16, 3, 3, 3)) * 0.1,
            "conv2": jax.random.normal(k[1], (32, 16, 3, 3)) * 0.1,
            "fc_w": jax.random.normal(k[2], (32 * 4 * 4, 10)) * 0.05,
            "fc_b": jnp.zeros((10,)),
        }

    def model(p, x):
        x = jax.lax.conv_general_dilated(x, p["conv1"], (2, 2), "SAME")
        x = jax.nn.relu(x)
        x = jax.lax.conv_general_dilated(x, p["conv2"], (2, 2), "SAME")
        x = jax.nn.relu(x)
        return x.reshape(x.shape[0], -1) @ p["fc_w"] + p["fc_b"]

    def loss_fn(p, x, y):
        logits = model(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    tx = optax.sgd(0.05, momentum=0.9)
    params = init(jax.random.PRNGKey(0))
    opt = tx.init(params)
    w = comm.world
    # per-replica grads: each row of the leading dim is one replica's local
    # gradient over its batch shard (the DDP contract)
    replica_grads = jax.jit(
        jax.vmap(jax.value_and_grad(loss_fn), in_axes=(None, 0, 0))
    )
    apply_fn = jax.jit(
        lambda p, o, g: (lambda u, o2: (optax.apply_updates(p, u), o2))(
            *tx.update(g, o, p)
        )
    )

    def allreduce_grads(grads):
        """Average per-replica gradients through the comm layer: flatten every
        leaf into one [world, K] bucket (DDP-style bucketing), one fused
        allreduce, unflatten."""
        leaves, treedef = jax.tree.flatten(grads)
        flat = jnp.concatenate([l.reshape(w, -1) for l in leaves], axis=1)
        avg = comm.all_reduce(comm.device_put(flat), algo=args.algo)[0] / w
        out, i = [], 0
        for l in leaves:
            k = l[0].size
            out.append(avg[i : i + k].reshape(l.shape[1:]))
            i += k
        return jax.tree.unflatten(treedef, out)

    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    b_local = max(1, args.batch // w)
    for step in range(args.steps):
        x = jnp.asarray(
            rng.standard_normal((w, b_local, 3, 16, 16)), jnp.float32
        )
        y = jnp.asarray(
            (np.asarray(x).mean(axis=(2, 3, 4)) > 0).astype(np.int32) * 5 % 10
        )
        losses, grads = replica_grads(params, x, y)
        loss = losses.mean()
        grads = allreduce_grads(grads)
        params, opt = apply_fn(params, opt, grads)
        if step % 5 == 0:
            print(f"step {step:3d} loss {float(loss):.4f}")
    dt = time.perf_counter() - t0
    print(f"done: {args.steps} steps in {dt:.2f}s ({args.steps / dt:.1f} steps/s), world={n}")


if __name__ == "__main__":
    main()
