"""Data-parallel training with explicit Communicator gradient sync.

The analog of the reference's examples/ddp_train.py (PyTorch DDP training
ResNet-50 over the UCCL NCCL plugin): per-replica forward/backward, then an
explicit allreduce of gradients through the collectives layer — the same
contract DDP has with NCCL, expressed over the mesh. --model picks the
workload: resnet50 is the reference's exact benchmark network
(models/resnet.py, 25.6M params), resnet18 a lighter variant, cnn a tiny
smoke-test net.

Usage: python examples/ddp_train.py [--devices N] [--steps 20]
       [--model cnn|resnet18|resnet50] [--algo xla|ring]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--algo", default="xla", choices=["xla", "ring"])
    ap.add_argument(
        "--model", default="cnn", choices=["cnn", "resnet18", "resnet50"]
    )
    ap.add_argument("--image-size", type=int, default=0,
                    help="input resolution (default: 16 for cnn, 32 resnet18, 64 resnet50)")
    args = ap.parse_args()

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    import jax.numpy as jnp
    import numpy as np
    import optax

    from uccl_tpu.collective import Communicator
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    n = len(jax.devices())
    mesh = make_mesh(MeshConfig(dp=n))
    comm = Communicator(mesh, "dp")

    # --- workload: tiny CNN or the reference's ResNet benchmark network ----
    if args.model == "cnn":
        img = args.image_size or 16
        # two SAME stride-2 convs: spatial dims ceil-divide per conv
        fc_side = (((img + 1) // 2) + 1) // 2

        def init(key):
            k = jax.random.split(key, 4)
            return {
                "conv1": jax.random.normal(k[0], (16, 3, 3, 3)) * 0.1,
                "conv2": jax.random.normal(k[1], (32, 16, 3, 3)) * 0.1,
                "fc_w": jax.random.normal(
                    k[2], (32 * fc_side * fc_side, 10)
                ) * 0.05,
                "fc_b": jnp.zeros((10,)),
            }

        def model(p, x):  # NCHW
            x = jax.lax.conv_general_dilated(x, p["conv1"], (2, 2), "SAME")
            x = jax.nn.relu(x)
            x = jax.lax.conv_general_dilated(x, p["conv2"], (2, 2), "SAME")
            x = jax.nn.relu(x)
            return x.reshape(x.shape[0], -1) @ p["fc_w"] + p["fc_b"]

        def loss_fn(p, x, y):
            logits = model(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        params = init(jax.random.PRNGKey(0))
        state0 = None
        data_shape = lambda b: (b, 3, img, img)  # noqa: E731
    else:
        from uccl_tpu.models import resnet

        depth = 18 if args.model == "resnet18" else 50
        img = args.image_size or (32 if depth == 18 else 64)
        rcfg = resnet.ResNetConfig(depth=depth, num_classes=10)
        params, state0 = resnet.init_params(jax.random.PRNGKey(0), rcfg)
        print(
            f"{args.model}: {resnet.num_params(params) / 1e6:.2f}M params, "
            f"{img}x{img} inputs"
        )

        def loss_fn(p, x, y, s):
            loss, new_s = resnet.loss_fn(p, s, x, y, rcfg)
            return loss, new_s

        data_shape = lambda b: (b, img, img, 3)  # noqa: E731 (NHWC)

    tx = optax.sgd(0.05, momentum=0.9)
    opt = tx.init(params)
    w = comm.world
    # per-replica grads: each row of the leading dim is one replica's local
    # gradient over its batch shard (the DDP contract). ResNet also carries
    # per-replica BN statistics (torch DDP leaves BN local too).
    if state0 is None:
        replica_grads = jax.jit(
            jax.vmap(jax.value_and_grad(loss_fn), in_axes=(None, 0, 0))
        )
    else:
        state0 = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (w,) + a.shape), state0
        )
        replica_grads = jax.jit(
            jax.vmap(
                jax.value_and_grad(loss_fn, has_aux=True),
                in_axes=(None, 0, 0, 0),
            )
        )
    apply_fn = jax.jit(
        lambda p, o, g: (lambda u, o2: (optax.apply_updates(p, u), o2))(
            *tx.update(g, o, p)
        )
    )

    def allreduce_grads(grads):
        """Average per-replica gradients through the comm layer: flatten every
        leaf into one [world, K] bucket (DDP-style bucketing), one fused
        allreduce, unflatten."""
        leaves, treedef = jax.tree.flatten(grads)
        flat = jnp.concatenate([l.reshape(w, -1) for l in leaves], axis=1)
        avg = comm.all_reduce(comm.device_put(flat), algo=args.algo)[0] / w
        out, i = [], 0
        for l in leaves:
            k = l[0].size
            out.append(avg[i : i + k].reshape(l.shape[1:]))
            i += k
        return jax.tree.unflatten(treedef, out)

    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    b_local = max(1, args.batch // w)
    for step in range(args.steps):
        x = jnp.asarray(
            rng.standard_normal((w,) + data_shape(b_local)), jnp.float32
        )
        y = jnp.asarray(
            (np.asarray(x).mean(axis=tuple(range(2, x.ndim))) > 0).astype(
                np.int32
            ) * 5 % 10
        )
        if state0 is None:
            losses, grads = replica_grads(params, x, y)
        else:
            (losses, state0), grads = replica_grads(params, x, y, state0)
        loss = losses.mean()
        grads = allreduce_grads(grads)
        params, opt = apply_fn(params, opt, grads)
        if step % 5 == 0:
            print(f"step {step:3d} loss {float(loss):.4f}")
    dt = time.perf_counter() - t0
    print(f"done: {args.steps} steps in {dt:.2f}s ({args.steps / dt:.1f} steps/s), world={n}")


if __name__ == "__main__":
    main()
