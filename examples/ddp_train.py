"""Data-parallel training with explicit Communicator gradient sync.

The analog of the reference's examples/ddp_train.py (PyTorch DDP training
ResNet-50 over the UCCL NCCL plugin): per-replica forward/backward, then an
explicit allreduce of gradients through the collectives layer — the same
contract DDP has with NCCL, expressed over the mesh. --model picks the
workload: resnet50 is the reference's exact benchmark network
(models/resnet.py, 25.6M params), resnet18 a lighter variant, cnn a tiny
smoke-test net.

Two gradient-sync substrates, matching the reference's two deployment
shapes:

* default: replicas are mesh devices; sync is `Communicator.all_reduce`
  (XLA collectives over ICI) — the single-host multi-chip shape.
* ``--processes N``: replicas are OS processes; sync is
  `uccl_tpu.compat.dist` (torch.distributed-shaped) over the DCN engine —
  the "DDP over the plugin" shape the reference's example actually runs
  (torchrun + NCCL plugin). Each rank computes local grads, one flat
  bucket rides `dist.all_reduce`, and training trajectories match the
  mesh path on the same global batch (same seed → same data partition).

Usage: python examples/ddp_train.py [--devices N] [--steps 20]
       [--model cnn|resnet18|resnet50] [--algo xla|ring]
       [--processes N]
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_workload(args):
    """Build the training workload (jax must already be initialized).

    Returns (params, state0, loss_fn, data_shape): state0 is None for
    stateless models, else the BN-statistics pytree (kept per-replica —
    torch DDP leaves BN local too). loss_fn is (p, x, y[, s]) -> loss
    (or (loss, new_state))."""
    import jax
    import jax.numpy as jnp
    import optax

    if args.model == "cnn":
        img = args.image_size or 16
        # two SAME stride-2 convs: spatial dims ceil-divide per conv
        fc_side = (((img + 1) // 2) + 1) // 2

        def init(key):
            k = jax.random.split(key, 4)
            return {
                "conv1": jax.random.normal(k[0], (16, 3, 3, 3)) * 0.1,
                "conv2": jax.random.normal(k[1], (32, 16, 3, 3)) * 0.1,
                "fc_w": jax.random.normal(
                    k[2], (32 * fc_side * fc_side, 10)
                ) * 0.05,
                "fc_b": jnp.zeros((10,)),
            }

        def model(p, x):  # NCHW
            x = jax.lax.conv_general_dilated(x, p["conv1"], (2, 2), "SAME")
            x = jax.nn.relu(x)
            x = jax.lax.conv_general_dilated(x, p["conv2"], (2, 2), "SAME")
            x = jax.nn.relu(x)
            return x.reshape(x.shape[0], -1) @ p["fc_w"] + p["fc_b"]

        def loss_fn(p, x, y):
            logits = model(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        return init(jax.random.PRNGKey(0)), None, loss_fn, (
            lambda b: (b, 3, img, img)
        )

    from uccl_tpu.models import resnet

    depth = 18 if args.model == "resnet18" else 50
    img = args.image_size or (32 if depth == 18 else 64)
    rcfg = resnet.ResNetConfig(depth=depth, num_classes=10)
    params, state0 = resnet.init_params(jax.random.PRNGKey(0), rcfg)
    print(
        f"{args.model}: {resnet.num_params(params) / 1e6:.2f}M params, "
        f"{img}x{img} inputs"
    )

    def loss_fn(p, x, y, s):
        loss, new_s = resnet.loss_fn(p, s, x, y, rcfg)
        return loss, new_s

    return params, state0, loss_fn, (lambda b: (b, img, img, 3))  # NHWC


def _batch(rng, data_shape, w, b_local):
    """One global batch [w, b_local, ...] — identical in both sync modes so
    trajectories are comparable (process rank r trains on row r)."""
    import jax.numpy as jnp
    import numpy as np

    x = jnp.asarray(
        rng.standard_normal((w,) + data_shape(b_local)), jnp.float32
    )
    y = jnp.asarray(
        (np.asarray(x).mean(axis=tuple(range(2, x.ndim))) > 0).astype(
            np.int32
        ) * 5 % 10
    )
    return x, y


def make_optimizer(params):
    """SGD + jitted apply, shared by both sync modes: the loss-trajectory
    parity test requires the hyperparameters to stay identical."""
    import jax
    import optax

    tx = optax.sgd(0.05, momentum=0.9)
    apply_fn = jax.jit(
        lambda p, o, g: (lambda u, o2: (optax.apply_updates(p, u), o2))(
            *tx.update(g, o, p)
        )
    )
    return tx.init(params), apply_fn


def run_process_rank(args, rank: int):
    """One DDP process rank: local grads, flat-bucket allreduce over the
    DCN engine via the torch.distributed-shaped compat API."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from uccl_tpu.compat import dist

    w = args.processes
    dist.init_process_group(
        rank, w, master_port=int(os.environ["DDP_MASTER_PORT"])
    )
    try:
        params, state0, loss_fn, data_shape = build_workload(args)
        opt, apply_fn = make_optimizer(params)
        grad_fn = jax.jit(
            jax.value_and_grad(loss_fn, has_aux=state0 is not None)
        )

        rng = np.random.default_rng(0)
        b_local = max(1, args.batch // w)
        t0 = time.perf_counter()
        for step in range(args.steps):
            x, y = _batch(rng, data_shape, w, b_local)
            if state0 is None:
                loss, grads = grad_fn(params, x[rank], y[rank])
            else:
                (loss, state0), grads = grad_fn(params, x[rank], y[rank], state0)
            # one flat bucket: [K] grads + the loss scalar (so rank 0 can
            # report the true global mean), one dist.all_reduce per step
            leaves, treedef = jax.tree.flatten(grads)
            flat = np.concatenate(
                [np.asarray(l, np.float32).reshape(-1) for l in leaves]
                + [np.asarray([float(loss)], np.float32)]
            )
            dist.all_reduce(flat)  # in-place sum across ranks
            flat /= w
            out, i = [], 0
            for l in leaves:
                out.append(jnp.asarray(flat[i : i + l.size].reshape(l.shape)))
                i += l.size
            grads = jax.tree.unflatten(treedef, out)
            params, opt = apply_fn(params, opt, grads)
            if rank == 0 and step % 5 == 0:
                print(f"step {step:3d} loss {flat[-1]:.4f}", flush=True)
        dt = time.perf_counter() - t0
        if rank == 0:
            print(
                f"done: {args.steps} steps in {dt:.2f}s "
                f"({args.steps / dt:.1f} steps/s), world={w} (process ranks)"
            )
    finally:
        dist.destroy_process_group()


def spawn_processes(args):
    """Parent: launch one child per rank, stream rank 0, propagate failure.

    A dead rank leaves its peers blocked inside the DcnGroup ring, so the
    parent polls and kills the survivors the moment any child exits
    nonzero (instead of waiting on a hang)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for rank in range(args.processes):
        env = dict(
            os.environ,
            DDP_CHILD_RANK=str(rank),
            DDP_MASTER_PORT=str(port),
            JAX_PLATFORMS="cpu",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable] + sys.argv,
                env=env,
                stdout=None if rank == 0 else subprocess.DEVNULL,
            )
        )
    while any(p.poll() is None for p in procs):
        if any(p.poll() not in (None, 0) for p in procs):
            for p in procs:
                if p.poll() is None:
                    p.kill()
            break
        time.sleep(0.2)
    rcs = [p.wait() for p in procs]
    if any(rcs):
        sys.exit(f"rank failures: {rcs}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--algo", default="xla", choices=["xla", "ring"])
    ap.add_argument(
        "--model", default="cnn", choices=["cnn", "resnet18", "resnet50"]
    )
    ap.add_argument("--image-size", type=int, default=0,
                    help="input resolution (default: 16 for cnn, 32 resnet18, 64 resnet50)")
    ap.add_argument("--processes", type=int, default=0,
                    help="run N OS-process ranks syncing over the DCN engine "
                         "(compat.dist) instead of mesh-device replicas")
    args = ap.parse_args()

    if args.processes:
        # Children are identified by a variable ONLY spawn_processes sets
        # (together with the rendezvous port) — a leaked DDP_RANK from some
        # other launcher must not make the parent think it's a child.
        rank = os.environ.get("DDP_CHILD_RANK")
        if rank is None or "DDP_MASTER_PORT" not in os.environ:
            return spawn_processes(args)
        return run_process_rank(args, int(rank))

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    import jax.numpy as jnp
    import numpy as np
    import optax

    from uccl_tpu.collective import Communicator
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    n = len(jax.devices())
    mesh = make_mesh(MeshConfig(dp=n))
    comm = Communicator(mesh, "dp")

    params, state0, loss_fn, data_shape = build_workload(args)

    opt, apply_fn = make_optimizer(params)
    w = comm.world
    # per-replica grads: each row of the leading dim is one replica's local
    # gradient over its batch shard (the DDP contract). ResNet also carries
    # per-replica BN statistics (torch DDP leaves BN local too).
    if state0 is None:
        replica_grads = jax.jit(
            jax.vmap(jax.value_and_grad(loss_fn), in_axes=(None, 0, 0))
        )
    else:
        state0 = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (w,) + a.shape), state0
        )
        replica_grads = jax.jit(
            jax.vmap(
                jax.value_and_grad(loss_fn, has_aux=True),
                in_axes=(None, 0, 0, 0),
            )
        )
    def allreduce_grads(grads):
        """Average per-replica gradients through the comm layer: flatten every
        leaf into one [world, K] bucket (DDP-style bucketing), one fused
        allreduce, unflatten."""
        leaves, treedef = jax.tree.flatten(grads)
        flat = jnp.concatenate([l.reshape(w, -1) for l in leaves], axis=1)
        avg = comm.all_reduce(comm.device_put(flat), algo=args.algo)[0] / w
        out, i = [], 0
        for l in leaves:
            k = l[0].size
            out.append(avg[i : i + k].reshape(l.shape[1:]))
            i += k
        return jax.tree.unflatten(treedef, out)

    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    b_local = max(1, args.batch // w)
    for step in range(args.steps):
        x, y = _batch(rng, data_shape, w, b_local)
        if state0 is None:
            losses, grads = replica_grads(params, x, y)
        else:
            (losses, state0), grads = replica_grads(params, x, y, state0)
        loss = losses.mean()
        grads = allreduce_grads(grads)
        params, opt = apply_fn(params, opt, grads)
        if step % 5 == 0:
            print(f"step {step:3d} loss {float(loss):.4f}")
    dt = time.perf_counter() - t0
    print(f"done: {args.steps} steps in {dt:.2f}s ({args.steps / dt:.1f} steps/s), world={n}")


if __name__ == "__main__":
    main()
