"""Disaggregated prefill → decode serving with KV-cache transfer over P2P.

The analog of the reference's prefill/decode disaggregation workload
(ep/bench/vllm/disagg_proxy.py; "KV cache transfer" README.md:18): a prefill
worker runs the prompt and ships the KV cache through the transfer engine's
one-sided write path (advertise → write, out-of-band FifoItems over the
engine's own send/recv); the decode worker continues generation from the
received cache. The script asserts the disaggregated output matches
single-worker generation exactly.

Usage: python examples/disagg_kv.py [--new-tokens 16]
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import time
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

def _maybe_force_cpu():
    if os.environ.get("UCCL_TPU_EXAMPLE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")


CFG_KW = dict(
    vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=16, ffn=128
)
MAX_SEQ = 64
PROMPT_LEN = 8
BATCH = 2


def _make(seed=0):
    import jax

    from uccl_tpu.models.dense import DenseConfig, init_params

    cfg = DenseConfig(**CFG_KW)
    params = init_params(jax.random.PRNGKey(seed), cfg)
    return cfg, params


def _prompt():
    import numpy as np

    return np.random.default_rng(7).integers(0, 128, (BATCH, PROMPT_LEN)).astype(
        np.int32
    )


def decode_worker(port_q, result_q, new_tokens):
    """Decode side: advertises cache buffers, receives them, continues."""
    _maybe_force_cpu()
    import jax.numpy as jnp
    import numpy as np

    from uccl_tpu.models.inference import (
        KVCache, decode_step, decode_step_elastic,
    )
    from uccl_tpu.p2p import Endpoint

    compress = os.environ.get("UCCL_TPU_EXAMPLE_COMPRESS", "off")
    elastic = os.environ.get("UCCL_TPU_EXAMPLE_ELASTIC") == "1"
    cfg, params = _make()
    ep = Endpoint()
    port_q.put(ep.port)
    conn = ep.accept(timeout_ms=30000)

    # advertise host buffers shaped like the cache the prefill side will send
    shape = (cfg.n_layers, BATCH, MAX_SEQ, cfg.n_kv_heads, cfg.head_dim)
    if compress != "off":
        # compressed blobs land here (reference: DietGPU KV transfer)
        from uccl_tpu.p2p.compress import compressed_bound, decode_any

        raw_bytes = int(np.prod(shape)) * 4
        bound = (
            compressed_bound(shape, np.float32)
            if compress == "fp8"
            else raw_bytes + (1 << 14)  # lossless: raw + header slack
        )
        k_host = np.zeros(bound, np.uint8)
        v_host = np.zeros(bound, np.uint8)
    else:
        k_host = np.zeros(shape, np.float32)
        v_host = np.zeros(shape, np.float32)
    ep.send(conn, ep.advertise(ep.reg(k_host)))
    ep.send(conn, ep.advertise(ep.reg(v_host)))
    # Data-arrival signal rides the NIXL notify pattern (reference
    # p2p/uccl_engine.h:218-226): the prefill side one-sided-writes the
    # cache, then sends a notif carrying (length, first generated token);
    # the decode side drains non-blocking — free to do other work (e.g.
    # serve other requests) between polls.
    deadline = time.monotonic() + 30.0
    while not (notifs := ep.get_notifs(max_n=1)):
        if time.monotonic() > deadline:
            raise TimeoutError("no KV-arrival notif within 30s")
        time.sleep(0.002)
    meta = np.frombuffer(notifs[0][1], np.int32)
    length, first_tok = int(meta[0]), meta[1 : 1 + BATCH]

    if compress != "off":
        k_arr, v_arr = decode_any(k_host), decode_any(v_host)
    else:
        k_arr, v_arr = k_host, v_host
    cache = KVCache(jnp.asarray(k_arr), jnp.asarray(v_arr), jnp.int32(length))
    toks = [first_tok]
    tok = jnp.asarray(first_tok)
    if elastic:
        # Re-home the received cache elastically: hot ring of 1 block in
        # device memory, the rest of the prefix offloaded to pinned host
        # memory — the decode worker's context is then bounded by host RAM,
        # not HBM (lite-ep's host-window elasticity, TPU-style).
        from uccl_tpu.ep import ElasticKVCache

        ekv = ElasticKVCache.from_cache(cache, block_tokens=8, hot_blocks=1)
        for _ in range(new_tokens - 1):
            logits = decode_step_elastic(params, tok, ekv, cfg)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            toks.append(np.asarray(tok))
        print(
            f"decode: elastic cache held {ekv.cold_blocks} cold blocks in "
            f"host memory, {ekv.device_committed_bytes() / 1e3:.1f} KB "
            f"committed HBM, context {ekv.length}"
        )
    else:
        for _ in range(new_tokens - 1):
            logits, cache = decode_step(params, tok, cache, cfg)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            toks.append(np.asarray(tok))
    result_q.put(np.stack(toks, axis=1))
    ep.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--new-tokens", type=int, default=12)
    ap.add_argument("--cpu", action="store_true", help="force CPU jax")
    ap.add_argument(
        "--compress", nargs="?", const="fp8", default="off",
        choices=["off", "fp8", "lossless"],
        help="ship the KV cache compressed: fp8 (lossy ~3.8x) or lossless "
             "(exact, byte-plane + native rANS; prints the wire ratio)",
    )
    ap.add_argument(
        "--elastic", action="store_true",
        help="decode over an elastic KV cache (cold blocks in host memory)",
    )
    args = ap.parse_args()
    if args.cpu:
        os.environ["UCCL_TPU_EXAMPLE_CPU"] = "1"  # inherited by the worker
    if args.compress != "off":
        os.environ["UCCL_TPU_EXAMPLE_COMPRESS"] = args.compress
    if args.elastic:
        os.environ["UCCL_TPU_EXAMPLE_ELASTIC"] = "1"
    _maybe_force_cpu()

    ctx = mp.get_context("spawn")
    port_q, result_q = ctx.Queue(), ctx.Queue()
    worker = ctx.Process(
        target=decode_worker, args=(port_q, result_q, args.new_tokens)
    )
    worker.start()

    import jax.numpy as jnp
    import numpy as np

    from uccl_tpu.models.inference import generate, prefill
    from uccl_tpu.p2p import Endpoint

    cfg, params = _make()
    prompt = jnp.asarray(_prompt())

    # --- prefill worker ---------------------------------------------------
    last_logits, cache = prefill(params, prompt, cfg, max_seq=MAX_SEQ)
    first_tok = np.asarray(jnp.argmax(last_logits, axis=-1), np.int32)

    ep = Endpoint()
    port = port_q.get(timeout=30)
    conn = ep.connect("127.0.0.1", port)
    fifo_k = ep.recv(conn, timeout_ms=30000)
    fifo_v = ep.recv(conn, timeout_ms=30000)
    k_host = np.ascontiguousarray(np.asarray(cache.k, np.float32))
    v_host = np.ascontiguousarray(np.asarray(cache.v, np.float32))
    if args.compress != "off":
        from uccl_tpu.p2p.compress import encode

        k_blob = encode(k_host, args.compress)
        v_blob = encode(v_host, args.compress)
        ep.write(conn, k_blob, fifo_k)  # one-sided compressed cache push
        ep.write(conn, v_blob, fifo_v)
        wire = k_blob.nbytes + v_blob.nbytes
        raw = k_host.nbytes + v_host.nbytes
        print(
            f"prefill: shipped {args.compress} KV cache {wire / 1e6:.3f} MB "
            f"(raw {raw / 1e6:.3f} MB, ratio {raw / wire:.2f}x)"
        )
    else:
        ep.write(conn, k_host, fifo_k)  # one-sided cache push
        ep.write(conn, v_host, fifo_v)
    meta = np.concatenate([[int(cache.length)], first_tok]).astype(np.int32)
    ep.send_notif(conn, np.ascontiguousarray(meta).tobytes())
    if args.compress == "off":
        print(
            f"prefill: shipped KV cache {k_host.nbytes * 2 / 1e6:.2f} MB "
            f"(stats {ep.stats})"
        )

    disagg = result_q.get(timeout=120)
    worker.join(timeout=60)
    ep.close()

    # --- oracle: single-worker generation --------------------------------
    want = np.asarray(
        generate(params, prompt, cfg, max_new_tokens=args.new_tokens, max_seq=MAX_SEQ)
    )
    if args.compress == "fp8":
        # fp8 KV is lossy; exact token equality is not guaranteed. Require
        # generation to complete and mostly agree with the oracle.
        agree = float(np.mean(disagg == want))
        print(f"disaggregated (fp8 wire) token agreement: {agree:.0%}")
        if disagg.shape != want.shape or agree < 0.5:
            sys.exit(1)
    else:
        # raw and lossless wires are exact: tokens must match bit-for-bit
        ok = np.array_equal(disagg, want)
        print(f"disaggregated tokens match single-worker generation: {ok}")
        if not ok:
            print("disagg:", disagg)
            print("want:  ", want)
            sys.exit(1)


if __name__ == "__main__":
    main()
