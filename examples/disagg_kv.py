"""Disaggregated prefill → decode serving with KV-cache transfer over P2P.

The analog of the reference's prefill/decode disaggregation workload
(ep/bench/vllm/disagg_proxy.py; "KV cache transfer" README.md:18), in two
tiers:

* **Default — chunk-streamed serving** (`uccl_tpu/serving/disagg.py`): a
  PrefillWorker engine (chunked prefill + prefix-reuse cache) streams each
  request's KV slabs chunk-by-chunk into a DecodeWorker process over the
  one-sided write path as they are computed; the decode engine adopts each
  request and continues generation. Three requests share a system-prompt
  prefix, so the run demonstrates ≥1 prefix-cache hit (tokens reused, not
  recomputed — the counters prove it) AND bit-exact output.
* **Legacy one-shot handoff** (`--compress` / `--elastic` / `--one-shot`):
  the original whole-cache advertise → write → notif flow, kept for the
  compressed-wire (DietGPU-style) and elastic-KV demos.

Either way the script asserts the disaggregated output matches
single-worker generation exactly (fp8 is lossy: agreement-checked) and
exits non-zero on mismatch — tests/test_disagg_kv.py pins that contract.

Usage: python examples/disagg_kv.py [--new-tokens 12] [--metrics-out M]
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import time
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

def _maybe_force_cpu():
    if os.environ.get("UCCL_TPU_EXAMPLE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")


CFG_KW = dict(
    vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=16, ffn=128
)
MAX_SEQ = 64
PROMPT_LEN = 8
BATCH = 2
STREAM_CHUNK = 4  # prefill chunk = KV stream granularity = prefix-trie key
STREAM_PROMPT_LEN = 12  # 3 chunks; requests share the first 8 tokens
STREAM_REQUESTS = 3


def _make(seed=0):
    import jax

    from uccl_tpu.models.dense import DenseConfig, init_params

    cfg = DenseConfig(**CFG_KW)
    params = init_params(jax.random.PRNGKey(seed), cfg)
    return cfg, params


def _prompt():
    import numpy as np

    return np.random.default_rng(7).integers(0, 128, (BATCH, PROMPT_LEN)).astype(
        np.int32
    )


# -- default: chunk-streamed disaggregated serving --------------------------
def _role_path(path: str, role: str) -> str:
    """Per-role artifact path: ``/tmp/t.json`` -> ``/tmp/t.decode.json``.
    The fleet smoke arm (qa.sh / ci.yml) merges the two roles' traces
    with scripts/trace_merge.py and federates the two metrics files with
    ``python -m uccl_tpu.obs.aggregate``."""
    stem, ext = os.path.splitext(path)
    return f"{stem}.{role}{ext or '.json'}"


def stream_decode_worker(port_q, result_q, n_requests, trace_out="",
                         metrics_out=""):
    """Decode-fleet process: advertises its slot-pool KV mirror, grants
    incoming streams, adopts + decodes each request, reports the outputs
    and its engine snapshot (with the disagg TTFT split). With
    ``trace_out``/``metrics_out`` it dumps its OWN role-labeled
    observability artifacts — the decode half of the fleet trace (its
    clock metadata carries the offset the HELLO exchange estimated)."""
    _maybe_force_cpu()
    import numpy as np

    from uccl_tpu import obs
    from uccl_tpu.p2p import Endpoint
    from uccl_tpu.serving import DenseBackend, ServingEngine, ServingMetrics
    from uccl_tpu.serving.disagg import DecodeWorker

    if trace_out:
        obs.enable_tracing()
    cfg, params = _make()
    backend = DenseBackend(params, cfg, n_slots=2, max_seq=MAX_SEQ)
    engine = ServingEngine(backend)
    ep = Endpoint()
    port_q.put(ep.port)
    dw = DecodeWorker(engine, ep)
    dw.attach()
    done = dw.serve(n_requests, timeout_s=180.0)
    snap = engine.snapshot()
    if trace_out:
        obs.write_trace(trace_out, process_name="uccl_tpu.decode")
    if metrics_out:
        obs.write_metrics(
            metrics_out,
            extra_lines=ServingMetrics.prometheus_lines(snap),
        )
    result_q.put((
        [(np.asarray(r.prompt), list(r.out_tokens), int(r.cache_hit_len))
         for r in done],
        snap,
    ))
    ep.close()


def _stream_main(args) -> int:
    import jax.numpy as jnp
    import numpy as np

    from uccl_tpu import obs
    from uccl_tpu.models.inference import generate
    from uccl_tpu.p2p import Endpoint
    from uccl_tpu.serving import (
        DenseBackend, PrefixCache, ServingEngine, ServingMetrics,
    )
    from uccl_tpu.serving.disagg import PrefillWorker

    ctx = mp.get_context("spawn")
    port_q, result_q = ctx.Queue(), ctx.Queue()
    worker = ctx.Process(
        target=stream_decode_worker,
        args=(port_q, result_q, STREAM_REQUESTS,
              _role_path(args.trace_out, "decode") if args.trace_out
              else "",
              _role_path(args.metrics_out, "decode") if args.metrics_out
              else ""),
    )
    worker.start()

    cfg, params = _make()
    backend = DenseBackend(params, cfg, n_slots=2, max_seq=MAX_SEQ)
    engine = ServingEngine(backend, prefill_chunk=STREAM_CHUNK,
                           prefix_cache=PrefixCache(STREAM_CHUNK))
    ep = Endpoint()
    pw = PrefillWorker(engine, ep, "127.0.0.1", port_q.get(timeout=60))

    # one cold prompt, then two sharing its first 8 tokens (a 2-chunk
    # "system prompt"): the second and third resume from the cache
    rng = np.random.default_rng(7)
    p0 = rng.integers(0, cfg.vocab, STREAM_PROMPT_LEN).astype(np.int32)
    prompts = [
        p0,
        np.concatenate([p0[:8], rng.integers(0, cfg.vocab, 4).astype(np.int32)]),
        p0.copy(),
    ]
    pw.submit(prompts[0], max_new_tokens=args.new_tokens)
    pw.drain()  # cold request fully streamed -> its slot parks as a donor
    for p in prompts[1:]:
        pw.submit(p, max_new_tokens=args.new_tokens)
    pw.drain()
    pw.close()

    results, snap = result_q.get(timeout=180)
    worker.join(timeout=60)

    hits = int(obs.counter("prefix_cache_hits_total").get())
    reused = int(obs.counter("prefix_cache_tokens_reused_total").get())
    computed = int(obs.counter("serving_prefill_tokens_total")
                   .get(kind="computed"))
    chunks = int(obs.counter("kv_stream_chunks_total").get(role="tx"))
    wire = obs.counter("p2p_bytes_total").get(verb="write")
    print(
        f"prefill fleet: {len(prompts)} requests, {hits} prefix-cache "
        f"hit(s), {reused} prompt tokens reused / {computed} computed, "
        f"{chunks} KV slabs ({wire / 1e3:.1f} KB) streamed chunk-wise"
    )
    split = {k: snap.get(k, {}).get("p50") for k in
             ("disagg_queue_ms", "disagg_prefill_ms", "disagg_transfer_ms")}
    print(
        f"decode fleet: adopted {snap.get('adopted', 0)} requests; TTFT "
        f"split p50 queue/prefill/transfer = {split['disagg_queue_ms']}/"
        f"{split['disagg_prefill_ms']}/{split['disagg_transfer_ms']} ms"
    )

    # per-role observability dumps: this (prefill) process writes the
    # paths the CLI asked for; the decode process already wrote its
    # _role_path siblings — together they are the fleet-trace inputs
    written = obs.dump_from_args(
        args, extra_lines=ServingMetrics.prometheus_lines(engine.snapshot()),
        process_name="uccl_tpu.prefill",
    )
    for path in written:
        print(f"wrote {path} (+ decode-role sibling "
              f"{_role_path(path, 'decode')})")
    if pw.clock_rtt_s is not None:
        print(f"clock exchange: offset {pw.clock_offset_s * 1e6:+.1f} us, "
              f"rtt {pw.clock_rtt_s * 1e6:.1f} us (decode vs prefill wall)")

    ok = len(results) == STREAM_REQUESTS and hits >= 1
    for prompt, toks, hit in results:
        want = np.asarray(generate(
            params, jnp.asarray(prompt)[None], cfg,
            max_new_tokens=args.new_tokens, max_seq=MAX_SEQ,
        ))[0].tolist()
        if toks != want:
            print(f"MISMATCH (hit={hit}): got {toks} want {want}")
            ok = False
    print(f"disaggregated tokens match single-worker generation: {ok}")
    return 0 if ok else 1


# -- legacy: one-shot whole-cache handoff ------------------------------------
def decode_worker(port_q, result_q, new_tokens):
    """Decode side: advertises cache buffers, receives them, continues."""
    _maybe_force_cpu()
    import jax.numpy as jnp
    import numpy as np

    from uccl_tpu.models.inference import KVCache, decode_step_elastic
    from uccl_tpu.p2p import Endpoint
    from uccl_tpu.serving.disagg import decode_continue

    compress = os.environ.get("UCCL_TPU_EXAMPLE_COMPRESS", "off")
    elastic = os.environ.get("UCCL_TPU_EXAMPLE_ELASTIC") == "1"
    cfg, params = _make()
    ep = Endpoint()
    port_q.put(ep.port)
    conn = ep.accept(timeout_ms=30000)

    # advertise host buffers shaped like the cache the prefill side will send
    shape = (cfg.n_layers, BATCH, MAX_SEQ, cfg.n_kv_heads, cfg.head_dim)
    if compress != "off":
        # compressed blobs land here (reference: DietGPU KV transfer)
        from uccl_tpu.p2p.compress import compressed_bound, decode_any

        raw_bytes = int(np.prod(shape)) * 4
        bound = (
            compressed_bound(shape, np.float32)
            if compress == "fp8"
            else raw_bytes + (1 << 14)  # lossless: raw + header slack
        )
        k_host = np.zeros(bound, np.uint8)
        v_host = np.zeros(bound, np.uint8)
    else:
        k_host = np.zeros(shape, np.float32)
        v_host = np.zeros(shape, np.float32)
    ep.send(conn, ep.advertise(ep.reg(k_host)))
    ep.send(conn, ep.advertise(ep.reg(v_host)))
    # Data-arrival signal rides the NIXL notify pattern (reference
    # p2p/uccl_engine.h:218-226): the prefill side one-sided-writes the
    # cache, then sends a notif carrying (length, first generated token);
    # the decode side drains non-blocking — free to do other work (e.g.
    # serve other requests) between polls.
    deadline = time.monotonic() + 30.0
    while not (notifs := ep.get_notifs(max_n=1)):
        if time.monotonic() > deadline:
            raise TimeoutError("no KV-arrival notif within 30s")
        time.sleep(0.002)
    meta = np.frombuffer(notifs[0][1], np.int32)
    length, first_tok = int(meta[0]), meta[1 : 1 + BATCH]

    if compress != "off":
        k_arr, v_arr = decode_any(k_host), decode_any(v_host)
    else:
        k_arr, v_arr = k_host, v_host
    cache = KVCache(jnp.asarray(k_arr), jnp.asarray(v_arr), jnp.int32(length))
    if elastic:
        # Re-home the received cache elastically: hot ring of 1 block in
        # device memory, the rest of the prefix offloaded to pinned host
        # memory — the decode worker's context is then bounded by host RAM,
        # not HBM (lite-ep's host-window elasticity, TPU-style).
        from uccl_tpu.ep import ElasticKVCache

        ekv = ElasticKVCache.from_cache(cache, block_tokens=8, hot_blocks=1)
        toks = [first_tok]
        tok = jnp.asarray(first_tok)
        for _ in range(new_tokens - 1):
            logits = decode_step_elastic(params, tok, ekv, cfg)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            toks.append(np.asarray(tok))
        print(
            f"decode: elastic cache held {ekv.cold_blocks} cold blocks in "
            f"host memory, {ekv.device_committed_bytes() / 1e3:.1f} KB "
            f"committed HBM, context {ekv.length}"
        )
        result_q.put(np.stack(toks, axis=1))
    else:
        result_q.put(decode_continue(params, cfg, cache, first_tok,
                                     new_tokens))
    ep.close()


def _legacy_main(args) -> int:
    ctx = mp.get_context("spawn")
    port_q, result_q = ctx.Queue(), ctx.Queue()
    worker = ctx.Process(
        target=decode_worker, args=(port_q, result_q, args.new_tokens)
    )
    worker.start()

    import jax.numpy as jnp
    import numpy as np

    from uccl_tpu.models.inference import generate, prefill
    from uccl_tpu.p2p import Endpoint

    cfg, params = _make()
    prompt = jnp.asarray(_prompt())

    # --- prefill worker ---------------------------------------------------
    last_logits, cache = prefill(params, prompt, cfg, max_seq=MAX_SEQ)
    first_tok = np.asarray(jnp.argmax(last_logits, axis=-1), np.int32)

    ep = Endpoint()
    port = port_q.get(timeout=30)
    conn = ep.connect("127.0.0.1", port)
    fifo_k = ep.recv(conn, timeout_ms=30000)
    fifo_v = ep.recv(conn, timeout_ms=30000)
    k_host = np.ascontiguousarray(np.asarray(cache.k, np.float32))
    v_host = np.ascontiguousarray(np.asarray(cache.v, np.float32))
    if args.compress != "off":
        from uccl_tpu.p2p.compress import encode

        k_blob = encode(k_host, args.compress)
        v_blob = encode(v_host, args.compress)
        ep.write(conn, k_blob, fifo_k)  # one-sided compressed cache push
        ep.write(conn, v_blob, fifo_v)
        wire = k_blob.nbytes + v_blob.nbytes
        raw = k_host.nbytes + v_host.nbytes
        print(
            f"prefill: shipped {args.compress} KV cache {wire / 1e6:.3f} MB "
            f"(raw {raw / 1e6:.3f} MB, ratio {raw / wire:.2f}x)"
        )
    else:
        ep.write(conn, k_host, fifo_k)  # one-sided cache push
        ep.write(conn, v_host, fifo_v)
    meta = np.concatenate([[int(cache.length)], first_tok]).astype(np.int32)
    ep.send_notif(conn, np.ascontiguousarray(meta).tobytes())
    if args.compress == "off":
        print(
            f"prefill: shipped KV cache {k_host.nbytes * 2 / 1e6:.2f} MB "
            f"(stats {ep.stats})"
        )

    disagg = result_q.get(timeout=120)
    worker.join(timeout=60)
    ep.close()

    # --- oracle: single-worker generation --------------------------------
    want = np.asarray(
        generate(params, prompt, cfg, max_new_tokens=args.new_tokens, max_seq=MAX_SEQ)
    )
    if args.compress == "fp8":
        # fp8 KV is lossy; exact token equality is not guaranteed. Require
        # generation to complete and mostly agree with the oracle.
        agree = float(np.mean(disagg == want))
        print(f"disaggregated (fp8 wire) token agreement: {agree:.0%}")
        if disagg.shape != want.shape or agree < 0.5:
            return 1
    else:
        # raw and lossless wires are exact: tokens must match bit-for-bit
        ok = np.array_equal(disagg, want)
        print(f"disaggregated tokens match single-worker generation: {ok}")
        if not ok:
            print("disagg:", disagg)
            print("want:  ", want)
            return 1
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--new-tokens", type=int, default=12)
    ap.add_argument("--cpu", action="store_true", help="force CPU jax")
    ap.add_argument(
        "--compress", nargs="?", const="fp8", default="off",
        choices=["off", "fp8", "lossless"],
        help="LEGACY one-shot handoff with a compressed wire: fp8 (lossy "
             "~3.8x) or lossless (exact, byte-plane + native rANS)",
    )
    ap.add_argument(
        "--elastic", action="store_true",
        help="LEGACY one-shot handoff decoding over an elastic KV cache "
             "(cold blocks in host memory)",
    )
    ap.add_argument(
        "--one-shot", action="store_true",
        help="run the legacy whole-cache handoff instead of the "
             "chunk-streamed serving pair",
    )
    from uccl_tpu import obs

    obs.add_cli_args(ap)
    args = ap.parse_args()
    if args.cpu:
        os.environ["UCCL_TPU_EXAMPLE_CPU"] = "1"  # inherited by the worker
    if args.compress != "off":
        os.environ["UCCL_TPU_EXAMPLE_COMPRESS"] = args.compress
    if args.elastic:
        os.environ["UCCL_TPU_EXAMPLE_ELASTIC"] = "1"
    _maybe_force_cpu()
    obs.setup_from_args(args)
    obs.dump_at_exit(args)

    if args.compress != "off" or args.elastic or args.one_shot:
        sys.exit(_legacy_main(args))
    sys.exit(_stream_main(args))


if __name__ == "__main__":
    main()
