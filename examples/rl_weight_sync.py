"""RL weight transfer: trainer → inference workers over the P2P engine.

The reference's other headline P2P workload ("RL weight transfer",
README.md:18; the use case that makes DietGPU's LOSSLESS codec mandatory —
p2p/rdma/compression.h:46): after each training phase, the trainer ships
updated policy weights to N rollout workers, bit-exactly, as fast as the
wire allows. This example drives that loop end to end through the
framework's own pieces:

* **channels** fan out from the trainer to each worker (multipath spraying);
* the **lossless codec** (byte-plane + rANS) shrinks bf16 weights ~1.5×
  with a bit-exact round trip — workers verify checksums;
* **EQDS pull mode** (optional, ``--pull-rate``) lets each worker pace its
  own inbound weight stream (receiver-driven credit), so a slow worker
  never forces the trainer to blast into its queue;
* staging rides the pipelined ``send_jax``-style chunk path.

Workers apply the weights to a live jitted policy and report the policy
output hash so the trainer can assert every worker is serving the NEW
weights — the actual correctness contract of RL weight sync.

Usage: python examples/rl_weight_sync.py [--workers 2] [--layers 4]
       [--hidden 256] [--rounds 2] [--compress] [--pull-rate MB_s]
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _force_cpu():
    # host-side example; keep it off a (possibly wedged) accelerator tunnel
    import jax

    jax.config.update("jax_platforms", "cpu")


def _policy_apply(params, x):
    import jax.numpy as jnp

    h = x
    for w in params:
        h = jnp.tanh(h @ w)
    return h


def _make_params(jnp, rng, layers, hidden):
    return [
        jnp.asarray(rng.standard_normal((hidden, hidden)) * 0.05).astype(
            jnp.bfloat16
        )
        for _ in range(layers)
    ]


def worker_main(widx, port_q, result_q, args):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _force_cpu()
    import jax
    import numpy as np

    from uccl_tpu.p2p import Channel, Endpoint, PullPacer
    from uccl_tpu.p2p.compress import decode_any

    with Endpoint(n_engines=2) as ep:
        port_q.put((widx, ep.port))
        chan = Channel.accept(ep, timeout_ms=30000)
        pacer = None
        if args.pull_rate:
            pacer = PullPacer(args.pull_rate * 1e6)
            pacer.attach(chan)
            pacer.start()
        apply = jax.jit(_policy_apply)
        probe = np.linspace(-1, 1, args.hidden, dtype=np.float32)
        try:
            for _ in range(args.rounds):
                # windows for this round's weights (advertised per round so
                # the trainer's FifoItems can't touch stale registrations)
                n_msgs = int(np.frombuffer(
                    chan.recv(timeout_ms=300000), np.int64)[0])
                sizes = np.frombuffer(
                    chan.recv(timeout_ms=300000), np.int64)
                bufs = [np.empty(int(s), np.uint8) for s in sizes]
                for b in bufs:
                    chan.send(ep.advertise(ep.reg(b)))
                chan.send(b"GO")
                assert chan.recv(timeout_ms=120000) == b"SENT"
                import ml_dtypes

                params = []
                for b in bufs[:n_msgs]:
                    arr = (decode_any(b) if args.compress
                           else b.view(ml_dtypes.bfloat16))
                    params.append(jax.numpy.asarray(arr).reshape(
                        args.hidden, args.hidden))
                out = apply(params, jax.numpy.asarray(probe))
                digest = float(np.asarray(out, np.float32).sum())
                chan.send(np.asarray([digest], np.float64).tobytes())
        finally:
            if pacer is not None:
                pacer.stop(flush_bytes=1 << 30)
            chan.close()
    result_q.put((widx, "ok"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--rounds", type=int, default=2)
    ap.add_argument("--compress", action="store_true",
                    help="lossless byte-plane+rANS wire codec")
    ap.add_argument("--pull-rate", type=float, default=0.0,
                    help="per-worker EQDS pull grant rate, MB/s (0 = push)")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _force_cpu()
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from uccl_tpu.p2p import Channel, Endpoint
    from uccl_tpu.p2p.lossless import encode_lossless

    ctx = mp.get_context("spawn")
    port_q, result_q = ctx.Queue(), ctx.Queue()
    procs = [
        ctx.Process(target=worker_main, args=(w, port_q, result_q, args))
        for w in range(args.workers)
    ]
    [p.start() for p in procs]
    ports = dict(port_q.get(timeout=60) for _ in procs)

    rng = np.random.default_rng(0)
    apply = jax.jit(_policy_apply)
    probe = jnp.asarray(np.linspace(-1, 1, args.hidden, dtype=np.float32))

    with Endpoint(n_engines=2) as ep:
        chans = [
            Channel.connect(ep, "127.0.0.1", ports[w], n_paths=2)
            for w in range(args.workers)
        ]
        if args.pull_rate:
            for c in chans:
                c.enable_pull_sender()
        for rnd in range(args.rounds):
            params = _make_params(jnp, rng, args.layers, args.hidden)
            want = float(np.asarray(apply(params, probe), np.float32).sum())
            blobs = []
            raw_bytes = 0
            for w_arr in params:
                host = np.asarray(w_arr)
                raw_bytes += host.nbytes
                blobs.append(
                    encode_lossless(host) if args.compress
                    else host.reshape(-1).view(np.uint8)
                )
            wire_bytes = sum(b.nbytes for b in blobs)
            t0 = time.perf_counter()
            for c in chans:
                c.send(np.asarray([len(blobs)], np.int64).tobytes())
                c.send(np.asarray([b.nbytes for b in blobs],
                                  np.int64).tobytes())
            fifos = {c: [c.recv(timeout_ms=300000) for _ in blobs]
                     for c in chans}
            for c in chans:
                assert c.recv(timeout_ms=300000) == b"GO"
            for c in chans:
                for blob, fifo in zip(blobs, fifos[c]):
                    c.write(np.ascontiguousarray(blob), fifo)
                c.send(b"SENT")
            digests = [
                np.frombuffer(c.recv(timeout_ms=120000), np.float64)[0]
                for c in chans
            ]
            dt = time.perf_counter() - t0
            for d in digests:
                assert abs(d - want) < 1e-3 * max(1.0, abs(want)), (d, want)
            print(
                f"round {rnd}: {args.workers} workers serving new weights | "
                f"{raw_bytes/1e6:.1f} MB raw -> {wire_bytes/1e6:.1f} MB wire "
                f"(x{raw_bytes/max(1,wire_bytes):.2f}) | "
                f"{dt*1e3:.0f} ms | mode="
                f"{'pull' if args.pull_rate else 'push'}"
                f"{'+lossless' if args.compress else ''}"
            )
        for c in chans:
            c.close()
    for p in procs:
        p.join(timeout=60)
    oks = [result_q.get(timeout=10) for _ in procs]
    assert all(s == "ok" for _, s in oks)
    print("weight sync verified: every worker serves the updated policy")


if __name__ == "__main__":
    main()
