"""Benchmark: flagship MoE training-step throughput on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": ...}

The headline metric is end-to-end training tokens/sec of the flagship MoE
transformer (sorted/ragged expert dispatch + flash attention code paths).
``vs_baseline`` compares against the *vendor stack*: the same model lowered
through XLA's stock paths — dense GShard-style one-hot einsum dispatch and
plain XLA attention — mirroring the reference's "UCCL vs NCCL, same app"
framing (README.md:29). ``mfu`` is model-FLOPs utilization against the
device's peak bf16 matmul throughput (the metric culture of
ep/bench/test_low_latency.py:438-464: report the number, not vibes).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import jax

# Peak dense-matmul TFLOP/s (bf16) by TPU generation, for the MFU estimate.
# Overridable via UCCL_TPU_PEAK_TFLOPS for new/unknown device kinds.
_PEAK_TFLOPS = (
    ("v6 lite", 918.0),  # Trillium
    ("v6e", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0),  # v5e
    ("v5e", 197.0),
    ("v4", 275.0),
)


def _peak_flops(device_kind: str):
    env = os.environ.get("UCCL_TPU_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = device_kind.lower()
    for tag, tf in _PEAK_TFLOPS:
        if tag in kind:
            return tf * 1e12
    return None


def _probe_device(attempts: int = None, timeouts=None):
    """Probe accelerator availability in a subprocess: a wedged tunnel
    (observed with the axon relay) hangs device init in native code holding
    the GIL, so neither signals nor threads can interrupt it in-process.
    Retries with growing deadlines; every failure mode is logged to stderr
    so a demoted run is diagnosable. If all attempts fail, this process pins
    jax to CPU before its own first device touch.

    UCCL_TPU_BENCH_PROBE_ATTEMPTS / _PROBE_TIMEOUT env knobs override the
    retry budget (e.g. for quick local runs)."""
    if attempts is None:
        attempts = int(os.environ.get("UCCL_TPU_BENCH_PROBE_ATTEMPTS", "3"))
    if timeouts is None:
        env_to = os.environ.get("UCCL_TPU_BENCH_PROBE_TIMEOUT")
        timeouts = (int(env_to),) if env_to else (120, 240, 300)
    src = "import jax; d = jax.devices()[0]; print('ok', d.platform, d.device_kind)"
    for i in range(attempts):
        deadline = timeouts[min(i, len(timeouts) - 1)]
        try:
            probe = subprocess.run(
                [sys.executable, "-c", src],
                timeout=deadline,
                capture_output=True,
                text=True,
            )
        except subprocess.TimeoutExpired:
            print(
                f"[bench] device probe {i + 1}/{attempts}: timed out after "
                f"{deadline}s (tunnel wedged?)",
                file=sys.stderr,
            )
            if i + 1 < attempts:
                time.sleep(10)
            continue
        line = next(
            (l for l in probe.stdout.splitlines() if l.startswith("ok ")), None
        )
        if probe.returncode == 0 and line:
            _, platform, kind = line.split(" ", 2)
            return True, platform, kind
        print(
            f"[bench] device probe {i + 1}/{attempts}: rc={probe.returncode} "
            f"stderr: {probe.stderr[-500:]}",
            file=sys.stderr,
        )
        if i + 1 < attempts:
            time.sleep(10)
    jax.config.update("jax_platforms", "cpu")
    return False, "cpu", "cpu"


import jax.numpy as jnp  # noqa: E402

_BASE_VOCAB = 16384  # full-size vocab; token sampling must match _build's cfg


def _build(cfg_kw=None):
    from uccl_tpu.models.flagship import (
        FlagshipConfig,
        init_params,
        make_train_step,
        shard_params,
    )
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    base = dict(
        vocab=_BASE_VOCAB,
        dim=1024,
        n_layers=4,
        n_heads=16,
        n_kv_heads=4,
        head_dim=64,
        moe_experts=8,
        moe_topk=2,
        moe_ffn=2816,
        capacity_factor=1.25,
        n_microbatches=1,
        dtype=jnp.bfloat16,
        aux_loss_weight=0.01,
        z_loss_weight=1e-3,
    )
    base.update(cfg_kw or {})  # caller overrides (impls, CPU shrink)
    cfg = FlagshipConfig(**base)
    mesh = make_mesh(MeshConfig(), jax.devices()[:1])
    params = shard_params(init_params(jax.random.PRNGKey(0), cfg), mesh, cfg)
    train_step, init_opt = make_train_step(cfg, mesh)
    opt_state = init_opt(params)
    return cfg, mesh, params, train_step, opt_state


def _model_flops_per_token(cfg, seq: int) -> float:
    """Analytic model FLOPs per token for one training step (fwd + bwd = 3x
    fwd), matmuls only, causal attention at half the full score cost. This is
    the standard MFU numerator: rematerialization recompute does NOT count."""
    h, hd = cfg.dim, cfg.head_dim
    qd = cfg.n_heads * hd
    kvd = cfg.n_kv_heads * hd
    per_layer_params = (
        h * qd  # wq
        + 2 * h * kvd  # wk, wv
        + qd * h  # wo
        + h * cfg.moe_experts  # router
        + cfg.moe_topk * 3 * h * cfg.moe_ffn  # active experts (SwiGLU)
    )
    n_active = cfg.n_layers * per_layer_params + h * cfg.vocab  # + unembed
    attn_core = cfg.n_layers * 2 * cfg.n_heads * hd * seq  # causal qk^T + att@v
    fwd = 2.0 * n_active + attn_core
    return 3.0 * fwd


class _Harness:
    """One config variant held resident so samples can be interleaved with
    another variant's (A-B-A-B): back-to-back measurement of ours/baseline
    is what let a single scheduling-noise window swing the round-4 recorded
    vs_baseline to 0.925 while the clean number was 1.254.

    Two tunneled-platform hazards shape the timing loop: block_until_ready
    can return before device work finishes (so: sync via a host read of the
    loss), and per-dispatch overhead is ~10 ms (so: run all iterations
    inside ONE jitted fori_loop dispatch instead of one dispatch per step).
    """

    def __init__(self, cfg_kw, tokens, targets):
        from jax import lax

        self.cfg, mesh, self._params, step, self._opt = _build(cfg_kw)
        step = jax.jit(step)

        def run(params, opt_state, n):
            def body(_, state):
                p, o, _m = state
                return step(p, o, tokens, targets)

            init = step(params, opt_state, tokens, targets)
            return lax.fori_loop(0, n - 1, body, init)

        # n traced -> one compile serves warmup and timing. params/opt_state
        # are DONATED: XLA aliases them into the loop-carried outputs, so the
        # step never pays an input copy of the largest buffers (each call
        # rebinds self._params/_opt to the returned state, keeping the
        # donated references dead).
        self._run = jax.jit(run, donate_argnums=(0, 1))

    def _call(self, n):
        self._params, self._opt, m = self._run(self._params, self._opt, n)
        return float(m["loss"])  # host read = real sync on the tunnel

    def warmup(self):
        self._call(2)  # compile + warm
        # The first call returns the state with XLA's canonicalized output
        # shardings, which can differ from the inputs' NamedShardings
        # (observed on 1-device meshes: named specs come back replicated) —
        # so the NEXT call recompiles for the new argument shardings.
        # Without this throwaway call the timed call was ~95% XLA compile
        # (measured 2078 "ms/step" vs 175 ms real on the CPU config). After
        # it, shardings are at their fixed point and every later call is a
        # pure cache hit.
        self._call(1)

    def sample(self, iters):
        """Median-able single observation: seconds per step over `iters`."""
        t0 = time.perf_counter()
        self._call(iters)
        return (time.perf_counter() - t0) / iters

    def free(self):
        self._params = self._opt = self._run = None


from statistics import median as _median  # noqa: E402


def _interleaved_dts(ours, base, rounds, iters):
    """A-B-A-B sample schedule; returns (ours_dts, base_dts) lists."""
    ours_dts, base_dts = [], []
    for _ in range(rounds):
        ours_dts.append(ours.sample(iters))
        base_dts.append(base.sample(iters))
    return ours_dts, base_dts


def main():
    import numpy as np

    # Fast-path MoE impl: "sort" (ragged layout, capacity-padded GEMMs) or
    # "ll" (packed grouped GEMMs via ragged_dot — no padded FLOPs; wins on
    # MXU, loses on XLA:CPU where ragged_dot lowers to loops — measured in
    # PERF.md). Env knob so the on-chip session can sweep without edits.
    # Validated BEFORE the device probe: a typo'd knob must fail in
    # milliseconds, not after minutes of tunnel-probe retries.
    moe_impl = os.environ.get("UCCL_TPU_BENCH_MOE", "sort")
    if moe_impl not in ("sort", "ll", "dense"):
        sys.exit(f"[bench] UCCL_TPU_BENCH_MOE={moe_impl!r}: want sort|ll|dense")
    # Remat schedule for BOTH the fast path and the baseline (identical
    # numerics across modes — tests/test_flagship.py::TestRematModes):
    # "dots" trades activation memory for zero backward GEMM recompute.
    remat = os.environ.get("UCCL_TPU_BENCH_REMAT", "full")
    if remat not in ("full", "dots", "mlp", "none"):
        sys.exit(
            f"[bench] UCCL_TPU_BENCH_REMAT={remat!r}: want full|dots|mlp|none"
        )
    # Batch/seq overrides validated here too — before the probe.
    try:
        batch_env = int(os.environ.get("UCCL_TPU_BENCH_BATCH", "0"))
        seq_env = int(os.environ.get("UCCL_TPU_BENCH_SEQ", "0"))
        rounds = int(os.environ.get("UCCL_TPU_BENCH_ROUNDS", "9"))
        iters = int(os.environ.get("UCCL_TPU_BENCH_ITERS", "5"))
    except ValueError as e:
        sys.exit(f"[bench] bad UCCL_TPU_BENCH_{{BATCH,SEQ,ROUNDS,ITERS}}: {e}")
    if batch_env < 0 or seq_env < 0:
        sys.exit("[bench] UCCL_TPU_BENCH_BATCH/SEQ must not be negative")
    if rounds < 1 or iters < 1:
        sys.exit("[bench] UCCL_TPU_BENCH_ROUNDS/ITERS must be >= 1")

    if os.environ.get("UCCL_TPU_BENCH_FORCE_CPU", "0").lower() not in (
        "", "0", "false", "no"
    ):
        jax.config.update("jax_platforms", "cpu")
        healthy, platform, device_kind = False, "cpu", "cpu"
    else:
        healthy, platform, device_kind = _probe_device()
    # A successful probe of a non-TPU backend (e.g. JAX_PLATFORMS=cpu in the
    # caller's env) still means the full-size config is off the table.
    on_chip = healthy and platform == "tpu"
    if not on_chip:
        # CPU can't run the full-size model at benchmark cadence
        batch, seq, cfg_shrink = 2, 128, {
            "dim": 256, "n_layers": 2, "n_heads": 4, "n_kv_heads": 2,
            "head_dim": 32, "moe_ffn": 512, "vocab": 2048,
        }
    else:
        # B=32 is the paired-harness HBM ceiling on v5e (B=64 OOMs) and the
        # best measured MFU point (ONCHIP_20260731) — but the ceiling moves
        # with the remat mode's saved-activation footprint: mlp's saved
        # expert tensors OOM at B>=24 (B=16 matches B=32/full throughput
        # anyway), and none saves everything and OOMs even at B=16.
        batch = {"mlp": 16, "none": 8}.get(remat, 32)
        seq, cfg_shrink = 1024, {}
    # On-chip MFU levers, sweepable without code edits (ladder step 7):
    # larger batch raises MXU utilization until HBM runs out. Applied to
    # the baseline too, so vs_baseline stays apples-to-apples.
    batch = batch_env or batch
    seq = seq_env or seq
    rng = np.random.default_rng(0)
    vocab = cfg_shrink.get("vocab", _BASE_VOCAB)
    tokens = jnp.asarray(rng.integers(0, vocab, (batch, seq)), jnp.int32)
    targets = jnp.asarray(rng.integers(0, vocab, (batch, seq)), jnp.int32)

    attn_impl = os.environ.get("UCCL_TPU_BENCH_ATTN", "auto")
    if attn_impl == "auto":
        # resolve before reporting so the JSON names the impl actually run.
        # With auto-sized blocks (cap 1024) flash beats XLA's fused
        # attention 1.7-4x fwd+bwd at every measured flagship shape
        # (PERF.md round-5 block sweep) — TPU always flash, CPU always xla
        # (pallas needs interpret mode off-TPU).
        attn_impl = "flash" if platform == "tpu" else "xla"
    ours_kw = {"moe_impl": moe_impl, "remat": remat, **cfg_shrink}
    flash_failed = None
    try:
        ours = _Harness({"attn_impl": attn_impl, **ours_kw}, tokens, targets)
        ours.warmup()
    except Exception as e:
        if attn_impl != "flash":
            raise  # nothing to fall back to — surface the real failure
        flash_failed = repr(e)
        ours = None
    if flash_failed is not None:
        # Retry outside the except block: a live exception pins the failed
        # run's params/opt_state via its traceback, and both builds must
        # never coexist in HBM.
        print(f"[bench] flash path failed ({flash_failed}); retrying with "
              "attn=xla", file=sys.stderr)
        attn_impl = "xla"
        ours = _Harness({"attn_impl": "xla", **ours_kw}, tokens, targets)
        ours.warmup()

    # Vendor baseline: stock XLA lowering of the same model — dense GShard
    # einsum dispatch, plain XLA attention. Same shapes, same optimizer.
    # Held resident alongside ours so samples interleave; if the pair does
    # not fit in HBM, fall back to sequential sampling (medians still
    # smooth noise, just without drift cancellation).
    base_kw = {"attn_impl": "xla", "moe_impl": "dense", "remat": remat,
               **cfg_shrink}
    sequential, base, ours_dirty = False, None, False
    try:
        base = _Harness(base_kw, tokens, targets)
        base.warmup()
        # The sampling itself is under the guard too: the first
        # ours.sample() with base resident is a peak (ours' scratch + both
        # states) never exercised before this point.
        ours_dirty = True  # donated state consumed once sampling starts
        ours_dts, base_dts = _interleaved_dts(ours, base, rounds, iters)
        cfg = ours.cfg
    except Exception as e:
        # The axon tunnel surfaces HBM OOM as INTERNAL/HTTP 500 "Ran out
        # of memory", not RESOURCE_EXHAUSTED — match both spellings.
        _oom = ("RESOURCE_EXHAUSTED", "ResourceExhausted",
                "Ran out of memory")
        if not any(s in repr(e) for s in _oom):
            raise
        print("[bench] ours+baseline do not fit together; sampling "
              "sequentially", file=sys.stderr)
        sequential = True

    if sequential:
        # One harness at a time, outside the except block (a live traceback
        # pins the dead buffers). The usual OOM site is base's build/warmup
        # — ours is then still warm and sampleable; only an OOM mid-sample
        # (ours_dirty) consumed its donated state and forces a rebuild.
        if base is not None:
            base.free()
        if ours_dirty:
            ours.free()
            ours = _Harness(
                {"attn_impl": attn_impl, **ours_kw}, tokens, targets
            )
            ours.warmup()
        ours_dts = [ours.sample(iters) for _ in range(rounds)]
        cfg = ours.cfg
        ours.free()
        base = _Harness(base_kw, tokens, targets)
        base.warmup()
        base_dts = [base.sample(iters) for _ in range(rounds)]

    dt, base_dt = _median(ours_dts), _median(base_dts)
    tps, base_tps = batch * seq / dt, batch * seq / base_dt
    spread = lambda xs: (max(xs) - min(xs)) / _median(xs)  # noqa: E731

    result = {
        "metric": "flagship_moe_train_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tps / base_tps, 3),
        "step_time_ms": round(dt * 1e3, 2),
        "baseline_tokens_per_sec": round(base_tps, 1),
        # Medians of `rounds` interleaved A-B samples, `iters` steps each;
        # rel_spread = (max-min)/median of the per-round step times. A
        # headline whose spread is wide is noise, not evidence — the JSON
        # now says so itself.
        "rounds": rounds,
        "iters_per_round": iters,
        "rel_spread": round(spread(ours_dts), 3),
        "baseline_rel_spread": round(spread(base_dts), 3),
        "samples_ms": [round(d * 1e3, 1) for d in ours_dts],
        "baseline_samples_ms": [round(d * 1e3, 1) for d in base_dts],
        "device": device_kind,
        "attn_impl": attn_impl,
        "moe_impl": moe_impl,
        "remat": remat,
        "batch": batch,
        "seq": seq,
    }
    peak = _peak_flops(device_kind)
    if peak:
        result["mfu"] = round(
            _model_flops_per_token(cfg, seq) * tps / peak, 4
        )
    if not on_chip:
        # shrunk-config CPU numbers are not comparable to TPU runs
        result["cpu_fallback"] = True
    print(json.dumps(result))


if __name__ == "__main__":
    main()
