"""Benchmark: flagship MoE training-step throughput on the local accelerator.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

The headline metric is end-to-end training tokens/sec of the flagship MoE
transformer (expert-parallel dispatch/combine + ring-attention code paths all
compiled in). ``vs_baseline`` compares against a naive dense-MoE baseline (every
expert computes every token — what you get without an EP dispatch layer), the
moral equivalent of the reference's "vs vendor stack" framing (README.md:29).
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def _init_devices(timeout_s: int = 120):
    """Probe accelerator availability in a subprocess first: a wedged tunnel
    (observed with the axon relay) hangs device init in native code holding
    the GIL, so neither signals nor threads can interrupt it in-process. If
    the probe hangs or fails, this process pins jax to CPU before its own
    first device touch."""
    import subprocess
    import sys

    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        healthy = probe.returncode == 0 and "ok" in probe.stdout
    except subprocess.TimeoutExpired:
        healthy = False
    if not healthy:
        jax.config.update("jax_platforms", "cpu")
    return jax.devices(), not healthy


import jax.numpy as jnp  # noqa: E402


def _build(cfg_kw=None):
    from uccl_tpu.models.flagship import (
        FlagshipConfig,
        init_params,
        make_train_step,
        shard_params,
    )
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    base = dict(
        vocab=16384,
        dim=1024,
        n_layers=4,
        n_heads=16,
        n_kv_heads=4,
        head_dim=64,
        moe_experts=8,
        moe_topk=2,
        moe_ffn=2816,
        capacity_factor=1.25,
        n_microbatches=1,
        dtype=jnp.bfloat16,
        aux_loss_weight=0.01,
        z_loss_weight=1e-3,
    )
    base.update(cfg_kw or {})  # caller overrides (attn impl, CPU shrink)
    cfg = FlagshipConfig(**base)
    mesh = make_mesh(MeshConfig(), jax.devices()[:1])
    params = shard_params(init_params(jax.random.PRNGKey(0), cfg), mesh, cfg)
    train_step, init_opt = make_train_step(cfg, mesh)
    opt_state = init_opt(params)
    return cfg, mesh, params, train_step, opt_state


def _time_steps(step, params, opt_state, tokens, targets, warmup=2, iters=5):
    # NB: sync via a host read of the loss — on tunneled/remote platforms
    # block_until_ready can return before the computation actually finishes.
    for _ in range(max(1, warmup)):  # at least one call so the sync read exists
        params, opt_state, m = step(params, opt_state, tokens, targets)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, m = step(params, opt_state, tokens, targets)
    float(m["loss"])
    return (time.perf_counter() - t0) / iters


def _dense_baseline_step(cfg, mesh):
    """Naive dense-MoE train step: every expert computes every token."""
    import optax

    from uccl_tpu.models.flagship import reference_dense_loss

    tx = optax.adamw(3e-4, weight_decay=0.01)

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(
            lambda p: reference_dense_loss(p, tokens, targets, cfg)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss}

    return step, tx


def main():
    import os

    _, cpu_fallback = _init_devices()
    if cpu_fallback:
        # CPU can't run the full-size model at benchmark cadence
        batch, seq, cfg_shrink = 2, 128, {
            "dim": 256, "n_layers": 2, "n_heads": 4, "n_kv_heads": 2,
            "head_dim": 32, "moe_ffn": 512, "vocab": 2048,
        }
    else:
        batch, seq, cfg_shrink = 8, 1024, {}
    rng = np.random.default_rng(0)
    attn_impl = os.environ.get("UCCL_TPU_BENCH_ATTN", "auto")
    cfg, mesh, params, train_step, opt_state = _build(
        {"attn_impl": attn_impl, **cfg_shrink}
    )
    tokens = jnp.asarray(rng.integers(0, cfg.vocab, (batch, seq)), jnp.int32)
    targets = jnp.asarray(rng.integers(0, cfg.vocab, (batch, seq)), jnp.int32)

    step = jax.jit(train_step)
    uses_flash = attn_impl == "flash" or (
        attn_impl == "auto" and jax.devices()[0].platform == "tpu"
    )
    try:
        dt = _time_steps(step, params, opt_state, tokens, targets)
    except Exception:
        if not uses_flash:
            raise  # nothing to fall back to — surface the real failure
        # Pallas path failed to lower on this backend — fall back to the XLA
        # attention implementation rather than failing the benchmark. Free the
        # first build before rebuilding so both never coexist in HBM.
        del params, opt_state, step
        cfg, mesh, params, train_step, opt_state = _build(
            {"attn_impl": "xla", **cfg_shrink}
        )
        step = jax.jit(train_step)
        dt = _time_steps(step, params, opt_state, tokens, targets)
    tokens_per_sec = batch * seq / dt

    # Baseline: dense-MoE (no EP dispatch) training step, same model size.
    # Smaller batch (throughput is per-token) and the MoE state freed first so
    # both runs fit HBM independently.
    del params, opt_state
    dense_step, tx = _dense_baseline_step(cfg, mesh)
    from uccl_tpu.models.flagship import init_params, shard_params

    dense_params = shard_params(init_params(jax.random.PRNGKey(0), cfg), mesh, cfg)
    dense_opt = tx.init(dense_params)
    dbatch = 2
    ddt = _time_steps(
        jax.jit(dense_step),
        dense_params,
        dense_opt,
        tokens[:dbatch],
        targets[:dbatch],
    )
    dense_tps = dbatch * seq / ddt

    result = {
        "metric": "flagship_moe_train_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / dense_tps, 3),
    }
    if cpu_fallback:
        # shrunk-config CPU numbers are not comparable to TPU runs
        result["cpu_fallback"] = True
    print(json.dumps(result))


if __name__ == "__main__":
    main()
