"""Compute/communication overlap: chunked DCN all-reduce under live GEMMs.

The measurable value proposition of an *explicit* collective schedule
(collective/plan.py; reference: experimental/ukernel's chunk executor,
src/ccl/executor.h:26-60): a monolithic all-reduce-then-compute step
serializes the wire behind the MXU, while a chunked schedule lets gradient
chunk i ride the DCN (native engine tx/io threads) WHILE the compute for
chunk i+1 runs. XLA cannot do this across a host collective — the DCN ring
is outside the XLA program — so the explicit plan is the only way to buy
the overlap.

Setup: 2 ranks over TCP loopback (DcnGroup ring), each all-reducing an
N-MB gradient while running a fixed GEMM workload (jitted matmul chain).

  serial    = all_reduce(grad)      ; then the GEMM workload
  overlap   = for each chunk: submit all_reduce(chunk) to a comm thread,
              run the next GEMM slice on the main thread, join at the end

Prints one JSON line per config with the overlap ratio (lower is better;
the floor is max(comm, compute) / (comm + compute)).
"""

from __future__ import annotations

import json
import os
import sys
import multiprocessing as mp
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _gemm_workload(jnp, d: int, chain: int):
    import jax

    @jax.jit
    def step(a, b):
        for _ in range(chain):
            a = jnp.tanh(a @ b)
        return a

    return step


def _run_rank(rank, world, port, grad_mb, chunks, gemm_d, gemm_chain,
              gemm_reps, out):
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Emulate the TPU regime on the host-only substrate: on a pod the GEMMs
    # run ON-CHIP and host cores are free to drive the DCN; multi-threaded
    # eigen GEMMs would instead saturate every host core and starve the
    # comm thread of CPU, measuring contention rather than overlap.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_cpu_multi_thread_eigen=false"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from uccl_tpu.collective.hierarchical import DcnGroup
    from uccl_tpu.p2p.store import StoreClient
    from uccl_tpu.parallel.distributed import Session

    client = StoreClient("127.0.0.1", port)
    sess = Session(rank=rank, world=world, store=client)
    dcn = DcnGroup(sess, n_paths=2, tag="ovl")
    try:
        n = grad_mb * (1 << 20) // 4
        grad = np.random.default_rng(rank).standard_normal(n).astype(np.float32)
        step = _gemm_workload(jnp, gemm_d, gemm_chain)
        a = jnp.ones((gemm_d, gemm_d), jnp.float32) * 0.01
        b = jnp.eye(gemm_d, dtype=jnp.float32)
        step(a, b).block_until_ready()  # compile

        def compute(reps):
            x = a
            for _ in range(reps):
                x = step(x, b)
            x.block_until_ready()
            return x

        # measure the legs once (rank-local, for the report)
        t0 = time.perf_counter()
        _ = dcn.all_reduce(grad)
        t_comm = time.perf_counter() - t0
        t0 = time.perf_counter()
        compute(gemm_reps)
        t_compute = time.perf_counter() - t0

        results = {}
        for mode in ("serial", "overlap"):
            dcn.barrier()
            t0 = time.perf_counter()
            if mode == "serial":
                _ = dcn.all_reduce(grad)
                compute(gemm_reps)
            else:
                parts = np.array_split(grad, chunks)
                reps_per = [gemm_reps // chunks] * chunks
                reps_per[-1] += gemm_reps - sum(reps_per)
                moved_during_compute = 0
                with ThreadPoolExecutor(max_workers=1) as pool:
                    futs = []
                    for i, part in enumerate(parts):
                        futs.append(pool.submit(dcn.all_reduce, part))
                        tx0 = dcn.ep.stats["bytes_tx"]
                        compute(reps_per[i])
                        # wire progress made by the engine threads WHILE this
                        # thread sat inside jitted compute — the overlap
                        # mechanism itself, independent of core count
                        moved_during_compute += dcn.ep.stats["bytes_tx"] - tx0
                    reduced = [f.result() for f in futs]
                assert sum(r.size for r in reduced) == grad.size
                results["moved_during_compute"] = moved_during_compute
            dcn.barrier()
            results[mode] = time.perf_counter() - t0
        results["comm_ms"] = t_comm * 1e3
        results["compute_ms"] = t_compute * 1e3
        out[rank] = results
    finally:
        dcn.close()
        client.close()


def run(grad_mb=128, chunks=8, gemm_d=1024, gemm_chain=8, gemm_reps=4):
    # ranks are PROCESSES: thread-ranks would share one GIL/CPU budget and
    # the contention would masquerade as (anti-)overlap
    from uccl_tpu.p2p.store import StoreServer

    server = StoreServer()
    mgr = mp.Manager()
    out = mgr.dict()
    ps = [
        mp.get_context("spawn").Process(
            target=_run_rank,
            args=(r, 2, server.port, grad_mb, chunks, gemm_d, gemm_chain,
                  gemm_reps, out),
        )
        for r in range(2)
    ]
    [t.start() for t in ps]
    [t.join(timeout=600) for t in ps]
    server.close()
    assert 0 in out and 1 in out, dict(out)
    r0 = out[0]
    ratio = r0["overlap"] / r0["serial"]
    floor = max(r0["comm_ms"], r0["compute_ms"]) / (
        r0["comm_ms"] + r0["compute_ms"]
    )
    bytes_frac = r0.get("moved_during_compute", 0) / (grad_mb * (1 << 20))
    from uccl_tpu import obs

    line = {
        "grad_mb": grad_mb,
        "schema_version": obs.SCHEMA_VERSION,
        "chunks": chunks,
        "serial_ms": round(r0["serial"] * 1e3, 1),
        "overlap_ms": round(r0["overlap"] * 1e3, 1),
        "overlap_vs_serial": round(ratio, 3),
        "ideal_floor": round(floor, 3),
        "comm_ms": round(r0["comm_ms"], 1),
        "compute_ms": round(r0["compute_ms"], 1),
        # fraction of the gradient's wire bytes that moved while the main
        # thread was inside compute: the overlap mechanism at work
        "bytes_moved_during_compute_frac": round(bytes_frac, 3),
        # the shared EP/plan metric name (docs/EP_BENCH.md): how much of the
        # wire was hidden under resident compute. Here the byte counter IS
        # the mechanism-level measurement, so it defines the metric...
        "overlap_efficiency": round(bytes_frac, 3),
        # ...and the wall-clock view of the same thing — the fraction of the
        # comm leg the chunked schedule actually removed from the serial
        # wall (<= 0 on a 1-core host where nothing can hide; approaches
        # bytes_moved_during_compute_frac as cores free up)
        "wire_time_hidden_frac": round(
            (r0["serial"] - r0["overlap"]) / max(r0["comm_ms"] / 1e3, 1e-9),
            3,
        ),
        "host_cores": os.cpu_count(),
    }
    print(json.dumps(line))
    return line


if __name__ == "__main__":
    import argparse

    from uccl_tpu import obs

    ap = argparse.ArgumentParser()
    ap.add_argument("--grad-mb", type=int, default=128)
    ap.add_argument("--chunks", type=int, default=8)
    obs.add_cli_args(ap)
    _args = ap.parse_args()
    obs.setup_from_args(_args)
    obs.dump_at_exit(_args)  # covers crashes too
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    run(grad_mb=_args.grad_mb, chunks=_args.chunks)
