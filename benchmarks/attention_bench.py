"""Flash-attention microbenchmark: Pallas kernels vs stock XLA attention.

Measures forward and forward+backward wall time and model TFLOP/s on the
local accelerator at a sweep of sequence lengths, causal, GQA-shaped.
This is the recorded evidence VERDICT round-1 item 2 asked for (the
reference's measured-wins culture: README.md:29, ep/bench/test_low_latency.py
metric definitions — report numbers, not vibes).

FLOP accounting (matmuls only): causal attention does ~half the score work,
so fwd = 2 * 2 * B*H*S^2*D * 0.5 (qk^T + p@v), bwd = 2.5x fwd (dq/dk/dv
recompute from LSE included for the flash path so both paths are charged the
same model FLOPs — utilization, not kernel-internal work).

Usage: python benchmarks/attention_bench.py [--seqs 1024,2048,4096,8192]
"""

from __future__ import annotations

import argparse
import functools
import json
import time

from _bootstrap import init_devices


def _ref_attention(q, k, v, causal=True):
    import jax
    import jax.numpy as jnp
    import numpy as np

    b, s, h, d = q.shape
    n_rep = h // k.shape[2]
    kk = jnp.repeat(k, n_rep, axis=2)
    vv = jnp.repeat(v, n_rep, axis=2)
    scores = (
        jnp.einsum(
            "bqhd,bkhd->bhqk",
            q.astype(jnp.float32),
            kk.astype(jnp.float32),
        )
        / np.sqrt(d)
    )
    if causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask, scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vv.astype(jnp.float32)).astype(
        q.dtype
    )


def _time(step, q, k, v, iters=10, warmup=2):
    """Time `step(q, k, v) -> (q', k', v')` by running `iters` chained
    repetitions inside ONE jitted `lax.fori_loop` dispatch, then forcing a
    host scalar read. Two tunneled-platform (axon) hazards drive this shape:
    `block_until_ready` can return before device work finishes (so: the
    dependency chain + host read), and per-dispatch overhead is ~10 ms (so:
    one dispatch for the whole measurement, not one per iteration)."""
    import jax
    from jax import lax
    import jax.numpy as jnp

    @jax.jit
    def run(state, n):
        return lax.fori_loop(0, n, lambda _, s: step(*s), state)

    state = run((q, k, v), warmup)
    float(jnp.sum(state[0][0, 0, 0]))  # sync the warmup/compile
    t0 = time.perf_counter()
    state = run(state, iters)
    float(jnp.sum(state[0][0, 0, 0]))
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0)
    # Defaults mirror the FLAGSHIP head geometry (B=8, NH=16, KV=4,
    # D=64): the old tiny defaults (B=2, H=8) under-utilized the chip and
    # produced a flash-vs-XLA crossover that did not transfer to the
    # model (PERF.md round-5 "Harness lesson")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--kv-heads", type=int, default=4)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--seqs", default="1024,2048,4096,8192")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument(
        "--block-sweep", action="store_true",
        help="sweep flash (block_q, block_k) tiles at each seq and print "
        "the fastest — the on-chip tuning pass (VERDICT: tune blocks at "
        "long sequence until flash beats XLA in its claimed regime)",
    )
    ap.add_argument(
        "--blocks", default="128,256,512,1024",
        help="candidate tile sizes for --block-sweep (1024 is the "
        "measured v5e optimum at head_dim 64)",
    )
    ap.add_argument(
        "--skip-xla-bwd-at",
        type=int,
        default=16384,
        help="skip the XLA fwd+bwd datapoint at/above this seq (it "
        "materializes [S,S] and OOMs / thrashes first)",
    )
    args = ap.parse_args()

    jax = init_devices(args.devices)
    import jax.numpy as jnp

    from uccl_tpu.ops.pallas_attention import flash_attention

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {getattr(dev, 'device_kind', '?')}")
    b, h, kv, d = args.batch, args.heads, args.kv_heads, args.head_dim

    rows = []
    for s in [int(x) for x in args.seqs.split(",")]:
        q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d), jnp.bfloat16)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, s, kv, d), jnp.bfloat16)
        v = jax.random.normal(jax.random.PRNGKey(2), (b, s, kv, d), jnp.bfloat16)
        fwd_flops = 2 * 2 * b * h * s * s * d * 0.5

        # Each step folds the measured op's output back into q (tiny scaled
        # add — negligible next to attention) so iterations form an on-device
        # dependency chain; see _time.
        def _chain_fwd(attn):
            def step(q, k, v):
                return q + 1e-6 * attn(q, k, v).astype(q.dtype), k, v
            return jax.jit(step)

        def _chain_bwd(attn):
            # grad wrt all three — grad-wrt-q-only would let XLA dead-code
            # the dk/dv kernel and we'd time half the backward.
            def step(q, k, v):
                def loss(q_, k_, v_):
                    o = attn(q_, k_, v_)
                    return jnp.sum(o.astype(jnp.float32) ** 2)
                dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
                return (
                    q + 1e-9 * dq.astype(q.dtype),
                    k + 1e-9 * dk.astype(k.dtype),
                    v + 1e-9 * dv.astype(v.dtype),
                )
            return jax.jit(step)

        if args.block_sweep:
            cands = [int(x) for x in args.blocks.split(",")]
            best = None
            for bq in cands:
                for bk in cands:
                    # a tile larger than S would silently clamp inside the
                    # kernel and re-measure (S, S) under a wrong label
                    if bq > s or bk > s or s % bq or s % bk:
                        continue
                    fn = _chain_fwd(functools.partial(
                        flash_attention, causal=True, block_q=bq, block_k=bk
                    ))
                    try:
                        dt = _time(fn, q, k, v, iters=args.iters)
                    except Exception as e:
                        print(json.dumps({"seq": s, "bq": bq, "bk": bk,
                                          "error": type(e).__name__}))
                        continue
                    rec = {"seq": s, "bq": bq, "bk": bk,
                           "ms": round(dt * 1e3, 3),
                           "tflops": round(fwd_flops / dt / 1e12, 2)}
                    print(json.dumps(rec))
                    if best is None or dt < best[0]:
                        best = (dt, rec)
            if best:
                print(json.dumps({"seq": s, "best": best[1]}))
            continue

        flash = _chain_fwd(functools.partial(flash_attention, causal=True))
        xla = _chain_fwd(functools.partial(_ref_attention, causal=True))
        g_flash = _chain_bwd(functools.partial(flash_attention, causal=True))
        g_xla = _chain_bwd(functools.partial(_ref_attention, causal=True))

        row = {"seq": s}
        for name, fn, flops in (
            ("flash_fwd", flash, fwd_flops),
            ("xla_fwd", xla, fwd_flops),
            ("flash_fwdbwd", g_flash, 3.5 * fwd_flops),
            ("xla_fwdbwd", g_xla, 3.5 * fwd_flops),
        ):
            if name == "xla_fwdbwd" and s >= args.skip_xla_bwd_at:
                row[name] = None
                continue
            try:
                dt = _time(fn, q, k, v, iters=args.iters)
            except Exception as e:  # OOM at long seq is a *result* here
                print(f"  {name} s={s}: {type(e).__name__}")
                row[name] = None
                continue
            row[name] = {"ms": round(dt * 1e3, 3), "tflops": round(flops / dt / 1e12, 2)}
        for k2 in ("fwd", "fwdbwd"):
            fr, xr = row.get(f"flash_{k2}"), row.get(f"xla_{k2}")
            if fr and xr:
                row[f"speedup_{k2}"] = round(xr["ms"] / fr["ms"], 3)
        rows.append(row)
        print(json.dumps(row))

    print(json.dumps({"device": f"{dev.platform} {getattr(dev, 'device_kind', '?')}", "rows": rows}))


if __name__ == "__main__":
    main()
