"""AllReduce performance sweep — the nccl-tests ``all_reduce_perf`` analog.

The reference's acceptance benchmark is nccl-tests' all_reduce_perf driven over
the UCCL plugin (collective/rdma/run_nccl_test.sh, SURVEY.md §4.5); this sweeps
message sizes over the mesh and prints alg/bus bandwidth per size for both the
XLA-scheduled and the explicit chunk-ring allreduce.

Bus bandwidth uses the standard ring factor 2*(n-1)/n over the data size.

Usage: python benchmarks/all_reduce_perf.py [--devices N] [--algo xla|ring|both]
On a machine without multiple accelerators, pass --devices N to use N virtual
CPU devices.

``--wire-dtype fp8,int8`` adds the quantized-wire arms (pallas ring,
``wire_dtype=`` — docs/QUANT_WIRE.md): per size it prints one JSON line per
arm with the per-shard wire bytes read off the REAL
``ep_bytes_total{verb="ring_all_reduce",...,wire_dtype}`` counter delta
(quantized payload + scale sidecar, counted at trace time by the rings
themselves — never mirrored arithmetic), the effective per-member wire
bandwidth those bytes imply, the wire-byte reduction vs the full-precision
arm, and the max-abs/rel error vs the full-precision result.
"""

from __future__ import annotations

import argparse
import json
import time

from _bootstrap import init_devices


def _ring_bytes_snapshot():
    from uccl_tpu.obs import counters as obsc

    fam = obsc.counter("ep_bytes_total")
    return {tuple(sorted(lb.items())): v for lb, v in fam.samples()
            if lb.get("verb") == "ring_all_reduce"}


def _ring_bytes_delta(before):
    out = {}
    for kk, v in _ring_bytes_snapshot().items():
        d = v - before.get(kk, 0)
        if d > 0:
            out[dict(kk)["wire_dtype"]] = out.get(
                dict(kk)["wire_dtype"], 0) + int(d)
    return out


def quant_sweep(jax, n, wire_dtypes, args):
    """Quantized-wire arms: per (size, wire_dtype) one JSON line — wire
    bytes off the counter delta around the compiling call, effective
    per-member wire bandwidth, wire-byte reduction and error vs the
    full-precision pallas arm."""
    import numpy as np
    from jax.sharding import Mesh

    from uccl_tpu import obs
    from uccl_tpu.collective import Communicator

    # 1-axis mesh: the legacy discharge interpreter addresses peers by flat
    # logical id along ONE named axis — same choice as ep_bench's pallas arm
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    comm = Communicator(mesh, "dp")

    size = args.min_bytes
    while size <= args.max_bytes:
        elems = size // 4
        x = comm.device_put(
            np.random.default_rng(0)
            .standard_normal((n, elems))
            .astype(np.float32)
        )
        arms = []
        ref = None
        ref_bytes = None
        for wd in [None] + list(wire_dtypes):
            before = _ring_bytes_snapshot()
            out = comm.all_reduce(x, algo="pallas", wire_dtype=wd)
            got = np.asarray(out)  # compile + host sync
            wire_bytes = _ring_bytes_delta(before).get(wd or "none", 0)
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = comm.all_reduce(x, algo="pallas", wire_dtype=wd)
            np.asarray(out)
            dt = (time.perf_counter() - t0) / args.iters
            if wd is None:
                ref, ref_bytes = got, wire_bytes
                err_abs = err_rel = 0.0
            else:
                err_abs = float(np.abs(got - ref).max())
                err_rel = float(err_abs / (np.abs(ref).max() + 1e-12))
            arms.append({
                "wire_dtype": wd or "none",
                "time_us": round(dt * 1e6, 1),
                "wire_bytes_per_shard": wire_bytes,
                "wire_gbps_per_member": round(wire_bytes / dt / 1e9, 3),
                "wire_byte_reduction": round(
                    ref_bytes / wire_bytes, 2) if wire_bytes else None,
                "max_abs_err": err_abs,
                "max_rel_err": err_rel,
            })
        print(json.dumps({
            "bench": "all_reduce_quant",
            "schema_version": obs.SCHEMA_VERSION,
            "bytes": size, "world": n,
            "substrate": jax.default_backend(),
            "arms": arms,
        }))
        size *= 4


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0,
                    help="force N virtual CPU devices (0 = use real devices)")
    ap.add_argument(
        "--algo", default="both",
        choices=["xla", "ring", "hd", "torus", "pallas", "both", "all"]
    )
    ap.add_argument(
        "--mesh2d", default="", metavar="AxB",
        help="use a 2D mesh (e.g. 2x4) — enables the torus algo",
    )
    ap.add_argument("--min-bytes", type=int, default=1 << 12)
    ap.add_argument("--max-bytes", type=int, default=1 << 26)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument(
        "--wire-dtype", default="",
        help="comma list of quantized pallas-ring arms to sweep "
             "(e.g. 'fp8,int8'): JSON line per size with counter-derived "
             "wire bytes, effective bandwidth, and error vs full precision",
    )
    args = ap.parse_args()

    jax = init_devices(args.devices)

    import numpy as np

    from uccl_tpu.collective import Communicator
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    n = len(jax.devices())
    if args.wire_dtype:
        # quant_sweep builds its own raw single-axis mesh (the legacy
        # discharge interpreter can't address peers on the canonical
        # 4-axis make_mesh mesh) — dispatch before constructing one here
        if args.mesh2d:
            ap.error("--wire-dtype rides the single-axis pallas ring; "
                     "drop --mesh2d")
        wire_dtypes = [w for w in args.wire_dtype.split(",") if w]
        for w in wire_dtypes:
            if w not in ("fp8", "int8"):
                ap.error(f"unknown --wire-dtype arm {w!r} (want fp8/int8)")
        quant_sweep(jax, n, wire_dtypes, args)
        return
    if args.mesh2d:
        a, b = (int(v) for v in args.mesh2d.lower().split("x"))
        assert a * b == n, f"mesh {a}x{b} != {n} devices"
        mesh = make_mesh(MeshConfig(dp=a, tp=b))
        comm = Communicator(mesh, ("dp", "tp"))
    else:
        mesh = make_mesh(MeshConfig(dp=n))
        comm = Communicator(mesh, "dp")

    if args.algo == "both":
        algos = ["xla", "ring"]
    elif args.algo == "all":
        algos = ["xla", "ring", "hd", "pallas"] + (["torus"] if args.mesh2d else [])
    else:
        algos = [args.algo]

    print(f"# all_reduce_perf  world={n}  devices={jax.devices()[0].platform}")
    print(f"# {'bytes':>12} {'algo':>6} {'time_us':>10} {'algbw_GB/s':>10} {'busbw_GB/s':>10}")
    size = args.min_bytes
    while size <= args.max_bytes:
        elems = size // 4
        x = comm.device_put(
            np.random.default_rng(0).standard_normal((n, elems)).astype(np.float32)
        )
        for algo in algos:
            if algo == "hd" and n & (n - 1):
                # hd falls back to ring off power-of-two worlds; skip rather
                # than record ring timings under the hd label
                continue
            if algo == "pallas" and args.mesh2d:
                continue  # pallas rings a single mesh axis
            out = comm.all_reduce(x, algo=algo)  # compile + warmup
            np.asarray(out)
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = comm.all_reduce(x, algo=algo)
            np.asarray(out)  # host read = hard sync (axon-safe)
            dt = (time.perf_counter() - t0) / args.iters
            algbw = size / dt / 1e9
            busbw = algbw * 2 * (n - 1) / n
            print(
                f"  {size:>12} {algo:>6} {dt * 1e6:>10.1f} {algbw:>10.3f} {busbw:>10.3f}"
            )
        size *= 4


if __name__ == "__main__":
    main()
