"""AllReduce performance sweep — the nccl-tests ``all_reduce_perf`` analog.

The reference's acceptance benchmark is nccl-tests' all_reduce_perf driven over
the UCCL plugin (collective/rdma/run_nccl_test.sh, SURVEY.md §4.5); this sweeps
message sizes over the mesh and prints alg/bus bandwidth per size for both the
XLA-scheduled and the explicit chunk-ring allreduce.

Bus bandwidth uses the standard ring factor 2*(n-1)/n over the data size.

Usage: python benchmarks/all_reduce_perf.py [--devices N] [--algo xla|ring|both]
On a machine without multiple accelerators, pass --devices N to use N virtual
CPU devices.
"""

from __future__ import annotations

import argparse
import time

from _bootstrap import init_devices


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0,
                    help="force N virtual CPU devices (0 = use real devices)")
    ap.add_argument(
        "--algo", default="both",
        choices=["xla", "ring", "hd", "torus", "pallas", "both", "all"]
    )
    ap.add_argument(
        "--mesh2d", default="", metavar="AxB",
        help="use a 2D mesh (e.g. 2x4) — enables the torus algo",
    )
    ap.add_argument("--min-bytes", type=int, default=1 << 12)
    ap.add_argument("--max-bytes", type=int, default=1 << 26)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    jax = init_devices(args.devices)

    import numpy as np

    from uccl_tpu.collective import Communicator
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    n = len(jax.devices())
    if args.mesh2d:
        a, b = (int(v) for v in args.mesh2d.lower().split("x"))
        assert a * b == n, f"mesh {a}x{b} != {n} devices"
        mesh = make_mesh(MeshConfig(dp=a, tp=b))
        comm = Communicator(mesh, ("dp", "tp"))
    else:
        mesh = make_mesh(MeshConfig(dp=n))
        comm = Communicator(mesh, "dp")
    if args.algo == "both":
        algos = ["xla", "ring"]
    elif args.algo == "all":
        algos = ["xla", "ring", "hd", "pallas"] + (["torus"] if args.mesh2d else [])
    else:
        algos = [args.algo]

    print(f"# all_reduce_perf  world={n}  devices={jax.devices()[0].platform}")
    print(f"# {'bytes':>12} {'algo':>6} {'time_us':>10} {'algbw_GB/s':>10} {'busbw_GB/s':>10}")
    size = args.min_bytes
    while size <= args.max_bytes:
        elems = size // 4
        x = comm.device_put(
            np.random.default_rng(0).standard_normal((n, elems)).astype(np.float32)
        )
        for algo in algos:
            if algo == "hd" and n & (n - 1):
                # hd falls back to ring off power-of-two worlds; skip rather
                # than record ring timings under the hd label
                continue
            if algo == "pallas" and args.mesh2d:
                continue  # pallas rings a single mesh axis
            out = comm.all_reduce(x, algo=algo)  # compile + warmup
            np.asarray(out)
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = comm.all_reduce(x, algo=algo)
            np.asarray(out)  # host read = hard sync (axon-safe)
            dt = (time.perf_counter() - t0) / args.iters
            algbw = size / dt / 1e9
            busbw = algbw * 2 * (n - 1) / n
            print(
                f"  {size:>12} {algo:>6} {dt * 1e6:>10.1f} {algbw:>10.3f} {busbw:>10.3f}"
            )
        size *= 4


if __name__ == "__main__":
    main()
