"""AllReduce performance sweep — the nccl-tests ``all_reduce_perf`` analog.

The reference's acceptance benchmark is nccl-tests' all_reduce_perf driven over
the UCCL plugin (collective/rdma/run_nccl_test.sh, SURVEY.md §4.5); this sweeps
message sizes over the mesh and prints alg/bus bandwidth per size for both the
XLA-scheduled and the explicit chunk-ring allreduce.

Bus bandwidth uses the standard ring factor 2*(n-1)/n over the data size.

Usage: python benchmarks/all_reduce_perf.py [--devices N] [--algo xla|ring|both]
On a machine without multiple accelerators, pass --devices N to use N virtual
CPU devices.

``--wire-dtype fp8,int8`` adds the quantized-wire arms (pallas ring,
``wire_dtype=`` — docs/QUANT_WIRE.md): per size it prints one JSON line per
arm with the per-shard wire bytes read off the REAL
``ep_bytes_total{verb="ring_all_reduce",...,wire_dtype}`` counter delta
(quantized payload + scale sidecar, counted at trace time by the rings
themselves — never mirrored arithmetic), the effective per-member wire
bandwidth those bytes imply, the wire-byte reduction vs the full-precision
arm, and the max-abs/rel error vs the full-precision result.

``--json`` switches the algo sweep to one ``all_reduce_plan`` JSON line
per size: every arm labeled off the REAL ``collective_plan_total`` counter
delta around its compile (the planner's decision, never the CLI arg
mirrored back) with the cost model's ``modeled_us`` (read off the
``collective_plan_predicted_us`` gauge the planner set) beside the
measured time — the record ``scripts/plan_calibrate.py`` refits the
alpha/beta/gamma constants from. ``--check`` makes every arm's result an
oracle assertion against an independent numpy sum (exit nonzero on mismatch) — the CI
planner smoke rides this. ``--metrics-out`` dumps the Prometheus
snapshot (``scripts/check_obs.py --plan`` validates the plan series
against the emitted JSON); ``--trace-out`` records the ``collective_plan``
decision instants.
"""

from __future__ import annotations

import argparse
import json
import time

from _bootstrap import init_devices


def _ring_bytes_snapshot():
    from uccl_tpu.obs import counters as obsc

    fam = obsc.counter("ep_bytes_total")
    return {tuple(sorted(lb.items())): v for lb, v in fam.samples()
            if lb.get("verb") == "ring_all_reduce"}


def _plan_snapshot():
    from uccl_tpu.obs import counters as obsc

    fam = obsc.counter("collective_plan_total")
    return {tuple(sorted(lb.items())): v for lb, v in fam.samples()}


def _planned_label(before, verb=None):
    """The plan decision an arm ACTUALLY emitted (counter delta around its
    compile) — the real label, never the CLI arg mirrored back. A
    ``fallback`` delta (the planned kernel degraded to its lax mirror at
    trace time) wins over the decision delta: the arm's timings are the
    mirror's, and plan_calibrate must be able to exclude them. Otherwise
    the largest plan delta; None if nothing moved. ``verb`` restricts to
    one verb's series (broadcast/all_gather carry a verb= label; the
    allreduce series has none — verb=None)."""
    deltas = []
    for k, v in _plan_snapshot().items():
        d = v - before.get(k, 0)
        lb = dict(k)
        if d > 0 and lb.get("algo") != "ep_a2a" and lb.get("verb") == verb:
            deltas.append((d, lb))
    if not deltas:
        return None
    for _, lb in deltas:
        if lb.get("outcome") == "fallback":
            return lb
    return max(deltas, key=lambda t: t[0])[1]


def _modeled_us(label):
    """The cost model's prediction for a plan label, read off the gauge the
    planner set at decision time (shared arithmetic, not mirrored)."""
    from uccl_tpu.obs import counters as obsc

    extra = {"verb": label["verb"]} if label.get("verb") else {}
    return obsc.gauge("collective_plan_predicted_us").get(
        algo=label["algo"], chunks=label["chunks"],
        wire_dtype=label["wire_dtype"], **extra,
    )


def _ring_bytes_delta(before):
    out = {}
    for kk, v in _ring_bytes_snapshot().items():
        d = v - before.get(kk, 0)
        if d > 0:
            out[dict(kk)["wire_dtype"]] = out.get(
                dict(kk)["wire_dtype"], 0) + int(d)
    return out


def quant_sweep(jax, n, wire_dtypes, args):
    """Quantized-wire arms: per (size, wire_dtype) one JSON line — wire
    bytes off the counter delta around the compiling call, effective
    per-member wire bandwidth, wire-byte reduction and error vs the
    full-precision pallas arm."""
    import numpy as np
    from jax.sharding import Mesh

    from uccl_tpu import obs
    from uccl_tpu.collective import Communicator

    # 1-axis mesh: the legacy discharge interpreter addresses peers by flat
    # logical id along ONE named axis — same choice as ep_bench's pallas arm
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    comm = Communicator(mesh, "dp")

    size = args.min_bytes
    while size <= args.max_bytes:
        elems = size // 4
        x = comm.device_put(
            np.random.default_rng(0)
            .standard_normal((n, elems))
            .astype(np.float32)
        )
        arms = []
        ref = None
        ref_bytes = None
        for wd in [None] + list(wire_dtypes):
            before = _ring_bytes_snapshot()
            out = comm.all_reduce(x, algo="pallas", wire_dtype=wd)
            got = np.asarray(out)  # compile + host sync
            wire_bytes = _ring_bytes_delta(before).get(wd or "none", 0)
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = comm.all_reduce(x, algo="pallas", wire_dtype=wd)
            np.asarray(out)
            dt = (time.perf_counter() - t0) / args.iters
            if wd is None:
                ref, ref_bytes = got, wire_bytes
                err_abs = err_rel = 0.0
            else:
                err_abs = float(np.abs(got - ref).max())
                err_rel = float(err_abs / (np.abs(ref).max() + 1e-12))
            arms.append({
                "wire_dtype": wd or "none",
                "time_us": round(dt * 1e6, 1),
                "wire_bytes_per_shard": wire_bytes,
                "wire_gbps_per_member": round(wire_bytes / dt / 1e9, 3),
                "wire_byte_reduction": round(
                    ref_bytes / wire_bytes, 2) if wire_bytes else None,
                "max_abs_err": err_abs,
                "max_rel_err": err_rel,
            })
        print(json.dumps({
            "bench": "all_reduce_quant",
            "schema_version": obs.SCHEMA_VERSION,
            "bytes": size, "world": n,
            "substrate": jax.default_backend(),
            "arms": arms,
        }))
        size *= 4


def _bcast_bytes_snapshot():
    from uccl_tpu.obs import counters as obsc

    fam = obsc.counter("ep_bytes_total")
    return {tuple(sorted(lb.items())): v for lb, v in fam.samples()
            if lb.get("verb") == "bcast"}


def verb_sweep(jax, n, verb, args):
    """The --bench bcast|ag arms: per size one ``collective_plan`` JSON
    line whose arms are labeled off the REAL
    ``collective_plan_total{verb=...}`` counter delta (the new verbs'
    decisions — docs/PLAN_BENCH.md round-9) with the gauge-read
    modeled_us beside the measured time; broadcast arms additionally
    carry the counter-audited per-member wire bytes (``ep_bytes_total
    {verb="bcast"}`` delta) so the psum-baseline reduction is a recorded
    counter fact. ``--check`` asserts every arm bit-exact against the
    root row / input (broadcast and all-gather are pure data movement at
    full precision)."""
    import numpy as np
    from jax.sharding import Mesh

    from uccl_tpu import obs
    from uccl_tpu.collective import Communicator

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    comm = Communicator(mesh, "dp")
    algos = (["psum", "xla", "tree", "scatter_ag", "auto"]
             if verb == "bcast" else ["xla", "ring", "bidir", "auto"])
    plan_verb = "broadcast" if verb == "bcast" else "all_gather"
    root = 1 % n
    failed = 0

    size = args.min_bytes
    while size <= args.max_bytes:
        elems = size // 4
        x = np.random.default_rng(0).standard_normal(
            (n, elems)).astype(np.float32)
        gx = comm.device_put(x)
        ref = np.tile(x[root], (n, 1)) if verb == "bcast" else x
        arms = []
        for algo in algos:
            before = _plan_snapshot()
            bbytes = _bcast_bytes_snapshot() if verb == "bcast" else {}
            if verb == "bcast":
                out = comm.broadcast(gx, root, algo=algo)
            else:
                out = comm.all_gather(gx, algo=algo)
            got = np.asarray(out)  # compile + host sync
            label = _planned_label(before, plan_verb) or {
                "algo": algo, "chunks": "1", "wire_dtype": "none",
                "verb": plan_verb}
            wire_delta = None
            if verb == "bcast":
                wire_delta = sum(
                    int(v - bbytes.get(k, 0))
                    for k, v in _bcast_bytes_snapshot().items()
                    if v - bbytes.get(k, 0) > 0
                ) or None
            t0 = time.perf_counter()
            for _ in range(args.iters):
                if verb == "bcast":
                    out = comm.broadcast(gx, root, algo=algo)
                else:
                    out = comm.all_gather(gx, algo=algo)
            np.asarray(out)
            dt = (time.perf_counter() - t0) / args.iters
            ok = bool(np.array_equal(got, ref))
            if args.check and not ok:
                print(f"all_reduce_perf: CHECK FAILED {verb}/{algo} @ "
                      f"{size}B (planned {label['algo']})", flush=True)
                failed = 1
            arms.append({
                "requested": algo,
                "algo": label["algo"],
                "chunks": int(label["chunks"]),
                "outcome": label.get("outcome", "explicit"),
                "time_us": round(dt * 1e6, 1),
                "modeled_us": round(_modeled_us(label), 2),
                "wire_bytes_per_member": wire_delta,
                "oracle_ok": ok,
            })
        print(json.dumps({
            "bench": "collective_plan",
            "verb": plan_verb,
            "schema_version": obs.SCHEMA_VERSION,
            "bytes": size, "world": n, "root": root, "n_axes": 1,
            "mesh2d": None,
            "substrate": jax.default_backend(),
            "arms": arms,
        }), flush=True)
        size *= 4
    return failed


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0,
                    help="force N virtual CPU devices (0 = use real devices)")
    ap.add_argument(
        "--algo", default="both",
        choices=["xla", "ring", "hd", "torus", "pallas", "bidir", "auto",
                 "both", "all"]
    )
    ap.add_argument(
        "--mesh2d", default="", metavar="AxB",
        help="use a 2D mesh (e.g. 2x4) — enables the torus algo",
    )
    ap.add_argument("--min-bytes", type=int, default=1 << 12)
    ap.add_argument("--max-bytes", type=int, default=1 << 26)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument(
        "--wire-dtype", default="",
        help="comma list of quantized pallas-ring arms to sweep "
             "(e.g. 'fp8,int8'): JSON line per size with counter-derived "
             "wire bytes, effective bandwidth, and error vs full precision",
    )
    ap.add_argument("--json", action="store_true",
                    help="emit one all_reduce_plan JSON line per size: arms "
                         "labeled off the real collective_plan_total delta "
                         "with modeled_us beside measured (the record "
                         "plan_calibrate.py refits from)")
    ap.add_argument("--bench", default="ar",
                    help="comma list of verbs to sweep: ar (the allreduce "
                         "sweep, default) and/or bcast,ag — the broadcast/"
                         "all-gather arms emit collective_plan JSON lines "
                         "labeled off the verb-labeled plan counter "
                         "(plan_calibrate.py fits the new verbs from them)")
    ap.add_argument("--check", action="store_true",
                    help="oracle mode: every arm must match the numpy sum oracle "
                         "(exit nonzero on mismatch) — the planner smoke")
    from uccl_tpu import obs  # safe pre-device-forcing: jax-free surfaces

    obs.add_cli_args(ap)
    args = ap.parse_args()

    jax = init_devices(args.devices)

    import numpy as np

    from uccl_tpu.collective import Communicator
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    obs.setup_from_args(args)

    n = len(jax.devices())
    benches = [b for b in args.bench.split(",") if b]
    for b in benches:
        if b not in ("ar", "bcast", "ag"):
            ap.error(f"unknown --bench verb {b!r} (want ar/bcast/ag)")
    if benches != ["ar"]:
        if args.mesh2d or args.wire_dtype:
            ap.error("--bench bcast/ag rides the single-axis sweep; drop "
                     "--mesh2d/--wire-dtype")
        failed = 0
        for b in benches:
            if b == "ar":
                ap.error("--bench ar composes with bcast/ag only when "
                         "listed alone (the ar sweep has its own flags)")
            failed |= verb_sweep(jax, n, b, args)
        obs.dump_from_args(args)
        if failed:
            raise SystemExit(failed)
        return
    if args.wire_dtype:
        # quant_sweep builds its own raw single-axis mesh (the legacy
        # discharge interpreter can't address peers on the canonical
        # 4-axis make_mesh mesh) — dispatch before constructing one here
        if args.mesh2d:
            ap.error("--wire-dtype rides the single-axis pallas ring; "
                     "drop --mesh2d")
        wire_dtypes = [w for w in args.wire_dtype.split(",") if w]
        for w in wire_dtypes:
            if w not in ("fp8", "int8"):
                ap.error(f"unknown --wire-dtype arm {w!r} (want fp8/int8)")
        quant_sweep(jax, n, wire_dtypes, args)
        obs.dump_from_args(args)
        return
    if args.mesh2d:
        a, b = (int(v) for v in args.mesh2d.lower().split("x"))
        assert a * b == n, f"mesh {a}x{b} != {n} devices"
        mesh = make_mesh(MeshConfig(dp=a, tp=b))
        comm = Communicator(mesh, ("dp", "tp"))
    else:
        # raw single-axis mesh: the same choice as quant_sweep, so the
        # pallas/bidir arms are kernel-addressable under the legacy
        # discharge interpreter and auto may plan them
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        comm = Communicator(mesh, "dp")

    if args.algo == "both":
        algos = ["xla", "ring"]
    elif args.algo == "all":
        algos = ["xla", "ring", "hd", "pallas", "bidir", "auto"] + (
            ["torus"] if args.mesh2d else [])
    else:
        algos = [args.algo]

    failed = 0
    if not args.json:
        print(f"# all_reduce_perf  world={n}  "
              f"devices={jax.devices()[0].platform}")
        print(f"# {'bytes':>12} {'algo':>8} {'planned':>8} {'time_us':>10}"
              f" {'model_us':>10} {'algbw_GB/s':>10} {'busbw_GB/s':>10}")
    size = args.min_bytes
    while size <= args.max_bytes:
        elems = size // 4
        x = comm.device_put(
            np.random.default_rng(0).standard_normal((n, elems)).astype(np.float32)
        )
        # the --check oracle: an independent numpy sum, NOT comm.all_reduce
        # — the comm memoizes plan resolutions per request, so going through
        # it here would consume the xla arm's counter delta before the arm
        # could label itself off it
        ref = np.tile(np.asarray(x).sum(0), (n, 1))
        arms = []
        for algo in algos:
            if algo == "hd" and n & (n - 1):
                # hd falls back to ring off power-of-two worlds; skip rather
                # than record ring timings under the hd label
                continue
            if algo in ("pallas", "bidir") and args.mesh2d:
                continue  # the ring kernels drive a single mesh axis
            before = _plan_snapshot()
            out = comm.all_reduce(x, algo=algo)  # compile + warmup (+ plan)
            got = np.asarray(out)
            label = _planned_label(before) or {
                "algo": algo, "chunks": "1", "wire_dtype": "none"}
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = comm.all_reduce(x, algo=algo)
            np.asarray(out)  # host read = hard sync (axon-safe)
            dt = (time.perf_counter() - t0) / args.iters
            err = float(np.abs(got - ref).max())
            ok = err <= 1e-4 * max(1.0, float(np.abs(ref).max()))
            if args.check and not ok:
                print(f"all_reduce_perf: CHECK FAILED {algo} @ {size}B "
                      f"(planned {label['algo']}): max abs err {err}",
                      flush=True)
                failed = 1
            algbw = size / dt / 1e9
            busbw = algbw * 2 * (n - 1) / n
            modeled = _modeled_us(label)
            arms.append({
                "requested": algo,
                "algo": label["algo"],  # the REAL plan label (counter)
                "chunks": int(label["chunks"]),
                # "fallback" = the planned kernel ran as its lax mirror —
                # plan_calibrate excludes those rows from the fit
                "outcome": label.get("outcome", "explicit"),
                "time_us": round(dt * 1e6, 1),
                "modeled_us": round(modeled, 2),
                "algbw_gbps": round(algbw, 3),
                "busbw_gbps": round(busbw, 3),
                "max_abs_err": err,
                "oracle_ok": ok,
            })
            if not args.json:
                print(f"  {size:>12} {algo:>8} {label['algo']:>8} "
                      f"{dt * 1e6:>10.1f} {modeled:>10.1f} {algbw:>10.3f} "
                      f"{busbw:>10.3f}")
        if args.json:
            print(json.dumps({
                "bench": "all_reduce_plan",
                "schema_version": obs.SCHEMA_VERSION,
                "bytes": size, "world": n,
                "n_axes": 2 if args.mesh2d else 1,
                "mesh2d": args.mesh2d or None,
                "substrate": jax.default_backend(),
                "arms": arms,
            }), flush=True)
        size *= 4
    obs.dump_from_args(args)
    if failed:
        raise SystemExit(failed)


if __name__ == "__main__":
    main()
