"""Wall-clock fleet prefix-cache bench: K prefill workers, one directory.

ISSUE 19 tentpole proof. K worker PROCESSES (real process isolation, not
threads) each run a ServingEngine + PrefixCache + FleetWorker over one
shared p2p store. Worker 0 computes a shared system prefix once; every
other worker's first request finds it in the fleet directory and pulls
the KV rows over the T2 wire path instead of recomputing — the bench
audits that cut with counter deltas, never with prints alone.

Arms (all run the same shared-prefix working set):

* ``no_directory`` — baseline: engines + local prefix caches only. Each
  worker recomputes the shared prefix cold on its first request.
* ``directory``    — FleetWorker attached: worker 0 seeds, workers 1..K
  import the prefix cross-process (``fleet_cache_hits_total``,
  ``p2p_bytes_total{verb="kv_tier"}`` deltas), computing strictly fewer
  prefill tokens and reaching first token sooner.
* ``chaos``        — worker 0 seeds then dies (``os._exit``) with its
  directory entries resident. Survivors dial the corpse (counted
  ``fleet_cache_errors_total{reason="dial"}``), sweep its entries via
  ``invalidate_owner`` (counted invalidations), and finish every
  request cold — conservation and bit-exactness hold.

Every finished request in every arm is replayed against the one-shot
``models.inference.generate`` oracle in the parent — the fleet path is
lossless or the bench exits non-zero.

Per-role observability: ``--metrics-out x.prom`` writes one prom per
worker (``x.<arm>-wN.prom``) plus the federated directory-arm snapshot
at ``x.prom`` via obs/aggregate; ``--trace-out`` writes per-role Chrome
traces merged through scripts/trace_merge.py. ``scripts/check_obs.py
--fleet-cache`` gates the JSON + prom in qa/ci.

Usage::

    JAX_PLATFORMS=cpu python benchmarks/fleet_bench.py --smoke \
        --metrics-out /tmp/fleet.prom --json-out /tmp/fleet.json
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

CHUNK = 8
PREFIX_CHUNKS = 20           # shared system prefix = 160 tokens
SUFFIX_LEN = CHUNK           # per-request tail = 1 more chunk
MAX_SEQ = 192
CFG_KW = dict(vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
              head_dim=8, ffn=64)

_PREFIX_LEN = CHUNK * PREFIX_CHUNKS


def _role_path(path: str, role: str) -> str:
    stem, ext = os.path.splitext(path)
    return f"{stem}.{role}{ext}"


def _make_model():
    import jax

    from uccl_tpu.models import dense

    cfg = dense.DenseConfig(**CFG_KW)
    params = dense.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _shared_prefix(vocab: int) -> np.ndarray:
    rng = np.random.default_rng(1234)
    return rng.integers(0, vocab, _PREFIX_LEN).astype(np.int32)


def _suffix(vocab: int, idx: int, r: int) -> np.ndarray:
    rng = np.random.default_rng(1000 + idx * 100 + r)
    return rng.integers(0, vocab, SUFFIX_LEN).astype(np.int32)


def _seed_prompt(vocab: int) -> np.ndarray:
    rng = np.random.default_rng(999)
    return np.concatenate([_shared_prefix(vocab),
                           rng.integers(0, vocab, SUFFIX_LEN)
                           .astype(np.int32)])


def _counters():
    from uccl_tpu import obs

    return {
        "computed": obs.counter("serving_prefill_tokens_total")
        .get(kind="computed"),
        "skipped": obs.counter("serving_prefill_tokens_total")
        .get(kind="skipped"),
        "hits": obs.counter("fleet_cache_hits_total").get(),
        "stale": obs.counter("fleet_cache_stale_total").get(),
        "imported_tokens": obs.counter("fleet_cache_tokens_imported_total")
        .get(),
        "kv_tier_bytes": obs.counter("p2p_bytes_total").get(verb="kv_tier"),
        "dial_errors": obs.counter("fleet_cache_errors_total")
        .get(reason="dial"),
        "invalidations": obs.counter("fleet_dir_invalidations_total").get(),
    }


def fleet_worker(idx: int, arm: str, n_requests: int,
                 new_tokens: int, store_port: int, result_q,
                 trace_out: str, metrics_out: str,
                 flight_dir: str = "") -> None:
    """One prefill-worker process: engine + cache (+ FleetWorker)."""
    from uccl_tpu import obs
    from uccl_tpu.p2p import Endpoint
    from uccl_tpu.p2p.store import StoreClient
    from uccl_tpu.serving import (
        DenseBackend, PrefixCache, ServingEngine, ServingMetrics,
    )
    from uccl_tpu.serving.fleet import FleetWorker

    if trace_out:
        obs.enable_tracing()
    recorder = None
    if flight_dir:
        # per-process recorder: the chaos arm's survivors must each land
        # EXACTLY ONE peer_dead bundle when they dial the corpse (the
        # dial-failure and fail-latch sites share the key fleet:<owner>,
        # so the recorder dedupes them into one), clean arms none
        from uccl_tpu.obs import flight as flight_mod

        recorder = flight_mod.enable(
            os.path.join(flight_dir, f"{arm}-w{idx}"))

    cfg, params = _make_model()
    eng = ServingEngine(
        DenseBackend(params, cfg, n_slots=3, max_seq=MAX_SEQ),
        prefill_chunk=CHUNK, prefix_cache=PrefixCache(CHUNK),
    )
    sc = StoreClient("127.0.0.1", store_port)

    # compile warmup BEFORE the fleet attaches: the warmup parks stay
    # private local donors, never published directory entries. The second
    # prompt re-uses the first one's prefix at the measured depth so the
    # T0 copy path (and its jit) is hot before any timed request
    warm = np.random.default_rng(42).integers(
        0, cfg.vocab, _PREFIX_LEN + 4).astype(np.int32)
    warm2 = np.concatenate([warm[:_PREFIX_LEN],
                            np.random.default_rng(43).integers(
                                0, cfg.vocab, 4).astype(np.int32)])
    for w in (warm, warm2):
        eng.submit(w, max_new_tokens=new_tokens)
        eng.drain()
    # warm the KV import jit on a free slot at the measured depth — the
    # slot's rows/lens are rewritten by its next admission, so this is
    # invisible to correctness (fleet hits land via the same call)
    rows = eng.backend.export_slot_kv(2, 0, _PREFIX_LEN)
    eng.backend.import_slot_kv(2, rows[0], rows[1], length=_PREFIX_LEN)
    eng.reset_metrics()

    fw = None
    if arm != "no_directory":
        fw = FleetWorker(f"w{idx}", sc, Endpoint(), chunk=CHUNK,
                         capacity_bytes=1 << 22, max_entry_bytes=1 << 22,
                         fail_limit=2, timeout_ms=8000)
        eng.attach_fleet(fw)

    def run_one(prompt):
        req = eng.submit(prompt, max_new_tokens=new_tokens)
        eng.drain()
        return req

    # -- warm phase: worker 0 computes the shared prefix once ---------------
    if idx == 0:
        run_one(_seed_prompt(cfg.vocab))
        sc.set(f"bench/{arm}/warm", b"1")
        if arm == "chaos":
            # die with directory entries resident — no close(), no
            # withdraw: the crash the survivors must absorb
            sc.wait(f"bench/{arm}/die", timeout_s=120)
            os._exit(0)
    else:
        sc.wait(f"bench/{arm}/warm", timeout_s=120)
        if arm == "chaos":
            sc.wait(f"bench/{arm}/dead", timeout_s=120)
        elif fw is not None:
            # steady-state TTFT: peers in a long-lived fleet dial each
            # other once and reuse the channel for every fetch after —
            # establish it here so the measured window times the fetch
            # path, not one TCP/Channel handshake
            fw.client._remote_for("w0")

    # -- measured batch -----------------------------------------------------
    c0 = _counters()
    reqs, invalidated = [], 0
    for r in range(n_requests):
        prompt = np.concatenate([_shared_prefix(cfg.vocab),
                                 _suffix(cfg.vocab, idx, r)])
        req = run_one(prompt)
        reqs.append(req)
        if arm == "chaos" and idx != 0 and r == 0:
            # first request dialed the corpse and fell back cold; now
            # sweep the dead owner's entries like the heartbeat plane
            # declaring it dead (idempotent across survivors)
            invalidated = fw.invalidate_owner("w0")
    c1 = _counters()

    snap = eng.snapshot()
    report = {
        "idx": idx,
        "requests": [
            {"prompt": np.asarray(q.prompt).tolist(),
             "out": [int(t) for t in q.out_tokens],
             "n_generated": int(q.n_generated),
             "ttft_ms": round(float(q.ttft) * 1e3, 3),
             "hit_len": int(q.cache_hit_len)}
            for q in reqs
        ],
        "batch": {k: c1[k] - c0[k] for k in c0},
        "invalidated": invalidated,
        # worker 0's seed request completed before the measured window
        "completed_expected": len(reqs) + (1 if idx == 0 else 0),
        "completed": int(snap["completed"]),
        "leaked": int(eng.pool.leaked()),
        "flight_bundles": (sorted(os.path.basename(p)
                                  for p in recorder.bundles)
                           if recorder is not None else []),
    }
    if metrics_out:
        obs.write_metrics(
            _role_path(metrics_out, f"{arm}-w{idx}"),
            extra_lines=ServingMetrics.prometheus_lines(snap),
        )
    if trace_out:
        obs.write_trace(_role_path(trace_out, f"{arm}-w{idx}"),
                        process_name=f"uccl_tpu.fleet.{arm}.w{idx}")
    result_q.put(report)
    if fw is not None:
        fw.close()
        fw.ep.close()
    sc.close()


def _oracle_check(cfg, params, reports, cache) -> bool:
    import jax.numpy as jnp

    from uccl_tpu.models.inference import generate

    ok = True
    for rep in reports:
        for q in rep["requests"]:
            key = tuple(q["prompt"]) + (len(q["out"]),)
            if key not in cache:
                toks = generate(params, jnp.asarray(
                    np.asarray(q["prompt"], np.int32))[None], cfg,
                    max_new_tokens=len(q["out"]), max_seq=MAX_SEQ)
                cache[key] = np.asarray(toks)[0].tolist()
            if q["out"] != cache[key][: len(q["out"])]:
                print(f"ORACLE MISMATCH w{rep['idx']}: got {q['out']} "
                      f"want {cache[key][: len(q['out'])]}")
                ok = False
    return ok


def run_arm(arm: str, *, n_workers: int, n_requests: int, new_tokens: int,
            trace_out: str, metrics_out: str, oracle_cache,
            flight_dir: str = "") -> dict:
    from uccl_tpu.p2p.store import StoreClient, StoreServer

    cfg, params = _make_model()
    srv = StoreServer()
    ctx = mp.get_context("spawn")
    result_q = ctx.Queue()
    procs = [
        ctx.Process(target=fleet_worker,
                    args=(i, arm, n_requests, new_tokens,
                          srv.port, result_q, trace_out, metrics_out,
                          flight_dir))
        for i in range(n_workers)
    ]
    t0 = time.perf_counter()
    for p in procs:
        p.start()

    coord = StoreClient("127.0.0.1", srv.port)
    if arm == "chaos":
        coord.wait(f"bench/{arm}/warm", timeout_s=120)
        coord.set(f"bench/{arm}/die", b"1")
        procs[0].join(timeout=60)
        # only after the corpse is truly gone may survivors dial it
        coord.set(f"bench/{arm}/dead", b"w0")

    expect = n_workers - 1 if arm == "chaos" else n_workers
    reports = [result_q.get(timeout=300) for _ in range(expect)]
    for p in procs:
        p.join(timeout=60)
    alive = [p for p in procs if p.is_alive()]
    for p in alive:
        p.terminate()
    coord.close()
    srv.close()
    wall_s = time.perf_counter() - t0

    batch = {k: sum(r["batch"][k] for r in reports)
             for k in reports[0]["batch"]}
    ttfts = [q["ttft_ms"] for r in reports for q in r["requests"]]
    non_owner = [r for r in reports if r["idx"] != 0]
    cross_reqs = sum(len(r["requests"]) for r in non_owner)
    oracle_exact = _oracle_check(cfg, params, reports, oracle_cache)
    conserved = (not alive and all(r["leaked"] == 0 for r in reports)
                 and all(r["completed"] == r["completed_expected"]
                         for r in reports))
    flight_ok = True
    flight_bundles = {f"w{r['idx']}": r.get("flight_bundles", [])
                      for r in reports}
    if flight_dir:
        # exactly one attributable dump per injected fault, zero on
        # clean arms: each chaos survivor dials the corpse once and must
        # land a single peer_dead bundle; no fault -> no bundle
        for r in reports:
            names = r.get("flight_bundles", [])
            if arm == "chaos" and r["idx"] != 0:
                want = (len(names) == 1
                        and names[0].endswith("_peer_dead.json"))
            else:
                want = not names
            if not want:
                print(f"FLIGHT MISMATCH arm={arm} w{r['idx']}: "
                      f"bundles={names}")
                flight_ok = False
    summary = {
        "arm": arm,
        "workers": n_workers,
        "requests": sum(len(r["requests"]) for r in reports),
        "computed_prefill_tokens": int(batch["computed"]),
        "skipped_prefill_tokens": int(batch["skipped"]),
        "fleet_hits": int(batch["hits"]),
        "fleet_stale": int(batch["stale"]),
        "fleet_tokens_imported": int(batch["imported_tokens"]),
        "kv_tier_bytes": int(batch["kv_tier_bytes"]),
        "dial_errors": int(batch["dial_errors"]),
        "invalidations": int(sum(r["invalidated"] for r in reports)),
        "cross_hit_rate": (round(batch["hits"] / cross_reqs, 4)
                           if cross_reqs else 0.0),
        "ttft_ms_mean": round(float(np.mean(ttfts)), 3),
        "ttft_ms_by_worker": {
            f"w{r['idx']}": [q["ttft_ms"] for q in r["requests"]]
            for r in reports},
        "oracle_exact": bool(oracle_exact),
        "conserved": bool(conserved),
        "flight_ok": bool(flight_ok),
        "flight_bundles": flight_bundles,
        "wall_s": round(wall_s, 2),
    }
    print("bench=serving_fleet " + " ".join(
        f"{k}={v}" for k, v in summary.items()))
    return summary


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=2,
                    help="prefill worker processes sharing one directory")
    ap.add_argument("--requests", type=int, default=2,
                    help="measured requests per worker (after the seed)")
    ap.add_argument("--new-tokens", type=int, default=4)
    ap.add_argument("--arms", default="no_directory,directory,chaos")
    ap.add_argument("--smoke", action="store_true",
                    help="CI shape: 2 workers x 2 requests, all arms")
    ap.add_argument("--metrics-out", default="")
    ap.add_argument("--json-out", default="")
    ap.add_argument("--trace-out", default="")
    ap.add_argument("--flight-dir", default="",
                    help="arm a per-worker flight recorder (bundles in "
                         "<dir>/<arm>-wN/); the chaos arm must dump "
                         "exactly one peer_dead per survivor, clean "
                         "arms none")
    args = ap.parse_args()
    if args.smoke:
        args.workers, args.requests = 2, 2

    if args.workers < 2:
        print("need --workers >= 2 (cross-worker reuse is the point)")
        return 2

    oracle_cache: dict = {}
    arms = {}
    for arm in [a.strip() for a in args.arms.split(",") if a.strip()]:
        arms[arm] = run_arm(
            arm, n_workers=args.workers, n_requests=args.requests,
            new_tokens=args.new_tokens, trace_out=args.trace_out,
            metrics_out=args.metrics_out, oracle_cache=oracle_cache,
            flight_dir=args.flight_dir)

    ok = all(a["oracle_exact"] and a["conserved"] and a["flight_ok"]
             for a in arms.values())
    if "directory" in arms and "no_directory" in arms:
        d, b = arms["directory"], arms["no_directory"]
        saved = b["computed_prefill_tokens"] - d["computed_prefill_tokens"]
        print(f"fleet directory: {d['fleet_hits']} cross-worker hit(s), "
              f"{saved} prefill tokens saved, TTFT "
              f"{b['ttft_ms_mean']} -> {d['ttft_ms_mean']} ms")
        ok = ok and d["fleet_hits"] >= 1 and saved > 0

    if args.metrics_out and "directory" in arms:
        # federate the directory-arm worker proms the way a Prometheus
        # scrape would (counters sum, gauges stay per-replica)
        from uccl_tpu.obs.aggregate import aggregate, fleet_text

        scrapes = []
        for i in range(args.workers):
            path = _role_path(args.metrics_out, f"directory-w{i}")
            with open(path) as f:
                scrapes.append((f"w{i}", f.read()))
        with open(args.metrics_out, "w") as f:
            f.write(fleet_text(aggregate(scrapes)))
        print(f"wrote {args.metrics_out} (+ per-worker role siblings)")

    if args.trace_out and "directory" in arms:
        inputs = [_role_path(args.trace_out, f"directory-w{i}")
                  for i in range(args.workers)]
        merge = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__))), "scripts", "trace_merge.py"),
             "--out", args.trace_out] + inputs)
        ok = ok and merge.returncode == 0

    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump({"bench": "serving_fleet", "workers": args.workers,
                       "requests_per_worker": args.requests,
                       "new_tokens": args.new_tokens,
                       "arms": arms}, f, indent=1)
        print(f"wrote {args.json_out}")

    print(f"fleet bench {'OK' if ok else 'FAILED'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
