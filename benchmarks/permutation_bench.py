"""Permutation-traffic microbenchmark: every rank streams to one distinct peer.

The reference's paper microbench pair is incast + permutation traffic
(collective/rdma/incast/, azure_perm_traffic/ — SURVEY.md §2.1); this is the
permutation half for the DCN engine: N processes, a derangement pairs each
sender with one receiver, all flows run concurrently. Healthy transports show
per-flow bandwidth independent of N (no cross-flow interference).

Usage: python benchmarks/permutation_bench.py [n_ranks] [mb_per_flow]
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo path)
import json
import multiprocessing as mp
import sys
import time

import numpy as np


def _rank(idx, n, port_q, target_q, out_q, mb):
    import os
    import sys as s2

    s2.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from uccl_tpu.p2p import Endpoint

    with Endpoint(n_engines=2) as ep:
        port_q.put((idx, ep.port))
        target_port = target_q.get()
        src = np.random.default_rng(idx).integers(0, 255, mb << 20, dtype=np.uint8)
        dst = np.zeros(mb << 20, np.uint8)
        fifo_local = ep.advertise(ep.reg(dst))
        conn = ep.connect("127.0.0.1", target_port)
        ep.send(conn, bytes(fifo_local))  # give MY window to my... see below
        # Protocol: rank i dials rank perm[i] and sends ITS OWN landing
        # window; the accepted side uses the received fifo to write into the
        # dialer. So each rank writes to the peer that dialed it.
        in_conn = ep.accept(timeout_ms=60000)
        peer_fifo = ep.recv(in_conn, timeout_ms=60000)
        ep.send(in_conn, b"go")  # both sides ready
        assert ep.recv(conn, timeout_ms=60000) == b"go"
        t0 = time.time()  # absolute: parent computes true overlap window
        ep.write(in_conn, src, peer_fifo)
        t1 = time.time()
        ep.send(in_conn, b"done")
        assert ep.recv(conn, timeout_ms=120000) == b"done"
        out_q.put((idx, (mb << 20) / (t1 - t0), t0, t1))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    mb = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    mp.set_start_method("spawn", force=True)
    port_q, out_q = mp.Queue(), mp.Queue()
    target_qs = [mp.Queue() for _ in range(n)]
    procs = [
        mp.Process(target=_rank, args=(i, n, port_q, target_qs[i], out_q, mb))
        for i in range(n)
    ]
    [p.start() for p in procs]
    ports = {}
    for _ in range(n):
        i, port = port_q.get(timeout=120)
        ports[i] = port
    # derangement: rank i targets rank (i+1) % n
    for i in range(n):
        target_qs[i].put(ports[(i + 1) % n])
    rates, starts, ends = {}, [], []
    for _ in range(n):
        i, bps, t0, t1 = out_q.get(timeout=300)
        rates[i] = bps
        starts.append(t0)
        ends.append(t1)
    # true transfer window: first flow start to last flow end (excludes RNG
    # payload generation and rendezvous, like incast_bench)
    wall = max(ends) - min(starts)
    [p.join(60) for p in procs]
    r = np.array([rates[i] for i in sorted(rates)])
    print(
        json.dumps(
            {
                "n_ranks": n,
                "mb_per_flow": mb,
                "aggregate_GBps": round(n * (mb << 20) / wall / 1e9, 3),
                "per_flow_MBps_min": round(float(r.min()) / 1e6, 1),
                "per_flow_MBps_max": round(float(r.max()) / 1e6, 1),
                "jain_fairness": round(
                    float(r.sum() ** 2 / (len(r) * (r**2).sum())), 4
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
