"""Incast benchmark: N senders converge on one receiver — the scenario
receiver-driven CC exists for (reference EQDS, include/cc/eqds.h).

Two modes:

* **Legacy** (``incast_bench.py [n] [mb]``): N raw-Endpoint sender
  *processes* blast framed-TCP writes at one receiver — measures the
  engine's own scheduling fairness (the docs/EQDS.md round-1 table).

* **Windowed-transport sweep** (``--fan-in N --cc ... --drop-rates ...``):
  N multipath *Channels* in one process (each sender thread owns its own
  Endpoint; the native engine threads move the bytes) drive the windowed
  SACK transport through a fault-injected loopback — drop × reorder ×
  congestion-control arm — and report **counter-audited** goodput, the
  fast-vs-RTO retransmit split, cwnd/srtt/rto, and credit-stall seconds.
  Arms: ``off`` (static window), ``timely``/``swift`` (sender-side window
  CC fed by per-chunk completion RTTs), ``eqds`` (receiver-driven
  PullPacer credit at the receiver's configured drain rate — the incast
  actuator). Every payload is verified bit-exact against its seeded
  source before an arm may report goodput.

  ``--disagg`` adds the serving arm: 2 PrefillWorkers → 1 DecodeWorker
  over the channel transport on a lossy/reordering loopback, oracle-exact
  with the TTFT transfer leg measured under incast (needs jax; CPU ok).

Honest caveat: in-process senders share the GIL for the windowed
bookkeeping loop, so absolute MB/s undersells a multi-process deployment;
arms are compared against each other under identical conditions, and the
counters (not wall-clock mirrors) label every arm.

Usage:
  python benchmarks/incast_bench.py 8 64                       # legacy
  python benchmarks/incast_bench.py --fan-in 4 --mb 8 \\
      --drop-rates 0,0.01,0.05 --cc off,timely,swift,eqds \\
      --json-out docs/incast_sack_r01.json
  python benchmarks/incast_bench.py --smoke --metrics-out m.prom
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo path)
import argparse
import json
import multiprocessing as mp
import sys
import threading
import time

import numpy as np


# --------------------------------------------------------------------------
# legacy raw-endpoint multiprocess mode (docs/EQDS.md round-1 measurement)
# --------------------------------------------------------------------------
def _sender(port, mb, out_q, idx):
    import os, sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from uccl_tpu.p2p import Endpoint

    with Endpoint() as ep:
        conn = ep.connect("127.0.0.1", port)
        src = np.random.default_rng(idx).integers(
            0, 255, mb << 20, dtype=np.uint8
        )
        ep.send(conn, b"ready")
        fifo = ep.recv(conn, 64, timeout_ms=120000)  # the starting gun
        t0 = time.perf_counter()
        ep.write(conn, src, fifo)
        dt = time.perf_counter() - t0
        ep.send(conn, b"done")
        out_q.put((idx, (mb << 20) / dt))


def run_legacy(n: int, mb: int) -> None:
    from uccl_tpu.p2p import Endpoint

    mp.set_start_method("spawn", force=True)
    out_q = mp.Queue()
    with Endpoint(n_engines=4) as recv_ep:
        procs = [
            mp.Process(target=_sender, args=(recv_ep.port, mb, out_q, i))
            for i in range(n)
        ]
        [p.start() for p in procs]
        # Rendezvous: accept everyone and hand out windows BEFORE timing, so
        # wall-clock measures the incast transfer, not process spawn/import.
        conns, bufs = [], []
        for _ in range(n):
            c = recv_ep.accept(timeout_ms=120000)
            dst = np.zeros(mb << 20, np.uint8)
            fifo = recv_ep.advertise(recv_ep.reg(dst))
            conns.append((c, bytes(fifo)))
            bufs.append(dst)
        for c, _ in conns:  # wait for payload generation everywhere
            assert recv_ep.recv(c, 16, timeout_ms=120000) == b"ready"
        t0 = time.perf_counter()
        for c, fifo in conns:
            recv_ep.send(c, fifo)  # the starting gun
        for c, _ in conns:
            assert recv_ep.recv(c, 16, timeout_ms=120000) == b"done"
        wall = time.perf_counter() - t0
        [p.join(30) for p in procs]

    per_flow = {}
    while not out_q.empty():
        i, bps = out_q.get()
        per_flow[i] = bps
    rates = np.array([per_flow[i] for i in sorted(per_flow)])
    jain = float(rates.sum() ** 2 / (len(rates) * (rates**2).sum()))
    agg = n * (mb << 20) / wall
    print(
        json.dumps(
            {
                "n_senders": n,
                "mb_per_sender": mb,
                "aggregate_GBps": round(agg / 1e9, 3),
                "per_flow_MBps_min": round(float(rates.min()) / 1e6, 1),
                "per_flow_MBps_max": round(float(rates.max()) / 1e6, 1),
                "jain_fairness": round(jain, 4),
                "wall_s": round(wall, 3),
            }
        )
    )


# --------------------------------------------------------------------------
# windowed-transport channel sweep
# --------------------------------------------------------------------------
def _counter_totals():
    """Snapshot the transport counters the arms are labeled from."""
    from uccl_tpu.p2p.channel import (_CHAN_CHUNKS, _CHAN_RETX,
                                      _CREDIT_STALL)

    retx = {"fast": 0.0, "rto": 0.0}
    for labels, v in _CHAN_RETX.samples():
        k = labels.get("kind", "rto")
        retx[k] = retx.get(k, 0.0) + v
    return {
        "chunks": _CHAN_CHUNKS.total(),
        "retx_fast": retx.get("fast", 0.0),
        "retx_rto": retx.get("rto", 0.0),
        "credit_stall_s": _CREDIT_STALL.total(),
    }


def run_channel_arm(n: int, mb: int, cc: str, drop: float, reorder: float,
                    *, chunk_kb: int = 64, n_paths: int = 4,
                    retries: int = 8, pull_rate_mbps: float = 400.0,
                    timeout_s: float = 300.0) -> dict:
    from uccl_tpu.p2p import Endpoint, PullPacer
    from uccl_tpu.p2p.channel import Channel, ChannelAcceptor

    recv_ep = Endpoint(n_engines=4)
    accepted = {}
    acceptor = ChannelAcceptor(
        recv_ep, lambda ch: accepted.setdefault(ch.meta[0], ch),
        chunk_bytes=chunk_kb << 10,
    )
    send_eps, chans = [], []
    try:
        for i in range(n):
            ep = Endpoint(n_engines=2)
            ch = Channel.connect(ep, "127.0.0.1", recv_ep.port,
                                 n_paths=n_paths,
                                 chunk_bytes=chunk_kb << 10,
                                 meta=bytes([i]))
            ch.retries = retries
            ep.set_drop_rate(drop)
            ep.set_reorder_rate(reorder)
            if cc in ("timely", "swift"):
                ch.enable_window_cc(cc)
            elif cc == "eqds":
                ch.enable_pull_sender()
            elif cc != "off":
                raise ValueError(f"unknown cc arm {cc!r}")
            send_eps.append(ep)
            chans.append(ch)
        deadline = time.monotonic() + 60
        while len(accepted) < n:
            if time.monotonic() > deadline:
                raise TimeoutError("acceptor never collected every channel")
            time.sleep(0.002)

        srcs = [np.random.default_rng(1000 + i).integers(
                    0, 255, mb << 20, dtype=np.uint8) for i in range(n)]
        dsts, fifos = [], []
        for i in range(n):
            dst = np.zeros(mb << 20, np.uint8)
            fifos.append(recv_ep.advertise(recv_ep.reg(dst)))
            dsts.append(dst)

        pacer = None
        if cc == "eqds":
            # the receiver's KNOWN drain rate: attach right before the gun
            # so credit cannot pre-accumulate while senders set up
            pacer = PullPacer(pull_rate_mbps * 1e6)
            for ch in accepted.values():
                pacer.attach(ch)

        before = _counter_totals()
        barrier = threading.Barrier(n + 1)
        per_flow, errors = {}, []

        def tx(i):
            try:
                barrier.wait()
                t0 = time.perf_counter()
                chans[i].write(srcs[i], fifos[i],
                               timeout_ms=int(timeout_s * 1e3))
                per_flow[i] = (mb << 20) / (time.perf_counter() - t0)
            except Exception as e:  # surfaced after join
                errors.append((i, repr(e)))

        threads = [threading.Thread(target=tx, args=(i,)) for i in range(n)]
        [t.start() for t in threads]
        if pacer is not None:
            pacer.start()
        barrier.wait()
        t0 = time.perf_counter()
        [t.join(timeout=timeout_s) for t in threads]
        wall = time.perf_counter() - t0
        if pacer is not None:
            pacer.stop()
        if errors:
            raise IOError(f"arm cc={cc} drop={drop}: sender failures {errors}")
        for i in range(n):
            if not np.array_equal(dsts[i], srcs[i]):
                raise AssertionError(
                    f"arm cc={cc} drop={drop}: sender {i} payload corrupt"
                )
        after = _counter_totals()

        rates = np.array([per_flow[i] for i in sorted(per_flow)])
        jain = float(rates.sum() ** 2 / (len(rates) * (rates ** 2).sum()))
        stats = [ch.transport_stats() for ch in chans]
        arm = {
            "bench": "incast_sack",
            "n_senders": n,
            "mb_per_sender": mb,
            "cc": cc,
            "drop_rate": drop,
            "reorder_rate": reorder,
            "chunk_kb": chunk_kb,
            "n_paths": n_paths,
            "goodput_MBps": round(n * (mb << 20) / wall / 1e6, 2),
            "per_flow_MBps_min": round(float(rates.min()) / 1e6, 2),
            "per_flow_MBps_max": round(float(rates.max()) / 1e6, 2),
            "jain_fairness": round(jain, 4),
            "wall_s": round(wall, 3),
            "payload": "bit_exact",
            # counter-delta labels (the REAL series, not mirrored math)
            "chunks_issued": int(after["chunks"] - before["chunks"]),
            "retx_fast": int(after["retx_fast"] - before["retx_fast"]),
            "retx_rto": int(after["retx_rto"] - before["retx_rto"]),
            "credit_stall_s": round(
                after["credit_stall_s"] - before["credit_stall_s"], 4),
            "cwnd_bytes_mean": int(np.mean([s["cwnd_bytes"] for s in stats])),
            "srtt_us_mean": round(
                float(np.mean([s["srtt_us"] for s in stats])), 1),
            "rto_ms_mean": round(
                float(np.mean([s["rto_ms"] for s in stats])), 2),
        }
        if cc == "eqds":
            arm["pull_rate_mbps"] = pull_rate_mbps
            arm["granted_bytes"] = int(sum(
                ch.pull_granted for ch in accepted.values()))
        return arm
    finally:
        acceptor.close()
        for ch in list(accepted.values()):
            try:
                ch.close()
            except Exception:
                pass
        for ep in send_eps:
            ep.close()
        recv_ep.close()


# --------------------------------------------------------------------------
# disagg fan-in arm: 2 prefill workers -> 1 decode worker over the channel
# transport, lossy loopback, oracle-exact with the TTFT split measured
# --------------------------------------------------------------------------
def run_disagg_arm(drop: float, reorder: float, *, requests: int = 6,
                   pull_rate_mbps: float = 64.0) -> dict:
    import jax

    from uccl_tpu.models import dense
    from uccl_tpu.models.inference import generate
    from uccl_tpu.p2p import Endpoint
    from uccl_tpu.serving import DenseBackend, ServingEngine
    from uccl_tpu.serving.disagg import DecodeWorker, add_local_prefill

    MAX_SEQ = 32
    cfg = dense.DenseConfig(vocab=64, dim=32, n_layers=2, n_heads=4,
                            n_kv_heads=2, head_dim=8, ffn=64)
    params = dense.init_params(jax.random.PRNGKey(0), cfg)
    pes = [ServingEngine(DenseBackend(params, cfg, n_slots=2,
                                      max_seq=MAX_SEQ), prefill_chunk=4)
           for _ in range(2)]
    de = ServingEngine(DenseBackend(params, cfg, n_slots=4, max_seq=MAX_SEQ))
    dw = DecodeWorker(de, Endpoint(), pull_rate_bps=pull_rate_mbps * 1e6)
    pws = [add_local_prefill(dw, pe, transport="channel", n_paths=2,
                             chunk_bytes=8 << 10, pull=True,
                             window_cc="swift") for pe in pes]
    for pw in pws:
        pw.chan.retries = 8

    def pump(n_done, done, deadline_s=180.0):
        deadline = time.monotonic() + deadline_s
        while len(done) < n_done:
            for pw in pws:
                pw.step()
            done.extend(dw.step())
            if time.monotonic() > deadline:
                raise TimeoutError(f"disagg arm stalled at {len(done)}")
        return done

    try:
        for pw in pws:
            pw.submit(np.zeros(8, np.int32), max_new_tokens=2)
        pump(2, [])
        for eng in pes + [de]:
            eng.reset_metrics()
        before = _counter_totals()
        for pw in pws:
            pw.ep.set_drop_rate(drop)
            pw.ep.set_reorder_rate(reorder)
        rng = np.random.default_rng(5)
        prompts = [rng.integers(0, 64, 6 + i % 5).astype(np.int32)
                   for i in range(requests)]
        done = []
        t0 = time.perf_counter()
        for i, p in enumerate(prompts):
            assert pws[i % 2].submit(p, max_new_tokens=4) is not None
            for pw in pws:
                pw.step()
            done.extend(dw.step())
        pump(requests, done)
        wall = time.perf_counter() - t0
    finally:
        for pw in pws:
            pw.ep.set_drop_rate(0.0)
            pw.ep.set_reorder_rate(0.0)
        granted = sum(ch.pull_granted for ch in dw.channels)
        dw.close()  # stops the pacer, releases the channel list

    # oracle-exactness asserted from real comparisons, not assumed
    exact = True
    for r in done:
        toks = generate(params, np.asarray(r.prompt)[None], cfg,
                        max_new_tokens=r.max_new_tokens, max_seq=MAX_SEQ)
        exact &= (np.asarray(toks)[0, : r.n_generated].tolist()
                  == r.out_tokens)
    after = _counter_totals()

    def pct(xs, q):
        return round(float(np.percentile(xs, q)) * 1e3, 2) if xs else None

    m = de.metrics
    return {
        "bench": "incast_disagg",
        "fan_in": "2->1",
        "transport": "channel+pull(swift cc)",
        "drop_rate": drop,
        "reorder_rate": reorder,
        "requests": requests,
        "oracle_exact": bool(exact),
        "wall_s": round(wall, 3),
        "retx_fast": int(after["retx_fast"] - before["retx_fast"]),
        "retx_rto": int(after["retx_rto"] - before["retx_rto"]),
        "chunks_issued": int(after["chunks"] - before["chunks"]),
        "credit_stall_s": round(
            after["credit_stall_s"] - before["credit_stall_s"], 4),
        "granted_bytes": int(granted),
        "disagg_ttft_ms_p50": pct(m.disagg_ttft_s, 50),
        "disagg_ttft_ms_p95": pct(m.disagg_ttft_s, 95),
        "transfer_ms_p50": pct(m.disagg_transfer_s, 50),
        "transfer_ms_p95": pct(m.disagg_transfer_s, 95),
    }


def main():
    argv = sys.argv[1:]
    if argv and not argv[0].startswith("-"):
        n = int(argv[0])
        mb = int(argv[1]) if len(argv) > 1 else 64
        run_legacy(n, mb)
        return

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--fan-in", type=int, default=4)
    ap.add_argument("--mb", type=int, default=8)
    ap.add_argument("--cc", default="off,timely,swift,eqds")
    ap.add_argument("--drop-rates", default="0,0.01,0.05")
    ap.add_argument("--reorder", type=float, default=0.0)
    ap.add_argument("--chunk-kb", type=int, default=64)
    ap.add_argument("--n-paths", type=int, default=4)
    ap.add_argument("--retries", type=int, default=8)
    ap.add_argument("--pull-rate-mbps", type=float, default=400.0)
    ap.add_argument("--disagg", action="store_true",
                    help="add the 2->1 disagg serving arm (needs jax)")
    ap.add_argument("--disagg-only", action="store_true")
    ap.add_argument("--smoke", action="store_true",
                    help="CI preset: tiny lossy+reordering sweep "
                    "(fan-in 4, 2 MB, drop 2%%, reorder 20%%, swift+eqds)")
    ap.add_argument("--json-out", default="")
    ap.add_argument("--metrics-out", default="")
    args = ap.parse_args(argv)

    if args.smoke:
        args.fan_in, args.mb = 4, 2
        args.cc, args.drop_rates, args.reorder = "swift,eqds", "0.02", 0.2

    arms = []
    if not args.disagg_only:
        ccs = [c.strip() for c in args.cc.split(",") if c.strip()]
        drops = [float(d) for d in args.drop_rates.split(",")]
        for drop in drops:
            for cc in ccs:
                arm = run_channel_arm(
                    args.fan_in, args.mb, cc, drop, args.reorder,
                    chunk_kb=args.chunk_kb, n_paths=args.n_paths,
                    retries=args.retries,
                    pull_rate_mbps=args.pull_rate_mbps,
                )
                arms.append(arm)
                print(json.dumps(arm), flush=True)
    if args.disagg or args.disagg_only:
        drops = [float(d) for d in args.drop_rates.split(",")]
        for drop in drops:
            arm = run_disagg_arm(drop, args.reorder or 0.2)
            arms.append(arm)
            print(json.dumps(arm), flush=True)

    if args.json_out:
        with open(args.json_out, "w") as f:
            for arm in arms:
                f.write(json.dumps(arm) + "\n")
    if args.metrics_out:
        from uccl_tpu.obs.export import write_metrics

        write_metrics(args.metrics_out)


if __name__ == "__main__":
    main()
