"""Incast microbenchmark: N senders blast one receiver over the DCN engine.

The scenario receiver-driven CC (the reference's EQDS, include/cc/eqds.h)
exists for: many senders converging on one receiver link. This bench measures
what our transport (framed TCP streams + per-conn non-blocking engine)
delivers under incast: aggregate goodput and per-sender fairness (Jain's
index). Results ground the EQDS design decision in docs/EQDS.md.

Usage: python benchmarks/incast_bench.py [n_senders] [mb_per_sender]
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo path)
import json
import multiprocessing as mp
import sys
import time

import numpy as np


def _sender(port, mb, out_q, idx):
    import os, sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from uccl_tpu.p2p import Endpoint

    with Endpoint() as ep:
        conn = ep.connect("127.0.0.1", port)
        src = np.random.default_rng(idx).integers(
            0, 255, mb << 20, dtype=np.uint8
        )
        ep.send(conn, b"ready")
        fifo = ep.recv(conn, 64, timeout_ms=120000)  # the starting gun
        t0 = time.perf_counter()
        ep.write(conn, src, fifo)
        dt = time.perf_counter() - t0
        ep.send(conn, b"done")
        out_q.put((idx, (mb << 20) / dt))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    mb = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    from uccl_tpu.p2p import Endpoint

    mp.set_start_method("spawn", force=True)
    out_q = mp.Queue()
    with Endpoint(n_engines=4) as recv_ep:
        procs = [
            mp.Process(target=_sender, args=(recv_ep.port, mb, out_q, i))
            for i in range(n)
        ]
        [p.start() for p in procs]
        # Rendezvous: accept everyone and hand out windows BEFORE timing, so
        # wall-clock measures the incast transfer, not process spawn/import.
        conns, bufs = [], []
        for _ in range(n):
            c = recv_ep.accept(timeout_ms=120000)
            dst = np.zeros(mb << 20, np.uint8)
            fifo = recv_ep.advertise(recv_ep.reg(dst))
            conns.append((c, bytes(fifo)))
            bufs.append(dst)
        for c, _ in conns:  # wait for payload generation everywhere
            assert recv_ep.recv(c, 16, timeout_ms=120000) == b"ready"
        t0 = time.perf_counter()
        for c, fifo in conns:
            recv_ep.send(c, fifo)  # the starting gun
        for c, _ in conns:
            assert recv_ep.recv(c, 16, timeout_ms=120000) == b"done"
        wall = time.perf_counter() - t0
        [p.join(30) for p in procs]

    per_flow = {}
    while not out_q.empty():
        i, bps = out_q.get()
        per_flow[i] = bps
    rates = np.array([per_flow[i] for i in sorted(per_flow)])
    jain = float(rates.sum() ** 2 / (len(rates) * (rates**2).sum()))
    agg = n * (mb << 20) / wall
    print(
        json.dumps(
            {
                "n_senders": n,
                "mb_per_sender": mb,
                "aggregate_GBps": round(agg / 1e9, 3),
                "per_flow_MBps_min": round(float(rates.min()) / 1e6, 1),
                "per_flow_MBps_max": round(float(rates.max()) / 1e6, 1),
                "jain_fairness": round(jain, 4),
                "wall_s": round(wall, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
